"""Headline benchmark: power-law push/push-pull gossip to 99% coverage.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "peers_rounds_per_sec", "vs_baseline": N, ...}

Metric per BASELINE.json: rounds-to-99%-coverage and peers·rounds/sec on a
1M-node power-law (γ=2.5) swarm, plus the 10M-peer north-star run
(BASELINE.json north_star: "10M-peer power-law swarm to 99% coverage < 60 s").
Runs are single on-device while_loops (compile + warmup excluded; min wall
over 3 reps because the axon tunnel has high run-to-run variance).

Graphs are built ON DEVICE (core/device_topology.py): at 10M nodes the host
numpy path plus the CSR transfer costs ~80 s; the device pipeline builds the
same erased configuration model in HBM in ~10 s (reported as setup_seconds).

``vs_baseline`` compares against the reference's intrinsic socket-mode
throughput: one gossip tick per 5 s per peer (reference Peer.py:396-408,
SURVEY.md §6) at its 1k-peer demonstrated scale ⇒ 1000 peers × 0.2
rounds/sec = 200 peers·rounds/sec. The reference publishes no other numbers
(readme.md:1-11; BASELINE.json "published": {}).

The JSON also carries measured hardware ceilings (elementwise GB/s and
random-access rate of this chip, measured in-run) and the per-config derived
utilization, so round times are accountable: dissemination is bound by
random gather/scatter access rate, not FLOPs (SURVEY.md §5.1 accounting).

Flags: --quick (1M only, 1 rep) · --dist (add a sharded-engine run on the
available device mesh).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

REFERENCE_PEERS_ROUNDS_PER_SEC = 200.0  # 1k peers, 1 round / 5 s (Peer.py:396-408)


def _measure_ceilings(jax, jnp):
    """Measure this chip's elementwise bandwidth and random-access rate with
    tiny in-loop kernels (dispatch overhead amortized over 20 iters)."""
    import numpy as np

    n = 1_000_000
    a = jnp.asarray(np.random.default_rng(0).integers(0, 2**31, (n,), dtype=np.int32))
    idx = jnp.asarray(np.random.default_rng(1).integers(0, n, (n,), dtype=np.int32))

    def loop(body, carry, iters=20):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, iters, body, c))
        out = f(carry)
        _ = float(jnp.sum(out))  # fetch = completion barrier on axon
        t0 = time.perf_counter()
        out = f(carry)
        _ = float(jnp.sum(out))
        return (time.perf_counter() - t0) / iters

    # elementwise: read 2 x 4MB, write 4MB per iter
    t_ew = loop(lambda i, c: c ^ (c | a), a)
    # random gather: 1M 4-byte accesses per iter
    t_g = loop(lambda i, c: c ^ a[(idx + i) % n], a)
    return {
        "elementwise_GBps": round(12e6 / max(t_ew, 1e-9) / 1e9, 2),
        "random_access_per_sec_M": round(n / max(t_g, 1e-9) / 1e6, 1),
        "note": "measured in-run on 1M-element ops; includes per-op overhead",
    }


def _accesses_per_round(cfg) -> int:
    """Random HBM accesses per round (gather+scatter), the binding resource."""
    n = cfg.n_peers
    acc = 0
    if cfg.mode in ("push", "push_pull"):
        acc += 2 * n * cfg.fanout  # target gather + delivery scatter
    if cfg.mode == "push_pull":
        acc += 2 * n  # pull: neighbor gather + seen gather
    return acc


def bench_one(dg, mode: str, fanout: int, *, reps: int, max_rounds: int = 500):
    import jax

    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.metrics import bench_swarm

    cfg = SwarmConfig(n_peers=dg.n_pad, msg_slots=1, fanout=fanout, mode=mode)
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    res, _ = bench_swarm(state, cfg, 0.99, max_rounds, reps=reps)
    acc = _accesses_per_round(cfg)
    return {
        **{k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in dataclasses.asdict(res).items()},
        "accesses_per_round_M": round(acc / 1e6, 2),
        "access_rate_per_sec_M": round(acc / max(res.ms_per_round, 1e-9) / 1e3, 1),
    }


def bench_dist(n: int):
    """Sharded-engine run over the available device mesh (1 real TPU chip
    here; 8 virtual CPU devices under the test env) — the multi-chip path's
    single-host measurement; cross-chip scaling is validated structurally by
    __graft_entry__.dryrun_multichip."""
    import jax
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig
    from tpu_gossip.core.topology import build_csr, configuration_model, powerlaw_degree_sequence
    from tpu_gossip.dist import (
        init_sharded_swarm, make_mesh, partition_graph,
        run_until_coverage_dist, shard_swarm,
    )

    rng = np.random.default_rng(0)
    graph = build_csr(n, configuration_model(powerlaw_degree_sequence(n, gamma=2.5, rng=rng), rng=rng))
    mesh = make_mesh()
    sg, relabeled, position = partition_graph(graph, mesh.size, seed=0)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=1, fanout=1, mode="push_pull")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 300)
    float(fin.coverage(0))  # warm
    t0 = time.perf_counter()
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 300)
    cov = float(fin.coverage(0))
    dt = time.perf_counter() - t0
    rounds = int(fin.round)
    return {
        "n_peers": n, "devices": mesh.size, "rounds": rounds,
        "coverage": round(cov, 4), "wall_seconds": round(dt, 3),
        "peers_rounds_per_sec": round(n * rounds / max(dt, 1e-9), 1),
    }


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    quick = "--quick" in argv
    with_dist = "--dist" in argv

    import jax
    import jax.numpy as jnp

    from tpu_gossip.core.device_topology import device_powerlaw_graph

    reps = 1 if quick else 3
    ceilings = _measure_ceilings(jax, jnp)

    # --- 1M standard configs ---------------------------------------------
    t0 = time.perf_counter()
    dg1 = device_powerlaw_graph(1_000_000, gamma=2.5, key=jax.random.key(0))
    int(dg1.row_ptr[-1])
    setup_1m = time.perf_counter() - t0

    headline = bench_one(dg1, "push_pull", 1, reps=reps)
    push3 = bench_one(dg1, "push", 3, reps=reps)

    out = {
        "metric": "1M-node power-law (gamma=2.5) push-pull gossip to 99% coverage",
        "value": headline["peers_rounds_per_sec"],
        "unit": "peers_rounds_per_sec",
        "vs_baseline": round(headline["peers_rounds_per_sec"] / REFERENCE_PEERS_ROUNDS_PER_SEC, 1),
        "rounds_to_99pct": headline["rounds"],
        "wall_seconds": headline["wall_seconds"],
        "setup_seconds_1m": round(setup_1m, 2),
        "configs": {"push_pull_k1": headline, "push_k3": push3},
        "hardware_ceilings": ceilings,
        "graph": "on-device erased configuration model (core/device_topology.py)",
    }

    # --- 10M north star ---------------------------------------------------
    if not quick:
        t0 = time.perf_counter()
        dg10 = device_powerlaw_graph(10_000_000, gamma=2.5, key=jax.random.key(0))
        int(dg10.row_ptr[-1])
        setup_10m = time.perf_counter() - t0
        ns = bench_one(dg10, "push_pull", 1, reps=reps)
        out["north_star"] = {
            **ns,
            "setup_seconds": round(setup_10m, 2),
            "target": "10M peers to 99% < 60 s (BASELINE.json north_star)",
            "met": bool(ns["wall_seconds"] < 60.0),
        }

    if with_dist:
        out["dist"] = bench_dist(200_000)

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
