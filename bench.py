"""Headline benchmark: 1M-node power-law push gossip to 99% coverage.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "peers_rounds_per_sec", "vs_baseline": N}

Metric per BASELINE.json: rounds-to-99%-coverage and peers·rounds/sec on a
1M-node power-law (γ=2.5) swarm, run as a single on-device while_loop
(compile + warmup excluded from timing).

``vs_baseline`` compares against the reference's intrinsic socket-mode
throughput: one gossip tick per 5 s per peer (reference Peer.py:396-408,
SURVEY.md §6) at its 1k-peer demonstrated scale ⇒ 1000 peers × 0.2
rounds/sec = 200 peers·rounds/sec. The reference publishes no other numbers
(readme.md:1-11; BASELINE.json "published": {}).
"""

from __future__ import annotations

import json
import sys

import numpy as np

REFERENCE_PEERS_ROUNDS_PER_SEC = 200.0  # 1k peers, 1 round / 5 s (Peer.py:396-408)


def main() -> int:
    import jax

    from tpu_gossip import SwarmConfig, build_csr, init_swarm
    from tpu_gossip.core.topology import configuration_model, powerlaw_degree_sequence
    from tpu_gossip.sim.metrics import bench_swarm

    n = 1_000_000
    rng = np.random.default_rng(0)
    deg = powerlaw_degree_sequence(n, gamma=2.5, rng=rng)
    graph = build_csr(n, configuration_model(deg, rng=rng))

    cfg = SwarmConfig(n_peers=n, msg_slots=16, fanout=3)
    state = init_swarm(graph, cfg, key=jax.random.key(0), origins=[0])

    res = bench_swarm(state, cfg, target=0.99, max_rounds=500)
    out = {
        "metric": "1M-node power-law (gamma=2.5) push gossip to 99% coverage",
        "value": round(res.peers_rounds_per_sec, 1),
        "unit": "peers_rounds_per_sec",
        "vs_baseline": round(res.peers_rounds_per_sec / REFERENCE_PEERS_ROUNDS_PER_SEC, 1),
        "rounds_to_99pct": res.rounds,
        "wall_seconds": round(res.wall_seconds, 4),
        "coverage": round(res.coverage, 4),
        "n_peers": n,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
