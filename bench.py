"""Headline benchmark: power-law push/push-pull gossip to 99% coverage.

Prints the COMPACT JSON headline line (≲1.5 KB so a tail capture can't
truncate it):
    {"metric": ..., "value": N, "unit": "peers_rounds_per_sec", "vs_baseline": N,
     "configs_ms_per_round": {...}, "north_star": {...}, "dist": {...}}
TWICE: once IMMEDIATELY after the 1M headline trio (so a driver timeout
mid-10M can never lose the headline again — the r5 artifact died at rc=124
with nothing on stdout) and once, enriched, as the final line. A tail
parse always reads the most complete one. The FULL result tree
(per-config rounds/coverage/msgs, hardware ceilings, accounting notes) is
written INCREMENTALLY to ``BENCH_DETAIL.json`` next to this file — each
completed section lands before the next begins, so the committed record
reflects everything that ran even if the process is killed. The 10M and
sharded-engine sections run behind an elapsed-time budget
(``BENCH_BUDGET_S`` env, default 2700 s): once the budget is near, the
remaining sections are recorded as skipped and the run exits rc=0.

Metric per BASELINE.json: rounds-to-99%-coverage and peers·rounds/sec on a
1M-node power-law (γ=2.5) swarm, plus the 10M-peer north-star run
(BASELINE.json north_star: "10M-peer power-law swarm to 99% coverage < 60 s").
Runs are single on-device while_loops (compile + warmup excluded; min wall
over 3 reps because the axon tunnel has high run-to-run variance).

Every dissemination config is measured over THREE delivery paths —
``xla`` (gather + serialized `.at[].max` scatter, kernels/gossip.py),
``pallas`` (the staircase MXU kernel, kernels/pallas_segment.py: flood via
``segment_or``, push/push-pull via ``segment_sampled`` — replacing the
reference's per-socket send loop, reference Peer.py:395-408), and
``matching`` (the gather-free structured-matching pipeline,
core/matching_topology.py + kernels/matching.py, measured on its own
generator of the same erased-configuration-model family). The headline
number is the fastest path; all appear under ``configs`` so the comparison
is reproducible from this artifact alone.

Headline configs run ``msg_slots=16`` with one rumor seeded per slot
(``init_swarm(origin_slots=...)``) so the dedup bitmap, packing, and (N, M)
traffic the engine is designed around are all exercised; the historical
``msg_slots=1`` shape is recorded too for cross-round comparability.

North-star accounting is explicit: ``setup_seconds_cold`` (first on-device
graph build, includes XLA compile) vs ``setup_seconds_warm`` (second build,
compile cached — the steady-state cost), and ``met`` is defined as
warm-setup + best sim wall < 60 s (``met_definition`` states this; the
sim-only and cold-setup readings are also reported).

``vs_baseline`` compares against the reference's intrinsic socket-mode
throughput: one gossip tick per 5 s per peer (reference Peer.py:396-408,
SURVEY.md §6) at its 1k-peer demonstrated scale ⇒ 1000 peers × 0.2
rounds/sec = 200 peers·rounds/sec. The reference publishes no other numbers
(readme.md:1-11; BASELINE.json "published": {}).

Hardware ceilings are measured on STREAMING-SCALE arrays (64 MiB, dispatch
amortized over the loop) so they are comparable to chip spec — a v5e's HBM
is ~819 GB/s; the measurement notes the spec fraction so utilization claims
are not self-referential. Per-config ``access_rate_per_sec_M`` uses the
random-access ceiling as denominator: dissemination is bound by random
gather/scatter access rate, not FLOPs (SURVEY.md §5.1 accounting).

Every record carries ``lint_clean``: the graftlint AST-rule verdict
(tpu_gossip/analysis, docs/static_analysis.md) for the tree that produced
the numbers — so a benchmark artifact from an invariant-dirty tree is
visibly marked — plus ``lint_deep_s``, the combined rules + contract
audit + jaxpr deep-tier wall time measured in a subprocess (the quantity
the CI lint-deep job budgets under 120 s). ``--quick`` runs never clobber
a full run's measurements, but they DO refresh the
``lint_clean``/``lint``/``lint_deep_s`` fields in BENCH_DETAIL.json. The r5 ``patch_note`` hand-patch mechanism is retired:
full runs emit no patch/provenance fields (the record IS what this script
measured), and the committed record's ``provenance_note`` — disclosing
the r5 entries that were hand-re-measured — rides along until the next
full hardware bench rewrites the record from scratch.

Flags: --quick (1M only, 1 rep, skips the sharded-engine entry — the smoke
invocation, see README) · --dist (force the sharded-engine run even under
--quick) · --profile DIR (jax.profiler trace of one warmed headline run).
Env: BENCH_BUDGET_S (elapsed-seconds budget for the post-headline
sections; default 2700).
"""

from __future__ import annotations

import dataclasses
import json
import math
import sys
import time

REFERENCE_PEERS_ROUNDS_PER_SEC = 200.0  # 1k peers, 1 round / 5 s (Peer.py:396-408)
V5E_HBM_GBPS = 819.0  # public v5e spec, the sanity anchor for the measurement


def _measure_ceilings(jax, jnp):
    """Measure this chip's elementwise bandwidth and random-access rate.

    Two-point slope method: time the same on-device fori_loop at N1 and N2
    iterations and divide the difference by (N2 - N1), so the constant
    per-dispatch + result-fetch latency (which dominates on the axon tunnel
    and previously made the figure look ~100x under spec) cancels exactly.
    64 MiB operands keep the loop body HBM-streaming-bound. The elementwise
    figure is then comparable to chip spec (the JSON carries the spec
    fraction); the random-access figure is the gather rate that actually
    bounds gossip rounds.
    """
    import numpy as np

    n = 16_777_216  # 64 MiB of int32
    a = jnp.asarray(np.random.default_rng(0).integers(0, 2**31, (n,), dtype=np.int32))
    idx = jnp.asarray(np.random.default_rng(1).integers(0, n, (n,), dtype=np.int32))

    def slope(body, carry, n1, n2):
        """Per-iteration seconds, by timing n1- vs n2-iteration loops.

        n2 - n1 must be large enough that the extra device time clears the
        tunnel's run-to-run noise (tens of ms) — the elementwise body is
        ~0.25 ms/iter at spec, hence its much larger n2. A nonpositive
        slope (noise won) returns NaN rather than an absurd ceiling.
        """

        def run(iters):
            f = jax.jit(
                lambda c: jax.lax.fori_loop(0, iters, body, c), static_argnums=()
            )
            out = f(carry)
            _ = float(jnp.sum(out))  # fetch = completion barrier on axon
            best = float("inf")
            for _rep in range(3):
                t0 = time.perf_counter()
                out = f(carry)
                _ = float(jnp.sum(out))
                best = min(best, time.perf_counter() - t0)
            return best

        dt = (run(n2) - run(n1)) / (n2 - n1)
        return dt if dt > 0 else float("nan")

    # elementwise: read a + read c + write c = 3 x 64 MiB per iter
    t_ew = slope(lambda i, c: c ^ (c | a), a, 32, 512)
    # random gather: 16M 4-byte accesses per iter (plus the streaming write)
    t_g = slope(lambda i, c: c ^ a[(idx + i) % n], a, 4, 64)
    def fin(x, digits):  # NaN -> None so the JSON line stays strictly parseable
        return round(x, digits) if math.isfinite(x) else None

    ew_gbps = 3 * 4 * n / t_ew / 1e9
    return {
        "elementwise_GBps": fin(ew_gbps, 1),
        "elementwise_frac_of_v5e_spec": fin(ew_gbps / V5E_HBM_GBPS, 3),
        "random_access_per_sec_M": fin(n / t_g / 1e6, 1),
        "note": "two-point slope over short-vs-long on-device loops, 64MiB "
        "operands (dispatch+fetch latency cancels); spec anchor 819 GB/s "
        "(v5e HBM) — frac > 1 means a newer-generation part (v6e ~1.64 TB/s)",
    }


def _accesses_per_round(cfg, n_edges: int) -> int:
    """Random HBM accesses per round (gather+scatter), the binding resource
    for the XLA delivery path."""
    n = cfg.n_peers
    acc = 0
    if cfg.mode in ("push", "push_pull"):
        acc += 2 * n * cfg.fanout  # target gather + delivery scatter
    if cfg.mode == "push_pull":
        acc += 2 * n  # pull: neighbor gather + seen gather
    if cfg.mode == "flood":
        acc += 2 * n_edges  # every edge slot: transmit gather + delivery scatter
    return acc


def _build_plan(dg, fanout, rows, device=False):
    """Staircase plan over the padded CSR (once per graph).

    Returns ``(plan, build_seconds)`` — plan prep is part of honest
    end-to-end accounting. ``device=True`` uses the on-device builder
    (build_staircase_plan_device): right at 10M scale, where the host
    build's ~620 MB of CSR-down + tables-up tunnel traffic costs ~90 s and
    the device build pays only one jit compile; at 1M the host build's few
    seconds beat the compile, so it stays. ``rows`` per the on-TPU tuning
    re-sweep (2026-07-30, 1M γ=2.5 m16, slope-timed on the CURRENT
    kernel): rows=1024 wins flood too now (49.3 ms core vs 64.9 at the
    previously-tuned 128 — that earlier result belonged to an older
    kernel) and sampled push_pull is flat 51-53 ms across 512-2048, so
    every config uses rows=1024.
    """
    import numpy as np

    from tpu_gossip.kernels.pallas_segment import (
        build_staircase_plan, build_staircase_plan_device,
    )

    t0 = time.perf_counter()
    if device:
        plan = build_staircase_plan_device(
            dg.row_ptr, dg.col_idx, fanout=fanout, rows=rows
        )
        int(plan.offs[-1, -1])  # scalar fetch = completion barrier on axon
    else:
        plan = build_staircase_plan(
            np.asarray(dg.row_ptr), np.asarray(dg.col_idx), fanout=fanout, rows=rows
        )
    return plan, time.perf_counter() - t0


def _build_matching(n: int, fanout: int, key_i: int = 0, export_csr: bool = True):
    """Structured-matching graph + plan (its own generator — the pairing IS
    the delivery plan, so one build covers both). Returns
    ``(graph, plan, build_seconds)``; the barrier is a host scalar fetch
    (axon's block_until_ready can return early). ``export_csr=False`` skips
    the CSR sorts — valid for configs that never read it (dissemination /
    SIR / liveness on the matching path); churn re-wiring requires it."""
    import jax
    import jax.numpy as jnp

    from tpu_gossip.core.matching_topology import matching_powerlaw_graph

    t0 = time.perf_counter()
    graph, plan = matching_powerlaw_graph(
        n, gamma=2.5, fanout=fanout, key=jax.random.key(key_i),
        export_csr=export_csr,
    )
    int(jnp.sum(plan.valid))
    return graph, plan, time.perf_counter() - t0


def bench_one(
    dg,
    mode: str,
    fanout: int,
    *,
    msg_slots: int,
    reps: int,
    plan=None,
    max_rounds: int = 500,
    **cfg_kwargs,
):
    import jax
    import numpy as np

    from tpu_gossip.core.matching_topology import MatchingPlan
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.metrics import bench_swarm

    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=msg_slots, fanout=fanout, mode=mode,
        **cfg_kwargs,
    )
    # one rumor per slot (distinct origins) so every slot carries traffic;
    # coverage/rounds-to-target are measured on slot 0 as always
    origins = np.arange(msg_slots)
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=origins,
        origin_slots=np.arange(msg_slots), exists=dg.exists,
        key=jax.random.key(0),
    )
    res, _ = bench_swarm(state, cfg, 0.99, max_rounds, reps=reps, plan=plan)
    # XLA flood touches every col_idx slot (erased ones included), so use
    # the real array length when a CSR exists; CSR-free builds (col_idx
    # (1,)) fall back to the degree-true row_ptr span
    n_edges = int(dg.col_idx.shape[0])
    if n_edges <= 1:
        n_edges = int(dg.row_ptr[-2])
    acc = _accesses_per_round(cfg, n_edges)
    if plan is None:
        delivery = "xla"
    elif isinstance(plan, MatchingPlan):
        delivery = "matching"
    else:
        delivery = "pallas"
    out = {
        **{k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in dataclasses.asdict(res).items()},
        "msg_slots": msg_slots,
        "delivery": delivery,
        "accesses_per_round_M": round(acc / 1e6, 2),
    }
    if plan is not None:
        # the kernel paths stream tiles/slots — random access is not their
        # binding resource, so no utilization rate here
        out["plan_rows"] = plan.rows
    else:
        out["access_rate_per_sec_M"] = round(
            acc / max(res.ms_per_round, 1e-9) / 1e3, 1
        )
    return out


def bench_liveness(n: int = 1000, silent_frac: float = 0.1, rounds: int = 20,
                   reps: int = 3):
    """BASELINE config 2: 1k peers + 3-miss liveness.

    ``silent_frac`` peers are silenced from round 0 (the operator-'1' fault,
    reference Peer.py:437-439, vectorized); the detector must declare all of
    them dead. Under the 1-round=5 s mapping the reference's worst-case
    detection is 30-42 s (SURVEY.md §6): stale after 6 rounds + the 2-round
    sweep puts detection at round 8 = 40 s-equivalent, inside the band.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.core.topology import build_csr, preferential_attachment
    from tpu_gossip.sim.engine import simulate

    rng = np.random.default_rng(0)
    graph = build_csr(n, preferential_attachment(n, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=3, mode="push")
    state = init_swarm(graph, cfg, origins=[0], key=jax.random.key(0))
    k = int(silent_frac * n)
    silent_ids = rng.choice(n, size=k, replace=False)
    state.silent = state.silent.at[jnp.asarray(silent_ids)].set(True)

    # simulate DONATES its state — every run gets a fresh clone, cloned
    # outside the timed region (sim/engine.py donation contract)
    from tpu_gossip.core.state import clone_state

    fin, stats = simulate(clone_state(state), cfg, rounds)  # warm + trace
    dead_per_round = np.asarray(stats.n_declared_dead)
    hit = np.nonzero(dead_per_round >= k)[0]
    detection_round = int(hit[0]) + 1 if hit.size else -1
    best = float("inf")
    for _ in range(max(reps, 1)):
        rep_state = clone_state(state)
        t0 = _time.perf_counter()
        fin, _ = simulate(rep_state, cfg, rounds)
        float(fin.coverage(0))  # completion barrier
        best = min(best, _time.perf_counter() - t0)
    secs = detection_round * cfg.round_seconds if detection_round > 0 else -1.0
    return {
        "n_peers": n, "silent": k,
        "detected": int(dead_per_round[-1]),
        "detection_round": detection_round,
        "detection_seconds_equiv": secs,
        "reference_band_seconds": [30, 42],
        "within_reference_band": bool(30 <= secs <= 42),
        "ms_per_round": round(best / rounds * 1000.0, 4),
    }


def bench_grow(n_target: int, n0: int, joins_per_round: int = 256,
               msg_slots: int = 16, reps: int = 1):
    """Growth engine at headline scale (growth/, docs/growth_engine.md).

    Admit ``n_target - n0`` peers by in-round preferential attachment
    (Gumbel-top-k over the realized degree vector) and price the GROWING
    round against the fixed-n round on the same capacity-padded state —
    the admission stage's marginal cost is one (J, N) Gumbel + top-k +
    registry scatters per round. Also reports the grown tail's γ-MLE so
    the headline-scale degree-evolution claim is measured, not assumed.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.core.topology import fit_powerlaw_gamma
    from tpu_gossip.growth import compile_growth, pad_graph_for_growth
    from tpu_gossip.growth.engine import realized_degrees
    from tpu_gossip.sim.engine import simulate

    dg = device_powerlaw_graph(n0, gamma=2.5, key=jax.random.key(0))
    cap = n_target + 1  # + the device builder's sentinel row
    graph, pad_exists = pad_graph_for_growth(dg.as_padded_graph(), cap)
    # the sentinel row must stay non-existent AND non-admittable: fold the
    # builder's exists into the padded mask; admission starts past it
    pad_exists[: n0 + 1] = np.asarray(dg.exists)
    cfg = SwarmConfig(
        n_peers=cap, msg_slots=msg_slots, fanout=1, mode="push_pull",
        rewire_slots=3,
    )
    state = init_swarm(
        graph, cfg, origins=np.arange(msg_slots),
        origin_slots=np.arange(msg_slots),
        exists=jnp.asarray(pad_exists), key=jax.random.key(0),
    )
    gp = compile_growth(
        n_initial=n0 + 1, target=cap, n_slots=cap,
        joins_per_round=joins_per_round, attach_m=3,
    )
    rounds = (n_target - n0) // joins_per_round + 2

    def timed(grow):
        best = float("inf")
        fin = None
        for _ in range(max(reps, 1)):
            rep = clone_state(state)  # outside the timer (donation contract)
            t0 = _time.perf_counter()
            fin, _ = simulate(rep, cfg, rounds, None, "fused", None, grow)
            float(fin.coverage(0))  # completion barrier
            best = min(best, _time.perf_counter() - t0)
        return best, fin

    # warm both compiles on throwaway clones (simulate donates its state)
    for g in (gp, None):
        fin_w, _ = simulate(clone_state(state), cfg, rounds, None, "fused",
                            None, g)
        float(fin_w.coverage(0))
    del fin_w
    grow_wall, fin = timed(gp)
    fixed_wall, _ = timed(None)
    deg = np.asarray(realized_degrees(
        fin.row_ptr, fin.exists, fin.rewired, fin.rewire_targets,
        fin.degree_credit,
    ))
    gamma = fit_powerlaw_gamma(deg[np.asarray(fin.exists)])
    ms_grow = grow_wall / rounds * 1000.0
    ms_fixed = fixed_wall / rounds * 1000.0
    return {
        "n_initial": n0, "n_target": n_target,
        "joins_per_round": joins_per_round, "rounds": rounds,
        "n_members_final": int(np.asarray(fin.exists).sum()),
        "growing": {"wall_seconds": round(grow_wall, 3),
                    "ms_per_round": round(ms_grow, 4)},
        "fixed_n": {"wall_seconds": round(fixed_wall, 3),
                    "ms_per_round": round(ms_fixed, 4)},
        "admission_overhead_vs_fixed": round(ms_grow / max(ms_fixed, 1e-9), 3),
        "grown_degree_gamma": round(gamma, 4),
    }


def _round_opt(x, nd: int = 2):
    """round() that passes None through (empty percentile tracks)."""
    return None if x is None else round(x, nd)


def bench_stream(n: int, rates=(0.5, 1.5, 4.0), msg_slots: int = 32,
                 ttl: int | None = None, measure_rounds: int = 96,
                 reps: int = 1, target: float = 0.99):
    """Streaming serving plane at headline scale (traffic/,
    docs/streaming_plane.md): sustained Poisson injection on the 1M
    swarm, measured over a SATURATION CURVE of >=3 injection rates.

    Each rate runs one fixed-horizon loaded simulate (ttl rounds of
    warmup dropped, ``measure_rounds`` measured) and reports the serving
    metrics the ROADMAP's millions-of-users claim is priced by:
    delivered msgs/sec (at the config's 5 s round), p50/p99
    rounds-to-coverage PER MESSAGE, conflation rate under load, and the
    delivered-vs-offered ratio — whose collapse past ``msg_slots/ttl``
    msgs/round (the slot budget over the lease horizon) IS the
    saturation point: ``saturation_rate_msgs_per_round`` records the
    smallest tested rate where delivered falls below half of offered
    (None when no tested rate collapses — an honest "not driven to
    saturation", never max(rates)).
    The loaded round is timed against the unloaded round on the same
    state, so the streaming stage's marginal cost is explicit. One
    compile serves every rate: ``max_inject`` is pinned to the largest
    rate's batch shape, and the arrival rate rides a traced scalar.
    """
    import time as _time

    import jax
    import numpy as np

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.sim import metrics as SM
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.traffic import (
        compile_stream, default_max_inject, min_feasible_ttl,
    )

    dg = device_powerlaw_graph(n, gamma=2.5, key=jax.random.key(0))
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=msg_slots, fanout=2, mode="push_pull"
    )
    state = init_swarm(
        dg.as_padded_graph(), cfg, exists=dg.exists, key=jax.random.key(0)
    )
    feasible = min_feasible_ttl(n, cfg.fanout)
    if ttl is None:
        ttl = int(1.5 * feasible)
    origin_rows = np.flatnonzero(np.asarray(dg.exists))
    horizon = ttl + measure_rounds
    max_inject = default_max_inject(max(rates))

    def stream_for(rate):
        return compile_stream(
            rate=rate, msg_slots=msg_slots, ttl=ttl,
            origin_rows=origin_rows, max_inject=max_inject,
        )

    def timed(strm, rounds):
        best, stats = float("inf"), None
        for _ in range(max(reps, 1)):
            rep = clone_state(state)  # outside the timer (donation contract)
            t0 = _time.perf_counter()
            fin, stats = simulate(rep, cfg, rounds, None, "fused", None,
                                  None, strm)
            float(fin.coverage(0))  # completion barrier
            best = min(best, _time.perf_counter() - t0)
        return best, stats

    # warm both compiles on throwaway clones (simulate donates its state)
    for s in (stream_for(rates[0]), None):
        fin_w, _ = simulate(clone_state(state), cfg, horizon, None, "fused",
                            None, None, s)
        float(fin_w.coverage(0))
    del fin_w
    unloaded_wall, _ = timed(None, horizon)
    ms_unloaded = unloaded_wall / horizon * 1000.0

    curve = []
    for rate in rates:
        wall, stats = timed(stream_for(rate), horizon)
        rep = SM.steady_state_report(
            stats, target=target, round_seconds=cfg.round_seconds,
            warmup_rounds=ttl,
        )
        ms_loaded = wall / horizon * 1000.0
        curve.append({
            "rate_msgs_per_round": rate,
            "delivered_msgs_per_sec": rep["delivered_msgs_per_sec"],
            "delivered_per_round": rep["delivered_per_round"],
            "offered_per_round": rep["offered_per_round"],
            "delivery_ratio": rep["delivery_ratio"],
            "conflation_rate": rep["conflation_rate"],
            "p50_rounds_to_coverage": _round_opt(
                rep["rounds_to_coverage"]["p50"]
            ),
            "p99_rounds_to_coverage": _round_opt(
                rep["rounds_to_coverage"]["p99"]
            ),
            "episodes_completed": rep["episodes_completed"],
            "ms_per_round": round(ms_loaded, 4),
            "stream_overhead_vs_unloaded": round(
                ms_loaded / max(ms_unloaded, 1e-9), 3
            ),
        })
    best = max(curve, key=lambda c: c["delivered_per_round"])
    # the MEASURED saturation onset: the smallest tested rate where most
    # offered traffic stops opening its own episode (delivered collapses
    # below half of offered — conflation/suppression dominating). None =
    # the curve never drove the plane past its knee, a statement the
    # record should make honestly rather than reporting max(rates)
    saturated = [
        c["rate_msgs_per_round"] for c in curve
        if c["delivered_per_round"] < 0.5 * c["offered_per_round"]
    ]
    return {
        "n_peers": n, "msg_slots": msg_slots, "slot_ttl": ttl,
        "mode": cfg.mode, "horizon_rounds": horizon,
        "warmup_rounds_dropped": ttl, "coverage_target": target,
        "slot_budget_msgs_per_round": round(msg_slots / ttl, 3),
        "unloaded_ms_per_round": round(ms_unloaded, 4),
        "curve": curve,
        "saturation_rate_msgs_per_round": min(saturated) if saturated
        else None,
        "peak_delivered_msgs_per_sec": best["delivered_msgs_per_sec"],
    }


def _reference_single_socket_msgs_per_sec(n_msgs: int = 50_000) -> float:
    """Measured throughput of the reference's peer send loop shape: ONE
    socket, one blocking ``sendall`` per gossip line (reference
    Peer.py:395-408 sends to each neighbor this way, serially). A
    drain thread reads lines off the other end so the kernel buffer
    never stalls the sender — this is therefore an UPPER bound for the
    reference loop, which also sleeps between ticks and re-enters
    Python per neighbor."""
    import socket as _socket
    import threading as _threading
    import time as _time

    from tpu_gossip.compat import wire

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    done = _threading.Event()

    def drain():
        conn, _ = srv.accept()
        f = conn.makefile("rb")
        for _ in range(n_msgs):
            f.readline()
        done.set()
        conn.close()

    t = _threading.Thread(target=drain, daemon=True)
    t.start()
    out = _socket.create_connection(("127.0.0.1", srv.getsockname()[1]))
    line = wire.encode_gossip("2025-01-01 00:00:00", "10.0.0.1", 6000, 1)
    t0 = _time.perf_counter()
    for _ in range(n_msgs):
        out.sendall(line)
    done.wait(120)
    wall = _time.perf_counter() - t0
    out.close()
    srv.close()
    return n_msgs / max(wall, 1e-9)


def bench_serve(n: int = 1_000_000, rounds: int = 12, clients: int = 8,
                msgs_per_client: int = 400, msg_slots: int = 32):
    """The live-ingestion frontend at headline scale (serve/,
    docs/serving_frontend.md): real loopback-socket clients hammer the
    reference wire protocol at a 1M-peer swarm while the round driver
    double-buffers each window's injection against the in-flight device
    round, unpaced (rounds_per_sec=0 — every round starts the moment
    the previous one's stats land).

    Reports sustained ACCEPTED msgs/sec through socket → parse → window
    → device injection, the loaded ms/round, and the measured
    single-socket throughput of the reference peer send loop for scale.
    CPU-container caveat: both sides of the socket and the device round
    share one host's cores, so the accepted-rate and the reference rate
    are both loopback-bound figures, not cross-machine wire rates.
    """
    import threading as _threading

    import jax
    import numpy as np

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.serve import ServeDriver, ServeFrontend, build_step, run_load
    from tpu_gossip.traffic import compile_stream, min_feasible_ttl
    from tpu_gossip.traffic.ingest import IngestPlan

    dg = device_powerlaw_graph(n, gamma=2.5, key=jax.random.key(0))
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=msg_slots, fanout=2, mode="push_pull"
    )
    state = init_swarm(
        dg.as_padded_graph(), cfg, exists=dg.exists, key=jax.random.key(0)
    )
    ttl = int(1.5 * min_feasible_ttl(n, cfg.fanout))
    origin_rows = np.flatnonzero(np.asarray(dg.exists))
    strm = compile_stream(rate=0.0, msg_slots=msg_slots, ttl=ttl,
                          origin_rows=origin_rows)
    max_inject = 1024
    plan = IngestPlan(msg_slots=msg_slots, max_inject=max_inject, k_hashes=1)

    fe = ServeFrontend(origin_rows=origin_rows, max_inject=max_inject, port=0)
    fe.start()
    try:
        box = {}
        loader = _threading.Thread(target=lambda: box.update(rep=run_load(
            "127.0.0.1", fe.port, clients=clients,
            msgs_per_client=msgs_per_client, jitter_s=0.0, seed=0,
        )), daemon=True)
        loader.start()
        driver = ServeDriver(build_step(cfg, stream=strm), state, fe, plan,
                             rounds=rounds, rounds_per_sec=0.0)
        rep = driver.run()
        loader.join(timeout=300)
    finally:
        fe.stop()

    offered = int(np.asarray(rep.stats.ingest_offered).sum())
    injected = int(np.asarray(rep.stats.ingest_injected).sum())
    overflow = int(np.asarray(rep.stats.ingest_overflow).sum())
    accepted_per_sec = rep.trace.total_arrivals / max(rep.wall_seconds, 1e-9)
    ref_rate = _reference_single_socket_msgs_per_sec()
    return {
        "n_peers": n, "msg_slots": msg_slots, "slot_ttl": ttl,
        "rounds": rounds, "max_inject": max_inject,
        "clients": clients, "msgs_sent": clients * msgs_per_client,
        "load_errors": box["rep"].errors if "rep" in box else None,
        "accepted_arrivals": rep.trace.total_arrivals,
        "ingest_offered": offered, "ingest_injected": injected,
        "ingest_overflow": overflow,
        "accepted_msgs_per_sec": round(accepted_per_sec, 1),
        "loaded_ms_per_round": round(
            1000.0 * rep.wall_seconds / rounds, 3
        ),
        "reference_single_socket_msgs_per_sec": round(ref_rate, 1),
        "caveat": "CPU container: clients, frontend and device round "
        "share one host's cores over loopback; the reference figure is "
        "a drain-thread upper bound on its blocking per-neighbor "
        "sendall loop (Peer.py:395-408), not a cross-machine rate",
    }


def bench_control(n: int, horizon: int = 48, reps: int = 1,
                  target: float = 0.99):
    """Adaptive control at headline scale (control/,
    docs/adaptive_control.md): controlled vs static
    messages-per-delivered-infection at equal-or-better rounds-to-99%,
    on the 1M sharded matching mesh — the acceptance metric of the
    coverage-feedback fanout.

    Both runs are fixed-horizon ``simulate_dist`` on the SAME swarm
    (per-round stats give the coverage curve and the message bill); the
    bill is cut at each run's own rounds-to-target, so the comparison is
    messages spent to REACH coverage, not messages spent idling after
    it. The controller opens at its widest clean level (the early
    epidemic, where extra fanout is nearly duplicate-free) and AIMD
    halves down as duplicates saturate — the two phases *Push is Fast on
    Sparse Random Graphs* says a static fanout overpays.
    """
    import time as _time

    import jax
    import numpy as np

    from tpu_gossip.control import compile_control
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.dist import (
        make_mesh, shard_matching_plan, shard_swarm, simulate_dist,
    )
    from tpu_gossip.sim import metrics as SM

    mesh = make_mesh()
    fanout = 3
    dg, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=fanout, key=jax.random.key(0),
        export_csr=False,
    )
    # push_pull: the mode where BOTH controller levers bite — the fanout
    # table shapes the push budget, the mix table hands the saturated
    # tail to the anti-entropy half (push-only runs floor at base and
    # save only the ramp rounds)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=fanout,
                      mode="push_pull")
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    state = shard_swarm(state, mesh)
    splan = shard_matching_plan(plan, mesh)
    ctl = compile_control(target_ratio=target, fanout=fanout, lo=1,
                          hi=2 * fanout)

    def run(control):
        best, stats = float("inf"), None
        for _ in range(max(reps, 1)):
            rep = clone_state(state)  # outside the timer (donation contract)
            t0 = _time.perf_counter()
            fin, stats = simulate_dist(rep, cfg, splan, mesh, horizon,
                                       control=control)
            float(fin.coverage(0))  # completion barrier
            best = min(best, _time.perf_counter() - t0)
        rtc = SM.rounds_to_coverage(stats, target)
        cut = rtc if rtc > 0 else horizon
        msgs = int(np.asarray(stats.msgs_sent[:cut]).astype(np.int64).sum())
        ninf = int(np.asarray(stats.n_infected)[cut - 1])
        return {
            "rounds_to_target": rtc,
            "msgs_to_target": msgs,
            "infections_delivered": ninf,
            "msgs_per_delivered_infection": round(msgs / max(ninf, 1), 3),
            "ms_per_round": round(best / horizon * 1000.0, 4),
            "final_coverage": float(np.asarray(stats.coverage)[-1]),
        }, stats

    # warm both compiles on throwaway clones (the engines donate)
    for c in (None, ctl):
        fin_w, _ = simulate_dist(clone_state(state), cfg, splan, mesh,
                                 horizon, control=c)
        float(fin_w.coverage(0))
    del fin_w

    static, _ = run(None)
    controlled, ctl_stats = run(ctl)
    s_mpi = static["msgs_per_delivered_infection"]
    c_mpi = controlled["msgs_per_delivered_infection"]
    return {
        "n_peers": n, "devices": mesh.size, "mode": cfg.mode,
        "fanout_static": fanout, "control_bounds": [1, 2 * fanout],
        "target": target, "horizon_rounds": horizon,
        "static": static,
        "controlled": controlled,
        # the acceptance pair: the message-bill reduction AND the
        # equal-or-better rounds guarantee it was bought at
        "msgs_per_infection_reduction": round(1.0 - c_mpi / s_mpi, 4),
        # the controlled wall-clock A/B as a first-class record entry
        # (previously a 'needs a real mesh' ROADMAP note)
        "wallclock_ab": {
            "static_ms_per_round": static["ms_per_round"],
            "controlled_ms_per_round": controlled["ms_per_round"],
            "controlled_over_static": round(
                controlled["ms_per_round"] / max(static["ms_per_round"], 1e-9),
                3,
            ),
            "hardware_note": HARDWARE_AB_NOTE,
        },
        "rounds_equal_or_better": (
            controlled["rounds_to_target"] > 0
            and (static["rounds_to_target"] <= 0
                 or controlled["rounds_to_target"]
                 <= static["rounds_to_target"])
        ),
        "reliability": SM.reliability_report(
            ctl_stats, target_ratio=target, coverage_target=target,
        ),
    }


def bench_adv(n: int, horizon: int = 16, reps: int = 1):
    """Quorum-detector overhead at headline scale (kernels/liveness.py,
    docs/adversarial_model.md): the hardened detector vs the direct one
    on the SAME 1M sharded matching swarm, no adversaries — the pure
    price of the defense (ms/round delta from the suspicion machine's
    extra row-level work, bytes/peer delta from the three new planes,
    quoted from the PLANES registry — 5 B/peer at any scale). The
    attack-vs-defense ACCEPTANCE numbers live in the byzantine_siege
    demonstration pair (tests/sim/test_adversary.py) and the fleet-smoke
    campaign; this entry records what a hardened production run pays
    when nothing is attacking it.
    """
    import time as _time

    import jax

    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import (
        SwarmConfig, clone_state, init_swarm, state_bytes_per_peer,
    )
    from tpu_gossip.dist import (
        make_mesh, shard_matching_plan, shard_swarm, simulate_dist,
    )
    from tpu_gossip.kernels.liveness import compile_quorum

    mesh = make_mesh()
    dg, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=3, key=jax.random.key(0),
        export_csr=False,
    )
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=3, mode="push")
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    state = shard_swarm(state, mesh)
    splan = shard_matching_plan(plan, mesh)
    quorum = compile_quorum(3, window=4, budget=3)

    def run(liveness):
        best = float("inf")
        for _ in range(max(reps, 1)):
            rep = clone_state(state)  # outside the timer (donation contract)
            t0 = _time.perf_counter()
            fin, _ = simulate_dist(rep, cfg, splan, mesh, horizon,
                                   liveness=liveness)
            float(fin.coverage(0))  # completion barrier
            best = min(best, _time.perf_counter() - t0)
        return round(best / horizon * 1000.0, 4)

    for lv in (None, quorum):  # warm both compiles on throwaway clones
        fin_w, _ = simulate_dist(clone_state(state), cfg, splan, mesh,
                                 horizon, liveness=lv)
        float(fin_w.coverage(0))
    del fin_w

    direct_ms = run(None)
    quorum_ms = run(quorum)
    # the plane cost is registry arithmetic — the REAL peak numbers ride
    # the mem tier (memory_budget.toml prices every traced entry)
    bpp = state_bytes_per_peer(n, cfg.msg_slots)
    plane_bytes = 5.0  # suspect_round i16 + suspect_mark i16 + quarantine b8
    return {
        "n_peers": n, "devices": mesh.size, "horizon_rounds": horizon,
        "quorum_k": quorum.quorum_k, "window": quorum.window,
        "budget": quorum.budget,
        "direct_ms_per_round": direct_ms,
        "quorum_ms_per_round": quorum_ms,
        "quorum_over_direct_ms": round(quorum_ms - direct_ms, 4),
        "bytes_per_peer": round(bpp, 1),
        "suspicion_planes_bytes_per_peer": plane_bytes,
        "hardware_note": HARDWARE_AB_NOTE,
    }


def bench_churn_remat(dg, *, msg_slots: int = 16, reps: int = 3,
                      remat_every: int = 16, plan=None,
                      rewire_compact_cap: int = 0):
    """BASELINE config 5 with periodic re-materialization, measured honestly.

    Churn runs ``remat_every`` rounds, the fresh edges are folded into the
    CSR (sim.engine.rematerialize_rewired), and the NEXT segment plus the
    rebuild's warm cost are measured. With ``rewire_compact_cap`` the
    segment runs the bounded-table side paths — the remat-era operating
    point: the cap only has to hold ``remat_every`` rounds of joiners
    (the fold empties the rewired set), so it can be ~N·join_prob·R
    instead of the whole-horizon accumulation the no-remat compact entry
    needs. The amortized figure's floor decomposes as
    base + O(cap) side paths + remat_seconds/remat_every — remat is a
    LONG-HORIZON correctness mechanism (the rewired set cannot grow
    without bound), not a short-run rate win; this entry prices that
    trade instead of asserting it (docs/kernel_profile_1m.md addendum).
    """
    import jax
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.sim.engine import (
        remat_capacity, rematerialize_rewired, simulate,
    )

    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=msg_slots, fanout=1, mode="push_pull",
        churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
        rewire_compact_cap=rewire_compact_cap,
    )
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=np.arange(msg_slots),
        origin_slots=np.arange(msg_slots), exists=dg.exists,
        key=jax.random.key(0),
    )
    def rebuild_plan(st):
        """Post-remat kernel plan: the fold changed the CSR, and
        rematerialize_rewired's contract requires plan holders to rebuild
        (stale plans would deliver the DROPPED edges and miss the folded
        fresh ones). Device build; cost is part of the epoch charge."""
        if plan is None:
            return None, 0.0
        from tpu_gossip.kernels.pallas_segment import (
            build_staircase_plan_device,
        )

        t0 = time.perf_counter()
        p = build_staircase_plan_device(
            st.row_ptr, st.col_idx, fanout=cfg.fanout, rows=plan.rows
        )
        int(p.offs[-1, -1])  # fetch = completion barrier
        return p, time.perf_counter() - t0

    cap = remat_capacity(state, cfg)
    state, _ = simulate(state, cfg, remat_every, plan)  # accumulate real churn
    state, _ = rematerialize_rewired(state, cfg, cap)
    seg_plan, _ = rebuild_plan(state)

    # the engines donate their state: clones per run, outside the timer
    fin, _ = simulate(clone_state(state), cfg, remat_every, seg_plan)  # warm
    float(fin.coverage(0))
    best = float("inf")
    for _ in range(max(reps, 1)):
        rep_state = clone_state(state)
        t0 = time.perf_counter()
        fin, _ = simulate(rep_state, cfg, remat_every, seg_plan)
        float(fin.coverage(0))  # completion barrier
        best = min(best, time.perf_counter() - t0)
    seg_ms = best / remat_every * 1000.0

    nxt, ov = rematerialize_rewired(clone_state(fin), cfg, cap)  # warm remat
    int(ov)
    fin2 = clone_state(fin)
    t0 = time.perf_counter()
    nxt, ov = rematerialize_rewired(fin2, cfg, cap)
    overflow = int(ov)  # fetch = completion barrier
    remat_s = time.perf_counter() - t0
    # warm THEN time on the SAME state: the device plan build's jit keys on
    # the (data-dependent, quantized) tile count, so a rebuild for a
    # different fold can recompile — the steady-state epoch charge is the
    # warm figure, like every other setup cost in this artifact
    rebuild_plan(nxt)
    _, plan_rebuild_s = rebuild_plan(nxt)
    epoch_s = remat_s + plan_rebuild_s
    return {
        "n_peers": dg.n_pad, "msg_slots": msg_slots,
        "remat_every": remat_every,
        "ms_per_round": round(seg_ms, 4),
        "remat_seconds": round(remat_s, 3),
        "plan_rebuild_seconds": round(plan_rebuild_s, 3),
        "ms_per_round_amortized": round(
            seg_ms + epoch_s * 1000.0 / remat_every, 4
        ),
        "overflow_edges": overflow,
        "rewire_compact_cap": rewire_compact_cap,
        "delivery": "pallas" if plan is not None else "xla",
    }


def bench_tail_ab(dg, plan=None, reps: int = 3, warm_rounds: int = 6):
    """The --tail default decision, automated (ISSUE 10 satellite): the
    composed round slope-timed per tail implementation on THIS platform,
    so the next hardware bench run answers the open pallas-default
    question without hand work.

    The config turns every tail branch on (SIR + churn fresh masks ride
    the producing selects). On a CPU container the pallas tail is
    interpret-mode — functional-only, unmeasurable at scale — so the
    A/B covers reference vs fused and records the caveat; on a TPU the
    pallas row appears and the decision is the fastest composed round.
    """
    import jax

    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.utils.profiling import profile_round_stages

    on_cpu = jax.default_backend() == "cpu"
    tails = ("reference", "fused") + (() if on_cpu else ("pallas",))
    if on_cpu:
        # the staircase delivery kernel interprets on CPU (functional-only,
        # hours at 1M) — the tail A/B needs only a delivery to feed the
        # tails, so the XLA path carries it here; on TPU the plan rides
        plan = None
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=16, fanout=1, mode="push_pull",
        sir_recover_rounds=8, churn_leave_prob=0.002, churn_join_prob=0.02,
        rewire_slots=2, rewire_compact_cap=65536,
    )
    st = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    warm, _ = simulate(clone_state(st), cfg, warm_rounds, plan)
    stages = profile_round_stages(warm, cfg, plan, reps=reps, tails=tails)
    composed = {
        impl: round(stages[f"full_round[{impl}]"] * 1e3, 4) for impl in tails
    }
    tail_ms = {
        impl: round(stages[f"tail[{impl}]"] * 1e3, 4) for impl in tails
    }
    decision = min(composed, key=composed.get)
    rec = {
        "n_peers": dg.n_pad, "mode": cfg.mode, "platform": jax.default_backend(),
        "tails_measured": list(tails),
        "tail_ms_per_round": tail_ms,
        "composed_ms_per_round": composed,
        "decision": decision,
        "decision_basis": "fastest composed round (SIR+churn config, all "
        "tail branches live) on this platform",
    }
    if on_cpu:
        rec["cpu_container_caveat"] = (
            "pallas tail is interpret-mode on CPU (functional-only, not "
            "measurable) — this A/B settles reference vs fused only; the "
            "pallas default stays open until this entry rides a TPU bench "
            "run, where the pallas row appears automatically"
        )
    return rec


def bench_packed_ab(n: int = 1_000_000, rounds: int = 8, reps: int = 3):
    """Packed-NATIVE vs unpack/repack round-trip at headline scale (the
    packed-native tentpole's measured claim): the same ``--packed`` loop
    timed twice — once with the round computing ON the uint8 bit words
    (sim/packed_engine), once through the retired shape that decoded the
    full bool planes every round and re-packed the product — on the
    local engine and the sharded-matching mesh. Alongside wall clock,
    graftmem's static ledger prices each round trace at this scale:
    peak-live over packed-resident, and the top source-line attribution
    (the acceptance ask: no longer the ``unpack_bits`` codec line).
    """
    import functools
    import time as _time

    import jax
    import numpy as np

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.packed import pack_state, unpack_state
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.sim.engine import gossip_round, simulate

    def graft(name, fn, state, n_peers):
        """Static ledger + attribution of one round trace at scale."""
        from tpu_gossip.analysis.deep.liveness import entry_liveness
        from tpu_gossip.analysis.entrypoints import EntryPoint, TracedEntry
        from tpu_gossip.analysis.mem.ledger import entry_ledger

        ep = EntryPoint(
            name=name, engine="bench", kind="round", audit_check="bench",
            build=lambda: (fn, state), n_peers=n_peers, packed=True,
        )
        te = TracedEntry(ep=ep, state=state)
        # drop cached helper jaxprs (jnp.where's jitted _where): a cached
        # trace re-inlines with its ORIGINAL source_info, so a bool warm
        # run would mislabel the packed round's attribution lines
        jax.clear_caches()
        te.jaxpr, te.out_shape = jax.make_jaxpr(fn, return_shape=True)(state)
        led = entry_ledger(name, te)
        live = entry_liveness(name, te)
        return {
            "peak_bytes_per_peer": round(led.peak_bytes / n, 2),
            "resident_bytes_per_peer": round(led.state_bytes / n, 2),
            "peak_over_resident": round(
                led.peak_bytes / max(led.state_bytes, 1), 2
            ),
            "top_attribution": live["top"][0][0],
        }

    def timed(fn, mk_state):
        jax.block_until_ready(fn(mk_state()))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            arg = mk_state()
            jax.block_until_ready(arg)
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(arg))
            best = min(best, _time.perf_counter() - t0)
        return round(best / rounds * 1e3, 4)

    # ---- local engine ---------------------------------------------------
    dg = device_powerlaw_graph(n, gamma=2.5, key=jax.random.key(5))
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=16, fanout=1, mode="push_pull"
    )
    st = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(5),
    )
    st, _ = simulate(st, cfg, 4)  # mid-epidemic planes: real work per word

    def native_local(ps):
        fin, _stats = simulate(ps, cfg, rounds)
        return fin

    @functools.partial(jax.jit, donate_argnums=0)
    def roundtrip_local(ps):
        def body(p, _):
            fin, _stats = gossip_round(unpack_state(p), cfg, None)
            return pack_state(fin), None
        out, _ = jax.lax.scan(body, ps, None, length=rounds)
        return out

    mk = lambda: pack_state(clone_state(st))  # noqa: E731
    local = {
        "native_ms_per_round": timed(native_local, mk),
        "roundtrip_ms_per_round": timed(roundtrip_local, mk),
        "graftmem_native": graft(
            "bench[packed-native,local]",
            lambda p: gossip_round(p, cfg, None)[0], mk(), dg.n_pad,
        ),
        "graftmem_roundtrip": graft(
            "bench[packed-roundtrip,local]",
            lambda p: pack_state(gossip_round(unpack_state(p), cfg, None)[0]),
            mk(), dg.n_pad,
        ),
    }
    local["native_over_roundtrip"] = round(
        local["native_ms_per_round"]
        / max(local["roundtrip_ms_per_round"], 1e-9), 3
    )
    del st, dg

    # ---- sharded-matching engine ---------------------------------------
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import make_mesh, shard_matching_plan, shard_swarm
    from tpu_gossip.dist.mesh import gossip_round_dist, simulate_dist

    mesh = make_mesh()
    if 128 % mesh.size:
        return {
            "n_peers": n, "rounds": rounds, "local": local,
            "dist_matching": {
                "unsupported": f"mesh size {mesh.size} does not divide 128"
            },
        }
    g, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    plan_m = shard_matching_plan(plan, mesh)
    cfg_d = SwarmConfig(
        n_peers=plan.n, msg_slots=16, fanout=1, mode="push_pull"
    )
    st0 = init_swarm(
        g.as_padded_graph(), cfg_d, origins=np.arange(cfg_d.msg_slots),
        origin_slots=np.arange(cfg_d.msg_slots), exists=g.exists,
        key=jax.random.key(0),
    )
    std = shard_swarm(st0, mesh)

    def native_dist(ps):
        fin, _stats = simulate_dist(ps, cfg_d, plan_m, mesh, rounds)
        return fin

    @functools.partial(jax.jit, donate_argnums=0)
    def roundtrip_dist(ps):
        def body(p, _):
            fin, _stats = gossip_round_dist(
                unpack_state(p), cfg_d, plan_m, mesh
            )
            return pack_state(fin), None
        out, _ = jax.lax.scan(body, ps, None, length=rounds)
        return out

    mkd = lambda: pack_state(clone_state(std))  # noqa: E731
    dist = {
        "devices": mesh.size,
        "native_ms_per_round": timed(native_dist, mkd),
        "roundtrip_ms_per_round": timed(roundtrip_dist, mkd),
        "graftmem_native": graft(
            "bench[packed-native,dist-matching]",
            lambda p: gossip_round_dist(p, cfg_d, plan_m, mesh)[0],
            mkd(), plan.n,
        ),
    }
    dist["native_over_roundtrip"] = round(
        dist["native_ms_per_round"]
        / max(dist["roundtrip_ms_per_round"], 1e-9), 3
    )
    return {
        "n_peers": n, "rounds": rounds, "msg_slots": 16,
        "local": local, "dist_matching": dist,
        "note": "roundtrip = the retired unpack->bool-round->repack loop "
        "body; native computes on the uint8 words (graftmem attribution "
        "names the residual full-width ops, not the codec)",
    }


def bench_pipeline(n: int, horizon: int = 24, reps: int = 1):
    """Pipelined vs serial sharded matching rounds at headline scale
    (ISSUE 10 acceptance): ms/round for the serial schedule vs the
    depth-1 double-buffered exchange on this mesh, with the extended
    profiler's stage decomposition attributing where the overlap can
    win (``delivery`` ≈ the issue the collective hides behind; the
    tail/liveness/stats rows are the shard-local work it hides in).

    Fixed-horizon ``simulate_dist`` on the SAME swarm both ways — the
    pipelined run does identical per-round work (same draws, same
    collective, one extra (N, M) carry), so the ms/round delta is pure
    schedule. Coverage context rides along: the depth-1 trajectory is
    one-round-stale (docs/pipelined_rounds.md), so rounds-to-99% grows —
    the win is round THROUGHPUT (and per-round-priced planes), priced
    honestly here next to the staleness cost.
    """
    import time as _time

    import jax
    import numpy as np

    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.dist import (
        make_mesh, shard_matching_plan, shard_swarm, simulate_dist,
    )
    from tpu_gossip.sim import metrics as SM
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.sim.stages import compile_pipeline
    from tpu_gossip.utils.profiling import profile_round_stages

    mesh = make_mesh()
    if 128 % mesh.size:
        return {
            "n_peers": n, "devices": mesh.size,
            "unsupported": f"mesh size {mesh.size} does not divide 128 "
            "(matching lane-split constraint)",
        }
    dg, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=1,
                      mode="push_pull")
    st0 = init_swarm(
        dg.as_padded_graph(), cfg, origins=np.arange(cfg.msg_slots),
        origin_slots=np.arange(cfg.msg_slots), exists=dg.exists,
        key=jax.random.key(0),
    )
    state = shard_swarm(st0, mesh)
    splan = shard_matching_plan(plan, mesh)

    def run(pipe):
        best, stats = float("inf"), None
        fin, stats = simulate_dist(clone_state(state), cfg, splan, mesh,
                                   horizon, pipeline=pipe)  # warm
        float(fin.coverage(0))
        for _ in range(max(reps, 1)):
            rep = clone_state(state)
            t0 = _time.perf_counter()
            fin, stats = simulate_dist(rep, cfg, splan, mesh, horizon,
                                       pipeline=pipe)
            float(fin.coverage(0))  # completion barrier
            best = min(best, _time.perf_counter() - t0)
        return {
            "ms_per_round": round(best / horizon * 1000.0, 4),
            "rounds_to_99pct": SM.rounds_to_coverage(stats, 0.99),
            "final_coverage": round(float(np.asarray(stats.coverage)[-1]), 4),
        }

    serial = run(None)
    pipelined = run(compile_pipeline(1))
    # the local twin's stage decomposition at the same scale: the overlap
    # attribution table (what the collective can hide behind/in)
    warm_l, _ = simulate(clone_state(st0), cfg, 4, plan)
    stages = profile_round_stages(warm_l, cfg, plan, reps=max(reps, 1),
                                  tails=("fused",))
    import math as _math

    return {
        "n_peers": n, "devices": mesh.size, "mode": cfg.mode,
        "horizon_rounds": horizon,
        "serial": serial,
        "pipelined": pipelined,
        "pipelined_over_serial_ms": round(
            pipelined["ms_per_round"] / max(serial["ms_per_round"], 1e-9), 3
        ),
        "stage_decomposition_local_ms": {
            k: (round(v * 1e3, 4) if _math.isfinite(v) else None)
            for k, v in stages.items()
        },
        "note": "depth-1 delivery is one round stale (rounds-to-coverage "
        "grows; the recurrence halves the effective hop rate) — the "
        "overlap win is ms/round and per-round-priced throughput. On "
        "this CPU container the all_to_all is a memcpy XLA does not "
        "run concurrently with compute, so the schedule win needs the "
        "real-mesh async collectives; the entry rides every bench run "
        "so the next hardware run records it without hand work. The "
        "local decomposition's delivery row interprets the matching "
        "lane shuffles on CPU (single-process; the dist rounds above "
        "run them 8-way per shard) — on TPU it is the real ~1.4 ms "
        "issue the collective hides behind",
    }


def bench_fleet(n: int = 131072, ks=(1, 8, 32), rounds: int = 10,
                reps: int = 1):
    """Fleet engine at aggregate-1M scale (fleet/, docs/fleet_campaigns.md):
    swarms/sec of ONE vmapped campaign program vs K serial runs — the
    batching win the ISSUE-12 tentpole exists for.

    K composed lanes (lossy scenario sweep × stream × adaptive control —
    the Monte Carlo certification workload) of n-peer swarms run as one
    batched program; at K=8 the fleet aggregates ~1M peers. Two serial
    baselines, both recorded: **in-process** (K sequential donated
    ``simulate`` calls sharing one compile — the conservative floor a
    smart serial driver could reach) and **serial processes** (the
    one-subprocess-per-config pattern the fleet-smoke CI job replaced:
    one real ``run_sim fleet --lane 0 --solo`` subprocess measured end
    to end — interpreter + jax import + campaign compile + jit + run —
    and charged K times, exactly what K independent certification runs
    pay without an orchestrator). The headline acceptance figure is the
    K=8 speedup vs serial processes; the in-process ratio sits beside it
    so the number cannot hide the compile amortization.
    """
    import os
    import subprocess
    import tempfile
    import time as _time

    import jax

    from tpu_gossip import fleet
    from tpu_gossip.core.state import clone_state

    tmp = tempfile.mkdtemp(prefix="fleet_bench_")
    scen_path = os.path.join(tmp, "lossy_short.toml")
    with open(scen_path, "w") as f:
        f.write(
            "[scenario]\nname = \"lossy-short\"\n"
            "[[phase]]\nname = \"lossy\"\nstart = 0\n"
            f"end = {max(rounds - 2, 1)}\nloss = 0.2\ndelay = 0.1\n"
        )
    k_max = max(ks)

    def write_campaign(path, seeds):
        with open(path, "w") as f:
            f.write(
                "[campaign]\nname = \"fleet-bench\"\nseed = 0\n"
                f"[base]\npeers = {n}\nrounds = {rounds}\nslots = 16\n"
                "fanout = 2\nmode = \"push_pull\"\ngraph = \"chung-lu\"\n"
                "coverage_target = 0.95\ntarget_ratio = 0.9\n"
                "stream_rate = 1.0\nslot_ttl = 24\n"
                "control = 0.9\ncontrol_hi = 4\nrewire_slots = 4\n"
                f"[[family]]\nname = \"lossy\"\nscenario = \"{scen_path}\"\n"
                f"seeds = {seeds}\n"
                "[[family.sweep]]\naxis = \"phase.loss\"\n"
                "dist = \"uniform\"\nlo = 0.05\nhi = 0.4\n"
            )

    camp_path = os.path.join(tmp, "campaign.toml")
    write_campaign(camp_path, k_max)
    # the serial-process subprocess compiles this MINIMAL twin (2 lanes —
    # the campaign floor) instead of the k_max-lane campaign, so its wall
    # reflects what one independent certification process actually pays
    # (one extra lane of host-side state build rides along — an
    # overcount-free baseline would be a 1-lane campaign, which is by
    # definition a solo run the compiler rejects)
    solo_path = os.path.join(tmp, "campaign_solo.toml")
    write_campaign(solo_path, 2)
    camp = fleet.compile_campaign(fleet.parse_campaign(camp_path))

    def take(pytree, k):
        return (
            None if pytree is None
            else jax.tree.map(lambda x: x[:k], pytree)
        )

    lanes = {}
    for k in ks:
        st_k = take(camp.states, k)
        plans = tuple(
            take(p, k)
            for p in (camp.scenario, camp.growth, camp.stream, camp.control)
        )
        fin, _ = fleet.simulate_fleet(  # warm this K's compile
            clone_state(st_k), camp.cfg, rounds, *plans
        )
        float(fin.round[0])
        del fin
        best = float("inf")
        for _ in range(max(reps, 1)):
            rep_st = clone_state(st_k)  # outside the timer (donation)
            t0 = _time.perf_counter()
            fin, _ = fleet.simulate_fleet(rep_st, camp.cfg, rounds, *plans)
            float(fin.round[0])  # fetch = completion barrier
            best = min(best, _time.perf_counter() - t0)
        del fin, st_k

        # serial in-process floor: K sequential solo runs, compile shared
        solo_fin, _ = fleet.run_lane_solo(camp, 0)  # warm the solo compile
        float(solo_fin.round)
        del solo_fin
        t0 = _time.perf_counter()
        for i in range(k):
            solo_fin, _ = fleet.run_lane_solo(camp, i)
            float(solo_fin.round)
        serial_in = _time.perf_counter() - t0
        del solo_fin
        lanes[str(k)] = {
            "batched_wall_s": round(best, 3),
            "batched_swarms_per_sec": round(k / max(best, 1e-9), 3),
            "batched_ms_per_round_per_lane": round(
                best / (k * rounds) * 1000.0, 4
            ),
            "serial_inprocess_wall_s": round(serial_in, 3),
            "serial_inprocess_ms_per_round_per_lane": round(
                serial_in / (k * rounds) * 1000.0, 4
            ),
            "speedup_vs_serial_inprocess": round(
                serial_in / max(best, 1e-9), 3
            ),
        }

    # one REAL serial process, measured end to end (the pattern the
    # fleet-smoke job replaced pays this K times, uncached)
    # the subprocess inherits the parent's env UNCHANGED — pinning it to
    # cpu would conflate a platform difference with the batching win on
    # an accelerator host (both sides of the A/B must run one backend)
    env = dict(os.environ)
    t0 = _time.perf_counter()
    proc_error = None
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_gossip.cli.run_sim", "fleet",
             solo_path, "--lane", "0", "--solo"],
            capture_output=True, text=True, timeout=1800, env=env,
        )
        if proc.returncode != 0:
            proc_error = (
                f"rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
            )
    except subprocess.TimeoutExpired:
        proc_error = "timeout after 1800s"
    proc_wall = _time.perf_counter() - t0
    # a broken baseline must be distinguishable from a skipped one: the
    # record carries WHY the process figure is absent, never a bare null
    proc_ok = proc_error is None
    for k in ks:
        row = lanes[str(k)]
        if proc_ok:
            row["serial_processes_wall_s_est"] = round(k * proc_wall, 1)
            row["speedup_vs_serial_processes"] = round(
                k * proc_wall / max(row["batched_wall_s"], 1e-9), 1
            )
    return {
        "n_peers_per_swarm": n, "rounds": rounds,
        "aggregate_peers_k8": 8 * n,
        "workload": "composed lossy-sweep x stream x control (the "
        "certification campaign shape)",
        "lanes": lanes,
        "serial_process_wall_s_one": (
            round(proc_wall, 1) if proc_ok else None
        ),
        **({} if proc_ok else {"serial_process_error": proc_error}),
        "serial_process_note": "one real `run_sim fleet --lane 0 --solo` "
        "subprocess over a MINIMAL 2-lane twin campaign, end to end "
        "(import + campaign compile + jit + run) — what each lane of the "
        "replaced one-subprocess-per-config CI pattern pays (one extra "
        "lane of host state build rides along; 1-lane campaigns are by "
        "definition solo runs the compiler rejects); the in-process "
        "floor beside it shares one compile",
        "headline_speedup_k8": (
            lanes.get("8", {}).get("speedup_vs_serial_processes")
            if proc_ok else None
        ),
        "headline_speedup_k8_inprocess": lanes.get("8", {}).get(
            "speedup_vs_serial_inprocess"
        ),
    }


def bench_ckpt(n: int = 1_000_000, shards: int = 8, msg_slots: int = 16,
               warm_rounds: int = 4):
    """Durable checkpoint save/restore at headline scale (tpu_gossip/
    ckpt/, docs/checkpointing.md): one warmed 1M swarm written as a
    ``shards``-file atomic checkpoint (manifest-last, sha256 per file),
    read back, digest-verified bit-exact. Records save/restore wall
    seconds, total bytes, and MB/s both ways — the numbers that price
    --checkpoint-every: a checkpoint cadence costs ``save_seconds``
    per K rounds of horizon, and a crash costs ``restore_seconds``
    instead of the whole replay the reference's config.txt re-bootstrap
    amounts to (PARITY.md)."""
    import shutil as _shutil
    import tempfile
    import time as _time

    import jax

    from tpu_gossip.ckpt import load_checkpoint, save_checkpoint
    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.fleet.engine import state_digest
    from tpu_gossip.sim.engine import simulate

    dg = device_powerlaw_graph(n, gamma=2.5, key=jax.random.key(7))
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=msg_slots, fanout=2, mode="push_pull"
    )
    state = init_swarm(
        dg.as_padded_graph(), cfg, exists=dg.exists, key=jax.random.key(7),
        origins=[0],
    )
    state, _ = simulate(state, cfg, warm_rounds)  # mid-epidemic planes
    tmp = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        t0 = _time.perf_counter()
        ckdir = save_checkpoint(tmp, state, step=warm_rounds, shards=shards)
        save_s = _time.perf_counter() - t0
        total_bytes = sum(
            p.stat().st_size for p in ckdir.iterdir() if p.is_file()
        )
        t0 = _time.perf_counter()
        restored, _stats, _manifest = load_checkpoint(ckdir)
        restore_s = _time.perf_counter() - t0
        bit_exact = state_digest(restored) == state_digest(state)
    finally:
        _shutil.rmtree(tmp, ignore_errors=True)
    return {
        "n_peers": n,
        "msg_slots": msg_slots,
        "shards": shards,
        "checkpoint_bytes": int(total_bytes),
        "save_seconds": round(save_s, 3),
        "restore_seconds": round(restore_s, 3),
        "save_mb_per_s": round(total_bytes / 1e6 / max(save_s, 1e-9), 1),
        "restore_mb_per_s": round(
            total_bytes / 1e6 / max(restore_s, 1e-9), 1
        ),
        "restore_bit_exact": bool(bit_exact),
    }


def bench_build(n: int = 10_000_000, rounds: int = 3):
    """Builder A/B at the 10M scale: local-then-place vs born-distributed
    (dist/builder.py), plus a short run on the born-distributed layout —
    the ≥10M build+run record the 100M item tracks.

    Measures wall seconds and the process ru_maxrss DELTA around each
    build (CPU-container caveat: the 8 "devices" share host RAM, so the
    born-distributed build's per-device memory win reads as roughly
    equal HOST peak here — the per-shard scaling is the ANALYTIC
    ``table_bytes`` split, which a real mesh realizes per HBM). The
    ``capacity_100m`` block prices the 100M layout from the registries
    alone (packed state ledger + declared plan tables, per shard) — no
    arrays built.
    """
    import resource
    import time as _time

    import jax

    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded, plan_table_widths,
    )
    from tpu_gossip.core.state import (
        SwarmConfig, init_swarm, state_bytes_per_peer,
    )
    from tpu_gossip.dist import (
        make_mesh, matching_powerlaw_graph_dist, shard_matching_plan,
        shard_swarm, simulate_dist,
    )

    mesh = make_mesh()

    def maxrss_mb():
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

    def timed_build(fn):
        rss0 = maxrss_mb()
        t0 = _time.perf_counter()
        dg, plan = fn()
        jax.block_until_ready(plan.valid)
        return dg, plan, round(_time.perf_counter() - t0, 2), round(
            maxrss_mb() - rss0, 1
        )

    # CSR export off on both sides: the pure layout-construction A/B
    # (the CSR sorts are a shared additive cost the config may not need)
    dg_l, plan_l, local_s, local_rss = timed_build(
        lambda: matching_powerlaw_graph_sharded(
            n, mesh.size, gamma=2.5, fanout=3, key=jax.random.key(11),
            block_keys=True, export_csr=False,
        )
    )
    del dg_l, plan_l
    dg, plan, dist_s, dist_rss = timed_build(
        lambda: matching_powerlaw_graph_dist(
            n, mesh, gamma=2.5, fanout=3, key=jax.random.key(11),
            export_csr=False,
        )
    )
    widths = plan_table_widths(n, n_shards=mesh.size)
    table_bytes = sum(row["bytes"] for row in widths.values())

    # the run half: a short packed horizon on the born-distributed layout
    from tpu_gossip.core.packed import pack_state

    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=3, mode="push")
    state = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    state = pack_state(shard_swarm(state, mesh))
    splan = shard_matching_plan(plan, mesh)
    t0 = _time.perf_counter()
    fin, _stats = simulate_dist(state, cfg, splan, mesh, rounds)
    cov = float(fin.coverage(0))
    run_s = _time.perf_counter() - t0
    w100 = plan_table_widths(100_000_000, n_shards=mesh.size)
    return {
        "n_peers": n,
        "devices": mesh.size,
        "local_build_seconds": local_s,
        "dist_build_seconds": dist_s,
        "local_build_maxrss_delta_mb": local_rss,
        "dist_build_maxrss_delta_mb": dist_rss,
        "plan_table_bytes": int(table_bytes),
        "plan_table_bytes_per_shard": int(table_bytes // mesh.size),
        "run_rounds": rounds,
        "run_seconds_packed": round(run_s, 2),
        "coverage_after_run": round(cov, 6),
        "container_note": (
            "8 host-CPU devices share one RAM pool, so ru_maxrss cannot "
            "show the per-device split the born-distributed build exists "
            "for; the analytic per-shard table bytes are what a real "
            "mesh holds per HBM (compile-time constants included in the "
            "CPU deltas)"
        ),
        "capacity_100m": {
            "packed_state_gb": round(
                state_bytes_per_peer(100_000_000, 16, packed=True)
                * 100_000_000 / 1e9, 2
            ),
            "unpacked_state_gb": round(
                state_bytes_per_peer(100_000_000, 16) * 100_000_000 / 1e9,
                2,
            ),
            "plan_table_gb": round(
                sum(r["bytes"] for r in w100.values()) / 1e9, 2
            ),
            "plan_table_gb_per_shard": round(
                sum(r["bytes"] for r in w100.values()) / mesh.size / 1e9, 2
            ),
            "note": (
                "registry arithmetic (PLANES packed=True + "
                "plan_table_widths) — the 100M build itself stays a "
                "real-mesh exercise; this container is memory-capable "
                "but a 557M-slot CPU build is hours of sort time"
            ),
        },
    }


def _lint_status(deep: bool = True) -> dict:
    """graftlint verdict for the tree being benchmarked. AST rules run
    in-process (sub-second); the combined run — rules + contract audit +
    jaxpr deep tier + graftmem memory tier — runs in a SUBPROCESS,
    because its entry-point matrix needs an 8-CPU mesh and this process's
    device layout must stay whatever the operator configured for the
    bench. ``lint_deep_s`` is that combined wall time, the same quantity
    the CI lint-deep job budgets (<120 s); ``mem_audit`` is the memory
    tier's record — per-entry bytes/peer over the traced matrix, the
    registry-derived state bytes/peer at 1M (the ROADMAP's tracked
    metric), and the auditor's own wall seconds. ``deep=False`` skips
    the subprocess (fast unit tests). Never raises: a crashed linter is
    itself recorded, not silently dropped."""
    out: dict
    try:
        from tpu_gossip.analysis import run_repo_lint

        res = run_repo_lint()
        out = {
            "lint_clean": bool(res["clean"]),
            "lint": {
                "new_findings": len(res["new"]),
                "baselined": res["baselined"],
                "scope": "ast-rules",
            },
        }
    except Exception as e:  # noqa: BLE001 — record, don't kill the bench
        return {"lint_clean": False, "lint": {"error": repr(e)[:200]}}
    if not deep:
        return out
    try:
        import os
        import subprocess

        env = dict(os.environ)
        env.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_gossip.analysis", "--deep", "--mem",
             "--format=json"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        rep = json.loads(proc.stdout)
        out["lint_deep_s"] = round(time.perf_counter() - t0, 1)
        out["lint"]["deep_clean"] = bool(rep["clean"]) and proc.returncode == 0
        out["lint"]["deep_elapsed_seconds"] = rep.get("elapsed_seconds")
        mem = rep.get("mem_report") or {}
        # the narrowed planes' measured win, from the declared registry:
        # bytes/peer each sub-int32 integer plane saves at the headline
        # shape vs the int32 it narrowed from (join_round/slot_lease led;
        # the table grows as PLANES narrows further)
        import numpy as _np

        from tpu_gossip.core.state import PLANES, state_plane_bytes

        plane_b = state_plane_bytes(1_000_000, 16)
        packed_b = state_plane_bytes(1_000_000, 16, packed=True)
        narrowed = {
            p.name: {
                "dtype": p.dtype,
                "bytes_per_peer": round(plane_b[p.name] / 1e6, 3),
                "saved_vs_int32_bytes_per_peer": round(
                    plane_b[p.name]
                    * (4 / _np.dtype(p.dtype).itemsize - 1) / 1e6, 3
                ),
            }
            for p in PLANES
            if p.dtype not in ("bool", "key")
            and _np.dtype(p.dtype).kind == "i"
            and _np.dtype(p.dtype).itemsize < 4
        }
        # the PACKED planes' measured win (core/packed.py): bytes/peer
        # each registry-declared packing saves at the headline shape vs
        # the unpacked bool materialization
        for p in PLANES:
            if p.packed is None:
                continue
            narrowed[p.name] = {
                "dtype": p.dtype,
                "storage": p.packed,
                "bytes_per_peer": round(packed_b[p.name] / 1e6, 3),
                "saved_vs_unpacked_bytes_per_peer": round(
                    (plane_b[p.name] - packed_b[p.name]) / 1e6, 3
                ),
            }
        out["mem_audit"] = {
            "state_bytes_per_peer_1m": mem.get("state_bytes_per_peer_1m"),
            "state_bytes_per_peer_1m_unpacked": mem.get(
                "state_bytes_per_peer_1m_unpacked"
            ),
            "narrowed_planes": narrowed,
            "entries_bytes_per_peer": {
                name: e["bytes_per_peer"]
                for name, e in (mem.get("entries") or {}).items()
            },
            "audit_seconds": rep.get("mem_seconds"),
            "budget": mem.get("budget_path", "memory_budget.toml"),
        }
    except Exception as e:  # noqa: BLE001 — record, don't kill the bench
        out["lint_deep_s"] = None
        out["lint"]["deep_error"] = repr(e)[:200]
    return out


HARDWARE_AB_NOTE = (
    "this entry rides every bench run so the next REAL-MESH run records "
    "the wall-clock A/B without hand work; on the CPU container the "
    "collectives are memcpy, so the wire-level win cannot show here "
    "(the stale ROADMAP hardware items fold into this entry)"
)


def _sparse_wallclock_ab(dense: dict, sparse: dict) -> dict:
    """The sparse-vs-dense wall-clock A/B as a first-class record entry
    (previously a 'needs a real mesh' ROADMAP note)."""
    return {
        "dense_ms_per_round": dense["ms_per_round"],
        "sparse_ms_per_round": sparse["ms_per_round"],
        "sparse_over_dense": round(
            sparse["ms_per_round"] / max(dense["ms_per_round"], 1e-9), 3
        ),
        "hardware_note": HARDWARE_AB_NOTE,
    }


def _timed_coverage(run, state, n: int, reps: int):
    """Warm + min-wall timing of a one-arg run-to-coverage callable.

    ``run(state) -> final_state``; the engines DONATE their state, so every
    invocation gets a fresh ``clone_state(state)``, cloned outside the
    timed region (the scalar fetch is the completion barrier on the axon
    tunnel)."""
    from tpu_gossip.core.state import clone_state

    fin = run(clone_state(state))  # warm (compile)
    cov, rounds = float(fin.coverage(0)), int(fin.round)
    best = float("inf")
    for _ in range(max(reps, 1)):
        rep_state = clone_state(state)
        t0 = time.perf_counter()
        fin = run(rep_state)
        float(fin.coverage(0))  # completion barrier
        best = min(best, time.perf_counter() - t0)
    return {
        "rounds": rounds, "coverage": round(cov, 4),
        "wall_seconds": round(best, 3),
        "ms_per_round": round(best / max(rounds, 1) * 1000.0, 4),
        "peers_rounds_per_sec": round(n * rounds / max(best, 1e-9), 1),
    }


def _ici_summary(ici) -> dict:
    """Reduce a per-round IciRound trajectory (dist/transport.py) to the
    BENCH_DETAIL entry: analytic bytes/round dense vs shipped vs occupied,
    with the early-phase reduction called out — the ROADMAP's ICI-sparse
    success metric, trackable even on the CPU-only container (the counter
    is analytic: it models the wire, it does not need one)."""
    import numpy as np

    d = np.asarray(ici.dense_words).astype(np.int64)
    s = np.asarray(ici.shipped_words).astype(np.int64)
    o = np.asarray(ici.occupied_words).astype(np.int64)
    lanes = np.asarray(ici.sparse_lanes).astype(np.int64)
    total = np.asarray(ici.total_lanes).astype(np.int64)
    out = {
        "rounds": int(len(d)),
        "dense_bytes_per_round": int(d.mean()) * 4,
        "shipped_bytes_per_round_mean": int(s.mean()) * 4,
        "occupied_bytes_per_round_mean": int(o.mean()) * 4,
        "reduction_vs_dense_mean": round(float(d.sum() / max(s.sum(), 1)), 3),
        # round 1 IS the early epidemic; late-phase rounds (forward_once
        # budgets spent, coverage saturated) read off the same trajectory
        "reduction_vs_dense_round1": round(float(d[0] / max(s[0], 1)), 3),
        "reduction_vs_dense_best": round(
            float((d / np.maximum(s, 1)).max()), 3
        ),
        "sparse_lane_rounds": int(((total > 0) & (lanes == total)).sum()),
        "gated_rounds": int((total > 0).sum()),
    }
    # per-interconnect columns (2-D cluster meshes only): the trajectory's
    # dcn_* fields carry the cross-host share, ici = total - dcn — the
    # same split run_sim's summary and the collectives.lock columns use,
    # so the three artifacts pin each other
    dd = np.asarray(ici.dcn_dense_words).astype(np.int64)
    ds = np.asarray(ici.dcn_shipped_words).astype(np.int64)
    if dd.sum() or ds.sum():
        for key, dn, sh in (("ici_bytes", d - dd, s - ds),
                            ("dcn_bytes", dd, ds)):
            out[key] = {
                "dense_per_round": int(dn.mean()) * 4,
                "shipped_per_round_mean": int(sh.mean()) * 4,
                "reduction_vs_dense_mean": round(
                    float(dn.sum() / max(sh.sum(), 1)), 3
                ),
                "reduction_vs_dense_round1": round(
                    float(dn[0] / max(sh[0], 1)), 3
                ),
            }
    return out


def bench_dist_matching(n: int, reps: int = 3):
    """Sharded MATCHING delivery over the available mesh vs the IDENTICAL
    plan through the local engine — the dist overhead decomposition for
    the gather-free pipeline (the round-6 tentpole).

    ``matching_powerlaw_graph_sharded`` lays the swarm out per shard; the
    dist round runs expand/shuffle/fold shard-locally with each transpose
    pass as one dense ``all_to_all`` (dist/matching_mesh.py), and the SAME
    plan object runs the local engine — same RNG stream, bit-identical
    trajectories (tests/sim/test_dist.py) — so ``overhead`` isolates pure
    collective + shard_map cost with zero statistical noise: identical
    rounds, identical work, the delta IS the transport. At mesh size 1
    that is the all_to_all(1)/reshape plumbing floor.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.dist import (
        make_mesh, run_until_coverage_dist, shard_matching_plan, shard_swarm,
    )
    from tpu_gossip.sim.engine import run_until_coverage

    mesh = make_mesh()
    if 128 % mesh.size:
        # the transpose all_to_all splits the 128-lane axis — a mesh size
        # that does not divide 128 cannot run this layout. Record the
        # incompatibility instead of raising: the benchmark's contract is
        # rc=0 with everything measurable recorded
        return {
            "n_peers": n, "devices": mesh.size,
            "unsupported": f"mesh size {mesh.size} does not divide 128 "
            "(matching_powerlaw_graph_sharded lane-split constraint); "
            "the bucketed-CSR dist entry covers this mesh",
        }
    t0 = time.perf_counter()
    g, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    int(jnp.sum(plan.valid))  # scalar fetch = completion barrier
    build_s = time.perf_counter() - t0
    plan_m = shard_matching_plan(plan, mesh)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=1, mode="push_pull")
    # one rumor per slot at the lowest ids (shard 0's minimum-degree peers
    # — the conservative origin choice, as in the local benchmarks)
    st0 = init_swarm(
        g.as_padded_graph(), cfg, origins=np.arange(cfg.msg_slots),
        origin_slots=np.arange(cfg.msg_slots), exists=g.exists,
        key=jax.random.key(0),
    )
    st = shard_swarm(st0, mesh)
    dist = _timed_coverage(
        lambda s: run_until_coverage_dist(s, cfg, plan_m, mesh, 0.99, 300),
        st, n, reps,
    )
    # sparsity-adaptive transport (dist/transport.py): identical rounds —
    # the compact lanes reorder bytes, never draws — so the timing delta
    # is pure transport, and the analytic ICI trajectory below records the
    # bytes metric the compaction exists for (dense vs realized-compact)
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.dist import build_transport, simulate_dist

    transport = build_transport(plan_m, mode="sparse", mesh=mesh)
    dist_sparse = _timed_coverage(
        lambda s: run_until_coverage_dist(s, cfg, plan_m, mesh, 0.99, 300,
                                          transport=transport),
        st, n, reps,
    )
    _, (_stats, ici) = simulate_dist(
        clone_state(st), cfg, plan_m, mesh, max(dist["rounds"], 1), None,
        None, None, transport, True,
    )
    local = _timed_coverage(
        lambda s: run_until_coverage(s, cfg, 0.99, 300, plan=plan),
        st0, n, reps,
    )
    return {
        "n_peers": n, "devices": mesh.size, "msg_slots": cfg.msg_slots,
        "build_seconds": round(build_s, 2),
        "dist": dist, "dist_sparse": dist_sparse,
        "ici_bytes_per_round": _ici_summary(ici),
        "sparse_wallclock_ab": _sparse_wallclock_ab(dist, dist_sparse),
        "local_same_plan": local,
        "overhead": {
            "dist_ms_per_round": dist["ms_per_round"],
            "local_ms_per_round": local["ms_per_round"],
            "collective_overhead_ms": round(
                dist["ms_per_round"] - local["ms_per_round"], 4
            ),
            "overhead_vs_local": round(
                dist["ms_per_round"] / max(local["ms_per_round"], 1e-9), 3
            ),
        },
        "note": "identical plan + RNG stream on both engines → bit-identical"
        " trajectories; the per-round delta is pure shard_map/collective"
        " transport (transposes as dense all_to_all), not sampling noise",
    }


def bench_hier_1m(n: int, reps: int = 1):
    """1M matching on the (2, D/2) cluster mesh: the flat (dense
    cross-host) exchange vs the two-level ICI/DCN transport
    (cluster/hier.py) — DCN bytes/round and ms/round for both.

    The headline figure is ``dcn_reduction_vs_flat_round1``: dense
    cross-host words / compacted cross-host words in the early phase,
    from the analytic per-axis trajectory (the same counters the traced
    wire audit pins) — the flat transport's tracked
    ``reduction_vs_dense_round1`` standard (docs/sparse_exchange.md),
    one interconnect level up. The horizon mean rides beside it and
    saturates under push_pull (the pull-answer plane is real occupancy,
    not compressible). On this CPU-only container both mesh axes are
    host RAM,
    so the ms/round delta measures collective re-plumbing, NOT a real
    DCN round-trip — the byte columns are the platform-independent
    metric; only a real multi-host run prices the latency win.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_gossip.cluster import make_cluster_mesh
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm
    from tpu_gossip.dist import (
        build_transport, run_until_coverage_dist, shard_matching_plan,
        shard_swarm, simulate_dist,
    )

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2 or 128 % n_dev:
        return {
            "n_peers": n, "devices": n_dev,
            "unsupported": f"{n_dev} device(s) cannot fold to a (2, D/2) "
            "mesh compatible with the matching 128-lane split",
        }
    mesh = make_cluster_mesh(hosts=2)
    t0 = time.perf_counter()
    g, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    int(jnp.sum(plan.valid))
    build_s = time.perf_counter() - t0
    plan_m = shard_matching_plan(plan, mesh)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=1, mode="push_pull")
    st0 = init_swarm(
        g.as_padded_graph(), cfg, origins=np.arange(cfg.msg_slots),
        origin_slots=np.arange(cfg.msg_slots), exists=g.exists,
        key=jax.random.key(0),
    )
    st = shard_swarm(st0, mesh)
    flat = _timed_coverage(
        lambda s: run_until_coverage_dist(s, cfg, plan_m, mesh, 0.99, 300),
        st, n, reps,
    )
    transport = build_transport(plan, mode="hier", hosts=2)
    hier = _timed_coverage(
        lambda s: run_until_coverage_dist(s, cfg, plan_m, mesh, 0.99, 300,
                                          transport=transport),
        st, n, reps,
    )
    # identical trajectory (the transport reorders bytes, never draws):
    # the untimed replay's analytic trajectory prices both stages
    _, (_stats, ici) = simulate_dist(
        clone_state(st), cfg, plan_m, mesh, max(flat["rounds"], 1), None,
        None, None, transport, True,
    )
    dd = np.asarray(ici.dcn_dense_words).astype(np.int64)
    ds = np.asarray(ici.dcn_shipped_words).astype(np.int64)
    return {
        "n_peers": n, "devices": mesh.size, "hosts": 2,
        "msg_slots": cfg.msg_slots,
        "build_seconds": round(build_s, 2),
        "flat": flat, "hier": hier,
        "dcn_bytes_per_round": {
            "flat_dense": int(dd.mean()) * 4,
            "hier_shipped_mean": int(ds.mean()) * 4,
            "hier_shipped_round1": int(ds[0]) * 4,
        },
        # round-1 is the tracked early-phase success metric, same standard
        # as the flat transport's reduction_vs_dense_round1 (>= 3x at 1M,
        # docs/sparse_exchange.md); the horizon mean saturates under
        # push_pull because the pull-answer plane is real occupancy, not
        # compressible — recorded beside it, not hidden
        "dcn_reduction_vs_flat_round1": round(
            float(dd[0] / max(ds[0], 1)), 3
        ),
        "dcn_reduction_vs_flat_mean": round(
            float(dd.sum() / max(ds.sum(), 1)), 3
        ),
        "ici_bytes_per_round": _ici_summary(ici),
        "note": "CPU-only container: both axes are host RAM, so ms/round "
        "deltas price collective plumbing, not DCN latency — the per-axis "
        "byte columns are the platform-independent metric",
    }


def bench_dist(n: int, reps: int = 3):
    """Sharded-engine run over the available device mesh (1 real TPU chip
    here; 8 virtual CPU devices under the test env) — the multi-chip path's
    single-host measurement; cross-chip scaling is validated structurally by
    __graft_entry__.dryrun_multichip.

    The LOCAL engine runs the identical relabeled topology from the same
    initial state, so the ``overhead_vs_local`` ratio isolates what the
    bucketed all_to_all exchange costs over the single-shard delivery path
    on this mesh size (at mesh size 1 that is pure bucketing overhead)."""
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig
    from tpu_gossip.core.topology import build_csr, configuration_model, powerlaw_degree_sequence
    from tpu_gossip.dist import (
        build_shard_plans, init_sharded_swarm, make_mesh, partition_graph,
        run_until_coverage_dist, shard_swarm,
    )
    from tpu_gossip.sim.engine import run_until_coverage

    rng = np.random.default_rng(0)
    graph = build_csr(n, configuration_model(powerlaw_degree_sequence(n, gamma=2.5, rng=rng), rng=rng))
    mesh = make_mesh()
    sg, relabeled, position = partition_graph(graph, mesh.size, seed=0)
    t0 = time.perf_counter()
    plans = build_shard_plans(sg)
    plans_s = time.perf_counter() - t0
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=16, fanout=1, mode="push_pull")
    st0 = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])

    st = shard_swarm(st0, mesh)

    def timed(run, state):
        return _timed_coverage(run, state, n, reps)

    dist = timed(
        lambda s: run_until_coverage_dist(s, cfg, sg, mesh, 0.99, 300), st
    )
    # the fused path: per-shard staircase plans replace the receive-side
    # scatter inside shard_map (bit-identical trajectory, VERDICT r3 item 1)
    dist_pal = timed(
        lambda s: run_until_coverage_dist(s, cfg, sg, mesh, 0.99, 300,
                                          shard_plan=plans), st
    )
    # sparsity-adaptive transport: same trajectory, compacted collectives;
    # the analytic ICI trajectory records dense vs realized-compact bytes
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.dist import build_transport, simulate_dist

    transport = build_transport(sg, mode="sparse")
    dist_sparse = timed(
        lambda s: run_until_coverage_dist(s, cfg, sg, mesh, 0.99, 300,
                                          transport=transport), st
    )
    _, (_stats, ici) = simulate_dist(
        clone_state(st), cfg, sg, mesh, max(dist["rounds"], 1), None, None,
        None, transport, True,
    )
    local = timed(lambda s: run_until_coverage(s, cfg, 0.99, 300), st0)
    return {
        "n_peers": n, "devices": mesh.size, "msg_slots": cfg.msg_slots,
        "dist": dist, "dist_pallas": dist_pal, "dist_sparse": dist_sparse,
        "ici_bytes_per_round": _ici_summary(ici),
        "sparse_wallclock_ab": _sparse_wallclock_ab(dist, dist_sparse),
        "local_same_graph": local,
        "shard_plan_build_seconds": round(plans_s, 2),
        "overhead_vs_local": round(
            dist["ms_per_round"] / max(local["ms_per_round"], 1e-9), 3
        ),
        "overhead_vs_local_pallas": round(
            dist_pal["ms_per_round"] / max(local["ms_per_round"], 1e-9), 3
        ),
    }


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    quick = "--quick" in argv
    with_dist = "--dist" in argv
    profile_dir = None
    if "--profile" in argv:
        i = argv.index("--profile")
        if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
            print("--profile requires a trace directory argument", file=sys.stderr)
            return 2
        profile_dir = argv[i + 1]

    import os

    import jax

    # elapsed-time budget for the post-headline sections (10M north star,
    # sharded-engine entries): the driver kills long runs (r5 died at
    # rc=124 with the headline unrecorded), so once the budget nears, the
    # remaining sections are RECORDED AS SKIPPED and the run exits rc=0
    # with everything measured so far committed
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "2700"))
    t_start = time.perf_counter()

    def elapsed() -> float:
        return time.perf_counter() - t_start

    # persistent on-disk compilation cache: compiles survive process
    # restarts, so 'cold' setup figures reflect a warmed production cache
    # (first-ever run on a machine still pays the compile; the JSON's
    # compilation_cache field says which happened)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    cache_entries = len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import jax.numpy as jnp

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.utils.profiling import trace

    reps = 1 if quick else 3
    lint_status = _lint_status()
    ceilings = _measure_ceilings(jax, jnp)

    # --- 1M graph + staircase plans --------------------------------------
    t0 = time.perf_counter()
    dg1 = device_powerlaw_graph(1_000_000, gamma=2.5, key=jax.random.key(0))
    int(dg1.row_ptr[-1])
    setup_1m = time.perf_counter() - t0
    plan1_k1, plan1_k1_s = _build_plan(dg1, fanout=1, rows=1024)
    plan1_k3, plan1_k3_s = (None, 0.0) if quick else _build_plan(dg1, fanout=3, rows=1024)
    plan1_fl, plan1_fl_s = (None, 0.0) if quick else _build_plan(dg1, fanout=None, rows=1024)

    # structured-matching twin: its own generator (same erased-configuration
    # model family, deterministic quantile degrees), whose pairing IS the
    # delivery plan — the gather-free path (core/matching_topology.py)
    mg1, mplan1, match1_s = _build_matching(1_000_000, fanout=1)

    # --- 1M standard configs, all delivery paths -------------------------
    hl_xla = bench_one(dg1, "push_pull", 1, msg_slots=16, reps=reps)
    hl_pal = bench_one(dg1, "push_pull", 1, msg_slots=16, reps=reps, plan=plan1_k1)
    hl_match = bench_one(mg1, "push_pull", 1, msg_slots=16, reps=reps, plan=mplan1)
    headline = min(hl_xla, hl_pal, hl_match, key=lambda r: r["wall_seconds"])

    configs = {
        "push_pull_k1_m16_xla": hl_xla,
        "push_pull_k1_m16_pallas": hl_pal,
        "push_pull_k1_m16_matching": hl_match,
    }
    out = {
        "metric": "1M-node power-law (gamma=2.5) push-pull gossip to 99% coverage",
        "value": headline["peers_rounds_per_sec"],
        "unit": "peers_rounds_per_sec",
        "vs_baseline": round(headline["peers_rounds_per_sec"] / REFERENCE_PEERS_ROUNDS_PER_SEC, 1),
        "rounds_to_99pct": headline["rounds"],
        "wall_seconds": headline["wall_seconds"],
        "headline_delivery": headline["delivery"],
        "setup_seconds_1m": round(setup_1m, 2),
        "plan_build_seconds_1m": round(plan1_k1_s + plan1_k3_s + plan1_fl_s, 2),
        "matching_build_seconds_1m": round(match1_s, 2),
        "configs": configs,
        "hardware_ceilings": ceilings,
        "graph": "on-device erased configuration model (core/device_topology.py"
        " for xla/pallas; structured-matching twin core/matching_topology.py"
        " for matching configs)",
        # entry count + jax version, not a bald warm/cold claim: cache keys
        # include the jaxlib version, so entries can be present yet stale
        "compilation_cache": {
            "entries_at_start": cache_entries,
            "jax": jax.__version__,
        },
        "budget_seconds": budget_s,
        "sections_skipped": [],
        **lint_status,
    }
    detail_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
    )

    def flush_detail():
        """Write the record INCREMENTALLY — each completed section lands
        before the next begins, so a killed run still leaves a truthful
        committed artifact. --quick smoke runs never clobber a full run's
        MEASUREMENTS — they refresh ONLY the analyzer verdict fields. Any
        ``provenance_note`` disclosing hand-patched entries stays with the
        numbers it describes; a FULL run rewrites the record wholesale
        from its own measurements, which is when such notes clear
        (VERDICT r5 item 2: the committed record must be what a re-run of
        this script produces — full runs emit no patch/provenance notes)."""
        if quick:
            rec = {}
            if os.path.exists(detail_path):
                try:
                    with open(detail_path) as f:
                        rec = json.load(f)
                except ValueError:
                    rec = {}  # corrupt record: rebuild the lint stub
            rec["lint_clean"] = lint_status["lint_clean"]
            rec["lint"] = lint_status["lint"]
            if "lint_deep_s" in lint_status:
                rec["lint_deep_s"] = lint_status["lint_deep_s"]
            if "mem_audit" in lint_status:
                rec["mem_audit"] = lint_status["mem_audit"]
            with open(detail_path, "w") as f:
                json.dump(rec, f, indent=1, sort_keys=True)
                f.write("\n")
            return
        out["elapsed_seconds"] = round(elapsed(), 1)
        with open(detail_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")

    def skip(section: str) -> bool:
        """True (and records the skip) when the budget is too spent for
        ``section`` — the guard that keeps rc=0 with the headline printed."""
        frac = {"tail_ab": 0.35, "north_star_10m": 0.40, "dist_200k": 0.70,
                "dist_1m": 0.78, "hier_1m": 0.79,
                "packed_ab_1m": 0.80, "grow_1m": 0.82,
                "stream_1m": 0.86, "serve_1m": 0.87,
                "control_1m": 0.88, "adv_1m": 0.885, "pipeline_1m": 0.89,
                "ckpt_1m": 0.893, "fleet_1m": 0.895, "build_10m": 0.897,
                "dist_10m": 0.90}[section]
        if elapsed() <= budget_s * frac:
            return False
        out["sections_skipped"].append(
            {"section": section, "elapsed_seconds": round(elapsed(), 1)}
        )
        return True

    # the headline is on stdout from HERE — a driver timeout in any later
    # section can no longer lose it (the final, enriched compact line is
    # printed again at exit; tail-parsing reads the most complete one)
    early = {**_compact(out), "partial": True}
    print(json.dumps(early), flush=True)
    flush_detail()

    # historical msg_slots=1 shape (cross-round comparability with r01/r02)
    configs["push_pull_k1_m1_xla"] = bench_one(
        dg1, "push_pull", 1, msg_slots=1, reps=reps
    )
    if not quick:
        # 64-slot headline shape (VERDICT r4 item 8): two word groups, the
        # multi-word path unit tests exercise, now measured at scale
        configs["push_pull_k1_m64_xla"] = bench_one(
            dg1, "push_pull", 1, msg_slots=64, reps=reps
        )
        configs["push_pull_k1_m64_pallas"] = bench_one(
            dg1, "push_pull", 1, msg_slots=64, reps=reps, plan=plan1_k1
        )
        configs["push_pull_k1_m64_matching"] = bench_one(
            mg1, "push_pull", 1, msg_slots=64, reps=reps, plan=mplan1
        )
        configs["push_k3_m16_xla"] = bench_one(dg1, "push", 3, msg_slots=16, reps=reps)
        configs["push_k3_m16_pallas"] = bench_one(
            dg1, "push", 3, msg_slots=16, reps=reps, plan=plan1_k3
        )
        configs["push_k3_m16_matching"] = bench_one(
            mg1, "push", 3, msg_slots=16, reps=reps, plan=mplan1.with_fanout(3)
        )
        # flood: the staircase kernel's original formulation, both paths
        # (VERDICT r2 item 3: the kernel's win must live in this artifact)
        configs["flood_m16_xla"] = bench_one(dg1, "flood", 1, msg_slots=16, reps=reps)
        configs["flood_m16_pallas"] = bench_one(
            dg1, "flood", 1, msg_slots=16, reps=reps, plan=plan1_fl
        )
        configs["flood_m16_matching"] = bench_one(
            mg1, "flood", 1, msg_slots=16, reps=reps, plan=mplan1
        )
        # BASELINE config 4: 1M SIR epidemic (per-slot recovery 8 rounds
        # after infection; coverage counts seen-ever, so the target stays
        # reachable while recovered slots stop relaying — push_pull k1, whose
        # anti-entropy wave outruns recovery; push k3 stalls ~98%)
        configs["sir_1m_push_pull_m16"] = bench_one(
            dg1, "push_pull", 1, msg_slots=16, reps=reps, sir_recover_rounds=8
        )
        # same SIR config through the staircase kernel (per-slot recovered
        # folds into transmit/receptive, so the sampled kernel covers
        # BASELINE config 4 — measured, not just claimed)
        configs["sir_1m_push_pull_m16_pallas"] = bench_one(
            dg1, "push_pull", 1, msg_slots=16, reps=reps, sir_recover_rounds=8,
            plan=plan1_k1,
        )
        configs["sir_1m_push_pull_m16_matching"] = bench_one(
            mg1, "push_pull", 1, msg_slots=16, reps=reps, sir_recover_rounds=8,
            plan=mplan1,
        )
        # BASELINE config 5: 1M dynamic Poisson churn with power-law
        # re-wiring (rejoiners attach 2 fresh degree-preferential edges),
        # on both delivery paths: the kernel carries the static-CSR bulk
        # (rewired senders zeroed pre-pack, rewired receivers row-masked)
        # while the sparse fresh-edge traffic rides the XLA side path
        churn_kw = dict(
            churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
        )
        configs["churn_rewire_1m_push_pull_m16"] = bench_one(
            dg1, "push_pull", 1, msg_slots=16, reps=reps, **churn_kw
        )
        configs["churn_rewire_1m_push_pull_m16_pallas"] = bench_one(
            dg1, "push_pull", 1, msg_slots=16, reps=reps, plan=plan1_k1,
            **churn_kw,
        )
        # config 5 with the bounded-table side paths (rewire_compact_cap):
        # fresh-edge traffic and join draws run at O(cap) instead of O(N) —
        # the access-count fix the dense-path decomposition called for
        # (docs/kernel_profile_1m.md); 65536 = ~16x the rewired population
        # this config accumulates before 99% coverage
        configs["churn_rewire_1m_compact_pallas"] = bench_one(
            dg1, "push_pull", 1, msg_slots=16, reps=reps, plan=plan1_k1,
            rewire_compact_cap=65536, **churn_kw,
        )
        # config 5 over the matching path: the gather-free bulk plus the
        # same compact fresh-edge side paths (which draw on the exported CSR)
        configs["churn_rewire_1m_compact_matching"] = bench_one(
            mg1, "push_pull", 1, msg_slots=16, reps=reps, plan=mplan1,
            rewire_compact_cap=65536, **churn_kw,
        )
        # config 5 + periodic re-materialization at its optimal operating
        # point (VERDICT r4 item 4): kernel delivery + a compact cap sized
        # for remat_every rounds of joiners (~1.8k/round at this churn), so
        # the amortized figure prices the real long-horizon trade:
        # base + O(cap) side paths + remat/remat_every
        configs["churn_rewire_1m_remat_compact"] = bench_churn_remat(
            dg1, reps=reps, remat_every=24, plan=plan1_k1,
            rewire_compact_cap=49152,
        )
        # BASELINE config 2: 1k peers + 3-miss liveness (detection latency
        # vs the reference's 30-42 s worst-case band, SURVEY.md §6)
        configs["liveness_1k"] = bench_liveness(reps=reps)
    flush_detail()

    if not quick and not skip("tail_ab"):
        # the --tail default decision A/B (pallas rows appear on TPU)
        out["tail_ab"] = bench_tail_ab(dg1, plan1_k1, reps=reps)
        flush_detail()

    if profile_dir:
        # one warmed headline rep under the device tracer (SURVEY.md §5.1)
        with trace(profile_dir):
            if headline is hl_match:
                bench_one(mg1, "push_pull", 1, msg_slots=16, reps=1, plan=mplan1)
            else:
                bench_one(dg1, "push_pull", 1, msg_slots=16, reps=1,
                          plan=plan1_k1 if headline is hl_pal else None)

    # --- 10M north star ---------------------------------------------------
    if not quick and not skip("north_star_10m"):
        t0 = time.perf_counter()
        dg10 = device_powerlaw_graph(10_000_000, gamma=2.5, key=jax.random.key(0))
        int(dg10.row_ptr[-1])
        setup_cold = time.perf_counter() - t0
        # second build, fresh key: compile is cached — the steady-state cost
        t0 = time.perf_counter()
        dg10 = device_powerlaw_graph(10_000_000, gamma=2.5, key=jax.random.key(1))
        int(dg10.row_ptr[-1])
        setup_warm = time.perf_counter() - t0
        # ns_xla runs BEFORE the ~700 MB staircase plan exists so the XLA
        # baseline is measured with the HBM it would have in isolation (the
        # same fairness the flood pair below gets by freeing the plan first;
        # a resident plan inflates XLA round times via spill)
        ns_xla = bench_one(dg10, "push_pull", 1, msg_slots=16, reps=reps)
        # BASELINE configs 4-5 at north-star scale (VERDICT r4 item 6):
        # SIR and churn were previously benched at 1M only. One rep each
        # (10M rounds are seconds); xla entries run plan-free like ns_xla
        sir10 = {
            "xla": bench_one(
                dg10, "push_pull", 1, msg_slots=16, reps=1,
                sir_recover_rounds=8,
            )
        }
        churn_kw10 = dict(
            churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
            rewire_compact_cap=131072,
        )
        churn10 = {
            "xla": bench_one(
                dg10, "push_pull", 1, msg_slots=16, reps=1, **churn_kw10
            )
        }
        # plan build cold vs warm, mirroring setup_seconds_cold/warm: the
        # first build pays ~17 s of trace+compile, a rebuild is ~5 s of
        # device compute — e2e accounting uses the steady-state (warm)
        # figure, same as it does for the graph build; both are reported
        plan10, plan10_cold_s = _build_plan(dg10, fanout=1, rows=1024, device=True)
        del plan10
        plan10, plan10_s = _build_plan(dg10, fanout=1, rows=1024, device=True)
        ns_pal = bench_one(dg10, "push_pull", 1, msg_slots=16, reps=reps, plan=plan10)
        sir10["pallas"] = bench_one(
            dg10, "push_pull", 1, msg_slots=16, reps=1, sir_recover_rounds=8,
            plan=plan10,
        )
        churn10["pallas"] = bench_one(
            dg10, "push_pull", 1, msg_slots=16, reps=1, plan=plan10,
            **churn_kw10,
        )
        # flood at north-star scale: the staircase kernel's strongest mode
        # (its all-edges streaming formulation), one rep each path. The
        # push_pull plan (~700 MB) is freed first: with it resident, XLA's
        # ~1 GB flood intermediates spill and its round time inflates ~12x
        # (observed 84 s/round vs 7 s isolated) — each path gets fair HBM.
        del plan10
        flood10_xla = bench_one(dg10, "flood", 1, msg_slots=16, reps=1, max_rounds=50)
        plan10_fl, plan10_fl_s = _build_plan(dg10, fanout=None, rows=1024, device=True)
        flood10 = {
            "xla": flood10_xla,
            "pallas": bench_one(
                dg10, "flood", 1, msg_slots=16, reps=1, max_rounds=50, plan=plan10_fl
            ),
            "plan_build_seconds": round(plan10_fl_s, 2),
        }
        del plan10_fl
        # structured-matching at north-star scale: its build replaces BOTH
        # the CSR graph build and the plan build (the pairing is the plan),
        # so its end-to-end charge is just build_warm + sim wall. Cold vs
        # warm mirrors the setup accounting above. The north-star config
        # (pure dissemination) never reads a CSR, so its build skips the
        # export (the dominant sorts); the churn entry below pays the full
        # CSR build, recorded in its own row.
        mg10, mplan10, match10_cold_s = _build_matching(
            10_000_000, 1, key_i=0, export_csr=False
        )
        del mg10, mplan10
        mg10, mplan10, match10_s = _build_matching(
            10_000_000, 1, key_i=1, export_csr=False
        )
        ns_match = bench_one(
            mg10, "push_pull", 1, msg_slots=16, reps=reps, plan=mplan10
        )
        flood10["matching"] = bench_one(
            mg10, "flood", 1, msg_slots=16, reps=1, max_rounds=50, plan=mplan10
        )
        sir10["matching"] = bench_one(
            mg10, "push_pull", 1, msg_slots=16, reps=1, sir_recover_rounds=8,
            plan=mplan10,
        )
        del mg10, mplan10
        mg10, mplan10, match10_full_s = _build_matching(
            10_000_000, 1, key_i=1, export_csr=True
        )
        churn10["matching"] = {
            **bench_one(
                mg10, "push_pull", 1, msg_slots=16, reps=1, plan=mplan10,
                **churn_kw10,
            ),
            "full_build_seconds": round(match10_full_s, 2),
        }
        del mg10, mplan10
        # end-to-end cost per path: each path is charged EVERYTHING it needs
        # beyond the warm graph build — the pallas path needs its staircase
        # plan, the xla path needs nothing extra, the matching path charges
        # its whole build (graph included) — so 'met' can't hide a
        # 90 s plan build behind a marginally faster sim wall
        e2e_xla = setup_warm + ns_xla["wall_seconds"]
        e2e_pal = setup_warm + plan10_s + ns_pal["wall_seconds"]
        e2e_match = match10_s + ns_match["wall_seconds"]
        ns = min(
            (e2e_xla, ns_xla), (e2e_pal, ns_pal), (e2e_match, ns_match),
            key=lambda t: t[0],
        )[1]
        out["north_star"] = {
            **ns,
            "xla": {**ns_xla, "end_to_end_seconds": round(e2e_xla, 2)},
            "pallas": {**ns_pal, "end_to_end_seconds": round(e2e_pal, 2)},
            "matching": {**ns_match, "end_to_end_seconds": round(e2e_match, 2)},
            "setup_seconds_cold": round(setup_cold, 2),
            "setup_seconds_warm": round(setup_warm, 2),
            "plan_build_seconds": round(plan10_s, 2),
            "plan_build_seconds_cold": round(plan10_cold_s, 2),
            "matching_build_seconds": round(match10_s, 2),
            "matching_build_seconds_cold": round(match10_cold_s, 2),
            "matching_build_csr_free": True,
            "target": "10M peers to 99% < 60 s (BASELINE.json north_star)",
            "met_definition": "min over delivery paths of (path-specific "
            "warm setup + prep + sim wall_seconds) < 60",
            "met_sim_only": bool(
                min(
                    ns_xla["wall_seconds"], ns_pal["wall_seconds"],
                    ns_match["wall_seconds"],
                ) < 60.0
            ),
            "met": bool(min(e2e_xla, e2e_pal, e2e_match) < 60.0),
            "flood_10m": flood10,
            "sir_10m": sir10,
            "churn_10m": churn10,
        }
        flush_detail()

    if with_dist or not quick:
        # sharded-engine overhead is part of the default artifact (VERDICT
        # r3 item 5): mesh size 1 on the TPU chip = pure bucketing overhead
        if not skip("dist_200k"):
            out["dist"] = bench_dist(200_000, reps=reps)
            flush_detail()
        if not quick and not skip("dist_1m"):
            # the 1M dist entries (VERDICT r4 item 2 + the round-6
            # tentpole): bucketed-CSR overhead on the zero-gather
            # streaming receive, AND the sharded matching pipeline quoted
            # against the identical plan's local round
            out["dist_1m"] = {
                **bench_dist(1_000_000, reps=reps),
                "matching": bench_dist_matching(1_000_000, reps=reps),
            }
            flush_detail()
        if not quick and not skip("hier_1m"):
            # the multi-host fold (ISSUE 20): 1M matching on the (2,4)
            # cluster mesh, dense cross-host exchange vs the two-level
            # ICI/DCN transport — the early-phase dcn-byte reduction is
            # the acceptance metric (round-1 ≥3x vs flat, the
            # sparse-transport standard)
            out["hier_1m"] = bench_hier_1m(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("packed_ab_1m"):
            # packed-native vs unpack/repack at 1M on both engines — the
            # compute-on-words tentpole's wall-clock + graftmem figures
            out["packed_ab_1m"] = bench_packed_ab(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("grow_1m"):
            # the growth engine at 1M capacity: admission-stage overhead
            # (growing vs fixed-n round on the same state) + the grown
            # tail's γ — the membership plane's headline numbers
            out["grow_1m"] = bench_grow(1_000_000, 950_000, reps=reps)
            flush_detail()
        if not quick and not skip("stream_1m"):
            # the streaming serving plane at 1M: sustained injection over
            # a >=3-rate saturation curve — delivered msgs/sec, p50/p99
            # rounds-to-coverage per message, conflation under load, and
            # the loaded round's marginal cost (docs/streaming_plane.md)
            out["stream_1m"] = bench_stream(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("serve_1m"):
            # the live-ingestion frontend at 1M: real loopback clients
            # speaking the reference wire protocol while the driver
            # double-buffers window injection against the device round —
            # sustained accepted msgs/sec + loaded ms/round vs the
            # reference peer loop's single-socket throughput
            # (docs/serving_frontend.md; CPU-container caveat recorded)
            out["serve_1m"] = bench_serve(1_000_000)
            flush_detail()
        if not quick and not skip("control_1m"):
            # the adaptive controller at 1M on the matching mesh:
            # controlled vs static messages-per-delivered-infection at
            # equal-or-better rounds-to-99% (docs/adaptive_control.md) —
            # the coverage-feedback fanout's acceptance metric
            out["control_1m"] = bench_control(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("adv_1m"):
            # the quorum failure detector's overhead at 1M on the
            # matching mesh: hardened vs direct ms/round on the same
            # swarm + the suspicion planes' bytes/peer (ISSUE 14 — the
            # price of Byzantine defense when nothing is attacking)
            out["adv_1m"] = bench_adv(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("pipeline_1m"):
            # pipelined vs serial sharded matching rounds at 1M — the
            # stage-DAG/double-buffer acceptance entry (ISSUE 10), with
            # the extended profiler's per-stage overlap attribution
            out["pipeline_1m"] = bench_pipeline(1_000_000, reps=reps)
            flush_detail()
        if not quick and not skip("ckpt_1m"):
            # durable-checkpoint save/restore wall + bytes at 1M — the
            # price of --checkpoint-every and of a crash (ckpt/,
            # docs/checkpointing.md); restore is digest-verified
            out["ckpt_1m"] = bench_ckpt(1_000_000)
            flush_detail()
        if not quick and not skip("fleet_1m"):
            # the fleet engine at aggregate-1M scale: ONE vmapped
            # campaign program vs K serial runs (in-process floor AND
            # the real serial-process cost) — the Monte Carlo
            # certification batching win (docs/fleet_campaigns.md)
            out["fleet_1m"] = bench_fleet(reps=reps)
            flush_detail()
        if not quick and not skip("build_10m"):
            # builder A/B at 10M: local-then-place vs born-distributed
            # (dist/builder.py) wall + maxrss delta + the analytic
            # per-shard table split, plus a short packed run on the
            # born-distributed layout and the 100M capacity arithmetic
            out["build_10m"] = bench_build(10_000_000)
            flush_detail()
        if not quick and not skip("dist_10m"):
            # north-star scale on the mesh: matching only (partition_graph
            # buckets a 10M CSR host-side — minutes of numpy — while the
            # matching layout is mesh-native from build)
            out["dist_10m"] = {
                "matching": bench_dist_matching(10_000_000, reps=1),
            }
            flush_detail()

    # stdout's LAST line is the enriched compact headline (the early print
    # after the 1M trio covers driver-timeout deaths; this one supersedes
    # it when the run completes). --quick touches only the record's
    # lint_clean/lint fields (flush_detail).
    flush_detail()
    compact = _compact(out)
    print(json.dumps(compact), flush=True)
    return 0


def _compact(out: dict) -> dict:
    """The driver-facing headline: metric/value/vs_baseline plus one
    ms_per_round figure per config — everything else lives in
    BENCH_DETAIL.json. Kept well under ~1.5 KB so the driver's stdout tail
    capture can never truncate it again."""
    compact = {
        k: out[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "rounds_to_99pct",
            "wall_seconds", "headline_delivery", "lint_clean",
        )
        if k in out
    }
    compact["configs_ms_per_round"] = {
        k: v.get("ms_per_round") for k, v in out.get("configs", {}).items()
    }
    mem = out.get("mem_audit")
    if mem and mem.get("state_bytes_per_peer_1m") is not None:
        # the ROADMAP's 100M-item metric starts here: declared state
        # bytes per peer slot at the 1M headline shape (m=16)
        compact["bytes_per_peer_1m"] = mem["state_bytes_per_peer_1m"]
    ns = out.get("north_star")
    if ns:
        paths = tuple(p for p in ("xla", "pallas", "matching") if p in ns)
        compact["north_star"] = {
            "met": ns["met"],
            "met_sim_only": ns["met_sim_only"],
            "best_delivery": ns["delivery"],
            "end_to_end_seconds": {
                p: ns[p]["end_to_end_seconds"] for p in paths
            },
            "ms_per_round": {p: ns[p]["ms_per_round"] for p in paths},
            "flood_ms_per_round": {
                p: ns["flood_10m"][p]["ms_per_round"]
                for p in paths if p in ns["flood_10m"]
            },
        }
    for key in ("dist", "dist_1m", "dist_10m"):
        dist = out.get(key)
        if not dist:
            continue
        row = {}
        if "dist" in dist:  # bucketed-CSR engine entry
            row.update({
                "devices": dist["devices"],
                "ms_per_round": dist["dist"]["ms_per_round"],
                "pallas_ms_per_round": dist["dist_pallas"]["ms_per_round"],
                "local_ms_per_round": dist["local_same_graph"]["ms_per_round"],
                "overhead_vs_local": dist["overhead_vs_local"],
                "overhead_vs_local_pallas": dist["overhead_vs_local_pallas"],
            })
            if "dist_sparse" in dist:
                row["sparse_ms_per_round"] = dist["dist_sparse"]["ms_per_round"]
            if "ici_bytes_per_round" in dist:
                row["ici_reduction_round1"] = (
                    dist["ici_bytes_per_round"]["reduction_vs_dense_round1"]
                )
        m = dist.get("matching")
        if m:  # sharded matching pipeline entry (bench_dist_matching)
            row.setdefault("devices", m["devices"])
            if "overhead" in m:
                row["matching_ms_per_round"] = m["overhead"]["dist_ms_per_round"]
                row["matching_local_ms_per_round"] = m["overhead"]["local_ms_per_round"]
                row["matching_overhead_vs_local"] = m["overhead"]["overhead_vs_local"]
            else:  # recorded as unsupported on this mesh size
                row["matching_unsupported"] = True
            if "ici_bytes_per_round" in m:
                row["matching_ici_reduction_round1"] = (
                    m["ici_bytes_per_round"]["reduction_vs_dense_round1"]
                )
        compact[key] = row
    h = out.get("hier_1m")
    if h and "unsupported" not in h:
        compact["hier_1m"] = {
            "dcn_reduction_vs_flat_round1": h["dcn_reduction_vs_flat_round1"],
            "dcn_reduction_vs_flat_mean": h["dcn_reduction_vs_flat_mean"],
            "flat_ms_per_round": h["flat"]["ms_per_round"],
            "hier_ms_per_round": h["hier"]["ms_per_round"],
        }
    b = out.get("build_10m")
    if b:
        compact["build_10m"] = {
            "local_vs_dist_build_seconds": [
                b["local_build_seconds"], b["dist_build_seconds"],
            ],
            "plan_table_mb_per_shard": round(
                b["plan_table_bytes_per_shard"] / 1e6, 1
            ),
            "run_seconds_packed": b["run_seconds_packed"],
        }
    g = out.get("grow_1m")
    if g:
        compact["grow_1m"] = {
            "ms_per_round_growing": g["growing"]["ms_per_round"],
            "ms_per_round_fixed": g["fixed_n"]["ms_per_round"],
            "admission_overhead": g["admission_overhead_vs_fixed"],
            "grown_degree_gamma": g["grown_degree_gamma"],
        }
    s = out.get("stream_1m")
    if s:
        compact["stream_1m"] = {
            "peak_delivered_msgs_per_sec": s["peak_delivered_msgs_per_sec"],
            "saturation_rate": s["saturation_rate_msgs_per_round"],
            "p99_rounds_to_coverage": [
                c["p99_rounds_to_coverage"] for c in s["curve"]
            ],
            "delivery_ratio": [c["delivery_ratio"] for c in s["curve"]],
        }
    sv = out.get("serve_1m")
    if sv:
        compact["serve_1m"] = {
            "accepted_msgs_per_sec": sv["accepted_msgs_per_sec"],
            "loaded_ms_per_round": sv["loaded_ms_per_round"],
            "reference_single_socket_msgs_per_sec":
                sv["reference_single_socket_msgs_per_sec"],
        }
    c = out.get("control_1m")
    if c:
        compact["control_1m"] = {
            "msgs_per_infection": [
                c["static"]["msgs_per_delivered_infection"],
                c["controlled"]["msgs_per_delivered_infection"],
            ],
            "reduction": c["msgs_per_infection_reduction"],
            "rounds": [
                c["static"]["rounds_to_target"],
                c["controlled"]["rounds_to_target"],
            ],
            "rounds_equal_or_better": c["rounds_equal_or_better"],
        }
    av = out.get("adv_1m")
    if av and "direct_ms_per_round" in av:
        compact["adv_1m"] = {
            "direct_ms_per_round": av["direct_ms_per_round"],
            "quorum_ms_per_round": av["quorum_ms_per_round"],
            "quorum_over_direct_ms": av["quorum_over_direct_ms"],
            "suspicion_planes_bytes_per_peer":
                av["suspicion_planes_bytes_per_peer"],
        }
    t = out.get("tail_ab")
    if t and "composed_ms_per_round" in t:
        compact["tail_ab"] = {
            "decision": t["decision"],
            "composed_ms_per_round": t["composed_ms_per_round"],
        }
    pk = out.get("packed_ab_1m")
    if pk and "local" in pk:
        compact["packed_ab_1m"] = {
            "local_ms": [
                pk["local"]["native_ms_per_round"],
                pk["local"]["roundtrip_ms_per_round"],
            ],
            "dist_ms": [
                pk["dist_matching"].get("native_ms_per_round"),
                pk["dist_matching"].get("roundtrip_ms_per_round"),
            ],
            "peak_over_resident": pk["local"]["graftmem_native"][
                "peak_over_resident"
            ],
        }
    fl = out.get("fleet_1m")
    if fl and "lanes" in fl:
        k8 = fl["lanes"].get("8", {})
        compact["fleet_1m"] = {
            "swarms_per_sec_k8": k8.get("batched_swarms_per_sec"),
            "speedup_k8_vs_processes": fl.get("headline_speedup_k8"),
            "speedup_k8_inprocess": fl.get("headline_speedup_k8_inprocess"),
        }
    ck = out.get("ckpt_1m")
    if ck and "save_seconds" in ck:
        compact["ckpt_1m"] = {
            "save_s": ck["save_seconds"],
            "restore_s": ck["restore_seconds"],
            "mb": round(ck["checkpoint_bytes"] / 1e6, 1),
            "bit_exact": ck["restore_bit_exact"],
        }
    pl = out.get("pipeline_1m")
    if pl and "serial" in pl:
        compact["pipeline_1m"] = {
            "serial_ms_per_round": pl["serial"]["ms_per_round"],
            "pipelined_ms_per_round": pl["pipelined"]["ms_per_round"],
            "pipelined_over_serial_ms": pl["pipelined_over_serial_ms"],
            "rounds_to_99pct": [
                pl["serial"]["rounds_to_99pct"],
                pl["pipelined"]["rounds_to_99pct"],
            ],
        }
    if out.get("sections_skipped"):
        compact["sections_skipped"] = [
            s["section"] for s in out["sections_skipped"]
        ]
    compact["detail_file"] = "BENCH_DETAIL.json"
    return compact


if __name__ == "__main__":
    sys.exit(main())
