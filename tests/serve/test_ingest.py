"""The ingest stage's engine contracts (traffic/ingest.py): zero-batch
identity, overflow billing, liveness gating, conflation/Bloom semantics,
packed parity — the deterministic twin of the streaming plane's landing
rules, unit-pinned so serve/trace.py's replay contract rests on tested
ground."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.device_topology import device_powerlaw_graph
from tpu_gossip.core.state import SwarmConfig, init_swarm, message_slots
from tpu_gossip.fleet.engine import state_digest
from tpu_gossip.sim.engine import gossip_round
from tpu_gossip.traffic.ingest import (
    IngestError,
    IngestPlan,
    empty_batch,
    make_batch,
)

N, M = 48, 8
PLAN = IngestPlan(msg_slots=M, max_inject=4, k_hashes=1)


@pytest.fixture(scope="module")
def ctx():
    dg = device_powerlaw_graph(N, gamma=2.5, key=jax.random.key(0))
    graph = dg.as_padded_graph()
    cfg = SwarmConfig(n_peers=graph.n, msg_slots=M, fanout=3, mode="push")
    state = init_swarm(graph, cfg, key=jax.random.key(0),
                       origins=np.array([0]), exists=dg.exists)
    return cfg, state, dg


def _hashes_for_distinct_slots(m, count):
    """Integer hashes whose k=1 slots are pairwise distinct."""
    out, seen = [], set()
    h = 1
    while len(out) < count:
        s = message_slots(h, m, 1)[0]
        if s not in seen:
            seen.add(s)
            out.append(h)
        h += 1
    return out


def _two_hashes_same_slot(m):
    by_slot = {}
    h = 1
    while True:
        s = message_slots(h, m, 1)[0]
        if s in by_slot:
            return by_slot[s], h
        by_slot[s] = h
        h += 1


def test_plan_rejects_impossible_shapes():
    with pytest.raises(IngestError):
        IngestPlan(msg_slots=8, max_inject=0)
    with pytest.raises(IngestError):
        IngestPlan(msg_slots=8, max_inject=4, k_hashes=9)


def test_make_batch_rejects_window_overrun():
    with pytest.raises(IngestError):
        make_batch(PLAN, list(range(5)), list(range(5)))


def test_zero_batch_is_bit_identical_to_none(ctx):
    cfg, state, _ = ctx
    s0, st0 = gossip_round(state, cfg, inject=None)
    s1, st1 = gossip_round(state, cfg, inject=empty_batch(PLAN))
    assert state_digest(s0) == state_digest(s1)
    for f in type(st0)._fields:
        a, b = np.asarray(getattr(st0, f)), np.asarray(getattr(st1, f))
        if np.issubdtype(a.dtype, np.integer):
            assert np.array_equal(a, b), f


def test_overflow_is_billed_not_dropped(ctx):
    cfg, state, _ = ctx
    h = _hashes_for_distinct_slots(M, 1)
    batch = make_batch(PLAN, [2], h, overflow=5)
    _, stats = gossip_round(state, cfg, inject=batch)
    assert int(stats.ingest_overflow) == 5
    assert int(stats.ingest_offered) == 1


def test_arrivals_land_and_latch_infection(ctx):
    cfg, state, _ = ctx
    hs = _hashes_for_distinct_slots(M, 3)
    batch = make_batch(PLAN, [2, 3, 4], hs)
    fin, stats = gossip_round(state, cfg, inject=batch)
    assert int(stats.ingest_offered) == 3
    assert int(stats.ingest_injected) == 3
    assert int(stats.ingest_conflated) == 0
    for row, h in zip([2, 3, 4], hs):
        s = message_slots(h, M, 1)[0]
        assert bool(fin.seen[row, s])
        assert int(fin.infected_round[row, s]) >= 0
        assert int(fin.slot_lease[s]) >= 0


def test_dead_origin_is_offered_but_not_injected(ctx):
    cfg, state, dg = ctx
    pad_row = int(dg.n_pad) - 1  # born-dead pad row: exists == False
    assert not bool(dg.exists[pad_row])
    h = _hashes_for_distinct_slots(M, 1)
    batch = make_batch(PLAN, [pad_row], h)
    fin, stats = gossip_round(state, cfg, inject=batch)
    assert int(stats.ingest_offered) == 1
    assert int(stats.ingest_injected) == 0
    s = message_slots(h[0], M, 1)[0]
    assert not bool(fin.seen[pad_row, s])


def test_same_slot_arrivals_conflate_sequentially(ctx):
    # k=1: the second arrival lands on the lease the first just took —
    # it rides the incumbent (still injected) and counts as conflated
    cfg, state, _ = ctx
    h1, h2 = _two_hashes_same_slot(M)
    batch = make_batch(PLAN, [2, 3], [h1, h2])
    _, stats = gossip_round(state, cfg, inject=batch)
    assert int(stats.ingest_injected) == 2
    assert int(stats.ingest_conflated) == 1


def test_k2_sets_both_bloom_planes(ctx):
    cfg, state, _ = ctx
    plan2 = IngestPlan(msg_slots=M, max_inject=4, k_hashes=2)
    h = 12345
    batch = make_batch(plan2, [5], [h])
    fin, stats = gossip_round(state, cfg, inject=batch)
    assert int(stats.ingest_injected) == 1
    for s in message_slots(h, M, 2):
        assert bool(fin.seen[5, s])


def test_packed_round_matches_unpacked_under_ingest(ctx):
    from tpu_gossip.core.packed import pack_state, unpack_state

    cfg, state, _ = ctx
    hs = _hashes_for_distinct_slots(M, 3)
    batch = make_batch(PLAN, [2, 9, 11], hs)
    fin_b, st_b = gossip_round(state, cfg, inject=batch)
    fin_p, st_p = gossip_round(pack_state(state), cfg, inject=batch)
    assert state_digest(fin_b) == state_digest(unpack_state(fin_p))
    for f in type(st_b)._fields:
        a, b = np.asarray(getattr(st_b, f)), np.asarray(getattr(st_p, f))
        if np.issubdtype(a.dtype, np.integer):
            assert np.array_equal(a, b), f


def test_arrival_first_transmits_next_round(ctx):
    # ingest runs post-tail: a round-r arrival cannot ride round r's
    # exchange — its row's seen bit is set only after delivery completed
    cfg, state, _ = ctx
    h = _hashes_for_distinct_slots(M, 1)
    s = message_slots(h[0], M, 1)[0]
    assert s != 0 or True  # slot may collide with the epidemic's slot 0
    row = 7
    batch = make_batch(PLAN, [row], h)
    fin, stats = gossip_round(state, cfg, inject=batch)
    # the arrival's slot gained exactly one holder this round (the
    # origin itself) unless it conflated with slot-0 epidemic spread
    if s != 0:
        holders = int(jnp.sum(fin.seen[:, s] & fin.alive))
        assert holders == 1
