"""The serving frontend end to end: live loopback-socket runs with real
client threads, the recorded-trace replay contract (bit-identical state
digest + integer-stat trajectory), frontend window semantics, and the
CLI's parse-time rejections — the golden tests of docs/serving_frontend.md."""

import asyncio
import functools
import json
import socket
import threading

import jax
import numpy as np
import pytest

from tpu_gossip.compat import wire
from tpu_gossip.core.device_topology import device_powerlaw_graph
from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.fleet.engine import state_digest, stats_digest
from tpu_gossip.serve import (
    ServeDriver,
    ServeFrontend,
    ServeTrace,
    build_step,
    replay_trace,
    run_load,
)
from tpu_gossip.serve.driver import stack_round_stats
from tpu_gossip.traffic.ingest import IngestPlan

N, M = 48, 8


def asyncio_test(fn):
    """pytest-asyncio is not in the image; run coroutine tests directly."""

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        return asyncio.run(fn(*a, **kw))

    return wrapper


def _swarm():
    dg = device_powerlaw_graph(N, gamma=2.5, key=jax.random.key(0))
    graph = dg.as_padded_graph()
    cfg = SwarmConfig(n_peers=graph.n, msg_slots=M, fanout=3, mode="push")

    def make_state():
        return init_swarm(graph, cfg, key=jax.random.key(0),
                          origins=np.array([0]), exists=dg.exists)

    rows = np.flatnonzero(np.asarray(dg.exists))
    return cfg, make_state, rows


def test_live_loopback_replay_bit_identical():
    """The golden contract: a live socket run — real client threads,
    jittered arrivals racing the round windows — replays through the
    pure-sim injection path bit for bit (state + integer stats)."""
    cfg, make_state, rows = _swarm()
    plan = IngestPlan(msg_slots=M, max_inject=4, k_hashes=1)
    fe = ServeFrontend(origin_rows=rows, max_inject=4, port=0)
    fe.start()
    try:
        # a synchronous burst first (guaranteed load: 6 arrivals pending
        # before round 0, > max_inject so the live run defers + bills)...
        pre = run_load("127.0.0.1", fe.port, clients=2, msgs_per_client=3,
                       jitter_s=0.0, seed=3)
        assert pre.sent == 6 and pre.errors == 0
        # ...then a jittered load racing the windows for real
        raced = {}
        t = threading.Thread(target=lambda: raced.update(
            rep=run_load("127.0.0.1", fe.port, clients=2, msgs_per_client=4,
                         jitter_s=0.003, seed=4)))
        t.start()
        driver = ServeDriver(build_step(cfg), make_state(), fe, plan,
                             rounds=10, rounds_per_sec=40.0)
        rep = driver.run()
        t.join(timeout=60.0)
    finally:
        fe.stop()
    assert raced["rep"].errors == 0
    assert rep.trace.num_rounds == 10
    assert rep.trace.total_arrivals >= 6  # the burst is guaranteed in
    # the burst overran the first window: deferred arrivals were billed
    assert int(rep.stats.ingest_overflow.sum()) >= 1
    # every recorded arrival was injected (deferred != dropped)
    assert int(rep.stats.ingest_offered.sum()) == rep.trace.total_arrivals

    # replay: a step built the same way + the same initial state
    fin2, trail = replay_trace(rep.trace, build_step(cfg), make_state())
    stats2 = stack_round_stats([jax.device_get(s) for s in trail])
    assert state_digest(fin2) == state_digest(rep.state)
    assert stats_digest(stats2) == stats_digest(rep.stats)


def test_trace_save_load_roundtrip(tmp_path):
    cfg, make_state, rows = _swarm()
    plan = IngestPlan(msg_slots=M, max_inject=4, k_hashes=1)
    from tpu_gossip.serve import TraceRecorder

    rec = TraceRecorder(plan)
    rec.record_round(0, [(2, 12345), (3, 67890)], overflow=0)
    rec.record_round(1, [], overflow=2)
    trace = rec.finish()
    path = tmp_path / "trace.jsonl"
    trace.save(path)
    assert ServeTrace.load(path) == trace


def test_frontend_window_defers_fifo_and_bills_overflow():
    fe = ServeFrontend(origin_rows=[0, 1, 2], max_inject=2, port=0)
    arrivals = [(i, 100 + i) for i in range(5)]
    with fe._lock:
        fe._pending.extend(arrivals)
    w1, ov1 = fe.take_window()
    assert w1 == arrivals[:2] and ov1 == 3
    w2, ov2 = fe.take_window()
    assert w2 == arrivals[2:4] and ov2 == 1  # FIFO carry, re-billed
    w3, ov3 = fe.take_window()
    assert w3 == arrivals[4:] and ov3 == 0
    assert fe.backlog() == 0
    assert fe.counters.overflow_billed == 4


@asyncio_test
async def test_frontend_speaks_the_reference_wire_protocol():
    fe = ServeFrontend(origin_rows=list(range(8)), max_inject=4, port=0,
                       query_snapshot=lambda: {"round": 3, "coverage": 0.5})
    fe.start()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", fe.port)
        # registration: the seed's contract replies with a pickled subset
        writer.write(wire.encode_peer_handshake(("10.0.0.9", 6000)))
        await writer.drain()
        subset_line = await asyncio.wait_for(reader.readline(), timeout=10.0)
        assert wire.decode_subset(subset_line) == []
        # PING -> heartbeat (the reference's liveness probe reply)
        writer.write(wire.encode_ping())
        await writer.drain()
        hb = await asyncio.wait_for(reader.readline(), timeout=10.0)
        assert wire.classify(hb)[0] == "heartbeat"
        # QUERY -> one JSON line from the driver snapshot
        writer.write(b"QUERY status\n")
        await writer.drain()
        q = await asyncio.wait_for(reader.readline(), timeout=10.0)
        assert json.loads(q) == {"round": 3, "coverage": 0.5}
        # gossip + malformed lines are accepted without a reply
        writer.write(wire.encode_gossip("t0", "10.0.0.9", 6000, 1))
        writer.write(b"Heartbeat from not-an-addr\n")
        writer.close()
    finally:
        fe.stop()
    window, overflow = fe.take_window()
    assert len(window) == 1 and overflow == 0
    assert fe.counters.registrations == 1
    assert fe.counters.pings == 1
    assert fe.counters.malformed == 1


def test_frontend_port_conflict_raises():
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    port = blocker.getsockname()[1]
    try:
        fe = ServeFrontend(origin_rows=[0], max_inject=1, port=port)
        with pytest.raises(OSError):
            fe.start()
    finally:
        blocker.close()


# --- CLI parse-time rejections (exit 2, before any engine builds) ----------

def _run(argv):
    from tpu_gossip.cli.run_sim import main

    return main(argv)


SERVE = ["serve", "--peers", "48", "--slots", "4", "--fanout", "2",
         "--quiet"]


def test_cli_serve_rejections(capsys):
    # run-to-coverage has no serving window
    assert _run(SERVE + ["--slot-ttl", "12"]) == 2
    assert "fixed horizon" in capsys.readouterr().err
    # no streaming slot-plane config at all
    assert _run(SERVE + ["--rounds", "20"]) == 2
    assert "--slot-ttl" in capsys.readouterr().err
    # TTL below the feasible coverage horizon
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "2"]) == 2
    assert "feasible" in capsys.readouterr().err
    # port outside the valid range
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "12",
                         "--port", "70000"]) == 2
    # the sharded serving engine is the matching mesh
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "12",
                         "--shard"]) == 2
    assert "matching" in capsys.readouterr().err
    # compositions the driver does not support yet are named errors
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "12",
                         "--control", "0.9"]) == 2
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "12",
                         "--grow", "96"]) == 2
    assert _run(SERVE + ["--rounds", "20", "--slot-ttl", "12",
                         "--remat-every", "8"]) == 2


def test_cli_serve_port_conflict_exits_2(capsys):
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    port = blocker.getsockname()[1]
    try:
        rc = _run(SERVE + ["--rounds", "6", "--slot-ttl", "10",
                           "--port", str(port)])
    finally:
        blocker.close()
    assert rc == 2
    assert "cannot listen" in capsys.readouterr().err
