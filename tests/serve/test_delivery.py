"""Live-load delivery conformance: the measured serving metrics of a
jittered loopback run agree with the closed-form predictors, at the
streaming plane's conformance tolerances (tests/sim/test_traffic.py).

Two contracts:
- the delivery ratio of a run whose TTL clears the feasible coverage
  horizon matches the predictor (every closed lease episode covers —
  ratio 1.0) within the stream tests' 0.15 relative tolerance;
- the conflation count of R live messages hashed into M slots matches
  ``expected_conflations`` (balls-in-bins) within the stream tests'
  ``0.2 * max(predicted, 1)`` absolute-count tolerance.
"""

import threading

import jax
import numpy as np

from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.serve import ServeDriver, ServeFrontend, build_step, run_load
from tpu_gossip.sim import metrics as M_
from tpu_gossip.traffic import compile_stream
from tpu_gossip.traffic.ingest import IngestPlan

N, M = 64, 8
TTL = 12
ROUNDS = 30


def test_jittered_live_load_matches_closed_form_predictors():
    # preferential attachment is connected by construction — the
    # closed-form delivery predictor (ratio 1.0 once TTL clears the
    # feasible horizon) assumes every peer is reachable
    graph = build_csr(N, preferential_attachment(
        N, m=3, use_native=False, rng=np.random.default_rng(0)))
    cfg = SwarmConfig(n_peers=N, msg_slots=M, fanout=3, mode="push")
    state = init_swarm(graph, cfg, key=jax.random.key(0),
                       origins=np.array([0]))
    rows = np.arange(N)
    # a zero-rate stream mounts the slot-lease age-out and the per-slot
    # coverage tracks the episode metrics read — serving's steady state
    strm = compile_stream(rate=0.0, msg_slots=M, ttl=TTL, origin_rows=rows)
    plan = IngestPlan(msg_slots=M, max_inject=8, k_hashes=1)

    fe = ServeFrontend(origin_rows=rows, max_inject=8, port=0)
    fe.start()
    try:
        raced = {}
        t = threading.Thread(target=lambda: raced.update(
            rep=run_load("127.0.0.1", fe.port, clients=4, msgs_per_client=5,
                         jitter_s=0.003, seed=11)))
        t.start()
        driver = ServeDriver(build_step(cfg, stream=strm), state, fe, plan,
                             rounds=ROUNDS, rounds_per_sec=40.0)
        rep = driver.run()
        t.join(timeout=60.0)
    finally:
        fe.stop()
    assert raced["rep"].errors == 0
    offered = int(rep.stats.ingest_offered.sum())
    assert offered == 20  # every jittered arrival made a window

    # conflation conformance: R live messages into M slots, leases held
    # for the whole arrival window -> balls-in-bins collisions
    measured_conf = int(rep.stats.ingest_conflated.sum())
    predicted_conf = M_.expected_conflations(offered, M)
    assert abs(measured_conf - predicted_conf) < 0.2 * max(predicted_conf, 1) + 2.0

    # delivery conformance: TTL clears the feasible horizon, so the
    # predictor says every closed episode covers (ratio 1.0)
    rel = M_.reliability_report(rep.stats, target_ratio=0.9,
                                coverage_target=0.99)
    assert rel["messages_judged"] > 0  # non-vacuous: leases closed in-run
    assert rel["delivery_ratio"] is not None
    assert abs(rel["delivery_ratio"] - 1.0) < 0.15
    assert rel["holds"]
