"""Serving-protocol conformance: literal reference wire strings in,
typed events out (PARITY.md "Serving live clients").

The frontend's parse is pinned against the reference's EXACT framing —
the same literal lines ``tests/compat/test_wire.py`` pins the codecs
with — plus the serving dispositions layered on top (register / gossip
/ query) and total-parse behavior on malformed lines (the latent
reference bug: its reader thread dies in ``ast.literal_eval``,
reference Peer.py:194-199)."""

import random

import pytest

from tpu_gossip.compat import wire
from tpu_gossip.core.state import message_slots
from tpu_gossip.serve import parse_line, payload_hash64, slots_for_payload
from tpu_gossip.serve.protocol import encode_query, encode_query_reply

ADDR = ("127.0.0.1", 5000)

SERVE_KINDS = {
    "empty", "ping", "seed_handshake", "heartbeat", "dead_node",
    "new_node_update", "malformed", "register", "gossip", "query",
}


# --- literal reference wire strings ----------------------------------------

@pytest.mark.parametrize(
    "line,kind",
    [
        ("PING", "ping"),
        ("I am seed|('127.0.0.1', 5000)", "seed_handshake"),
        ("Heartbeat from ('127.0.0.1', 5000)", "heartbeat"),
        ("Dead Node: ('127.0.0.1', 5000)", "dead_node"),
        ("NewNodeUpdate|('a', 1)|[('b', 2)]", "new_node_update"),
        ("('127.0.0.1', 5000)", "register"),  # bare handshake (Peer.py:95-97)
        ("QUERY coverage", "query"),
        ("2025-01-01 00:00:00:127.0.0.1:5000:3", "gossip"),
        ("hello world", "gossip"),  # unknown text disseminates as-is
        ("", "empty"),
        ("Heartbeat from not-an-addr", "malformed"),
        ("Dead Node: 42", "malformed"),
        ("NewNodeUpdate|('a',1)|5", "malformed"),
    ],
)
def test_parse_line_literal_strings(line, kind):
    ev = parse_line(line)
    assert ev.kind == kind
    assert ev.kind in SERVE_KINDS


def test_register_carries_decoded_addr():
    ev = parse_line(wire.encode_peer_handshake(ADDR))
    assert ev.kind == "register" and ev.payload == ADDR


def test_heartbeat_carries_decoded_addr():
    ev = parse_line(wire.encode_heartbeat(ADDR))
    assert ev.kind == "heartbeat" and ev.payload == ADDR


def test_gossip_event_identity_is_wire_message_id():
    raw = wire.encode_gossip("2025-01-01 00:00:00", "10.0.0.1", 6000, 7)
    ev = parse_line(raw)
    assert ev.kind == "gossip"
    assert ev.message_id == wire.gossip_message_id(raw.decode())
    assert ev.payload_hash == payload_hash64(ev.message_id)


def test_query_strips_prefix_and_frames_reply():
    ev = parse_line(encode_query("liveness"))
    assert ev.kind == "query" and ev.payload == "liveness"
    reply = encode_query_reply('{"a": 1,\n "b": 2}')
    assert reply.endswith(b"\n") and reply.count(b"\n") == 1


def test_malformed_lines_never_raise():
    # total parse: the frontend's reader loop survives any bytes
    for raw in (b"\xff\xfe garbage", b"Heartbeat from ('x',",
                b"I am seed|[[[", b"\x00" * 64, "Dead Node: ".encode()):
        assert parse_line(raw).kind in SERVE_KINDS


# --- property round-trips (seeded; hypothesis is not in the image) ---------

def test_gossip_roundtrip_property():
    rng = random.Random(0)
    for _ in range(300):
        ts = f"2025-01-01 00:00:{rng.randrange(60):02d}"
        ip = ".".join(str(rng.randrange(256)) for _ in range(4))
        port, count = rng.randrange(1, 65536), rng.randrange(10**6)
        raw = wire.encode_gossip(ts, ip, port, count)
        ev = parse_line(raw)
        assert ev.kind == "gossip"
        assert ev.message_id == raw.decode().strip()
        # the hash is a pure function of the dedup identity
        assert ev.payload_hash == parse_line(raw).payload_hash


def test_wire_framing_records_roundtrip_property():
    # every reference framing record round-trips through parse_line with
    # its wire kind preserved (the PARITY framing catalog)
    rng = random.Random(1)
    for _ in range(200):
        addr = (f"10.{rng.randrange(256)}.{rng.randrange(256)}.1",
                rng.randrange(1, 65536))
        assert parse_line(wire.encode_heartbeat(addr)).payload == addr
        assert parse_line(wire.encode_dead_node(addr)).payload == addr
        assert parse_line(wire.encode_seed_handshake(addr)).payload == addr
        assert parse_line(wire.encode_peer_handshake(addr)).payload == addr
        assert parse_line(wire.encode_ping()).kind == "ping"


def test_parse_total_on_random_bytes():
    rng = random.Random(2)
    for _ in range(300):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        assert parse_line(blob).kind in SERVE_KINDS


# --- the hash → slot contract (live == replay by construction) -------------

def test_payload_hash64_is_fnv1a_64():
    # pinned constants: changing them would silently break every recorded
    # trace's replay
    assert payload_hash64("") == 0xCBF29CE484222325
    assert payload_hash64("a") == 0xAF63DC4C8601EC8C


def test_slots_for_payload_matches_message_slots():
    rng = random.Random(3)
    for _ in range(100):
        h = rng.getrandbits(64)
        m = rng.choice([4, 8, 16, 32])
        k = rng.randrange(1, min(m, 4) + 1)
        assert slots_for_payload(h, m, k) == message_slots(h, m, k)


def test_slot_draw_agrees_across_the_socket_boundary():
    # a gossip line hashed live maps to the same slots as its recorded
    # trace integer does in replay — the bit-identity hinge
    raw = wire.encode_gossip("2025-01-01 00:00:00", "10.0.0.1", 6000, 7)
    ev = parse_line(raw)
    assert slots_for_payload(ev.payload_hash, 16, 2) == \
        message_slots(ev.payload_hash, 16, 2)
