"""Test harness: force CPU with 8 virtual devices so multi-chip sharding
tests run anywhere (SURVEY.md §4).

The axon (TPU tunnel) sitecustomize imports jax at interpreter start and
calls jax.config.update("jax_platforms", "axon,cpu"), so env vars alone are
too late — the config must be re-updated here. XLA_FLAGS still works because
CPU client creation is lazy (first jax.devices() happens inside the tests).
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""  # belt-and-braces for subprocesses
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
