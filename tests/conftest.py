"""Test harness: force CPU with 8 virtual devices so multi-chip sharding
tests run anywhere (SURVEY.md §4) — must run before jax is imported."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
