"""Conformance: simulated failure-detection latency pinned inside the
reference's wall-clock band (SURVEY §2.5), as a CI test rather than only a
bench entry (BENCH_DETAIL.json ``liveness_1k``).

The reference's constants — 15 s heartbeats, 30 s stale threshold, 10 s
detector sweep, 2 s ping grace — bound worst-case silent-peer detection at
30–42 s after the last heartbeat. Under the round mapping (1 round =
``gossip_period`` seconds) the engine detects at round 8, i.e. 40 s of
reference time: inside the band. The test pins the whole derivation —
``ProtocolTiming`` → ``SwarmConfig`` round constants → detector behavior —
and pins it as SCALE-INVARIANT: a uniformly scaled timing (the 100×-faster
integration-test clock, ``ProtocolTiming.scaled``) must produce the same
round schedule, hence the same reference-equivalent latency.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.sim.engine import simulate

REFERENCE_BAND_SECONDS = (30.0, 42.0)  # SURVEY §2.5 worst-case detection
N = 500
SILENT = 50


def _cfg_from_timing(t: ProtocolTiming) -> SwarmConfig:
    """The one mapping from the reference's wall-clock contract to round
    constants (core/state.py's documented defaults, derived not copied)."""
    round_s = t.gossip_period
    return SwarmConfig(
        n_peers=N,
        msg_slots=4,
        fanout=3,
        mode="push",
        hb_period_rounds=round(t.heartbeat_period / round_s),
        timeout_rounds=round(t.heartbeat_timeout / round_s),
        detect_period_rounds=round(t.detect_period / round_s),
        round_seconds=round_s,
    )


def _detection_round(cfg: SwarmConfig, rounds: int = 12,
                     liveness=None) -> int:
    graph = build_csr(
        N, preferential_attachment(N, m=3, use_native=False,
                                   rng=np.random.default_rng(7))
    )
    state = init_swarm(graph, cfg, origins=[0], key=jax.random.key(0))
    silent_ids = np.random.default_rng(7).choice(N, size=SILENT, replace=False)
    state.silent = state.silent.at[jnp.asarray(silent_ids)].set(True)
    fin, stats = simulate(state, cfg, rounds, None, "fused", None, None,
                          None, None, None, liveness)
    dead = np.asarray(stats.n_declared_dead)
    assert dead[-1] == SILENT, "detector missed silent peers"
    live_false = np.asarray(fin.declared_dead) & ~np.isin(
        np.arange(N), silent_ids
    )
    assert not live_false.any(), "a responsive peer was declared dead"
    hit = np.nonzero(dead >= SILENT)[0]
    return int(hit[0]) + 1


@pytest.mark.parametrize(
    "factor",
    [1.0, pytest.param(0.01, marks=pytest.mark.slow)],
    ids=["reference", "scaled-100x"],
)  # the unscaled row carries tier-1; scaling cancels in every ratio
def test_detection_latency_inside_reference_band(factor):
    timing = ProtocolTiming().scaled(factor)
    cfg = _cfg_from_timing(timing)
    # the mapping itself must reproduce the documented round constants
    # whatever the scale — uniform scaling cancels in every ratio
    assert (cfg.hb_period_rounds, cfg.timeout_rounds,
            cfg.detect_period_rounds) == (3, 6, 2)
    detection_round = _detection_round(cfg)
    # reference-equivalent seconds: rounds × the UNSCALED 5 s gossip tick
    secs = detection_round * ProtocolTiming().gossip_period
    lo, hi = REFERENCE_BAND_SECONDS
    assert lo <= secs <= hi, (
        f"simulated detection at {secs:.0f}s-equivalent (round "
        f"{detection_round}) left the reference's {lo:.0f}-{hi:.0f}s band"
    )


@pytest.mark.parametrize(
    "quorum_k",
    [2, pytest.param(3, marks=pytest.mark.slow),
     pytest.param(7, marks=pytest.mark.slow)],
)  # one quorum point witnesses the band in tier-1
def test_quorum_detection_stays_inside_reference_band(quorum_k):
    """The defense cannot cost the parity contract: with no adversaries
    and quorum_k > 1, the hardened detector's latency must still land
    inside the reference's 30-42 s band under the scaled ProtocolTiming —
    the whole live witness cohort confirms a genuinely-stale suspect on
    its first sweep, so quorum adds no sweeps (ISSUE 14 satellite)."""
    from tpu_gossip.kernels.liveness import compile_quorum

    timing = ProtocolTiming().scaled(0.01)
    cfg = _cfg_from_timing(timing)
    detection_round = _detection_round(
        cfg, liveness=compile_quorum(quorum_k, window=4, budget=3)
    )
    secs = detection_round * ProtocolTiming().gossip_period
    lo, hi = REFERENCE_BAND_SECONDS
    assert lo <= secs <= hi, (
        f"quorum_k={quorum_k} detection at {secs:.0f}s-equivalent (round "
        f"{detection_round}) left the reference's {lo:.0f}-{hi:.0f}s band"
    )


def test_band_is_tight_not_vacuous():
    """The pin must fail if someone loosens the detector: doubling the
    timeout pushes detection past the band's upper edge."""
    t = ProtocolTiming()
    cfg = _cfg_from_timing(t)
    slow = SwarmConfig(
        n_peers=cfg.n_peers, msg_slots=cfg.msg_slots, fanout=cfg.fanout,
        mode=cfg.mode, hb_period_rounds=cfg.hb_period_rounds,
        timeout_rounds=cfg.timeout_rounds * 2,
        detect_period_rounds=cfg.detect_period_rounds,
        round_seconds=cfg.round_seconds,
    )
    secs = _detection_round(slow, rounds=20) * t.gossip_period
    assert secs > REFERENCE_BAND_SECONDS[1]
