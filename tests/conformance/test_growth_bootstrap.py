"""Conformance: socket-mode bootstrap vs the sim growth engine.

The reference's membership growth is seeds handing each registering peer
a degree-preferential subset (compat/seed.py ``get_peer_subset``,
subset_policy="powerlaw" — the corrected semantics of the reference's
dead ``powerlaw_connect``). The growth engine (growth/) is the same
process vectorized: per-round join batches attaching degree-
preferentially inside the jitted round. Both bootstrap processes must
therefore build the SAME KIND of topology — compared here on one
degree-distribution statistic with tolerance, the curves-style contract
of test_curves.py ("matching distributions, not traces").

The socket side is a real localhost cluster: peers register one at a
time through the seeds' rendezvous handout; the resulting topology is
read from the seeds' replicated registry. The sim side grows a K4 clique
to the same size at one admission per round (sequential, like
registration). Both sides attach 3 edges per arrival, so the comparison
pins the SHAPE the preferential bias produces — mean degree (edge
accounting) and hub mass (the power-law signature).
"""

import asyncio
import functools
import socket as socketlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.compat.seed import SeedNode
from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.growth import compile_growth, pad_graph_for_growth
from tpu_gossip.growth.engine import realized_degrees
from tpu_gossip.sim.engine import simulate

N_SWARM = 24  # final size, both transports
ATTACH = 3  # seed subset_size == growth attach_m
SCALE = 0.02
TIMING = ProtocolTiming().scaled(SCALE)


def asyncio_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        return asyncio.run(fn(*a, **kw))

    return wrapper


def free_ports(n):
    socks = [socketlib.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


async def socket_bootstrap_degrees(tmp_path, n_peers) -> np.ndarray:
    """Register ``n_peers`` through a 2-seed cluster (powerlaw subset
    handout, ATTACH neighbors each) and return the peers' degree
    sequence from the replicated seed registry."""
    config = tmp_path / "config.txt"
    config.write_text("")
    ports = free_ports(2 + n_peers)
    seeds = []
    for p in ports[:2]:
        s = SeedNode("127.0.0.1", p, str(config), timing=TIMING,
                     subset_policy="powerlaw", subset_size=ATTACH,
                     log_dir=str(tmp_path), rng_seed=0)
        await s.start()
        seeds.append(s)
    await asyncio.sleep(TIMING.seed_reconnect_period * 1.5)
    peers = []
    try:
        for p in ports[2:]:
            node = PeerNode("127.0.0.1", p, str(config), timing=TIMING,
                            log_dir=str(tmp_path))
            await node.start()
            peers.append(node)
            await asyncio.sleep(TIMING.registration_settle * 2.5)
        await asyncio.sleep(TIMING.heartbeat_period)  # topology replicates
        topo = seeds[0].network_topology
        addrs = [p.addr for p in peers]
        return np.asarray([len(topo.get(a, ())) for a in addrs])
    finally:
        for n in peers + seeds:
            await n.stop()


def sim_growth_degrees(n_final, seed) -> np.ndarray:
    """Grow a K4 clique to ``n_final`` at one admission per round —
    the registration process, vectorized — and return the realized
    degree sequence."""
    n0 = ATTACH + 1
    graph = build_csr(
        n0, preferential_attachment(n0, m=ATTACH, use_native=False,
                                    rng=np.random.default_rng(seed))
    )
    pg, exists = pad_graph_for_growth(graph, n_final)
    cfg = SwarmConfig(n_peers=n_final, msg_slots=1, fanout=2, mode="push",
                      rewire_slots=ATTACH)
    st = init_swarm(pg, cfg, origins=[0], exists=jnp.asarray(exists),
                    key=jax.random.key(seed))
    gp = compile_growth(n_initial=n0, target=n_final, n_slots=n_final,
                        joins_per_round=1, attach_m=ATTACH)
    fin, _ = simulate(st, cfg, n_final - n0 + 1, None, "fused", None, gp)
    assert int(np.asarray(fin.exists).sum()) == n_final
    return np.asarray(
        realized_degrees(fin.row_ptr, fin.exists, fin.rewired,
                     fin.rewire_targets, fin.degree_credit)
    )[: n_final]


@pytest.mark.slow  # 3-seed socket bootstrap sweep; the socket-vs-sim curve
# keeps socket conformance in tier-1
@asyncio_test
async def test_socket_bootstrap_vs_growth_engine_degrees(tmp_path):
    sock_deg = await socket_bootstrap_degrees(tmp_path, N_SWARM)
    sim_degs = [sim_growth_degrees(N_SWARM, seed=s) for s in range(3)]

    # every socket peer except the very first got a non-empty handout
    assert (sock_deg > 0).sum() >= N_SWARM - 1

    # edge accounting: both processes add ~ATTACH edges per arrival, so
    # mean degrees agree within stochastic tolerance (the socket side's
    # first registrant and dropped handouts shave a little)
    sim_mean = np.median([d.mean() for d in sim_degs])
    assert 0.6 * sim_mean <= sock_deg.mean() <= 1.4 * sim_mean, (
        sock_deg.mean(), sim_mean,
    )

    # the preferential-attachment signature: early/hub nodes accumulate a
    # disproportionate share of the edges on BOTH sides, and the hub mass
    # (top-3 share of total degree) agrees within a band
    def hub_share(d):
        d = np.sort(d)[::-1]
        return d[:3].sum() / max(d.sum(), 1)

    sim_share = np.median([hub_share(d) for d in sim_degs])
    assert abs(hub_share(sock_deg) - sim_share) <= 0.15, (
        hub_share(sock_deg), sim_share,
    )
    # and both are genuinely skewed (a uniform handout would sit at 3/24)
    assert hub_share(sock_deg) > 0.2
    assert sim_share > 0.2
