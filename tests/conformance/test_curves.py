"""Conformance: socket-transport coverage curves vs the tpu-sim engine on
the SAME graph (BASELINE.json north star: "coverage-vs-round curves matching
the socket baseline").

Both transports run identical push-gossip semantics — every round/tick, each
infected peer pushes what it has seen to `fanout` uniformly sampled
neighbors — over one fixed preferential-attachment graph. The curves are
stochastic (independent RNGs), so we compare rounds-to-X% within a
tolerance, not traces (SURVEY.md §7.4 "matching distributions, not traces").

The socket side is BARRIER-STEPPED (relay_mode="manual" + an explicit
drain between rounds): a "round" is exactly one push_tick per peer, never a
wall-clock bin, so the curve cannot run ahead of the sim under machine load
(the round-1 flake: free-running ticks let several relay hops land in one
0.08 s bin).
"""

import asyncio
import functools
import socket as socketlib

import numpy as np
import pytest

from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.compat.simnet import SimCluster
from tpu_gossip.compat.timing import ProtocolTiming
from tpu_gossip.core.topology import build_csr, preferential_attachment

N = 40  # default swarm size; the 1k north-star-scale test overrides per call
FANOUT = 3
TICK = 0.08  # socket gossip period (seconds per round)


def asyncio_test(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        return asyncio.run(fn(*a, **kw))

    return wrapper


def fixed_graph(n: int = N):
    return build_csr(n, preferential_attachment(n, m=3, use_native=False,
                                                rng=np.random.default_rng(42)))


def free_ports(n):
    socks = [socketlib.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


async def drain(peers, msg: str, settle: float = 0.01, timeout: float = 2.0) -> None:
    """Wait until the per-round coverage stops changing: all in-flight writes
    from this barrier's push_ticks have been read and counted. Requires 3
    consecutive stable polls so a briefly starved reader coroutine (loaded
    machine) doesn't fake quiescence."""
    deadline = asyncio.get_event_loop().time() + timeout
    prev, stable = -1, 0
    while asyncio.get_event_loop().time() < deadline:
        cur = sum(p.has_seen(msg) for p in peers)
        stable = stable + 1 if cur == prev else 0
        if stable >= 3:
            return
        prev = cur
        await asyncio.sleep(settle)


async def socket_curve(graph, origin: int, rounds: int, tmp_path) -> np.ndarray:
    """Barrier-stepped push gossip over real sockets on the given graph."""
    n = graph.n
    timing = ProtocolTiming(
        gossip_period=TICK, heartbeat_period=10.0, detect_period=10.0,
        heartbeat_timeout=60.0,
    )
    ports = free_ports(n)
    addrs = [("127.0.0.1", p) for p in ports]
    peers = [
        PeerNode(*a, timing=timing, relay_mode="manual", fanout=FANOUT,
                 log_dir=str(tmp_path))
        for a in addrs
    ]
    for p in peers:
        await p.start_detached()
    for i, p in enumerate(peers):
        await p.connect_to([addrs[j] for j in graph.neighbors(i) if j > i])
    await asyncio.sleep(TICK)

    peers[origin].gossip("conformance-msg")
    curve = []
    for _ in range(rounds):
        # barrier: snapshot every peer's seen-set first (simultaneous-round
        # semantics — receipts during the barrier relay next round), then
        # exactly one push tick per peer, then settle so every write issued
        # this round is received before the next round starts
        snaps = [list(p.seen_messages) for p in peers]
        for p, snap in zip(peers, snaps):
            await p.push_tick(snap)
        await drain(peers, "conformance-msg")
        curve.append(sum(p.has_seen("conformance-msg") for p in peers) / n)
    for p in peers:
        await p.stop()
    return np.asarray(curve)


def sim_curve(graph, origin: int, rounds: int, seed: int) -> np.ndarray:
    """Per-round coverage of the message's hash slot on the tpu-sim engine."""
    cluster = SimCluster(msg_slots=8, fanout=FANOUT, seed=seed)
    peers = [
        PeerNode("10.0.0.1", 9000 + i, transport="tpu-sim", cluster=cluster)
        for i in range(graph.n)
    ]
    cluster.materialize(graph=graph)
    peers[origin].gossip("conformance-msg")
    curve = []
    for _ in range(rounds):
        cluster.step(1)
        curve.append(cluster.coverage("conformance-msg"))
    return np.asarray(curve)


def rounds_to(curve: np.ndarray, frac: float) -> int:
    hit = np.nonzero(curve >= frac)[0]
    return int(hit[0]) + 1 if hit.size else len(curve) + 10


@asyncio_test
async def test_socket_vs_sim_curves_agree(tmp_path):
    graph = fixed_graph()
    origin = int(np.argmax(graph.degrees))
    rounds = 25

    sock = await socket_curve(graph, origin, rounds, tmp_path)
    sims = [sim_curve(graph, origin, rounds, seed=s) for s in range(3)]

    # both reach (near-)full coverage
    assert sock[-1] >= 0.99
    assert all(c[-1] >= 0.99 for c in sims)

    # rounds-to-50% and rounds-to-99% agree within stochastic tolerance
    sim_r50 = np.median([rounds_to(c, 0.5) for c in sims])
    sim_r99 = np.median([rounds_to(c, 0.99) for c in sims])
    assert abs(rounds_to(sock, 0.5) - sim_r50) <= 3
    assert abs(rounds_to(sock, 0.99) - sim_r99) <= 5

    # same epidemic shape: monotone, and mid-curve values within 0.35
    mid = slice(2, rounds - 5)
    assert np.all(np.diff(sock) >= -1e-9)
    assert np.max(np.abs(sock[mid] - np.mean(sims, axis=0)[mid])) <= 0.35


@pytest.mark.slow  # 1000 real sockets; the 40-peer curve above keeps the
# socket-vs-sim conformance law in tier-1
@asyncio_test
async def test_socket_vs_sim_curves_agree_1k(tmp_path):
    """The north-star conformance criterion at its stated scale
    (BASELINE.json: "curves matching the 1k-peer socket baseline").
    1000 real localhost sockets, barrier-stepped, ~5 s wall."""
    import resource

    import pytest

    # 1000 servers + ~2x3000 per-edge connections need ~8k descriptors;
    # restore the process-wide limit afterwards so it can't leak into
    # later tests in this process
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = 10_000
    hard_cap = want if hard == resource.RLIM_INFINITY else hard
    raised = False
    if soft < want:
        if hard_cap < want:
            pytest.skip(f"needs ~{want} fds; RLIMIT_NOFILE hard cap is {hard}")
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
            raised = True
        except (ValueError, OSError):
            pytest.skip(f"needs ~{want} fds; RLIMIT_NOFILE is {soft}/{hard}")
    try:
        graph = fixed_graph(1000)
        origin = int(np.argmax(graph.degrees))
        rounds = 20

        sock = await socket_curve(graph, origin, rounds, tmp_path)
        sims = [sim_curve(graph, origin, rounds, seed=s) for s in range(3)]

        assert sock[-1] >= 0.99
        assert all(c[-1] >= 0.99 for c in sims)
        sim_r50 = np.median([rounds_to(c, 0.5) for c in sims])
        sim_r99 = np.median([rounds_to(c, 0.99) for c in sims])
        # tighter than the 40-peer test: at 1k the stochastic curves
        # concentrate (observed exact agreement, 7/7 and 11/11)
        assert abs(rounds_to(sock, 0.5) - sim_r50) <= 2
        assert abs(rounds_to(sock, 0.99) - sim_r99) <= 3
    finally:
        if raised:
            resource.setrlimit(resource.RLIMIT_NOFILE, (soft, hard))


def test_sim_curve_deterministic():
    graph = fixed_graph()
    a = sim_curve(graph, 0, 10, seed=7)
    b = sim_curve(graph, 0, 10, seed=7)
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow  # cross-family statistical sweep; unit-level matching
# twins and the socket conformance curve carry tier-1
def test_matching_vs_device_family_curves_agree():
    """Cross-family conformance: the structured-matching generator and the
    sort-based device generator sample the SAME erased configuration model
    (same truncated-Pareto law, same erasure rule), so at matched
    (gamma, fanout, n) their coverage-vs-round curves must agree within
    stochastic tolerance — the matching family's deterministic quantile
    degrees and pipeline pairing must not change the epidemic. Push mode,
    hub origin on both sides (matching ids are degree-ascending, so its
    hub is the last real id; the device family's is argmax degree)."""
    import jax

    from tpu_gossip.core.device_topology import device_powerlaw_graph
    from tpu_gossip.core.matching_topology import matching_powerlaw_graph
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.sim.metrics import rounds_to_coverage

    n, gamma, fanout, rounds = 20_000, 2.5, 3, 40
    seeds = range(3)

    def device_curves():
        out = []
        for s in seeds:
            dg = device_powerlaw_graph(n, gamma=gamma, key=jax.random.key(s))
            cfg = SwarmConfig(n_peers=dg.n_pad, msg_slots=4, fanout=fanout,
                              mode="push")
            origin = int(np.argmax(np.asarray(dg.degrees)[:n]))
            st = init_swarm(dg.as_padded_graph(), cfg, origins=[origin],
                            exists=dg.exists, key=jax.random.key(100 + s))
            _, stats = simulate(st, cfg, rounds)
            out.append(stats)
        return out

    def matching_curves():
        out = []
        for s in seeds:
            mg, plan = matching_powerlaw_graph(
                n, gamma=gamma, fanout=fanout, key=jax.random.key(s)
            )
            cfg = SwarmConfig(n_peers=plan.n + 1, msg_slots=4, fanout=fanout,
                              mode="push")
            st = init_swarm(mg.as_padded_graph(), cfg, origins=[n - 1],
                            exists=mg.exists, key=jax.random.key(100 + s))
            _, stats = simulate(st, cfg, rounds, plan)
            out.append(stats)
        return out

    dev, mat = device_curves(), matching_curves()
    for target, tol in ((0.5, 3), (0.99, 4)):
        r_dev = np.median([rounds_to_coverage(s, target) for s in dev])
        r_mat = np.median([rounds_to_coverage(s, target) for s in mat])
        assert r_dev > 0 and r_mat > 0, (target, r_dev, r_mat)
        assert abs(r_dev - r_mat) <= tol, (target, r_dev, r_mat)
    # same epidemic shape mid-curve (both families, mean over seeds)
    c_dev = np.mean([np.asarray(s.coverage) for s in dev], axis=0)
    c_mat = np.mean([np.asarray(s.coverage) for s in mat], axis=0)
    assert np.max(np.abs(c_dev[5:25] - c_mat[5:25])) <= 0.35
