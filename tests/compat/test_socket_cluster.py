"""Integration: a real localhost swarm over the socket transport, timers
scaled 50× so the whole reference protocol plays out in seconds
(SURVEY.md §4: the reference's only 'test' was this, manually, in N
terminals)."""

import asyncio
import functools
import socket


def asyncio_test(fn):
    """pytest-asyncio is not in the image; run coroutine tests directly."""

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        return asyncio.run(fn(*a, **kw))

    return wrapper

from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.compat.seed import SeedNode
from tpu_gossip.compat.timing import ProtocolTiming

SCALE = 0.02  # 50x faster than the reference contract
TIMING = ProtocolTiming().scaled(SCALE)


def free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


async def start_cluster(tmp_path, n_seeds=2, n_peers=5, **peer_kw):
    config = tmp_path / "config.txt"
    config.write_text("")
    ports = free_ports(n_seeds + n_peers)
    seeds = []
    for p in ports[:n_seeds]:
        s = SeedNode("127.0.0.1", p, str(config), timing=TIMING,
                     log_dir=str(tmp_path), rng_seed=0)
        await s.start()
        seeds.append(s)
    await asyncio.sleep(TIMING.seed_reconnect_period * 1.5)  # mesh forms
    peers = []
    for p in ports[n_seeds:]:
        node = PeerNode("127.0.0.1", p, str(config), timing=TIMING,
                        log_dir=str(tmp_path), **peer_kw)
        await node.start()
        peers.append(node)
        await asyncio.sleep(TIMING.registration_settle * 2.5)
    return seeds, peers


async def stop_all(seeds, peers):
    for n in peers + seeds:
        await n.stop()


@asyncio_test
async def test_bootstrap_and_seed_mesh(tmp_path):
    seeds, peers = await start_cluster(tmp_path, n_seeds=3, n_peers=4)
    try:
        # config.txt self-registration: every seed appended itself
        lines = (tmp_path / "config.txt").read_text().splitlines()
        assert len(lines) == 3
        # seed mesh is fully connected
        for s in seeds:
            assert len(s.seed_writers) == 2
        # every peer got registered at its quorum of seeds and learned
        # neighbors (except the very first peer, who had nobody to meet)
        connected = [p for p in peers if p.neighbors]
        assert len(connected) >= len(peers) - 1
        # replicated topology: all seeds eventually know all peers
        await asyncio.sleep(TIMING.heartbeat_period)
        peer_addrs = {p.addr for p in peers}
        for s in seeds:
            assert peer_addrs <= set(s.known_peers)
    finally:
        await stop_all(seeds, peers)


@asyncio_test
async def test_gossip_epidemic_relay(tmp_path):
    """A message injected at one peer floods the whole swarm through relay +
    dedup (the north-star generalization; reference gossip is one-hop)."""
    seeds, peers = await start_cluster(tmp_path, n_seeds=2, n_peers=6)
    try:
        peers[0].gossip("hello-swarm")
        await asyncio.sleep(TIMING.gossip_period * 6)
        got = [p for p in peers if p.has_seen("hello-swarm")]
        assert len(got) == len(peers)
        # dedup: each peer recorded it exactly once
        for p in peers:
            assert p.gossip_log.count("hello-swarm") == 1
    finally:
        await stop_all(seeds, peers)


@asyncio_test
async def test_silent_peer_detected_and_purged(tmp_path):
    """Silent-mode fault: neighbors PING, declare dead, report to seeds,
    seeds purge the node from the replicated topology (Peer.py:298-363 →
    Seed.py:358-406)."""
    seeds, peers = await start_cluster(tmp_path, n_seeds=2, n_peers=5)
    try:
        victim = next(p for p in peers if p.neighbors)
        victim.set_silent(True)
        # worst case ≈ timeout + sweep + grace (SURVEY §6: 30-42 s real time)
        await asyncio.sleep(
            TIMING.heartbeat_timeout + 3 * TIMING.detect_period + 3 * TIMING.ping_grace
        )
        assert all(victim.addr not in s.network_topology for s in seeds)
        assert all(victim.addr not in s.known_peers for s in seeds)
    finally:
        await stop_all(seeds, peers)


@asyncio_test
async def test_simultaneous_failures_detected_in_one_sweep(tmp_path):
    """The detector batches its PING grace: k simultaneously-silent peers
    are declared dead in ONE grace period, not k (the reference serializes
    the grace per stale peer, Peer.py:298-363 — a deliberate divergence).

    Timing is chosen so the batched and serial behaviors are far apart:
    grace is the dominant term, so 4 victims under a serial sweep need
    ~sweep + 4*grace = ~2.45 s while the batched sweep finishes in
    ~sweep + grace = ~0.95 s. The deadline sits between them."""
    timing = ProtocolTiming(
        heartbeat_period=0.1, detect_period=0.15, heartbeat_timeout=0.3,
        ping_grace=0.5, gossip_period=10.0, seed_reconnect_period=10.0,
        registration_settle=0.1, subset_apply_delay=0.1, connect_timeout=2.0,
        topology_dump_period=60.0,
    )
    config = tmp_path / "config.txt"
    config.write_text("")
    ports = free_ports(5)
    nodes = [
        PeerNode("127.0.0.1", p, str(config), timing=timing, log_dir=str(tmp_path))
        for p in ports
    ]
    observer, victims = nodes[0], nodes[1:]
    for n in nodes:
        await n.start_detached()
    try:
        await observer.connect_to([v.addr for v in victims])
        assert len(observer.out_conns) == 4
        await asyncio.sleep(timing.heartbeat_period * 1.5)  # heartbeats flow
        for v in victims:
            v.set_silent(True)
        t0 = asyncio.get_event_loop().time()
        # stale by t=timeout, swept within detect_period, ONE shared grace
        deadline = t0 + timing.heartbeat_timeout + timing.detect_period \
            + timing.ping_grace + 0.85
        while asyncio.get_event_loop().time() < deadline:
            if not observer.out_conns:
                break
            await asyncio.sleep(0.05)
        assert not observer.out_conns, (
            f"still connected after one batched sweep window: "
            f"{list(observer.out_conns)}"
        )
    finally:
        for n in nodes:
            await n.stop()


@asyncio_test
async def test_healthy_swarm_no_false_positives(tmp_path):
    seeds, peers = await start_cluster(tmp_path, n_seeds=2, n_peers=4)
    try:
        await asyncio.sleep(TIMING.heartbeat_timeout * 1.5)
        peer_addrs = {p.addr for p in peers}
        for s in seeds:
            assert peer_addrs <= set(s.known_peers)  # nobody purged
    finally:
        await stop_all(seeds, peers)


@asyncio_test
async def test_reference_conformant_one_hop(tmp_path):
    """gossip_relay=False reproduces the reference's log-only receive
    (Peer.py:286,206): messages reach direct neighbors only."""
    seeds, peers = await start_cluster(tmp_path, n_seeds=2, n_peers=6,
                                       gossip_relay=False)
    try:
        origin = max(peers, key=lambda p: len(p.neighbors))
        origin.gossip("one-hop")
        await asyncio.sleep(TIMING.gossip_period * 4)
        nbrs = set(origin.neighbors)
        for p in peers:
            if p is origin:
                continue
            if p.addr in nbrs:
                assert p.has_seen("one-hop")
            else:
                assert not p.has_seen("one-hop")
    finally:
        await stop_all(seeds, peers)


@asyncio_test
async def test_seed_mesh_survives_hung_and_hostile_config_entries(tmp_path):
    """A config.txt entry that accepts-and-never-replies (hung service) or
    replies with garbage must cost one sweep iteration, not kill or stall
    the reconnect loop: the two real seeds still form their mesh."""
    config = tmp_path / "config.txt"
    hung_port, garbage_port, s1, s2 = free_ports(4)
    hung_tasks = []

    async def hung_handler(reader, writer):
        hung_tasks.append(asyncio.current_task())
        try:
            await asyncio.sleep(30)  # accept, never reply (cancelled at teardown)
        finally:
            writer.close()

    async def garbage_handler(reader, writer):
        # writer must be closed, else 3.12's Server.wait_closed() waits
        # forever on the lingering connection
        try:
            await reader.readline()
            writer.write(b"I am seed|((((\n")
            await writer.drain()
        finally:
            writer.close()

    hung = await asyncio.start_server(hung_handler, "127.0.0.1", hung_port)
    garbage = await asyncio.start_server(garbage_handler, "127.0.0.1", garbage_port)
    # pre-seed the config with the two bad entries; real seeds self-append
    config.write_text(f"127.0.0.1:{hung_port}\n127.0.0.1:{garbage_port}\n")

    seeds = []
    for p in (s1, s2):
        s = SeedNode("127.0.0.1", p, str(config), timing=TIMING,
                     log_dir=str(tmp_path), rng_seed=0)
        await s.start()
        seeds.append(s)
    try:
        # two sweeps: the first pays the bad-entry timeouts, the second must
        # still run (loop alive) and link the real seeds
        deadline = asyncio.get_event_loop().time() + 30 * TIMING.connect_timeout
        while asyncio.get_event_loop().time() < deadline:
            if (seeds[1].addr in seeds[0].seed_writers
                    or seeds[0].addr in seeds[1].seed_writers):
                break
            await asyncio.sleep(TIMING.seed_reconnect_period / 2)
        else:
            raise AssertionError(
                f"seed mesh never formed past the bad entries: "
                f"{[list(s.seed_writers) for s in seeds]}"
            )
    finally:
        for s in seeds:
            await s.stop()
        for t in hung_tasks:
            t.cancel()
        hung.close()
        garbage.close()
        for srv in (hung, garbage):
            try:
                await asyncio.wait_for(srv.wait_closed(), timeout=5)
            except (asyncio.TimeoutError, TimeoutError):
                pass  # teardown is best-effort; never hang the suite


@asyncio_test
async def test_stdin_passthrough_reaches_seeds(tmp_path):
    """The reference forwards unrecognized stdin lines to every seed
    (Peer.py:441-442); the seed logs them as unrecognized traffic
    (Seed.py:440-441 counterpart: our seed logs the raw line)."""
    seeds, peers = await start_cluster(tmp_path, n_seeds=2, n_peers=1)
    try:
        n = peers[0].send_to_seeds("operator note: hello")
        assert n == len(peers[0].seed_writers) >= 1
        await asyncio.sleep(TIMING.seed_reconnect_period)
    finally:
        await stop_all(seeds, peers)
    # the line reached at least one seed's log as unrecognized/raw traffic
    logged = ""
    for f in tmp_path.glob("seed_log_*"):
        logged += f.read_text()
    assert "operator note: hello" in logged


@asyncio_test
async def test_peer_connection_dump_lists_neighbors(tmp_path):
    seeds, peers = await start_cluster(tmp_path, n_seeds=1, n_peers=3)
    try:
        dumps = [p.neighbors for p in peers]
        assert any(len(d) > 0 for d in dumps)
        for d in dumps:
            for addr in d:
                assert isinstance(addr, tuple) and len(addr) == 2
    finally:
        await stop_all(seeds, peers)
