"""Adversarial wire-protocol fuzzing: network bytes are untrusted input.

The reference calls bare ``pickle.loads`` and ``ast.literal_eval`` on
socket bytes with no guard (reference Peer.py:103,194-199) — one malformed
line kills a connection thread, and a crafted pickle executes arbitrary
code. These tests pin the hardened contract: ``classify`` is total,
``decode_subset`` never resolves a global, and a live peer survives
garbage on the wire.
"""

import asyncio
import io
import pickle
import pickletools

import pytest

# property-based tests need hypothesis; environments without it (the
# container image bakes a fixed dependency set) skip cleanly instead of
# erroring at collection
pytest.importorskip("hypothesis")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from tpu_gossip.compat import wire

KINDS = {
    "empty", "ping", "seed_handshake", "heartbeat", "dead_node",
    "new_node_update", "gossip_or_text", "malformed",
}

PREFIXES = [
    wire.SEED_HANDSHAKE_PREFIX, wire.HEARTBEAT_PREFIX, wire.DEAD_NODE_PREFIX,
    wire.NEW_NODE_PREFIX, wire.PING,
]


@settings(max_examples=300, deadline=None)
@given(st.text())
def test_classify_total_on_text(s):
    kind, _ = wire.classify(s)
    assert kind in KINDS


@settings(max_examples=300, deadline=None)
@given(st.binary())
def test_classify_total_on_bytes(b):
    kind, _ = wire.classify(b)
    assert kind in KINDS


@settings(max_examples=200, deadline=None)
@given(st.sampled_from(PREFIXES), st.text())
def test_classify_total_on_prefixed_garbage(prefix, tail):
    """A recognized prefix with an arbitrary payload must classify (usually
    as 'malformed'), never raise — this is the exact line shape that killed
    the reference's reader thread (ast.literal_eval on garbage)."""
    kind, _ = wire.classify(prefix + tail)
    assert kind in KINDS


addr_strategy = st.tuples(
    st.text(
        alphabet=st.characters(blacklist_categories=("Cs",)),  # no surrogates
        max_size=40,
    ),
    st.integers(min_value=0, max_value=65535),
)


@settings(max_examples=200, deadline=None)
@given(addr_strategy)
def test_addr_codecs_roundtrip(addr):
    """repr-escaping makes any codec-able ip string wire-safe (newlines and
    quotes included) for every address-carrying message."""
    assert wire.decode_peer_handshake(wire.encode_peer_handshake(addr).decode()) == addr
    assert wire.decode_heartbeat(wire.encode_heartbeat(addr).decode()) == addr
    assert wire.decode_dead_node(wire.encode_dead_node(addr).decode()) == addr
    assert wire.decode_seed_handshake(wire.encode_seed_handshake(addr).decode()) == addr


# NewNodeUpdate inherits the reference's '|'-separated framing
# (Seed.py:203-206): ips containing '|' are not representable (hypothesis
# found this; the decoder rejects such lines as malformed, it never
# mis-parses) — so the roundtrip property holds on the '|'-free domain
nnu_addr = st.tuples(
    st.text(
        alphabet=st.characters(blacklist_categories=("Cs",), blacklist_characters="|"),
        max_size=40,
    ),
    st.integers(min_value=0, max_value=65535),
)


@settings(max_examples=100, deadline=None)
@given(nnu_addr, st.lists(nnu_addr, max_size=5))
def test_new_node_update_roundtrip(peer, subset):
    got_peer, got_subset = wire.decode_new_node_update(
        wire.encode_new_node_update(peer, subset).decode()
    )
    assert got_peer == peer and got_subset == subset


def test_new_node_update_pipe_ip_is_malformed_not_misparsed():
    line = wire.encode_new_node_update(("a|b", 1), [("x", 2)]).decode()
    kind, _ = wire.classify(line)
    assert kind == "malformed"


def test_classify_malformed_regressions():
    """Escapes found by review: non-list subsets (TypeError in the entry
    comprehension) and garbage seed handshakes (SyntaxError from
    literal_eval) must classify as malformed, not raise."""
    for line in (
        "NewNodeUpdate|('a', 1)|5",
        "NewNodeUpdate|('a', 1)|[1, 2]",
        "I am seed|((((",
        "Heartbeat from {'a': 1}",
    ):
        kind, _ = wire.classify(line)
        assert kind == "malformed", line
    with pytest.raises((ValueError, SyntaxError)):
        wire.decode_seed_handshake("I am seed|((((")  # seed.py reconnect catches both


@settings(max_examples=300, deadline=None)
@given(st.binary(max_size=200))
def test_decode_subset_never_resolves_globals(payload):
    """Arbitrary bytes either decode to an address list or raise — and
    whenever a payload reaches find_class (a GLOBAL/STACK_GLOBAL opcode),
    the load must abort: no global is ever resolved into a value. Verified
    with a spy, so a regression to permissive unpickling can't hide behind
    an unrelated downstream exception."""
    calls = []
    orig = wire._SubsetUnpickler.find_class

    def spy(self, module, name):
        calls.append((module, name))
        return orig(self, module, name)

    wire._SubsetUnpickler.find_class = spy
    try:
        raised = False
        try:
            got = wire.decode_subset(payload)
        except Exception:
            raised = True  # malformed pickles may raise many things
        else:
            assert isinstance(got, list)
            assert all(isinstance(a, tuple) and len(a) == 2 for a in got)
        if calls:
            assert raised, f"global lookup {calls} did not abort the load"
    finally:
        wire._SubsetUnpickler.find_class = orig


def test_decode_subset_blocks_code_execution():
    """A classic RCE pickle (GLOBAL os.system + REDUCE) must be rejected at
    find_class, before any call happens."""
    evil = (
        b"cos\nsystem\n"  # GLOBAL 'os system'
        b"(S'echo pwned'\n"  # MARK, STRING
        b"tR."  # TUPLE, REDUCE, STOP
    )
    pickletools.dis(io.BytesIO(evil))  # sanity: it IS a valid pickle program
    with pytest.raises(pickle.UnpicklingError, match="forbidden global"):
        wire.decode_subset(evil)


def test_decode_subset_roundtrip_with_trailing_bytes():
    subset = [("127.0.0.1", 5000), ("10.0.0.2", 121)]
    payload = wire.encode_subset(subset) + b"Heartbeat from ('1.2.3.4', 5)\n"
    assert wire.decode_subset(payload) == subset  # §2.6.9 trailing bytes


def test_live_peer_survives_garbage_bytes(tmp_path):
    """Socket-level: invalid UTF-8, a hostile heartbeat, and a deep literal
    must not kill the reader — a valid heartbeat afterwards still lands."""
    from tpu_gossip.compat.peer import PeerNode
    from tpu_gossip.compat.timing import ProtocolTiming

    import socket as socketlib

    with socketlib.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    async def run():
        timing = ProtocolTiming(heartbeat_period=60, detect_period=60,
                                heartbeat_timeout=120, gossip_period=60)
        peer = PeerNode("127.0.0.1", port, timing=timing, log_dir=str(tmp_path))
        await peer.start_detached()
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"\xff\xfe garbage \xba\xad\n")
        writer.write(b"Heartbeat from not-a-tuple)(\n")
        writer.write(b"Heartbeat from " + b"(" * 200 + b"\n")
        writer.write(b"Dead Node: {'a': object}\n")
        writer.write(wire.encode_heartbeat(("9.9.9.9", 999)))
        await writer.drain()
        for _ in range(100):
            await asyncio.sleep(0.02)
            conns = list(peer.in_conns.values())
            if any(c.identity == ("9.9.9.9", 999) for c in conns):
                break
        else:
            raise AssertionError(
                f"valid heartbeat never processed; conns="
                f"{[c.identity for c in peer.in_conns.values()]}"
            )
        assert peer.running
        writer.close()
        await peer.stop()

    asyncio.run(run())
