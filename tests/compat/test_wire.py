"""Wire codec round-trips + classification (SURVEY.md §2.4 catalog)."""

import pickle

import pytest

from tpu_gossip.compat import wire


ADDR = ("127.0.0.1", 5000)


def test_peer_handshake_roundtrip():
    raw = wire.encode_peer_handshake(ADDR)
    assert raw == b"('127.0.0.1', 5000)\n"  # exact reference format (Peer.py:95-97)
    assert wire.decode_peer_handshake(raw.decode()) == ADDR


def test_seed_handshake_roundtrip():
    raw = wire.encode_seed_handshake(ADDR)
    assert raw.startswith(b"I am seed|")
    assert wire.decode_seed_handshake(raw.decode()) == ADDR


def test_subset_roundtrip_with_trailing_bytes():
    subset = [("127.0.0.1", 5000), ("10.0.0.2", 6000)]
    raw = wire.encode_subset(subset)
    assert raw.endswith(b"\n")
    # §2.6.9: trailing bytes after the pickle are ignored
    assert wire.decode_subset(raw + b"Heartbeat from ('x', 1)\n") == subset


def test_subset_rejects_malicious_pickle():
    evil = pickle.dumps(ValueError)  # any global reference must be refused
    with pytest.raises(pickle.UnpicklingError):
        wire.decode_subset(evil)


def test_new_node_update_roundtrip():
    subset = [("a", 1), ("b", 2)]
    raw = wire.encode_new_node_update(ADDR, subset)
    peer, got = wire.decode_new_node_update(raw.decode())
    assert peer == ADDR and got == subset


def test_heartbeat_roundtrip():
    raw = wire.encode_heartbeat(ADDR)
    assert raw == b"Heartbeat from ('127.0.0.1', 5000)\n"
    assert wire.decode_heartbeat(raw.decode()) == ADDR


def test_dead_node_roundtrip():
    raw = wire.encode_dead_node(ADDR)
    assert raw == b"Dead Node: ('127.0.0.1', 5000)\n"
    assert wire.decode_dead_node(raw.decode()) == ADDR


@pytest.mark.parametrize(
    "line,kind",
    [
        ("PING", "ping"),
        ("I am seed|('a', 1)", "seed_handshake"),
        ("Heartbeat from ('a', 1)", "heartbeat"),
        ("Dead Node: ('a', 1)", "dead_node"),
        ("NewNodeUpdate|('a', 1)|[('b', 2)]", "new_node_update"),
        ("2025-01-01 00:00:00:127.0.0.1:3", "gossip_or_text"),
        ("", "empty"),
    ],
)
def test_classify(line, kind):
    assert wire.classify(line)[0] == kind
