"""tpu-sim transport: same PeerNode constructor, batched device engine
underneath (the BASELINE.json north-star flag)."""

import pytest

from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.compat.simnet import SimCluster


def make_cluster(n=64, **kw):
    cluster = SimCluster(msg_slots=16, fanout=3, seed=0, **kw)
    peers = [
        PeerNode("10.0.0.1", 9000 + i, transport="tpu-sim", cluster=cluster)
        for i in range(n)
    ]
    cluster.materialize(m=3)
    return cluster, peers


def test_requires_cluster():
    with pytest.raises(ValueError):
        PeerNode("127.0.0.1", 1, transport="tpu-sim")


def test_gossip_reaches_everyone():
    cluster, peers = make_cluster(64)
    peers[0].gossip("hello")
    assert peers[0].has_seen("hello")
    cluster.step(25)
    assert cluster.coverage("hello") >= 0.99
    assert all(p.has_seen("hello") for p in peers)


def test_multiple_messages_dedup_slots():
    cluster, peers = make_cluster(64)
    peers[0].gossip("msg-a")
    peers[10].gossip("msg-b")
    cluster.step(30)
    assert cluster.coverage("msg-a") >= 0.99
    assert cluster.coverage("msg-b") >= 0.99


def test_silent_peer_declared_dead():
    cluster, peers = make_cluster(64)
    peers[5].set_silent(True)
    cluster.step(12)  # timeout 6 rounds + sweep 2 → declared by round 8
    assert cluster.is_declared_dead(peers[5].addr)
    assert not cluster.is_declared_dead(peers[6].addr)


def test_neighbors_power_law():
    cluster, peers = make_cluster(128)
    degs = sorted(len(p.neighbors) for p in peers)
    assert degs[0] >= 3  # PA guarantees m edges per node
    assert degs[-1] > 3 * degs[len(degs) // 2]  # hubs exist


def test_register_after_materialize_rejected():
    cluster, peers = make_cluster(16)
    with pytest.raises(RuntimeError):
        cluster.register_peer(("10.9.9.9", 1))
