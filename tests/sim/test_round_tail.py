"""Fused protocol tail + buffer donation (the round-tail tentpole).

Two contracts under test:

1. **Tail bit-identity** — kernels/round_tail.py states the post-delivery
   slot passes three ways (reference multi-pass oracle, fused single
   traversal, Pallas single launch); every implementation must produce the
   IDENTICAL state trajectory on every engine (xla / staircase-pallas /
   matching) in every mode, churn and SIR included. Integer ops only, so
   equality is exact — and transitively the local↔sharded bit-identity
   contract survives any tail choice.
2. **Donation safety** — the jitted round entry points donate their state:
   the donated input must actually be deleted (the alias is real, not
   ceremonial), ``clone_state`` must keep an original alive, and
   ``init_swarm`` must OWN its leaves so donating a state can never delete
   a caller's graph/plan arrays.
"""

import dataclasses

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
from tpu_gossip.core.matching_topology import matching_powerlaw_graph
from tpu_gossip.core.state import clone_state
from tpu_gossip.kernels.pallas_segment import build_staircase_plan
from tpu_gossip.sim.engine import (
    gossip_round,
    rematerialize_rewired,
    remat_capacity,
    run_until_coverage,
    simulate,
)

N = 600
STATE_FIELDS = (
    "seen", "forwarded", "infected_round", "recovered", "alive", "silent",
    "last_hb", "declared_dead", "rewired", "rewire_targets",
)

MODE_GRID = [
    ("push", {}),
    ("push_pull", {}),
    ("flood", {}),
    ("push_pull", dict(sir_recover_rounds=2)),
    ("push_pull", dict(churn_leave_prob=0.05, churn_join_prob=0.3,
                       rewire_slots=2)),
    ("push_pull", dict(churn_leave_prob=0.05, churn_join_prob=0.3,
                       rewire_slots=2, rewire_compact_cap=64)),
    ("push_pull", dict(forward_once=True)),
]
MODE_IDS = ["push", "push_pull", "flood", "sir", "churn", "churn_compact",
            "forward_once"]
# tier-1 keeps the richest witnesses of the tail-identity law per engine
# — push_pull (both lanes), churn (fresh-mask filters live), and on the
# XLA engine forward_once (the latch) — the remaining modes assert the
# same law through cheaper heads and ride the slow lane (CI's slow job
# still sweeps the full grid)


def _grid(keep):
    return [
        p if i in keep else pytest.param(*p, marks=pytest.mark.slow)
        for p, i in zip(MODE_GRID, MODE_IDS)
    ]


XLA_ENGINE_GRID = _grid({"push_pull", "churn", "forward_once"})
PLAN_ENGINE_GRID = _grid({"push_pull", "churn"})

# rematerialize_rewired donates its state but the CSR leaves change
# shape (capacity padding), so XLA reports them as unusable donations
# at every compile — expected here, and the REAL donation behavior is
# asserted directly by the donation tests
pytestmark = pytest.mark.filterwarnings(
    "ignore:Some donated buffers were not usable"
)



@pytest.fixture(scope="module")
def pa_graph():
    return build_csr(N, preferential_attachment(N, m=3, use_native=False))


@pytest.fixture(scope="module")
def matching():
    g, plan = matching_powerlaw_graph(N, fanout=2, key=jax.random.key(0))
    return g, plan


def _assert_identical(a, b, label):
    for f in STATE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
            err_msg=f"{label}: {f}",
        )


def _run_tails(state, cfg, plan, rounds=4, tails=("fused", "reference", "pallas")):
    outs = {}
    for tail in tails:
        s = clone_state(state)
        stats_all = []
        for _ in range(rounds):
            s, stats = gossip_round(s, cfg, plan, tail=tail)
            stats_all.append(stats)
        outs[tail] = (s, stats_all)
    return outs


@pytest.mark.parametrize("mode,extra", XLA_ENGINE_GRID, ids=MODE_IDS)
def test_tail_bit_identity_xla_engine(pa_graph, mode, extra):
    # the full five-impl oracle sweep rides the XLA engine: the word-level
    # packed tails must land the identical trajectory as the bool oracle
    # in every mode (SIR, churn fresh masks, forward-once latch included)
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=2, mode=mode, **extra)
    st = init_swarm(pa_graph, cfg, origins=[0, 3], key=jax.random.key(7))
    outs = _run_tails(
        st, cfg, None,
        tails=("fused", "reference", "pallas", "packed", "packed_pallas"),
    )
    for tail in ("reference", "pallas", "packed", "packed_pallas"):
        _assert_identical(outs["fused"][0], outs[tail][0], f"xla/{tail}")
        for sa, sb in zip(outs["fused"][1], outs[tail][1]):
            assert int(sa.msgs_sent) == int(sb.msgs_sent)
            assert float(sa.coverage) == float(sb.coverage)


@pytest.mark.parametrize("mode,extra", PLAN_ENGINE_GRID, ids=MODE_IDS)
def test_tail_bit_identity_staircase_engine(pa_graph, mode, extra):
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=2, mode=mode, **extra)
    plan = build_staircase_plan(
        pa_graph.row_ptr, pa_graph.col_idx,
        fanout=None if mode == "flood" else cfg.fanout,
    )
    st = init_swarm(pa_graph, cfg, origins=[0, 3], key=jax.random.key(8))
    outs = _run_tails(st, cfg, plan)
    for tail in ("reference", "pallas"):
        _assert_identical(outs["fused"][0], outs[tail][0], f"pallas/{tail}")


@pytest.mark.parametrize("mode,extra", PLAN_ENGINE_GRID, ids=MODE_IDS)
def test_tail_bit_identity_matching_engine(matching, mode, extra):
    g, plan = matching
    cfg = SwarmConfig(
        n_peers=g.n_pad, msg_slots=8, fanout=2, mode=mode, **extra
    )
    st = init_swarm(
        g.as_padded_graph(), cfg, origins=[0, 3], exists=g.exists,
        key=jax.random.key(9),
    )
    outs = _run_tails(st, cfg, plan)
    for tail in ("reference", "pallas"):
        _assert_identical(outs["fused"][0], outs[tail][0], f"matching/{tail}")


@pytest.mark.slow  # loop-composed variant; the per-engine MODE_GRID
# bit-identity tests above keep the tail oracle in tier-1
def test_tail_variants_identical_through_jitted_loops(pa_graph):
    """The tail choice rides simulate/run_until_coverage as a static arg:
    every implementation must yield the same trajectory AND the same
    stopping round through the scan/while_loop carries."""
    cfg = SwarmConfig(
        n_peers=N, msg_slots=8, fanout=2, mode="push_pull",
        sir_recover_rounds=3, churn_leave_prob=0.02, churn_join_prob=0.1,
        rewire_slots=2,
    )
    st = init_swarm(pa_graph, cfg, origins=[0], key=jax.random.key(4))
    fins = {
        tail: simulate(clone_state(st), cfg, 8, None, tail)[0]
        for tail in ("fused", "reference", "pallas")
    }
    _assert_identical(fins["fused"], fins["reference"], "simulate")
    _assert_identical(fins["fused"], fins["pallas"], "simulate")
    rounds = {
        tail: int(run_until_coverage(
            clone_state(st), cfg, 0.9, 60, tail=tail
        ).round)
        for tail in ("fused", "reference")
    }
    assert rounds["fused"] == rounds["reference"]


# ------------------------------------------------------------- donation ---


def test_simulate_donates_and_clone_survives(pa_graph):
    cfg = SwarmConfig(n_peers=N, msg_slots=8)
    st = init_swarm(pa_graph, cfg, origins=[0])
    fin_a, _ = simulate(clone_state(st), cfg, 5)
    # the original is untouched by a cloned run...
    assert float(st.coverage(0)) > 0
    fin_b, _ = simulate(st, cfg, 5)
    # ...and identical trajectories either way (clone is a true deep copy)
    np.testing.assert_array_equal(np.asarray(fin_a.seen), np.asarray(fin_b.seen))
    # the donated input is genuinely deleted — the alias is real
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(st.seen)


def test_init_swarm_owns_leaves_against_donation(matching):
    """Donating a state must never delete a caller's arrays: the matching
    graph's CSR/exists live on device and previously aliased straight into
    the state. After a donated run, the graph (and a second state built
    from it) must still be fully usable."""
    g, plan = matching
    cfg = SwarmConfig(n_peers=g.n_pad, msg_slots=8, fanout=2, mode="push_pull")
    st1 = init_swarm(
        g.as_padded_graph(), cfg, origins=[0], exists=g.exists,
        key=jax.random.key(0),
    )
    st2 = init_swarm(
        g.as_padded_graph(), cfg, origins=[0], exists=g.exists,
        key=jax.random.key(0),
    )
    fin, _ = simulate(st1, cfg, 3, plan)  # donates st1
    # graph arrays survive
    assert int(np.asarray(g.col_idx).shape[0]) >= 1
    assert bool(np.asarray(g.exists).any())
    # the sibling state built from the same graph survives too
    fin2, _ = simulate(st2, cfg, 3, plan)
    np.testing.assert_array_equal(np.asarray(fin.seen), np.asarray(fin2.seen))


def test_same_key_reused_across_states(pa_graph):
    """init_swarm copies the caller's PRNG key: donating one state must not
    delete the key another state (or the caller) still holds."""
    key = jax.random.key(42)
    cfg = SwarmConfig(n_peers=N, msg_slots=4)
    st1 = init_swarm(pa_graph, cfg, origins=[0], key=key)
    simulate(st1, cfg, 2)
    st2 = init_swarm(pa_graph, cfg, origins=[0], key=key)  # key still alive
    fin, _ = simulate(st2, cfg, 2)
    assert int(fin.round) == 2


def test_bench_swarm_donation_safe(pa_graph):
    """bench_swarm reps clone internally: the caller's state survives the
    benchmark, and the legacy zero-arg runner is rejected loudly."""
    from tpu_gossip.sim import metrics as M

    cfg = SwarmConfig(n_peers=N, msg_slots=4, fanout=3, mode="push")
    st = init_swarm(pa_graph, cfg, origins=[0])
    res, fin = M.bench_swarm(st, cfg, 0.9, 100, reps=2)
    assert res.rounds > 0
    assert float(st.coverage(0)) > 0  # caller's state intact
    with pytest.raises(TypeError, match="run\\(state\\)"):
        M.bench_swarm(st, cfg, 0.9, 100, run=lambda: None)
    with pytest.raises(ValueError, match="plan"):
        M.bench_swarm(st, cfg, 0.9, 100, run=lambda s: s, plan=object())


def test_rematerialize_rewired_donates(pa_graph):
    cfg = SwarmConfig(
        n_peers=N, msg_slots=4, fanout=2, mode="push_pull",
        churn_leave_prob=0.05, churn_join_prob=0.3, rewire_slots=2,
    )
    st = init_swarm(pa_graph, cfg, origins=[0], key=jax.random.key(2))
    cap = remat_capacity(st, cfg)
    st, _ = simulate(st, cfg, 10)
    keep = clone_state(st)
    new, overflow = rematerialize_rewired(st, cfg, cap)
    assert int(overflow) == 0
    assert not bool(np.asarray(new.rewired).any())
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(st.seen)  # donated
    # the kept clone still runs (and matches the folded state's protocol
    # fields — the fold touches only topology/rewire leaves)
    np.testing.assert_array_equal(np.asarray(keep.seen), np.asarray(new.seen))


def test_clone_preserves_sharding():
    """clone_state on a mesh-sharded swarm keeps the peer sharding — the
    dist benchmarks clone per rep and a silently-replicated clone would
    invalidate every multi-chip measurement."""
    from tpu_gossip.dist import (
        init_sharded_swarm, make_mesh, partition_graph, shard_swarm,
        simulate_dist,
    )

    g = build_csr(200, preferential_attachment(200, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=0)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, fanout=2, mode="push")
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh
    )
    cl = clone_state(st)
    assert "peers" in str(cl.seen.sharding.spec)
    fin, _ = simulate_dist(cl, cfg, sg, mesh, 3)  # donates the clone
    fin2, _ = simulate_dist(st, cfg, sg, mesh, 3)  # original still usable
    np.testing.assert_array_equal(np.asarray(fin.seen), np.asarray(fin2.seen))


def test_fresh_mask_resets_exactly_like_pre_fusion(pa_graph):
    """The churn fresh-slot reset is folded into the fused tail; a rejoined
    slot must come back with EMPTY protocol state (the pre-fusion second
    sweep's semantics), not carry the departed occupant's bits."""
    cfg = SwarmConfig(
        n_peers=N, msg_slots=4, fanout=2, mode="push_pull",
        churn_leave_prob=0.2, churn_join_prob=0.5,
    )
    st = init_swarm(pa_graph, cfg, origins=[0], key=jax.random.key(11))
    st = dataclasses.replace(st, forwarded=st.seen)  # give slot 0 history
    prev = clone_state(st)
    for _ in range(6):
        nxt, _ = gossip_round(prev, cfg)
        freshly_joined = (
            np.asarray(nxt.alive) & ~np.asarray(prev.alive)
        )
        if freshly_joined.any():
            rows = np.nonzero(freshly_joined)[0]
            assert not np.asarray(nxt.seen)[rows].any()
            assert not np.asarray(nxt.forwarded)[rows].any()
            assert (np.asarray(nxt.infected_round)[rows] == -1).all()
            assert not np.asarray(nxt.recovered)[rows].any()
        prev = nxt
