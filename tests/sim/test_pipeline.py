"""Pipelined rounds (sim/stages.py, docs/pipelined_rounds.md): the
depth-0-equals-serial contract on both mesh engines across modes × a
chaos scenario × growth × stream × control, the depth-1 double-buffer
semantics (flood closed form, pipelined local ↔ mesh bit-identity, scan
continuation), and mid-pipeline checkpointing (non-empty in-flight
buffer round-trips; pre-pipeline checkpoints load with it empty in both
formats)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.control import compile_control
from tpu_gossip.core import topology
from tpu_gossip.core.state import (
    SwarmConfig, clone_state, init_swarm, load_swarm, save_swarm,
)
from tpu_gossip.dist import make_mesh, shard_swarm, simulate_dist
from tpu_gossip.faults import compile_scenario, scenario_from_dict
from tpu_gossip.growth import compile_growth, matching_admit_rows
from tpu_gossip.sim.engine import simulate
from tpu_gossip.sim.stages import PipelineSpec, compile_pipeline
from tpu_gossip.traffic import compile_stream

ATTACH = 2
_CHURN = dict(churn_leave_prob=0.02, churn_join_prob=0.2, rewire_slots=3)

INT_STATS = (
    "msgs_sent", "n_infected", "n_alive", "n_declared_dead",
    "msgs_dropped", "msgs_held", "msgs_delivered", "n_members",
    "stream_offered", "stream_injected", "stream_conflated",
    "stream_expired", "slot_infected", "slot_age", "control_level",
    "control_fanout", "msgs_duplicate", "control_refreshed",
)


def _assert_states_equal(a_st, b_st):
    for f in dataclasses.fields(type(a_st)):
        a, b = getattr(a_st, f.name), getattr(b_st, f.name)
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f.name
        )


def _assert_stats_equal(a, b):
    for f in INT_STATS + ("coverage",):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


def _chaos(n_slots, n_real, node_map=None):
    return compile_scenario(
        scenario_from_dict({
            "name": "pipe-chaos",
            "phases": [
                {"name": "lossy", "start": 0, "end": 3, "loss": 0.2,
                 "delay": 0.2},
                {"name": "split", "start": 3, "end": 5, "partition": "half"},
                {"name": "storm", "start": 5, "end": 7,
                 "churn_leave": 0.05, "churn_join": 0.2,
                 "blackout": {"frac": 0.1, "seed": 1}},
            ],
        }),
        n_peers=n_real, n_slots=n_slots, total_rounds=10,
        node_map=node_map,
    )


# ------------------------------------------------------------- spec


def test_compile_pipeline_validates():
    assert compile_pipeline(0).depth == 0
    assert compile_pipeline().depth == 1
    with pytest.raises(ValueError):
        compile_pipeline(2)
    with pytest.raises(ValueError):
        PipelineSpec(depth=-1)


# --------------------------------------------- depth 0 == serial (matrix)


@pytest.fixture(scope="module")
def matching_setup():
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import shard_matching_plan

    g, plan = matching_powerlaw_graph_sharded(
        800, 8, fanout=2, key=jax.random.key(0), growth_rows=32,
    )
    mesh = make_mesh(8)
    return g, plan, shard_matching_plan(plan, mesh), mesh


@pytest.fixture(scope="module")
def bucketed_setup():
    from tpu_gossip.dist import partition_graph
    from tpu_gossip.growth import pad_graph_for_growth

    rng = np.random.default_rng(0)
    g = topology.build_csr(
        600, topology.preferential_attachment(600, m=3, rng=rng)
    )
    pg, gexists = pad_graph_for_growth(g, 640)  # headroom for the grow cell
    sg, relabeled, position = partition_graph(pg, 8, seed=0)
    return sg, relabeled, position, gexists, make_mesh(8)


def _matching_state(g, cfg, seed=3):
    return init_swarm(
        g.as_padded_graph(), cfg, origins=[0, 5], exists=g.exists,
        key=jax.random.key(seed),
    )


def _matching_planes(plan, composed: bool):
    """(scenario, growth, stream, control) for the composed matrix cell."""
    if not composed:
        return None, None, None, None
    scen = _chaos(plan.n, 800)
    gp = compile_growth(
        n_initial=800, target=896, n_slots=plan.n, joins_per_round=12,
        attach_m=ATTACH, admit_rows=matching_admit_rows(plan, 96),
        max_join_burst=4,
    )
    sp = compile_stream(
        rate=2.0, msg_slots=8, ttl=6,
        origin_rows=np.flatnonzero(np.asarray(
            jnp.ones((plan.n,), bool)))[:800],
        k_hashes=2, burst_every=3,
    )
    cp = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=3,
                         refresh_every=3)
    return scen, gp, sp, cp


@pytest.mark.parametrize(
    "mode,extra,composed",
    [
        pytest.param("flood", {}, False, marks=pytest.mark.slow),
        pytest.param("push", {}, False, marks=pytest.mark.slow),
        ("push_pull", {}, False),
        pytest.param("push_pull", dict(rewire_slots=ATTACH, **{
            k: v for k, v in _CHURN.items() if k != "rewire_slots"
        }), True, marks=pytest.mark.slow),
    ],
    ids=["flood", "push", "push_pull", "composed"],
)  # push_pull (both lanes) is the tier-1 depth-0 witness; flood/push
# assert the same law and ride the slow lane with the composed long pole
def test_matching_depth0_bit_identical_to_serial(
    matching_setup, mode, extra, composed
):
    """PipelineSpec(depth=0) reproduces the serial sharded matching run
    BIT FOR BIT — full final state + the whole integer-stat trajectory —
    across modes and the fully composed scenario × growth × stream ×
    control cell (the ``control=None`` contract pattern: the off-setting
    is the identity)."""
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode=mode,
                      **extra)
    scen, gp, sp, cp = _matching_planes(plan, composed)
    st = _matching_state(g, cfg)
    fin_s, stats_s = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, plan_m, mesh, 7, None,
        scen, gp, stream=sp, control=cp,
    )
    fin_0, stats_0 = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 7, None,
        scen, gp, stream=sp, control=cp, pipeline=compile_pipeline(0),
    )
    _assert_states_equal(fin_s, fin_0)
    _assert_stats_equal(stats_s, stats_0)


@pytest.mark.parametrize(
    "mode,composed",
    [("push", False), ("push_pull", False),
     pytest.param("push_pull", True, marks=pytest.mark.slow)],
    ids=["push", "push_pull", "composed"],
)  # as above: composed cell slow, plain modes carry tier-1
def test_bucketed_depth0_bit_identical_to_serial(
    bucketed_setup, mode, composed
):
    """The same depth-0 identity on the bucketed CSR engine, including
    the composed scenario × growth × stream × control cell (growth rides
    the padded exists plane; the scenario carries every fault class)."""
    from tpu_gossip.dist import init_sharded_swarm

    sg, relabeled, position, gexists, mesh = bucketed_setup
    extra = dict(rewire_slots=ATTACH, churn_leave_prob=0.01,
                 churn_join_prob=0.05) if composed else {}
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2, mode=mode,
                      **extra)
    node_map = lambda ids: position[np.asarray(ids)]  # noqa: E731
    scen = gp = sp = cp = None
    if composed:
        scen = _chaos(sg.n_pad, 600, node_map=node_map)
        gp = compile_growth(
            n_initial=600, target=640, n_slots=sg.n_pad,
            joins_per_round=8, attach_m=ATTACH, node_map=node_map,
            max_join_burst=4,
        )
        sp = compile_stream(
            rate=1.5, msg_slots=8, ttl=6,
            origin_rows=position[np.arange(600)], k_hashes=1,
        )
        cp = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=2,
                             refresh_every=3)
    st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0],
                            exists=gexists)
    fin_s, stats_s = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, sg, mesh, 7, None,
        scen, gp, stream=sp, control=cp,
    )
    fin_0, stats_0 = simulate_dist(
        shard_swarm(st, mesh), cfg, sg, mesh, 7, None,
        scen, gp, stream=sp, control=cp, pipeline=compile_pipeline(0),
    )
    _assert_states_equal(fin_s, fin_0)
    _assert_stats_equal(stats_s, stats_0)


# --------------------------------------------------- depth 1 semantics


def test_flood_depth1_closed_form():
    """The double-buffer recurrence seen_t = seen_{t-1} | F(seen_{t-2})
    has a closed form under flood (F monotone, no draws): 2k pipelined
    rounds land exactly on k serial rounds' seen set — the two-round
    effective hop the overlap buys its concurrency with."""
    rng = np.random.default_rng(0)
    g = topology.build_csr(300, topology.preferential_attachment(300, m=2, rng=rng))
    cfg = SwarmConfig(n_peers=300, msg_slots=4, fanout=2, mode="flood")
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(1))
    for k in (1, 2, 3):
        fin_p, _ = simulate(clone_state(st), cfg, 2 * k,
                            pipeline=compile_pipeline(1))
        fin_s, _ = simulate(clone_state(st), cfg, k)
        np.testing.assert_array_equal(
            np.asarray(fin_p.seen), np.asarray(fin_s.seen), err_msg=str(k)
        )


def test_depth1_local_vs_matching_mesh_bit_identical(matching_setup):
    """PIPELINED runs keep the matching family's local ↔ sharded
    bit-identity contract: the issued exchange is the engines' (already
    bit-identical) dissemination product, and the buffer swap is
    engine-agnostic — full state + integer stats, depth 1."""
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull")
    st = _matching_state(g, cfg)
    pipe = compile_pipeline(1)
    fin_l, stats_l = simulate(clone_state(st), cfg, 6, plan, pipeline=pipe)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 6, pipeline=pipe,
    )
    _assert_states_equal(fin_l, fin_d)
    _assert_stats_equal(stats_l, stats_d)
    assert np.asarray(fin_l.pipe_buf).any()  # the buffer is genuinely live


def test_depth1_continuation_is_exact():
    """Splitting a pipelined run across two simulate calls lands on the
    same trajectory: the in-flight buffer is a true state carry, so a
    3+2 split equals a straight 5 bit for bit."""
    rng = np.random.default_rng(2)
    g = topology.build_csr(240, topology.preferential_attachment(240, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=240, msg_slots=4, fanout=2, mode="push_pull")
    st = init_swarm(g, cfg, origins=[1], key=jax.random.key(4))
    pipe = compile_pipeline(1)
    fin_a, _ = simulate(clone_state(st), cfg, 5, pipeline=pipe)
    mid, _ = simulate(clone_state(st), cfg, 3, pipeline=pipe)
    assert np.asarray(mid.pipe_buf).any()
    fin_b, _ = simulate(mid, cfg, 2, pipeline=pipe)
    _assert_states_equal(fin_a, fin_b)


def test_depth1_reaches_coverage():
    """The epidemic tolerates the one-round staleness: a pipelined
    push_pull run still converges (more rounds, same fixed point)."""
    from tpu_gossip.sim.engine import run_until_coverage

    rng = np.random.default_rng(3)
    g = topology.build_csr(400, topology.preferential_attachment(400, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=400, msg_slots=4, fanout=2, mode="push_pull")
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(5))
    fin = run_until_coverage(st, cfg, 0.99, 200,
                             pipeline=compile_pipeline(1))
    assert float(fin.coverage(0)) >= 0.99


# ------------------------------------------------------- checkpointing


def test_mid_pipeline_checkpoint_roundtrips_bit_exact(tmp_path):
    """Save/resume with a NON-EMPTY in-flight buffer: the loaded state is
    leaf-for-leaf identical, and resuming both (the saved original and
    the loaded copy) stays bit-identical — the buffered exchange
    delivers on the first resumed round."""
    rng = np.random.default_rng(7)
    g = topology.build_csr(200, topology.preferential_attachment(200, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=200, msg_slots=4, fanout=2, mode="push_pull",
                      **_CHURN)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(9))
    pipe = compile_pipeline(1)
    mid, _ = simulate(st, cfg, 3, pipeline=pipe)
    assert np.asarray(mid.pipe_buf).any(), "fixture buffer unexpectedly empty"
    save_swarm(tmp_path / "pipe.npz", mid)
    loaded = load_swarm(tmp_path / "pipe.npz")
    _assert_states_equal(mid, loaded)
    fin_a, _ = simulate(clone_state(mid), cfg, 3, pipeline=pipe)
    fin_b, _ = simulate(loaded, cfg, 3, pipeline=pipe)
    _assert_states_equal(fin_a, fin_b)


def test_pre_pipeline_named_checkpoint_loads_empty_buffer(tmp_path):
    """A named-format checkpoint written before the field existed (the
    key stripped) loads with an empty buffer — a pipelined run's cold
    start, and a serial resume carries it untouched."""
    g = topology.build_csr(64, topology.preferential_attachment(
        64, m=2, rng=np.random.default_rng(0)))
    cfg = SwarmConfig(n_peers=64, msg_slots=4)
    st = init_swarm(g, cfg, origins=[1])
    save_swarm(tmp_path / "new.npz", st)
    data = dict(np.load(tmp_path / "new.npz"))
    assert "field_pipe_buf" in data
    del data["field_pipe_buf"]
    np.savez(tmp_path / "old.npz", **data)
    st2 = load_swarm(tmp_path / "old.npz")
    assert st2.pipe_buf.shape == st.seen.shape
    assert not bool(st2.pipe_buf.any())


def test_v1_checkpoint_loads_empty_buffer(tmp_path):
    """The legacy positional format predates the field too: it loads
    with an empty buffer at the (N, M) slot shape."""
    from tests.unit.test_state import save_v1

    g = topology.build_csr(32, topology.preferential_attachment(
        32, m=2, rng=np.random.default_rng(1)))
    st = init_swarm(g, SwarmConfig(n_peers=32, msg_slots=4), origins=[2])
    save_v1(st, tmp_path / "v1.npz", per_peer_sir=True)
    st2 = load_swarm(tmp_path / "v1.npz")
    assert st2.pipe_buf.shape == st.seen.shape
    assert not bool(st2.pipe_buf.any())


def test_serial_rounds_carry_buffer_untouched():
    """The no-pipeline hot path never touches the buffer: a serial run
    from a mid-pipeline state carries the in-flight plane verbatim
    (resume-without-spec freezes it, like fault_held without its
    scenario)."""
    rng = np.random.default_rng(11)
    g = topology.build_csr(150, topology.preferential_attachment(150, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=150, msg_slots=4, fanout=2, mode="push")
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(2))
    mid, _ = simulate(st, cfg, 2, pipeline=compile_pipeline(1))
    buf = np.asarray(mid.pipe_buf).copy()
    assert buf.any()
    fin, _ = simulate(mid, cfg, 3)  # serial continuation
    np.testing.assert_array_equal(np.asarray(fin.pipe_buf), buf)


# ------------------------------------------------------------------ CLI


def _run(argv):
    from tpu_gossip.cli.run_sim import main

    return main(argv)


def test_cli_pipeline_requires_shard(capsys):
    rc = _run(["--peers", "96", "--rounds", "5", "--quiet",
               "--pipeline", "1"])
    assert rc == 2
    assert "--shard" in capsys.readouterr().err


def test_cli_pipelined_shard_run_summary(capsys):
    """A pipelined sharded run completes and reports its depth; depth 0
    emits a summary identical to the serial run's (the CLI face of the
    depth-0 contract — the engine-level bit-identity matrix is above)."""
    import json

    base = ["--peers", "200", "--rounds", "6", "--slots", "4",
            "--fanout", "2", "--quiet", "--shard"]
    assert _run(base + ["--pipeline", "1"]) == 0
    row1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row1["pipeline"] == 1
    assert _run(base) == 0
    serial = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert _run(base + ["--pipeline", "0"]) == 0
    depth0 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "pipeline" not in serial and depth0.pop("pipeline") == 0
    assert depth0 == serial


def test_depth1_expired_columns_die_in_the_buffer():
    """Pipelined + streaming: a column recycled at round t must not keep
    its retired message's bits in the in-flight buffer — the issue read
    the pre-expiry seen plane, and without the ageout mask those bits
    would deliver into the column's NEW lease at t+1 (cross-message
    contamination)."""
    rng = np.random.default_rng(13)
    g = topology.build_csr(200, topology.preferential_attachment(200, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=200, msg_slots=4, fanout=2, mode="push_pull")
    st = init_swarm(g, cfg, origins=[0, 1, 2], key=jax.random.key(6))
    sp = compile_stream(rate=1.0, msg_slots=4, ttl=6,
                        origin_rows=np.arange(200))
    pipe = compile_pipeline(1)
    state = clone_state(st)
    from tpu_gossip.sim.engine import gossip_round
    from tpu_gossip.traffic.engine import slot_expiry

    saw_expiry = False
    for _ in range(14):
        rnd_next = int(state.round) + 1
        expired = np.asarray(
            slot_expiry(state.slot_lease, rnd_next, sp.ttl)
        )
        state, _ = gossip_round(state, cfg, stream=sp, pipeline=pipe)
        if expired.any():
            saw_expiry = True
            buf = np.asarray(state.pipe_buf)
            assert not buf[:, expired].any(), (
                "retired message's bits survived in the in-flight buffer"
            )
    assert saw_expiry, "fixture never recycled a slot — raise ttl pressure"
