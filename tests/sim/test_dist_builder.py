"""dist/builder.py — the born-distributed matching builder's contracts.

The conformance contract (the checkpoint resharding contract run
forward): ``matching_powerlaw_graph_dist`` built inside ``shard_map``
must be BIT-IDENTICAL on every plan leaf and graph array to the local
``matching_powerlaw_graph_sharded(..., block_keys=True)`` layout truth —
tables, erasure survivors, degree tables, the CSR, the exists mask. Plus:
rounds on the born-distributed layout run bit-identical local vs mesh
(the existing engine contract, on the new layout), growth composes, and
the narrow degree tables hold their declared dtype.

Builds are shared module-wide (each (rows, classes) shape is a fresh
jit compile); the CI builder-smoke job runs this file INCLUDING the
slow-marked growing run.
"""

import dataclasses

import jax
import numpy as np
import pytest

from tpu_gossip.core.matching_topology import (
    DEG_TABLE_CAP,
    matching_powerlaw_graph_sharded,
    plan_table_widths,
)
from tpu_gossip.core.state import SwarmConfig, clone_state, init_swarm


@pytest.fixture(scope="module")
def mesh():
    from tpu_gossip.dist import make_mesh

    mesh = make_mesh()
    if 128 % mesh.size:
        pytest.skip(f"mesh size {mesh.size} does not divide 128")
    return mesh


@pytest.fixture(scope="module")
def builds(mesh):
    """One (local block-keyed, dist-native) build pair at n=256, shared
    by the conformance and round-contract tests."""
    from tpu_gossip.dist import matching_powerlaw_graph_dist

    g1, p1 = matching_powerlaw_graph_sharded(
        256, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(3),
        block_keys=True,
    )
    g2, p2 = matching_powerlaw_graph_dist(
        256, mesh, gamma=2.5, fanout=1, key=jax.random.key(3),
    )
    return g1, p1, g2, p2


@pytest.mark.slow  # CI's builder-smoke job runs this file INCLUDING the
# slow rows on every push (see module docstring) — the n=256 build pair
# is the long pole, so the conformance checks ride there, out of tier-1
def test_dist_build_bit_identical_to_block_keys_local(builds):
    g1, p1, g2, p2 = builds
    assert p1.classes == p2.classes
    assert p1.local_classes == p2.local_classes
    assert (p1.n, p1.rows, p1.n_per, p1.n_blk, p1.per_rows,
            p1.mesh_shards) == (p2.n, p2.rows, p2.n_per, p2.n_blk,
                                p2.per_rows, p2.mesh_shards)
    for name in ("m3", "valid", "deg_other", "deg_real"):
        a, b = getattr(p1, name), getattr(p2, name)
        assert a.dtype == b.dtype, name
        assert (np.asarray(a) == np.asarray(b)).all(), name
    for group in ("lanes", "lanes_inv"):
        for i, (a, b) in enumerate(zip(getattr(p1, group),
                                       getattr(p2, group))):
            assert a.dtype == b.dtype
            assert (np.asarray(a) == np.asarray(b)).all(), (group, i)
    assert (np.asarray(g1.row_ptr) == np.asarray(g2.row_ptr)).all()
    assert (np.asarray(g1.col_idx) == np.asarray(g2.col_idx)).all()
    assert (np.asarray(g1.exists) == np.asarray(g2.exists)).all()
    # the born-distributed arrays land placed on the mesh's peer axis
    assert "peers" in str(p2.valid.sharding)


@pytest.mark.slow
def test_rounds_on_born_distributed_layout_local_vs_mesh(mesh, builds):
    """The engine bit-identity contract holds on the new layout: the
    born-distributed plan runs the mesh round bit-identical to the local
    round on the block-keyed twin. (Slow-marked: two engine compiles on
    top of the shared builds; the CI builder-smoke job runs it on every
    push — the tier-1 pin is the leaf-equality conformance above, which
    the engine contract then inherits: both engines already run
    bit-identically on ANY shared plan.)"""
    from tpu_gossip.dist import (
        shard_matching_plan,
        shard_swarm,
        simulate_dist,
    )
    from tpu_gossip.sim.engine import simulate

    gl, pl, gd, pd = builds
    cfg = SwarmConfig(n_peers=pd.n, msg_slots=16, fanout=1,
                      mode="push_pull")
    st = init_swarm(gd.as_padded_graph(), cfg, origins=[0],
                    exists=gd.exists, key=jax.random.key(0))
    fin_d, stats_d = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg,
        shard_matching_plan(pd, mesh), mesh, 6,
    )
    stl = init_swarm(gl.as_padded_graph(), cfg, origins=[0],
                     exists=gl.exists, key=jax.random.key(0))
    fin_l, stats_l = simulate(stl, cfg, 6, pl)
    for f in dataclasses.fields(type(fin_l)):
        a, b = getattr(fin_l, f.name), getattr(fin_d, f.name)
        if f.name == "rng":
            assert (jax.random.key_data(a) == jax.random.key_data(b)).all()
        else:
            assert (np.asarray(a) == np.asarray(b)).all(), f.name
    for name, a, b in zip(stats_l._fields, stats_l, stats_d):
        assert (np.asarray(a) == np.asarray(b)).all(), name


@pytest.mark.slow
def test_growing_dist_build_conformance_and_run(mesh):
    """Growth capacity rows: the dist build stays bit-identical to the
    block-keyed local truth WITH reserved rows, and the shared growth
    engine admits into them on the mesh. (Slow-marked: two more builds
    + a growing mesh compile; the CI builder-smoke job runs it.)"""
    from tpu_gossip.dist import (
        matching_powerlaw_graph_dist,
        shard_matching_plan,
        shard_swarm,
        simulate_dist,
    )
    from tpu_gossip.growth import compile_growth, matching_admit_rows

    grow_rows = 8
    g1, p1 = matching_powerlaw_graph_sharded(
        256, mesh.size, gamma=2.5, fanout=2, key=jax.random.key(5),
        block_keys=True, growth_rows=grow_rows,
    )
    gd, pd = matching_powerlaw_graph_dist(
        256, mesh, gamma=2.5, fanout=2, key=jax.random.key(5),
        growth_rows=grow_rows,
    )
    for name in ("valid", "deg_other", "deg_real"):
        assert (np.asarray(getattr(p1, name))
                == np.asarray(getattr(pd, name))).all(), name
    assert (np.asarray(g1.row_ptr) == np.asarray(gd.row_ptr)).all()
    assert (np.asarray(g1.col_idx) == np.asarray(gd.col_idx)).all()

    cfg = SwarmConfig(n_peers=pd.n, msg_slots=16, fanout=2,
                      mode="push_pull", rewire_slots=2)
    st = init_swarm(gd.as_padded_graph(), cfg, origins=[0],
                    exists=gd.exists, key=jax.random.key(0))
    n0 = int(np.asarray(st.exists).sum())
    target = n0 + mesh.size * grow_rows
    gp = compile_growth(
        n_initial=n0, target=target, n_slots=pd.n, joins_per_round=8,
        attach_m=2,
        admit_rows=matching_admit_rows(pd, target - n0),
    )
    fin, stats = simulate_dist(
        shard_swarm(st, mesh), cfg, shard_matching_plan(pd, mesh), mesh,
        12, growth=gp,
    )
    assert int(np.asarray(fin.exists).sum()) == target
    assert int(np.asarray(stats.n_members)[-1]) == target


@pytest.mark.slow
def test_dist_build_csr_free_row_ptr_identical(mesh):
    from tpu_gossip.dist import matching_powerlaw_graph_dist

    g1, _p1 = matching_powerlaw_graph_sharded(
        256, mesh.size, fanout=1, key=jax.random.key(1), block_keys=True,
        export_csr=False,
    )
    g2, _p2 = matching_powerlaw_graph_dist(
        256, mesh, fanout=1, key=jax.random.key(1), export_csr=False,
    )
    assert (np.asarray(g1.row_ptr) == np.asarray(g2.row_ptr)).all()
    assert g2.col_idx.shape == (1,)  # the CSR-free sentinel shape


@pytest.mark.slow  # rides with the build pair in CI's builder-smoke job;
# the host-side plan_table_widths declarations stay covered there too
def test_degree_tables_declared_narrow(builds):
    """The registry-declared int16 degree tables land when d_max fits the
    cap (every tracked scale) and stay int32 when it cannot."""
    _g1, p, _g2, _p2 = builds
    assert str(p.deg_other.dtype) == "int16"
    assert str(p.deg_real.dtype) == "int16"
    assert int(np.asarray(p.deg_other).max()) <= DEG_TABLE_CAP
    w = plan_table_widths(1_000_000, n_shards=8)
    assert w["deg_other"]["dtype"] == "int16"
    assert w["lanes"]["dtype"] == "int8"
    # past the cap the declaration widens (d_max > 32767)
    w100 = plan_table_widths(100_000_000, n_shards=8)
    assert w100["deg_other"]["dtype"] == "int32"
