"""Chaos scenario engine (tpu_gossip/faults/): parser, validator, and the
fault semantics on the local engine — loss, delay, partition, blackout,
churn bursts — plus the bit-compatibility guarantees the subsystem is
built on (quiescent scenarios change nothing; checkpoints carry the
scenario cursor). The local↔sharded half of the contract lives in
tests/sim/test_dist.py."""

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
from tpu_gossip.core.state import clone_state, load_swarm, save_swarm
from tpu_gossip.faults import (
    ScenarioError,
    compile_scenario,
    parse_scenario,
    scenario_from_dict,
)
from tpu_gossip.sim import metrics as M
from tpu_gossip.sim.engine import simulate

N = 200


@pytest.fixture(scope="module")
def setup():
    g = build_csr(N, preferential_attachment(N, m=3, use_native=False))
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(0))
    return g, cfg, st


def _compile(d, total_rounds=40, n=N, **kw):
    return compile_scenario(
        scenario_from_dict(d), n_peers=n, n_slots=n,
        total_rounds=total_rounds, **kw,
    )


# ------------------------------------------------------------- the parser
def test_toml_round_trip():
    text = """
    # a comment
    [scenario]
    name = "demo"

    [[phase]]
    name  = "lossy"
    start = 0
    end   = 10
    loss  = 0.3           # inline comment
    delay = 0.1

    [[phase]]
    name      = "split"
    start     = 10
    end       = 20
    partition = {frac = 0.5, seed = 3}
    blackout  = {span = [0.25, 0.5]}
    churn_leave = 0.05
    churn_nodes = {ids = [1, 2, 3]}
    """
    spec = parse_scenario(text)
    assert spec.name == "demo"
    assert len(spec.phases) == 2
    lossy, split = spec.phases
    assert (lossy.start, lossy.end, lossy.loss, lossy.delay) == (0, 10, 0.3, 0.1)
    assert split.partition.kind == "frac" and split.partition.seed == 3
    assert split.blackout.span == (0.25, 0.5)
    assert split.churn_nodes.ids == (1, 2, 3)
    spec.validate(total_rounds=20, n_peers=100)


def test_parse_from_file(tmp_path):
    p = tmp_path / "s.toml"
    p.write_text('[scenario]\nname = "f"\n[[phase]]\nstart = 0\nend = 5\n')
    assert parse_scenario(p).name == "f"


def test_parser_rejects_garbage():
    with pytest.raises(ScenarioError, match="unknown table"):
        parse_scenario("[nonsense]\nx = 1\n")
    with pytest.raises(ScenarioError, match="key = value"):
        parse_scenario("[scenario]\njust words\n")
    with pytest.raises(ScenarioError, match="cannot parse"):
        parse_scenario("[scenario]\nname = @@@\n")
    with pytest.raises(ScenarioError, match="unknown keys"):
        scenario_from_dict({"phases": [{"start": 0, "end": 1, "lss": 0.1}]})


@pytest.mark.parametrize(
    "phases,match",
    [
        ([], "no phases"),
        ([{"start": 5, "end": 5}], "empty"),
        ([{"start": 0, "end": 50}], "beyond the run's horizon"),
        ([{"start": 0, "end": 9}, {"start": 5, "end": 12}], "overlap"),
        ([{"start": 0, "end": 5, "loss": 1.5}], "outside"),
        ([{"start": 0, "end": 5, "partition": "all"}], "every peer"),
        # every spelling of an all-peer partition is the same silent no-op
        ([{"start": 0, "end": 5, "partition": {"frac": 1.0}}], "every peer"),
        ([{"start": 0, "end": 5, "partition": {"span": [0.0, 1.0]}}],
         "every peer"),
        ([{"start": 0, "end": 5,
           "partition": {"ids": list(range(200))}}], "every peer"),
        ([{"start": 0, "end": 5, "blackout": {"ids": [999]}}], "outside"),
        ([{"start": 0, "end": 5, "blackout": {"shards": [0]}}], "not sharded"),
    ],
)
def test_validation_rejects(phases, match):
    spec = scenario_from_dict({"phases": phases})
    with pytest.raises(ScenarioError, match=match):
        spec.validate(total_rounds=40, n_peers=N)


def test_shard_sets_validate_with_layout():
    spec = scenario_from_dict(
        {"phases": [{"start": 0, "end": 5, "blackout": {"shards": [1]}}]}
    )
    spec.validate(total_rounds=10, n_peers=16, n_shards=4)
    sc = compile_scenario(
        spec, n_peers=16, n_slots=16, total_rounds=10, n_shards=4,
        shard_ranges=[(0, 4), (4, 8), (8, 12), (12, 16)],
    )
    mask = np.asarray(sc.blackout)[0]
    assert mask[4:8].all() and mask.sum() == 4


# --------------------------------------------------- semantics, per fault
def test_quiescent_scenario_is_bit_identical_to_none(setup):
    """The foundation: a scenario whose phases inject nothing must leave
    the trajectory bit-for-bit unchanged — the protocol's key split is
    untouched and the fault stream is derived, not taken."""
    _, cfg, st = setup
    sc = _compile({"phases": [{"start": 0, "end": 10}]})
    fin_a, stats_a = simulate(clone_state(st), cfg, 12)
    fin_b, stats_b = simulate(clone_state(st), cfg, 12, None, "fused", sc)
    for f in type(fin_a).__dataclass_fields__:
        if f == "rng":  # typed PRNG key: compare raw key data instead
            va = jax.random.key_data(fin_a.rng)
            vb = jax.random.key_data(fin_b.rng)
        else:
            va, vb = getattr(fin_a, f), getattr(fin_b, f)
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=f
        )
    np.testing.assert_array_equal(
        np.asarray(stats_a.msgs_sent), np.asarray(stats_b.msgs_sent)
    )
    assert not np.asarray(stats_b.msgs_dropped).any()


def test_total_loss_stalls_dissemination(setup):
    _, cfg, st = setup
    sc = _compile(
        {"phases": [{"name": "dark", "start": 0, "end": 8, "loss": 1.0}]}
    )
    _, stats = simulate(clone_state(st), cfg, 16, None, "fused", sc)
    cov = np.asarray(stats.coverage)
    assert cov[7] == cov[0], "coverage grew through 100% loss"
    assert cov[-1] > cov[7], "network never healed after the loss phase"
    assert np.asarray(stats.msgs_dropped)[:8].sum() > 0
    # sends still happen (and are billed): the network eats them, the
    # senders don't know
    assert np.asarray(stats.msgs_sent)[:8].sum() > 0


def test_partial_loss_slows_but_not_stops(setup):
    _, cfg, st = setup
    sc = _compile(
        {"phases": [{"name": "lossy", "start": 0, "end": 30, "loss": 0.5}]}
    )
    _, stats_clean = simulate(clone_state(st), cfg, 30)
    _, stats_lossy = simulate(clone_state(st), cfg, 30, None, "fused", sc)
    r_clean = M.rounds_to_coverage(stats_clean, 0.95)
    r_lossy = M.rounds_to_coverage(stats_lossy, 0.95)
    assert r_clean > 0 and r_lossy > 0
    assert r_lossy > r_clean, (r_lossy, r_clean)
    # realized loss rate tracks the configured probability
    rep = M.phase_report(stats_lossy, scenario_from_dict(
        {"phases": [{"name": "lossy", "start": 0, "end": 30, "loss": 0.5}]}
    ))
    assert 0.35 < rep[0]["delivery_loss_rate"] < 0.65


def test_delay_holds_then_releases(setup):
    """delay=1.0 freezes every delivery in the held buffer; when the
    phase ends, the backlog drains and the epidemic resumes."""
    _, cfg, st = setup
    sc = _compile(
        {"phases": [{"name": "frozen", "start": 0, "end": 6, "delay": 1.0}]}
    )
    _, stats = simulate(clone_state(st), cfg, 14, None, "fused", sc)
    cov = np.asarray(stats.coverage)
    held = np.asarray(stats.msgs_held)
    assert cov[5] == cov[0], "deliveries landed through delay=1.0"
    assert held[:6].max() > 0, "nothing was ever held"
    assert held[-1] == 0, "the buffer never drained after the phase"
    assert cov[-1] > 0.5


def test_geometric_delay_adds_latency(setup):
    _, cfg, st = setup
    sc = _compile(
        {"phases": [{"name": "slow", "start": 0, "end": 40, "delay": 0.6}]}
    )
    _, fast = simulate(clone_state(st), cfg, 40)
    _, slow = simulate(clone_state(st), cfg, 40, None, "fused", sc)
    r_fast = M.rounds_to_coverage(fast, 0.95)
    r_slow = M.rounds_to_coverage(slow, 0.95)
    assert 0 < r_fast < r_slow


def test_split_brain_stalls_at_boundary_then_heals(setup):
    """The acceptance scenario: coverage under a partition caps at the
    origin side's share of the swarm, then recovers to >=99% within a
    bounded number of rounds after heal."""
    _, cfg, st = setup
    heal = 12
    # partition from round 0: the origin's rumor must never seed side B,
    # so coverage is provably capped at side A's share for the whole
    # phase (a later-starting partition merely freezes whatever mix
    # existed at onset — tested via the explicit-groups flood case)
    spec = scenario_from_dict({"phases": [
        {"name": "split", "start": 0, "end": heal, "partition": "half"},
    ]})
    sc = compile_scenario(spec, n_peers=N, n_slots=N, total_rounds=40)
    _, stats = simulate(clone_state(st), cfg, 30, None, "fused", sc)
    cov = np.asarray(stats.coverage)
    # origin 0 is in group A (lower half): during the partition coverage
    # cannot exceed A's share, and sits exactly there by phase end
    group_b = np.asarray(sc.group_b)[np.asarray(sc.phase_of_round)[5]]
    share = 1.0 - group_b.mean()
    assert (cov[:heal] <= share + 1e-6).all(), "traffic crossed the partition"
    assert cov[heal - 1] == pytest.approx(share), "side A never saturated"
    # bounded re-coverage after heal
    rec = M.recoverage_rounds(stats, heal, 0.99)
    assert 0 < rec <= 8, f"re-coverage took {rec} rounds"
    rep = M.phase_report(stats, spec)
    assert rep[0]["recoverage_rounds_after_heal"] == rec


def test_partition_respects_explicit_groups(setup):
    """One round of flood under a partition: NO bit crosses the boundary,
    every reachable same-side neighbor still gets traffic."""
    g, _, _ = setup
    cfg = SwarmConfig(n_peers=N, msg_slots=4, mode="flood")
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(1))
    sc = _compile({"phases": [
        {"name": "p", "start": 0, "end": 4, "partition": "half"},
    ]})
    fin, _ = simulate(clone_state(st), cfg, 4, None, "fused", sc)
    seen = np.asarray(fin.seen)[:, 0]
    assert seen[: N // 2].sum() > 1, "flood died inside group A"
    assert not seen[N // 2 :].any(), "flood crossed the partition"


def test_blackout_silences_and_detector_fires(setup):
    """A blackout longer than the liveness timeout reads as a silent
    fault: the detector declares the blacked-out set dead (SURVEY §2.5
    band — detection inside the phase), while the rest of the swarm
    keeps full delivery."""
    _, cfg, st = setup
    spec = scenario_from_dict({"phases": [
        {"name": "rack", "start": 0, "end": 16,
         "blackout": {"span": [0.5, 0.75]}},
    ]})
    sc = compile_scenario(spec, n_peers=N, n_slots=N, total_rounds=40)
    fin, stats = simulate(clone_state(st), cfg, 16, None, "fused", sc)
    blacked = np.asarray(sc.blackout)[0]
    assert blacked.sum() == N // 4
    dead = np.asarray(fin.declared_dead)
    assert dead[blacked].all(), "blackout escaped the failure detector"
    assert not dead[~blacked].any(), "a live peer was declared dead"
    # no delivery INTO the blacked set while dark
    assert not np.asarray(fin.seen)[blacked].any()
    rep = M.phase_report(stats, spec)
    # stale after 6 rounds + 2-round sweep cadence → detection at round
    # 7-9 (the reference's 30-42 s band at 5 s/round)
    assert 7 <= rep[0]["detection_latency_rounds"] <= 9


def test_churn_burst_composes_with_config_churn(setup):
    g, _, _ = setup
    cfg = SwarmConfig(
        n_peers=N, msg_slots=8, fanout=3, mode="push",
        churn_leave_prob=0.002, churn_join_prob=0.1,
    )
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(2))
    sc = _compile({"phases": [
        {"name": "storm", "start": 2, "end": 8, "churn_leave": 0.25},
    ]})
    _, calm = simulate(clone_state(st), cfg, 12)
    _, storm = simulate(clone_state(st), cfg, 12, None, "fused", sc)
    calm_alive = np.asarray(calm.n_alive)
    storm_alive = np.asarray(storm.n_alive)
    # the storm kills a visible fraction the calm run keeps
    assert storm_alive[7] < calm_alive[7] - N * 0.3
    # after the storm, rejoin pressure recovers population
    assert storm_alive[-1] > storm_alive[7]


def test_burst_node_mask_scopes_the_storm(setup):
    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "storm", "start": 0, "end": 10, "churn_leave": 1.0,
         "churn_nodes": {"span": [0.0, 0.25]}},
    ]})
    fin, _ = simulate(clone_state(st), cfg, 3, None, "fused", sc)
    alive = np.asarray(fin.alive)
    assert not alive[: N // 4].any(), "burst rows survived churn_leave=1.0"
    assert alive[N // 4 :].all(), "the storm leaked outside its node mask"


# ------------------------------------------- scenario cursor / checkpoint
def test_checkpoint_mid_scenario_resumes_bit_exactly(setup, tmp_path):
    """The scenario cursor (state.round + fault_held) round-trips through
    a checkpoint: interrupted-and-resumed equals uninterrupted, bit for
    bit, mid-delay-phase included."""
    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "slow", "start": 0, "end": 12, "delay": 0.7, "loss": 0.1},
    ]})
    mid, _ = simulate(clone_state(st), cfg, 5, None, "fused", sc)
    assert np.asarray(mid.fault_held).any(), "test needs a live held buffer"
    save_swarm(tmp_path / "mid.npz", mid)
    restored = load_swarm(tmp_path / "mid.npz")
    np.testing.assert_array_equal(
        np.asarray(mid.fault_held), np.asarray(restored.fault_held)
    )
    fin_direct, _ = simulate(mid, cfg, 7, None, "fused", sc)
    fin_resumed, _ = simulate(restored, cfg, 7, None, "fused", sc)
    np.testing.assert_array_equal(
        np.asarray(fin_direct.seen), np.asarray(fin_resumed.seen)
    )
    np.testing.assert_array_equal(
        np.asarray(fin_direct.fault_held), np.asarray(fin_resumed.fault_held)
    )


def test_legacy_checkpoint_loads_with_faults_disabled(setup, tmp_path):
    """A checkpoint saved before the scenario engine existed (no
    fault_held key) loads with the buffer zeroed — faults disabled,
    exactly its semantics when saved — and still runs."""
    _, cfg, st = setup
    mid, _ = simulate(clone_state(st), cfg, 3)
    save_swarm(tmp_path / "new.npz", mid)
    data = dict(np.load(tmp_path / "new.npz"))
    assert "field_fault_held" in data
    del data["field_fault_held"]  # forge the pre-scenario format
    np.savez(tmp_path / "old.npz", **data)
    restored = load_swarm(tmp_path / "old.npz")
    assert restored.fault_held.shape == mid.seen.shape
    assert not np.asarray(restored.fault_held).any()
    fin, _ = simulate(restored, cfg, 3)
    assert int(fin.round) == 6


def test_all_shard_partition_rejected():
    spec = scenario_from_dict({"phases": [
        {"start": 0, "end": 5, "partition": {"shards": [0, 1, 2, 3]}},
    ]})
    with pytest.raises(ScenarioError, match="every peer"):
        spec.validate(total_rounds=10, n_peers=64, n_shards=4)


def test_scenarios_without_loss_delay_skip_the_stage(setup):
    """Absent fault classes cost nothing: a partition-only scenario keeps
    the telemetry counters at zero and the held buffer untouched (the
    loss/delay stage is compiled out via the static has_loss_delay)."""
    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "p", "start": 0, "end": 6, "partition": "half"},
    ]})
    assert not sc.has_loss_delay
    fin, stats = simulate(clone_state(st), cfg, 8, None, "fused", sc)
    assert not np.asarray(stats.msgs_dropped).any()
    assert not np.asarray(stats.msgs_held).any()
    assert not np.asarray(stats.msgs_delivered).any()
    assert not np.asarray(fin.fault_held).any()


def test_drain_held_releases_a_scenarioless_resume(setup, tmp_path):
    """Resuming a mid-delay checkpoint WITHOUT its scenario freezes the
    held backlog by design; faults.drain_held releases it through the
    round's receptive gate and clears the buffer."""
    from tpu_gossip.faults import drain_held

    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "frozen", "start": 0, "end": 8, "delay": 1.0},
    ]})
    mid, _ = simulate(clone_state(st), cfg, 5, None, "fused", sc)
    held = np.asarray(mid.fault_held)
    assert held.any(), "test needs a live held buffer"
    save_swarm(tmp_path / "mid.npz", mid)
    restored = load_swarm(tmp_path / "mid.npz")
    # scenario-less rounds leave the backlog frozen (documented)
    stuck, _ = simulate(clone_state(restored), cfg, 2)
    np.testing.assert_array_equal(np.asarray(stuck.fault_held), held)
    # the explicit drain releases it: seen grows by the held bits of
    # receptive peers, infected_round latches, the buffer clears
    drained = drain_held(restored)
    assert not np.asarray(drained.fault_held).any()
    live = np.asarray(restored.alive) & ~np.asarray(restored.declared_dead)
    releasable = held & live[:, None] & ~np.asarray(restored.recovered)
    np.testing.assert_array_equal(
        np.asarray(drained.seen), np.asarray(restored.seen) | releasable
    )
    assert (np.asarray(drained.infected_round)[releasable] >= 0).all()


def test_scenario_rounds_are_absolute(setup):
    """Phases index absolute state.round — running the first rounds
    without the scenario then attaching it mid-run lands in the right
    phase (the cursor is the round counter, not wall position)."""
    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "late-dark", "start": 6, "end": 12, "loss": 1.0},
    ]})
    mid, _ = simulate(clone_state(st), cfg, 6)
    _, stats = simulate(mid, cfg, 6, None, "fused", sc)
    cov = np.asarray(stats.coverage)
    assert cov[-1] == cov[0], "the late phase did not engage on resume"


def test_repartition_carries_fault_held(setup):
    """repartition_swarm remaps every per-peer leaf — the delay buffer
    included — so an epoch rebuild mid-scenario keeps held deliveries
    with their (permuted) owners."""
    from tpu_gossip.dist import repartition_swarm

    _, cfg, st = setup
    sc = _compile({"phases": [
        {"name": "slow", "start": 0, "end": 10, "delay": 0.8},
    ]})
    mid, _ = simulate(clone_state(st), cfg, 4, None, "fused", sc)
    held_rows = np.asarray(mid.fault_held).any(1)
    assert held_rows.any()
    _, remapped, position = repartition_swarm(mid, 4, seed=1)
    new_held = np.asarray(remapped.fault_held)
    np.testing.assert_array_equal(
        new_held[position[: len(held_rows)]].any(1), held_rows
    )


# ------------------------------------------------------- stats & metrics
def test_jsonl_carries_fault_telemetry(setup):
    import io
    import json

    _, cfg, st = setup
    sc = _compile({"phases": [{"start": 0, "end": 5, "loss": 0.5}]})
    _, stats = simulate(clone_state(st), cfg, 5, None, "fused", sc)
    buf = io.StringIO()
    M.write_jsonl(stats, buf)
    rows = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert {"msgs_dropped", "msgs_held", "msgs_delivered"} <= set(rows[0])
    assert sum(r["msgs_dropped"] for r in rows) > 0


def test_cli_scenario_end_to_end(tmp_path, capsys):
    import json

    from tpu_gossip.cli.run_sim import main as run_sim_main

    p = tmp_path / "s.toml"
    p.write_text(
        '[scenario]\nname = "cli-demo"\n'
        "[[phase]]\nstart = 0\nend = 6\nloss = 0.4\n"
    )
    rc = run_sim_main([
        "--peers", "96", "--rounds", "12", "--slots", "4", "--quiet",
        "--scenario", str(p),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["scenario"] == "cli-demo"
    assert summary["phases"][0]["msgs_dropped"] > 0


def test_cli_rejects_invalid_scenario(tmp_path, capsys):
    from tpu_gossip.cli.run_sim import main as run_sim_main

    p = tmp_path / "bad.toml"
    p.write_text("[scenario]\n[[phase]]\nstart = 0\nend = 50\n")
    rc = run_sim_main([
        "--peers", "64", "--rounds", "10", "--slots", "4", "--quiet",
        "--scenario", str(p),
    ])
    assert rc == 2
    assert "beyond the run's horizon" in capsys.readouterr().err


def test_catalogued_scenarios_parse_and_validate():
    """Every scenario shipped in scenarios/ must parse and fit the smoke
    horizon CI runs them under — the catalogue-smoke campaign's [base]
    rounds (scenarios/campaigns/catalogue_smoke.toml), read here so the
    pin tracks the campaign instead of a hand-copied constant."""
    import pathlib

    from tpu_gossip.fleet import parse_campaign

    root = pathlib.Path(__file__).resolve().parents[2] / "scenarios"
    horizon = int(parse_campaign(
        root / "campaigns" / "catalogue_smoke.toml"
    ).base["rounds"])
    files = sorted(root.glob("*.toml"))
    assert len(files) >= 4, "the scenario catalogue shrank"
    for f in files:
        spec = parse_scenario(f)
        spec.validate(total_rounds=horizon, n_peers=96)
