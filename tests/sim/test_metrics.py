"""Metrics + run_sim CLI tests (the minimum end-to-end slice, SURVEY.md §7.3)."""

import io
import json

import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
from tpu_gossip.core.state import clone_state
from tpu_gossip.cli.run_sim import main as run_sim_main
from tpu_gossip.sim import metrics as M
from tpu_gossip.sim.engine import simulate


@pytest.fixture(scope="module")
def setup():
    g = build_csr(256, preferential_attachment(256, m=3, use_native=False))
    cfg = SwarmConfig(n_peers=256, msg_slots=8)
    return cfg, init_swarm(g, cfg, origins=[0])


def test_rounds_to_coverage(setup):
    cfg, st = setup
    _, stats = simulate(clone_state(st), cfg, 25)
    r = M.rounds_to_coverage(stats, 0.99)
    cov = np.asarray(stats.coverage)
    assert r > 0 and cov[r - 1] >= 0.99
    assert r == 1 or cov[r - 2] < 0.99
    assert M.rounds_to_coverage(stats, 1.1) == -1  # unreachable target


def test_bench_swarm_agrees_with_curve(setup):
    cfg, st = setup
    res, _fin = M.bench_swarm(st, cfg, 0.99, 200)
    _, stats = simulate(clone_state(st), cfg, res.rounds)
    assert float(np.asarray(stats.coverage)[-1]) >= 0.99
    assert res.coverage >= 0.99
    assert res.peers_rounds_per_sec > 0
    assert json.loads(res.to_json())["n_peers"] == 256


def test_jsonl_rows(setup):
    cfg, st = setup
    _, stats = simulate(clone_state(st), cfg, 5)
    buf = io.StringIO()
    M.write_jsonl(stats, buf)
    rows = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert len(rows) == 5
    assert rows[0]["round"] == 1
    assert set(rows[0]) == {
        "round", "coverage", "msgs_sent", "n_infected", "n_alive", "n_declared_dead",
        "msgs_dropped", "msgs_held", "msgs_delivered",
        "n_members", "degree_gamma",
        "stream_offered", "stream_injected", "stream_conflated",
        "stream_expired", "slot_infected", "slot_age",
        "control_level", "control_fanout", "msgs_duplicate",
        "control_refreshed",
        "evictions_new", "false_evictions", "n_quarantined",
        "dead_undeclared", "adv_accusations", "adv_forged",
        "ingest_offered", "ingest_injected", "ingest_conflated",
        "ingest_overflow",
    }
    # the streaming plane's per-slot tracks emit as JSON lists (one entry
    # per dedup slot); scalars stay scalars — and an unloaded run's
    # streaming counters read all-zero
    assert rows[0]["slot_infected"] == [0] * cfg.msg_slots
    assert rows[0]["stream_offered"] == 0


def test_cli_fixed_horizon(capsys):
    rc = run_sim_main(
        ["--peers", "128", "--rounds", "10", "--slots", "4", "--quiet"]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["summary"] and summary["rounds_run"] == 10


def test_cli_run_to_target(capsys):
    rc = run_sim_main(["--peers", "128", "--slots", "4", "--quiet", "--graph", "chung-lu"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["coverage"] >= summary["target"]


@pytest.mark.slow  # test_cli_shard_fixed_horizon_with_churn below keeps
# the --shard CLI path (churn + checkpoint included) in tier-1; the
# run-to-target + --staircase variant rides the slow lane
def test_cli_shard_run_to_target(capsys):
    """--shard runs the dist engine over the (virtual 8-device) mesh; with
    --staircase the receive side is the per-shard kernel (north-star CLI)."""
    rc = run_sim_main(
        ["--peers", "200", "--slots", "4", "--quiet", "--shard", "--staircase",
         "--mode", "push_pull", "--fanout", "2"]
    )
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["devices"] == 8
    assert summary["coverage"] >= summary["target"]


def test_cli_shard_fixed_horizon_with_churn(capsys, tmp_path):
    ck = tmp_path / "shard.npz"
    rc = run_sim_main(
        ["--peers", "200", "--rounds", "8", "--slots", "4", "--quiet", "--shard",
         "--mode", "push_pull", "--fanout", "2", "--churn-leave", "0.01",
         "--churn-join", "0.1", "--rewire-slots", "2", "--silent-frac", "0.05",
         "--checkpoint", str(ck)]
    )
    assert rc == 0 and ck.exists()
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["rounds_run"] == 8 and summary["devices"] == 8
    from tpu_gossip.core.state import load_swarm

    assert int(load_swarm(ck).round) == 8


def test_cli_checkpoint(tmp_path, capsys):
    ck = tmp_path / "final.npz"
    rc = run_sim_main(
        ["--peers", "128", "--rounds", "5", "--slots", "4", "--quiet",
         "--checkpoint", str(ck)]
    )
    assert rc == 0 and ck.exists()
    from tpu_gossip.core.state import load_swarm

    st = load_swarm(ck)
    assert int(st.round) == 5
