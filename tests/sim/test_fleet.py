"""Fleet engine contracts: batched lanes vs solo runs, campaign parsing.

The conformance contract (docs/fleet_campaigns.md): lane k of a batched
campaign is BIT-IDENTICAL — full state plus every integer stat — to a
solo ``simulate`` over exactly the plans the campaign compiled for that
lane. Pinned here at sampled lanes of a 16-lane campaign whose lanes
compose scenario × stream × control (the maximal plan surface), plus
the campaign compiler's parse-time rejections (exit 2 through the CLI).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip import fleet
from tpu_gossip.core.state import lane_state


def _composed_campaign(tmp_path, seeds=16):
    """A 16-lane campaign composing scenario × stream × control, with a
    loss sweep and a controller-bound sweep split over two families."""
    scen = tmp_path / "chaos.toml"
    scen.write_text(
        "[scenario]\nname = \"test-chaos\"\n"
        "[[phase]]\nname = \"lossy\"\nstart = 0\nend = 6\n"
        "loss = 0.2\ndelay = 0.15\n"
        "[[phase]]\nname = \"split\"\nstart = 6\nend = 10\n"
        "partition = \"half\"\n"
        "[[phase]]\nname = \"storm\"\nstart = 10\nend = 14\n"
        "churn_leave = 0.05\nchurn_join = 0.2\n"
        "blackout = {frac = 0.1, seed = 1}\n"
    )
    spec = fleet.campaign_from_dict({
        "name": "composed", "seed": 3,
        "base": {
            "peers": 96, "rounds": 18, "slots": 8, "fanout": 2,
            "mode": "push_pull", "coverage_target": 0.9,
            "target_ratio": 0.8, "stream_rate": 1.0, "slot_ttl": 12,
            "control": 0.9, "control_hi": 5, "rewire_slots": 5,
            "churn_join": 0.02, "refresh_every": 4,
        },
        "families": [
            {"name": "loss-sweep", "scenario": str(scen),
             "seeds": seeds // 2,
             "sweeps": [{"axis": "phase.loss", "dist": "uniform",
                         "lo": 0.05, "hi": 0.5}]},
            {"name": "bound-sweep", "scenario": str(scen),
             "seeds": seeds - seeds // 2,
             "sweeps": [{"axis": "control.hi", "dist": "linspace",
                         "lo": 2, "hi": 5},
                        {"axis": "stream.rate", "dist": "uniform",
                         "lo": 0.5, "hi": 2.0}]},
        ],
    })
    return fleet.compile_campaign(spec)


@pytest.fixture(scope="module")
def composed(tmp_path_factory):
    camp = _composed_campaign(tmp_path_factory.mktemp("fleet"))
    fin, stats = fleet.run_campaign(camp, keep_states=True)
    return camp, fin, stats


def _assert_lane_bit_identical(camp, fin, stats, k):
    solo_fin, solo_stats = fleet.run_lane_solo(camp, k)
    for f in dataclasses.fields(solo_fin):
        a = getattr(solo_fin, f.name)
        b = getattr(fin, f.name)[k]
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"lane {k}: state leaf {f.name} diverges from solo",
        )
    for name in solo_stats._fields:
        a = np.asarray(getattr(solo_stats, name))
        if a.dtype.kind not in "biu":
            continue  # float tracks excluded, as in the dist matrix
        b = np.asarray(getattr(stats, name))[k]
        np.testing.assert_array_equal(
            a, b, err_msg=f"lane {k}: integer stat {name} diverges",
        )


@pytest.mark.parametrize(
    "k", [0, pytest.param(7, marks=pytest.mark.slow), 13]
)  # one lane per family in tier-1 (0: loss sweep, 13: bound×rate sweep);
# the second loss-family sample rides the slow lane
def test_lane_bit_identical_to_solo(composed, k):
    """3 sampled lanes of the 16-lane composed campaign — incl. lanes of
    both families (loss sweep / bound×rate sweep) — reproduce their solo
    run bit for bit: full state + whole integer stat trajectory."""
    camp, fin, stats = composed
    assert camp.k == 16
    _assert_lane_bit_identical(camp, fin, stats, k)


@pytest.mark.slow  # the fleet-smoke CI job exercises the digest pair
# across real processes on every push; the in-process equality check
# rides the slow lane
def test_lane_digests_match_solo(composed):
    """The digest pair the fleet-smoke CI job compares across processes
    equals the in-process comparison."""
    camp, fin, stats = composed
    k = 5
    solo_fin, solo_stats = fleet.run_lane_solo(camp, k)
    assert fleet.state_digest(lane_state(fin, k)) == fleet.state_digest(
        solo_fin
    )
    assert fleet.stats_digest(stats, k) == fleet.stats_digest(solo_stats)


def test_unified_scenario_value_identical_to_family_compile():
    """Flag unification is VALUE-transparent: a lane whose family never
    partitions/blacks-out runs that machinery over zero tables under the
    unified batch structure, and its STATE trajectory equals a solo run
    over the family's own (unpadded, flag-minimal) compile."""
    from tpu_gossip.faults import compile_scenario, scenario_from_dict
    from tpu_gossip.sim.engine import simulate

    spec = fleet.campaign_from_dict({
        "name": "mix", "seed": 0,
        "base": {"peers": 64, "rounds": 30, "slots": 4, "fanout": 2,
                 "mode": "push"},
        "families": [
            # loss-only family: no partition/blackout/churn of its own
            {"name": "lossy", "scenario": "scenarios/lossy_links.toml",
             "seeds": 2},
            # partition family: forces has_partition on the whole batch
            {"name": "split", "scenario": "scenarios/split_brain.toml",
             "seeds": 2},
        ],
    }, root="scenarios/campaigns")
    camp = fleet.compile_campaign(spec)
    assert camp.scenario.has_partition and camp.scenario.has_loss_delay
    fin, _ = fleet.run_campaign(camp, keep_states=True)

    # lane 0 (lossy family) vs a solo run over the FAMILY's own compile
    # — flags off for the classes it never declares
    own = compile_scenario(
        scenario_from_dict(
            fleet.plan._scenario_dict("scenarios/lossy_links.toml", None)
        ),
        n_peers=64, n_slots=64, total_rounds=30,
    )
    assert not own.has_partition
    st0, _, _, _, _ = camp.lane(0)
    solo_fin, _ = simulate(
        st0, camp.cfg, camp.rounds, None, "fused", own,
    )
    for f in ("seen", "infected_round", "alive", "declared_dead", "round"):
        np.testing.assert_array_equal(
            np.asarray(getattr(solo_fin, f)),
            np.asarray(getattr(fin, f)[0]),
            err_msg=f"unified-flag lane diverges from family compile: {f}",
        )


def test_report_has_quantiles_bins_and_frontier(composed):
    camp, _, stats = composed
    rep = fleet.campaign_report(camp, stats)
    fam = {f["family"]: f for f in rep["families"]}
    rel = fam["loss-sweep"]["reliability"]
    assert set(rel["quantiles"]) == {"p05", "p25", "p50", "p75", "p95"}
    lo, hi = rel["bootstrap_ci95_mean"]
    assert 0.0 <= lo <= hi <= 1.0
    bins = fam["loss-sweep"]["sweeps"][0]["bins"]
    assert bins and all("bootstrap_ci95_mean" in b for b in bins)
    assert sum(b["lanes"] for b in bins) == fam["loss-sweep"]["lanes_judged"]
    fr = fam["bound-sweep"]["frontier"]
    assert fr["axis"] == "control.hi"
    assert {t["value"] for t in fr["per_value"]} == {2.0, 3.0, 4.0, 5.0}


def test_clamped_control_bounds_saturate(composed):
    """A lane's clamped fanout table never exceeds its sampled bound and
    the batch shares ONE static table width."""
    camp, _, _ = composed
    tbl = np.asarray(camp.control.fanout_table)
    assert tbl.shape[0] == camp.k  # stacked
    for lane in camp.lanes:
        if "control.hi" in lane.sampled:
            assert tbl[lane.index].max() <= int(lane.sampled["control.hi"])
        assert tbl[lane.index].min() >= 1


def test_frontier_nonmonotone_top_break_no_crash():
    """A noisy sweep whose HIGHEST bound value breaks while lower values
    hold must report its one-sided truth (first_hold None), not crash
    (regression: min() of an empty generator)."""
    from tpu_gossip.fleet.metrics import _frontier

    fr = _frontier(
        "control.hi",
        [2, 2, 3, 3, 4, 4],
        [0.95, 0.93, 0.92, 0.91, 0.80, 0.85],
        0.9,
    )
    assert fr["found"] and fr["last_break"] == 4.0
    assert fr["first_hold"] is None


def test_control_bound_samples_are_integral():
    """control.lo/hi samples round AT SAMPLING time for every dist, so
    the value the frontier groups by IS the bound the lane ran with."""
    rng = np.random.default_rng(0)
    ax = fleet.SweepAxis(axis="control.hi", dist="uniform", lo=2, hi=5)
    v = ax.sample(16, rng)
    np.testing.assert_array_equal(v, np.rint(v))


@pytest.mark.slow  # error-path composition over a full campaign run; lane
# parity keeps the fleet contract in tier-1
def test_consumed_campaign_refuses_lane_extraction(tmp_path):
    camp = _composed_campaign(tmp_path, seeds=4)
    fleet.run_campaign(camp, keep_states=False)
    with pytest.raises(fleet.CampaignError, match="donated"):
        camp.lane(0)


# ------------------------------------------------------ parse rejections
def test_reject_single_lane_campaign():
    with pytest.raises(fleet.CampaignError, match="solo run"):
        fleet.campaign_from_dict({
            "name": "one", "base": {"peers": 16, "rounds": 4},
            "families": [{"name": "f", "seeds": 1}],
        })


def test_reject_duplicate_family_names():
    """Lanes, scenarios, and report blocks group by family name — a
    duplicated name would silently cross-wire them."""
    with pytest.raises(fleet.CampaignError, match="duplicate family"):
        fleet.campaign_from_dict({
            "name": "dup", "base": {"peers": 16, "rounds": 4},
            "families": [
                {"name": "f", "seeds": 2},
                {"name": "f", "seeds": 2},
            ],
        })


def test_reject_out_of_range_phase_probability():
    """A phase.* axis sampling outside [0, 1] would run clamped values
    while the report groups lanes by the raw sample — rejected at parse
    time instead of misreporting what ran."""
    with pytest.raises(fleet.CampaignError, match="probability"):
        fleet.campaign_from_dict({
            "name": "bad", "base": {"peers": 16, "rounds": 4},
            "families": [{
                "name": "f", "seeds": 4,
                "sweeps": [{"axis": "phase.loss", "dist": "uniform",
                            "lo": 0.5, "hi": 1.5}],
            }],
        })


def test_reject_unknown_sampled_axis():
    with pytest.raises(fleet.CampaignError, match="unknown sampled axis"):
        fleet.campaign_from_dict({
            "name": "bad", "base": {"peers": 16, "rounds": 4},
            "families": [{
                "name": "f", "seeds": 4,
                "sweeps": [{"axis": "slots", "dist": "uniform",
                            "lo": 4, "hi": 64}],
            }],
        })


def test_reject_mixed_static_shapes():
    """The shared-static-shape backstop: lanes whose compiled plans
    disagree on structure or leaf shapes can never reach vmap."""
    import jax.numpy as jnp

    a = {"x": jnp.zeros((4,)), "y": jnp.zeros((2,))}
    b_shape = {"x": jnp.zeros((5,)), "y": jnp.zeros((2,))}
    with pytest.raises(fleet.CampaignError, match="static shape"):
        fleet.plan._check_lane_structures([a, b_shape], "probe")
    b_struct = {"x": jnp.zeros((4,))}
    with pytest.raises(fleet.CampaignError, match="structure"):
        fleet.plan._check_lane_structures([a, b_struct], "probe")


def test_reject_join_burst_without_grow(tmp_path):
    """join_burst phases need a growing fleet — capacity is a static
    shape the whole batch shares, so one lane cannot grow alone."""
    spec = fleet.campaign_from_dict({
        "name": "jb", "seed": 0,
        "base": {"peers": 96, "rounds": 20, "slots": 4, "fanout": 2},
        "families": [
            {"name": "flash",
             "scenario": "scenarios/flash_crowd_under_fire.toml",
             "seeds": 2}],
    }, root="scenarios/campaigns")
    with pytest.raises(fleet.CampaignError, match="static shape"):
        fleet.compile_campaign(spec)


def test_reject_sweep_matching_no_phase(tmp_path):
    """A phase-parameter axis that matches no declaring phase would flip
    a static has_* flag mid-batch — rejected by name."""
    scen = tmp_path / "noloss.toml"
    scen.write_text(
        "[scenario]\nname = \"noloss\"\n"
        "[[phase]]\nname = \"p\"\nstart = 0\nend = 4\nchurn_leave = 0.1\n"
    )
    spec = fleet.campaign_from_dict({
        "name": "miss", "seed": 0,
        "base": {"peers": 32, "rounds": 8, "slots": 4, "fanout": 2},
        "families": [{
            "name": "f", "scenario": str(scen), "seeds": 2,
            "sweeps": [{"axis": "phase.loss", "dist": "uniform",
                        "lo": 0.1, "hi": 0.5}],
        }],
    })
    with pytest.raises(fleet.CampaignError, match="matched no phase"):
        fleet.compile_campaign(spec)


def test_reject_bound_sweep_without_controller():
    with pytest.raises(fleet.CampaignError, match="control"):
        fleet.compile_campaign(fleet.campaign_from_dict({
            "name": "b", "seed": 0,
            "base": {"peers": 32, "rounds": 8, "slots": 4, "fanout": 2},
            "families": [{
                "name": "f", "seeds": 2,
                "sweeps": [{"axis": "control.hi", "dist": "linspace",
                            "lo": 2, "hi": 4}],
            }],
        }))


def test_cli_exit_2_on_bad_campaign(tmp_path, capsys):
    from tpu_gossip.cli.run_sim import main

    bad = tmp_path / "bad.toml"
    bad.write_text(
        "[campaign]\nname = \"bad\"\n[base]\npeers = 16\nrounds = 4\n"
        "[[family]]\nname = \"f\"\nseeds = 4\n"
        "[[family.sweep]]\naxis = \"peers\"\ndist = \"uniform\"\n"
        "lo = 16\nhi = 64\n"
    )
    assert main(["fleet", str(bad)]) == 2
    assert "unknown sampled axis" in capsys.readouterr().err


def test_cli_exit_2_on_missing_campaign(capsys):
    from tpu_gossip.cli.run_sim import main

    assert main(["fleet", "/nonexistent/campaign.toml"]) == 2


def test_fleet_salt_registered():
    from tpu_gossip.core.streams import registered_salts

    assert fleet.FLEET_STREAM_SALT in registered_salts()
    assert registered_salts()[fleet.FLEET_STREAM_SALT] == "fleet"


def test_stack_states_roundtrip_and_pricing():
    from tpu_gossip.core.state import (
        SwarmConfig, init_swarm, lane_state, stack_states,
        state_bytes_per_peer,
    )
    from tpu_gossip.core.topology import build_csr, preferential_attachment

    rng = np.random.default_rng(0)
    g = build_csr(32, preferential_attachment(32, m=2, rng=rng))
    cfg = SwarmConfig(n_peers=32, msg_slots=4)
    sts = [init_swarm(g, cfg, key=jax.random.key(k), origins=[k])
           for k in range(3)]
    b = stack_states(sts)
    assert b.seen.shape == (3, 32, 4)
    back = lane_state(b, 1)
    np.testing.assert_array_equal(np.asarray(back.seen),
                                  np.asarray(sts[1].seen))
    # batch-rank pricing: stacking adds no per-peer overhead
    assert state_bytes_per_peer(1000, 16, lanes=8) == pytest.approx(
        state_bytes_per_peer(1000, 16)
    )
