"""The adversarial fault plane + quorum-suspicion defense (ISSUE 14,
docs/adversarial_model.md).

Pins the whole contract: the hardened detector at quorum_k=1 with no
adversaries is bit-identical to the direct detector (full state, the
suspicion planes included); quorum detection costs no latency (the
witness-cohort sweep); a single accuser evicts healthy peers at
quorum_k=1 (the reference's Seed.py single-report purge, reproduced) and
cannot at quorum_k=3; repeat false accusers quarantine with their rewire
slots released through the degree-credit book balance; forged heartbeats
stall detection entry but not an active suspicion; flood replay bills
wire cost as duplicate pressure; the suspicion cursor checkpoints and
scan-splits bit-exactly mid-window; and the byzantine_siege
demonstration pair — quorum_k=1 fails the 0.9 reliability target and
the 0.95 eviction-precision floor where quorum_k=3 holds both.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.state import (
    SwarmConfig,
    clone_state,
    init_swarm,
    load_swarm,
    save_swarm,
)
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.faults import compile_scenario, parse_scenario, scenario_from_dict
from tpu_gossip.kernels.liveness import (
    SUSPECT_STRIKE_CAP,
    SUSPECT_VOTE_CAP,
    QuorumSpec,
    compile_quorum,
    pack_suspicion,
    unpack_suspicion,
)
from tpu_gossip.sim import metrics as M
from tpu_gossip.sim.engine import simulate

N = 200


def _graph(n=N, m=3, seed=0):
    return build_csr(
        n, preferential_attachment(n, m=m, rng=np.random.default_rng(seed))
    )


def _state(cfg, graph=None, seed=0, silent=0):
    g = _graph(cfg.n_peers) if graph is None else graph
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
    if silent:
        ids = np.random.default_rng(7).choice(
            cfg.n_peers, size=silent, replace=False
        )
        st.silent = st.silent.at[jnp.asarray(ids)].set(True)
    return st


def _adv_scenario(n, rounds, accusers=0.05, forgers=0.0, floods=0.0,
                  **phase_extra):
    phase = {"name": "adv", "start": 0, "end": rounds, **phase_extra}
    if accusers:
        phase["accusers"] = {"frac": accusers, "seed": 3}
    if forgers:
        phase["forgers"] = {"frac": forgers, "seed": 4}
    if floods:
        phase["floods"] = {"frac": floods, "seed": 5}
    spec = scenario_from_dict({"name": "adv", "phases": [phase]})
    return compile_scenario(spec, n_peers=n, n_slots=n, total_rounds=rounds)


def _assert_states_equal(a, b):
    for f in dataclasses.fields(a):
        la, lb = getattr(a, f.name), getattr(b, f.name)
        if jnp.issubdtype(la.dtype, jax.dtypes.prng_key):
            la, lb = jax.random.key_data(la), jax.random.key_data(lb)
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb), err_msg=f.name
        )


# ---------------------------------------------------------------- packing
def test_suspicion_packing_roundtrip_and_caps():
    votes = jnp.asarray([0, 1, 17, SUSPECT_VOTE_CAP], dtype=jnp.int32)
    strikes = jnp.asarray([0, 3, 99, SUSPECT_STRIKE_CAP], dtype=jnp.int32)
    mark = pack_suspicion(votes, strikes)
    assert mark.dtype == jnp.int16
    v, s = unpack_suspicion(mark)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(votes))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(strikes))
    # the maximal packed value is exactly int16's ceiling — no overflow
    assert int(pack_suspicion(
        jnp.asarray(SUSPECT_VOTE_CAP), jnp.asarray(SUSPECT_STRIKE_CAP)
    )) == 2**15 - 1


def test_quorum_spec_validation():
    with pytest.raises(ValueError):
        QuorumSpec(quorum_k=0)
    with pytest.raises(ValueError):
        QuorumSpec(quorum_k=SUSPECT_VOTE_CAP + 1)
    with pytest.raises(ValueError):
        QuorumSpec(window=0)
    with pytest.raises(ValueError):
        QuorumSpec(budget=SUSPECT_STRIKE_CAP + 1)


# ------------------------------------------- determinism anchor contracts
@pytest.mark.slow  # direct-detector parity anchor pair rides the slow
# lane; quorum semantics keep six cheaper tier-1 tests below
def test_quorum_k1_no_adversary_bit_identical_to_direct_detector():
    """THE determinism anchor: quorum_k=1 with no adversaries reproduces
    the unhardened detector bit for bit — the FULL state, suspicion
    planes included (entry, cohort confirmation and declaration land on
    the same sweep, so suspicion never persists across rounds)."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push_pull")
    st = _state(cfg, silent=20)
    fin_direct, stats_direct = simulate(clone_state(st), cfg, 12)
    fin_q, stats_q = simulate(clone_state(st), cfg, 12, None, "fused",
                              None, None, None, None, None,
                              compile_quorum(1))
    _assert_states_equal(fin_direct, fin_q)
    np.testing.assert_array_equal(
        np.asarray(stats_direct.n_declared_dead),
        np.asarray(stats_q.n_declared_dead),
    )
    assert int(stats_direct.n_declared_dead[-1]) == 20  # it actually bit


@pytest.mark.slow  # latency anchor; the k=1 bit-identity anchor above is
# the tier-1 representative of direct-detector parity
def test_quorum_detection_latency_equals_direct_detector():
    """The witness cohort confirms a genuinely-stale suspect in ONE
    sweep, so for any quorum_k up to the live witness count the hardened
    detector declares on the SAME round the direct one does — quorum
    costs no detection latency (the liveness-band satellite's engine
    half)."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg, silent=30)
    _, s_direct = simulate(clone_state(st), cfg, 12)
    for k in (2, 5, 50):
        _, s_q = simulate(clone_state(st), cfg, 12, None, "fused",
                          None, None, None, None, None, compile_quorum(k))
        np.testing.assert_array_equal(
            np.asarray(s_direct.n_declared_dead),
            np.asarray(s_q.n_declared_dead),
            err_msg=f"quorum_k={k}",
        )


def test_unhardened_round_carries_suspicion_planes_untouched():
    """liveness=None never touches the new planes — the no-defense hot
    path (and with it every pre-PR trajectory) is unchanged."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg, silent=10)
    fin, _ = simulate(st, cfg, 8)
    assert int(np.asarray(fin.suspect_round).max()) == -1
    assert int(np.asarray(fin.suspect_mark).max()) == 0
    assert not np.asarray(fin.quarantine).any()


def test_adversary_scenario_requires_defense():
    """An adversary-carrying scenario without a QuorumSpec is a config
    error at trace time, not a silent no-op."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    sc = _adv_scenario(N, 8)
    with pytest.raises(ValueError, match="quorum"):
        simulate(st, cfg, 8, None, "fused", sc)


# ------------------------------------------------------- the attack plane
def test_single_accuser_evicts_healthy_peers_at_k1():
    """The reference's vulnerability, reproduced: at quorum_k=1 ONE
    accusation is a purge (Seed.py trusts the first "Dead Node" report),
    so healthy peers fall every round and nobody is ever quarantined
    (an accusation that evicts is never refuted)."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    sc = _adv_scenario(N, 10, accusers=0.05)
    _, stats = simulate(st, cfg, 10, None, "fused", sc, None, None, None,
                        None, compile_quorum(1))
    lv = M.liveness_report(stats)
    assert lv["false_evictions"] > 20
    assert lv["eviction_precision"] < 0.5
    assert lv["quarantined"] == 0


def test_quorum_resists_accusers_and_quarantines_them():
    """At quorum_k=3 uniformly-sampled accusations never concentrate
    inside the refutation window: zero false evictions, and every repeat
    accuser crosses the strike budget into quarantine — after which its
    accusations stop (sends masked)."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    sc = _adv_scenario(N, 20, accusers=0.05)
    fin, stats = simulate(st, cfg, 20, None, "fused", sc, None, None, None,
                          None, compile_quorum(3, window=4, budget=3))
    lv = M.liveness_report(stats)
    assert lv["false_evictions"] == 0
    assert lv["quarantined"] == 10  # all 5% of 200 accusers
    acc = np.asarray(stats.adv_accusations)
    assert acc[:3].sum() > 0 and acc[-5:].sum() == 0  # budget shut them up
    # quarantined peers stay live members (suspected liars, not purged)
    assert int(stats.n_alive[-1]) == N


def test_lone_repeat_accuser_never_meets_quorum_2():
    """The distinct-witness contract, exactly: votes are the suspicion's
    largest SINGLE-round cohort (max, never sum), so one Byzantine
    reporter re-accusing the same victim across the window can never add
    itself up to quorum_k=2 — zero false evictions from a lone accuser,
    deterministically, over any horizon."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    spec = scenario_from_dict({"name": "lone", "phases": [
        {"name": "adv", "start": 0, "end": 40, "accusers": {"ids": [7]}},
    ]})
    sc = compile_scenario(spec, n_peers=N, n_slots=N, total_rounds=40)
    _, stats = simulate(st, cfg, 40, None, "fused", sc, None, None, None,
                        None, compile_quorum(2, window=6, budget=0))
    assert int(np.asarray(stats.adv_accusations).sum()) > 30  # it kept trying
    assert int(np.asarray(stats.false_evictions).sum()) == 0
    assert int(np.asarray(stats.evictions_new).sum()) == 0


def test_blacked_out_adversaries_emit_nothing():
    """An adversary inside a blackout is cut off like everyone else: its
    accusations and forgeries never land (the blackout contract applies
    to the attack plane too)."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    spec = scenario_from_dict({"name": "dark-adv", "phases": [
        {"name": "adv", "start": 0, "end": 10,
         "accusers": {"ids": [3, 4]}, "forgers": {"ids": [5]},
         "blackout": {"ids": [3, 4, 5]}},
    ]})
    sc = compile_scenario(spec, n_peers=N, n_slots=N, total_rounds=10)
    _, stats = simulate(st, cfg, 10, None, "fused", sc, None, None, None,
                        None, compile_quorum(1))
    assert int(np.asarray(stats.adv_accusations).sum()) == 0
    assert int(np.asarray(stats.adv_forged).sum()) == 0


@pytest.mark.slow  # credit-book composition; quarantine + accusation
# invariants stay in tier-1 via the cheaper quorum tests
def test_quarantine_releases_rewire_credit_book_balance():
    """A quarantined row's fresh edges are discarded: its stored targets'
    degree credit is RELEASED and the row leaves the re-wired set — the
    book-balance invariant (sum(credit) == stored fresh targets of
    re-wired rows) survives the quarantine transition."""
    cfg = SwarmConfig(
        n_peers=N, msg_slots=8, fanout=2, mode="push",
        churn_leave_prob=0.05, churn_join_prob=0.3, rewire_slots=2,
    )
    st = _state(cfg)
    sc = _adv_scenario(N, 16, accusers=0.08)
    fin, _ = simulate(st, cfg, 16, None, "fused", sc, None, None, None,
                      None, compile_quorum(3, window=4, budget=2))
    assert np.asarray(fin.quarantine).sum() > 0
    q_rw = np.asarray(fin.quarantine) & np.asarray(fin.rewired)
    assert not q_rw.any(), "quarantined rows must leave the re-wired set"
    stored = int(
        (np.asarray(fin.rewire_targets)[np.asarray(fin.rewired)] >= 0).sum()
    )
    assert int(np.asarray(fin.degree_credit).sum()) == stored


@pytest.mark.slow  # forger-lane composition; forged-heartbeat billing is
# asserted in tier-1 by the flood/replay billing test
def test_forgery_stalls_detection_entry_but_not_active_suspicion():
    """Forged heartbeats refresh non-suspected targets' last_hb, delaying
    suspicion ENTRY of the genuinely silent — detection falls far behind
    the forgery-free run (the detection-latency-under-forgery metric) —
    but an active suspicion's nonce-carrying probe cannot be answered by
    a third party, so detections that do latch complete: forgery
    degrades latency, never correctness."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg, silent=30)
    q = compile_quorum(3)
    base = _adv_scenario(N, 30, accusers=0.0, forgers=0.0, floods=0.0,
                         loss=0.01)  # some fault so the scenario compiles
    forged = _adv_scenario(N, 30, accusers=0.0, forgers=0.10, floods=0.0,
                           forge_fanout=4)
    _, s0 = simulate(clone_state(st), cfg, 30, None, "fused", base, None,
                     None, None, None, q)
    _, s1 = simulate(clone_state(st), cfg, 30, None, "fused", forged, None,
                     None, None, None, q)
    dead0 = np.asarray(s0.n_declared_dead)
    dead1 = np.asarray(s1.n_declared_dead)
    assert dead0[-1] == 30
    assert int(np.asarray(s1.adv_forged).sum()) > 0
    # forgery stalls the trajectory hard...
    assert dead1.sum() < 0.5 * dead0.sum()
    assert dead1[-1] < 30
    # ...but detection still progresses: staleness that slips through the
    # forgers' sampling is confirmed and declared (a declared peer is
    # never resurrected by later forgeries)
    assert dead1[-1] > 0
    assert (np.diff(dead1) >= 0).all()


def test_flood_replay_bills_wire_and_duplicates():
    """Flood adversaries replay their seen bitmaps: billed sends rise
    while the epidemic's reachable set does not shrink — pure duplicate
    pressure on the dedup plane."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg)
    q = compile_quorum(3)
    quiet = _adv_scenario(N, 12, accusers=0.0, floods=0.0, loss=0.01)
    flooded = _adv_scenario(N, 12, accusers=0.0, floods=0.10,
                            flood_fanout=4)
    _, s0 = simulate(clone_state(st), cfg, 12, None, "fused", quiet, None,
                     None, None, None, q)
    _, s1 = simulate(clone_state(st), cfg, 12, None, "fused", flooded,
                     None, None, None, None, q)
    # ~20 flooders x 4 targets x their seen bits per round — the replay
    # wire cost is real and billed (the quiet twin differs only by its
    # 1% loss phase)
    assert int(s1.msgs_sent.sum()) > int(s0.msgs_sent.sum()) + 300
    assert float(s1.coverage[-1]) >= 0.95


# ------------------------------------------------- checkpoint / determinism
def test_suspicion_cursor_checkpoint_roundtrip_mid_window():
    """A checkpoint cut mid-suspicion (votes pending inside the window,
    strikes accrued, some rows quarantined) resumes bit-exactly: the
    suspicion planes are part of the state cursor like fault_held and
    slot_lease."""
    cfg = SwarmConfig(n_peers=N, msg_slots=8, fanout=3, mode="push")
    st = _state(cfg, silent=10)
    sc = _adv_scenario(N, 14, accusers=0.06, forgers=0.03, floods=0.03)
    q = compile_quorum(5, window=6, budget=4)
    mid, _ = simulate(clone_state(st), cfg, 7, None, "fused", sc, None,
                      None, None, None, q)
    # the cut must actually be mid-suspicion, or the pin is vacuous
    assert (np.asarray(mid.suspect_round) >= 0).any()
    assert (np.asarray(mid.suspect_mark) != 0).any()
    straight, _ = simulate(clone_state(mid), cfg, 7, None, "fused", sc,
                           None, None, None, None, q)
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as d:
        p = Path(d) / "mid.npz"
        save_swarm(p, mid)
        resumed = load_swarm(p)
    _assert_states_equal(mid, resumed)
    refin, _ = simulate(resumed, cfg, 7, None, "fused", sc, None, None,
                        None, None, q)
    _assert_states_equal(straight, refin)


def test_pre_adversarial_checkpoint_loads_planes_zeroed(tmp_path):
    """A checkpoint written before the suspicion planes existed loads
    with them zeroed — no suspicion, no strikes, nobody quarantined."""
    import dataclasses as _dc

    import jax as _jax

    cfg = SwarmConfig(n_peers=64, msg_slots=4, fanout=2, mode="push")
    st = _state(cfg, graph=_graph(64))
    p = tmp_path / "old.npz"
    # write the PRE-PACKING named layout directly (every plane unpacked —
    # what the old save_swarm emitted; since the packed-plane PR the
    # current writer stores quarantine as a flags bit, so stripping it
    # from a fresh archive is no longer expressible), then strip the
    # suspicion planes: the pre-adversarial, pre-packing format
    arrays = {}
    for f in _dc.fields(type(st)):
        leaf = getattr(st, f.name)
        if f.name == "rng":
            arrays["prngkey_rng"] = np.asarray(_jax.random.key_data(leaf))
        elif f.name not in ("suspect_round", "suspect_mark", "quarantine"):
            arrays[f"field_{f.name}"] = np.asarray(leaf)
    np.savez(p, **arrays)
    loaded = load_swarm(p)
    assert (np.asarray(loaded.suspect_round) == -1).all()
    assert (np.asarray(loaded.suspect_mark) == 0).all()
    assert not np.asarray(loaded.quarantine).any()


def test_partial_suspicion_planes_never_silently_zeroed(tmp_path):
    """A file carrying SOME suspicion planes keeps them: the legacy
    backfill fills only the missing ones (a stored quarantine verdict
    must never be overwritten by the pre-format default); the sharded
    store goes further and rejects partial subsets as torn/foreign."""
    cfg = SwarmConfig(n_peers=64, msg_slots=4, fanout=2, mode="push")
    st = _state(cfg, graph=_graph(64))
    st.quarantine = st.quarantine.at[3].set(True)
    p = tmp_path / "partial.npz"
    save_swarm(p, st)
    data = dict(np.load(p))
    del data["field_suspect_round"], data["field_suspect_mark"]
    np.savez(p, **data)
    loaded = load_swarm(p)
    assert bool(np.asarray(loaded.quarantine)[3])  # stored verdict kept
    assert (np.asarray(loaded.suspect_round) == -1).all()  # missing: zeroed
    assert (np.asarray(loaded.suspect_mark) == 0).all()


# ------------------------------------------------ the demonstration pair
@pytest.mark.slow  # the demonstration pair is narrative, not a contract;
# the quorum/forgery invariant tests above carry tier-1
def test_byzantine_siege_demonstration_pair():
    """THE acceptance pin: under scenarios/byzantine_siege.toml with
    traffic and control, the unhardened detector (quorum_k=1 — the
    reference's single-report purge) evicts healthy peers and misses the
    0.9 reliability target, where the quorum detector holds >= 0.9 with
    eviction precision >= 0.95 and quarantines the accusers."""
    from tpu_gossip.control import compile_control
    from tpu_gossip.traffic import compile_stream

    n, rounds = 96, 55
    g = _graph(n, m=2)
    cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=2, mode="push_pull",
                      rewire_slots=6, churn_join_prob=0.02)
    spec = parse_scenario("scenarios/byzantine_siege.toml")
    spec.validate(total_rounds=rounds, n_peers=n)
    sc = compile_scenario(spec, n_peers=n, n_slots=n, total_rounds=rounds)
    strm = compile_stream(rate=1.5, msg_slots=8, ttl=24,
                          origin_rows=np.arange(n))
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=6,
                          refresh_every=5, ttl=24)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(0))

    def run(q):
        _, stats = simulate(clone_state(st), cfg, rounds, None, "fused",
                            sc, None, strm, ctl, None, q)
        return (
            M.reliability_report(stats, target_ratio=0.9,
                                 coverage_target=0.95),
            M.liveness_report(stats),
        )

    rel1, lv1 = run(compile_quorum(1, window=4, budget=2))
    rel3, lv3 = run(compile_quorum(3, window=4, budget=2))
    # the unhardened baseline fails BOTH contract halves
    assert rel1["delivery_ratio"] < 0.9 and not rel1["holds"]
    assert lv1["eviction_precision"] < 0.95
    assert lv1["false_evictions"] > 20
    # the quorum detector holds both
    assert rel3["delivery_ratio"] >= 0.9 and rel3["holds"]
    assert lv3["eviction_precision"] >= 0.95
    assert lv3["quarantined"] > 0


# --------------------------------------------------------------- fleet
@pytest.mark.slow  # fleet x adversary composition; fleet lane parity and
# solo adversary runs each stay in tier-1 on their own
def test_fleet_adversary_lane_bit_identical_to_solo():
    """The fleet extension: a byzantine campaign ([base] quorum_k) keeps
    the lane↔solo bit-identity contract — the QuorumSpec is jit-static
    and lane-shared, the adversary draws per-lane. A campaign fielding
    adversaries without [base] quorum_k is a compile-time CampaignError.
    """
    from tpu_gossip import fleet

    adv_scenario = {
        "name": "siege",
        "phases": [{
            "name": "adv", "start": 0, "end": 6,
            "accusers": {"frac": 0.06, "seed": 3},
            "floods": {"frac": 0.05, "seed": 5},
            "blackout": {"frac": 0.1, "seed": 2},
        }],
    }
    base = {"peers": 64, "rounds": 8, "slots": 4, "fanout": 2,
            "mode": "push"}
    with pytest.raises(fleet.CampaignError, match="quorum_k"):
        fleet.compile_campaign(fleet.campaign_from_dict({
            "name": "no-defense", "seed": 0, "base": base,
            "families": [{"name": "adv", "scenario": adv_scenario,
                          "seeds": 2}],
        }))
    camp = fleet.compile_campaign(fleet.campaign_from_dict({
        "name": "siege", "seed": 0,
        "base": {**base, "quorum_k": 3, "suspicion_window": 4,
                 "accusation_budget": 2},
        "families": [{"name": "adv", "scenario": adv_scenario,
                      "seeds": 3}],
    }))
    assert camp.liveness is not None and camp.liveness.quorum_k == 3
    fin, stats = fleet.run_campaign(camp)
    k = 1
    fin_solo, stats_solo = fleet.run_lane_solo(camp, k)
    _assert_states_equal(
        jax.tree.map(lambda leaf: leaf[k], fin), fin_solo
    )
    assert fleet.stats_digest(stats, k) == fleet.stats_digest(stats_solo)
    # the attack bit in at least one lane, or the pin is vacuous
    assert int(np.asarray(stats.adv_accusations).sum()) > 0


# ------------------------------------------------------------ CLI surface
def test_cli_rejects_adversary_scenario_without_quorum(tmp_path):
    from tpu_gossip.cli.run_sim import main

    p = tmp_path / "adv.toml"
    p.write_text(
        "[scenario]\nname = \"adv\"\n\n[[phase]]\nname = \"a\"\n"
        "start = 0\nend = 4\naccusers = {frac = 0.05, seed = 1}\n"
    )
    assert main(["--peers", "64", "--rounds", "8",
                 "--scenario", str(p)]) == 2


@pytest.mark.parametrize("argv", [
    ["--suspicion-window", "4"],  # defense flag without --quorum-k
    ["--accusation-budget", "2"],
    ["--quorum-k", "0"],  # K < 1
    ["--quorum-k", "-3"],
    ["--quorum-k", "2", "--suspicion-window", "1"],  # below the grace
    ["--quorum-k", "2", "--accusation-budget", "200"],  # past the cap
    ["--quorum-k", "2", "--profile-round", "3"],
])
def test_cli_quorum_rejections(argv):
    from tpu_gossip.cli.run_sim import main

    assert main(["--peers", "64", "--rounds", "6"] + argv) == 2


def test_cli_liveness_summary_block(tmp_path, capsys):
    import json

    from tpu_gossip.cli.run_sim import main

    p = tmp_path / "adv.toml"
    p.write_text(
        "[scenario]\nname = \"adv\"\n\n[[phase]]\nname = \"a\"\n"
        "start = 0\nend = 8\naccusers = {frac = 0.05, seed = 1}\n"
        "blackout = {frac = 0.1, seed = 2}\n"
    )
    rc = main(["--peers", "96", "--rounds", "16", "--scenario", str(p),
               "--quorum-k", "3", "--quiet"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(out)
    lv = summary["liveness"]
    assert lv["quorum_k"] == 3
    assert lv["suspicion_window"] == 4  # the settled default (2x sweep)
    assert lv["accusation_budget"] == 3
    for k in ("evictions", "false_evictions", "eviction_precision",
              "quarantined"):
        assert k in lv
