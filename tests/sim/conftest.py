"""Shared session-scoped builds for tests/sim (tier-1 wall headroom).

The heaviest engine builds used by more than one module live here ONCE
per pytest session instead of once per module: the virtual 8-device
mesh and the n=1500 sharded matching (graph, plan) pair that both the
dist parity suite and the sparse-transport suite run their witnesses
on. The topology builders memoize on identical args, but routing every
consumer through one fixture makes the sharing load-bearing — an arg
drift in one module can no longer silently fork a second multi-second
build.
"""

import jax
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from tpu_gossip.dist import make_mesh

    return make_mesh(8)


@pytest.fixture(scope="session")
def matching_1500():
    """The shared sharded-matching build: (graph, plan) at n=1500 on 8
    shards — the single-chip-vs-mesh witnesses in test_dist.py and the
    sparse-transport parity witnesses both run on this layout."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )

    return matching_powerlaw_graph_sharded(
        1500, 8, fanout=2, key=jax.random.key(0)
    )
