"""Re-materialization of rewired edges into the CSR (SURVEY §7.4's periodic
rebuild): edge algebra, parity with a from-scratch CSR build, tail-handling
on both delivery paths, overflow clipping, and steady-state churn use."""

import contextlib
import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
from tpu_gossip.core.state import clone_state
from tpu_gossip.kernels.gossip import flood_all
from tpu_gossip.sim.engine import (
    remat_capacity,
    rematerialize_rewired,
    simulate,
)

# rematerialize_rewired donates its state but the CSR leaves change
# shape (capacity padding), so XLA reports them as unusable donations
# at every compile — expected here, and the REAL donation behavior is
# asserted directly by the donation tests
pytestmark = pytest.mark.filterwarnings(
    "ignore:Some donated buffers were not usable"
)



def _churned_state(n=400, rewired_frac=0.15, seed=0):
    """A mid-churn state: random rewired subset with valid fresh targets."""
    rng = np.random.default_rng(seed)
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False, rng=rng))
    cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=2, mode="push_pull",
                      rewire_slots=2)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
    rw = rng.choice(n, size=int(rewired_frac * n), replace=False)
    tgts = rng.integers(0, n, size=(len(rw), 2))
    # a sprinkle of sentinel (-1) draws, like real churn produces
    tgts[rng.random(tgts.shape) < 0.1] = -1
    rewired = np.zeros(n, bool)
    rewired[rw] = True
    st = dataclasses.replace(
        st,
        rewired=jnp.asarray(rewired),
        rewire_targets=st.rewire_targets.at[jnp.asarray(rw), :].set(
            jnp.asarray(tgts, dtype=st.rewire_targets.dtype)
        ),
    )
    return g, cfg, st


def _expected_edges(g, st, cfg):
    """The surviving directed edge MULTISET, computed independently in numpy
    (parallel fresh edges — two slots drawing one target — count twice)."""
    from collections import Counter

    rewired = np.asarray(st.rewired)
    src = np.repeat(np.arange(g.n), np.diff(np.asarray(st.row_ptr)))
    dst = np.asarray(st.col_idx)[: len(src)]
    keep = ~rewired[src] & ~rewired[dst]
    edges = Counter((int(a), int(b)) for a, b in zip(src[keep], dst[keep]))
    tg = np.asarray(st.rewire_targets)[:, : cfg.rewire_slots]
    for r in np.nonzero(rewired)[0]:
        for t in tg[r]:
            if t >= 0 and t != r:  # self targets are excluded by remat
                edges[(int(r), int(t))] += 1
                edges[(int(t), int(r))] += 1
    return edges


def test_remat_edge_algebra_and_invariants():
    g, cfg, st = _churned_state()
    cap = remat_capacity(st, cfg)
    new, overflow = rematerialize_rewired(clone_state(st), cfg, cap)
    assert int(overflow) == 0
    assert not bool(jnp.any(new.rewired))
    assert bool(jnp.all(new.rewire_targets == -1))
    row_ptr = np.asarray(new.row_ptr)
    col = np.asarray(new.col_idx)
    assert col.shape[0] == cap
    assert row_ptr[0] == 0 and np.all(np.diff(row_ptr) >= 0)
    # the rebuilt edge MULTISET matches the independent computation, with
    # multiplicity (parallel fresh edges are deliberately kept)
    from collections import Counter

    got = Counter(
        (i, int(c))
        for i in range(g.n)
        for c in col[row_ptr[i] : row_ptr[i + 1]]
    )
    assert got == _expected_edges(g, st, cfg)
    # tail past row_ptr[-1] is self-loops on the repeat-attribution row
    # (the last row with degree > 0) — defense in depth on top of
    # flood_all's explicit tail mask
    deg = np.diff(row_ptr)
    r_star = int(np.max(np.nonzero(deg > 0)[0]))
    assert np.all(col[row_ptr[-1] :] == r_star)
    # non-CSR state is untouched
    np.testing.assert_array_equal(np.asarray(new.seen), np.asarray(st.seen))


def test_remat_flood_matches_fresh_csr_build():
    """Delivery over the re-materialized CSR is bit-exact vs a from-scratch
    build_csr of the same surviving edge set (tail self-loops included —
    they must contribute nothing)."""
    g, cfg, st = _churned_state(seed=3)
    new, _ = rematerialize_rewired(clone_state(st), cfg, remat_capacity(st, cfg))
    edges = _expected_edges(g, st, cfg)
    und = np.asarray(sorted({(min(a, b), max(a, b)) for a, b in edges}))
    ref = build_csr(g.n, und)
    transmit = jnp.asarray(np.random.default_rng(9).random((g.n, 8)) < 0.4)
    got = flood_all(transmit, new.row_ptr, new.col_idx)
    want = flood_all(transmit, jnp.asarray(ref.row_ptr), jnp.asarray(ref.col_idx))
    # parallel fresh edges OR-merge away, so delivery agrees exactly even
    # though the remat CSR may store a duplicate the dedup'd build lacks
    assert bool(jnp.array_equal(got, want))


def test_remat_staircase_plan_parity():
    """The staircase plan built over a re-materialized CSR (capacity tail
    and all) floods bit-exactly like flood_all over the same arrays."""
    from tpu_gossip.kernels.pallas_segment import build_staircase_plan, segment_or

    g, cfg, st = _churned_state(seed=5)
    new, _ = rematerialize_rewired(st, cfg, remat_capacity(st, cfg))
    plan = build_staircase_plan(np.asarray(new.row_ptr), np.asarray(new.col_idx))
    transmit = jnp.asarray(np.random.default_rng(11).random((g.n, 8)) < 0.3)
    ref = flood_all(transmit, new.row_ptr, new.col_idx)
    assert bool(jnp.array_equal(ref, segment_or(plan, transmit, 8)))


def test_remat_overflow_clips_and_reports():
    g, cfg, st = _churned_state(seed=7)
    cap = int(st.row_ptr[-1]) // 2  # deliberately too small
    new, overflow = rematerialize_rewired(st, cfg, cap)
    assert int(overflow) > 0
    assert int(new.row_ptr[-1]) == cap
    assert new.col_idx.shape[0] == cap


def test_churn_with_periodic_remat_sustains_coverage():
    """Steady-state churn story: simulate → remat → simulate keeps the swarm
    covered, empties `rewired` at each remat, and later rounds run on the
    folded topology (fresh edges persist as CSR edges)."""
    n = 2000
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False,
                                             rng=np.random.default_rng(21)))
    cfg = SwarmConfig(
        n_peers=n, msg_slots=4, fanout=3, mode="push_pull",
        churn_leave_prob=0.03, churn_join_prob=0.3, rewire_slots=2,
    )
    st = init_swarm(g, cfg, origins=list(range(5)), key=jax.random.key(2))
    cap = remat_capacity(st, cfg)
    # first segment runs on the original capacity; remat pads to `cap`,
    # later segments all share the padded shape
    for seg in range(3):
        st, stats = simulate(st, cfg, 12)
        assert float(stats.coverage[-1]) > 0.6, (seg, float(stats.coverage[-1]))
        rewired_before = int(jnp.sum(st.rewired))
        st, overflow = rematerialize_rewired(st, cfg, cap)
        assert int(overflow) == 0
        assert int(jnp.sum(st.rewired)) == 0
        if seg > 0:
            assert rewired_before > 0  # churn really was accumulating
    # endpoint draws after remat stay on real peers (the capacity tail must
    # not bias them): run more churn rounds and check targets' validity
    st, _ = simulate(st, cfg, 12)
    rw = np.asarray(st.rewired)
    if rw.any():
        t = np.asarray(st.rewire_targets)[rw].ravel()
        assert ((t == -1) | ((t >= 0) & (t < n))).all()


@pytest.mark.slow  # the composed remat-then-repartition drill; the
# periodic-remat coverage test keeps the remat law in tier-1
def test_remat_then_repartition_back_onto_mesh():
    """The dist epoch-rebuild cycle: dist churn rounds → re-materialize the
    accumulated fresh edges → repartition_swarm → resume on the mesh. The
    live protocol state must survive the permutation and the epidemic must
    keep spreading over the folded topology."""
    from tpu_gossip.dist import (
        build_shard_plans,
        init_sharded_swarm,
        make_mesh,
        partition_graph,
        repartition_swarm,
        shard_swarm,
        simulate_dist,
    )

    n = 400
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False,
                                             rng=np.random.default_rng(40)))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=4)
    cfg = SwarmConfig(
        n_peers=sg.n_pad, msg_slots=4, fanout=2, mode="push_pull",
        churn_leave_prob=0.03, churn_join_prob=0.3, rewire_slots=2,
    )
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0],
                           key=jax.random.key(8)), mesh)
    st, _ = simulate_dist(st, cfg, sg, mesh, 10, build_shard_plans(sg))
    assert int(jnp.sum(st.rewired)) > 0, "no churn accumulated to fold"
    cov_before = float(st.coverage(0))
    seen_before = int(jnp.sum(st.seen))

    st, overflow = rematerialize_rewired(st, cfg, remat_capacity(st, cfg))
    assert int(overflow) == 0
    sg2, st2, pos2 = repartition_swarm(st, 8, seed=5)
    cfg2 = dataclasses.replace(cfg, n_peers=sg2.n_pad)
    # the permutation moved, not changed, the protocol state
    assert float(st2.coverage(0)) == pytest.approx(cov_before, abs=1e-6)
    assert int(jnp.sum(st2.seen)) == seen_before
    np.testing.assert_array_equal(
        np.asarray(st.seen)[np.asarray(st.exists)].sum(0),
        np.asarray(st2.seen)[np.asarray(st2.exists)].sum(0),
    )
    # and the swarm keeps disseminating on the new partition: under
    # 3%/round churn rejoiners reset their seen state, so coverage hovers
    # near (not monotonically above) the pre-remat level — demand it stays
    # in that band rather than strictly grows (the strict form flakes on
    # RNG trajectory)
    st2 = shard_swarm(st2, mesh)
    fin, _ = simulate_dist(st2, cfg2, sg2, mesh, 10, build_shard_plans(sg2))
    assert int(fin.round) == 20
    assert float(fin.coverage(0)) > 0.9


@pytest.mark.parametrize("mode", ["push", "push_pull"])
def test_remat_identity_when_nothing_rewired(mode):
    """With no rewired slots, remat at the same capacity is a pure identity
    on the edge structure (order within rows aside)."""
    n = 300
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False,
                                             rng=np.random.default_rng(33)))
    cfg = SwarmConfig(n_peers=n, msg_slots=4, fanout=2, mode=mode, rewire_slots=1)
    st = init_swarm(g, cfg, origins=[0])
    new, overflow = rematerialize_rewired(
        clone_state(st), cfg, int(st.col_idx.shape[0])
    )
    assert int(overflow) == 0
    np.testing.assert_array_equal(np.asarray(new.row_ptr), np.asarray(st.row_ptr))
    # same multiset of neighbors per row
    rp = np.asarray(st.row_ptr)
    a, b = np.asarray(st.col_idx), np.asarray(new.col_idx)
    for i in range(n):
        np.testing.assert_array_equal(
            np.sort(a[rp[i]:rp[i+1]]), np.sort(b[rp[i]:rp[i+1]]), err_msg=str(i)
        )

@pytest.mark.slow  # end-to-end CLI epoch loop; the direct remat/repartition
# parity tests above keep the law in tier-1
def test_cli_shard_epoch_loop_runs_churn_remat_repartition():
    """VERDICT r4 item 3: the full churn -> remat -> repartition -> continue
    epoch loop through the CLI path, on the 8-device CPU mesh, both receive
    paths (scatter and per-shard staircase kernel)."""
    import json

    from tpu_gossip.cli.run_sim import main

    for extra in ([], ["--staircase"]):
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = main([
                "--peers", "600", "--graph", "chung-lu", "--mode", "push_pull",
                "--fanout", "1", "--slots", "4", "--shard",
                "--churn-leave", "0.01", "--churn-join", "0.05",
                "--rewire-slots", "2", "--remat-every", "4",
                "--rounds", "12", "--quiet", "--seed", "3",
            ] + extra)
        assert rc == 0
        summary = json.loads(out.getvalue().strip().splitlines()[-1])
        assert summary["remats"] >= 2  # the epoch loop actually cycled
        assert summary["devices"] == 8
        assert summary["rounds_run"] == 12
