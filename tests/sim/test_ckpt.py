"""Durable checkpoints (tpu_gossip/ckpt/): sharded atomic round-trips,
torn-write detection + rollback, bit-exact crash recovery, fleet-rank
round-trips, legacy-format loading, and the CLI's rejection surface."""

import json
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.ckpt import (
    CORRUPTION_MODES,
    CheckpointError,
    corrupt_checkpoint,
    latest_complete,
    list_checkpoint_steps,
    load_any,
    load_checkpoint,
    next_cut,
    prune_checkpoints,
    save_checkpoint,
    verify_checkpoint,
)
from tpu_gossip.core.state import (
    PLANES,
    SwarmConfig,
    init_swarm,
    lane_state,
    load_swarm,
    save_swarm,
    stack_states,
)
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.fleet.engine import state_digest, stats_digest
from tpu_gossip.sim.engine import simulate


def small_graph(n=96, seed=0):
    rng = np.random.default_rng(seed)
    return build_csr(
        n, preferential_attachment(n, m=2, rng=rng, use_native=False)
    )


def churny_cfg(n=96, **kw):
    return SwarmConfig(
        n_peers=n, msg_slots=8, fanout=2,
        churn_leave_prob=0.05, churn_join_prob=0.3, rewire_slots=2, **kw,
    )


@pytest.fixture()
def warm_state():
    g = small_graph()
    cfg = churny_cfg()
    st = init_swarm(g, cfg, origins=[0, 3], key=jax.random.key(1))
    st, stats = simulate(st, cfg, 6)
    return g, cfg, st, stats


# ------------------------------------------------------ store round-trip
def test_checkpoint_format_covers_every_plane():
    """Shard/global/CSR membership derives from the PLANES registry, so a
    future SwarmState plane lands in the format automatically — and this
    pin makes a plane that somehow escapes all three groups a test
    failure, not silent data loss."""
    import dataclasses as _dc

    from tpu_gossip.ckpt.store import (
        _CSR_PLANES,
        _global_planes,
        _row_planes,
    )
    from tpu_gossip.core.state import SwarmState

    names = {f.name for f in _dc.fields(SwarmState)}
    covered = set(_row_planes()) | set(_global_planes()) | set(_CSR_PLANES)
    assert covered == names, names ^ covered

def test_sharded_roundtrip_is_bit_exact(tmp_path, warm_state):
    """S shard files + global.npz concatenate back to the EXACT state —
    every leaf, the PRNG key, and the stats prefix included."""
    _g, _cfg, st, stats = warm_state
    stats_d = {f: np.asarray(getattr(stats, f)) for f in stats._fields}
    save_checkpoint(tmp_path, st, step=6, shards=4, stats=stats_d,
                    run_config={"peers": 96})
    st2, stats2, manifest = load_checkpoint(tmp_path / "ckpt-00000006")
    assert state_digest(st2) == state_digest(st)
    for f, arr in stats_d.items():
        np.testing.assert_array_equal(stats2[f], arr)
    assert manifest["round"] == 6 and manifest["shards"] == 4
    assert manifest["run"] == {"peers": 96}
    # the manifest declares every plane at its registry STORAGE dtype:
    # packed "bits" planes and the shared flags word land as uint8, the
    # six flag planes collapse into it, everything else keeps its
    # registry compute dtype (the packed-plane PR's format-3 contract)
    reg = {p.name: p for p in PLANES}
    assert manifest["format"] == 3
    assert manifest["planes"]["flags"]["dtype"] == "uint8"
    for name, entry in manifest["planes"].items():
        if name == "flags":
            continue
        spec = reg[name]
        assert spec.packed is None or spec.packed == "bits", name
        if spec.dtype == "key":
            continue
        want = "uint8" if spec.packed == "bits" else spec.dtype
        assert entry["dtype"] == want, name
    for p in PLANES:
        if p.packed is not None and p.packed.startswith("flag:"):
            assert p.name not in manifest["planes"], p.name


def test_shard_count_is_a_storage_choice(tmp_path, warm_state):
    """The resharding contract's file half: the SAME state saved at S=1,
    S=3 and S=8 loads to identical bits — shard count never leaks into
    the restored state."""
    _g, _cfg, st, _stats = warm_state
    digests = set()
    for s in (1, 3, 8):
        d = tmp_path / f"s{s}"
        save_checkpoint(d, st, step=6, shards=s)
        st2, _, _ = load_checkpoint(d / "ckpt-00000006")
        digests.add(state_digest(st2))
    assert digests == {state_digest(st)}


def test_capacity_tail_survives_the_roundtrip(tmp_path):
    """A re-materialized CSR keeps a capacity tail past row_ptr[-1]; the
    tail rides global.npz verbatim so the reassembled pair is
    byte-identical (anything else would break jit shape reuse)."""
    from tpu_gossip.sim.engine import remat_capacity, rematerialize_rewired

    g = small_graph()
    cfg = churny_cfg()
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(2))
    cap = remat_capacity(st, cfg)
    st, _ = simulate(st, cfg, 5)
    st, _overflow = rematerialize_rewired(st, cfg, cap)
    assert int(st.col_idx.shape[0]) > int(np.asarray(st.row_ptr)[-1])
    save_checkpoint(tmp_path, st, step=5, shards=3)
    st2, _, _ = load_checkpoint(tmp_path / "ckpt-00000005")
    np.testing.assert_array_equal(np.asarray(st2.col_idx),
                                  np.asarray(st.col_idx))
    np.testing.assert_array_equal(np.asarray(st2.row_ptr),
                                  np.asarray(st.row_ptr))


# ------------------------------------------------- torn-write detection
@pytest.mark.parametrize("mode", CORRUPTION_MODES)
def test_every_corruption_mode_is_detected_and_rolled_back(
    tmp_path, warm_state, mode
):
    """The acceptance contract: an injected truncation, byte flip,
    missing manifest, or dropped shard is DETECTED (named reason) and
    recovery rolls back to the previous complete checkpoint — never
    loads damage."""
    _g, cfg, st, _stats = warm_state
    save_checkpoint(tmp_path, st, step=6, shards=2)
    st2, _ = simulate(st, cfg, 3)
    save_checkpoint(tmp_path, st2, step=9, shards=2)
    early = state_digest(load_checkpoint(tmp_path / "ckpt-00000006")[0])

    corrupt_checkpoint(tmp_path / "ckpt-00000009", mode)
    with pytest.raises(CheckpointError):
        verify_checkpoint(tmp_path / "ckpt-00000009")
    logs = []
    path, _manifest = latest_complete(tmp_path, log=logs.append)
    assert path.name == "ckpt-00000006"
    assert logs and "ckpt-00000009" in logs[0]
    assert state_digest(load_checkpoint(path)[0]) == early


def test_all_checkpoints_corrupt_is_a_clean_error(tmp_path, warm_state):
    _g, _cfg, st, _stats = warm_state
    save_checkpoint(tmp_path, st, step=6, shards=2)
    corrupt_checkpoint(tmp_path / "ckpt-00000006", "flip_byte")
    with pytest.raises(CheckpointError, match="no COMPLETE checkpoint"):
        latest_complete(tmp_path, log=lambda _m: None)


def test_retention_prunes_oldest(tmp_path, warm_state):
    _g, cfg, st, _stats = warm_state
    for k in range(4):
        save_checkpoint(tmp_path, st, step=6 + 3 * k, shards=1, keep=2)
        st, _ = simulate(st, cfg, 3)
    steps = [s for s, _ in list_checkpoint_steps(tmp_path)]
    assert steps == [15, 12]
    prune_checkpoints(tmp_path, keep=1)
    assert [s for s, _ in list_checkpoint_steps(tmp_path)] == [15]


# ------------------------------------------------- crash-resume parity
@pytest.mark.slow  # the composed-matrix resume; single-feature resume
# parity stays in tier-1 via the CLI checkpoint/remat tests
def test_resume_bit_identity_composed_local(tmp_path):
    """Interrupted-and-resumed == uninterrupted, bit for bit, on the
    composed scenario×growth×stream×control cell (the mid-flight cursor
    pins — fault_held, slot_lease, control_lvl, growth cursor — all
    exercised through a disk round-trip)."""
    from tpu_gossip.ckpt import CheckpointPolicy, host_stats, run_checkpointed
    from tpu_gossip.control import compile_control
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.faults import compile_scenario
    from tpu_gossip.faults.scenario import scenario_from_dict
    from tpu_gossip.growth import compile_growth, pad_graph_for_growth
    from tpu_gossip.traffic import compile_stream

    rounds = 14
    g = small_graph(96)
    g2, exists = pad_graph_for_growth(g, 128)
    cfg = SwarmConfig(n_peers=128, msg_slots=8, fanout=2, mode="push_pull",
                      churn_leave_prob=0.05, churn_join_prob=0.3,
                      rewire_slots=2)
    spec = scenario_from_dict({"name": "t", "phases": [
        {"start": 2, "end": 8, "loss": 0.3, "delay": 0.4},
    ]})
    scen = compile_scenario(spec, n_peers=96, n_slots=128,
                            total_rounds=rounds)
    grow = compile_growth(n_initial=96, target=120, n_slots=128,
                          joins_per_round=4, attach_m=2)
    strm = compile_stream(rate=1.5, msg_slots=8, ttl=6,
                          origin_rows=np.arange(96))
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=2)
    st = init_swarm(g2, cfg, origins=[0], key=jax.random.key(3),
                    exists=exists)

    fin_ref, stats_ref = simulate(clone_state(st), cfg, rounds, None,
                                  "fused", scen, grow, strm, ctl)

    policy = CheckpointPolicy(every=5, directory=str(tmp_path))

    def seg_run(s, seg):
        s, stats = simulate(s, cfg, seg, None, "fused", scen, grow, strm,
                            ctl)
        return s, host_stats(stats)

    # phase 1: "crash" after the round-5 checkpoint lands (the driver
    # never saves at its own horizon end, so 10 leaves only ckpt-5)
    run_checkpointed(clone_state(st), 10, seg_run, policy=policy)
    path, _m = latest_complete(tmp_path)
    assert path.name == "ckpt-00000005"  # 10 == horizon end, not saved
    loaded, prefix, _ = load_checkpoint(path)
    # phase 2: resume to the full horizon
    fin_res, sd = run_checkpointed(loaded, rounds, seg_run, policy=policy,
                                   stats_prefix=prefix)
    assert state_digest(fin_res) == state_digest(fin_ref)
    ref_d = {f: np.asarray(getattr(stats_ref, f))
             for f in stats_ref._fields}
    for f, arr in ref_d.items():
        if arr.dtype.kind in "biu":
            np.testing.assert_array_equal(sd[f], arr, err_msg=f)


@pytest.mark.slow  # cross-topology restore; test_sharded_roundtrip_is_bit_
# exact keeps the sharded save/load law in tier-1
def test_sharded_matching_save_local_load_bit_identity(tmp_path):
    """The resharding contract's S'=1 leg at small n: a mesh-run
    sharded-matching swarm checkpointed at S=8 files restores into the
    LOCAL engine and finishes bit-identically to finishing on the mesh
    — the s=1 layout-truth contract run in reverse."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.dist import (
        make_mesh,
        shard_matching_plan,
        shard_swarm,
        simulate_dist,
    )

    mesh = make_mesh()
    dgraph, plan = matching_powerlaw_graph_sharded(
        600, mesh.size, fanout=2, key=jax.random.key(0),
    )
    plan_m = shard_matching_plan(plan, mesh)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull")
    rows = (np.arange(1) // plan.n_per) * plan.n_blk + (
        np.arange(1) % plan.n_per
    )
    st = init_swarm(dgraph.as_padded_graph(), cfg, key=jax.random.key(0),
                    origins=rows, exists=dgraph.exists)
    mid, _ = simulate_dist(shard_swarm(clone_state(st), mesh), cfg, plan_m,
                           mesh, 4)
    save_checkpoint(tmp_path, mid, step=4, shards=mesh.size)

    fin_mesh, stats_mesh = simulate_dist(mid, cfg, plan_m, mesh, 4)
    loaded, _, _ = load_checkpoint(tmp_path / "ckpt-00000004")
    fin_local, stats_local = simulate(loaded, cfg, 4, plan)
    assert state_digest(fin_local) == state_digest(fin_mesh)
    assert stats_digest(stats_local) == stats_digest(stats_mesh)


# ------------------------------------------------- fleet rank round-trip
def test_fleet_stack_roundtrip_and_per_lane_recovery(tmp_path):
    """stack_states → save → (a) the whole stack and (b) one lane solo
    both load bit-exactly, and a recovered lane CONTINUES bit-identically
    to its slice of the continued batch."""
    from tpu_gossip.fleet.engine import simulate_fleet

    g = small_graph(64)
    cfg = SwarmConfig(n_peers=64, msg_slots=4, fanout=2)
    lanes = [
        init_swarm(g, cfg, origins=[k], key=jax.random.key(100 + k))
        for k in range(3)
    ]
    batch = stack_states(lanes)
    mid, _ = simulate_fleet(stack_states(lanes), cfg, 4)
    save_checkpoint(tmp_path, mid, step=4, kind="fleet")
    ck = tmp_path / "ckpt-00000004"

    whole, _, manifest = load_checkpoint(ck)
    assert manifest["lanes"] == 3
    assert state_digest(whole) == state_digest(mid)
    for k in range(3):
        solo, _, _ = load_checkpoint(ck, lane=k)
        assert state_digest(solo) == state_digest(lane_state(mid, k)), k

    # continuation parity: the restored stack vs the live one, and one
    # restored lane solo vs its batch slice
    fin_live, _ = simulate_fleet(mid, cfg, 3)
    fin_restored, _ = simulate_fleet(whole, cfg, 3)
    assert state_digest(fin_restored) == state_digest(fin_live)
    solo1, _, _ = load_checkpoint(ck, lane=1)
    fin_solo, _ = simulate(solo1, cfg, 3)
    assert state_digest(fin_solo) == state_digest(lane_state(fin_restored, 1))
    del batch


# ---------------------------------------------------- legacy + validation
def test_both_legacy_formats_load_through_load_any(tmp_path):
    """v1 positional and pre-plane named npz checkpoints load through the
    new entry point — same states load_swarm produces, no manifest
    required."""
    from tests.unit.test_state import save_v1

    g = small_graph(32)
    st = init_swarm(g, SwarmConfig(n_peers=32, msg_slots=4), origins=[2])
    v1 = tmp_path / "v1.npz"
    save_v1(st, v1, per_peer_sir=True)
    st_v1, stats, manifest = load_any(v1)
    assert stats is None and manifest["format"] == "legacy-npz"
    assert bool(jnp.array_equal(st_v1.seen, st.seen))

    named = tmp_path / "named.npz"
    save_swarm(named, st)
    data = dict(np.load(named))
    for newer in ("field_fault_held", "field_join_round",
                  "field_admitted_by", "field_degree_credit",
                  "field_slot_lease", "field_control_lvl",
                  "field_pipe_buf"):
        data.pop(newer)
    np.savez(named, **data)
    st_named, _, _ = load_any(named)
    assert bool(jnp.array_equal(st_named.seen, st.seen))
    assert not bool(st_named.fault_held.any())
    assert str(st_named.join_round.dtype) == "int16"


def test_load_swarm_names_the_broken_plane(tmp_path):
    """A stale/foreign npz fails at load with the PLANE named — never as
    a shape/dtype error inside jit."""
    g = small_graph(32)
    st = init_swarm(g, SwarmConfig(n_peers=32, msg_slots=4), origins=[2])
    path = tmp_path / "ck.npz"

    save_swarm(path, st)
    data = dict(np.load(path))
    data["field_seen"] = data["field_seen"].astype(np.float32)
    np.savez(path, **data)
    with pytest.raises(ValueError, match="'seen'.*dtype"):
        load_swarm(path)

    save_swarm(path, st)
    data = dict(np.load(path))
    # the six (N,) masks ride the shared packed flags word now — a
    # truncated word surfaces as a named shape error on a flag plane
    data["field_flags"] = data["field_flags"][:16]
    np.savez(path, **data)
    with pytest.raises(ValueError, match="'exists'.*shape"):
        load_swarm(path)


def test_checkpoint_plane_validation_catches_foreign_manifest_dir(
    tmp_path, warm_state
):
    """The same named-plane gate guards the manifest path: a shard file
    whose plane dtype drifted (forged here by rewriting one shard AND
    its digest) still fails with the plane's name."""
    _g, _cfg, st, _stats = warm_state
    save_checkpoint(tmp_path, st, step=6, shards=2)
    ck = tmp_path / "ckpt-00000006"
    name = "shard-00000-of-00002.npz"
    arrays = dict(np.load(ck / name))
    arrays["rows_seen"] = arrays["rows_seen"].astype(np.float32)
    import io as _io

    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    (ck / name).write_bytes(payload)
    manifest = json.loads((ck / "MANIFEST.json").read_text())
    import hashlib

    manifest["files"][name] = {
        "sha256": hashlib.sha256(payload).hexdigest(),
        "bytes": len(payload),
        "rows": manifest["files"][name]["rows"],
    }
    (ck / "MANIFEST.json").write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="'seen'"):
        load_checkpoint(ck)


# ------------------------------------------------------------ driver bits
def test_next_cut_grids():
    assert next_cut(0, 20, 5) == 5
    assert next_cut(7, 20, 5) == 3
    assert next_cut(18, 20, 5) == 2
    assert next_cut(0, 20, 0) == 20
    assert next_cut(4, 30, 6, 10) == 2  # min(6, 10) - 4
    assert next_cut(6, 30, 6, 10) == 4  # next is 10


# ------------------------------------------------------- CLI rejections
@pytest.mark.parametrize("argv,needle", [
    (["--rounds", "10", "--checkpoint-every", "3"], "--checkpoint-dir"),
    (["--checkpoint-every", "3", "--checkpoint-dir", "d"], "FIXED horizon"),
    (["--rounds", "10", "--keep", "2"], "--checkpoint-every"),
    (["--rounds", "10", "--checkpoint-shards", "2"], "--checkpoint-every"),
    (["--rounds", "10", "--checkpoint-every", "12",
      "--checkpoint-dir", "d"], "below --rounds"),
    (["--rounds", "12", "--checkpoint-every", "4", "--checkpoint-dir",
      "d", "--shard", "--remat-every", "3"], "MULTIPLE of --remat-every"),
])
def test_cli_checkpoint_rejections(capsys, argv, needle):
    from tpu_gossip.cli.run_sim import main as run_sim_main

    rc = run_sim_main(["--peers", "64", "--slots", "4", "--quiet"] + argv)
    assert rc == 2
    assert needle in capsys.readouterr().err


def test_cli_resume_rejects_empty_dir(tmp_path, capsys):
    from tpu_gossip.cli.run_sim import main as run_sim_main

    rc = run_sim_main(["resume", str(tmp_path)])
    assert rc == 2
    assert "no checkpoints" in capsys.readouterr().err


def test_cli_checkpointed_run_resumes_bit_identically(tmp_path, capsys):
    """End to end through the CLI: a checkpointing local run, the newest
    checkpoint deleted (as if the crash hit mid-save), `run_sim resume`
    — digests equal the uninterrupted run's."""
    from tpu_gossip.cli.run_sim import main as run_sim_main

    base = ["--peers", "64", "--rounds", "12", "--slots", "4",
            "--fanout", "2", "--churn-leave", "0.05", "--churn-join",
            "0.3", "--rewire-slots", "2", "--quiet", "--digest"]
    assert run_sim_main(base) == 0
    ref = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    d = tmp_path / "ck"
    assert run_sim_main(base + ["--checkpoint-every", "4",
                                "--checkpoint-dir", str(d)]) == 0
    full = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert full["state_digest"] == ref["state_digest"]
    assert full["stats_digest"] == ref["stats_digest"]

    shutil.rmtree(d / "ckpt-00000008")
    assert run_sim_main(["resume", str(d)]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["state_digest"] == ref["state_digest"]
    assert res["stats_digest"] == ref["stats_digest"]


@pytest.mark.slow  # remat x resume composition; the plain CLI resume test
# keeps the crash-resume law in tier-1
def test_cli_remat_run_resumes_bit_identically(tmp_path, capsys):
    """The local remat epoch loop composes with checkpointing: fold
    boundaries and checkpoint boundaries interleave, and a resumed run
    (including an epoch-boundary checkpoint that must replay its fold)
    matches the uninterrupted digests."""
    from tpu_gossip.cli.run_sim import main as run_sim_main

    base = ["--peers", "64", "--rounds", "12", "--slots", "4",
            "--fanout", "2", "--churn-leave", "0.1", "--churn-join",
            "0.4", "--rewire-slots", "2", "--remat-every", "3",
            "--quiet", "--digest"]
    d = tmp_path / "ck"
    assert run_sim_main(base + ["--checkpoint-every", "6",
                                "--checkpoint-dir", str(d)]) == 0
    full = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    assert run_sim_main(["resume", str(d)]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["state_digest"] == full["state_digest"]
    assert res["stats_digest"] == full["stats_digest"]


@pytest.mark.slow
def test_sigkill_mid_horizon_resume(tmp_path):
    """The real thing: a checkpointing subprocess SIGKILLed mid-horizon,
    resumed in a fresh process, digest-equal to an uninterrupted run.
    (The recovery-smoke CI job runs this same drill on the 8-CPU mesh
    against the sharded matching engine.)"""
    import os
    import signal
    import subprocess
    import sys as _sys
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    d = tmp_path / "ck"
    base = [_sys.executable, "-m", "tpu_gossip.cli.run_sim", "--peers",
            "96", "--rounds", "40", "--slots", "4", "--fanout", "2",
            "--quiet", "--digest"]
    ref = subprocess.run(base, capture_output=True, text=True, env=env,
                         timeout=300)
    assert ref.returncode == 0, ref.stderr
    want = json.loads(ref.stdout.strip().splitlines()[-1])

    proc = subprocess.Popen(
        base + ["--checkpoint-every", "10", "--checkpoint-dir", str(d)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 240
    while time.time() < deadline:
        if list_checkpoint_steps(d):
            break
        if proc.poll() is not None:
            break
        time.sleep(0.2)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    assert list_checkpoint_steps(d), "no checkpoint landed before the kill"

    res = subprocess.run(
        [_sys.executable, "-m", "tpu_gossip.cli.run_sim", "resume", str(d)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert res.returncode == 0, res.stderr
    got = json.loads(res.stdout.strip().splitlines()[-1])
    assert got["state_digest"] == want["state_digest"]
    assert got["stats_digest"] == want["stats_digest"]
