"""Sparsity-adaptive ICI transport (dist/transport.py): the compact lanes
must be invisible to the protocol — bit-identical state AND stats across
modes, scenarios, and growth on both shard engines — while the analytic
counter proves bytes actually left the wire. Runs on the virtual 8-device
CPU mesh (conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import dataclasses

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, preferential_attachment
from tpu_gossip.core.state import clone_state, init_swarm
from tpu_gossip.dist import (
    build_shard_plans,
    build_transport,
    init_sharded_swarm,
    make_mesh,
    partition_graph,
    run_until_coverage_dist,
    shard_matching_plan,
    shard_swarm,
    simulate_dist,
)
from tpu_gossip.dist.transport import (
    IciRound,
    accumulate_ici,
    compact_index,
    gather_compact,
    occupancy_counts,
    scatter_compact,
    zero_ici_totals,
)
from tpu_gossip.sim.engine import simulate

N = 997  # not divisible by 8: pad slots ride along


@pytest.fixture(scope="module")
def setup():
    g = build_csr(N, preferential_attachment(N, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=1)
    return mesh, sg, relabeled, position


@pytest.fixture(scope="module")
def matching_setup(matching_1500, mesh8):
    """The session-shared n=1500 sharded-matching build (tests/sim/
    conftest.py) — the same layout the dist parity suite runs on, so the
    multi-second build happens once per session, not once per module."""
    g, plan = matching_1500
    mesh = mesh8
    plan_m = shard_matching_plan(plan, mesh)
    return g, plan, plan_m, mesh, build_transport(plan_m, mode="sparse", mesh=mesh)


def _assert_same_run(fin_a, stats_a, fin_b, stats_b):
    """Full state + stats trajectory equality — the transport contract."""
    for f in ("seen", "alive", "rewired", "declared_dead", "recovered",
              "last_hb", "rewire_targets", "fault_held", "exists",
              "join_round", "admitted_by", "degree_credit"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_a, f)), np.asarray(getattr(fin_b, f)),
            err_msg=f,
        )
    for f in stats_a._fields:
        if f == "degree_gamma":
            # the one float reduction: documented to match across engines
            # to 1 ULP (growth engine, PR 5), not bit-for-bit
            np.testing.assert_allclose(
                np.asarray(stats_a.degree_gamma),
                np.asarray(stats_b.degree_gamma), rtol=5e-7, err_msg=f,
            )
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_a, f)), np.asarray(getattr(stats_b, f)),
            err_msg=f,
        )


# ------------------------------------------------------------ unit pieces
def test_compaction_round_trip_identity():
    """gather -> send -> scatter is the identity on occupied words, zeros
    elsewhere — the compact lane's whole correctness argument, at tiny n."""
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 5, size=(4, 24, 3)).astype(np.int32)
    payload[rng.random((4, 24)) < 0.7] = 0  # sparse
    occ = jax.numpy.asarray((payload != 0).any(-1))
    cap = int(np.asarray(occ).sum(axis=1).max())
    idx = compact_index(occ, cap)
    vals = gather_compact(jax.numpy.asarray(payload), idx)
    back = scatter_compact(idx, vals, 24)
    np.testing.assert_array_equal(np.asarray(back), payload)
    # header row: one count per destination, int32 — the declared spec
    counts = occupancy_counts(occ)
    assert counts.shape == (4,) and counts.dtype == np.int32
    np.testing.assert_array_equal(
        np.asarray(counts), (payload != 0).any(-1).sum(1)
    )


def test_compaction_overflow_goes_to_junk_column():
    """Entries past the budget land in the discarded junk column, never in
    a kept slot (the runtime gate prevents this case from shipping; the
    index math must still be safe when probed directly)."""
    occ = jax.numpy.asarray(np.ones((2, 10), dtype=bool))
    idx = np.asarray(compact_index(occ, 4))
    assert idx.shape == (2, 4)
    np.testing.assert_array_equal(idx, [[0, 1, 2, 3], [0, 1, 2, 3]])


@pytest.mark.parametrize("hubs", [0, 3], ids=["plain", "hub"])
def test_sparse_transpose_round_trip(hubs):
    """transpose_pass_sparse == transpose_pass_sharded on word-sparse data
    (and the untranspose twin), under the real shard_map harness — with an
    empty hub table (pure occupancy compaction) and with fully-dense hub
    rows riding the static sub-lane. The budget covers the nonzero WORD
    count, the engine gate's invariant (occupied rows per shard and per
    destination range are both bounded by it)."""
    import functools

    from jax.sharding import PartitionSpec as P

    from tpu_gossip.dist._compat import shard_map_compat
    from tpu_gossip.dist.transport import (
        transpose_pass_sparse, untranspose_pass_sparse,
    )
    from tpu_gossip.kernels.permute import (
        transpose_pass_sharded, untranspose_pass_sharded,
    )

    mesh = make_mesh(8)
    s, per = 8, 128
    r = s * per
    rng = np.random.default_rng(7)
    x = np.zeros((r, 128), dtype=np.int32)
    # ~20 scattered nonzero leaf words — well under the budget
    ii = rng.integers(0, r, 20)
    jj = rng.integers(0, 128, 20)
    x[ii, jj] = rng.integers(1, 1 << 20, 20)
    hub_local = np.sort(rng.choice(per, size=hubs, replace=False)).astype(np.int32)
    if hubs:
        # the SAME local rows on every shard are fully dense (the sharded
        # matching layout puts hub classes at identical block positions)
        for sh in range(s):
            x[sh * per + hub_local] = rng.integers(1, 1 << 20, (hubs, 128))
    x = jax.numpy.asarray(x)
    leaf_words = int(
        np.asarray((np.asarray(x) != 0)).sum()
    ) - hubs * s * 128
    cap = leaf_words + 4
    tbl_local = jax.numpy.asarray(np.broadcast_to(hub_local, (s, hubs)).copy())
    empty = jax.numpy.zeros((s, 0), dtype=jax.numpy.int32)
    # the untranspose's table space is OUTPUT slab rows: a dense input row
    # smears across up to 128 slab rows (the reason deep stages go
    # "plain"), so the hub case gives that pass the full per-dest budget —
    # which always fits — while the t pass exercises the real hub sub-lane
    cap_untr = per if hubs else cap
    for k, (sparse_fn, dense_fn, tbl, c) in enumerate((
        (transpose_pass_sparse, transpose_pass_sharded, tbl_local, cap),
        (untranspose_pass_sparse, untranspose_pass_sharded, empty, cap_untr),
    )):

        @functools.partial(
            shard_map_compat, mesh=mesh, in_specs=(P("peers"),),
            out_specs=P("peers"), check_vma=False,
        )
        def go(blk, fn=sparse_fn, t=tbl, c=c):
            return fn(blk, "peers", s, t, c)

        @functools.partial(
            shard_map_compat, mesh=mesh, in_specs=(P("peers"),),
            out_specs=P("peers"), check_vma=False,
        )
        def go_dense(blk, fn=dense_fn):
            return fn(blk, "peers", s)

        np.testing.assert_array_equal(
            np.asarray(jax.jit(go)(x)), np.asarray(jax.jit(go_dense)(x)),
            err_msg=f"pass {k}",
        )


def test_transport_rejects_mismatched_layout(setup, matching_setup):
    from tpu_gossip.dist.mesh import gossip_round_dist

    mesh, sg, relabeled, position = setup
    _, plan, plan_m, _, tr_match = matching_setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, fanout=2, mode="push")
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh
    )
    with pytest.raises(ValueError, match="matching family"):
        gossip_round_dist(st, cfg, sg, mesh, transport=tr_match)
    # and a transport from a different partition of the same sizes
    sg2, _, _ = partition_graph(
        build_csr(N, preferential_attachment(N, m=3, use_native=False)), 8,
        seed=99,
    )
    tr2 = build_transport(sg2, mode="sparse")
    with pytest.raises(ValueError, match="fingerprint"):
        gossip_round_dist(st, cfg, sg, mesh, transport=tr2)


# ------------------------------------------------- bucketed engine parity
@pytest.mark.parametrize(
    "mode,extra",
    [
        pytest.param("flood", {}, marks=pytest.mark.slow),
        pytest.param("push", {}, marks=pytest.mark.slow),
        pytest.param("push_pull", {}, marks=pytest.mark.slow),
        pytest.param("push_pull", dict(forward_once=True),
                     marks=pytest.mark.slow),
        ("push_pull", dict(churn_leave_prob=0.01, churn_join_prob=0.1,
                           rewire_slots=2)),
    ],  # churn (both lanes + re-wiring live) is the tier-1 witness; the
    # plainer modes assert the same compaction-invisibility law and ride
    # the slow lane with the fwd_once twin
    ids=["flood", "push", "push_pull", "push_pull_fwd_once",
         "push_pull_churn"],
)
def test_bucketed_sparse_bit_identical(setup, mode, extra):
    """Sparse vs dense transport on the bucketed engine: compaction
    reorders bytes, not draws — the full state + stats trajectory must be
    bit-identical in every mode, churn re-wiring included."""
    mesh, sg, relabeled, position = setup
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2, mode=mode,
                      **extra)
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0, 1],
                           key=jax.random.key(3)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 6)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 6, None, None, None, tr)
    _assert_same_run(fin_a, stats_a, fin_b, stats_b)


def test_bucketed_sparse_kernel_receive_bit_identical(setup):
    """The staircase-kernel receive streams the RECONSTRUCTED dense buffer
    — compact lane + kernel receive must still match the dense scatter."""
    mesh, sg, relabeled, position = setup
    plans = build_shard_plans(sg)
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2,
                      mode="push_pull")
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0, 1],
                           key=jax.random.key(3)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 6)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 6, plans, None, None, tr)
    _assert_same_run(fin_a, stats_a, fin_b, stats_b)


def test_bucketed_sparse_scenario_bit_identical(setup):
    """Every fault class active (loss + delay + partition + blackout +
    churn burst): the fault head wraps the dissemination core ABOVE the
    lane choice, so the trajectories must stay bit-identical."""
    from tests.sim.test_dist import _chaos_spec
    from tpu_gossip.faults import compile_scenario

    mesh, sg, relabeled, position = setup
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2,
                      mode="push_pull")
    sc = compile_scenario(
        _chaos_spec(), n_peers=N, n_slots=sg.n_pad, total_rounds=8,
        node_map=lambda ids: position[np.asarray(ids)],
    )
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0, 1],
                           key=jax.random.key(3)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 6, None, sc)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 6, None, sc, None, tr)
    _assert_same_run(fin_a, stats_a, fin_b, stats_b)
    assert np.asarray(stats_b.msgs_dropped).sum() > 0  # the chaos must bite


def test_bucketed_gate_falls_back_when_dense(setup):
    """A mid-epidemic state whose occupancy exceeds the budget must ride
    the dense lane at runtime (sparse_lanes == 0) and still be identical."""
    mesh, sg, relabeled, position = setup
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, mode="flood")
    st0 = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    # everyone transmits: every valid bucket entry is occupied
    st0 = dataclasses.replace(st0, seen=st0.seen.at[:, 0].set(st0.exists))
    st = shard_swarm(st0, mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 2)
    fin_b, (stats_b, ici) = simulate_dist(
        st, cfg, sg, mesh, 2, None, None, None, tr, True
    )
    _assert_same_run(fin_a, stats_a, fin_b, stats_b)
    assert int(np.asarray(ici.sparse_lanes)[0]) == 0
    assert int(np.asarray(ici.shipped_words)[0]) > int(
        np.asarray(ici.dense_words)[0]
    )  # dense + header: the fallback is priced honestly


# ------------------------------------------------- matching engine parity
# tier-1 keeps push_pull (both lanes live) as the parity witness; the
# other modes assert the same law and ride the slow lane
@pytest.mark.parametrize(
    "mode,extra",
    [
        pytest.param("flood", {}, marks=pytest.mark.slow),
        pytest.param("push", {}, marks=pytest.mark.slow),
        ("push_pull", {}),
        pytest.param("push_pull", dict(forward_once=True),
                     marks=pytest.mark.slow),
        pytest.param("push_pull", dict(sir_recover_rounds=2),
                     marks=pytest.mark.slow),
        pytest.param("push_pull", dict(churn_leave_prob=0.02,
                                       churn_join_prob=0.2, rewire_slots=2),
                     marks=pytest.mark.slow),
    ],
    ids=["flood", "push", "push_pull", "push_pull_fwd_once", "push_pull_sir",
         "push_pull_churn"],
)
def test_matching_sparse_bit_identical_to_local(matching_setup, mode, extra):
    """THE acceptance criterion, matching family: a sparse mesh round must
    be bit-identical to the LOCAL engine's round — the strongest statement
    available, since the dense mesh round already is."""
    g, plan, plan_m, mesh, tr = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode=mode,
                      **extra)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0, 5],
                    exists=g.exists, key=jax.random.key(3))
    fin_l, stats_l = simulate(clone_state(st), cfg, 5, plan)
    fin_d, (stats_d, ici) = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 5, None, None, None, tr,
        True,
    )
    _assert_same_run(fin_l, stats_l, fin_d, stats_d)
    # the sparse lane must actually run in the early phase, or the parity
    # above is vacuous
    assert int(np.asarray(ici.sparse_lanes)[0]) > 0


@pytest.mark.slow  # scenario composition of the parity law held in tier-1
# by the push_pull case
def test_matching_sparse_scenario_bit_identical(matching_setup):
    """Every fault class + sparse transport vs the local engine."""
    from tests.sim.test_dist import _chaos_spec
    from tpu_gossip.faults import compile_scenario

    g, plan, plan_m, mesh, tr = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull")
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0, 5],
                    exists=g.exists, key=jax.random.key(3))

    def rows_of(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    sc = compile_scenario(
        _chaos_spec(), n_peers=1500, n_slots=plan.n, total_rounds=8,
        node_map=rows_of,
    )
    fin_l, stats_l = simulate(clone_state(st), cfg, 6, plan, "fused", sc)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 6, None, sc, None, tr
    )
    _assert_same_run(fin_l, stats_l, fin_d, stats_d)
    assert np.asarray(stats_d.msgs_dropped).sum() > 0


@pytest.mark.slow  # growth composition of the parity law held in tier-1
# by the push_pull case
def test_matching_sparse_growing_bit_identical():
    """A GROWING sparse mesh run (the tests/sim/test_dist.py PR 4/5
    pattern): admissions ride advance_round outside the transport, so the
    membership extension of the parity contract holds under compaction."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.growth import compile_growth, matching_admit_rows

    mesh = make_mesh(8)
    g, plan = matching_powerlaw_graph_sharded(
        4000, 8, fanout=2, key=jax.random.key(0), growth_rows=16
    )
    plan_m = shard_matching_plan(plan, mesh)
    tr = build_transport(plan_m, mode="sparse", mesh=mesh)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull", rewire_slots=2)
    grow = compile_growth(
        n_initial=4000, target=4100, n_slots=plan.n, joins_per_round=16,
        attach_m=2, admit_rows=matching_admit_rows(plan, 100),
    )
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0, 5],
                    exists=g.exists, key=jax.random.key(3))
    fin_l, stats_l = simulate(clone_state(st), cfg, 8, plan, growth=grow)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 8, None, None, grow, tr
    )
    _assert_same_run(fin_l, stats_l, fin_d, stats_d)
    assert int(np.asarray(stats_d.n_members)[-1]) > 4000


# --------------------------------------------------------- ici accounting
@pytest.mark.slow  # multi-round billing curve; the parity witness asserts
# sparse_lanes > 0 so the tier-1 lane-activity guard remains
def test_ici_counter_early_phase_reduction():
    """The analytic counter: early-phase shipped bytes must undercut dense
    by >= 3x (the ROADMAP success metric, tracked from this PR on), and
    the trajectory must go dense mid-epidemic. Needs a swarm big enough
    for the per-lane header + hub sub-lane overhead to amortize (at the
    tier-1 fixture's n=1500 the fixed overhead eats the early-phase win),
    so this slow-lane test keeps its own n=6000 build."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )

    g, plan = matching_powerlaw_graph_sharded(
        6000, 8, fanout=2, key=jax.random.key(0)
    )
    mesh = make_mesh(8)
    plan_m = shard_matching_plan(plan, mesh)
    tr = build_transport(plan_m, mode="sparse", mesh=mesh)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode="push_pull")
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0],
                    exists=g.exists, key=jax.random.key(3))
    _, (stats, ici) = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 10, None, None, None, tr,
        True,
    )
    dense = np.asarray(ici.dense_words).astype(np.int64)
    shipped = np.asarray(ici.shipped_words).astype(np.int64)
    assert dense[0] >= 3 * shipped[0], (dense[0], shipped[0])
    assert (shipped <= dense + np.asarray(ici.total_lanes) * 16 * 3).all()
    # mid-epidemic rounds fall back to dense (plus the tiny header)
    assert (np.asarray(ici.sparse_lanes) < np.asarray(ici.total_lanes)).any()


def test_ici_coverage_totals_accumulate(setup):
    mesh, sg, relabeled, position = setup
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2, mode="push")
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh
    )
    fin, tot = run_until_coverage_dist(
        st, cfg, sg, mesh, 0.99, 100, transport=tr, collect_ici=True
    )
    rounds = int(fin.round)
    words = tot.words()
    assert rounds > 0
    assert words["total_lanes"] == rounds  # one gated exchange per push round
    assert words["shipped_words"] < words["dense_words"]


def test_ici_totals_accumulator_exact_past_int32():
    """The while-carry totals ride a hi/lo int32 pair: folding in 100
    rounds of 3e7 dense words each must read back the exact 3e9 total —
    a plain int32 sum wraps negative at this (1M-matching-realistic)
    scale."""
    import jax.numpy as jnp

    one = IciRound(
        jnp.int32(30_000_000), jnp.int32(7_654_321), jnp.int32(123_456),
        jnp.int32(5), jnp.int32(6),
        jnp.int32(25_000_000), jnp.int32(4_321_987),
    )
    tot = zero_ici_totals()
    step = jax.jit(accumulate_ici)
    for _ in range(100):
        tot = step(tot, one)
    words = tot.words()
    assert words["dense_words"] == 3_000_000_000
    assert words["shipped_words"] == 765_432_100
    assert words["occupied_words"] == 12_345_600
    assert words["sparse_lanes"] == 500
    assert words["total_lanes"] == 600
    assert words["dcn_dense_words"] == 2_500_000_000
    assert words["dcn_shipped_words"] == 432_198_700


def test_auto_mode_is_bit_identical_too(setup):
    mesh, sg, relabeled, position = setup
    tr = build_transport(sg, mode="auto")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2,
                      mode="push_pull")
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0],
                           key=jax.random.key(1)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 4)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 4, None, None, None, tr)
    _assert_same_run(fin_a, stats_a, fin_b, stats_b)
