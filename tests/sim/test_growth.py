"""Growth engine (growth/): in-round preferential-attachment joins.

The membership plane's contracts, each test one rail:

- admission reaches the target and fills the registry plane;
- attachment is genuinely degree-preferential (hubs attract joiners);
- a zero-join / exhausted schedule reproduces the fixed-n trajectory BIT
  FOR BIT (the growth stream is derived, never drawn from the protocol's
  5-way split);
- a growing run is bit-identical local vs sharded on the matching engine
  (full state + integer-stat trajectory; the γ track to float reduction
  tolerance) — the acceptance criterion;
- the running γ-MLE of a grown swarm lands in the tolerance band of the
  init-time generator's γ;
- mid-growth checkpoints resume bit-exactly; pre-growth checkpoints load
  with the registry plane zeroed;
- scenario ``join_burst`` phases compose admission waves with churn;
- ``rematerialize_rewired`` folds growth edges into the CSR and zeroes
  the credit (the realized degree vector never double-counts);
- run_sim rejects impossible --grow configs with exit 2.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.state import (
    SwarmConfig,
    clone_state,
    init_swarm,
    load_swarm,
    save_swarm,
)
from tpu_gossip.core.topology import (
    build_csr,
    fit_powerlaw_gamma,
    preferential_attachment,
)
from tpu_gossip.growth import (
    GrowthError,
    compile_growth,
    matching_admit_rows,
    pad_graph_for_growth,
)
from tpu_gossip.growth.engine import hill_gamma_device, realized_degrees
from tpu_gossip.sim.engine import rematerialize_rewired, remat_capacity, simulate

N0, CAP = 64, 128
ATTACH = 3


def seed_graph(n=N0, m=ATTACH, seed=0):
    return build_csr(
        n, preferential_attachment(n, m=m, use_native=False,
                                   rng=np.random.default_rng(seed))
    )


def grown_setup(n0=N0, cap=CAP, target=None, rate=8, attach=ATTACH, seed=0,
                **cfg_kw):
    """(cfg, state, growth) over a flat padded layout."""
    target = cap if target is None else target
    graph, exists = pad_graph_for_growth(seed_graph(n0), cap)
    cfg = SwarmConfig(
        n_peers=cap, msg_slots=4, fanout=2, mode="push_pull",
        rewire_slots=max(attach, cfg_kw.pop("rewire_slots", 0)), **cfg_kw,
    )
    st = init_swarm(graph, cfg, origins=[0], exists=jnp.asarray(exists),
                    key=jax.random.key(seed))
    gp = compile_growth(
        n_initial=n0, target=target, n_slots=cap, joins_per_round=rate,
        attach_m=attach,
    )
    return cfg, st, gp


def test_growth_admits_to_target_and_fills_registry():
    cfg, st, gp = grown_setup()
    fin, stats = simulate(st, cfg, 12, None, "fused", None, gp)
    members = np.asarray(stats.n_members)
    assert members[0] == N0 + 8 and members[-1] == CAP
    assert (np.diff(members) >= 0).all()
    ex = np.asarray(fin.exists)
    assert ex.all()  # capacity == target here: every slot admitted
    grown = np.arange(N0, CAP)
    jr = np.asarray(fin.join_round)
    assert (jr[:N0] == 0).all()
    assert (jr[grown] >= 1).all()
    # admission order is schedule order: join rounds are non-decreasing
    assert (np.diff(jr[grown]) >= 0).all()
    # every joiner recorded its admitting seed (an existing member) and
    # attached ATTACH fresh edges onto the re-wiring plane
    ab = np.asarray(fin.admitted_by)
    assert (ab[grown] >= 0).all() and (ab[grown] < CAP).all()
    assert np.asarray(fin.rewired)[grown].all()
    tg = np.asarray(fin.rewire_targets)[grown, :ATTACH]
    assert (tg >= 0).all()
    # per-joiner targets are distinct (Gumbel-top-k samples WITHOUT
    # replacement) and never the joiner itself
    for row, t in zip(grown, tg):
        assert len(set(t.tolist())) == ATTACH
        assert row not in t
    # joiners are live protocol participants
    assert np.asarray(fin.alive)[grown].all()
    assert not np.asarray(fin.declared_dead)[grown].any()
    # degree credit counts the IN side (+1 per fresh edge at its target);
    # the joiners' own side is their stored targets, so realized degrees
    # see both endpoints of every growth edge
    assert np.asarray(fin.degree_credit).sum() == ATTACH * len(grown)
    deg = np.asarray(realized_degrees(fin.row_ptr, fin.exists, fin.rewired,
                                      fin.rewire_targets, fin.degree_credit))
    base = np.asarray(fin.row_ptr[1:] - fin.row_ptr[:-1])
    assert (deg[grown] >= ATTACH).all()
    assert deg.sum() == base[:N0].sum() + 2 * ATTACH * len(grown)


def test_growth_attachment_is_degree_preferential():
    """Hubs of the seed graph must attract far more growth edges than
    leaves — the defining preferential-attachment bias (reference
    demonstrate_powerlaw.py / Seed.get_peer_subset 'powerlaw')."""
    graph = seed_graph(200, seed=3)
    pg, exists = pad_graph_for_growth(graph, 600)
    cfg = SwarmConfig(n_peers=600, msg_slots=1, fanout=2, mode="push",
                      rewire_slots=ATTACH)
    st = init_swarm(pg, cfg, origins=[0], exists=jnp.asarray(exists),
                    key=jax.random.key(2))
    gp = compile_growth(n_initial=200, target=600, n_slots=600,
                        joins_per_round=40, attach_m=ATTACH)
    fin, _ = simulate(st, cfg, 12, None, "fused", None, gp)
    credit = np.asarray(fin.degree_credit)[:200]
    deg0 = graph.degrees
    top = np.argsort(deg0)[-10:]
    bottom = np.argsort(deg0)[:100]
    # 10 hubs out-attract 100 leaves per capita by a wide margin
    assert credit[top].mean() > 3 * credit[bottom].mean(), (
        credit[top].mean(), credit[bottom].mean(),
    )


@pytest.mark.parametrize(
    "shape",
    ["empty", pytest.param("exhausted", marks=pytest.mark.slow)],
)  # one zero-join witness in tier-1; the exhausted twin rides slow
def test_zero_join_growth_is_bit_identical_to_fixed_n(shape):
    """THE determinism rail: a growth schedule with nothing to admit —
    zero-total or already exhausted — must reproduce the growth=None
    trajectory bit for bit (the growth stream is a parallel fold_in
    derivation; the protocol's 5-way split never moves)."""
    cfg, st, gp = grown_setup(churn_leave_prob=0.02, churn_join_prob=0.2)
    if shape == "empty":
        gp0 = compile_growth(n_initial=N0, target=N0, n_slots=CAP,
                             joins_per_round=8, attach_m=ATTACH)
        st0 = clone_state(st)
        base, _ = simulate(clone_state(st), cfg, 10)
        grown, _ = simulate(st0, cfg, 10, None, "fused", None, gp0)
    else:
        # run the schedule dry, then compare continuation with/without it
        mid, _ = simulate(st, cfg, 10, None, "fused", None, gp)
        assert np.asarray(mid.exists).all()
        base, _ = simulate(clone_state(mid), cfg, 8)
        grown, _ = simulate(mid, cfg, 8, None, "fused", None, gp)
    for f in type(base).__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(base, f)) if f != "rng"
            else np.asarray(jax.random.key_data(base.rng)),
            np.asarray(getattr(grown, f)) if f != "rng"
            else np.asarray(jax.random.key_data(grown.rng)),
            err_msg=f,
        )


# --- the acceptance criterion: growing local vs sharded, bit-identical ---


@pytest.fixture(scope="module")
def matching_growth_setup():
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import make_mesh, shard_matching_plan

    g, plan = matching_powerlaw_graph_sharded(
        800, 8, fanout=2, key=jax.random.key(0), growth_rows=32,
    )
    mesh = make_mesh(8)
    return g, plan, shard_matching_plan(plan, mesh), mesh


@pytest.mark.parametrize(
    "mode,extra",
    [
        pytest.param("push_pull", {}, marks=pytest.mark.slow),
        pytest.param("push_pull",
                     dict(churn_leave_prob=0.02, churn_join_prob=0.2),
                     marks=pytest.mark.slow),
        ("flood", {}),
    ],
    ids=["push_pull", "push_pull_churn", "flood"],
)  # one growing-run parity witness in tier-1; dearer modes ride slow
def test_matching_growth_local_vs_sharded_bit_identical(
    matching_growth_setup, mode, extra
):
    """A GROWING run is bit-identical local vs sharded on the matching
    engine: same admissions, same PA draws (global-shape Gumbel-top-k),
    same registry — full state + integer-stat trajectory equality; the
    γ-MLE track (the one float reduction) agrees to reduction tolerance.
    """
    from tpu_gossip.dist import shard_swarm, simulate_dist

    g, plan, plan_m, mesh = matching_growth_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=4, fanout=2, mode=mode,
                      rewire_slots=ATTACH, **extra)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0, 5],
                    exists=g.exists, key=jax.random.key(3))
    gp = compile_growth(
        n_initial=800, target=960, n_slots=plan.n, joins_per_round=16,
        attach_m=ATTACH, admit_rows=matching_admit_rows(plan, 160),
    )
    fin_l, stats_l = simulate(clone_state(st), cfg, 8, plan, "fused",
                              None, gp)
    fin_d, stats_d = simulate_dist(shard_swarm(st, mesh), cfg, plan_m,
                                   mesh, 8, None, None, gp)
    for f in ("seen", "exists", "alive", "rewired", "declared_dead",
              "recovered", "last_hb", "rewire_targets", "join_round",
              "admitted_by", "degree_credit", "fault_held"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_l, f)), np.asarray(getattr(fin_d, f)),
            err_msg=f,
        )
    for f in ("msgs_sent", "coverage", "n_members", "n_alive",
              "n_declared_dead"):
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_l, f)), np.asarray(getattr(stats_d, f)),
            err_msg=f,
        )
    np.testing.assert_allclose(
        np.asarray(stats_l.degree_gamma), np.asarray(stats_d.degree_gamma),
        rtol=1e-5,
    )
    assert np.asarray(stats_l.n_members)[-1] == 928  # 800 + 8*16
    # admissions stayed inside the reserved rows (pads/sentinels dead)
    leaked = np.asarray(fin_l.exists) & ~np.asarray(g.exists)
    allowed = set(matching_admit_rows(plan, 160).tolist())
    assert set(np.nonzero(leaked)[0].tolist()) <= allowed


def test_matching_growth_admissions_spread_across_shards(
    matching_growth_setup,
):
    g, plan, plan_m, mesh = matching_growth_setup
    rows = matching_admit_rows(plan, 80)
    shards = rows // (plan.n_blk)
    counts = np.bincount(shards, minlength=8)
    assert counts.max() - counts.min() <= 1  # round-robin balance


# --- degree evolution: the grown tail matches the generator's ------------


@pytest.mark.slow  # statistical gamma-fit demonstration; the growth
# bit-identity and admission laws stay tier-1
def test_grown_swarm_gamma_matches_generator():
    """Grow a BA seed 4k -> 24k by in-round PA (attach_m = the
    generator's m) and demand the realized degree tail's γ-MLE land
    within the tolerance band of the init-time generator's γ at the
    grown size — the degree-evolution acceptance criterion at tier-1
    scale (the 100k -> 1M version of this test is marked slow below)."""
    n0, target, m = 4000, 24000, 3
    graph = seed_graph(n0, m=m, seed=1)
    pg, exists = pad_graph_for_growth(graph, target)
    cfg = SwarmConfig(n_peers=target, msg_slots=1, fanout=2, mode="push",
                      rewire_slots=m)
    st = init_swarm(pg, cfg, origins=[0], exists=jnp.asarray(exists),
                    key=jax.random.key(7))
    gp = compile_growth(n_initial=n0, target=target, n_slots=target,
                        joins_per_round=128, attach_m=m)
    rounds = (target - n0) // 128 + 2
    fin, stats = simulate(st, cfg, rounds, None, "fused", None, gp)
    assert np.asarray(stats.n_members)[-1] == target
    deg = np.asarray(realized_degrees(fin.row_ptr, fin.exists, fin.rewired,
                     fin.rewire_targets, fin.degree_credit))
    gamma_grown = fit_powerlaw_gamma(deg[np.asarray(fin.exists)])
    ref = build_csr(
        target,
        preferential_attachment(target, m=m, use_native=False,
                                rng=np.random.default_rng(2)),
    )
    gamma_ref = fit_powerlaw_gamma(ref.degrees)
    # observed |Δγ| ~ 0.01 at this scale; 0.25 is the stochastic band
    assert abs(gamma_grown - gamma_ref) < 0.25, (gamma_grown, gamma_ref)
    # the device-side running track ends at the host fitter's value
    assert abs(np.asarray(stats.degree_gamma)[-1] - gamma_grown) < 1e-3


def test_device_gamma_track_matches_host_estimator():
    cfg, st, gp = grown_setup()
    fin, _ = simulate(st, cfg, 12, None, "fused", None, gp)
    deg = realized_degrees(fin.row_ptr, fin.exists, fin.rewired,
                     fin.rewire_targets, fin.degree_credit)
    live = fin.alive & ~fin.declared_dead
    dev = float(hill_gamma_device(deg, live, 4))
    host = fit_powerlaw_gamma(np.asarray(deg)[np.asarray(live)], d_min=4)
    assert abs(dev - host) < 1e-4


@pytest.mark.slow
def test_grown_swarm_gamma_matches_generator_1m():
    """The acceptance criterion at headline scale: 100k -> 1M. The
    per-round Gumbel matrix is (1024, 1M) — an accelerator-scale job
    (hours of CPU), hence slow-marked; the tier-1 twin above runs the
    identical machinery at 4k -> 24k."""
    n0, target, m = 100_000, 1_000_000, 3
    graph = seed_graph(n0, m=m, seed=1)
    pg, exists = pad_graph_for_growth(graph, target)
    cfg = SwarmConfig(n_peers=target, msg_slots=1, fanout=2, mode="push",
                      rewire_slots=m)
    st = init_swarm(pg, cfg, origins=[0], exists=jnp.asarray(exists),
                    key=jax.random.key(7))
    gp = compile_growth(n_initial=n0, target=target, n_slots=target,
                        joins_per_round=1024, attach_m=m)
    rounds = (target - n0) // 1024 + 2
    fin, stats = simulate(st, cfg, rounds, None, "fused", None, gp)
    assert np.asarray(stats.n_members)[-1] == target
    deg = np.asarray(realized_degrees(fin.row_ptr, fin.exists, fin.rewired,
                     fin.rewire_targets, fin.degree_credit))
    gamma_grown = fit_powerlaw_gamma(deg[np.asarray(fin.exists)])
    ref = build_csr(
        target,
        preferential_attachment(target, m=m,
                                rng=np.random.default_rng(2)),
    )
    gamma_ref = fit_powerlaw_gamma(ref.degrees)
    assert abs(gamma_grown - gamma_ref) < 0.15, (gamma_grown, gamma_ref)


# --- checkpointing (satellite: the registry plane round-trips) -----------


@pytest.mark.slow  # the ckpt matrices + mid-stream twin keep
# mid-flight resume in tier-1; this compose rides slow
def test_mid_growth_checkpoint_resumes_bit_exactly(tmp_path):
    cfg, st, gp = grown_setup()
    mid, _ = simulate(st, cfg, 4, None, "fused", None, gp)
    assert N0 < int(np.asarray(mid.exists).sum()) < CAP  # genuinely mid-growth
    save_swarm(tmp_path / "mid.npz", mid)
    restored = load_swarm(tmp_path / "mid.npz")
    for f in ("join_round", "admitted_by", "degree_credit"):
        np.testing.assert_array_equal(
            np.asarray(getattr(mid, f)), np.asarray(getattr(restored, f)),
            err_msg=f,
        )
    fin_a, _ = simulate(mid, cfg, 8, None, "fused", None, gp)
    fin_b, _ = simulate(restored, cfg, 8, None, "fused", None, gp)
    for f in ("seen", "exists", "join_round", "admitted_by",
              "degree_credit", "rewire_targets", "alive"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_a, f)), np.asarray(getattr(fin_b, f)),
            err_msg=f,
        )
    assert int(np.asarray(fin_b.exists).sum()) == CAP  # resume finished the schedule


def test_pre_growth_checkpoint_loads_with_registry_zeroed(tmp_path):
    """A checkpoint saved before the growth engine existed (no registry
    keys) loads with the plane zeroed — every existing row a bootstrap
    member, capacity == n — and still runs."""
    g = seed_graph(32)
    cfg = SwarmConfig(n_peers=32, msg_slots=4)
    st = init_swarm(g, cfg, origins=[1])
    mid, _ = simulate(st, cfg, 3)
    save_swarm(tmp_path / "new.npz", mid)
    data = dict(np.load(tmp_path / "new.npz"))
    for k in ("field_join_round", "field_admitted_by",
              "field_degree_credit"):
        assert k in data
        del data[k]  # forge the pre-growth format
    np.savez(tmp_path / "old.npz", **data)
    restored = load_swarm(tmp_path / "old.npz")
    ex = np.asarray(restored.exists)
    assert (np.asarray(restored.join_round)[ex] == 0).all()
    assert (np.asarray(restored.join_round)[~ex] == -1).all()
    assert (np.asarray(restored.admitted_by) == -1).all()
    assert not np.asarray(restored.degree_credit).any()
    fin, _ = simulate(restored, cfg, 3)
    assert int(fin.round) == 6


def test_v1_checkpoint_loads_with_registry_zeroed(tmp_path):
    """The round-1 positional layout predates the registry plane too."""
    from tests.unit.test_state import save_v1

    g = seed_graph(32)
    st = init_swarm(g, SwarmConfig(n_peers=32), origins=[2])
    save_v1(st, tmp_path / "v1.npz", per_peer_sir=True)
    restored = load_swarm(tmp_path / "v1.npz")
    assert (np.asarray(restored.join_round) == 0).all()  # v1 exists all-True
    assert (np.asarray(restored.admitted_by) == -1).all()
    assert not np.asarray(restored.degree_credit).any()


# --- scenario composition: join_burst admission waves --------------------


def test_join_burst_phase_adds_admissions():
    """A join_burst phase is an admission WAVE on top of the schedule's
    rate — churn storms and growth waves compose in one scenario."""
    from tpu_gossip.faults import compile_scenario, scenario_from_dict

    cfg, st, gp = grown_setup(rate=2)
    spec = scenario_from_dict({"name": "wave", "phases": [
        {"name": "w", "start": 2, "end": 5, "join_burst": 6},
    ]})
    gp = compile_growth(n_initial=N0, target=CAP, n_slots=CAP,
                        joins_per_round=2, attach_m=ATTACH,
                        max_join_burst=spec.max_join_burst)
    sc = compile_scenario(spec, n_peers=N0, n_slots=CAP, total_rounds=12)
    _, stats = simulate(clone_state(st), cfg, 12, None, "fused", sc, gp)
    members = np.asarray(stats.n_members)
    per_round = np.diff(np.concatenate([[N0], members]))
    np.testing.assert_array_equal(per_round[:2], [2, 2])
    np.testing.assert_array_equal(per_round[2:5], [8, 8, 8])  # 2 + 6 wave
    assert (per_round[5:] <= 2).all()
    # and it composes with a simultaneous churn storm
    spec2 = scenario_from_dict({"name": "storm+wave", "phases": [
        {"name": "sw", "start": 2, "end": 5, "join_burst": 6,
         "churn_leave": 0.2},
    ]})
    sc2 = compile_scenario(spec2, n_peers=N0, n_slots=CAP, total_rounds=12)
    fin2, stats2 = simulate(clone_state(st), cfg, 12, None, "fused", sc2, gp)
    members2 = np.asarray(stats2.n_members)
    assert members2[4] == members[4]  # admissions unaffected by the storm
    assert np.asarray(stats2.n_alive)[4] < np.asarray(stats.n_alive)[4]


def test_growth_composes_with_churn_rewire():
    """Growing while Poisson churn + re-wiring runs: both planes share
    the rewire tables without clobbering the other's semantics."""
    cfg, st, gp = grown_setup(churn_leave_prob=0.05, churn_join_prob=0.3)
    fin, stats = simulate(st, cfg, 16, None, "fused", None, gp)
    assert np.asarray(stats.n_members)[-1] == CAP
    assert np.asarray(stats.n_alive)[-1] > CAP * 0.6
    assert float(fin.coverage(0)) > 0.5


# --- remat: growth edges fold into the CSR -------------------------------


def test_remat_folds_growth_edges_and_zeroes_credit():
    cfg, st, gp = grown_setup()
    cap = remat_capacity(st, cfg)
    mid, _ = simulate(st, cfg, 12, None, "fused", None, gp)
    deg_before = np.asarray(
        realized_degrees(mid.row_ptr, mid.exists, mid.rewired,
                     mid.rewire_targets, mid.degree_credit)
    )
    folded, overflow = rematerialize_rewired(mid, cfg, cap)
    assert int(overflow) == 0
    assert not np.asarray(folded.rewired).any()
    assert not np.asarray(folded.degree_credit).any()
    deg_after = np.asarray(
        realized_degrees(folded.row_ptr, folded.exists, folded.rewired,
                     folded.rewire_targets, folded.degree_credit)
    )
    # the realized degree vector is preserved by the fold: credit became
    # real CSR edges, both endpoints
    np.testing.assert_array_equal(deg_before, deg_after)
    # and the folded swarm keeps gossiping at static-topology cost
    fin, _ = simulate(folded, cfg, 6, None, "fused", None, gp)
    assert float(fin.coverage(0)) > 0.9


def test_credit_books_balance_under_churn_rejoin():
    """A grown peer that churn-rejoins overwrites its fresh targets — the
    credit those edges granted must be RELEASED with them (the phantom-
    credit leak a review found: without the release, PA weights and the γ
    track are biased and the fold shrinks degrees silently). The balance
    invariant: total degree_credit == total valid stored targets of
    rewired rows; never negative; and the fold preserves realized degrees
    EXACTLY on rewired rows while non-rewired rows lose exactly their
    stale CSR edges into rewired rows."""
    cfg, st, gp = grown_setup(churn_leave_prob=0.05, churn_join_prob=0.5)
    cap = remat_capacity(st, cfg)
    mid, _ = simulate(st, cfg, 12, None, "fused", None, gp)
    credit = np.asarray(mid.degree_credit)
    rew = np.asarray(mid.rewired)
    tg = np.asarray(mid.rewire_targets)
    assert (credit >= 0).all()
    assert rew.any() and credit.sum() == (tg[rew] >= 0).sum()

    deg_before = np.asarray(realized_degrees(
        mid.row_ptr, mid.exists, mid.rewired, mid.rewire_targets,
        mid.degree_credit,
    ))
    row_ptr = np.asarray(mid.row_ptr)
    col_idx = np.asarray(mid.col_idx)
    stale = np.asarray([
        rew[col_idx[row_ptr[r]:row_ptr[r + 1]]].sum()
        for r in range(len(rew))
    ])
    folded, _ = rematerialize_rewired(mid, cfg, cap)
    assert not np.asarray(folded.degree_credit).any()
    deg_after = np.asarray(realized_degrees(
        folded.row_ptr, folded.exists, folded.rewired,
        folded.rewire_targets, folded.degree_credit,
    ))
    np.testing.assert_array_equal(deg_after[rew], deg_before[rew])
    np.testing.assert_array_equal(
        deg_after[~rew], deg_before[~rew] - stale[~rew]
    )


# --- validation ----------------------------------------------------------


def test_compile_growth_rejects_impossible_schedules():
    with pytest.raises(GrowthError, match="below initial"):
        compile_growth(n_initial=64, target=32, n_slots=128,
                       joins_per_round=4, attach_m=2)
    with pytest.raises(GrowthError, match="never grow"):
        compile_growth(n_initial=64, target=128, n_slots=128,
                       joins_per_round=0, attach_m=2)
    with pytest.raises(GrowthError, match="initial peers"):
        compile_growth(n_initial=4, target=16, n_slots=16,
                       joins_per_round=2, attach_m=4)
    with pytest.raises(GrowthError, match="row space"):
        compile_growth(n_initial=64, target=128, n_slots=100,
                       joins_per_round=4, attach_m=2)
    with pytest.raises(GrowthError, match="twice"):
        compile_growth(n_initial=64, target=66, n_slots=128,
                       joins_per_round=4, attach_m=2,
                       admit_rows=np.asarray([70, 70]))


def test_apply_growth_rejects_narrow_rewire_plane():
    """attach_m wider than the state's rewire_targets is a config error
    at trace time, mirroring validate_rewire_width."""
    cfg, st, gp = grown_setup()
    st = dataclasses.replace(st, rewire_targets=st.rewire_targets[:, :1])
    with pytest.raises(ValueError, match="rewire_slots"):
        simulate(st, cfg, 2, None, "fused", None, gp)


def test_matching_admit_rows_rejects_overflow(matching_growth_setup):
    _, plan, _, _ = matching_growth_setup
    with pytest.raises(GrowthError, match="growth_rows"):
        matching_admit_rows(plan, 8 * 32 + 1)


# --- CLI -----------------------------------------------------------------


def _run(argv):
    from tpu_gossip.cli.run_sim import main

    return main(argv)


def test_cli_grow_rejections(tmp_path, capsys):
    base = ["--peers", "64", "--rounds", "8", "--slots", "2", "--quiet"]
    assert _run(base + ["--grow", "32"]) == 2
    assert _run(base + ["--grow", "128", "--grow-capacity", "100"]) == 2
    # (--grow --profile-round now composes: the growth-stage row —
    # pinned in tests/unit/test_profiling.py)
    assert _run(base + ["--grow", "128", "--shard", "--remat-every", "4"]) == 2
    assert _run(base + ["--grow", "128", "--m", "64"]) == 2
    # join_burst without --grow
    wave = tmp_path / "wave.toml"
    wave.write_text(
        "[scenario]\nname = 'w'\n[[phase]]\nname = 'w'\nstart = 0\n"
        "end = 4\njoin_burst = 4\n"
    )
    assert _run(base + ["--scenario", str(wave)]) == 2
    # node-scoped sets beyond the INITIAL membership (satellite: parse-time
    # error, not a jit failure)
    bad = tmp_path / "bad.toml"
    bad.write_text(
        "[scenario]\nname = 'b'\n[[phase]]\nname = 'b'\nstart = 0\n"
        "end = 4\nblackout = {ids = [100]}\n"
    )
    assert _run(base + ["--grow", "128", "--scenario", str(bad)]) == 2
    err = capsys.readouterr().err
    assert "INITIAL --peers" in err


def test_cli_grow_smoke_local(capsys):
    rc = _run(["--peers", "64", "--grow", "96", "--grow-rate", "8",
               "--rounds", "10", "--slots", "2", "--m", "2", "--quiet"])
    assert rc == 0
    import json

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["n_members"] == 96
    assert out["grow_target"] == 96
    assert out["degree_gamma"] is None or out["degree_gamma"] > 1.0
