"""The stage-DAG driver (sim/stages.py): declared-carry enforcement at
trace time, stage composition per config, and the driver's equivalence
to the public ``advance_round`` (which now runs on it)."""

import jax.numpy as jnp
import pytest

from tpu_gossip.core.state import SwarmConfig
from tpu_gossip.sim.stages import Stage, build_round_stages, run_stages


def test_undeclared_read_raises():
    st = Stage(
        "bad", reads=("a",), writes=("out",),
        fn=lambda ctx: {"out": ctx["b"]},  # reads b without declaring it
    )
    with pytest.raises(ValueError, match="reads carry 'b'"):
        run_stages((st,), {"a": 1, "b": 2})


def test_undeclared_write_raises():
    st = Stage(
        "bad", reads=("a",), writes=("out",),
        fn=lambda ctx: {"out": ctx["a"], "sneaky": 1},
    )
    with pytest.raises(ValueError, match="undeclared carries \\['sneaky'\\]"):
        run_stages((st,), {"a": 1})


def test_missing_carry_raises():
    st = Stage("bad", reads=("nope",), writes=(), fn=lambda ctx: {})
    with pytest.raises(ValueError, match="declares reads \\['nope'\\]"):
        run_stages((st,), {"a": 1})


def test_stages_run_in_order_and_update_carries():
    a = Stage("a", reads=("x",), writes=("y",),
              fn=lambda ctx: {"y": ctx["x"] + 1})
    b = Stage("b", reads=("y",), writes=("y",),
              fn=lambda ctx: {"y": ctx["y"] * 10})
    values = run_stages((a, b), {"x": 4})
    assert values["y"] == 50


def test_round_dag_composition_per_config():
    """The stage list mirrors the config: absent subsystems contribute no
    stage; present ones land in protocol order (liveness → churn →
    growth → age-out → tail → inject → control)."""
    base = SwarmConfig(n_peers=64, msg_slots=4)
    names = [s.name for s in build_round_stages(base)]
    assert names == ["liveness", "tail"]

    churn = SwarmConfig(n_peers=64, msg_slots=4, churn_leave_prob=0.01,
                        churn_join_prob=0.1)
    names = [s.name for s in build_round_stages(churn)]
    assert names == ["liveness", "churn", "tail"]

    # a burst scenario forces the churn stage even at zero configured churn
    names = [s.name for s in build_round_stages(
        base, has_faults=True, churn_faults=True
    )]
    assert names == ["liveness", "churn", "tail"]

    class _FakeStream:
        ttl = 4

    class _FakeControl:
        pass

    class _FakeGrowth:
        attach_m = 0

    names = [s.name for s in build_round_stages(
        churn, growth=_FakeGrowth(), stream=_FakeStream(),
        control=_FakeControl(),
    )]
    assert names == [
        "liveness", "churn", "growth", "stream_ageout", "tail",
        "stream_inject", "control",
    ]


def test_growth_stage_validates_attach_width():
    class _FakeGrowth:
        attach_m = 3

    cfg = SwarmConfig(n_peers=64, msg_slots=4, rewire_slots=1)
    with pytest.raises(ValueError, match="attach_m"):
        build_round_stages(cfg, growth=_FakeGrowth())


def test_stage_view_is_a_mapping():
    st = Stage("m", reads=("a", "b"), writes=(), fn=lambda ctx: {})
    from tpu_gossip.sim.stages import StageView

    view = StageView({"a": 1, "b": 2, "c": 3}, st)
    assert dict(view) == {"a": 1, "b": 2}
    assert len(view) == 2


def test_declarations_cover_real_round():
    """Every stage of a fully-composed config declares carries that the
    initial set + earlier stages satisfy (the driver would raise inside
    jit otherwise — this pins it cheaply, without a trace)."""
    cfg = SwarmConfig(n_peers=64, msg_slots=4, churn_leave_prob=0.01,
                      churn_join_prob=0.1, rewire_slots=2)
    initial = {
        "row_ptr", "col_idx", "seen", "forwarded", "infected_round",
        "recovered", "exists", "alive", "silent", "last_hb",
        "declared_dead", "rewired", "rewire_targets", "join_round",
        "admitted_by", "degree_credit", "slot_lease", "control_lvl",
        "rng", "incoming", "transmit", "receptive", "rnd", "k_leave",
        "k_join", "faults", "fstats", "rctl", "seen_prev", "held",
        "fresh", "expired", "stel", "ctel",
    }

    class _FakeStream:
        ttl = 4

    class _FakeControl:
        pass

    class _FakeGrowth:
        attach_m = 2

    have = set(initial)
    for st in build_round_stages(
        cfg, has_faults=True, churn_faults=True, growth=_FakeGrowth(),
        stream=_FakeStream(), control=_FakeControl(),
    ):
        missing = set(st.reads) - have
        assert not missing, (st.name, missing)
        have |= set(st.writes)


def test_jnp_available_in_stage_bodies():
    """Smoke: stage fns run under tracing (they're plain callables)."""
    st = Stage("t", reads=("x",), writes=("y",),
               fn=lambda ctx: {"y": jnp.asarray(ctx["x"]) + 1})
    out = run_stages((st,), {"x": 1})
    assert int(out["y"]) == 2
