"""Bounded-table rewire side paths (SwarmConfig.rewire_compact_cap): same
fresh-edge semantics as the dense paths at O(cap) access cost, with
documented bandwidth-capping when over-subscribed."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm
from tpu_gossip.core.state import clone_state
from tpu_gossip.core.topology import configuration_model, powerlaw_degree_sequence
from tpu_gossip.kernels.pallas_segment import build_staircase_plan
from tpu_gossip.sim.engine import simulate
from tpu_gossip.sim.metrics import rounds_to_coverage


def test_compact_stale_and_fresh_semantics_kernel_path():
    """The 3-node invariants (stale CSR blocked both ways, fresh edges carry
    both ways) hold verbatim with the compact side paths on."""
    g = build_csr(3, np.array([[0, 1]]))
    cfg = SwarmConfig(n_peers=3, msg_slots=4, fanout=1, mode="push",
                      rewire_slots=1, rewire_compact_cap=2)
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=1)
    st = init_swarm(g, cfg, origins=[0])
    rw = dataclasses.replace(
        st,
        seen=st.seen.at[2, 1].set(True),
        rewired=st.rewired.at[1].set(True),
        rewire_targets=st.rewire_targets.at[1, 0].set(2),
    )
    fin, _ = simulate(clone_state(rw), cfg, 5, plan)
    seen = np.asarray(fin.seen)
    assert not seen[1, 0] and not seen[2, 0], "stale CSR push leaked (compact)"
    assert seen[1, 1], "reverse-fresh push lost (compact)"

    rw_origin1 = dataclasses.replace(
        clone_state(rw), seen=st.seen.at[1, 2].set(True)
    )
    fin_fresh, _ = simulate(rw_origin1, cfg, 5, plan)
    assert bool(fin_fresh.seen[2, 2]), "fresh-edge push lost (compact)"

    cfg_pp = dataclasses.replace(cfg, mode="push_pull")
    fin_pull, _ = simulate(clone_state(rw), cfg_pp, 5, plan)
    assert bool(fin_pull.seen[1, 1]), "fresh-edge pull lost (compact)"


def test_compact_caps_serviced_rows_deterministically():
    """Over-subscription: with cap=1 and two rewired senders, only the
    lowest-index one's fresh target is served this round."""
    # two disjoint pairs 0-1, 2-3 plus isolated receivers 4, 5
    g = build_csr(6, np.array([[0, 1], [2, 3]]))
    cfg = SwarmConfig(n_peers=6, msg_slots=4, fanout=2, mode="push",
                      rewire_slots=1, rewire_compact_cap=1)
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=2)
    st = init_swarm(g, cfg, origins=None)
    rw = dataclasses.replace(
        st,
        # both rewired peers carry private rumors destined for fresh targets
        seen=st.seen.at[1, 1].set(True).at[3, 2].set(True),
        rewired=st.rewired.at[jnp.asarray([1, 3])].set(True),
        rewire_targets=st.rewire_targets.at[1, 0].set(4).at[3, 0].set(5),
    )
    fin, _ = simulate(rw, cfg, 1, plan)
    seen = np.asarray(fin.seen)
    assert seen[4, 1], "the in-cap rewired row's fresh push was dropped"
    assert not seen[5, 2], "cap=1 must not service the second rewired row"


def test_compact_caps_joiner_rewiring_per_round():
    """At most cap joiners become rewired per round; the rest rejoin on
    their slot's existing edges (rewired stays False for them)."""
    n = 500
    g = build_csr(n, configuration_model(
        powerlaw_degree_sequence(n, gamma=2.5, rng=np.random.default_rng(2)),
        rng=np.random.default_rng(3)))
    cap = 8
    cfg = SwarmConfig(
        n_peers=n, msg_slots=4, fanout=2, mode="push_pull",
        churn_leave_prob=0.0, churn_join_prob=1.0, rewire_slots=2,
        rewire_compact_cap=cap,
    )
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(4))
    # kill half the swarm; with join_prob=1 they ALL rejoin next round.
    # Mark the dead slots as PREVIOUSLY rewired with stale targets: an
    # over-cap rejoiner must not inherit the departed occupant's fresh
    # edge as its only link (it rejoins on its slot's CSR edges instead)
    dead = jnp.arange(0, n, 2)
    st = dataclasses.replace(
        st,
        alive=st.alive.at[dead].set(False),
        rewired=st.rewired.at[dead].set(True),
        rewire_targets=st.rewire_targets.at[dead, :].set(7),
    )
    fin, _ = simulate(st, cfg, 1)
    assert int(jnp.sum(fin.alive)) == n  # everyone rejoined...
    assert int(jnp.sum(fin.rewired)) == cap  # ...but only cap re-wired
    rw = np.asarray(fin.rewired)
    tg = np.asarray(fin.rewire_targets)
    # re-wired rows drew fresh targets; over-cap rejoiners cleared both the
    # inherited flag AND the departed occupant's stale targets
    assert ((tg[rw] == -1) | (tg[rw] >= 0)).all() and (tg[rw] >= 0).any()
    joined_uncapped = np.asarray(dead)[~rw[np.asarray(dead)]]
    assert (tg[joined_uncapped] == -1).all(), (
        "over-cap rejoiner kept the departed occupant's fresh targets"
    )


@pytest.mark.slow  # full-curve comparison; the kernel-path semantics test
# below is the tier-1 compact-rewire witness
def test_compact_curves_match_dense_paths():
    """Statistical parity: BASELINE config 5 dynamics through the compact
    side paths (kernel delivery) match the dense XLA path — median
    rounds-to-target within 2 over 5 seeds, like every cross-path bound."""
    g = build_csr(3000, configuration_model(
        powerlaw_degree_sequence(3000, gamma=2.5, rng=np.random.default_rng(51)),
        rng=np.random.default_rng(52)))
    base = dict(
        n_peers=3000, msg_slots=4, fanout=1, mode="push_pull",
        churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
    )
    cfg_dense = SwarmConfig(**base)
    cfg_compact = SwarmConfig(**base, rewire_compact_cap=512)
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=1)

    def rounds(cfg, use_plan, seed, target):
        st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
        _, stats = simulate(st, cfg, 40, plan if use_plan else None)
        return rounds_to_coverage(stats, target)

    for target in (0.5, 0.95):
        dense = [rounds(cfg_dense, False, s, target) for s in range(5)]
        comp = [rounds(cfg_compact, True, s, target) for s in range(5)]
        assert all(r > 0 for r in dense + comp), (dense, comp)
        assert abs(np.median(dense) - np.median(comp)) <= 2.0, (
            target, dense, comp,
        )