"""Dedup collision semantics (VERDICT r4 item 5, docs/dedup_semantics.md):
R > M conflation behaves as specified, the accounting helpers match
empirical rates, and the k-hash Bloom mode trades conflation for the
documented false-positive law."""

import numpy as np

from tpu_gossip.compat.simnet import SimCluster
from tpu_gossip.compat.peer import PeerNode
from tpu_gossip.core.state import message_slot, message_slots
from tpu_gossip.sim.metrics import (
    bloom_false_positive_rate,
    expected_conflations,
)


def _cluster(n=40, msg_slots=8, **kw):
    cluster = SimCluster(msg_slots=msg_slots, fanout=3, mode="push", **kw)
    peers = [
        PeerNode(f"10.0.0.{i}", 9000, transport="tpu-sim", cluster=cluster)
        for i in range(n)
    ]
    cluster.materialize(m=3)
    return cluster, peers


def test_rumors_sharing_a_slot_are_conflated():
    """R > M regime: two rumors in one slot are indistinguishable — seeing
    one reads as having seen both. This IS the documented semantics."""
    cluster, peers = _cluster()
    m = 8
    # find two distinct rumor ids that collide
    a = "rumor-a"
    b = next(
        f"probe-{i}" for i in range(1000)
        if f"probe-{i}" != a
        and message_slot(f"probe-{i}", m) == message_slot(a, m)
    )
    peers[0].gossip(a)
    assert peers[0].has_seen(a)
    assert peers[0].has_seen(b)  # conflation: same slot
    # a rumor in a DIFFERENT slot is not conflated
    c = next(
        f"probe2-{i}" for i in range(1000)
        if message_slot(f"probe2-{i}", m) != message_slot(a, m)
    )
    assert not peers[0].has_seen(c)
    # conflated rumors share one coverage curve
    cluster.step(12)
    assert cluster.coverage(a) == cluster.coverage(b) > 0.5


def test_expected_conflations_matches_empirical():
    m = 64
    rng = np.random.default_rng(0)
    trials = 400
    for r in (8, 32, 128):
        got = 0
        for t in range(trials):
            ids = rng.integers(0, 2**31, size=r)
            slots = [message_slot(int(x) ^ (t << 40), m) for x in ids]
            got += r - len(set(slots))
        emp = got / trials
        want = expected_conflations(r, m)
        assert abs(emp - want) < max(0.25 * want, 0.6), (r, emp, want)


def test_bloom_mode_false_positive_law():
    """k=2 Bloom dedup: insert R rumors, measure P(novel rumor reads seen)
    against the closed form."""
    m, k, r = 64, 2, 20
    cluster, peers = _cluster(n=6, msg_slots=m, dedup_hashes=k)
    p = peers[0]
    for i in range(r):
        p.gossip(f"known-{i}")
        assert p.has_seen(f"known-{i}")  # no false negatives, ever
    probes = 2000
    fp = sum(p.has_seen(f"novel-{j}") for j in range(probes)) / probes
    want = bloom_false_positive_rate(r, m, k)
    assert abs(fp - want) < 0.06, (fp, want)


def test_bloom_mode_coverage_propagates():
    """k=2 bits both propagate: coverage(text) under Bloom mode reaches the
    swarm like single-slot mode does."""
    cluster, peers = _cluster(n=40, msg_slots=64, dedup_hashes=2)
    peers[0].gossip("hello-bloom")
    cluster.step(15)
    assert cluster.coverage("hello-bloom") > 0.9


def test_message_slots_planes_are_distinct_hashes():
    m = 4096
    collide = sum(
        len(set(message_slots(f"x-{i}", m, 2))) == 1 for i in range(2000)
    )
    # planes agree only at the ~1/M chance level
    assert collide < 10


def test_int_id_hash_planes_independent():
    """Regression: an affine per-plane mix of integer ids cancels modulo a
    power-of-two M, collapsing k>1 Bloom dedup to k=1 conflation. Integer
    ids that collide in plane 0 must not systematically collide in plane 1."""
    m = 16
    base = message_slots(0, m, 2)
    both = sum(
        message_slots(i, m, 2) == base
        for i in range(0, 16 * 400, 16)  # ids congruent mod M
    )
    # independent planes: P(both match) ~ 1/M per id; affine planes: all match
    assert both < 60
