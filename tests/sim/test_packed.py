"""Packed runs are BIT-IDENTICAL to unpacked runs — the tentpole contract.

The packed entry points keep the scan/while carry as the registry's
packed storage ledger and run each round as unpack -> the identical
round program -> repack, so equality here is strong evidence the codec
is exact AND that nothing in the round path leaks representation. The
matrix cells below compose every optional plane (chaos scenario, growth,
stream, control, quorum/adversary, pipeline) on the local engine and the
sharded matching mesh; the durability half pins packed checkpoints
against both legacy formats and the sharded store.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from tpu_gossip.analysis.entrypoints import (
    _chaos_scenario,
    _control_plan,
    _growth_plan,
    _quorum_spec,
    _stream_plan,
)
from tpu_gossip.core.packed import PackedSwarm, pack_state, unpack_state
from tpu_gossip.core.state import (
    SwarmConfig,
    clone_state,
    init_swarm,
    load_swarm,
    save_swarm,
)
from tpu_gossip.core.topology import (
    build_csr,
    configuration_model,
    powerlaw_degree_sequence,
)
from tpu_gossip.sim.engine import run_until_coverage, simulate

N = 300


def _graph(n=N):
    rng = np.random.default_rng(0)
    return build_csr(
        n, configuration_model(
            powerlaw_degree_sequence(n, gamma=2.5, rng=rng), rng=rng
        )
    )


def _assert_states_equal(a, b, where=""):
    for f in dataclasses.fields(type(a)):
        x, y = getattr(a, f.name), getattr(b, f.name)
        if f.name == "rng":
            assert (jax.random.key_data(x) == jax.random.key_data(y)).all()
        else:
            assert bool((x == y).all()), f"{where}: {f.name}"


def _assert_stats_equal(a, b, where=""):
    for name, x, y in zip(a._fields, a, b):
        assert bool((np.asarray(x) == np.asarray(y)).all()), f"{where}: {name}"


# ----------------------------------------------------- local composed matrix
@pytest.mark.slow  # the maximal composed cell; the dist composed packed
# parity below keeps the packed-plane law in tier-1
def test_packed_simulate_bit_identical_maximal_cell():
    """Packed vs unpacked `simulate` on ONE maximal composed cell —
    chaos faults (loss + delay + blackout) AND Byzantine attacks in the
    scenario, growth, stream, control, and the quorum detector all
    active, full final state + every per-round stat bit for bit. One
    compile pair covers every optional stage's packed carry (a plain
    cell is subsumed by the coverage-loop test below; the pipelined
    swap is pinned by the mesh composed cell)."""
    from tpu_gossip.faults import compile_scenario, scenario_from_dict

    g = _graph()
    sc = compile_scenario(scenario_from_dict({
        "name": "packed-maximal",
        "phases": [
            {"name": "lossy", "start": 0, "end": 3, "loss": 0.2,
             "delay": 0.2},
            {"name": "siege", "start": 3, "end": 7,
             "accusers": {"frac": 0.05, "seed": 3},
             "forgers": {"frac": 0.02, "seed": 4},
             "floods": {"frac": 0.03, "seed": 5},
             "blackout": {"frac": 0.1, "seed": 2},
             "forge_fanout": 2, "flood_fanout": 3},
        ],
    }), n_peers=N, n_slots=N, total_rounds=8)
    kw = dict(
        scenario=sc,
        growth=_growth_plan(N, N - 40),
        stream=_stream_plan(16, np.ones(N, bool)),
        control=_control_plan(ttl=8),
        liveness=_quorum_spec(),
    )
    cfg = SwarmConfig(n_peers=N, msg_slots=16, fanout=1, mode="push_pull",
                      churn_join_prob=0.02, churn_leave_prob=0.002,
                      rewire_slots=2)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(2))
    fin_u, stats_u = simulate(clone_state(st), cfg, 8, **kw)
    fin_p, stats_p = simulate(pack_state(st), cfg, 8, **kw)
    assert isinstance(fin_p, PackedSwarm)
    _assert_states_equal(fin_u, unpack_state(fin_p), "maximal")
    _assert_stats_equal(stats_u, stats_p, "maximal")


def test_packed_coverage_loop_bit_identical():
    g = _graph()
    cfg = SwarmConfig(n_peers=N, msg_slots=16, fanout=2, mode="push_pull",
                      sir_recover_rounds=6)
    st = init_swarm(g, cfg, origins=[0, 1], key=jax.random.key(1))
    fin_u = run_until_coverage(clone_state(st), cfg, 0.95, 60)
    fin_p = run_until_coverage(pack_state(st), cfg, 0.95, 60)
    fin_pu = unpack_state(fin_p)
    assert int(fin_u.round) == int(fin_pu.round)
    _assert_states_equal(fin_u, fin_pu, "coverage")


# ------------------------------------------------------------ the mesh half
@pytest.fixture(scope="module")
def mesh_fixture():
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import make_mesh, shard_matching_plan, shard_swarm

    mesh = make_mesh()
    if 128 % mesh.size:
        pytest.skip(f"mesh size {mesh.size} does not divide 128")
    dg, plan = matching_powerlaw_graph_sharded(
        256, mesh.size, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=16, fanout=1,
                      mode="push_pull")
    st = init_swarm(dg.as_padded_graph(), cfg, origins=[0],
                    exists=dg.exists, key=jax.random.key(0))
    return mesh, plan, cfg, shard_swarm(st, mesh), shard_matching_plan(
        plan, mesh
    )


@pytest.mark.slow  # composed dist cell; CI builder-smoke runs this file
# unfiltered, and the plain packed parity tests stay in tier-1
def test_packed_dist_matching_bit_identical_composed(mesh_fixture):
    """Packed vs unpacked `simulate_dist` on the matching mesh with
    scenario + stream + pipeline composed — the packed carry keeps the
    peer-axis sharding and the mesh trajectory bit for bit."""
    from tpu_gossip.dist import simulate_dist
    from tpu_gossip.sim.stages import compile_pipeline

    mesh, plan, cfg, st, splan = mesh_fixture
    kw = dict(
        scenario=_chaos_scenario(plan.n, 256),
        stream=_stream_plan(16, np.asarray(st.exists)),
        pipeline=compile_pipeline(1),
    )
    fin_u, stats_u = simulate_dist(clone_state(st), cfg, splan, mesh, 6,
                                   **kw)
    # pack a CLONE: pack_state aliases the pass-through leaves (row_ptr,
    # infected_round, ...), so donating the packed pytree would delete
    # the module fixture's buffers under the next test
    p = pack_state(clone_state(st))
    # the packed pytree keeps the peer-axis sharding (row-parallel codec)
    assert "peers" in str(p.seen.sharding)
    fin_p, stats_p = simulate_dist(p, cfg, splan, mesh, 6, **kw)
    _assert_states_equal(fin_u, unpack_state(fin_p), "dist")
    _assert_stats_equal(stats_u, stats_p, "dist")


@pytest.mark.slow
def test_packed_dist_coverage_loop(mesh_fixture):
    """(Slow-marked: two more while-loop compiles; the local coverage
    twin and the packed dist scan above carry the tier-1 pin.)"""
    from tpu_gossip.dist import run_until_coverage_dist

    mesh, _plan, cfg, st, splan = mesh_fixture
    fin_u = run_until_coverage_dist(clone_state(st), cfg, splan, mesh,
                                    0.9, 40)
    fin_p = run_until_coverage_dist(pack_state(clone_state(st)), cfg,
                                    splan, mesh, 0.9, 40)
    _assert_states_equal(fin_u, unpack_state(fin_p), "dist-coverage")


# -------------------------------------------------- packed-plane durability
def test_pre_packing_named_npz_loads_losslessly(tmp_path):
    """A pre-packing (unpacked-plane) named npz — the format every
    checkpoint on disk before this PR uses — loads bit-losslessly, and
    packing the loaded state round-trips."""
    g = _graph(64)
    cfg = SwarmConfig(n_peers=64, msg_slots=8, fanout=2)
    st = init_swarm(g, cfg, origins=[3], key=jax.random.key(9))
    path = tmp_path / "old.npz"
    arrays = {}
    for f in dataclasses.fields(type(st)):
        leaf = getattr(st, f.name)
        if f.name == "rng":
            arrays["prngkey_rng"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"field_{f.name}"] = np.asarray(leaf)
    np.savez(path, **arrays)  # the OLD writer's layout, verbatim
    loaded = load_swarm(path)
    _assert_states_equal(st, loaded, "pre-packing npz")
    _assert_states_equal(st, unpack_state(pack_state(loaded)), "repack")


def test_packed_npz_roundtrip_and_smaller(tmp_path):
    g = _graph(64)
    cfg = SwarmConfig(n_peers=64, msg_slots=16, fanout=2)
    st = init_swarm(g, cfg, origins=[3], key=jax.random.key(9))
    new = tmp_path / "new.npz"
    save_swarm(new, st)
    _assert_states_equal(st, load_swarm(new), "packed npz")
    # the packed payload stores the five bit planes + flags word packed
    data = np.load(new)
    assert data["field_seen"].dtype == np.uint8
    assert data["field_seen"].shape == (64, 2)
    assert data["field_flags"].dtype == np.uint8
    assert "field_alive" not in data.files


def test_pre_packing_sharded_checkpoint_loads(tmp_path):
    """A format-2 (unpacked) sharded-store checkpoint — written here with
    the old plane layout and a format-2 manifest — loads bit-losslessly
    through the format-3 reader."""
    import hashlib
    import io

    from tpu_gossip.ckpt.store import load_checkpoint

    g = _graph(60)
    cfg = SwarmConfig(n_peers=60, msg_slots=8, fanout=2)
    st = init_swarm(g, cfg, origins=[2], key=jax.random.key(5))
    host = {}
    for f in dataclasses.fields(type(st)):
        leaf = getattr(st, f.name)
        host[f.name] = (
            np.asarray(jax.random.key_data(leaf)) if f.name == "rng"
            else np.asarray(leaf)
        )
    ck = tmp_path / "ckpt-00000003"
    ck.mkdir(parents=True)
    files = {}

    def put(name, arrays):
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        (ck / name).write_bytes(payload)
        files[name] = {"sha256": hashlib.sha256(payload).hexdigest(),
                       "bytes": len(payload)}
        return files[name]

    from tpu_gossip.ckpt.store import _global_planes, _row_planes

    rp = host["row_ptr"]
    shard = {f"rows_{p}": host[p] for p in _row_planes(packed=False)}
    shard["rows_row_ptr"] = rp
    shard["rows_col_idx"] = host["col_idx"][: int(rp[-1])]
    put("shard-00000-of-00001.npz", shard)["rows"] = [0, 60]
    gl = {f"field_{p}": host[p] for p in _global_planes() if p != "rng"}
    gl["prngkey_rng"] = host["rng"]
    gl["col_tail"] = host["col_idx"][int(rp[-1]):]
    put("global.npz", gl)
    manifest = {
        "format": 2, "kind": "run", "round": 3, "files": files,
        "n_peers": 60, "msg_slots": 8, "shards": 1,
        "planes": {},
    }
    (ck / "MANIFEST.json").write_text(json.dumps(manifest))
    loaded, _stats, mf = load_checkpoint(ck)
    assert mf["format"] == 2
    _assert_states_equal(st, loaded, "format-2 store")


def test_packed_store_roundtrip_bit_exact_and_resharded(tmp_path):
    """The format-3 (packed) store round-trips bit-exactly at any file
    shard count — packing is along the slot axis, so row slicing
    commutes with it — and accepts a PackedSwarm directly (the packed
    driver's periodic-save path)."""
    from tpu_gossip.ckpt.store import load_checkpoint, save_checkpoint

    g = _graph(96)
    cfg = SwarmConfig(n_peers=96, msg_slots=16, fanout=2, mode="push_pull",
                      churn_join_prob=0.02, churn_leave_prob=0.01,
                      rewire_slots=2)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(3))
    st, _ = simulate(st, cfg, 5)
    for s, state_in in ((1, st), (3, st), (4, pack_state(st))):
        d = tmp_path / f"s{s}"
        save_checkpoint(d, state_in, step=5, shards=s)
        loaded, _, mf = load_checkpoint(d / "ckpt-00000005")
        assert mf["format"] == 3 and mf["msg_slots"] == 16
        _assert_states_equal(st, loaded, f"s={s}")
