"""Engine tests: dissemination curves, liveness state machine, SIR, churn
(SURVEY.md §4 'simulation/integration' tier — deterministic, CPU-only)."""

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
from tpu_gossip.core.state import clone_state
from tpu_gossip.sim.engine import gossip_round, run_until_coverage, simulate

N = 512


@pytest.fixture(scope="module")
def graph():
    return build_csr(N, preferential_attachment(N, m=3, use_native=False))


def make(graph, **kw):
    cfg = SwarmConfig(n_peers=N, msg_slots=8, **kw)
    return cfg, init_swarm(graph, cfg, origins=[0])


def test_push_reaches_full_coverage(graph):
    cfg, st = make(graph)
    fin, stats = simulate(st, cfg, 25)
    cov = np.asarray(stats.coverage)
    assert cov[-1] >= 0.99
    # epidemic growth: coverage is monotone non-decreasing without churn/SIR
    assert np.all(np.diff(cov) >= -1e-6)


def test_flood_covers_in_diameter_rounds(graph):
    cfg, st = make(graph, mode="flood")
    _, stats = simulate(st, cfg, 8)
    # flooding a BA graph (diameter ~ log N) must cover almost immediately
    assert float(stats.coverage[4]) == 1.0


def test_push_pull_faster_than_push(graph):
    cfg_p, st_p = make(graph)
    cfg_pp, st_pp = make(graph, mode="push_pull")
    r_p = int(run_until_coverage(st_p, cfg_p, 0.99, 100).round)
    r_pp = int(run_until_coverage(st_pp, cfg_pp, 0.99, 100).round)
    assert r_pp <= r_p


def test_run_until_coverage_matches_scan_curve(graph):
    cfg, st = make(graph)
    fin = run_until_coverage(clone_state(st), cfg, 0.99, 100)
    rounds = int(fin.round)
    _, stats = simulate(st, cfg, rounds)
    cov = np.asarray(stats.coverage)
    assert cov[rounds - 1] >= 0.99
    assert rounds < 2 or cov[rounds - 2] < 0.99


def test_determinism(graph):
    cfg, st = make(graph)
    a, sa = simulate(clone_state(st), cfg, 10)
    b, sb = simulate(st, cfg, 10)
    np.testing.assert_array_equal(np.asarray(a.seen), np.asarray(b.seen))
    np.testing.assert_array_equal(np.asarray(sa.coverage), np.asarray(sb.coverage))


def test_dedup_no_reinfection(graph):
    """Hash-slot dedup: a seen bit never unsets, and infected_round is latched."""
    cfg, st = make(graph)
    mid, _ = simulate(st, cfg, 5)
    fin, _ = simulate(clone_state(mid), cfg, 5)
    m_seen = np.asarray(mid.seen)
    f_seen = np.asarray(fin.seen)
    assert np.all(f_seen[m_seen])  # no bit lost
    ir_mid = np.asarray(mid.infected_round)
    ir_fin = np.asarray(fin.infected_round)
    assert np.all(ir_fin[ir_mid >= 0] == ir_mid[ir_mid >= 0])  # latched


def test_forward_once_spreads_then_stops(graph):
    """Relay-once mode: dissemination still spreads widely, and message
    complexity is bounded — once every holder has relayed, sends cease."""
    cfg, st = make(graph, forward_once=True, fanout=4)
    _, stats = simulate(st, cfg, 40)
    assert float(stats.coverage[-1]) >= 0.7
    msgs = np.asarray(stats.msgs_sent)
    assert msgs[-1] == 0  # everyone forwarded already; no chatter forever
    assert msgs.sum() < 4 * N  # ≤ fanout sends per peer total


def test_silent_peer_declared_dead_on_schedule(graph):
    """Silent peers (fault injection, Peer.py:437-439) must be declared dead at
    the first detector sweep after the stale threshold: timeout 6 rounds +
    sweep every 2 ⇒ round 8 (the reference's 30-42 s worst case, §6)."""
    cfg, st = make(graph)
    st.silent = st.silent.at[:50].set(True)
    _, stats = simulate(st, cfg, 12)
    dead = np.asarray(stats.n_declared_dead)
    assert dead[6] == 0  # not yet stale at round 7 sweep boundary
    assert dead[7] == 50  # declared at round 8 sweep
    assert dead[-1] == 50  # no false positives ever


def test_healthy_peers_never_declared_dead(graph):
    cfg, st = make(graph)
    _, stats = simulate(st, cfg, 30)
    assert int(stats.n_declared_dead[-1]) == 0


def test_crashed_peers_detected_and_excluded(graph):
    cfg, st = make(graph)
    st.alive = st.alive.at[100:200].set(False)  # keep origin (peer 0) alive
    fin, stats = simulate(st, cfg, 15)
    assert int(stats.n_declared_dead[-1]) == 100
    # coverage is over live peers only, so it can still reach ~1
    assert float(stats.coverage[-1]) >= 0.95


def test_sir_recovery_halts_transmission(graph):
    cfg, st = make(graph, sir_recover_rounds=1, fanout=1)
    fin, stats = simulate(st, cfg, 50)
    # 1-round infectious period with fanout 1 on a sparse graph: epidemic
    # dies out well below full coverage
    assert float(stats.coverage[-1]) < 0.9
    rec = np.asarray(fin.recovered)  # (N, M): per-slot removal
    seen = np.asarray(fin.seen)
    assert rec.sum() > 0
    assert np.all(seen[rec])  # only infected slots recover


def test_sir_recovery_is_per_slot(graph):
    """A peer removed from one rumor must still receive and relay others
    (the round-1 bug: global `recovered` made the first recovery block ALL
    slots forever)."""
    import dataclasses

    cfg, st = make(graph, sir_recover_rounds=4, mode="push_pull", fanout=3)
    fin, stats = simulate(st, cfg, 30)
    # everyone who saw slot 0 has recovered from it by now
    assert np.asarray(fin.recovered)[:, 0].sum() > 0.9 * N
    # inject a SECOND rumor (slot 1) after the first epidemic is over
    seen = fin.seen.at[7, 1].set(True)
    infected = fin.infected_round.at[7, 1].set(
        fin.round.astype(fin.infected_round.dtype)
    )
    st2 = dataclasses.replace(fin, seen=seen, infected_round=infected)
    fin2, _ = simulate(st2, cfg, 30)
    cov1 = np.asarray(fin2.seen)[:, 1].mean()
    assert cov1 > 0.9, f"slot-1 epidemic stalled at {cov1} — recovery leaked across slots"


def test_rewired_peers_attach_degree_preferentially(graph):
    """BASELINE config 5: rejoining peers draw fresh neighbors with
    probability proportional to degree (endpoint-list sampling)."""
    cfg, st = make(
        graph, churn_leave_prob=0.08, churn_join_prob=0.4, rewire_slots=4,
        mode="push_pull",
    )
    fin, _ = simulate(st, cfg, 60)
    rewired = np.asarray(fin.rewired)
    assert rewired.sum() > 30, "not enough rejoin events to test"
    targets = np.asarray(fin.rewire_targets)[rewired].ravel()
    deg = np.asarray(fin.row_ptr[1:] - fin.row_ptr[:-1])
    # endpoint sampling is size-biased: E[deg(target)] = E[d^2]/E[d] > E[d]
    expected = (deg.astype(float) ** 2).sum() / deg.sum()
    got = deg[targets].mean()
    assert got > 0.6 * expected, (got, expected)
    assert got > 1.5 * deg.mean(), (got, deg.mean())
    # rejoiners stay in the swarm: most rewired live peers are re-infected
    alive_rw = rewired & np.asarray(fin.alive)
    if alive_rw.sum() > 10:
        assert np.asarray(fin.seen).any(-1)[alive_rw].mean() > 0.5


def test_stale_edges_blocked_fresh_edges_bidirectional():
    """Re-wiring semantics: a rejoined slot's old CSR edges (the departed
    occupant's) carry NOTHING either way; the rejoiner's fresh edges carry
    traffic BOTH ways, like the TCP connections a socket rejoin opens
    (ADVICE r2: push leaked over stale edges; the naive symmetric fix made
    rejoiners unreachable in push mode)."""
    import dataclasses

    # path 0-1, isolated 2: CSR neighbor of 0 is 1; rewired 1 attaches to 2
    g = build_csr(3, np.array([[0, 1]]))
    cfg = SwarmConfig(n_peers=3, msg_slots=4, fanout=1, mode="push", rewire_slots=1)
    st = init_swarm(g, cfg, origins=[0])
    rw = dataclasses.replace(
        st,
        seen=st.seen.at[2, 1].set(True),  # second rumor at the fresh target
        rewired=st.rewired.at[1].set(True),
        rewire_targets=st.rewire_targets.at[1, 0].set(2),
    )
    fin, _ = simulate(clone_state(rw), cfg, 5)
    seen = np.asarray(fin.seen)
    # stale CSR edge 0->1 delivers nothing (slot 0 never reaches 1 or 2)
    assert not seen[1, 0] and not seen[2, 0], "stale CSR push leaked"
    # reverse-fresh: target 2's rumor reaches the rejoiner over 1's edge
    assert seen[1, 1], "reverse-fresh push lost — rejoiner unreachable"

    # the rejoiner's OWN traffic flows outward over its fresh edge
    rw_origin1 = dataclasses.replace(
        clone_state(rw), seen=st.seen.at[1, 2].set(True)
    )
    fin_fresh, _ = simulate(rw_origin1, cfg, 5)
    assert bool(fin_fresh.seen[2, 2]), "fresh-edge push from a rewired peer lost"

    # pull over a fresh edge delivers too (push_pull, rewired puller)
    cfg_pp = dataclasses.replace(cfg, mode="push_pull")
    fin_pull, _ = simulate(clone_state(rw), cfg_pp, 5)
    assert bool(fin_pull.seen[1, 1]), "fresh-edge pull by a rewired peer lost"

    # sanity: with the rewire flag cleared the CSR edge infects peer 1 again
    st2 = dataclasses.replace(rw, rewired=rw.rewired.at[1].set(False))
    fin2, _ = simulate(st2, cfg, 5)  # last use of rw's leaves
    assert bool(fin2.seen[1, 0])


def test_heavy_churn_swarm_sustains_coverage():
    """Under sustained churn + re-wiring most slots eventually hold
    rejoiners; bidirectional fresh edges must keep the swarm connected
    (directional fresh edges collapsed push coverage to ~0.2)."""
    g = build_csr(2000, preferential_attachment(2000, m=3, use_native=False,
                                                rng=np.random.default_rng(31)))
    cfg = SwarmConfig(
        n_peers=2000, msg_slots=4, fanout=3, mode="push",
        churn_leave_prob=0.05, churn_join_prob=0.3, rewire_slots=4,
    )
    st = init_swarm(g, cfg, origins=list(range(5)), key=jax.random.key(9))
    _, stats = simulate(st, cfg, 40)
    assert float(stats.coverage[-1]) > 0.7, float(stats.coverage[-1])


def test_sentinel_rewire_draws_are_invalidated():
    """Endpoint draws landing on padding edges (DeviceGraph sentinel row) must
    not become fan-out targets (ADVICE r2)."""
    from tpu_gossip.core.device_topology import device_powerlaw_graph

    dg = device_powerlaw_graph(300, gamma=2.5, key=jax.random.key(3))
    cfg = SwarmConfig(
        n_peers=dg.n_pad, msg_slots=4, churn_leave_prob=0.1,
        churn_join_prob=0.5, rewire_slots=4, mode="push_pull",
    )
    st = init_swarm(dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists)
    fin, _ = simulate(st, cfg, 40)
    rewired = np.asarray(fin.rewired)
    assert rewired.sum() > 10
    targets = np.asarray(fin.rewire_targets)[rewired].ravel()
    exists = np.asarray(fin.exists)
    ok = (targets == -1) | ((targets >= 0) & exists[np.maximum(targets, 0)])
    assert ok.all(), "a rewire target points at the sentinel/padding row"


def test_narrow_rewire_targets_fails_loudly():
    """Resuming with cfg.rewire_slots wider than the stored rewire_targets
    must raise instead of silently clamping (ADVICE r2)."""
    g = build_csr(8, preferential_attachment(8, m=2, use_native=False))
    cfg_narrow = SwarmConfig(n_peers=8, msg_slots=4, rewire_slots=1)
    st = init_swarm(g, cfg_narrow, origins=[0])  # rewire_targets width 1
    cfg_wide = SwarmConfig(n_peers=8, msg_slots=4, rewire_slots=3)
    with pytest.raises(ValueError, match="rewire_slots"):
        gossip_round(st, cfg_wide)


def test_churn_join_resets_state(graph):
    cfg, st = make(graph, churn_leave_prob=0.05, churn_join_prob=0.2)
    fin, stats = simulate(st, cfg, 20)
    alive = np.asarray(stats.n_alive)
    assert alive.min() < N  # some departures happened
    assert float(stats.coverage[-1]) > 0.5  # gossip survives churn


def test_round_counter_and_rng_advance(graph):
    cfg, st = make(graph)
    nxt, _ = gossip_round(st, cfg)
    assert int(nxt.round) == 1
    assert not np.array_equal(
        jax.random.key_data(nxt.rng), jax.random.key_data(st.rng)
    )


@pytest.mark.slow  # the ckpt matrices + CI recovery drill keep resume
# equivalence covered; this full-machine compose rides slow
def test_resume_equivalence_full_state_machine(tmp_path):
    """Checkpoint/resume is lossless mid-run: simulate(4) + save/load +
    simulate(4) must be BIT-EXACT vs simulate(8) uninterrupted — the RNG
    key rides the state pytree, so the trajectories are identical. Run with
    the full protocol tail live (SIR + Poisson churn + power-law
    re-wiring), which pins every checkpointed field."""
    import jax
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig, init_swarm, load_swarm, save_swarm
    from tpu_gossip.core.topology import build_csr, preferential_attachment

    g = build_csr(400, preferential_attachment(400, m=3, use_native=False,
                                               rng=np.random.default_rng(31)))
    cfg = SwarmConfig(
        n_peers=400, msg_slots=8, fanout=2, mode="push_pull",
        sir_recover_rounds=5, churn_leave_prob=0.02, churn_join_prob=0.1,
        rewire_slots=2,
    )
    st0 = init_swarm(g, cfg, origins=[0, 7], key=jax.random.key(9))

    from tpu_gossip.core.state import clone_state as _clone

    mid, _ = simulate(_clone(st0), cfg, 4)
    save_swarm(tmp_path / "mid.npz", mid)
    resumed, _ = simulate(load_swarm(tmp_path / "mid.npz"), cfg, 4)
    straight, _ = simulate(st0, cfg, 8)

    import dataclasses

    for f in dataclasses.fields(resumed):
        a, b = getattr(resumed, f.name), getattr(straight, f.name)
        if f.name == "rng":
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=f.name)


@pytest.mark.slow  # same coverage note as the full-machine compose above;
# the staircase plan itself is pinned by the kernel parity suite
def test_resume_equivalence_pallas_path(tmp_path):
    """Same losslessness through the sampled staircase kernel."""
    import jax
    import numpy as np

    from tpu_gossip.core.state import SwarmConfig, init_swarm, load_swarm, save_swarm
    from tpu_gossip.core.topology import build_csr, preferential_attachment
    from tpu_gossip.kernels.pallas_segment import build_staircase_plan

    g = build_csr(400, preferential_attachment(400, m=3, use_native=False,
                                               rng=np.random.default_rng(32)))
    cfg = SwarmConfig(n_peers=400, msg_slots=8, fanout=2, mode="push_pull")
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=cfg.fanout)
    st0 = init_swarm(g, cfg, origins=[3], key=jax.random.key(10))

    mid, _ = simulate(clone_state(st0), cfg, 3, plan)
    save_swarm(tmp_path / "mid.npz", mid)
    resumed, _ = simulate(load_swarm(tmp_path / "mid.npz"), cfg, 3, plan)
    straight, _ = simulate(st0, cfg, 6, plan)
    assert bool((resumed.seen == straight.seen).all())
    assert int(resumed.round) == int(straight.round) == 6


def test_edgeless_graph_is_not_mistaken_for_csr_free():
    """The CSR-free sentinel is the exact (1,) col_idx shape that
    matching_powerlaw_graph(export_csr=False) emits. A genuinely EDGELESS
    graph carries col_idx of shape (0,) — the old ``<= 1`` heuristic
    rejected it with a misleading export_csr=False message. It must run:
    delivery finds no neighbors, churn re-wiring finds no endpoints, and
    nobody beyond the origin is ever infected."""
    from tpu_gossip.sim.engine import _require_csr, validate_rewire_width

    n = 12
    g = build_csr(n, np.zeros((0, 2), dtype=np.int64))
    assert g.col_idx.shape[0] == 0
    cfg = SwarmConfig(
        n_peers=n, msg_slots=4, fanout=2, mode="push",
        churn_leave_prob=0.05, churn_join_prob=0.3, rewire_slots=2,
    )
    st = init_swarm(g, cfg, origins=[0])
    _require_csr(st, "test")  # must not raise
    validate_rewire_width(st, cfg)  # must not raise
    fin, stats = simulate(st, cfg, 5)
    assert int(fin.round) == 5
    assert int(np.asarray(fin.seen).any(-1).sum()) <= 1  # nothing spreads
    assert not np.asarray(fin.rewired).any()  # no endpoints to attach to


def test_csr_free_matching_graph_still_fails_loudly():
    """The real CSR-free case keeps its loud error after the sentinel-shape
    fix (regression guard for the heuristic change)."""
    from tpu_gossip.core.matching_topology import matching_powerlaw_graph
    from tpu_gossip.sim.engine import validate_rewire_width

    mg, plan = matching_powerlaw_graph(
        600, fanout=2, key=jax.random.key(0), export_csr=False
    )
    assert mg.col_idx.shape[0] == 1  # the sentinel shape, exactly
    cfg = SwarmConfig(
        n_peers=plan.n + 1, msg_slots=4, fanout=2, mode="push",
        churn_join_prob=0.1, rewire_slots=2,
    )
    st = init_swarm(mg.as_padded_graph(), cfg, origins=[0], exists=mg.exists)
    with pytest.raises(ValueError, match="export_csr"):
        validate_rewire_width(st, cfg)
    cfg2 = SwarmConfig(n_peers=plan.n + 1, msg_slots=4, fanout=2, mode="push")
    st2 = init_swarm(mg.as_padded_graph(), cfg2, origins=[0], exists=mg.exists)
    with pytest.raises(ValueError, match="export_csr"):
        gossip_round(st2, cfg2)  # XLA delivery without a plan reads the CSR
