"""Adaptive protocol control (tpu_gossip/control/): the off-switch, the
zero-adjustment identity, the local ↔ sharded bit-identity under active
control, the PeerSwap credit invariant, and the reliability contract over
the scenario catalogue (docs/adaptive_control.md)."""

import dataclasses
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.control import ControlError, compile_control
from tpu_gossip.core import topology
from tpu_gossip.core.state import (
    SwarmConfig, clone_state, init_swarm, load_swarm, save_swarm,
)
from tpu_gossip.faults import compile_scenario, parse_scenario, scenario_from_dict
from tpu_gossip.growth import compile_growth, matching_admit_rows
from tpu_gossip.sim import metrics as M
from tpu_gossip.sim.engine import simulate
from tpu_gossip.traffic import compile_stream

SCENARIO_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "scenarios")

_CHURN = dict(churn_leave_prob=0.01, churn_join_prob=0.05, rewire_slots=3)


def _pa_state(n=300, seed=0, mode="push_pull", msg_slots=4, **cfg_kw):
    rng = np.random.default_rng(seed)
    g = topology.build_csr(n, topology.preferential_attachment(n, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=n, msg_slots=msg_slots, fanout=3, mode=mode,
                      **cfg_kw)
    return g, cfg, init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))


def _states_equal(a_st, b_st, skip=()):
    for f in dataclasses.fields(type(a_st)):
        if f.name in skip:
            continue
        a, b = getattr(a_st, f.name), getattr(b_st, f.name)
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return f.name
    return None


PROTOCOL_STATS = (
    "coverage", "msgs_sent", "n_infected", "n_alive", "n_declared_dead",
    "msgs_dropped", "msgs_held", "msgs_delivered", "n_members",
)


def _protocol_stats_equal(a, b):
    for f in PROTOCOL_STATS:
        if not np.array_equal(np.asarray(getattr(a, f)), np.asarray(getattr(b, f))):
            return f
    return None


# --------------------------------------------------------------- compile


def test_compile_control_validates():
    with pytest.raises(ControlError):
        compile_control(target_ratio=0.0, fanout=3)
    with pytest.raises(ControlError):
        compile_control(target_ratio=0.9, fanout=3, lo=0, hi=4)
    with pytest.raises(ControlError):
        compile_control(target_ratio=0.9, fanout=3, lo=4, hi=2)
    with pytest.raises(ControlError):
        compile_control(target_ratio=0.9, fanout=5, lo=1, hi=4)
    with pytest.raises(ControlError):
        compile_control(target_ratio=0.9, fanout=3, refresh_every=-1)
    spec = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=6)
    # clean levels 1..6 + the stress rung; start = widest clean level
    assert spec.levels == 7 and spec.start == 5
    assert list(np.asarray(spec.fanout_table)) == [1, 2, 3, 4, 5, 6, 6]
    # pull at-or-below base, off on widened clean levels, ON at the rung
    assert list(np.asarray(spec.pull_table)) == [
        True, True, True, False, False, False, True,
    ]
    assert spec.pull_needy  # active bounds default the needy gate on
    z = compile_control(target_ratio=0.9, fanout=3, lo=3, hi=3)
    assert z.levels == 1 and bool(np.asarray(z.pull_table)[0])
    assert not z.pull_needy  # pinned bounds: exactly the uncontrolled law


# -------------------------------------------------- off-switch / identity


@pytest.mark.parametrize(
    "mode",
    ["push", pytest.param("push_pull", marks=pytest.mark.slow)],
)  # tier-1 keeps one off-switch mode; the pull lane rides the slow lane
def test_zero_adjustment_is_bit_identical_to_uncontrolled(mode):
    """Bounds pinned to the static m + no refresh: the controlled run's
    PROTOCOL trajectory (state + stats) is the uncontrolled run's, bit
    for bit — only the controller's own cursor/telemetry move."""
    _, cfg, st = _pa_state(mode=mode, **_CHURN)
    ctl = compile_control(target_ratio=0.9, fanout=3, lo=3, hi=3)
    f0, s0 = simulate(clone_state(st), cfg, 15)
    fz, sz = simulate(clone_state(st), cfg, 15, control=ctl)
    assert _states_equal(f0, fz, skip=("control_lvl",)) is None
    assert _protocol_stats_equal(s0, sz) is None
    # the off-track reads off (uncontrolled), the zero-adjustment run
    # reports its (single) level and the base fanout
    assert np.all(np.asarray(s0.control_level) == -1)
    assert np.all(np.asarray(s0.control_fanout) == 0)
    assert np.all(np.asarray(sz.control_fanout) == 3)


@pytest.mark.slow  # staircase/matching off-switch: the dense-path variant
# above is the tier-1 representative of the same identity law
def test_zero_adjustment_staircase_and_matching():
    from tpu_gossip.core.matching_topology import matching_powerlaw_graph
    from tpu_gossip.kernels.pallas_segment import build_staircase_plan

    ctl = compile_control(target_ratio=0.9, fanout=2, lo=2, hi=2)
    # staircase
    g, cfg, st = _pa_state(mode="push_pull")
    cfg2 = SwarmConfig(n_peers=300, msg_slots=4, fanout=2, mode="push_pull")
    st2 = init_swarm(g, cfg2, origins=[0], key=jax.random.key(0))
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=2)
    f0, s0 = simulate(clone_state(st2), cfg2, 12, plan)
    fz, sz = simulate(clone_state(st2), cfg2, 12, plan, control=ctl)
    assert _states_equal(f0, fz, skip=("control_lvl",)) is None
    assert _protocol_stats_equal(s0, sz) is None
    # matching
    dg, mplan = matching_powerlaw_graph(
        256, gamma=2.5, fanout=2, key=jax.random.key(0)
    )
    cfgm = SwarmConfig(n_peers=dg.n_pad, msg_slots=4, fanout=2,
                       mode="push_pull")
    stm = init_swarm(dg.as_padded_graph(), cfgm, origins=[0],
                     exists=dg.exists, key=jax.random.key(0))
    f0, s0 = simulate(clone_state(stm), cfgm, 12, mplan)
    fz, sz = simulate(clone_state(stm), cfgm, 12, mplan, control=ctl)
    assert _states_equal(f0, fz, skip=("control_lvl",)) is None
    assert _protocol_stats_equal(s0, sz) is None


def test_control_none_carries_cursor_untouched():
    """The no-control hot path: control=None leaves control_lvl exactly
    as loaded — a checkpoint's cursor survives uncontrolled rounds."""
    _, cfg, st = _pa_state()
    st.control_lvl = jnp.asarray(4, dtype=jnp.int32)
    fin, _ = simulate(clone_state(st), cfg, 3)
    assert int(fin.control_lvl) == 4


# ------------------------------------------------------- active control


def test_controlled_run_saves_messages_at_coverage():
    """The headline mechanism at test scale: AIMD narrowing + the mix
    drop the message bill at equal-or-better rounds-to-coverage. The
    margin GROWS with scale (the saturated late phase dominates the bill
    — ~10% at 2k, ~26% at 1M); the headline-scale figure is bench.py's
    ``control_1m``, this pins the mechanism and the direction."""
    _, cfg, st = _pa_state(n=2000, mode="push_pull", msg_slots=4)
    ctl = compile_control(target_ratio=0.99, fanout=3, lo=1, hi=6)
    _, s0 = simulate(clone_state(st), cfg, 25)
    _, s1 = simulate(clone_state(st), cfg, 25, control=ctl)
    r0, r1 = M.rounds_to_coverage(s0, 0.99), M.rounds_to_coverage(s1, 0.99)
    assert r1 > 0 and r0 > 0 and r1 <= r0
    m0 = int(np.asarray(s0.msgs_sent[:r0]).sum())
    m1 = int(np.asarray(s1.msgs_sent[:r1]).sum())
    assert m1 < 0.95 * m0, (m0, m1, r0, r1)
    # the level trajectory actually moved: started wide, narrowed
    lvls = np.asarray(s1.control_level)
    assert lvls[0] == ctl.start and lvls[-1] < ctl.start


def test_controller_widens_under_loss():
    """Sustained loss drives the under-delivery signal: the level climbs
    from the clean start onto the stress rung."""
    _, cfg, st = _pa_state(n=200)
    scen = compile_scenario(
        scenario_from_dict({
            "name": "loss",
            "phases": [{"name": "l", "start": 0, "end": 12, "loss": 0.5}],
        }),
        n_peers=200, n_slots=200, total_rounds=12,
    )
    ctl = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=5)
    _, s1 = simulate(clone_state(st), cfg, 12, scenario=scen, control=ctl)
    lvls = np.asarray(s1.control_level)
    assert lvls.max() == ctl.levels - 1  # reached the stress rung
    assert np.asarray(s1.control_fanout).max() == 5


@pytest.mark.slow  # refresh coverage stays in tier-1 via the controlled
# dist parity (refresh_every=3); the credit book rides the slow lane
def test_peerswap_refresh_preserves_credit_invariant():
    """PeerSwap swaps fire on cadence and the re-wiring plane's
    book-balance invariant — sum(degree_credit) == stored fresh targets
    of re-wired rows — survives every swap."""
    _, cfg, st = _pa_state(**_CHURN)
    ctl = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=3,
                          refresh_every=2)
    fin, s1 = simulate(clone_state(st), cfg, 20, control=ctl)
    refreshed = np.asarray(s1.control_refreshed)
    assert refreshed.sum() > 0
    assert np.all(refreshed[np.arange(1, 21) % 2 != 0] == 0)  # cadence
    credit = int(np.asarray(fin.degree_credit).sum())
    stored = int(
        (np.asarray(fin.rewire_targets)[np.asarray(fin.rewired)] >= 0).sum()
    )
    assert credit == stored
    # refresh draws ride their own stream: the protocol trajectory with
    # refresh_every=0 matches the uncontrolled level trajectory's fanout
    ctl_no = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=3)
    _, s2 = simulate(clone_state(st), cfg, 20, control=ctl_no)
    assert np.array_equal(
        np.asarray(s1.control_fanout), np.asarray(s2.control_fanout)
    )


def test_control_cursor_checkpoint_roundtrip(tmp_path):
    """The cursor is the checkpointable control cursor: save/resume under
    the same spec replays bit-exactly; pre-control checkpoints load -1."""
    _, cfg, st = _pa_state()
    ctl = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=6)
    mid, _ = simulate(clone_state(st), cfg, 6, control=ctl)
    path = tmp_path / "ctl.npz"
    save_swarm(path, mid)
    resumed = load_swarm(path)
    assert int(resumed.control_lvl) == int(mid.control_lvl)
    fin_a, sa = simulate(clone_state(mid), cfg, 6, control=ctl)
    fin_b, sb = simulate(resumed, cfg, 6, control=ctl)
    assert _states_equal(fin_a, fin_b) is None
    assert np.array_equal(np.asarray(sa.control_level),
                          np.asarray(sb.control_level))
    # forged pre-control checkpoint: the field is absent -> loads -1
    data = dict(np.load(path))
    data.pop("field_control_lvl")
    legacy = tmp_path / "legacy.npz"
    np.savez(legacy, **data)
    old = load_swarm(legacy)
    assert int(old.control_lvl) == -1


# --------------------------------------------- local vs sharded identity


@pytest.mark.parametrize(
    "mode",
    [pytest.param("push", marks=pytest.mark.slow), "push_pull"],
)  # push_pull (the richer lane) is the tier-1 controlled-dist witness
def test_controlled_matching_dist_bit_identical(mode):
    """Active bounds + PeerSwap + needy pulls: the controlled matching
    round stays BIT-IDENTICAL local vs sharded (the adaptive extension
    of the bit-identity contract)."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import (
        make_mesh, shard_matching_plan, shard_swarm, simulate_dist,
    )

    mesh = make_mesh()
    g, plan = matching_powerlaw_graph_sharded(
        512, mesh.size, gamma=2.5, fanout=2, key=jax.random.key(0)
    )
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=4, fanout=2, mode=mode,
                      churn_leave_prob=0.01, churn_join_prob=0.05,
                      rewire_slots=2)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0], exists=g.exists,
                    key=jax.random.key(0))
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=4,
                          refresh_every=3)
    fl, sl = simulate(clone_state(st), cfg, 15, plan, control=ctl)
    fs, ss = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg,
        shard_matching_plan(plan, mesh), mesh, 15, control=ctl,
    )
    assert _states_equal(fl, fs) is None
    for f in sl._fields:
        a = np.asarray(getattr(sl, f))
        if a.dtype.kind in "iub":
            assert np.array_equal(a, np.asarray(getattr(ss, f))), f


@pytest.mark.slow  # the composed matrix is the longest control case; the
# single-feature dist parity above stands in for it in tier-1
def test_controlled_composed_matrix_bit_identical():
    """scenario × growth × stream × control, local vs sharded matching:
    the FULL composition keeps the bit-identity contract."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import (
        make_mesh, shard_matching_plan, shard_swarm, simulate_dist,
    )

    mesh = make_mesh()
    n = 512
    g, plan = matching_powerlaw_graph_sharded(
        n, mesh.size, gamma=2.5, fanout=2, key=jax.random.key(0),
        growth_rows=8,
    )
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull", churn_leave_prob=0.01,
                      churn_join_prob=0.05, rewire_slots=2)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0], exists=g.exists,
                    key=jax.random.key(0))

    def to_rows(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    scen = compile_scenario(
        scenario_from_dict({"name": "t", "phases": [
            {"name": "lossy", "start": 1, "end": 5, "loss": 0.2,
             "delay": 0.2},
            {"name": "storm", "start": 5, "end": 9, "churn_leave": 0.05,
             "churn_join": 0.2, "blackout": {"frac": 0.1, "seed": 1},
             "join_burst": 2},
        ]}),
        n_peers=n, n_slots=plan.n, total_rounds=15, node_map=to_rows,
        shard_ranges=[(s * plan.n_blk, (s + 1) * plan.n_blk)
                      for s in range(mesh.size)],
        n_shards=mesh.size,
    )
    grow = compile_growth(
        n_initial=n, target=n + 24, n_slots=plan.n, joins_per_round=2,
        attach_m=2, admit_rows=matching_admit_rows(plan, 24),
        max_join_burst=2,
    )
    strm = compile_stream(rate=2.0, msg_slots=8, ttl=10,
                          origin_rows=to_rows(np.arange(n)), k_hashes=2)
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=4,
                          refresh_every=3, ttl=10)
    fl, sl = simulate(clone_state(st), cfg, 15, plan, scenario=scen,
                      growth=grow, stream=strm, control=ctl)
    fs, ss = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg,
        shard_matching_plan(plan, mesh), mesh, 15, scenario=scen,
        growth=grow, stream=strm, control=ctl,
    )
    assert _states_equal(fl, fs) is None
    for f in sl._fields:
        a = np.asarray(getattr(sl, f))
        if a.dtype.kind in "iub":
            assert np.array_equal(a, np.asarray(getattr(ss, f))), f


@pytest.mark.slow  # bucketed variant of the off-switch law held in tier-1
# by the matching zero-adjustment test
def test_controlled_bucketed_zero_adjust_and_runs():
    """The bucketed engine: zero-adjustment reproduces its own
    uncontrolled run bit for bit; active control completes and narrows."""
    from tpu_gossip.dist import (
        init_sharded_swarm, make_mesh, partition_graph, shard_swarm,
        simulate_dist,
    )

    rng = np.random.default_rng(0)
    g = topology.build_csr(
        400, topology.preferential_attachment(400, m=3, rng=rng)
    )
    mesh = make_mesh()
    sg, rel, pos = partition_graph(g, mesh.size, seed=0)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, fanout=3,
                      mode="push_pull", churn_leave_prob=0.01,
                      churn_join_prob=0.05, rewire_slots=2)
    st = shard_swarm(
        init_sharded_swarm(sg, rel, pos, cfg, origins=[0],
                           key=jax.random.key(0)),
        mesh,
    )
    f0, s0 = simulate_dist(clone_state(st), cfg, sg, mesh, 12)
    ctl0 = compile_control(target_ratio=0.9, fanout=3, lo=3, hi=3)
    fz, sz = simulate_dist(clone_state(st), cfg, sg, mesh, 12, control=ctl0)
    assert _states_equal(f0, fz, skip=("control_lvl",)) is None
    assert _protocol_stats_equal(s0, sz) is None
    ctl = compile_control(target_ratio=0.9, fanout=3, lo=1, hi=5,
                          refresh_every=4)
    fc, sc = simulate_dist(clone_state(st), cfg, sg, mesh, 12, control=ctl)
    assert float(sc.coverage[-1]) > 0.9
    assert np.asarray(sc.control_fanout).min() >= 1


# ------------------------------------------------- reliability contract


def _run_catalogue_entry(path, *, seed=0):
    """One controlled run under a catalogue scenario, with the composition
    each scenario was written for (flash crowd: growth + stream;
    degraded_under_control: stream + churn re-wiring + refresh)."""
    name = os.path.basename(path)
    n, rounds = 96, 60
    rng = np.random.default_rng(seed)
    g = topology.build_csr(n, topology.preferential_attachment(n, m=3, rng=rng))
    cfg_kw = dict(mode="push_pull", churn_join_prob=0.02, rewire_slots=4)
    grow = strm = None
    # the declared per-message window is part of the contract: a message
    # injected INTO a 16-round partition cannot reach the far side until
    # the heal — no fanout punches through a partition — so the
    # split-brain entry declares a lease that outlives it. Every other
    # scenario holds the tight 12-round window.
    ttl = 26 if name == "split_brain.toml" else 12
    n_slots = n
    spec = parse_scenario(path)
    if name == "flash_crowd_under_fire.toml":
        cap = 192
        from tpu_gossip.growth import pad_graph_for_growth

        g, exists = pad_graph_for_growth(g, cap)
        cfg = SwarmConfig(n_peers=cap, msg_slots=8, fanout=2, **cfg_kw)
        st = init_swarm(g, cfg, origins=[0], exists=exists,
                        key=jax.random.key(seed))
        n_slots = cap
        grow = compile_growth(
            n_initial=n, target=cap, n_slots=cap, joins_per_round=2,
            attach_m=2, max_join_burst=spec.max_join_burst,
        )
    else:
        cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=2, **cfg_kw)
        st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
    strm = compile_stream(rate=1.5, msg_slots=8, ttl=ttl,
                          origin_rows=np.arange(n))
    scen = compile_scenario(spec, n_peers=n, n_slots=n_slots,
                            total_rounds=rounds)
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=4,
                          refresh_every=5, ttl=ttl)
    # byzantine_siege fields adversaries, which REQUIRE the quorum
    # defense (the composition it was written for — the same [base]
    # quorum the catalogue-smoke campaign runs)
    lqs = None
    if spec.uses_adversaries:
        from tpu_gossip.kernels.liveness import compile_quorum

        lqs = compile_quorum(3, window=4, budget=2)
    _, stats = simulate(st, cfg, rounds, scenario=scen, growth=grow,
                        stream=strm, control=ctl, liveness=lqs)
    return M.reliability_report(stats, target_ratio=0.9,
                                coverage_target=0.95)


@pytest.mark.slow  # sweeps the whole scenario catalogue; tier-1 keeps the
# single-scenario reliability checks
def test_reliability_contract_holds_across_catalogue():
    """THE acceptance sweep: a controlled loaded run holds the declared
    delivery-ratio target on EVERY scenario in scenarios/ (the catalogue
    as of this PR), per sim.metrics.reliability_report."""
    paths = sorted(glob.glob(os.path.join(SCENARIO_DIR, "*.toml")))
    assert len(paths) >= 6  # the catalogue incl. degraded_under_control
    for path in paths:
        rep = _run_catalogue_entry(path)
        assert rep["holds"], (os.path.basename(path), rep)


@pytest.mark.slow  # demonstration pair (controller beats static);
# the controlled bit-identity laws stay tier-1
def test_static_fanout_misses_where_controller_holds():
    """The degraded scenario's demonstration pair: at the same config the
    STATIC fanout misses the delivery-ratio target the controller
    holds."""
    path = os.path.join(SCENARIO_DIR, "degraded_under_control.toml")
    n, rounds, ttl = 96, 60, 12
    rng = np.random.default_rng(0)
    g = topology.build_csr(n, topology.preferential_attachment(n, m=3, rng=rng))
    cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=2, mode="push_pull",
                      churn_join_prob=0.02, rewire_slots=4)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(0))
    scen = compile_scenario(parse_scenario(path), n_peers=n, n_slots=n,
                            total_rounds=rounds)
    strm = compile_stream(rate=1.5, msg_slots=8, ttl=ttl,
                          origin_rows=np.arange(n))
    _, s_static = simulate(clone_state(st), cfg, rounds, scenario=scen,
                           stream=strm)
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=4,
                          refresh_every=5, ttl=ttl)
    _, s_ctl = simulate(clone_state(st), cfg, rounds, scenario=scen,
                        stream=strm, control=ctl)
    r_static = M.reliability_report(s_static, target_ratio=0.9,
                                    coverage_target=0.95)
    r_ctl = M.reliability_report(s_ctl, target_ratio=0.9,
                                 coverage_target=0.95)
    assert not r_static["holds"], r_static
    assert r_ctl["holds"], r_ctl


# ------------------------------------------------------------------ CLI


def _run(argv):
    from tpu_gossip.cli.run_sim import main

    return main(argv)


BASE = ["--peers", "96", "--slots", "4", "--fanout", "2", "--quiet"]


def test_cli_control_rejections(capsys):
    # control-shaping flags without --control
    assert _run(BASE + ["--rounds", "20", "--control-bounds", "1,4"]) == 2
    assert _run(BASE + ["--rounds", "20", "--refresh-every", "3"]) == 2
    # the target is a ratio
    assert _run(BASE + ["--rounds", "20", "--control", "1.5"]) == 2
    # bounds below 1, inverted, or excluding the static fanout
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--control-bounds", "0,4"]) == 2
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--control-bounds", "4,2"]) == 2
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--control-bounds", "3,5"]) == 2
    # bounds above the re-wiring width
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--churn-join", "0.1", "--rewire-slots", "2",
                        "--control-bounds", "1,5"]) == 2
    err = capsys.readouterr().err
    assert "rewire" in err
    # (--profile-round now COMPOSES with --control — the controlled
    # stage decomposition; pinned in tests/unit/test_profiling.py)
    # flood has no sampled fanout and no pull half — nothing to modulate
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--mode", "flood"]) == 2
    # the refresh rides the re-wiring plane
    assert _run(BASE + ["--rounds", "20", "--control", "0.9",
                        "--refresh-every", "3"]) == 2


def test_cli_control_smoke_summary(capsys):
    rc = _run(BASE + ["--rounds", "25", "--control", "0.9",
                      "--churn-join", "0.05", "--rewire-slots", "4",
                      "--refresh-every", "4"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    c = summary["control"]
    assert c["target_ratio"] == 0.9 and c["refresh_every"] == 4
    assert c["bounds"][0] >= 1 and c["bounds"][1] <= 4
    rel = summary["reliability"]
    for key in ("delivery_ratio", "holds", "msgs_per_delivered_infection",
                "rounds_to_coverage"):
        assert key in rel, key


def test_reliability_report_epidemic_shape():
    """The single-epidemic branch: one judged message, p50 == p99 ==
    rounds-to-coverage, msgs-per-infection from the real bill."""
    _, cfg, st = _pa_state(n=200)
    _, stats = simulate(clone_state(st), cfg, 20)
    rep = M.reliability_report(stats, target_ratio=0.9)
    rtc = M.rounds_to_coverage(stats, 0.99)
    assert rep["messages_judged"] == 1
    assert rep["holds"] and rep["delivery_ratio"] == 1.0
    assert rep["rounds_to_coverage"]["p99"] == float(rtc)
    assert rep["infections_delivered"] >= 198
    assert rep["msgs_per_delivered_infection"] > 0


def test_reliability_report_all_censored_judges_nothing():
    """A horizon too short to close any lease judges no messages: the
    verdict is vacuous (holds, ratio None), not a violation on zero
    evidence — callers read messages_judged."""
    _, cfg, st = _pa_state(n=96, msg_slots=8)
    strm = compile_stream(rate=1.0, msg_slots=8, ttl=30,
                          origin_rows=np.arange(96))
    _, stats = simulate(clone_state(st), cfg, 5, stream=strm)
    rep = M.reliability_report(stats, target_ratio=0.9)
    assert rep["messages_judged"] == 0
    assert rep["delivery_ratio"] is None and rep["holds"]
