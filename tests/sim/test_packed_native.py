"""Packed-NATIVE round stages (sim/packed_engine, kernels/packed_ops):
per-stage word-vs-bool oracles for the hot path that now computes ON the
uint8 bit words — the round head (roles + transmit + forward-once
latch), the word-native delivery (merge/dedup as word OR/AND/ANDN,
billing as popcounts), the popcount == sum law at a ragged tail
(M % 8 != 0: padding bits must never leak into counts), and the packed
byte wire riding the sparse transport's dense-overflow fallback.

The loop-level bit-identity pins live in tests/sim/test_packed.py; this
file pins each STAGE against its bool twin so a word-algebra regression
names the stage, not just "the round diverged".
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, preferential_attachment
from tpu_gossip.core.packed import pack_bits, pack_state, unpack_bits, unpack_state
from tpu_gossip.core.state import clone_state, init_swarm
from tpu_gossip.kernels import packed_ops as po
from tpu_gossip.sim import engine as _engine
from tpu_gossip.sim.packed_engine import (
    _decode_flags,
    _disseminate_local_packed,
    packed_round_head,
)

N = 257  # not divisible by 8: ragged row counts ride along


def _state_for(m, **cfg_kw):
    g = build_csr(N, preferential_attachment(N, m=3, use_native=False))
    cfg = SwarmConfig(n_peers=N, msg_slots=m, fanout=2, **cfg_kw)
    st = init_swarm(g, cfg, origins=[0, 3], key=jax.random.key(2))
    # a mid-epidemic shape: extra seen slots, some forwarded, some
    # recovered — every branch of the head algebra has work to do
    key = jax.random.key(11)
    k1, k2, k3 = jax.random.split(key, 3)
    seen = st.seen | (jax.random.bernoulli(k1, 0.3, st.seen.shape)
                      & st.exists[:, None])
    st = dataclasses.replace(
        st,
        seen=seen,
        forwarded=seen & jax.random.bernoulli(k2, 0.4, seen.shape),
        recovered=jax.random.bernoulli(k3, 0.1, seen.shape),
    )
    return st, cfg


# ------------------------------------------------------------- round head
@pytest.mark.parametrize("m", [16, 13], ids=["aligned", "ragged"])
@pytest.mark.parametrize("forward_once", [False, True],
                         ids=["plain", "fwd_once"])
def test_round_head_words_match_bool_oracle(m, forward_once):
    """packed_round_head == compute_roles + transmit_bitmap, decoded:
    role words, the transmit plane, and the forward-once ANDN latch are
    the bool masks bit for bit (padding words stay zero)."""
    st, cfg = _state_for(m, mode="push_pull", forward_once=forward_once)
    ps = pack_state(st)
    flags = _decode_flags(ps)
    active_w, role_w, tx_w = packed_round_head(ps, cfg, flags, None)

    active, transmitter, receptive = _engine.compute_roles(st)
    transmit = _engine.transmit_bitmap(st, cfg, transmitter)
    np.testing.assert_array_equal(np.asarray(active_w), np.asarray(active))
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(role_w, m)), np.asarray(transmitter))
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(tx_w, m)), np.asarray(transmit))
    # padding bits of the last word stay zero — the invariant every
    # popcount and rows_any depends on
    if m % 8:
        tail = np.asarray(tx_w)[:, -1]
        assert not (tail >> (m % 8)).any()


# --------------------------------------------------- word-native delivery
@pytest.mark.parametrize("mode", ["push", "push_pull"])
@pytest.mark.parametrize("forward_once", [False, True],
                         ids=["plain", "fwd_once"])
def test_delivery_merge_dedup_words_match_bool_oracle(mode, forward_once):
    """The word-native delivery (gather + OR-fold merge, popcount
    billing) returns the SAME incoming plane and message count as the
    bool `_disseminate_local` under identical keys — the merge/dedup
    algebra on words is the bool algebra, not an approximation."""
    st, cfg = _state_for(16, mode=mode, forward_once=forward_once)
    ps = pack_state(st)
    flags = _decode_flags(ps)
    _, role_w, tx_w = packed_round_head(ps, cfg, flags, None)
    kp, kq = jax.random.split(jax.random.key(7))

    inc_w, msgs_w = _disseminate_local_packed(
        ps, cfg, flags, role_w, tx_w, kp, kq, None, None)

    _, transmitter, receptive = _engine.compute_roles(st)
    transmit = _engine.transmit_bitmap(st, cfg, transmitter)
    inc_b, msgs_b = _engine._disseminate_local(
        st, cfg, transmit, transmitter, receptive, kp, kq, None, None)
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(inc_w, 16)), np.asarray(inc_b))
    assert int(msgs_w) == int(msgs_b)


# --------------------------------------------------- popcount == sum law
@pytest.mark.parametrize("m", [13, 17, 8, 1], ids=["m13", "m17", "m8", "m1"])
def test_popcount_rows_equals_bool_sum_ragged(m):
    """po.popcount_rows(pack_bits(b)) == b.sum(-1, int32) including at
    M % 8 != 0 — the ragged tail's padding bits contribute nothing, and
    the result dtype is the stats contract's int32 (uint8 popcounts that
    sum in uint8 would wrap at 256)."""
    rng = np.random.default_rng(3)
    b = jnp.asarray(rng.random((301, m)) < 0.5)
    counts = po.popcount_rows(pack_bits(b))
    assert counts.dtype == jnp.int32
    np.testing.assert_array_equal(
        np.asarray(counts), np.asarray(b.sum(-1, dtype=jnp.int32)))
    # and the word-shape nonzero test agrees with any()
    np.testing.assert_array_equal(
        np.asarray(po.rows_any(pack_bits(b))), np.asarray(b.any(-1)))


# ----------------------------------------- packed wire, overflow fallback
def test_packed_wire_sparse_overflow_roundtrip():
    """A packed mesh run under sparse transport whose occupancy exceeds
    the compact budget: the runtime gate must ride the DENSE lane
    (sparse_lanes == 0) shipping the packed byte planes, and the
    trajectory must stay bit-identical to the unpacked dense run —
    the overflow fallback round-trips words, not re-decoded bools."""
    from tpu_gossip.dist import (
        build_transport,
        init_sharded_swarm,
        make_mesh,
        partition_graph,
        shard_swarm,
        simulate_dist,
    )

    n = 997
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=1)
    tr = build_transport(sg, mode="sparse")
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, mode="flood")
    st0 = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    # everyone transmits: every valid bucket entry is occupied, so the
    # compact lane cannot fit and the gate must fall back
    st0 = dataclasses.replace(st0, seen=st0.seen.at[:, 0].set(st0.exists))
    st = shard_swarm(st0, mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 2)
    fin_p, (stats_p, ici) = simulate_dist(
        pack_state(clone_state(st)), cfg, sg, mesh, 2, None, None, None,
        tr, True,
    )
    fin_b = unpack_state(fin_p)
    for f in ("seen", "alive", "declared_dead", "recovered", "exists"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_a, f)), np.asarray(getattr(fin_b, f)),
            err_msg=f,
        )
    for f in stats_a._fields:
        if f == "degree_gamma":
            np.testing.assert_allclose(
                np.asarray(stats_a.degree_gamma),
                np.asarray(stats_p.degree_gamma), rtol=5e-7)
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_a, f)),
            np.asarray(getattr(stats_p, f)), err_msg=f)
    assert int(np.asarray(ici.sparse_lanes)[0]) == 0
    assert int(np.asarray(ici.shipped_words)[0]) > int(
        np.asarray(ici.dense_words)[0])  # dense + header, honestly priced
