"""The 2-D (hosts, devices) cluster mesh: fold bit-identity + the
two-level ICI/DCN transport (tpu_gossip/cluster/).

The multi-host contract is a FLATTENING invariant: the (H, D) mesh's
row-major flattening is the flat shard order, collectives run over the
axis tuple, so a 2-D round is literally the flat program over the same
shard ids — state AND every integer stat bit-identical, any fold, and
transitively bit-identical to the local engine where that parity holds
(the matching pipeline). The hierarchical transport (dense intra-host
ICI stage + occupancy-compacted cross-host DCN stage) changes only the
wire representation, never the delivered bits, and must ship fewer DCN
words than the dense cross-host exchange. The CLI half pins the
cross-host-count checkpoint leg (save on (2,4), resume on (4,2) and
flat) and the parse-time rejection surface.

CI runs this file unfiltered in the multihost-smoke job (plus a real
2-process ``jax.distributed`` launch); the slow-marked folds ride there.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, preferential_attachment
from tpu_gossip.cluster import make_cluster_mesh
from tpu_gossip.core.state import clone_state, init_swarm
from tpu_gossip.dist import (
    build_transport,
    init_sharded_swarm,
    partition_graph,
    shard_matching_plan,
    shard_swarm,
    simulate_dist,
)
from tpu_gossip.sim.engine import simulate

N_BUCKETED = 250  # not divisible by 8: pad slots ride through the fold
N_MATCHING = 256


def _assert_states_equal(a, b, where=""):
    for f in dataclasses.fields(type(a)):
        x, y = getattr(a, f.name), getattr(b, f.name)
        if f.name == "rng":
            assert (jax.random.key_data(x) == jax.random.key_data(y)).all()
        else:
            assert bool((np.asarray(x) == np.asarray(y)).all()), \
                f"{where}: {f.name}"


def _assert_stats_equal(a, b, where=""):
    for name, x, y in zip(a._fields, a, b):
        assert bool((np.asarray(x) == np.asarray(y)).all()), \
            f"{where}: {name}"


# ------------------------------------------------------- bucketed engine
@pytest.fixture(scope="module")
def bucketed_setup():
    g = build_csr(
        N_BUCKETED, preferential_attachment(N_BUCKETED, m=3, use_native=False)
    )
    sg, relabeled, position = partition_graph(g, 8, seed=1)
    cfg = SwarmConfig(
        n_peers=sg.n_pad, msg_slots=8, fanout=2, mode="push_pull",
        churn_leave_prob=0.02, churn_join_prob=0.2,
    )
    st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    return sg, cfg, st


@pytest.fixture(scope="module")
def bucketed_flat_run(bucketed_setup):
    """The flat-mesh reference trajectory every fold must reproduce."""
    sg, cfg, st = bucketed_setup
    mesh = make_cluster_mesh(hosts=1)
    fin, stats = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, sg, mesh, 6
    )
    return fin, stats


@pytest.mark.parametrize(
    "hosts", [2, pytest.param(4, marks=pytest.mark.slow)]
)  # the (2,4) fold is the tier-1 witness; (4,2) re-proves the same
# flattening law through a different row shape on the smoke lane
def test_bucketed_2d_fold_bit_identical_to_flat(
    bucketed_setup, bucketed_flat_run, hosts
):
    """THE flattening invariant, bucketed engine: the (H, D) fold runs
    the identical program over the identical shard ids — full state
    (RNG key included) and every per-round stat, bit for bit."""
    sg, cfg, st = bucketed_setup
    fin_f, stats_f = bucketed_flat_run
    mesh = make_cluster_mesh(hosts=hosts)
    fin_2, stats_2 = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, sg, mesh, 6
    )
    _assert_states_equal(fin_f, fin_2, f"(H={hosts})")
    _assert_stats_equal(stats_f, stats_2, f"(H={hosts})")


def test_bucketed_hier_bit_identical_and_saves_dcn(
    bucketed_setup, bucketed_flat_run
):
    """The two-level transport on (2,4) delivers the dense flat bits
    exactly, and its compacted DCN stage ships fewer words than the
    dense cross-host exchange it replaces (the analytic ICI trajectory's
    per-axis split records both stages)."""
    sg, cfg, st = bucketed_setup
    fin_f, stats_f = bucketed_flat_run
    mesh = make_cluster_mesh(hosts=2)
    tp = build_transport(sg, mode="hier", hosts=2)
    fin_h, (stats_h, ici) = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, sg, mesh, 6,
        transport=tp, collect_ici=True,
    )
    _assert_states_equal(fin_f, fin_h, "hier")
    _assert_stats_equal(stats_f, stats_h, "hier")
    dcn_dense = int(np.asarray(ici.dcn_dense_words).sum())
    dcn_ship = int(np.asarray(ici.dcn_shipped_words).sum())
    assert dcn_dense > 0, "the DCN stage never priced its dense baseline"
    assert dcn_ship < dcn_dense, (
        f"two-level transport shipped {dcn_ship} DCN words vs dense "
        f"{dcn_dense} — the compacted cross-host stage saved nothing"
    )
    # the ICI stage is intra-host only: dcn words are a strict subset
    assert dcn_ship <= int(np.asarray(ici.shipped_words).sum())


# ------------------------------------------------------- matching engine
@pytest.fixture(scope="module")
def matching_setup():
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )

    dg, plan = matching_powerlaw_graph_sharded(
        N_MATCHING, 8, gamma=2.5, fanout=1, key=jax.random.key(0),
        export_csr=False,
    )
    cfg = SwarmConfig(
        n_peers=plan.n, msg_slots=16, fanout=1, mode="push_pull",
    )
    st = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(0),
    )
    return plan, cfg, st


@pytest.mark.slow  # the hier matching pipeline's compile dominates
# (~14 s); the fold law keeps its tier-1 witness on the bucketed engine
# (test_bucketed_2d_fold_bit_identical_to_flat[2]) and the hier lane on
# test_bucketed_hier_bit_identical_and_saves_dcn — this cell still runs
# unfiltered in CI's multihost-smoke job and the slow lane
def test_matching_2d_hier_bit_identical_to_local(matching_setup):
    """The strongest single witness: the (2,4) fold UNDER the two-level
    transport is bit-identical to the single-chip engine — which pins
    fold == flat == local transitively (test_dist.py holds flat ==
    local), state and stats, and proves the DCN compaction exact."""
    plan, cfg, st = matching_setup
    mesh = make_cluster_mesh(hosts=2)
    splan = shard_matching_plan(plan, mesh)
    tp = build_transport(plan, mode="hier", hosts=2)
    fin_l, stats_l = simulate(clone_state(st), cfg, 5, plan)
    fin_d, (stats_d, ici) = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, splan, mesh, 5,
        transport=tp, collect_ici=True,
    )
    _assert_states_equal(fin_l, fin_d, "matching-hier")
    _assert_stats_equal(stats_l, stats_d, "matching-hier")
    assert int(np.asarray(ici.dcn_shipped_words).sum()) < int(
        np.asarray(ici.dcn_dense_words).sum()
    )


@pytest.mark.slow  # composed cell on the smoke lane; the plain hier
# witness above keeps the fold law in tier-1
def test_matching_2d_composed_scenario_stream_control(matching_setup):
    """One composed scenario x stream x control cell on the (2,4) fold:
    the optional planes draw at global shape outside shard_map, so the
    fold must not perturb a single draw — bit-identical to local."""
    from tpu_gossip.analysis.entrypoints import (
        _chaos_scenario,
        _control_plan,
        _stream_plan,
    )

    plan, cfg, st = matching_setup
    mesh = make_cluster_mesh(hosts=2)
    splan = shard_matching_plan(plan, mesh)
    kw = dict(
        scenario=_chaos_scenario(plan.n, N_MATCHING),
        stream=_stream_plan(16, np.asarray(st.exists)),
        control=_control_plan(ttl=8),
    )
    fin_l, stats_l = simulate(clone_state(st), cfg, 6, plan, **kw)
    fin_d, stats_d = simulate_dist(
        shard_swarm(clone_state(st), mesh), cfg, splan, mesh, 6, **kw
    )
    _assert_states_equal(fin_l, fin_d, "composed")
    _assert_stats_equal(stats_l, stats_d, "composed")


@pytest.mark.slow  # packed fold leg on the smoke lane; packed parity
# itself is pinned tier-1 by tests/sim/test_packed.py
def test_matching_2d_hier_packed_bit_identical(matching_setup):
    """The packed carry rides the fold + two-level transport unchanged:
    packed vs unpacked on (2,4) hier, state and stats bit for bit."""
    from tpu_gossip.core.packed import PackedSwarm, pack_state, unpack_state

    plan, cfg, st = matching_setup
    mesh = make_cluster_mesh(hosts=2)
    splan = shard_matching_plan(plan, mesh)
    tp = build_transport(plan, mode="hier", hosts=2)
    sharded = shard_swarm(clone_state(st), mesh)
    fin_u, stats_u = simulate_dist(
        clone_state(sharded), cfg, splan, mesh, 6, transport=tp
    )
    p = pack_state(sharded)
    assert "peers" in str(p.seen.sharding)
    fin_p, stats_p = simulate_dist(p, cfg, splan, mesh, 6, transport=tp)
    assert isinstance(fin_p, PackedSwarm)
    _assert_states_equal(fin_u, unpack_state(fin_p), "packed-hier")
    _assert_stats_equal(stats_u, stats_p, "packed-hier")


# ------------------------------------------- cross-host checkpoint resume
@pytest.mark.slow  # four CLI compiles; the multihost-smoke job runs it
def test_cli_checkpoint_resumes_across_host_counts(tmp_path, capsys):
    """The resharding contract's cross-host leg, end to end through the
    CLI: a (2,4) checkpointing run, then the mid-horizon checkpoint
    resumed onto (4,2) AND onto the flat mesh — every fold finishes
    with the uninterrupted run's digests."""
    from tpu_gossip.cli.run_sim import main as run_sim_main

    d = tmp_path / "ck"
    base = ["--peers", "300", "--graph", "matching", "--fanout", "2",
            "--shard", "--hosts", "2", "--rounds", "10", "--slots", "4",
            "--quiet", "--digest"]
    assert run_sim_main(base + ["--checkpoint-every", "5",
                                "--checkpoint-dir", str(d)]) == 0
    ref = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    for hosts in (4, 1):
        assert run_sim_main(["resume", str(d), "--hosts", str(hosts)]) == 0
        got = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert got["state_digest"] == ref["state_digest"], f"hosts={hosts}"
        assert got["stats_digest"] == ref["stats_digest"], f"hosts={hosts}"


# --------------------------------------------------- CLI rejection surface
@pytest.mark.parametrize("argv,needle", [
    (["--shard", "--hosts", "3"], "does not divide the device count"),
    (["--hosts", "2"], "add --shard"),
    (["--transport", "hier"], "two-level"),
    (["--shard", "--hosts", "2", "--remat-every", "3"],
     "cannot compose with --remat-every"),
], ids=["indivisible", "hosts_without_shard", "hier_without_mesh",
        "hosts_with_remat"])
def test_cli_cluster_rejections(capsys, argv, needle):
    """Impossible cluster configs exit 2 at parse time with an error
    naming the conflict — never a traceback from inside the build."""
    from tpu_gossip.cli.run_sim import main as run_sim_main

    rc = run_sim_main(["--peers", "64", "--slots", "4", "--quiet"] + argv)
    assert rc == 2
    assert needle in capsys.readouterr().err
