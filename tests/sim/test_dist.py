"""Multi-chip path tests on the virtual 8-device CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, preferential_attachment
from tpu_gossip.dist import (
    init_sharded_swarm,
    make_mesh,
    partition_graph,
    run_until_coverage_dist,
    shard_swarm,
    simulate_dist,
)
from tpu_gossip.sim.engine import simulate

N = 997  # deliberately not divisible by 8: exercises pad slots


@pytest.fixture(scope="module")
def setup():
    g = build_csr(N, preferential_attachment(N, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=1)
    return g, mesh, sg, relabeled, position


def test_partition_preserves_edges(setup):
    g, mesh, sg, relabeled, position = setup
    assert sg.n_pad % 8 == 0 and sg.n_pad >= N
    # every original edge appears exactly once (relabeled) in the padded CSR
    assert relabeled.num_edges == g.num_edges
    # bucket tables route every directed edge: valid count == 2E
    assert int(np.asarray(sg.send_valid).sum()) == 2 * g.num_edges
    # spot-check: relabeled neighbors of original node 0
    nb_old = set(position[g.neighbors(0)].tolist())
    assert set(relabeled.neighbors(int(position[0])).tolist()) == nb_old


def test_flood_parity_with_single_device(setup):
    """The bucketed all_to_all exchange must deliver EXACTLY the same bits as
    the single-device flood on the identical relabeled graph."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, mode="flood")
    st_d = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    st_l = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    fin_d, stats_d = simulate_dist(st_d, cfg, sg, mesh, 6)
    fin_l, stats_l = simulate(st_l, cfg, 6)
    np.testing.assert_array_equal(np.asarray(fin_d.seen), np.asarray(fin_l.seen))
    np.testing.assert_array_equal(
        np.asarray(stats_d.coverage), np.asarray(stats_l.coverage)
    )


def test_push_reaches_coverage_dist(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=3, mode="push")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 200)
    assert float(fin.coverage(0)) >= 0.99
    assert int(fin.round) < 50


def test_push_pull_dist(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=3, mode="push_pull")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 200)
    assert float(fin.coverage(0)) >= 0.99


def test_pad_slots_stay_dead(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode="push")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 10)
    alive = np.asarray(fin.alive)
    seen = np.asarray(fin.seen)
    assert not alive[sg.n :].any()
    assert not seen[sg.n :].any()  # pads never receive


def test_churn_never_resurrects_pad_slots():
    """Rejoin sampling must exclude pad slots (exists=False): with 30 peers on
    8 shards (2 pads) and aggressive join probability, pads must stay dead —
    otherwise they dilute the coverage denominator (caps at 30/32) and
    run-to-coverage spins to max_rounds."""
    n = 30
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=0)
    # join-only churn: the pads are the ONLY vacant slots, so any rejoin
    # that fires is exactly the resurrection bug
    cfg = SwarmConfig(
        n_peers=sg.n_pad, msg_slots=4, mode="push", fanout=2,
        churn_join_prob=0.9,
    )
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 30)
    exists = np.asarray(fin.exists)
    alive = np.asarray(fin.alive)
    assert exists.sum() == n
    assert not alive[~exists].any(), "pad slots were resurrected by churn rejoin"
    # denominator excludes pads, so full coverage is reachable (was capped
    # at 30/32 with resurrected degree-0 pads)
    assert float(fin.coverage(0)) >= 0.99


def test_liveness_dist(setup):
    """Silent-peer detection must work identically under sharding."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode="push")
    st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    silent_slots = position[np.arange(40)]  # 40 real peers silent
    st.silent = st.silent.at[silent_slots].set(True)
    st = shard_swarm(st, mesh)
    fin, stats = simulate_dist(st, cfg, sg, mesh, 12)
    n_pads = sg.n_pad - sg.n
    dead = np.asarray(stats.n_declared_dead) - n_pads  # pads born declared-dead
    assert dead[-1] == 40


def test_sharding_layout(setup):
    """State stays peer-sharded across rounds (no silent full replication)."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode="push")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 2)
    shardings = {str(fin.seen.sharding.spec), str(fin.alive.sharding.spec)}
    assert all("peers" in s for s in shardings), shardings
