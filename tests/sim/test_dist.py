"""Multi-chip path tests on the virtual 8-device CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip import SwarmConfig, build_csr, preferential_attachment
from tpu_gossip.core.state import clone_state
from tpu_gossip.dist import (
    build_shard_plans,
    init_sharded_swarm,
    make_mesh,
    partition_graph,
    run_until_coverage_dist,
    shard_swarm,
    simulate_dist,
)
from tpu_gossip.sim.engine import simulate

N = 997  # deliberately not divisible by 8: exercises pad slots


@pytest.fixture(scope="module")
def setup():
    g = build_csr(N, preferential_attachment(N, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=1)
    return g, mesh, sg, relabeled, position


def test_partition_preserves_edges(setup):
    g, mesh, sg, relabeled, position = setup
    assert sg.n_pad % 8 == 0 and sg.n_pad >= N
    # every original edge appears exactly once (relabeled) in the padded CSR
    assert relabeled.num_edges == g.num_edges
    # bucket tables route every directed edge: valid count == 2E
    assert int(np.asarray(sg.send_valid).sum()) == 2 * g.num_edges
    # spot-check: relabeled neighbors of original node 0
    nb_old = set(position[g.neighbors(0)].tolist())
    assert set(relabeled.neighbors(int(position[0])).tolist()) == nb_old


def test_flood_parity_with_single_device(setup):
    """The bucketed all_to_all exchange must deliver EXACTLY the same bits as
    the single-device flood on the identical relabeled graph."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, mode="flood")
    st_d = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    st_l = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    fin_d, stats_d = simulate_dist(st_d, cfg, sg, mesh, 6)
    fin_l, stats_l = simulate(st_l, cfg, 6)
    np.testing.assert_array_equal(np.asarray(fin_d.seen), np.asarray(fin_l.seen))
    np.testing.assert_array_equal(
        np.asarray(stats_d.coverage), np.asarray(stats_l.coverage)
    )


def test_push_reaches_coverage_dist(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=3, mode="push")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 200)
    assert float(fin.coverage(0)) >= 0.99
    assert int(fin.round) < 50


def test_push_pull_dist(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=3, mode="push_pull")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin = run_until_coverage_dist(st, cfg, sg, mesh, 0.99, 200)
    assert float(fin.coverage(0)) >= 0.99


def test_pad_slots_stay_dead(setup):
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode="push")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 10)
    alive = np.asarray(fin.alive)
    seen = np.asarray(fin.seen)
    assert not alive[sg.n :].any()
    assert not seen[sg.n :].any()  # pads never receive


def test_churn_never_resurrects_pad_slots():
    """Rejoin sampling must exclude pad slots (exists=False): with 30 peers on
    8 shards (2 pads) and aggressive join probability, pads must stay dead —
    otherwise they dilute the coverage denominator (caps at 30/32) and
    run-to-coverage spins to max_rounds."""
    n = 30
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=0)
    # join-only churn: the pads are the ONLY vacant slots, so any rejoin
    # that fires is exactly the resurrection bug
    cfg = SwarmConfig(
        n_peers=sg.n_pad, msg_slots=4, mode="push", fanout=2,
        churn_join_prob=0.9,
    )
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 30)
    exists = np.asarray(fin.exists)
    alive = np.asarray(fin.alive)
    assert exists.sum() == n
    assert not alive[~exists].any(), "pad slots were resurrected by churn rejoin"
    # denominator excludes pads, so full coverage is reachable (was capped
    # at 30/32 with resurrected degree-0 pads)
    assert float(fin.coverage(0)) >= 0.99


@pytest.mark.slow  # the local engine's twin (tests/sim/test_engine.py::
# test_rewired_peers_attach_degree_preferentially) keeps the attachment
# law in tier-1; this sharded rerun rides the slow lane
def test_rewired_peers_attach_degree_preferentially_dist(setup):
    """BASELINE config 5 in the sharded engine (VERDICT r2 item 4): rejoiners
    draw fresh degree-preferential neighbors AND those fresh edges actually
    carry traffic — rewired peers get re-infected through them."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(
        n_peers=sg.n_pad, msg_slots=8, fanout=3, mode="push_pull",
        churn_leave_prob=0.08, churn_join_prob=0.4, rewire_slots=4,
    )
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 60)
    rewired = np.asarray(fin.rewired)
    assert rewired.sum() > 30, "not enough rejoin events to test"
    targets = np.asarray(fin.rewire_targets)[rewired].ravel()
    targets = targets[targets >= 0]
    deg = np.asarray(sg.deg)
    # endpoint sampling is size-biased: E[deg(target)] = E[d^2]/E[d] > E[d]
    expected = (deg.astype(float) ** 2).sum() / max(deg.sum(), 1)
    got = deg[targets].mean()
    assert got > 0.6 * expected, (got, expected)
    # the fresh edges MUST carry dissemination: most live rewired peers are
    # re-infected even though all their static CSR edges are masked stale
    alive_rw = rewired & np.asarray(fin.alive)
    assert alive_rw.sum() > 10
    assert np.asarray(fin.seen).any(-1)[alive_rw].mean() > 0.5


def test_dist_stale_and_fresh_edge_semantics():
    """One round, hand-built rewiring: stale CSR edges deliver nothing to a
    rewired slot; a rewired sender's traffic flows only via fresh targets —
    matching the local engine's semantics exactly."""
    import dataclasses

    n = 16
    # ring so every peer has deg 2 and sampling is deterministic in coverage
    edges = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    g = build_csr(n, edges)
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=3)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, fanout=2, mode="push",
                      rewire_slots=2)
    pos = {old: int(position[old]) for old in range(n)}

    # origin = old peer 0; mark old peer 1 (a CSR neighbor) rewired with
    # fresh targets pointing at old peer 5 (far side of the ring)
    st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    st = dataclasses.replace(
        st,
        rewired=st.rewired.at[pos[1]].set(True),
        rewire_targets=st.rewire_targets.at[pos[1], :].set(pos[5]),
        # seed the rewired peer too so its fresh edges must carry something
        seen=st.seen.at[pos[1], 1].set(True),
    )
    st = shard_swarm(st, mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 1)
    seen = np.asarray(fin.seen)
    # slot 0 spread from origin 0 along CSR — but NOT to rewired neighbor 1
    assert not seen[pos[1], 0], "stale CSR edge delivered into a rewired slot"
    # the rewired peer's own rumor (slot 1) reached its fresh target 5
    # (fanout 2 over 2 identical fresh targets fires w.h.p.; assert via OR
    # over several rounds is not possible in 1 round — accept either the
    # fresh target or nobody, never a CSR neighbor)
    csr_nb = {pos[0], pos[2]}
    got_slot1 = set(np.nonzero(seen[:, 1])[0].tolist()) - {pos[1]}
    assert got_slot1 <= {pos[5]}, f"slot 1 leaked over stale CSR edges: {got_slot1 - {pos[5]}} (csr nb {csr_nb})"


def test_liveness_dist(setup):
    """Silent-peer detection must work identically under sharding."""
    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode="push")
    st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    silent_slots = position[np.arange(40)]  # 40 real peers silent
    st.silent = st.silent.at[silent_slots].set(True)
    st = shard_swarm(st, mesh)
    fin, stats = simulate_dist(st, cfg, sg, mesh, 12)
    n_pads = sg.n_pad - sg.n
    dead = np.asarray(stats.n_declared_dead) - n_pads  # pads born declared-dead
    assert dead[-1] == 40


@pytest.mark.parametrize(
    "mode,fanout",
    [pytest.param("push", 3, marks=pytest.mark.slow), ("push_pull", 1)],
)  # one curve-parity witness in tier-1; the push lane rides slow
def test_dist_local_curve_parity(setup, mode, fanout):
    """Quantified parity bound (VERDICT r2 item 5): dist samples Bernoulli
    k/deg per edge where the local engine samples exactly-k neighbors; the
    means match, and over >=5 seeds per engine the median rounds-to-50% and
    rounds-to-99% on the SAME relabeled graph must agree within 2 rounds."""
    from tpu_gossip.sim.metrics import rounds_to_coverage

    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, fanout=fanout, mode=mode)
    seeds = range(5)

    def run_local(seed):
        st = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0],
                                key=jax.random.key(seed))
        _, stats = simulate(st, cfg, 60)
        return stats

    def run_dist(seed):
        st = shard_swarm(
            init_sharded_swarm(sg, relabeled, position, cfg, origins=[0],
                               key=jax.random.key(seed)), mesh)
        _, stats = simulate_dist(st, cfg, sg, mesh, 60)
        return stats

    for target in (0.5, 0.99):
        loc = np.median([rounds_to_coverage(run_local(s), target) for s in seeds])
        dst = np.median([rounds_to_coverage(run_dist(s), target) for s in seeds])
        assert loc > 0 and dst > 0, (mode, target, loc, dst)
        assert abs(loc - dst) <= 2.0, (mode, target, loc, dst)


@pytest.mark.parametrize(
    "mode,extra",
    [
        ("flood", {}),
        pytest.param("push", {}, marks=pytest.mark.slow),
        pytest.param("push_pull", {}, marks=pytest.mark.slow),
        ("push_pull", dict(churn_leave_prob=0.01, churn_join_prob=0.1,
                           rewire_slots=2)),
        pytest.param("push_pull",
                     dict(churn_leave_prob=0.01, churn_join_prob=0.1,
                          rewire_slots=2, rewire_compact_cap=64),
                     marks=pytest.mark.slow),
    ],  # churn keeps the re-wiring receive path in tier-1 and flood the
    # everyone-transmits activation; push/push_pull assert the same
    # scatter-vs-kernel law in between and ride the slow lane with the
    # compact twin
    ids=["flood", "push", "push_pull", "push_pull_churn",
         "push_pull_churn_compact"],
)
def test_kernel_receive_path_bit_parity(setup, mode, extra):
    """The fused staircase kernel (VERDICT r3 item 1): replacing the
    receive-side ``.at[].max`` scatter with the per-shard staircase kernel
    changes NOTHING upstream — activation draws, all_to_all, stale filters
    and billing are shared — so the full state trajectory must be
    bit-identical, every mode, churn re-wiring included. (Transitively this
    also gives flood bit-parity with the single-device engine via
    test_flood_parity_with_single_device.)"""
    _, mesh, sg, relabeled, position = setup
    plans = build_shard_plans(sg)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2, mode=mode, **extra)
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0, 1],
                           key=jax.random.key(3)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 6)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 6, plans)
    np.testing.assert_array_equal(np.asarray(fin_a.seen), np.asarray(fin_b.seen))
    np.testing.assert_array_equal(
        np.asarray(stats_a.msgs_sent), np.asarray(stats_b.msgs_sent)
    )
    for f in ("alive", "rewired", "declared_dead", "recovered", "last_hb"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_a, f)), np.asarray(getattr(fin_b, f)), err_msg=f
        )


def test_kernel_receive_path_multiword(setup):
    """m > 32 through the fused path: one kernel launch per 32-slot word
    group per shard, same edge activation across groups — still bit-exact
    vs the scatter receive."""
    import dataclasses

    _, mesh, sg, relabeled, position = setup
    plans = build_shard_plans(sg)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=48, fanout=2, mode="push_pull")
    st = init_sharded_swarm(sg, relabeled, position, cfg, key=jax.random.key(5))
    # one distinct rumor per slot (init_sharded_swarm seeds only slot 0):
    # BOTH word groups must carry live traffic or group 1's parity is vacuous
    st = dataclasses.replace(
        st, seen=st.seen.at[position[np.arange(48)], np.arange(48)].set(True)
    )
    st = shard_swarm(st, mesh)
    fin_a, _ = simulate_dist(clone_state(st), cfg, sg, mesh, 4)
    fin_b, _ = simulate_dist(st, cfg, sg, mesh, 4, plans)
    seen_a = np.asarray(fin_a.seen)
    assert seen_a[:, 32:].any(), "second word group never carried traffic"
    np.testing.assert_array_equal(seen_a, np.asarray(fin_b.seen))


@pytest.mark.slow  # the ckpt matrix (tests/sim/test_ckpt.py) and the CLI
# --shard --checkpoint run keep sharded-snapshot resume in tier-1
def test_dist_checkpoint_resume_local(tmp_path):
    """A sharded run's checkpoint resumes bit-exactly — in the local engine
    (operator takes a multi-chip snapshot to a single chip: the state pytree
    is placement-agnostic) and in the dist engine on the same mesh."""
    from tpu_gossip.core.state import load_swarm, save_swarm

    g = build_csr(200, preferential_attachment(200, m=3, use_native=False))
    mesh = make_mesh(8)
    sg, relabeled, position = partition_graph(g, 8, seed=2)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2, mode="push_pull")
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    mid, _ = simulate_dist(st, cfg, sg, mesh, 3)
    save_swarm(tmp_path / "dist.npz", mid)
    restored = load_swarm(tmp_path / "dist.npz")
    # dist-engine resume on the same mesh: identical trajectory. shard_swarm
    # may ALIAS replicated leaves (device_put reuses the source buffer for
    # the device it already lives on), so the donated sharded copy is made
    # from a clone — `restored` must survive for the local-engine resume
    fin_a, _ = simulate_dist(mid, cfg, sg, mesh, 3)
    fin_b, _ = simulate_dist(
        shard_swarm(clone_state(restored), mesh), cfg, sg, mesh, 3
    )
    np.testing.assert_array_equal(np.asarray(fin_a.seen), np.asarray(fin_b.seen))
    assert int(fin_b.round) == 6
    # local-engine resume runs too (same state machine, single shard)
    fin_l, _ = simulate(restored, cfg, 3)
    assert int(fin_l.round) == 6
    assert float(fin_l.coverage(0)) > 0


# --- sharded matching delivery (the gather-free pipeline multi-chip) -----


@pytest.fixture(scope="module")
def matching_setup(matching_1500, mesh8):
    from tpu_gossip.dist import shard_matching_plan

    g, plan = matching_1500
    return g, plan, shard_matching_plan(plan, mesh8), mesh8


def _matching_state(g, cfg, seed=3, origins=(0, 5)):
    from tpu_gossip.core.state import init_swarm

    return init_swarm(
        g.as_padded_graph(), cfg, origins=list(origins), exists=g.exists,
        key=jax.random.key(seed),
    )


@pytest.mark.parametrize(
    "mode,extra",
    [
        pytest.param("flood", {}, marks=pytest.mark.slow),
        pytest.param("push", {}, marks=pytest.mark.slow),
        ("push_pull", {}),
        pytest.param("push_pull",
                     dict(churn_leave_prob=0.02, churn_join_prob=0.2,
                          rewire_slots=2), marks=pytest.mark.slow),
        pytest.param("push_pull",
                     dict(churn_leave_prob=0.02, churn_join_prob=0.2,
                          rewire_slots=2, rewire_compact_cap=64),
                     marks=pytest.mark.slow),
        pytest.param("push_pull", dict(sir_recover_rounds=2),
                     marks=pytest.mark.slow),
        # forward_once is the only config taking the answer-bitmap branch
        # (a second expand+pipeline pass per word group inside shard_map)
        ("push_pull", dict(forward_once=True)),
    ],  # push_pull (both lanes) + fwd_once (the answer-bitmap branch) are
    # the tier-1 witnesses; flood/push assert the same single-chip parity
    # law through cheaper heads and ride the slow lane with the churn twins
    ids=["flood", "push", "push_pull", "push_pull_churn",
         "push_pull_churn_compact", "push_pull_sir", "push_pull_fwd_once"],
)
def test_matching_dist_bit_identical_to_single_chip(matching_setup, mode, extra):
    """The shard-vs-single-chip equivalence is BIT-exact, full trajectory:
    the mesh round splits keys exactly like gossip_round, draws sampling
    bits at the global shape (threefry is position-deterministic), and the
    all_to_all transposes compute the identical global bijection — so the
    same plan + state must yield identical seen/msgs/liveness/churn on
    both engines, every mode, re-wiring included. (The bucketed CSR engine
    can only match the local engine in distribution; the matching pipeline
    matches it bit for bit.)"""
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode=mode, **extra)
    st = _matching_state(g, cfg)
    fin_l, stats_l = simulate(clone_state(st), cfg, 5, plan)
    fin_d, stats_d = simulate_dist(shard_swarm(st, mesh), cfg, plan_m, mesh, 5)
    np.testing.assert_array_equal(np.asarray(fin_l.seen), np.asarray(fin_d.seen))
    np.testing.assert_array_equal(
        np.asarray(stats_l.msgs_sent), np.asarray(stats_d.msgs_sent)
    )
    np.testing.assert_array_equal(
        np.asarray(stats_l.coverage), np.asarray(stats_d.coverage)
    )
    for f in ("alive", "rewired", "declared_dead", "recovered", "last_hb",
              "rewire_targets"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_l, f)), np.asarray(getattr(fin_d, f)),
            err_msg=f,
        )


def test_matching_dist_reaches_coverage(matching_setup):
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode="push_pull")
    st = shard_swarm(_matching_state(g, cfg), mesh)
    fin = run_until_coverage_dist(st, cfg, plan_m, mesh, 0.95, 200)
    assert float(fin.coverage(0)) >= 0.95
    assert int(fin.round) < 60


@pytest.mark.slow  # multiword receive stays tier-1 via the bucketed
# test_kernel_receive_path_multiword; this matching twin rides slow
def test_matching_dist_multiword(matching_setup):
    """m > 32: one pipeline application per 32-slot word group per shard,
    same edge activation across groups — still bit-exact vs local."""
    import dataclasses

    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=48, fanout=2, mode="push_pull")
    st = _matching_state(g, cfg, seed=5, origins=(0,))
    # one distinct rumor per slot so BOTH word groups carry live traffic
    rows = (np.arange(48) // plan.n_per) * plan.n_blk + (np.arange(48) % plan.n_per)
    st = dataclasses.replace(
        st, seen=st.seen.at[rows, np.arange(48)].set(True)
    )
    fin_l, _ = simulate(clone_state(st), cfg, 3, plan)
    fin_d, _ = simulate_dist(shard_swarm(st, mesh), cfg, plan_m, mesh, 3)
    seen_l = np.asarray(fin_l.seen)
    assert seen_l[:, 32:].any(), "second word group never carried traffic"
    np.testing.assert_array_equal(seen_l, np.asarray(fin_d.seen))


def test_matching_dist_sharding_layout(matching_setup):
    """Peer-axis state leaves stay peer-sharded through matching rounds —
    the pipeline's collectives must not leave anything replicated."""
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(
        n_peers=plan.n, msg_slots=4, fanout=2, mode="push_pull",
        churn_leave_prob=0.01, churn_join_prob=0.1, rewire_slots=2,
    )
    st = shard_swarm(_matching_state(g, cfg), mesh)
    fin, _ = simulate_dist(st, cfg, plan_m, mesh, 3)
    bad = {}
    for f in type(fin).__dataclass_fields__:
        v = getattr(fin, f)
        if hasattr(v, "ndim") and v.ndim >= 1 and v.shape[0] == plan.n:
            spec = str(v.sharding.spec)
            if "peers" not in spec:
                bad[f] = spec
    assert not bad, f"state leaves lost the peer sharding: {bad}"


def test_matching_dist_pad_rows_stay_dead(matching_setup):
    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(
        n_peers=plan.n, msg_slots=4, fanout=2, mode="push_pull",
        churn_join_prob=0.5,
    )
    st = shard_swarm(_matching_state(g, cfg), mesh)
    fin, _ = simulate_dist(st, cfg, plan_m, mesh, 10)
    exists = np.asarray(g.exists)
    assert not np.asarray(fin.alive)[~exists].any()
    assert not np.asarray(fin.seen)[~exists].any()


def test_matching_dist_rejects_mismatched_mesh(matching_setup):
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )

    g, plan, plan_m, mesh = matching_setup
    _, plan4 = matching_powerlaw_graph_sharded(
        600, 4, fanout=2, key=jax.random.key(0)
    )
    cfg = SwarmConfig(n_peers=plan4.n, msg_slots=4, fanout=2, mode="push")
    st = _matching_state(g, SwarmConfig(n_peers=plan.n, msg_slots=4,
                                        fanout=2, mode="push"))
    from tpu_gossip.dist import gossip_round_dist

    with pytest.raises(ValueError, match="shards"):
        gossip_round_dist(st, cfg, plan4, mesh)


@pytest.mark.parametrize(
    "mode,extra,kernel",
    [
        ("push", {}, False),
        pytest.param("push_pull", dict(churn_leave_prob=0.01,
                                       churn_join_prob=0.1,
                                       rewire_slots=2), False,
                     marks=pytest.mark.slow),
        ("push_pull", dict(churn_leave_prob=0.01, churn_join_prob=0.1,
                           rewire_slots=2), True),
    ],  # the kernel-receive churn row subsumes the scatter-receive one
    # (same global-view re-wiring path outside shard_map); the scatter
    # twin rides the slow lane
    ids=["push", "push_pull_churn", "push_pull_churn_kernel"],
)
def test_sharding_layout(setup, mode, extra, kernel):
    """EVERY peer-axis state leaf stays peer-sharded across rounds — no
    silent full replication. The churn configs guard the re-wiring path
    (VERDICT r3 item 6): fresh_rewire_traffic runs global-view
    gather/scatter OUTSIDE shard_map, trusting the SPMD partitioner — a
    partitioner decision to all-gather the (N, M) arrays there would be
    invisible to a plain-push-only check."""
    _, mesh, sg, relabeled, position = setup
    plans = build_shard_plans(sg) if kernel else None
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=4, mode=mode, fanout=2, **extra)
    st = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    fin, _ = simulate_dist(st, cfg, sg, mesh, 3, plans)
    bad = {}
    for f in type(fin).__dataclass_fields__:
        v = getattr(fin, f)
        if hasattr(v, "ndim") and v.ndim >= 1 and v.shape[0] == sg.n_pad:
            spec = str(v.sharding.spec)
            if "peers" not in spec:
                bad[f] = spec
    assert not bad, f"state leaves lost the peer sharding: {bad}"


# --- chaos scenarios on the mesh (faults/: the bit-identity extension) ----


def _chaos_spec(heal=4):
    """Loss + delay + split-brain + churn burst + blackout across three
    phases — every fault class the scenario engine injects."""
    from tpu_gossip.faults import scenario_from_dict

    return scenario_from_dict({"name": "chaos", "phases": [
        {"name": "lossy", "start": 0, "end": 2, "loss": 0.3, "delay": 0.3},
        {"name": "split", "start": 2, "end": heal, "partition": "half",
         "loss": 0.1},
        {"name": "storm", "start": heal, "end": heal + 2,
         "churn_leave": 0.1, "churn_join": 0.3,
         "blackout": {"frac": 0.1, "seed": 9}},
    ]})


@pytest.mark.parametrize(
    "mode,extra",
    [
        pytest.param("push_pull", {}, marks=pytest.mark.slow),
        pytest.param("push_pull",
                     dict(churn_leave_prob=0.02, churn_join_prob=0.2,
                          rewire_slots=2), marks=pytest.mark.slow),
        ("flood", {}),
    ],
    ids=["push_pull", "push_pull_churn", "flood"],
)  # one scenario-parity witness in tier-1; the dearer modes ride slow
def test_matching_dist_scenario_bit_identical(matching_setup, mode, extra):
    """THE acceptance criterion: a mesh round under an active scenario
    (loss + delay + partition + churn burst + blackout) is bit-identical
    to the local round — fault draws are made at global shape from the
    derived fault stream, the two-pass partition delivery wraps the same
    dissemination core on both engines."""
    from tpu_gossip.faults import compile_scenario

    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode=mode, **extra)
    st = _matching_state(g, cfg)

    def rows_of(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    sc = compile_scenario(
        _chaos_spec(), n_peers=1500, n_slots=plan.n, total_rounds=8,
        node_map=rows_of,
    )
    fin_l, stats_l = simulate(clone_state(st), cfg, 6, plan, "fused", sc)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 6, None, sc
    )
    for f in ("seen", "alive", "rewired", "declared_dead", "recovered",
              "last_hb", "rewire_targets", "fault_held"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_l, f)), np.asarray(getattr(fin_d, f)),
            err_msg=f,
        )
    for f in ("msgs_sent", "msgs_dropped", "msgs_held", "msgs_delivered",
              "coverage"):
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_l, f)), np.asarray(getattr(stats_d, f)),
            err_msg=f,
        )
    # the scenario must actually bite, or the parity is vacuous
    assert np.asarray(stats_l.msgs_dropped).sum() > 0
    assert np.asarray(stats_l.msgs_held).max() > 0


@pytest.mark.slow  # the matching-engine scenario flood witness keeps
# scenario parity in tier-1; this bucketed twin rides the slow lane
def test_bucketed_scenario_flood_parity_with_single_device(setup):
    """Flood is deterministic, so the bucketed mesh under a scenario must
    match the single-device engine bit for bit — loss/delay draws land at
    identical stream positions on both."""
    from tpu_gossip.faults import compile_scenario

    _, mesh, sg, relabeled, position = setup
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, mode="flood")
    sc = compile_scenario(
        _chaos_spec(), n_peers=N, n_slots=sg.n_pad, total_rounds=8,
        node_map=lambda ids: position[np.asarray(ids)],
    )
    st_d = shard_swarm(init_sharded_swarm(sg, relabeled, position, cfg, origins=[0]), mesh)
    st_l = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    fin_d, stats_d = simulate_dist(st_d, cfg, sg, mesh, 6, None, sc)
    fin_l, stats_l = simulate(st_l, cfg, 6, None, "fused", sc)
    np.testing.assert_array_equal(np.asarray(fin_d.seen), np.asarray(fin_l.seen))
    np.testing.assert_array_equal(
        np.asarray(fin_d.fault_held), np.asarray(fin_l.fault_held)
    )
    for f in ("coverage", "msgs_dropped", "msgs_held", "msgs_delivered"):
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_d, f)), np.asarray(getattr(stats_l, f)),
            err_msg=f,
        )


@pytest.mark.slow  # dist x adversary composition; solo adversary invariants
# and plain dist parity each keep their law in tier-1
def test_matching_dist_adversary_bit_identical(matching_setup):
    """The ADVERSARIAL extension of the bit-identity contract: a mesh
    round under Byzantine accusers + forgers + floods (composed with a
    blackout and churn, under the quorum defense) is bit-identical to the
    local round — full state (suspicion planes included) plus every
    integer stat. All adversary draws land at global shape from the
    registered adversary stream, outside shard_map."""
    import dataclasses

    from tpu_gossip.faults import compile_scenario, scenario_from_dict
    from tpu_gossip.kernels.liveness import compile_quorum

    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(
        n_peers=plan.n, msg_slots=8, fanout=2, mode="push_pull",
        churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
    )
    st = _matching_state(g, cfg)

    def rows_of(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    spec = scenario_from_dict({"name": "siege", "phases": [
        {"name": "dark", "start": 0, "end": 4, "loss": 0.1, "delay": 0.1,
         "blackout": {"frac": 0.1, "seed": 9}},
        {"name": "adv", "start": 4, "end": 8,
         "accusers": {"frac": 0.05, "seed": 3},
         "forgers": {"frac": 0.02, "seed": 4},
         "floods": {"frac": 0.03, "seed": 5},
         "forge_fanout": 2, "flood_fanout": 3},
    ]})
    sc = compile_scenario(
        spec, n_peers=1500, n_slots=plan.n, total_rounds=8,
        node_map=rows_of,
    )
    q = compile_quorum(3, window=4, budget=2)
    fin_l, stats_l = simulate(clone_state(st), cfg, 8, plan, "fused", sc,
                              None, None, None, None, q)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 8, None, sc,
        liveness=q,
    )
    for f in dataclasses.fields(fin_l):
        la, lb = getattr(fin_l, f.name), getattr(fin_d, f.name)
        if jnp.issubdtype(la.dtype, jax.dtypes.prng_key):
            la, lb = jax.random.key_data(la), jax.random.key_data(lb)
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb), err_msg=f.name
        )
    for f in stats_l._fields:
        a = np.asarray(getattr(stats_l, f))
        if a.dtype.kind in "biu":
            np.testing.assert_array_equal(
                a, np.asarray(getattr(stats_d, f)), err_msg=f
            )
    # the attack must actually bite, or the parity is vacuous
    assert int(np.asarray(stats_l.adv_accusations).sum()) > 0
    assert int(np.asarray(stats_l.adv_forged).sum()) > 0
    assert int(np.asarray(stats_l.evictions_new).sum()) > 0


@pytest.mark.slow  # composed variant; the plain adversary-parity test
# above is the tier-1 witness
def test_matching_dist_adversary_composed_bit_identical(matching_setup):
    """The composed cell: adversary × chaos scenario × stream × control ×
    pipeline on the mesh vs local — the whole adversarial round (attack
    scatters, quorum machine, quarantine masking) under a loaded,
    controlled, double-buffered swarm stays bit-identical."""
    import dataclasses

    from tpu_gossip.control import compile_control
    from tpu_gossip.faults import compile_scenario, scenario_from_dict
    from tpu_gossip.kernels.liveness import compile_quorum
    from tpu_gossip.sim.stages import compile_pipeline
    from tpu_gossip.traffic import compile_stream

    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2,
                      mode="push_pull")
    st = _matching_state(g, cfg)

    def rows_of(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    spec = scenario_from_dict({"name": "siege", "phases": [
        {"name": "adv", "start": 0, "end": 6,
         "accusers": {"frac": 0.05, "seed": 3},
         "floods": {"frac": 0.03, "seed": 5},
         "blackout": {"frac": 0.08, "seed": 9}, "loss": 0.1},
    ]})
    sc = compile_scenario(spec, n_peers=1500, n_slots=plan.n,
                          total_rounds=8, node_map=rows_of)
    strm = compile_stream(rate=1.5, msg_slots=8, ttl=12,
                          origin_rows=rows_of(np.arange(1500)))
    ctl = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=4, ttl=12)
    q = compile_quorum(3, window=4, budget=2)
    pipe = compile_pipeline(1)
    fin_l, stats_l = simulate(clone_state(st), cfg, 6, plan, "fused", sc,
                              None, strm, ctl, pipe, q)
    fin_d, stats_d = simulate_dist(
        shard_swarm(st, mesh), cfg, plan_m, mesh, 6, None, sc,
        stream=strm, control=ctl, pipeline=pipe, liveness=q,
    )
    for f in dataclasses.fields(fin_l):
        la, lb = getattr(fin_l, f.name), getattr(fin_d, f.name)
        if jnp.issubdtype(la.dtype, jax.dtypes.prng_key):
            la, lb = jax.random.key_data(la), jax.random.key_data(lb)
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb), err_msg=f.name
        )
    for f in stats_l._fields:
        a = np.asarray(getattr(stats_l, f))
        if a.dtype.kind in "biu":
            np.testing.assert_array_equal(
                a, np.asarray(getattr(stats_d, f)), err_msg=f
            )
    assert int(np.asarray(stats_l.adv_accusations).sum()) > 0


@pytest.mark.slow  # the matching scenario-parity flood witness keeps
# scenario kernel-parity in tier-1; the bucketed twin rides slow
def test_bucketed_scenario_kernel_receive_parity(setup):
    """The staircase-kernel receive path under an active scenario stays
    bit-identical to the scatter receive — the fault stage wraps the
    dissemination core ABOVE the receive-side choice."""
    from tpu_gossip.faults import compile_scenario

    _, mesh, sg, relabeled, position = setup
    plans = build_shard_plans(sg)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=8, fanout=2,
                      mode="push_pull")
    sc = compile_scenario(
        _chaos_spec(), n_peers=N, n_slots=sg.n_pad, total_rounds=8,
        node_map=lambda ids: position[np.asarray(ids)],
    )
    st = shard_swarm(
        init_sharded_swarm(sg, relabeled, position, cfg, origins=[0, 1],
                           key=jax.random.key(3)), mesh)
    fin_a, stats_a = simulate_dist(clone_state(st), cfg, sg, mesh, 6, None, sc)
    fin_b, stats_b = simulate_dist(st, cfg, sg, mesh, 6, plans, sc)
    np.testing.assert_array_equal(np.asarray(fin_a.seen), np.asarray(fin_b.seen))
    np.testing.assert_array_equal(
        np.asarray(stats_a.msgs_sent), np.asarray(stats_b.msgs_sent)
    )
    for f in ("alive", "declared_dead", "fault_held"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_a, f)), np.asarray(getattr(fin_b, f)),
            err_msg=f,
        )


@pytest.mark.slow  # tests/sim/test_faults.py::
# test_split_brain_stalls_at_boundary_then_heals keeps the stall-and-heal
# law in tier-1; this mesh rerun rides the slow lane
def test_split_brain_heals_on_the_mesh(matching_setup):
    """The acceptance scenario end-to-end on the mesh: coverage stalls at
    the partition boundary, then recovers past 99% after heal."""
    from tpu_gossip.faults import compile_scenario, scenario_from_dict
    from tpu_gossip.sim.metrics import recoverage_rounds

    g, plan, plan_m, mesh = matching_setup
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode="push_pull")
    st = _matching_state(g, cfg, origins=(0,))
    heal = 10

    def rows_of(ids):
        ids = np.asarray(ids)
        return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)

    spec = scenario_from_dict({"phases": [
        {"name": "split", "start": 0, "end": heal, "partition": "half"},
    ]})
    sc = compile_scenario(spec, n_peers=1500, n_slots=plan.n,
                          total_rounds=40, node_map=rows_of)
    fin, stats = simulate_dist(shard_swarm(st, mesh), cfg, plan_m, mesh, 30,
                               None, sc)
    cov = np.asarray(stats.coverage)
    group_b = np.asarray(sc.group_b)[0]
    exists = np.asarray(g.exists)
    share = (exists & ~group_b).sum() / exists.sum()
    assert (cov[:heal] <= share + 1e-6).all(), "traffic crossed the partition"
    # the erased configuration model leaves a ~1.5% unreachable tail at
    # this size, so "99%" is of the ACHIEVABLE ceiling (the no-fault
    # engine tests saturate at the same cov[-1] plateau)
    ceiling = cov[-1]
    assert ceiling > 0.95, f"epidemic never recovered (final {ceiling})"
    rec = recoverage_rounds(stats, heal, 0.99 * ceiling)
    assert 0 < rec <= 18, f"mesh re-coverage took {rec} rounds"
