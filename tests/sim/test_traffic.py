"""Streaming serving plane (traffic/): sustained many-message traffic on
the slot/Bloom dedup engine (docs/streaming_plane.md).

The serving plane's contracts, each test one rail:

- the age-out recycles a slot's column THROUGH the fused round tail: the
  (N, M) bitmap is a sliding window over live messages, bit-identical
  across all three tail implementations;
- a zero-rate stream — and ``stream=None`` — reproduce the fixed
  single-epidemic trajectory bit for bit (the injection draws come from
  the registered ``TRAFFIC_STREAM_SALT`` stream, never the protocol's
  5-way split);
- a LOADED run is bit-identical local vs sharded on the matching engine
  (full state + integer stats incl. the per-slot tracks), across modes,
  under a chaos scenario, and while a flash crowd joins — the acceptance
  criterion;
- measured conflation / Bloom-FP rates conform to the closed-form
  ``expected_conflations`` / ``bloom_false_positive_rate`` predictors in
  sim/metrics.py, k=1 and k>=2 regimes;
- mid-stream checkpoints resume bit-exactly; pre-stream checkpoints load
  with the implied round-0 leases;
- the steady-state report reconstructs per-message latency percentiles
  from the per-slot tracks alone;
- run_sim rejects impossible --stream configs with exit 2 and emits the
  steady-state serving block in the summary JSON.
"""

import json
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.state import (
    SwarmConfig,
    clone_state,
    init_swarm,
    load_swarm,
    save_swarm,
)
from tpu_gossip.core.topology import build_csr, preferential_attachment
from tpu_gossip.sim import metrics as M
from tpu_gossip.sim.engine import simulate
from tpu_gossip.traffic import (
    StreamError,
    compile_stream,
    min_feasible_ttl,
    slot_expiry,
)
from tpu_gossip.traffic.engine import apply_stream

N = 256


def seed_graph(n=N, seed=0):
    return build_csr(
        n, preferential_attachment(n, m=3, use_native=False,
                                   rng=np.random.default_rng(seed))
    )


def stream_setup(n=N, m=8, seed=1, origins=(0,), **cfg_kw):
    g = seed_graph(n)
    cfg = SwarmConfig(n_peers=n, msg_slots=m, fanout=2, mode="push_pull",
                      **cfg_kw)
    st = init_swarm(g, cfg, origins=list(origins) or None,
                    key=jax.random.key(seed))
    return g, cfg, st


# --- unit: lease mechanics and compile-time validation -------------------


def test_slot_expiry_mask():
    lease = jnp.asarray([-1, 0, 3, 7], dtype=jnp.int32)
    exp = np.asarray(slot_expiry(lease, jnp.asarray(7), ttl=4))
    # free slots never expire; age >= ttl does (7-0=7, 7-3=4), younger not
    np.testing.assert_array_equal(exp, [False, True, True, False])


def test_min_feasible_ttl_scales():
    assert min_feasible_ttl(1_000_000, 2) > min_feasible_ttl(1000, 2)
    assert min_feasible_ttl(1000, 8) < min_feasible_ttl(1000, 1)
    assert min_feasible_ttl(2, 1) >= 1


def test_compile_stream_rejections():
    rows = np.arange(16)
    ok = dict(rate=1.0, msg_slots=8, ttl=10, origin_rows=rows)
    compile_stream(**ok)  # the baseline config is valid
    with pytest.raises(StreamError, match=">= 0"):
        compile_stream(**{**ok, "rate": -1.0})
    with pytest.raises(StreamError, match="TTL"):
        compile_stream(**{**ok, "ttl": 0})
    with pytest.raises(StreamError, match="k_hashes"):
        compile_stream(**ok, k_hashes=9)
    with pytest.raises(StreamError, match="origin law"):
        compile_stream(**{**ok, "origins": "zipf"})
    with pytest.raises(StreamError, match="burst"):
        compile_stream(**ok, burst_every=-1)
    with pytest.raises(StreamError, match="row table"):
        compile_stream(**{**ok, "origin_rows": np.zeros((0,))})
    with pytest.raises(StreamError, match="hot_weight"):
        compile_stream(**ok, hot_weight=1.5)
    with pytest.raises(StreamError, match="hot_frac"):
        compile_stream(**ok, origins="hotspot", hot_frac=0.0)


# --- age-out semantics: the sliding window -------------------------------


def test_age_out_recycles_seeded_epidemic_through_tail():
    """A zero-rate stream still runs the age-out: the round-0 seeded
    epidemic's slot expires at round ttl, its column clears across the
    whole swarm in ONE round (the fused tail folds the expired mask into
    the producing selects), and the lease frees."""
    _, cfg, st = stream_setup(m=4)
    strm = compile_stream(rate=0.0, msg_slots=4, ttl=5,
                          origin_rows=np.arange(N))
    fin, stats = simulate(clone_state(st), cfg, 8, None, "fused", None,
                          None, strm)
    cov = np.asarray(stats.coverage)
    assert cov[3] > 0.1  # the epidemic was genuinely spreading
    assert (cov[5:] == 0).all()  # round 5's tail recycled slot 0 everywhere
    assert not np.asarray(fin.seen).any()
    assert (np.asarray(fin.slot_lease) == -1).all()
    assert np.asarray(stats.stream_expired).sum() == 1
    # the per-slot age track reads the lease's life: 1..ttl-1 then free
    age = np.asarray(stats.slot_age)[:, 0]
    np.testing.assert_array_equal(age[:5], [1, 2, 3, 4, -1][:5])


@pytest.mark.parametrize(
    "tail",
    [pytest.param("reference", marks=pytest.mark.slow), "fused",
     pytest.param("pallas", marks=pytest.mark.slow)],
)  # fused (the default) is the tier-1 witness; the other tails assert
# the same law and ride the slow lane
def test_stream_bit_identical_across_tails(tail):
    """The expired-column mask rides all three tail implementations
    bit-identically — the streaming extension of the round-tail
    equivalence (tests/sim/test_round_tail.py covers the fresh mask)."""
    _, cfg, st = stream_setup(m=8, churn_leave_prob=0.02,
                              churn_join_prob=0.2, rewire_slots=2)
    strm = compile_stream(rate=3.0, msg_slots=8, ttl=6,
                          origin_rows=np.arange(N))
    ref, sref = simulate(clone_state(st), cfg, 15, None, "reference", None,
                         None, strm)
    got, sgot = simulate(clone_state(st), cfg, 15, None, tail, None, None,
                         strm)
    for f in ("seen", "forwarded", "infected_round", "recovered",
              "slot_lease"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(got, f)),
            err_msg=f,
        )
    np.testing.assert_array_equal(
        np.asarray(sref.stream_expired), np.asarray(sgot.stream_expired)
    )
    assert np.asarray(sref.stream_expired).sum() > 0  # age-out genuinely ran


# --- injection semantics -------------------------------------------------


def test_counter_balance_k1_and_k2():
    """k=1: every live arrival lands (conflation counts, never drops) —
    injected == offered. k>=2: a Bloom-FP arrival is suppressed at
    ingestion — injected + conflated == offered. No churn, so no arrival
    is lost to a dead origin in either regime."""
    for k, m in ((1, 8), (2, 16)):
        _, cfg, st = stream_setup(m=m)
        strm = compile_stream(rate=4.0, msg_slots=m, ttl=1000,
                              origin_rows=np.arange(N), k_hashes=k)
        _, stats = simulate(clone_state(st), cfg, 30, None, "fused", None,
                            None, strm)
        off = np.asarray(stats.stream_offered).sum()
        inj = np.asarray(stats.stream_injected).sum()
        conf = np.asarray(stats.stream_conflated).sum()
        assert off > 0 and conf > 0
        if k == 1:
            assert inj == off
            assert conf < inj  # conflations ride, they don't suppress
        else:
            assert inj + conf == off  # suppressed = conflated counter


def _raw_injection_rows(stream, st, key, rnd=1):
    """Call the injection stage directly on a virgin swarm and read the
    rows its arrivals landed on (the per-law distribution probe)."""
    seen = jnp.zeros_like(st.seen)
    ir = jnp.full(st.seen.shape, -1, dtype=jnp.int16)
    lease = jnp.full((st.seen.shape[1],), -1, dtype=jnp.int16)
    seen2, _, _, telem = apply_stream(
        stream, key, jnp.asarray(rnd, jnp.int32), jnp.zeros((), jnp.int32),
        seen=seen, infected_round=ir, slot_lease=lease,
        row_ptr=st.row_ptr, col_idx=st.col_idx, exists=st.exists,
        alive=st.alive, declared_dead=st.declared_dead,
    )
    return np.flatnonzero(np.asarray(seen2).any(axis=1)), telem


def test_hotspot_origin_law_concentrates():
    g, cfg, st = stream_setup(m=64, origins=())
    strm = compile_stream(
        rate=400.0, msg_slots=64, ttl=50, origin_rows=np.arange(N),
        origins="hotspot", hot_frac=0.05, hot_weight=0.9, max_inject=512,
    )
    rows, _ = _raw_injection_rows(strm, st, jax.random.key(11))
    hot_n = int(0.05 * N)
    hot_present = len(rows[rows < hot_n]) / hot_n
    cold_present = len(rows[rows >= hot_n]) / (N - hot_n)
    # ~90% of ~400 arrivals over the 12 hot ids saturates them; the 10%
    # uniform remainder touches only a sliver of the other 244 rows
    assert hot_present == 1.0, rows
    assert cold_present < 0.3, cold_present
    assert len(rows) > 20


def test_degree_origin_law_favors_hubs():
    g, cfg, st = stream_setup(m=64, origins=())
    strm = compile_stream(
        rate=400.0, msg_slots=64, ttl=50, origin_rows=np.arange(N),
        origins="degree", max_inject=512,
    )
    # count landed BITS per row (m=64 slots make per-row slot collisions
    # rare, so bits approximate arrival counts — row presence would
    # saturate at this rate) over several independent batches
    counts = np.zeros(N)
    for s in range(6):
        seen = jnp.zeros_like(st.seen)
        ir = jnp.full(st.seen.shape, -1, dtype=jnp.int16)
        lease = jnp.full((64,), -1, dtype=jnp.int16)
        seen2, _, _, _ = apply_stream(
            strm, jax.random.key(100 + s), jnp.asarray(1, jnp.int32),
            jnp.zeros((), jnp.int32), seen=seen, infected_round=ir,
            slot_lease=lease, row_ptr=st.row_ptr, col_idx=st.col_idx,
            exists=st.exists, alive=st.alive,
            declared_dead=st.declared_dead,
        )
        counts += np.asarray(seen2).sum(axis=1)
    deg = seed_graph().degrees
    top = np.argsort(deg)[-10:]
    bottom = np.argsort(deg)[:100]
    assert counts[top].mean() > 2 * counts[bottom].mean(), (
        counts[top].mean(), counts[bottom].mean(),
    )


def test_degree_origin_law_requires_csr():
    from tpu_gossip.core.matching_topology import matching_powerlaw_graph

    g, _ = matching_powerlaw_graph(256, fanout=2, key=jax.random.key(0),
                                   export_csr=False)
    cfg = SwarmConfig(n_peers=g.n_pad, msg_slots=8, fanout=2)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0], exists=g.exists,
                    key=jax.random.key(1))
    strm = compile_stream(rate=2.0, msg_slots=8, ttl=20,
                          origin_rows=np.flatnonzero(np.asarray(g.exists)),
                          origins="degree")
    with pytest.raises(ValueError, match="export_csr"):
        simulate(st, cfg, 4, None, "fused", None, None, strm)


def test_dead_origin_loses_arrival():
    """An arrival whose drawn origin is down is offered but not injected —
    a user knocking on a dead peer."""
    import dataclasses

    _, cfg, st = stream_setup(m=8, origins=())
    # kill everything: every arrival must be lost at ingestion
    st = dataclasses.replace(st, alive=jnp.zeros_like(st.alive))
    strm = compile_stream(rate=4.0, msg_slots=8, ttl=100,
                          origin_rows=np.arange(N))
    _, stats = simulate(st, cfg, 10, None, "fused", None, None, strm)
    assert np.asarray(stats.stream_offered).sum() > 0
    assert np.asarray(stats.stream_injected).sum() == 0


# --- determinism rails ---------------------------------------------------


def _assert_states_equal(a, b):
    for f in type(a).__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)) if f != "rng"
            else np.asarray(jax.random.key_data(a.rng)),
            np.asarray(getattr(b, f)) if f != "rng"
            else np.asarray(jax.random.key_data(b.rng)),
            err_msg=f,
        )


@pytest.mark.parametrize(
    "shape",
    ["none-vs-zero", pytest.param("with-churn", marks=pytest.mark.slow)],
)  # one zero-rate witness in tier-1; the churn compose rides slow
def test_zero_rate_stream_bit_identical_to_no_stream(shape):
    """THE determinism rail: a zero-rate stream must reproduce the fixed
    single-epidemic trajectory bit for bit — the injection stage draws
    from its own registered PRNG stream (TRAFFIC_STREAM_SALT), so the
    protocol's 5-way split never moves. The age-out is gated the same
    way: a ttl longer than the horizon never bites."""
    extra = {} if shape == "none-vs-zero" else dict(
        churn_leave_prob=0.02, churn_join_prob=0.2, rewire_slots=2,
    )
    _, cfg, st = stream_setup(m=8, **extra)
    strm = compile_stream(rate=0.0, msg_slots=8, ttl=1000,
                          origin_rows=np.arange(N))
    base, _ = simulate(clone_state(st), cfg, 12)
    zero, _ = simulate(clone_state(st), cfg, 12, None, "fused", None, None,
                       strm)
    _assert_states_equal(base, zero)


# --- the acceptance criterion: loaded local vs sharded, bit-identical ----


STREAM_STATE_FIELDS = (
    "seen", "exists", "alive", "rewired", "declared_dead", "recovered",
    "last_hb", "rewire_targets", "fault_held", "slot_lease", "join_round",
    "admitted_by", "degree_credit",
)
STREAM_STAT_FIELDS = (
    "msgs_sent", "coverage", "n_alive", "n_members",
    "stream_offered", "stream_injected", "stream_conflated",
    "stream_expired", "slot_infected", "slot_age",
)


@pytest.fixture(scope="module")
def matching_stream_setup():
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )
    from tpu_gossip.dist import make_mesh, shard_matching_plan

    g, plan = matching_powerlaw_graph_sharded(
        800, 8, fanout=2, key=jax.random.key(0), growth_rows=32,
    )
    mesh = make_mesh(8)
    return g, plan, shard_matching_plan(plan, mesh), mesh


def _matching_rows(plan, ids):
    ids = np.asarray(ids)
    return (ids // plan.n_per) * plan.n_blk + (ids % plan.n_per)


@pytest.mark.parametrize(
    "mode,law,compose",
    [
        pytest.param("push_pull", "uniform", None, marks=pytest.mark.slow),
        ("flood", "hotspot", None),
        pytest.param("push_pull", "uniform", "scenario",
                     marks=pytest.mark.slow),
        pytest.param("push_pull", "uniform", "growth",
                     marks=pytest.mark.slow),
    ],  # one loaded-run parity witness in tier-1, three on the slow lane
    ids=["push_pull", "flood_hotspot", "chaos_scenario", "flash_crowd"],
)
def test_matching_stream_local_vs_sharded_bit_identical(
    matching_stream_setup, mode, law, compose
):
    """THE acceptance criterion: a LOADED run — sustained injection +
    age-out — is bit-identical local vs sharded on the matching engine
    (full state + integer stats incl. the per-slot serving tracks),
    across modes, under a chaos scenario with every fault class active,
    and while a flash crowd joins. Streaming draws happen at GLOBAL
    shape outside shard_map from the dedicated traffic stream."""
    from tpu_gossip.dist import shard_swarm, simulate_dist
    from tpu_gossip.growth import compile_growth, matching_admit_rows

    g, plan, plan_m, mesh = matching_stream_setup
    extra = dict(rewire_slots=2) if compose == "growth" else {}
    if compose == "scenario":
        extra = dict(churn_leave_prob=0.02, churn_join_prob=0.2,
                     rewire_slots=2)
    cfg = SwarmConfig(n_peers=plan.n, msg_slots=8, fanout=2, mode=mode,
                      **extra)
    st = init_swarm(g.as_padded_graph(), cfg, origins=[0, 5],
                    exists=g.exists, key=jax.random.key(3))
    strm = compile_stream(
        rate=4.0, msg_slots=8, ttl=7,
        origin_rows=_matching_rows(plan, np.arange(800)),
        origins=law, burst_every=3,
    )
    sc = gp = None
    if compose == "scenario":
        from tests.sim.test_dist import _chaos_spec
        from tpu_gossip.faults import compile_scenario

        sc = compile_scenario(
            _chaos_spec(), n_peers=800, n_slots=plan.n, total_rounds=10,
            node_map=lambda ids: _matching_rows(plan, ids),
        )
    elif compose == "growth":
        gp = compile_growth(
            n_initial=800, target=900, n_slots=plan.n, joins_per_round=16,
            attach_m=2, admit_rows=matching_admit_rows(plan, 100),
        )
    fin_l, stats_l = simulate(clone_state(st), cfg, 10, plan, "fused", sc,
                              gp, strm)
    fin_d, stats_d = simulate_dist(shard_swarm(st, mesh), cfg, plan_m,
                                   mesh, 10, None, sc, gp, None, False,
                                   strm)
    for f in STREAM_STATE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(fin_l, f)), np.asarray(getattr(fin_d, f)),
            err_msg=f,
        )
    for f in STREAM_STAT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(stats_l, f)), np.asarray(getattr(stats_d, f)),
            err_msg=f,
        )
    # the load must actually bite, or the parity is vacuous
    assert np.asarray(stats_l.stream_injected).sum() > 10
    assert np.asarray(stats_l.stream_expired).sum() > 0
    if compose == "scenario":
        assert np.asarray(stats_l.msgs_dropped).sum() > 0
    if compose == "growth":
        assert np.asarray(stats_l.n_members)[-1] == 900


# --- checkpointing: the lease table is the stream cursor -----------------


def test_mid_stream_checkpoint_resumes_bit_exactly(tmp_path):
    _, cfg, st = stream_setup(m=8)
    strm = compile_stream(rate=3.0, msg_slots=8, ttl=10,
                          origin_rows=np.arange(N))
    mid, _ = simulate(clone_state(st), cfg, 12, None, "fused", None, None,
                      strm)
    assert (np.asarray(mid.slot_lease) >= 0).any()  # genuinely mid-stream
    save_swarm(tmp_path / "mid.npz", mid)
    restored = load_swarm(tmp_path / "mid.npz")
    np.testing.assert_array_equal(
        np.asarray(mid.slot_lease), np.asarray(restored.slot_lease)
    )
    fin_a, _ = simulate(mid, cfg, 10, None, "fused", None, None, strm)
    fin_b, _ = simulate(restored, cfg, 10, None, "fused", None, None, strm)
    _assert_states_equal(fin_a, fin_b)


def test_pre_stream_checkpoint_loads_with_implied_leases(tmp_path):
    """A checkpoint saved before the streaming plane existed loads with
    every occupied slot leased at round 0 and the rest free — attaching
    a stream treats the old epidemics as round-0 injections."""
    _, cfg, st = stream_setup(m=4)
    mid, _ = simulate(clone_state(st), cfg, 3)
    save_swarm(tmp_path / "new.npz", mid)
    data = dict(np.load(tmp_path / "new.npz"))
    assert "field_slot_lease" in data
    del data["field_slot_lease"]  # forge the pre-stream format
    np.savez(tmp_path / "old.npz", **data)
    restored = load_swarm(tmp_path / "old.npz")
    lease = np.asarray(restored.slot_lease)
    occupied = np.asarray(mid.seen).any(axis=0)
    np.testing.assert_array_equal(lease, np.where(occupied, 0, -1))
    # and the restored swarm runs under a freshly-attached stream
    strm = compile_stream(rate=1.0, msg_slots=4, ttl=20,
                          origin_rows=np.arange(N))
    fin, _ = simulate(restored, cfg, 3, None, "fused", None, None, strm)
    assert int(fin.round) == 6


# --- conformance: measured rates vs the closed-form predictors -----------


def test_conflation_rate_conforms_k1():
    """k=1 filling regime (no expiry inside the horizon): every arrival
    inserts, so the measured conflation total must track
    ``expected_conflations(R, M)`` with R the realized arrival count —
    the predictor's exact model (sequential uniform hashing)."""
    _, cfg, st = stream_setup(m=64, origins=())
    strm = compile_stream(rate=4.0, msg_slots=64, ttl=1000,
                          origin_rows=np.arange(N))
    _, stats = simulate(clone_state(st), cfg, 40, None, "fused", None,
                        None, strm)
    R = int(np.asarray(stats.stream_offered).sum())
    measured = int(np.asarray(stats.stream_conflated).sum())
    predicted = M.expected_conflations(R, 64)
    assert R > 100
    assert abs(measured - predicted) < 0.15 * predicted, (
        measured, predicted,
    )


def test_bloom_fp_rate_conforms_k2():
    """k=2 Bloom regime: the suppression probability at any instant is
    ``fill^k`` — exactly ``bloom_false_positive_rate``'s law, with the
    fill read off the per-slot age track (suppressed messages are NOT
    inserted, so the textbook kR-bits fill model only applies to the
    low-fill head; the law itself must hold at every occupancy)."""
    g = seed_graph()
    cfg = SwarmConfig(n_peers=N, msg_slots=128, fanout=2, mode="push_pull")
    st = init_swarm(g, cfg, key=jax.random.key(5))
    strm = compile_stream(rate=6.0, msg_slots=128, ttl=1000,
                          origin_rows=np.arange(N), k_hashes=2)
    _, stats = simulate(clone_state(st), cfg, 50, None, "fused", None,
                        None, strm)
    off = np.asarray(stats.stream_offered)
    sup = np.asarray(stats.stream_conflated)
    age = np.asarray(stats.slot_age)
    # fill BEFORE round r = leased fraction after round r-1
    fill = np.concatenate([[0.0], (age >= 0).mean(axis=1)[:-1]])
    predicted = float((off * fill**2).sum())
    measured = int(sup.sum())
    assert measured > 50
    assert abs(measured - predicted) < 0.2 * max(predicted, 1), (
        measured, predicted,
    )
    # the low-fill head (first rounds) also matches the closed-form's
    # kR-random-bits fill model directly: R landed messages set <= kR bits
    head = 10
    R_head = int(np.asarray(stats.stream_injected)[:head].sum())
    fp_pred = M.bloom_false_positive_rate(R_head, 128, 2)
    fp_meas = sup[:head].sum() / max(off[:head].sum(), 1)
    assert fp_meas <= fp_pred + 0.1, (fp_meas, fp_pred)


def test_steady_state_conflation_band_k1():
    """Steady state WITH expiry: conflated arrivals ride the incumbent
    lease without renewing it, so live leases L solve the self-consistent
    occupancy L = ttl*rate*(1 - L/M) and the measured conflation rate
    sits at L/M — bounded above by the predictor's marginal conflation
    probability after rate*ttl inserts (the insert-every-arrival model
    fills strictly faster)."""
    _, cfg, st = stream_setup(m=64, origins=())
    rate, ttl = 2.0, 16
    strm = compile_stream(rate=rate, msg_slots=64, ttl=ttl,
                          origin_rows=np.arange(N))
    _, stats = simulate(clone_state(st), cfg, 120, None, "fused", None,
                        None, strm)
    off = np.asarray(stats.stream_offered)[40:]
    conf = np.asarray(stats.stream_conflated)[40:]
    measured = conf.sum() / max(off.sum(), 1)
    L = ttl * rate * 64 / (64 + ttl * rate)
    predicted = L / 64
    assert abs(measured - predicted) < 0.08, (measured, predicted)
    # the predictor's MARGINAL conflation probability after rate*ttl
    # inserts (its occupancy fraction) upper-bounds the steady state:
    # conflated arrivals never renew leases, so expiry keeps occupancy
    # strictly below the insert-every-arrival fill
    R = rate * ttl
    upper = M.expected_conflations(R + 1, 64) - M.expected_conflations(R, 64)
    assert measured < upper + 0.02, (measured, upper)


# --- steady-state report: per-message latency from the slot tracks -------


def test_stream_episodes_reconstruction_synthetic():
    """A hand-built per-slot track: one lease covering at round 3 of its
    life, one recycled uncovered, one censored by the horizon."""
    stats = types.SimpleNamespace(
        # rounds x 2 slots
        slot_age=np.asarray([
            [0, -1], [1, -1], [2, 0], [3, 1], [-1, 2], [-1, 3],
        ]),
        slot_infected=np.asarray([
            [10, 0], [40, 0], [95, 5], [99, 10], [0, 20], [0, 30],
        ]),
        n_alive=np.full(6, 100),
        coverage=np.zeros(6, dtype=np.float32),
    )
    eps = M.stream_episodes(stats, target=0.9)
    by_slot = {}
    for e in eps:
        by_slot.setdefault(e["slot"], []).append(e)
    (s0,), (s1,) = by_slot[0], by_slot[1]
    assert s0["start_round"] == 1 and s0["end_round"] == 4
    assert s0["completed_age"] == 2  # hit 95/100 >= 0.9 at age 2
    assert s1["end_round"] == -1  # censored: horizon cut it
    assert s1["completed_age"] == -1  # never covered


def test_steady_state_report_on_loaded_run():
    _, cfg, st = stream_setup(m=8)
    strm = compile_stream(rate=2.0, msg_slots=8, ttl=18,
                          origin_rows=np.arange(N))
    _, stats = simulate(clone_state(st), cfg, 80, None, "fused", None,
                        None, strm)
    rep = M.steady_state_report(stats, target=0.9, round_seconds=5.0,
                                warmup_rounds=18)
    assert rep["episodes_completed"] > 5
    p = rep["rounds_to_coverage"]
    assert p["p50"] is not None and p["p50"] <= p["p99"]
    assert p["p99"] < 18  # covered inside the lease, or not counted
    assert rep["delivered_msgs_per_sec"] == pytest.approx(
        rep["delivered_per_round"] / 5.0, rel=1e-6, abs=1e-4
    )
    assert 0 <= rep["delivery_ratio"] <= 1
    assert rep["msgs_offered"] >= rep["msgs_injected"]


@pytest.mark.slow  # load-collapse demonstration; the counter-balance
# and stream bit-identity laws stay tier-1
def test_saturation_collapses_delivery_ratio():
    """The saturation story the bench curve measures, at test scale: at a
    few messages per round the swarm delivers nearly every closed
    episode; far past the slot budget the delivery ratio collapses —
    the conflation/suppression knee the predictors price."""
    _, cfg, st = stream_setup(m=4, origins=())
    reports = []
    for rate in (0.5, 8.0):
        strm = compile_stream(rate=rate, msg_slots=4, ttl=12,
                              origin_rows=np.arange(N))
        _, stats = simulate(clone_state(st), cfg, 80, None, "fused", None,
                            None, strm)
        reports.append(M.steady_state_report(stats, target=0.9,
                                             warmup_rounds=12))
    lo, hi = reports
    assert lo["delivery_ratio"] > 0.6
    assert hi["conflation_rate"] > lo["conflation_rate"]
    # offered/delivered diverge at saturation: most arrivals conflate
    # into incumbents instead of opening their own episode
    assert hi["delivered_per_round"] < 0.5 * hi["offered_per_round"]


# --- CLI -----------------------------------------------------------------


def _run(argv):
    from tpu_gossip.cli.run_sim import main

    return main(argv)


BASE = ["--peers", "96", "--slots", "4", "--fanout", "2", "--quiet"]


def test_cli_stream_rejections(capsys):
    # stream-shaping flags without --stream
    assert _run(BASE + ["--rounds", "20", "--slot-ttl", "9"]) == 2
    assert _run(BASE + ["--rounds", "20", "--stream-origins", "degree"]) == 2
    # negative rate
    assert _run(BASE + ["--rounds", "20", "--stream", "-1"]) == 2
    # steady state needs a fixed horizon (run-to-coverage stops on slot 0)
    assert _run(BASE + ["--rounds", "0", "--stream", "2"]) == 2
    # (--profile-round now COMPOSES with --stream — the loaded stage
    # decomposition; pinned in tests/unit/test_profiling.py)
    # TTL below the feasible coverage horizon
    assert _run(BASE + ["--rounds", "20", "--stream", "2",
                        "--slot-ttl", "2"]) == 2
    err = capsys.readouterr().err
    assert "feasible" in err
    # Bloom planes live in the slot dimension
    assert _run(BASE + ["--rounds", "20", "--stream", "2",
                        "--stream-hashes", "5"]) == 2
    # epoch re-partition would permute the compiled origin tables
    assert _run(BASE + ["--rounds", "20", "--stream", "2", "--shard",
                        "--remat-every", "8"]) == 2


def test_cli_stream_smoke_summary(capsys):
    rc = _run(BASE + ["--rounds", "40", "--stream", "2",
                      "--slot-ttl", "12"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    s = summary["stream"]
    assert s["rate"] == 2.0 and s["slot_ttl"] == 12
    for key in ("delivered_msgs_per_sec", "conflation_rate",
                "rounds_to_coverage", "delivery_ratio",
                "episodes_completed"):
        assert key in s, key
    assert s["msgs_offered"] > 0
