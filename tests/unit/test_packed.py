"""core/packed.py — the bit-true storage codec.

Pins: the LSB-first bit-order contract (a hand-computed vector, so the
encoding can never silently flip), exact pack/unpack inversion on odd
widths, the numpy twins matching the jnp codec byte for byte (the
checkpoint stores depend on it), the packed coverage accessor, and the
registry's packed pricing arithmetic (142 -> 67 B/peer at the headline
shape).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.packed import (
    BIT_PLANES,
    FLAG_BITS,
    FLAG_PLANES,
    bit_column,
    np_pack_bits,
    np_pack_flags,
    np_unpack_bits,
    np_unpack_flag,
    pack_bits,
    pack_flags,
    pack_state,
    unpack_bits,
    unpack_flag,
    unpack_state,
)
from tpu_gossip.core.state import (
    PLANES,
    SwarmConfig,
    init_swarm,
    state_bytes_per_peer,
    state_plane_bytes,
)
from tpu_gossip.core.topology import (
    build_csr,
    configuration_model,
    powerlaw_degree_sequence,
)


def _state(n=200, m=13, **cfg_kw):
    rng = np.random.default_rng(0)
    g = build_csr(
        n, configuration_model(
            powerlaw_degree_sequence(n, gamma=2.5, rng=rng), rng=rng
        )
    )
    cfg = SwarmConfig(n_peers=n, msg_slots=m, fanout=2, **cfg_kw)
    st = init_swarm(g, cfg, origins=[0, 3], key=jax.random.key(1))
    st.silent = st.silent.at[5].set(True)
    st.recovered = st.recovered.at[7, m - 1].set(True)
    return st


def test_bit_order_is_lsb_first_pinned():
    """The encoding contract: bit k of word j holds slot 8*j + k. A
    hand-computed vector — if this flips, every checkpoint on disk
    becomes unreadable, so it is a pinned constant, not a convention."""
    x = jnp.asarray([[True, False, True, False, False, False, False, False,
                      True]])  # slots 0,2 -> 0b101 = 5; slot 8 -> word 1
    words = pack_bits(x)
    assert words.dtype == jnp.uint8 and words.shape == (1, 2)
    assert words.tolist() == [[5, 1]]
    back = unpack_bits(words, 9)
    assert bool((back == x).all())


@pytest.mark.parametrize("m", [1, 7, 8, 9, 16, 33])
def test_pack_unpack_roundtrip_odd_widths(m):
    rng = np.random.default_rng(m)
    x = jnp.asarray(rng.random((37, m)) < 0.3)
    words = pack_bits(x)
    assert words.shape == (37, -(-m // 8)) and words.dtype == jnp.uint8
    assert bool((unpack_bits(words, m) == x).all())
    # numpy twin: byte-for-byte the same words (the store's codec)
    assert (np.asarray(words) == np_pack_bits(np.asarray(x))).all()
    assert (np_unpack_bits(np.asarray(words), m) == np.asarray(x)).all()


def test_flags_word_roundtrip_and_bit_assignment():
    rng = np.random.default_rng(7)
    planes = {n: jnp.asarray(rng.random(50) < 0.4) for n in FLAG_PLANES}
    word = pack_flags(planes)
    assert word.dtype == jnp.uint8
    for name, bit in FLAG_BITS.items():
        assert bool((unpack_flag(word, name) == planes[name]).all())
        # the bit assignment is a stored-format constant
        assert ((np.asarray(word) >> bit) & 1
                == np.asarray(planes[name])).all(), name
    npw = np_pack_flags({n: np.asarray(v) for n, v in planes.items()})
    assert (np.asarray(word) == npw).all()
    for name in FLAG_PLANES:
        assert (np_unpack_flag(npw, name) == np.asarray(planes[name])).all()


def test_state_roundtrip_exact():
    st = _state(m=13, churn_join_prob=0.02, churn_leave_prob=0.01,
                rewire_slots=2)
    p = pack_state(st)
    assert p.msg_slots == 13
    st2 = unpack_state(p)
    for f in dataclasses.fields(type(st)):
        a, b = getattr(st, f.name), getattr(st2, f.name)
        if f.name == "rng":
            assert (jax.random.key_data(a) == jax.random.key_data(b)).all()
        else:
            assert a.dtype == b.dtype and a.shape == b.shape
            assert bool((a == b).all()), f.name


def test_packed_coverage_matches_unpacked():
    st = _state(m=16)
    p = pack_state(st)
    for slot in (0, 7, 15):
        assert float(p.coverage(slot)) == float(st.coverage(slot))
    assert bool((bit_column(p.seen, 0) == st.seen[:, 0]).all())


def test_every_bit_and_flag_plane_is_registry_declared():
    """The codec's membership derives from the PLANES registry — a plane
    packed here but not declared there (or vice versa) is a drift the
    mem tier and the checkpoint format would disagree about."""
    reg = {p.name: p.packed for p in PLANES}
    assert set(BIT_PLANES) == {n for n, v in reg.items() if v == "bits"}
    assert set(FLAG_PLANES) == {
        n for n, v in reg.items() if v is not None and v.startswith("flag:")
    }
    # flag bit indices match the registry's flag:<k> declarations
    for name, bit in FLAG_BITS.items():
        assert reg[name] == f"flag:{bit}"


def test_packed_pricing_arithmetic():
    """Hand sums at (N=100, M=16): bits planes cost ceil(M/8) B/row, the
    six flag planes one shared byte, everything else unchanged — and the
    headline figure lands at 67 B/peer (was 142)."""
    by_plane = state_plane_bytes(100, 16, packed=True)
    assert by_plane["seen"] == 100 * 2
    assert by_plane["fault_held"] == 100 * 2
    assert by_plane["infected_round"] == 100 * 16 * 2  # not packable
    assert by_plane["exists"] == 100  # the shared flags byte, charged once
    for other in ("alive", "silent", "declared_dead", "rewired",
                  "quarantine"):
        assert by_plane[other] == 0
    assert by_plane["last_hb"] == 100 * 2
    # odd widths round the word count up
    assert state_plane_bytes(100, 13, packed=True)["seen"] == 100 * 2
    assert state_plane_bytes(100, 17, packed=True)["seen"] == 100 * 3
    assert state_bytes_per_peer(1_000_000, 16) == pytest.approx(142.0, abs=0.01)
    assert state_bytes_per_peer(1_000_000, 16, packed=True) == pytest.approx(
        67.0, abs=0.01
    )
