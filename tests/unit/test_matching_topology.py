"""Structured-matching topology: pipeline algebra, model statistics, and
delivery parity with the general-graph paths (SURVEY.md §4 conformance
strategy — kernel twins must be bit-exact where deterministic, statistical
twins where sampled)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.matching_topology import (
    MatchingPlan,
    _plan_classes,
    matching_powerlaw_graph,
    quantile_degrees,
)
from tpu_gossip.kernels.matching import matching_flood, matching_sampled
from tpu_gossip.kernels.gossip import flood_all
from tpu_gossip.kernels.permute import (
    BLOCK_ROWS,
    inverse_tables,
    lane_shuffle,
    transpose_pass,
    untranspose_pass,
)


def test_lane_shuffle_matches_numpy():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**31, (BLOCK_ROWS, 128), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, 128, (BLOCK_ROWS, 128), dtype=np.int8))
    out = lane_shuffle(x, idx)
    ref = np.take_along_axis(
        np.asarray(x), np.asarray(idx).astype(np.int64), axis=1
    )
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_transpose_pass_roundtrip():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 2**31, (BLOCK_ROWS, 128), dtype=np.int32))
    np.testing.assert_array_equal(
        np.asarray(untranspose_pass(transpose_pass(x))), np.asarray(x)
    )


def test_inverse_tables_invert():
    rng = np.random.default_rng(2)
    perm = np.stack([rng.permutation(128) for _ in range(BLOCK_ROWS)]).astype(
        np.int8
    )
    x = jnp.asarray(rng.integers(0, 2**31, (BLOCK_ROWS, 128), dtype=np.int32))
    idx = jnp.asarray(perm)
    out = lane_shuffle(lane_shuffle(x, idx), inverse_tables(idx))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def _small_plan(n=3000, fanout=1, key=0):
    return matching_powerlaw_graph(n, key=jax.random.key(key), fanout=fanout)


def test_pairing_is_fixed_point_free_involution():
    _, plan = _small_plan()
    r = plan.rows
    iota = jnp.arange(r * 128, dtype=jnp.int32).reshape(r, 128)
    part = plan.partner(iota)
    back = plan.partner(part)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(iota))
    assert not bool(jnp.any(part == iota))  # no fixed points anywhere


def test_quantile_degrees_match_law():
    deg = quantile_degrees(100_000, 2.5, 2, 316)
    assert deg.min() == 2 and 200 <= deg.max() <= 316
    assert (np.diff(deg) >= 0).all()
    # tail exponent: P(D >= d) ~ d^-(gamma-1); regress the empirical CCDF
    ds = np.unique(deg)
    ccdf = np.array([(deg >= d).mean() for d in ds])
    keep = (ds >= 2) & (ds <= 100)
    slope = np.polyfit(np.log(ds[keep]), np.log(ccdf[keep]), 1)[0]
    assert -1.75 < slope < -1.25  # gamma-1 = 1.5


def test_classes_cover_and_pad_lightly():
    deg = quantile_degrees(50_000, 2.5, 2, 224)
    classes = _plan_classes(deg)
    total_nodes = sum(c for _, _, c, _, _ in classes)
    assert total_nodes == 50_000
    real = int(deg.sum())
    padded = sum(c * w for _, _, c, w, _ in classes)
    assert real <= padded <= real * 1.08
    aligned_off_ok = True
    for (i, off, c, w, cs) in classes:
        assert (deg[i : i + c] <= w).all()
        # populous classes 1024-align their plane stride (Pallas fold
        # blocks); hub classes stay exact (alignment would multiply their
        # span ~1024/count-fold)
        if c >= 8192:
            assert c <= cs < c + 1024 and cs % 1024 == 0
            aligned_off_ok &= off % 1024 == 0
        else:
            assert cs == c
    assert aligned_off_ok  # aligned classes lead the slot layout


def test_exported_csr_is_consistent():
    graph, plan = _small_plan()
    g = graph.to_host_graph()
    deg = np.diff(g.row_ptr)
    # symmetric: every edge appears in both directions
    pairs = set()
    for u in range(g.n):
        for v in g.col_idx[g.row_ptr[u] : g.row_ptr[u + 1]]:
            assert v != u  # no self loops
            pairs.add((u, int(v)))
    for u, v in pairs:
        assert (v, u) in pairs
    # no duplicate neighbor entries
    for u in range(200):
        nbrs = g.col_idx[g.row_ptr[u] : g.row_ptr[u + 1]]
        assert len(set(nbrs.tolist())) == len(nbrs)
    # valid-slot count == directed edge count
    assert int(jnp.sum(plan.valid)) == len(g.col_idx)
    # degrees ascend with node id (class-sorted relabelling) up to erasure
    assert deg.mean() > 2.0


def test_erasure_fraction_small():
    graph, plan = _small_plan()
    deg_law = quantile_degrees(3000, 2.5, 2, max(3, int(round(3000 ** (1 / 1.5)))))
    realized = int(jnp.sum(plan.valid))
    assert realized >= 0.88 * deg_law.sum()  # few % pad/self/dup erasure


def test_flood_parity_with_csr():
    graph, plan = _small_plan()
    n_state = plan.n + 1
    rng = np.random.default_rng(3)
    transmit = jnp.asarray(rng.random((n_state, 8)) < 0.05)
    got = matching_flood(plan, transmit, 8)
    want = flood_all(
        transmit,
        jnp.asarray(graph.row_ptr),
        jnp.asarray(graph.col_idx),
    )
    # real rows only: the sentinel row's erased (n, n) self-edges deliver
    # under raw flood_all but the sentinel is never alive in the engine
    np.testing.assert_array_equal(
        np.asarray(got)[: plan.n], np.asarray(want)[: plan.n]
    )


def test_sampled_delivery_statistics():
    """Push k=1: each live sender fires ~fanout edges; delivered bits land
    only on true neighbors; expected per-round infection rate matches the
    CSR twin within sampling noise."""
    graph, plan = _small_plan()
    n_state = plan.n + 1
    transmit = jnp.zeros((n_state, 1), bool).at[: plan.n : 7, 0].set(True)
    g = graph.to_host_graph()
    nbr = [set() for _ in range(n_state)]
    for u in range(g.n):
        for v in g.col_idx[g.row_ptr[u] : g.row_ptr[u + 1]]:
            nbr[u].add(int(v))
    allowed = np.zeros(n_state, bool)
    senders = np.flatnonzero(np.asarray(transmit[:, 0]))
    for s in senders:
        for v in nbr[s]:
            allowed[v] = True
    hits = np.zeros(n_state)
    trials = 40
    for t in range(trials):
        inc, msgs = matching_sampled(
            plan, transmit, None, 1, jax.random.key(100 + t),
            do_push=True, do_pull=False,
        )
        inc = np.asarray(inc[:, 0])
        assert not (inc & ~allowed).any()  # only true neighbors receive
        hits += inc
    assert hits[allowed].sum() > 0
    # expected pushes per sender ~ fanout; messages scale with senders
    assert 0.3 * len(senders) < float(msgs) < 3.0 * len(senders)


@pytest.mark.slow  # statistical twin sweep; the structural pairing tests
# keep the matching topology in tier-1
def test_push_pull_reaches_coverage_like_csr_twin():
    """Statistical twin: rounds-to-90% on the matching graph vs the XLA
    exactly-k path on the EXPORTED CSR are within a couple of rounds."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import gossip_round

    graph, plan = _small_plan(n=4000)
    cfg = SwarmConfig(n_peers=plan.n + 1, msg_slots=1, mode="push_pull", fanout=1)
    state = init_swarm(
        graph.as_padded_graph(), cfg, origins=[0], exists=graph.exists
    )

    def rounds_to(state, plan_arg, target=0.9, cap=40):
        r = 0
        while float(state.coverage(0)) < target and r < cap:
            state, _ = gossip_round(state, cfg, plan_arg)
            r += 1
        return r

    r_matching = rounds_to(state, plan)
    state2 = init_swarm(
        graph.as_padded_graph(), cfg, origins=[0], exists=graph.exists
    )
    r_xla = rounds_to(state2, None)
    assert abs(r_matching - r_xla) <= 3
    assert r_matching < 40


def test_msgs_accounting_matches_popcount_bound():
    graph, plan = _small_plan()
    n_state = plan.n + 1
    transmit = jnp.ones((n_state, 4), bool)
    inc, msgs = matching_sampled(
        plan, transmit, None, 4, jax.random.key(0),
        do_push=True, do_pull=True,
    )
    n_edges = int(jnp.sum(plan.valid))
    # push: ~fanout/deg per edge * 4 bits; pull: ~1/deg per edge * (1+4)
    assert 0 < int(msgs) < n_edges * 9


def test_multi_word_groups():
    graph, plan = _small_plan()
    n_state = plan.n + 1
    rng = np.random.default_rng(5)
    transmit = jnp.asarray(rng.random((n_state, 40)) < 0.1)
    got = matching_flood(plan, transmit, 40)
    want = flood_all(
        transmit, jnp.asarray(graph.row_ptr), jnp.asarray(graph.col_idx)
    )
    np.testing.assert_array_equal(
        np.asarray(got)[: plan.n], np.asarray(want)[: plan.n]
    )


def test_receptive_rows_gate():
    graph, plan = _small_plan()
    n_state = plan.n + 1
    transmit = jnp.ones((n_state, 2), bool)
    rec = jnp.zeros((n_state,), bool)
    inc, msgs = matching_sampled(
        plan, transmit, None, 2, jax.random.key(1),
        receptive_rows=rec, do_push=True, do_pull=True,
    )
    assert not bool(jnp.any(inc))


@pytest.mark.slow  # model-statistics sweep; quantile-degree and involution
# invariants pin the generator in tier-1
def test_degree_correlation_near_neutral():
    """Configuration models are degree-uncorrelated; the structured pairing
    must not introduce assortativity (|r| small)."""
    graph, plan = _small_plan(n=6000)
    g = graph.to_host_graph()
    deg = np.diff(g.row_ptr).astype(np.float64)
    src = np.repeat(np.arange(g.n), np.diff(g.row_ptr))
    du, dv = deg[src], deg[g.col_idx]
    r = np.corrcoef(du, dv)[0, 1]
    assert abs(r) < 0.1


@pytest.mark.slow  # rebind-vs-rebuild twin; plan-class algebra tests keep
# the rebind law in tier-1
def test_with_fanout_rebind_matches_build():
    _, plan1 = _small_plan(n=2000, fanout=1, key=9)
    _, plan3 = matching_powerlaw_graph(
        2000, key=jax.random.key(9), fanout=3
    )
    rebound = plan1.with_fanout(3)
    np.testing.assert_array_equal(
        np.asarray(rebound.push_threshold()), np.asarray(plan3.push_threshold())
    )
    np.testing.assert_array_equal(
        np.asarray(rebound.pull_threshold()), np.asarray(plan3.pull_threshold())
    )
    # and rebinding really changes the gate (fanout enters the law)
    assert not np.array_equal(
        np.asarray(plan1.push_threshold()), np.asarray(rebound.push_threshold())
    )


@pytest.mark.slow  # SIR + churn epidemics at n=2500; the sim suite's
# matching-mode parity tests cover the same delivery path
def test_engine_modes_on_matching_plan():
    """SIR recovery and Poisson churn + re-wiring run through the matching
    delivery path (the engine's advance_round is delivery-agnostic)."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    graph, plan = _small_plan(n=2500)
    n_state = plan.n + 1
    # SIR
    cfg = SwarmConfig(
        n_peers=n_state, msg_slots=4, mode="push_pull", fanout=1,
        sir_recover_rounds=3,
    )
    state = init_swarm(
        graph.as_padded_graph(), cfg, origins=[0], exists=graph.exists
    )
    fin, stats = simulate(state, cfg, 15, plan)
    assert float(fin.coverage(0)) > 0.3
    assert bool(jnp.any(fin.recovered))
    # churn + rewiring
    cfg2 = SwarmConfig(
        n_peers=n_state, msg_slots=4, mode="push_pull", fanout=1,
        churn_leave_prob=0.01, churn_join_prob=0.05, rewire_slots=2,
    )
    state2 = init_swarm(
        graph.as_padded_graph(), cfg2, origins=[0], exists=graph.exists
    )
    fin2, stats2 = simulate(state2, cfg2, 12, plan)
    assert float(fin2.coverage(0)) > 0.3
    assert bool(jnp.any(fin2.rewired))


@pytest.mark.slow  # scales the stage count up to large n; the involution
# invariant below pins pairing correctness in tier-1
def test_pairing_reach_spans_all_rows():
    """Regression for the 10M banding bug: with too few transpose stages,
    pairs can only form within ~128^K rows, turning the swarm into a 1-D
    banded structure (measured: 64 rounds to 99% at 10M instead of ~16).
    The stage count must scale so partner displacement spans the array."""
    import math

    from tpu_gossip.core.matching_topology import _build_plan, _plan_classes

    r = 20480  # > 128^2/8: needs K=3
    k = max(2, math.ceil(math.log(r) / math.log(128)))
    assert k == 3
    # synthetic degree-2 swarm exactly filling r rows: n*2 = r*128
    n = r * 128 // 2
    deg = np.full(n, 2, dtype=np.int32)
    classes = _plan_classes(deg)
    (lanes, m3, lanes_inv, valid, *_rest) = _build_plan(
        jax.random.key(0), jnp.asarray(deg), n=n, rows=r, classes=classes,
        interpret=True,
    )
    plan = MatchingPlan(
        lanes=lanes, m3=m3, lanes_inv=lanes_inv, valid=valid,
        deg_other=None, n=n, rows=r, classes=classes,
    )
    iota = jnp.arange(r * 128, dtype=jnp.int32).reshape(r, 128)
    part = np.asarray(plan.partner(iota, interpret=True))
    disp = np.abs(part // 128 - np.arange(r * 128).reshape(r, 128) // 128)
    # sample rows across the array; median displacement must span rows
    sample = disp[:: r // 97].ravel()
    assert np.median(sample) > r / 8, np.median(sample)
    assert sample.max() > r / 2


@pytest.mark.slow  # the no-CSR build path also runs in CI's
# builder-smoke job; rides the slow lane locally
def test_build_without_csr_export_runs_dissemination():
    """export_csr=False: degree-true row_ptr, empty neighbor list, and the
    full matching round (push_pull + SIR + liveness) still runs — churn
    re-wiring configs are the ones that need the export."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    graph, plan = matching_powerlaw_graph(
        2500, key=jax.random.key(2), fanout=1, export_csr=False
    )
    assert graph.col_idx.shape == (1,)
    np.testing.assert_array_equal(
        np.asarray(graph.row_ptr[1 : plan.n + 1] - graph.row_ptr[: plan.n]),
        np.asarray(plan.deg_real),
    )
    cfg = SwarmConfig(
        n_peers=plan.n + 1, msg_slots=4, mode="push_pull", fanout=1,
        sir_recover_rounds=5,
    )
    state = init_swarm(
        graph.as_padded_graph(), cfg, origins=[0], exists=graph.exists
    )
    fin, _ = simulate(state, cfg, 14, plan)
    assert float(fin.coverage(0)) > 0.5


def test_fold_planes_matches_numpy():
    """Direct contract of the single-operand plane fold (the second grid
    dimension accumulates planes over one input — operand count no longer
    scales with pad_deg)."""
    from tpu_gossip.kernels.permute import fold_planes

    rng = np.random.default_rng(11)
    cstride, pad_deg, count, slot_off = 2048, 5, 1900, 1024
    total = slot_off + pad_deg * cstride
    rows = -(-total // 1024) * 8
    flat = rng.integers(0, 2**31, (rows * 128,), dtype=np.int32)
    slots = jnp.asarray(flat.reshape(rows, 128))
    view = flat[slot_off : slot_off + pad_deg * cstride].reshape(
        pad_deg, cstride
    )[:, :count]
    got_or = fold_planes(slots, slot_off, cstride, count, pad_deg, "or")
    got_sum = fold_planes(slots, slot_off, cstride, count, pad_deg, "sum")
    np.testing.assert_array_equal(
        np.asarray(got_or), np.bitwise_or.reduce(view, axis=0)
    )
    np.testing.assert_array_equal(
        np.asarray(got_sum), view.sum(axis=0, dtype=np.int32)
    )


@pytest.mark.slow  # structural audit of the sharded build; the sim dist-
# builder bit-identity tests keep the sharded path in tier-1
def test_sharded_builder_structure():
    """matching_powerlaw_graph_sharded: identical per-shard blocks, pad
    rows dead, CSR consistent with the plan's valid set, and the pairing a
    fixed-point-free involution over the GLOBAL slot array (cross-shard
    reach included)."""
    from tpu_gossip.core.matching_topology import (
        matching_powerlaw_graph_sharded,
    )

    g, p = matching_powerlaw_graph_sharded(1200, 8, fanout=2,
                                           key=jax.random.key(4))
    s = p.mesh_shards
    assert s == 8 and p.rows == s * p.per_rows and p.n == s * p.n_blk
    assert p.n_blk == p.n_per + 1
    # global classes are the one local table shifted per shard
    per_cls = len(p.local_classes)
    for sh in range(s):
        for i, (no, so, c, pd, cs) in enumerate(p.local_classes):
            g_no, g_so, g_c, g_pd, g_cs = p.classes[sh * per_cls + i]
            assert (g_no, g_so) == (sh * p.n_blk + no, sh * p.per_rows * 128 + so)
            assert (g_c, g_pd, g_cs) == (c, pd, cs)
    # involution, no fixed points
    iota = jnp.arange(p.rows * 128, dtype=jnp.int32).reshape(p.rows, 128)
    part = p.partner(iota)
    np.testing.assert_array_equal(np.asarray(p.partner(part)), np.asarray(iota))
    assert not bool(jnp.any(part == iota))
    # pairing reaches across shard boundaries (the matching must not be
    # banded per shard — cross-shard edges are the whole point)
    shard_of = np.asarray(part) // (p.per_rows * 128)
    own = np.arange(p.rows * 128).reshape(p.rows, 128) // (p.per_rows * 128)
    assert (shard_of != own).mean() > 0.5
    # exists pattern + degree consistency
    exists = np.asarray(g.exists)
    assert exists.sum() == s * p.n_per
    assert not exists[np.arange(s) * p.n_blk + p.n_per].any()
    deg_csr = np.diff(np.asarray(g.row_ptr))
    dr = np.asarray(p.deg_real)
    np.testing.assert_array_equal(dr[exists], deg_csr[: p.n][exists])
    assert (dr[~exists] == 0).all()
    # valid slots == directed edges (sentinel row absorbs erased slots)
    assert int(jnp.sum(p.valid)) == int(g.row_ptr[-1] - deg_csr[-1])
