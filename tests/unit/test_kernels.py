"""Unit tests for the gossip/liveness ops against tiny hand-checked graphs."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.topology import build_csr
from tpu_gossip.kernels.gossip import (
    edge_sources,
    flood_all,
    pull_fanout,
    push_fanout,
    sample_fanout_targets,
)
from tpu_gossip.kernels.liveness import detect_failures, emit_heartbeats


def path_graph(n):
    edges = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
    return build_csr(n, edges)


def test_edge_sources_matches_csr_rows():
    g = path_graph(4)  # degrees 1,2,2,1
    src = np.asarray(edge_sources(jnp.asarray(g.row_ptr), g.col_idx.shape[0]))
    expect = np.repeat(np.arange(4), g.degrees)
    np.testing.assert_array_equal(src, expect)


def test_flood_all_one_hop_exact():
    g = path_graph(5)
    transmit = jnp.zeros((5, 2), dtype=bool).at[2, 0].set(True)
    out = np.asarray(flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx)))
    # only the path-neighbors of node 2 receive slot 0
    np.testing.assert_array_equal(out[:, 0], [False, True, False, True, False])
    assert not out[:, 1].any()


def test_sample_targets_are_neighbors():
    g = path_graph(16)
    tgt, valid = sample_fanout_targets(
        jax.random.key(0), jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx), 4
    )
    tgt, valid = np.asarray(tgt), np.asarray(valid)
    assert valid.all()  # path graph: every node has a neighbor
    for i in range(16):
        nbrs = set(g.neighbors(i).tolist())
        assert set(tgt[i].tolist()) <= nbrs


def test_sample_targets_isolated_nodes_invalid():
    edges = np.array([[0, 1]])
    g = build_csr(4, edges)  # nodes 2,3 isolated
    _, valid = sample_fanout_targets(
        jax.random.key(1), jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx), 3
    )
    valid = np.asarray(valid)
    assert valid[0].all() and valid[1].all()
    assert not valid[2].any() and not valid[3].any()


def test_push_fanout_delivers_only_to_targets():
    transmit = jnp.zeros((4, 3), dtype=bool).at[0, 1].set(True)
    targets = jnp.array([[2], [0], [0], [0]], dtype=jnp.int32)
    valid = jnp.array([[True], [False], [False], [False]])
    out = np.asarray(push_fanout(transmit, targets, valid))
    assert out[2, 1] and out.sum() == 1


def test_pull_fanout_gathers():
    transmit = jnp.zeros((3, 2), dtype=bool).at[1, 0].set(True)
    targets = jnp.array([[1], [2], [1]], dtype=jnp.int32)
    valid = jnp.ones((3, 1), dtype=bool)
    out = np.asarray(pull_fanout(transmit, targets, valid))
    np.testing.assert_array_equal(out[:, 0], [True, False, True])


def test_heartbeat_cadence():
    n = 4
    last = jnp.zeros((n,), jnp.int32)
    alive = jnp.ones((n,), bool)
    silent = jnp.zeros((n,), bool).at[1].set(True)
    dead = jnp.zeros((n,), bool)
    # round 3 is a heartbeat tick (period 3); round 4 is not
    out3 = np.asarray(emit_heartbeats(last, alive, silent, dead, jnp.int32(3), 3))
    out4 = np.asarray(emit_heartbeats(last, alive, silent, dead, jnp.int32(4), 3))
    np.testing.assert_array_equal(out3, [3, 0, 3, 3])  # silent peer skipped
    np.testing.assert_array_equal(out4, [0, 0, 0, 0])


def test_detector_probe_revives_responsive_peer():
    """A stale-but-responsive peer answers the PING (Peer.py:201-205) and is
    NOT declared dead — last_hb refreshes instead."""
    n = 2
    last = jnp.array([0, 0], jnp.int32)
    alive = jnp.ones((n,), bool)
    silent = jnp.array([False, True])
    dead = jnp.zeros((n,), bool)
    rnd = jnp.int32(8)  # stale (8 - 0 > 6), sweep round (8 % 2 == 0)
    new_last, new_dead = detect_failures(last, alive, silent, dead, rnd, 6, 2)
    np.testing.assert_array_equal(np.asarray(new_last), [8, 0])
    np.testing.assert_array_equal(np.asarray(new_dead), [False, True])


def test_detector_only_sweeps_on_schedule():
    last = jnp.array([0], jnp.int32)
    alive = jnp.ones((1,), bool)
    silent = jnp.ones((1,), bool)
    dead = jnp.zeros((1,), bool)
    _, d = detect_failures(last, alive, silent, dead, jnp.int32(9), 6, 2)
    assert not bool(d[0])  # round 9 is not a sweep round
