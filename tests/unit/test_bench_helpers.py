"""Unit coverage for bench.py's helper logic (the driver artifact's math)."""

import pytest

import bench
from tpu_gossip.kernels.pallas_segment import _pad_tiles


class _Cfg:
    n_peers = 1000
    fanout = 3


def test_accesses_per_round_by_mode():
    c = _Cfg()
    c.mode = "push"
    assert bench._accesses_per_round(c, 9999) == 2 * 1000 * 3
    c.mode = "push_pull"
    assert bench._accesses_per_round(c, 9999) == 2 * 1000 * 3 + 2 * 1000
    c.mode = "flood"
    assert bench._accesses_per_round(c, 9999) == 2 * 9999


def test_pad_tiles_properties():
    for t in [1, 2, 63, 64, 65, 127, 128, 129, 1000, 8191, 8192, 8193, 59904,
              123456]:
        p = _pad_tiles(t)
        b = max(1, 1 << max(0, t.bit_length() - 7))
        assert p >= t
        assert p % b == 0
        assert p - t < b  # minimal rounding
        # worst-case inert-padding overhead bound documented in the docstring
        assert (p - t) / t <= 1 / 64 + 1e-9 or t < 128


def test_pad_tiles_buckets_similar_sizes_together():
    # graphs of the same configuration differ by a handful of tiles across
    # seeds; a ±100-tile spread crosses at most one 512-tile bucket
    # boundary (usually none — one compile for the whole family)
    base = 59904
    buckets = {_pad_tiles(base + d) for d in range(-100, 101)}
    assert len(buckets) <= 2
    assert len({_pad_tiles(base - d) for d in range(100)}) == 1


def test_bench_liveness_detection_contract():
    """Detection at round 8 = 40 s-equivalent, inside the reference's
    30-42 s worst-case band (SURVEY.md §6), with every silenced peer found."""
    r = bench.bench_liveness(n=300, silent_frac=0.1, rounds=12, reps=1)
    assert r["detected"] == r["silent"] == 30
    assert r["detection_round"] == 8
    assert r["within_reference_band"]


def test_lint_status_shape():
    """bench records the graftlint verdict per run (BENCH_DETAIL.json
    lint_clean field) — and the tree is clean. deep=False skips the
    combined-analysis subprocess (slow-test territory, below) so the
    tier-1 loop doesn't pay the entry-point matrix trace here."""
    s = bench._lint_status(deep=False)
    assert set(s) == {"lint_clean", "lint"}
    assert s["lint_clean"] is True, s
    assert s["lint"]["scope"] == "ast-rules"
    assert s["lint"]["new_findings"] == 0


@pytest.mark.slow
def test_lint_status_deep_subprocess():
    """The full verdict: ``lint_deep_s`` is the combined rules + audit +
    deep wall time, measured in a subprocess with its own 8-CPU mesh —
    the CI lint-deep job's <120 s budget metric (slow-marked for the same
    reason test_deep.py::test_run_deep_clean_on_repo is: the tier-1 loop
    must not pay the matrix trace twice)."""
    s = bench._lint_status()
    assert set(s) == {"lint_clean", "lint", "lint_deep_s"}
    assert s["lint_clean"] is True, s
    assert s["lint"]["deep_clean"] is True, s
    assert isinstance(s["lint_deep_s"], float) and s["lint_deep_s"] < 120, s


def test_compact_carries_lint_clean():
    out = {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "rounds_to_99pct": 1, "wall_seconds": 1.0, "headline_delivery": "x",
        "lint_clean": True, "configs": {},
    }
    compact = bench._compact(out)
    assert compact["lint_clean"] is True
