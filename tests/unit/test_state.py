"""SwarmState pytree: construction, coverage metric, slot hashing, checkpointing."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.state import SwarmConfig, SwarmState, init_swarm, message_slot
from tpu_gossip.core.topology import build_csr, configuration_model, powerlaw_degree_sequence


def small_graph(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return build_csr(n, configuration_model(powerlaw_degree_sequence(n, rng=rng), rng=rng))


def test_init_swarm_shapes_and_origin():
    g = small_graph(100)
    cfg = SwarmConfig(n_peers=100, msg_slots=8)
    st = init_swarm(g, cfg, origins=[0, 3], origin_slot=2)
    assert st.seen.shape == (100, 8)
    assert bool(st.seen[0, 2]) and bool(st.seen[3, 2])
    assert int(st.seen.sum()) == 2
    assert st.n_peers == 100
    # infected_round is per (peer, slot)
    assert int(st.infected_round[0, 2]) == 0 and int(st.infected_round[0, 0]) == -1
    assert int(st.infected_round[1, 2]) == -1


def test_state_is_pytree():
    g = small_graph(50)
    st = init_swarm(g, SwarmConfig(n_peers=50), origins=[0])
    leaves = jax.tree_util.tree_leaves(st)
    assert len(leaves) == len(dataclasses.fields(SwarmState))
    # jit through the pytree
    f = jax.jit(lambda s: s.seen.sum())
    assert int(f(st)) == 1


def test_coverage_counts_only_live_peers():
    g = small_graph(10)
    st = init_swarm(g, SwarmConfig(n_peers=10), origins=list(range(5)))
    assert float(st.coverage()) == pytest.approx(0.5)
    st2 = dataclasses.replace(st, alive=jnp.arange(10) < 5)  # only infected ones alive
    assert float(st2.coverage()) == pytest.approx(1.0)


def test_message_slot_stable_and_in_range():
    assert message_slot("2025-01-01 00:00:00:127.0.0.1:1", 64) == message_slot(
        "2025-01-01 00:00:00:127.0.0.1:1", 64
    )
    slots = {message_slot(f"msg-{i}", 64) for i in range(200)}
    assert all(0 <= s < 64 for s in slots)
    assert len(slots) > 32  # spreads over slots


def test_int_message_ids_mask_to_64_bits():
    """Ids are masked to 64 bits before hashing (docs/dedup_semantics.md):
    wide ids (uuid.int, 128-bit digests) hash their low 64 bits instead of
    raising OverflowError, and — because two's complement makes the masked
    bytes identical to the historical signed encoding — every in-range id
    keeps its exact slot mapping, k=1 and k>1 alike."""
    from tpu_gossip.core.state import message_slots

    # in-range ids: masked-unsigned bytes == the old signed encoding
    for mid in (0, 1, -1, 2**62, -(2**63), 2**63 - 1):
        want_bytes = mid.to_bytes(8, "little", signed=True)
        got_bytes = (mid & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
        assert want_bytes == got_bytes, mid
    # wide ids no longer raise and equal their low-64-bit truncation
    wide = 0xDEADBEEF_CAFEBABE_01234567_89ABCDEF
    assert message_slots(wide, 64, 3) == message_slots(
        wide & 0xFFFFFFFFFFFFFFFF, 64, 3
    )
    assert message_slots(-(2**100) - 7, 64, 2) == message_slots(
        (-(2**100) - 7) & 0xFFFFFFFFFFFFFFFF, 64, 2
    )
    # the historical mapping must never drift — sim/socket conformance and
    # existing checkpoints depend on it; re-derive it with the PRE-MASK
    # encoding (signed to_bytes) and demand equality
    def old_slots(mid, m, k):
        data = mid.to_bytes(8, "little", signed=True)
        out = []
        for plane in range(k):
            h = (2166136261 ^ (plane * 0x9E3779B9)) & 0xFFFFFFFF
            for b in data:
                h = ((h ^ b) * 16777619) & 0xFFFFFFFF
            out.append(h % m)
        return tuple(out)

    for mid in (424242, -5, 0, 2**63 - 1, -(2**63)):
        assert message_slots(mid, 64, 3) == old_slots(mid, 64, 3), mid


def test_checkpoint_roundtrip(tmp_path):
    """SURVEY.md §5.4: checkpoint/resume is pytree serialization."""
    from tpu_gossip.core.state import load_swarm, save_swarm

    g = small_graph(64)
    st = init_swarm(g, SwarmConfig(n_peers=64), origins=[1])
    save_swarm(tmp_path / "ckpt.npz", st)
    st2 = load_swarm(tmp_path / "ckpt.npz")
    assert bool(jnp.array_equal(st2.seen, st.seen))
    assert bool(jnp.array_equal(st2.col_idx, st.col_idx))
    assert bool(jnp.array_equal(jax.random.key_data(st2.rng), jax.random.key_data(st.rng)))


def save_v1(st, path, *, per_peer_sir):
    """Write `st` in the round-1 positional arr_i/key_i checkpoint layout.

    ``per_peer_sir=True`` emulates a true early-round-1 checkpoint (SIR
    fields stored per-peer (N,)); ``False`` the late-round-1 per-slot form.
    """
    from tpu_gossip.core.state import _V1_FIELDS

    arrays = {}
    for i, name in enumerate(_V1_FIELDS):
        leaf = getattr(st, name)
        if per_peer_sir and name in ("infected_round", "recovered"):
            leaf = leaf[:, 0]
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            arrays[f"key_{i}"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"arr_{i}"] = np.asarray(leaf)
    np.savez(path, **arrays)


def test_legacy_v1_checkpoint_loads(tmp_path):
    """Round-1 checkpoints used positional arr_i/key_i keys and predate the
    `exists` field — they must still load, with exists defaulting to ones."""
    from tpu_gossip.core.state import load_swarm

    g = small_graph(32)
    st = init_swarm(g, SwarmConfig(n_peers=32), origins=[2])
    save_v1(st, tmp_path / "v1.npz", per_peer_sir=True)

    st2 = load_swarm(tmp_path / "v1.npz")
    assert bool(jnp.array_equal(st2.seen, st.seen))
    assert bool(jnp.array_equal(st2.alive, st.alive))
    assert bool(st2.exists.all()) and st2.exists.shape == st.alive.shape
    # per-peer (N,) fields come back broadcast to the (N, M) slot layout
    assert st2.infected_round.shape == st.seen.shape
    assert st2.recovered.shape == st.seen.shape
    assert bool(jnp.array_equal(st2.infected_round[:, 0], st.infected_round[:, 0]))


def test_legacy_v1_checkpoint_with_per_slot_sir_loads(tmp_path):
    """Late round-1 checkpoints already stored (N, M) SIR fields under the
    positional keys — the v1 branch must accept those shapes unchanged."""
    from tpu_gossip.core.state import load_swarm

    g = small_graph(32)
    st = init_swarm(g, SwarmConfig(n_peers=32), origins=[2])
    save_v1(st, tmp_path / "v1b.npz", per_peer_sir=False)

    st2 = load_swarm(tmp_path / "v1b.npz")
    assert bool(jnp.array_equal(st2.seen, st.seen))
    assert bool(jnp.array_equal(st2.infected_round, st.infected_round))
    assert bool(jnp.array_equal(st2.recovered, st.recovered))


def test_config_validation():
    with pytest.raises(ValueError):
        SwarmConfig(n_peers=0)
    with pytest.raises(ValueError):
        SwarmConfig(n_peers=10, msg_slots=0)
    g = small_graph(50)
    with pytest.raises(ValueError):
        init_swarm(g, SwarmConfig(n_peers=49))


def test_init_swarm_origin_slots_multi_rumor():
    """origin_slots seeds one rumor per slot (the M>1 bench shape)."""
    import jax
    import numpy as np
    import pytest

    from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment

    g = build_csr(64, preferential_attachment(64, m=2, use_native=False))
    cfg = SwarmConfig(n_peers=64, msg_slots=8)
    st = init_swarm(g, cfg, origins=list(range(8)), origin_slots=list(range(8)))
    seen = np.asarray(st.seen)
    assert seen.sum() == 8
    assert all(seen[i, i] for i in range(8))
    with pytest.raises(ValueError, match="origin_slots"):
        init_swarm(g, cfg, origins=[0, 1], origin_slots=[0])
    with pytest.raises(ValueError, match="origin_slots"):
        init_swarm(g, cfg, origins=[0], origin_slots=[8])
