"""On-device graph construction: statistical + structural parity with the
host erased configuration model (core/topology.py)."""

import jax
import numpy as np
import pytest

from tpu_gossip.core.device_topology import (
    device_powerlaw_graph,
    truncated_pareto_mean,
)
from tpu_gossip.core.topology import (
    build_csr,
    configuration_model,
    fit_powerlaw_gamma,
    powerlaw_degree_sequence,
)

N = 20_000


@pytest.fixture(scope="module")
def dg():
    return device_powerlaw_graph(N, gamma=2.5, key=jax.random.key(7))


def test_structure_is_a_clean_graph(dg):
    g = dg.to_host_graph()
    assert g.n == N
    deg = g.degrees
    for i in np.random.default_rng(0).integers(0, N, 200):
        nb = g.neighbors(int(i))
        assert len(set(nb.tolist())) == len(nb), "duplicate neighbor survived"
        assert int(i) not in nb, "self-loop survived"
    # symmetry on a sample
    for i in np.random.default_rng(1).integers(0, N, 50):
        for j in g.neighbors(int(i))[:5]:
            assert int(i) in g.neighbors(int(j))
    # sentinel row owns all invalid slots; real rows own the rest
    total = int(np.asarray(dg.row_ptr)[-1])
    assert total == dg.col_idx.shape[0]
    assert int(np.asarray(dg.row_ptr)[N]) == deg.sum()


def test_degree_law_matches_request(dg):
    deg = dg.to_host_graph().degrees
    est = fit_powerlaw_gamma(deg, d_min=5)
    assert abs(est - 2.5) < 0.3, f"gamma_hat={est}"
    # erasure removes few edges: mean degree close to the sampled law
    mean = truncated_pareto_mean(2.5, 2, int(round(N ** (1 / 1.5))))
    assert deg.mean() == pytest.approx(mean, rel=0.05)


def test_parity_with_host_model():
    """Device and host builders realize the same law: edge counts within a
    few percent and matching tail exponents on the same parameters."""
    rng = np.random.default_rng(3)
    host = build_csr(
        N, configuration_model(powerlaw_degree_sequence(N, gamma=2.5, rng=rng), rng=rng)
    )
    dev = device_powerlaw_graph(N, gamma=2.5, key=jax.random.key(3)).to_host_graph()
    assert dev.num_edges == pytest.approx(host.num_edges, rel=0.05)
    assert fit_powerlaw_gamma(dev.degrees, d_min=5) == pytest.approx(
        fit_powerlaw_gamma(host.degrees, d_min=5), abs=0.25
    )


# the mild-clip point needs enough margin that the sampled demand reliably
# exceeds the shrunken cap: at 0.98 the post-erasure total landed ~0.3%
# UNDER the cap on some RNG/library streams (observed on jax 0.4.x) and the
# test's own precondition flaked; 0.96 keeps the "barely clipped" regime
# with robust firing on every stream
@pytest.mark.parametrize("slack", [0.90, 0.96])
def test_clip_tail_keeps_law_and_structure(slack):
    """Force the stub budget below the sampled demand so the silent clip
    path (core/device_topology.py _build: deg_eff = clip(total-start, 0,
    deg)) actually fires, then assert the graph is still clean and the
    degree law is only perturbed by O(1-slack).

    Clipping zeroes the trailing ~(1-slack) fraction of nodes' stubs (the
    cumsum boundary), so those rows become isolated — the tail exponent and
    the surviving mean must stay within tolerance."""
    key = jax.random.key(11)
    clipped = device_powerlaw_graph(N, gamma=2.5, key=key, slack=slack)
    full = device_powerlaw_graph(N, gamma=2.5, key=key)  # default slack 1.02

    # the clip fired: even the POST-erasure realized total of the unclipped
    # build exceeds the shrunken budget, so pre-erasure demand certainly did
    tot_c = int(np.asarray(clipped.row_ptr)[N])
    tot_f = int(np.asarray(full.row_ptr)[N])
    d_max = max(3, int(round(N ** (1 / 1.5))))
    mean = truncated_pareto_mean(2.5, 2, d_max)
    s_cap = 2 * int(np.ceil(N * mean * slack / 2))
    assert tot_f > s_cap, f"slack={slack} never constrained ({tot_f} <= {s_cap})"
    assert tot_c < tot_f
    assert tot_c <= s_cap  # budget is a hard cap
    assert tot_c >= 0.90 * s_cap  # ... and erasure is the only other loss

    # structure survives the clip: symmetric, no self-loops, no duplicates
    g = clipped.to_host_graph()
    rng = np.random.default_rng(0)
    for i in rng.integers(0, N, 100):
        nb = g.neighbors(int(i))
        assert len(set(nb.tolist())) == len(nb)
        assert int(i) not in nb
        for j in nb[:3]:
            assert int(i) in g.neighbors(int(j))

    # the law survives: tail exponent within tolerance, surviving-node mean
    # within the clip fraction of the full build's
    deg = g.degrees
    est = fit_powerlaw_gamma(deg, d_min=5)
    assert abs(est - 2.5) < 0.35, f"gamma_hat={est} after clip"
    zero_frac = float((deg == 0).mean())
    assert zero_frac < 1.6 * (1.02 - slack) + 0.02, (
        f"clip isolated {zero_frac:.1%} of nodes"
    )
    surviving_mean = float(deg[deg > 0].mean())
    full_mean = float(full.to_host_graph().degrees.mean())
    assert surviving_mean == pytest.approx(full_mean, rel=0.10)


def test_deterministic_per_key():
    a = device_powerlaw_graph(2000, key=jax.random.key(5))
    b = device_powerlaw_graph(2000, key=jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(a.col_idx), np.asarray(b.col_idx))
    c = device_powerlaw_graph(2000, key=jax.random.key(6))
    assert not np.array_equal(np.asarray(a.col_idx), np.asarray(c.col_idx))


def test_engine_runs_on_device_graph(dg):
    """End to end: a swarm initialized straight from the device-built CSR
    (sentinel row dead via exists) reaches full coverage."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import run_until_coverage

    cfg = SwarmConfig(n_peers=dg.n_pad, msg_slots=1, fanout=3, mode="push")
    st = init_swarm(
        dg.as_padded_graph(), cfg, origins=[0], exists=dg.exists,
        key=jax.random.key(1),
    )
    fin = run_until_coverage(st, cfg, 0.99, 200)
    assert float(fin.coverage(0)) >= 0.99
    assert not bool(fin.seen[N].any())  # sentinel never infected
    assert not bool(fin.alive[N])


def test_exists_masks_only_sentinel(dg):
    exists = np.asarray(dg.exists)
    assert exists.shape == (N + 1,)
    assert exists[:N].all() and not exists[N]
    assert dg.n_pad == N + 1
