"""Staircase Pallas segment-OR: bit-exact parity with the XLA flood path
(interpret mode on the CPU test backend; the same kernel runs compiled on
TPU — see bench accounting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.topology import build_csr, configuration_model, powerlaw_degree_sequence, preferential_attachment
from tpu_gossip.kernels.gossip import flood_all
from tpu_gossip.kernels.pallas_segment import (
    build_staircase_plan,
    pack_words,
    segment_or,
    unpack_words,
)


def graphs():
    rng = np.random.default_rng(0)
    yield build_csr(300, preferential_attachment(300, m=3, use_native=False, rng=rng))
    deg = powerlaw_degree_sequence(2000, gamma=2.5, rng=rng)
    yield build_csr(2000, configuration_model(deg, rng=rng))


def test_pack_roundtrip():
    rng = np.random.default_rng(1)
    bm = jnp.asarray(rng.random((257, 21)) < 0.4)
    assert bool(jnp.array_equal(unpack_words(pack_words(bm), 21), bm))
    with pytest.raises(ValueError):
        pack_words(jnp.zeros((4, 33), dtype=bool))


@pytest.mark.parametrize("m", [1, 8, 24])
def test_parity_with_flood_all(m):
    for g in graphs():
        plan = build_staircase_plan(g.row_ptr, g.col_idx)
        transmit = jnp.asarray(np.random.default_rng(2).random((g.n, m)) < 0.25)
        ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
        got = segment_or(plan, transmit, m)
        assert bool(jnp.array_equal(ref, got)), f"mismatch n={g.n} m={m}"


def test_plan_covers_every_block():
    g = next(iter(graphs()))
    plan = build_staircase_plan(g.row_ptr, g.col_idx)
    blocks = np.asarray(plan.tile_block)
    first = np.asarray(plan.first_visit)
    # every output block visited, first tile of each block flagged
    assert set(blocks.tolist()) == set(range(plan.n_blocks))
    assert first[0] == 1
    assert ((np.diff(blocks) != 0) == first[1:].astype(bool)).all()


def test_engine_flood_with_plan_matches_without():
    """Full engine parity: flood dissemination is deterministic, so simulate
    with the staircase plan must produce the exact same state trajectory."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    g = build_csr(700, preferential_attachment(700, m=3, use_native=False,
                                               rng=np.random.default_rng(5)))
    plan = build_staircase_plan(g.row_ptr, g.col_idx)
    cfg = SwarmConfig(n_peers=700, msg_slots=8, mode="flood")
    st = init_swarm(g, cfg, origins=[0, 13], key=jax.random.key(3))
    fin_a, stats_a = simulate(st, cfg, 6)
    fin_b, stats_b = simulate(st, cfg, 6, plan)
    assert bool(jnp.array_equal(fin_a.seen, fin_b.seen))
    np.testing.assert_array_equal(np.asarray(stats_a.coverage), np.asarray(stats_b.coverage))
