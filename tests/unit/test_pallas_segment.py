"""Staircase Pallas segment-OR: bit-exact parity with the XLA flood path
(interpret mode on the CPU test backend; the same kernel runs compiled on
TPU — see bench accounting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.core.state import clone_state

from tpu_gossip.core.topology import build_csr, configuration_model, powerlaw_degree_sequence, preferential_attachment
from tpu_gossip.kernels.gossip import flood_all
from tpu_gossip.kernels.pallas_segment import (
    build_staircase_plan,
    build_staircase_plan_device,
    pack_words,
    segment_or,
    segment_sampled,
    unpack_words,
)


def graphs():
    rng = np.random.default_rng(0)
    yield build_csr(300, preferential_attachment(300, m=3, use_native=False, rng=rng))
    deg = powerlaw_degree_sequence(2000, gamma=2.5, rng=rng)
    yield build_csr(2000, configuration_model(deg, rng=rng))


def test_pack_roundtrip():
    rng = np.random.default_rng(1)
    bm = jnp.asarray(rng.random((257, 21)) < 0.4)
    assert bool(jnp.array_equal(unpack_words(pack_words(bm), 21), bm))
    with pytest.raises(ValueError):
        pack_words(jnp.zeros((4, 33), dtype=bool))


@pytest.mark.parametrize("m", [1, 8, 24, 33, 64, 70])
def test_parity_with_flood_all(m):
    """m > 32 exercises the multi-word path (one launch per 32-slot group)."""
    for g in graphs():
        plan = build_staircase_plan(g.row_ptr, g.col_idx)
        transmit = jnp.asarray(np.random.default_rng(2).random((g.n, m)) < 0.25)
        ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
        got = segment_or(plan, transmit, m)
        assert bool(jnp.array_equal(ref, got)), f"mismatch n={g.n} m={m}"


@pytest.mark.parametrize("rows", [256, 512])
def test_parity_with_wider_blocks(rows):
    """rows > 128 (the tile-count-vs-compute knob) keeps exact parity, for
    both flood and saturated-fanout sampled delivery, pull included."""
    for g in graphs():
        max_deg = int(np.max(np.diff(np.asarray(g.row_ptr))))
        plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=max_deg, rows=rows)
        assert plan.rows == rows
        transmit = jnp.asarray(np.random.default_rng(6).random((g.n, 8)) < 0.3)
        ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
        assert bool(jnp.array_equal(ref, segment_or(plan, transmit, 8)))
        got, _ = segment_sampled(
            plan, transmit, None, 8, jax.random.key(1),
            receptive_rows=jnp.ones((g.n,), dtype=bool),
            do_push=True, do_pull=True,
        )
        assert bool(jnp.array_equal(ref, got))
    with pytest.raises(ValueError, match="multiple of 128"):
        build_staircase_plan(g.row_ptr, g.col_idx, rows=100)


@pytest.mark.parametrize("rows,fanout", [(128, None), (128, 2), (512, 3)])
def test_device_plan_matches_host_plan(rows, fanout):
    """build_staircase_plan_device: routing tables bit-exact vs the host
    build; Bernoulli thresholds within f32 rounding of the host's f64."""
    for g in graphs():
        hp = build_staircase_plan(g.row_ptr, g.col_idx, fanout=fanout, rows=rows)
        dp = build_staircase_plan_device(
            jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx), fanout=fanout, rows=rows
        )
        assert (dp.n, dp.n_tiles, dp.n_blocks, dp.rows, dp.fanout) == (
            hp.n, hp.n_tiles, hp.n_blocks, hp.rows, hp.fanout
        )
        for f in ("tile_block", "first_visit", "offs", "col_gather"):
            np.testing.assert_array_equal(
                np.asarray(getattr(dp, f)), np.asarray(getattr(hp, f)), err_msg=f
            )
        if fanout is None:
            assert dp.push_thresh is None and dp.pull_thresh is None
        else:
            for f in ("push_thresh", "pull_thresh"):
                h = np.asarray(getattr(hp, f)).astype(np.int64)
                d = np.asarray(getattr(dp, f)).astype(np.int64)
                # ~2^-24 relative agreement: |Δthresh| <= max(512, thresh>>23)
                tol = np.maximum(512, h >> 23)
                assert (np.abs(h - d) <= tol).all(), f
        # and the kernel accepts the device-built plan
        transmit = jnp.asarray(np.random.default_rng(4).random((g.n, 8)) < 0.3)
        ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
        assert bool(jnp.array_equal(ref, segment_or(dp, transmit, 8)))


def test_plan_covers_every_block():
    g = next(iter(graphs()))
    plan = build_staircase_plan(g.row_ptr, g.col_idx)
    blocks = np.asarray(plan.tile_block)
    first = np.asarray(plan.first_visit)
    # every output block visited, first tile of each block flagged
    assert set(blocks.tolist()) == set(range(plan.n_blocks))
    assert first[0] == 1
    assert ((np.diff(blocks) != 0) == first[1:].astype(bool)).all()


def test_sampled_with_saturated_fanout_equals_flood():
    """fanout >= max degree drives every push threshold to ~1, so sampled
    push delivery must reproduce the deterministic flood (up to the 2^-32
    threshold slack, which cannot flip an edge in a 10^4-draw test)."""
    for g in graphs():
        max_deg = int(np.max(np.diff(np.asarray(g.row_ptr))))
        plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=max_deg)
        transmit = jnp.asarray(np.random.default_rng(3).random((g.n, 8)) < 0.3)
        ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
        got, msgs = segment_sampled(
            plan, transmit, transmit, 8, jax.random.key(0), do_push=True
        )
        assert bool(jnp.array_equal(ref, got))
        assert int(msgs) == int(
            jnp.sum(transmit.sum(-1) * jnp.diff(jnp.asarray(g.row_ptr)))
        )


def test_sampled_activation_rate_matches_expectation():
    """Bernoulli thresholds: a transmitting peer of degree d fires each
    out-edge w.p. k/d, so expected deliveries per round ~= k per sender."""
    g = build_csr(
        4000,
        configuration_model(
            powerlaw_degree_sequence(4000, gamma=2.5, rng=np.random.default_rng(7)),
            rng=np.random.default_rng(8),
        ),
    )
    k = 2
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=k)
    transmit = jnp.ones((g.n, 1), dtype=bool)
    total = 0
    reps = 20
    for i in range(reps):
        _, msgs = segment_sampled(
            plan, transmit, transmit, 1, jax.random.key(i), do_push=True
        )
        total += int(msgs)
    deg = np.diff(np.asarray(g.row_ptr))
    expected = np.minimum(k, deg).sum()  # senders with deg<k fire all edges
    got = total / reps
    assert abs(got - expected) / expected < 0.05, (got, expected)


def test_sampled_multiword_activation_is_edge_consistent():
    """M > 32: the Bernoulli draw is per EDGE, not per word group — with
    saturated fanout every edge fires, so sampled delivery across 2+ words
    must equal the flood of the full-width bitmap (bit-exact)."""
    g = next(iter(graphs()))
    max_deg = int(np.max(np.diff(np.asarray(g.row_ptr))))
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=max_deg)
    m = 50
    transmit = jnp.asarray(np.random.default_rng(9).random((g.n, m)) < 0.3)
    ref = flood_all(transmit, jnp.asarray(g.row_ptr), jnp.asarray(g.col_idx))
    got, msgs = segment_sampled(
        plan, transmit, transmit, m, jax.random.key(0), do_push=True
    )
    assert bool(jnp.array_equal(ref, got))
    assert int(msgs) == int(
        jnp.sum(transmit.sum(-1) * jnp.diff(jnp.asarray(g.row_ptr)))
    )


def test_sampled_multiword_subsampled_edges_agree_across_words():
    """With a non-saturating fanout, a fired edge must deliver ALL its word
    groups: no (dst, src-word) combination where word 0 arrived but word 1
    didn't, given the sender offered both. Seed each sender's slots 0 and 40
    identically, so any cross-word disagreement in delivery is a shared-draw
    violation."""
    g = next(iter(graphs()))
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=2)
    m = 48
    rng = np.random.default_rng(10)
    base = rng.random(g.n) < 0.5
    transmit = np.zeros((g.n, m), dtype=bool)
    transmit[:, 0] = base
    transmit[:, 40] = base
    got, _ = segment_sampled(
        plan, jnp.asarray(transmit), None, m, jax.random.key(4), do_push=True
    )
    got = np.asarray(got)
    np.testing.assert_array_equal(got[:, 0], got[:, 40])


def test_receptive_row_gating_and_billing():
    """Row-level receptive gating: non-receptive rows receive nothing, an
    all-true mask is identical to no mask (same key => same draws), and the
    pull bill of masked rows is exactly the msgs difference."""
    for m in (8, 48):  # single- and multi-word (bill rides the LAST group's launch)
        g = next(iter(graphs()))
        plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=2)
        transmit = jnp.asarray(np.random.default_rng(11).random((g.n, m)) < 0.4)
        key = jax.random.key(5)
        inc_none, msgs_none = segment_sampled(
            plan, transmit, None, m, key, do_push=True, do_pull=True
        )
        inc_all, msgs_all = segment_sampled(
            plan, transmit, None, m, key,
            receptive_rows=jnp.ones((g.n,), dtype=bool),
            do_push=True, do_pull=True,
        )
        assert bool(jnp.array_equal(inc_none, inc_all))
        assert int(msgs_none) == int(msgs_all)

        rec = jnp.asarray(np.random.default_rng(12).random(g.n) < 0.5)
        inc_p, msgs_p = segment_sampled(
            plan, transmit, None, m, key, receptive_rows=rec,
            do_push=True, do_pull=True,
        )
        assert not bool(jnp.any(inc_p[~rec]))  # masked rows get nothing
        assert bool(jnp.array_equal(inc_p[rec], inc_all[rec]))
        # exact billing: push billing is rec-independent and the pull bill
        # partitions over complementary masks, so
        #   msgs(rec) + msgs(~rec) == msgs(all) + push_only
        # (same key => identical push/pull draws in every call)
        _, msgs_c = segment_sampled(
            plan, transmit, None, m, key, receptive_rows=~rec,
            do_push=True, do_pull=True,
        )
        _, msgs_push = segment_sampled(
            plan, transmit, None, m, key, do_push=True, do_pull=False
        )
        assert int(msgs_p) < int(msgs_all)
        assert int(msgs_p) + int(msgs_c) == int(msgs_all) + int(msgs_push)


def test_sampled_pull_requires_thresholds():
    g = next(iter(graphs()))
    plan = build_staircase_plan(g.row_ptr, g.col_idx)  # no fanout
    transmit = jnp.zeros((g.n, 4), dtype=bool)
    with pytest.raises(ValueError, match="without fanout"):
        segment_sampled(plan, transmit, transmit, 4, jax.random.key(0))


@pytest.mark.slow  # 7-seed statistical curve sweep; the single-round
# semantic parity tests keep the sampled kernel in tier-1
def test_engine_sampled_kernel_curves_match_xla_path():
    """Statistical parity (VERDICT r2 item 2): the kernel's Bernoulli-per-edge
    push_pull and the XLA exactly-k path must produce the same coverage
    dynamics — median rounds-to-{50%,99%} within 1 round over 7 seeds."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.sim.metrics import rounds_to_coverage

    g = build_csr(
        3000,
        configuration_model(
            powerlaw_degree_sequence(3000, gamma=2.5, rng=np.random.default_rng(11)),
            rng=np.random.default_rng(12),
        ),
    )
    cfg = SwarmConfig(n_peers=3000, msg_slots=4, fanout=1, mode="push_pull")
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=cfg.fanout)

    def rounds(use_plan, seed, target):
        st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
        _, stats = simulate(st, cfg, 40, plan if use_plan else None)
        return rounds_to_coverage(stats, target)

    for target in (0.5, 0.99):
        xla = np.median([rounds(False, s, target) for s in range(7)])
        ker = np.median([rounds(True, s, target) for s in range(7)])
        assert xla > 0 and ker > 0
        assert abs(xla - ker) <= 1.0, (target, xla, ker)


def test_engine_sampled_kernel_push_mode():
    """push-only routing through the kernel reaches coverage like XLA push."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import run_until_coverage

    g = build_csr(1500, preferential_attachment(1500, m=3, use_native=False,
                                                rng=np.random.default_rng(21)))
    cfg = SwarmConfig(n_peers=1500, msg_slots=4, fanout=3, mode="push")
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=cfg.fanout)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(2))
    fin = run_until_coverage(clone_state(st), cfg, 0.99, 60, plan=plan)
    assert float(fin.coverage(0)) >= 0.99
    r_xla = int(run_until_coverage(st, cfg, 0.99, 60).round)
    assert abs(int(fin.round) - r_xla) <= 3, (int(fin.round), r_xla)


def test_engine_churn_kernel_stale_and_fresh_semantics():
    """Churn re-wiring on the KERNEL path (VERDICT r3 item 3): the staircase
    kernel carries the static CSR with rewired senders zeroed and rewired
    receivers row-masked, while fresh-edge traffic rides the XLA side path —
    same invariants as the XLA path's test
    (test_engine.test_stale_edges_blocked_fresh_edges_bidirectional)."""
    import dataclasses

    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    # path 0-1, isolated 2: CSR neighbor of 0 is 1; rewired 1 attaches to 2
    g = build_csr(3, np.array([[0, 1]]))
    cfg = SwarmConfig(n_peers=3, msg_slots=4, fanout=1, mode="push", rewire_slots=1)
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=1)
    st = init_swarm(g, cfg, origins=[0])
    rw = dataclasses.replace(
        st,
        seen=st.seen.at[2, 1].set(True),  # second rumor at the fresh target
        rewired=st.rewired.at[1].set(True),
        rewire_targets=st.rewire_targets.at[1, 0].set(2),
    )
    fin, _ = simulate(clone_state(rw), cfg, 5, plan)
    seen = np.asarray(fin.seen)
    # stale CSR edge 0->1 delivers nothing (slot 0 never reaches 1 or 2)
    assert not seen[1, 0] and not seen[2, 0], "stale CSR push leaked via kernel"
    # reverse-fresh: target 2's rumor reaches the rejoiner over 1's edge
    assert seen[1, 1], "reverse-fresh push lost on the kernel path"

    # the rejoiner's OWN traffic flows outward over its fresh edge
    rw_origin1 = dataclasses.replace(
        clone_state(rw), seen=st.seen.at[1, 2].set(True)
    )
    fin_fresh, _ = simulate(rw_origin1, cfg, 5, plan)
    assert bool(fin_fresh.seen[2, 2]), "fresh-edge push from a rewired peer lost"

    # pull over a fresh edge delivers too (push_pull, rewired puller)
    cfg_pp = dataclasses.replace(cfg, mode="push_pull")
    fin_pull, _ = simulate(clone_state(rw), cfg_pp, 5, plan)
    assert bool(fin_pull.seen[1, 1]), "fresh-edge pull by a rewired peer lost"

    # sanity: with the rewire flag cleared the CSR edge infects peer 1 again
    st2 = dataclasses.replace(rw, rewired=rw.rewired.at[1].set(False))
    fin2, _ = simulate(st2, cfg, 5, plan)
    assert bool(fin2.seen[1, 0])


def test_engine_churn_kernel_isolated_rewired_rows_untouched():
    """Scale check of both churn masks on the kernel path: rewired slots
    whose fresh targets are all sentinels (-1) have NO edges at all — their
    static CSR edges are stale both ways and they own no fresh ones — so a
    saturated round must neither deliver to them nor carry their words."""
    import dataclasses

    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    g = build_csr(
        2000,
        configuration_model(
            powerlaw_degree_sequence(2000, gamma=2.5, rng=np.random.default_rng(30)),
            rng=np.random.default_rng(31),
        ),
    )
    max_deg = int(np.max(np.diff(np.asarray(g.row_ptr))))
    cfg = SwarmConfig(
        n_peers=2000, msg_slots=4, fanout=max_deg, mode="push_pull",
        rewire_slots=2,
    )
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=max_deg)
    st = init_swarm(g, cfg, origins=list(range(50)), key=jax.random.key(7))
    rng = np.random.default_rng(32)
    rw_ids = jnp.asarray(rng.choice(2000, size=200, replace=False))
    rw = dataclasses.replace(
        st,
        # the rewired peers carry a private rumor in slot 3 that must go nowhere
        seen=st.seen.at[rw_ids, 3].set(True),
        rewired=st.rewired.at[rw_ids].set(True),
        rewire_targets=st.rewire_targets.at[rw_ids, :].set(-1),
    )
    fin, _ = simulate(clone_state(rw), cfg, 8, plan)
    seen = np.asarray(fin.seen)
    rw_mask = np.asarray(rw.rewired)
    # saturated fanout floods every non-rewired peer, so leakage is decisive:
    assert seen[~rw_mask, 0].mean() > 0.95
    # (a) nothing arrived at the edge-less rewired slots
    np.testing.assert_array_equal(seen[rw_mask], np.asarray(rw.seen)[rw_mask])
    # (b) their slot-3 rumor never escaped over the stale CSR edges
    assert not seen[~rw_mask, 3].any(), "rewired sender's words leaked via kernel"


@pytest.mark.slow  # multi-seed curve sweep; stale/fresh semantics and row
# gating keep the churn kernel in tier-1
def test_engine_churn_kernel_curves_match_xla_path():
    """Statistical parity for BASELINE config 5 on the kernel path: Poisson
    churn + power-law re-wiring must show the same coverage dynamics through
    the staircase kernel as through the XLA path (median rounds-to-target
    within 2 over 5 seeds; the two paths draw different RNG streams)."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.sim.metrics import rounds_to_coverage

    g = build_csr(
        3000,
        configuration_model(
            powerlaw_degree_sequence(3000, gamma=2.5, rng=np.random.default_rng(41)),
            rng=np.random.default_rng(42),
        ),
    )
    cfg = SwarmConfig(
        n_peers=3000, msg_slots=4, fanout=1, mode="push_pull",
        churn_leave_prob=0.002, churn_join_prob=0.02, rewire_slots=2,
    )
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=cfg.fanout)

    def rounds(use_plan, seed, target):
        st = init_swarm(g, cfg, origins=[0], key=jax.random.key(seed))
        _, stats = simulate(st, cfg, 40, plan if use_plan else None)
        return rounds_to_coverage(stats, target)

    for target in (0.5, 0.95):
        xla_runs = [rounds(False, s, target) for s in range(5)]
        ker_runs = [rounds(True, s, target) for s in range(5)]
        # -1 = never reached within the horizon; every seed must converge or
        # the medians silently compare skewed samples
        assert all(r > 0 for r in xla_runs + ker_runs), (xla_runs, ker_runs)
        assert abs(np.median(xla_runs) - np.median(ker_runs)) <= 2.0, (
            target, xla_runs, ker_runs,
        )


def test_engine_fanout_mismatch_raises():
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import gossip_round

    g = next(iter(graphs()))
    plan = build_staircase_plan(g.row_ptr, g.col_idx, fanout=2)
    cfg = SwarmConfig(n_peers=g.n, msg_slots=4, fanout=3, mode="push")
    st = init_swarm(g, cfg, origins=[0])
    with pytest.raises(ValueError, match="fanout"):
        gossip_round(st, cfg, plan)


def test_engine_flood_with_plan_matches_without():
    """Full engine parity: flood dissemination is deterministic, so simulate
    with the staircase plan must produce the exact same state trajectory."""
    from tpu_gossip.core.state import SwarmConfig, init_swarm
    from tpu_gossip.sim.engine import simulate

    g = build_csr(700, preferential_attachment(700, m=3, use_native=False,
                                               rng=np.random.default_rng(5)))
    plan = build_staircase_plan(g.row_ptr, g.col_idx)
    cfg = SwarmConfig(n_peers=700, msg_slots=8, mode="flood")
    st = init_swarm(g, cfg, origins=[0, 13], key=jax.random.key(3))
    fin_a, stats_a = simulate(clone_state(st), cfg, 6)
    fin_b, stats_b = simulate(st, cfg, 6, plan)
    assert bool(jnp.array_equal(fin_a.seen, fin_b.seen))
    np.testing.assert_array_equal(np.asarray(stats_a.coverage), np.asarray(stats_b.coverage))
