"""The jax.profiler trace hook (tpu_gossip/utils/profiling.py; SURVEY.md §5.1)."""

import jax
import jax.numpy as jnp

from tpu_gossip.utils.profiling import trace


def test_trace_writes_profile_artifacts(tmp_path):
    log_dir = tmp_path / "trace"
    with trace(log_dir):
        x = jax.jit(lambda a: a * 2 + 1)(jnp.arange(128))
        float(jnp.sum(x))
    # jax writes plugins/profile/<run>/*.xplane.pb under the log dir
    artifacts = list(log_dir.rglob("*.xplane.pb"))
    assert artifacts, f"no trace artifacts under {log_dir}"


def test_trace_disabled_is_noop(tmp_path):
    with trace(None):
        pass
    with trace(""):
        pass
    assert list(tmp_path.iterdir()) == []
