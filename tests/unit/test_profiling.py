"""The jax.profiler trace hook (tpu_gossip/utils/profiling.py; SURVEY.md §5.1)."""

import jax
import jax.numpy as jnp
import pytest

from tpu_gossip.utils.profiling import trace


@pytest.mark.slow  # spins up the real xplane writer; the no-op contract
# below keeps the trace hook in tier-1
def test_trace_writes_profile_artifacts(tmp_path):
    log_dir = tmp_path / "trace"
    with trace(log_dir):
        x = jax.jit(lambda a: a * 2 + 1)(jnp.arange(128))
        float(jnp.sum(x))
    # jax writes plugins/profile/<run>/*.xplane.pb under the log dir
    artifacts = list(log_dir.rglob("*.xplane.pb"))
    assert artifacts, f"no trace artifacts under {log_dir}"


def test_trace_disabled_is_noop(tmp_path):
    with trace(None):
        pass
    with trace(""):
        pass
    assert list(tmp_path.iterdir()) == []


def test_slope_time_measures_positive_per_iteration_cost():
    from tpu_gossip.utils.profiling import slope_time

    x = jnp.arange(1 << 16, dtype=jnp.int32)

    def body(i, c, arr):
        return c ^ jnp.sum(arr + i, dtype=jnp.int32)

    dt = slope_time(body, jnp.int32(0), 2, 50, reps=2, operands=(x,))
    assert dt == dt and dt > 0  # finite, positive


@pytest.mark.slow  # slope-timing every stage is wall-heavy; the CLI test
# below drives the same decomposition and stays in tier-1
def test_profile_round_stages_covers_every_stage():
    """The stage decomposition (run_sim --profile-round): every declared
    stage present, tails selectable, values floats (NaN allowed at toy
    scales where noise wins the slope)."""
    import numpy as np

    from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.utils.profiling import (
        format_stage_table, profile_round_stages,
    )

    n = 512
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False))
    cfg = SwarmConfig(
        n_peers=n, msg_slots=8, fanout=2, mode="push_pull",
        churn_leave_prob=0.02, churn_join_prob=0.1, rewire_slots=2,
    )
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(0))
    st, _ = simulate(clone_state(st), cfg, 3)
    stages = profile_round_stages(
        st, cfg, None, reps=1, loop_lengths=(2, 6),
        tails=("reference", "fused", "pallas"),
    )
    want = {
        "delivery", "liveness", "stats", "rng",
        "tail[reference]", "tail[fused]", "tail[pallas]",
        "full_round[reference]", "full_round[fused]", "full_round[pallas]",
    }
    assert set(stages) == want
    assert all(isinstance(v, float) for v in stages.values())
    table = format_stage_table(stages)
    assert "| stage | ms/round |" in table and "tail[fused]" in table


@pytest.mark.slow  # composed-planes variant of the stage decomposition
def test_profile_round_stages_composed_planes():
    """PR 10 satellite: the decomposition covers the post-PR-3 stages —
    growth / stream / control rows appear when compiled planes are
    passed, and the transport_compact probe measures the sparse lane's
    compaction round-trip."""
    import numpy as np

    from tpu_gossip import SwarmConfig, build_csr, init_swarm, preferential_attachment
    from tpu_gossip.control import compile_control
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.growth import compile_growth
    from tpu_gossip.sim.engine import simulate
    from tpu_gossip.traffic import compile_stream
    from tpu_gossip.utils.profiling import profile_round_stages

    n = 256
    g = build_csr(n, preferential_attachment(n, m=3, use_native=False))
    cfg = SwarmConfig(n_peers=n, msg_slots=8, fanout=2, mode="push_pull",
                      rewire_slots=2)
    st = init_swarm(g, cfg, origins=[0], key=jax.random.key(0))
    gp = compile_growth(n_initial=n - 32, target=n, n_slots=n,
                        joins_per_round=4, attach_m=2,
                        admit_rows=np.arange(n - 32, n))
    sp = compile_stream(rate=1.0, msg_slots=8, ttl=8,
                        origin_rows=np.arange(n - 32))
    cp = compile_control(target_ratio=0.9, fanout=2, lo=1, hi=2)
    st, _ = simulate(clone_state(st), cfg, 2, growth=gp, stream=sp,
                     control=cp)
    stages = profile_round_stages(
        st, cfg, None, reps=1, loop_lengths=(2, 6), tails=("fused",),
        growth=gp, stream=sp, control=cp,
        transport_probe=(8, 1024, 1, 128),
    )
    for row in ("growth", "stream", "control", "transport_compact",
                "full_round[fused]"):
        assert row in stages, row
    assert all(isinstance(v, float) for v in stages.values())


@pytest.mark.slow  # planes-composed CLI variant; the plain CLI profile
# test remains the tier-1 witness
def test_run_sim_profile_round_cli_composes_with_planes(capsys):
    """run_sim --profile-round with --grow/--stream/--control runs the
    composed decomposition (the old parse-time rejections are gone) and
    the summary JSON carries the new rows."""
    import json

    from tpu_gossip.cli.run_sim import main as run_sim_main

    rc = run_sim_main([
        "--peers", "96", "--slots", "4", "--fanout", "2", "--quiet",
        "--mode", "push_pull", "--profile-round", "1",
        "--grow", "128", "--m", "2", "--stream", "1", "--control", "0.9",
    ])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for k in ("growth", "stream", "control", "transport_compact"):
        assert k in row["stages_ms"], k


@pytest.mark.slow  # full profile table over a real run; slope_time and the
# no-op trace contract keep the profiling util in tier-1
def test_run_sim_profile_round_cli(capsys):
    import json

    from tpu_gossip.cli.run_sim import main as run_sim_main

    rc = run_sim_main([
        "--peers", "256", "--mode", "push_pull", "--fanout", "2",
        "--profile-round", "2", "--quiet",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)  # strict JSON — NaNs must have become null
    assert row["profile_round"] is True
    assert "tail[fused]" in row["stages_ms"]
    # --shard is the dist engines' territory: loud exit, not silence
    rc = run_sim_main([
        "--peers", "64", "--profile-round", "1", "--shard",
    ])
    assert rc == 2
