"""Config cache + CLI port-prompt parity (SURVEY.md §2.1 is_my_turn,
reference Seed.py:479-492 / Peer.py:456-465 stdin prompts)."""

import pytest

from tpu_gossip.cli import prompt_port
from tpu_gossip.compat.seed import ConfigCache, SeedNode, load_config


def test_config_cache_invalidates_on_append(tmp_path):
    p = tmp_path / "config.txt"
    p.write_text("127.0.0.1:121\n")
    cache = ConfigCache(str(p))
    assert cache.entries() == [("127.0.0.1", 121)]
    with open(p, "a") as f:
        f.write("127.0.0.1:122\n")
    assert cache.entries() == [("127.0.0.1", 121), ("127.0.0.1", 122)]


def test_config_cache_skips_reparse_when_unchanged(tmp_path, monkeypatch):
    p = tmp_path / "config.txt"
    p.write_text("127.0.0.1:121\n127.0.0.1:122\n")
    cache = ConfigCache(str(p))
    first = cache.entries()
    # a second read with the same (mtime, size) must not touch the parser
    import tpu_gossip.compat.seed as seed_mod

    def boom(path):
        raise AssertionError("load_config called on unchanged file")

    monkeypatch.setattr(seed_mod, "load_config", boom)
    assert cache.entries() is first


def test_is_my_turn_elects_exactly_one_quorum_seed(tmp_path):
    p = tmp_path / "config.txt"
    addrs = [("127.0.0.1", 121 + i) for i in range(5)]
    p.write_text("".join(f"{ip}:{port}\n" for ip, port in addrs))
    seeds = [
        SeedNode(ip, port, config_path=str(p), log_dir=str(tmp_path))
        for ip, port in addrs
    ]
    quorum = addrs[: len(addrs) // 2 + 1]
    for peer in [("10.0.0.9", 5000 + i) for i in range(20)]:
        winners = [s.addr for s in seeds if s.is_my_turn(peer)]
        assert len(winners) == 1
        assert winners[0] in quorum


def test_prompt_port_retries_until_valid(monkeypatch):
    answers = iter(["nope", "99999", " 5001 "])
    monkeypatch.setattr("builtins.input", lambda _: next(answers))
    assert prompt_port("peer") == 5001


def test_prompt_port_eof_exits(monkeypatch):
    def eof(_):
        raise EOFError

    monkeypatch.setattr("builtins.input", eof)
    with pytest.raises(SystemExit):
        prompt_port("seed")


def test_bare_cli_parsers_accept_missing_port():
    from tpu_gossip.cli.run_peer import build_parser as peer_parser
    from tpu_gossip.cli.run_seed import build_parser as seed_parser

    assert peer_parser().parse_args([]).port is None
    assert seed_parser().parse_args([]).port is None
