"""Topology generation: power-law exponent, CSR integrity, PA semantics.

The reference's topology capability is aspirational (dead ``powerlaw_connect``,
Seed.py:151-185; standalone demonstrate_powerlaw.py) — these tests pin down
the *intended* contract: degree distributions with the requested tail
exponent, and valid adjacency structure.
"""

import numpy as np
import pytest

from tpu_gossip.core.topology import (
    build_csr,
    configuration_model,
    edges_to_adjacency_sets,
    fit_powerlaw_gamma,
    powerlaw_degree_sequence,
    preferential_attachment,
)


def test_degree_sequence_even_sum_and_bounds():
    deg = powerlaw_degree_sequence(10_000, gamma=2.5, d_min=2, rng=np.random.default_rng(1))
    assert deg.sum() % 2 == 0
    assert deg.min() >= 2
    assert deg.max() <= int(round(10_000 ** (1 / 1.5))) + 1


@pytest.mark.parametrize("gamma", [2.2, 2.5, 3.0])
def test_degree_sequence_tail_exponent(gamma):
    deg = powerlaw_degree_sequence(200_000, gamma=gamma, d_min=2, rng=np.random.default_rng(7))
    est = fit_powerlaw_gamma(deg, d_min=5)
    assert abs(est - gamma) < 0.25, f"gamma_hat={est} for gamma={gamma}"


def test_configuration_model_valid_edges():
    rng = np.random.default_rng(3)
    deg = powerlaw_degree_sequence(5_000, gamma=2.5, rng=rng)
    edges = configuration_model(deg, rng=rng)
    assert edges.ndim == 2 and edges.shape[1] == 2
    # no self loops, no duplicates, canonical order
    assert np.all(edges[:, 0] < edges[:, 1])
    assert len(np.unique(edges, axis=0)) == len(edges)
    # erased fraction small: realized degree mass close to requested
    assert 2 * len(edges) > 0.9 * deg.sum()


def test_configuration_model_preserves_tail():
    rng = np.random.default_rng(11)
    deg = powerlaw_degree_sequence(100_000, gamma=2.5, rng=rng)
    g = build_csr(100_000, configuration_model(deg, rng=rng))
    est = fit_powerlaw_gamma(g.degrees, d_min=5)
    assert abs(est - 2.5) < 0.3


def test_csr_roundtrip_matches_adjacency_sets():
    rng = np.random.default_rng(5)
    deg = powerlaw_degree_sequence(200, gamma=2.5, rng=rng)
    edges = configuration_model(deg, rng=rng)
    g = build_csr(200, edges)
    adj = edges_to_adjacency_sets(edges)
    assert g.num_edges == len(edges)
    for i in range(200):
        assert set(g.neighbors(i).tolist()) == adj.get(i, set())
    # symmetric: i in N(j) iff j in N(i)
    for i in range(200):
        for j in g.neighbors(i):
            assert i in g.neighbors(int(j))


def test_preferential_attachment_python_path():
    edges = preferential_attachment(2_000, m=3, rng=np.random.default_rng(2), use_native=False)
    g = build_csr(2_000, edges)
    assert g.degrees.min() >= 3  # every non-seed node attaches m edges
    # BA yields gamma ~ 3
    est = fit_powerlaw_gamma(g.degrees, d_min=6)
    assert 2.2 < est < 4.0
    # degree-proportional growth: early nodes are hubs
    assert g.degrees[:10].mean() > 5 * g.degrees[-1000:].mean()


def test_preferential_attachment_connected():
    edges = preferential_attachment(500, m=2, rng=np.random.default_rng(9), use_native=False)
    g = build_csr(500, edges)
    # BFS from 0 reaches everyone (BA graphs are connected by construction)
    seen = np.zeros(500, dtype=bool)
    seen[0] = True
    frontier = [0]
    while frontier:
        nxt = []
        for u in frontier:
            for v in g.neighbors(u):
                if not seen[v]:
                    seen[v] = True
                    nxt.append(int(v))
        frontier = nxt
    assert seen.all()
