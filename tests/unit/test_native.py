"""Native C++ preferential-attachment generator vs the numpy fallback."""

import subprocess

import numpy as np
import pytest

import tpu_gossip.native as native
from tpu_gossip.core.topology import (
    build_csr,
    fit_powerlaw_gamma,
    preferential_attachment,
)


@pytest.fixture(scope="module")
def lib_available():
    if native._load() is None:
        # toolchain is in the image; build on demand
        try:
            subprocess.run(
                ["make", "-C", "tpu_gossip/native"], check=True,
                capture_output=True, timeout=120,
            )
        except Exception:
            pytest.skip("native toolchain unavailable")
        native._lib = None  # force re-load
    if native._load() is None:
        pytest.skip("libtpugossip.so missing")
    return True


def test_native_structure(lib_available):
    n, m = 5000, 3
    e = native.pa_edges_native(n, m, seed=1)
    g = build_csr(n, e)
    # BA invariants: every node has >= m edges; edge count is exact
    assert g.degrees.min() >= m
    assert g.num_edges == m * (m + 1) // 2 + (n - m - 1) * m
    # no self loops, ids in range
    assert np.all(e[:, 0] != e[:, 1])
    assert e.min() >= 0 and e.max() < n


def test_native_matches_python_distribution(lib_available):
    n, m = 20000, 3
    g_c = build_csr(n, native.pa_edges_native(n, m, seed=2))
    g_py = build_csr(n, preferential_attachment(n, m=m, use_native=False))
    assert g_c.num_edges == g_py.num_edges
    # same power-law tail (BA gamma ≈ 3) within estimator noise
    gamma_c = fit_powerlaw_gamma(g_c.degrees)
    gamma_py = fit_powerlaw_gamma(g_py.degrees)
    assert abs(gamma_c - gamma_py) < 0.4
    assert 2.2 < gamma_c < 3.6


def test_native_deterministic(lib_available):
    a = native.pa_edges_native(1000, 3, seed=9)
    b = native.pa_edges_native(1000, 3, seed=9)
    np.testing.assert_array_equal(a, b)
    c = native.pa_edges_native(1000, 3, seed=10)
    assert not np.array_equal(a, c)


def test_default_path_prefers_native(lib_available):
    # preferential_attachment(use_native=True) must route through the lib
    e = preferential_attachment(2000, m=3)
    g = build_csr(2000, e)
    assert g.degrees.min() >= 3
