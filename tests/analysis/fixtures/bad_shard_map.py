"""Fixture: raw shard_map references graftlint must catch."""

import jax
from jax.experimental.shard_map import shard_map  # raw import


def raw_attribute(f, mesh, specs):
    return jax.shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs)


def raw_experimental(f, mesh, specs):
    return jax.experimental.shard_map.shard_map(
        f, mesh=mesh, in_specs=specs, out_specs=specs
    )


def raw_from_import(f, mesh, specs):
    return shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs)
