"""Fixture: static_argnames drift graftlint must catch."""

import functools

import jax


@functools.partial(  # typo'd static name; donation declared (not under test)
    jax.jit, static_argnames=("cfg", "capactiy"), donate_argnames=("state",)
)
def renamed_param(state, cfg, capacity: int):
    return state[:capacity]


@jax.jit(static_argnames="num_rouns", donate_argnames=("state",))  # the parameter is num_rounds
def direct_call_form(state, num_rounds: int):
    return state * num_rounds


@functools.partial(  # only 2 positional params
    jax.jit, static_argnums=(3,), donate_argnames=("state",)
)
def nums_out_of_range(state, n):
    return state + n


def wrapped(state, mode):
    return state


jitted = jax.jit(  # assignment form
    wrapped, static_argnames=("moed",), donate_argnames=("state",)
)
