"""Fixture: static_argnames drift graftlint must catch."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("cfg", "capactiy"))  # typo'd
def renamed_param(state, cfg, capacity: int):
    return state[:capacity]


@jax.jit(static_argnames="num_rouns")  # the parameter is num_rounds
def direct_call_form(state, num_rounds: int):
    return state * num_rounds


@functools.partial(jax.jit, static_argnums=(3,))  # only 2 positional params
def nums_out_of_range(state, n):
    return state + n


def wrapped(state, mode):
    return state


jitted = jax.jit(wrapped, static_argnames=("moed",))  # assignment form
