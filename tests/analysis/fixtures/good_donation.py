"""Fixture: jit-state-donation graftlint must NOT flag these."""

import functools

import jax


@functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnames=("state",)
)
def donating_entry(state, cfg):
    return state


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def donating_by_num(state, n):
    return state


@functools.partial(jax.jit, donate_argnames=("state", "other"))
def donating_tuple(state, other):
    return state


@jax.jit
def no_state_param(x, y):
    return x + y  # donation not required: nothing is named state


def helper(state):
    return state  # not jitted: the rule only binds jit entry points


NAMES = ("state",)


@functools.partial(jax.jit, donate_argnames=NAMES)
def computed_names(state):
    return state  # non-literal donate_argnames: unprovable, trusted
