"""Fixture: every shape of PRNG key reuse graftlint must catch.

NOT importable production code — linted as text by tests/analysis.
"""

import jax
import jax.numpy as jnp


def double_sample(key):
    a = jax.random.uniform(key, (4,))
    b = jax.random.normal(key, (4,))  # reuse: same key, second sampler
    return a + b


def sample_then_split(key):
    u = jax.random.uniform(key, (4,))
    k1, k2 = jax.random.split(key)  # reuse: key already consumed
    return u, k1, k2


def double_split(key):
    ka = jax.random.split(key, 2)
    kb = jax.random.split(key, 3)  # reuse: identical leading subkeys
    return ka, kb


def loop_reuse(key, n):
    out = jnp.zeros(())
    for _ in range(n):
        out = out + jax.random.uniform(key)  # reuse across iterations
    return out


def transfer_then_sample(key, helper):
    x = helper(key)  # ownership moved to the callee
    return x + jax.random.uniform(key)  # reuse after transfer


def inline_root_key():
    return jax.random.uniform(jax.random.key(0), (4,))  # constant stream


def scan_body_captures_key(key, xs):
    def body(carry, x):
        # captured key consumed per ITERATION: one value, many draws
        return carry + jax.random.bernoulli(key, 0.5), x

    out, _ = jax.lax.scan(body, jnp.zeros(()), xs)
    return out


def closure_capture_then_outer_use(key):
    def helper():
        return jax.random.uniform(key)  # consumes the captured key

    a = helper()
    return a + jax.random.normal(key)  # reuse after closure consumption
