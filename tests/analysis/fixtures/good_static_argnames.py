"""Fixture: correct static_argnames graftlint must NOT flag."""

import functools

import jax


@functools.partial(
    jax.jit, static_argnames=("cfg", "capacity"), donate_argnames=("state",)
)
def correct(state, cfg, capacity: int):
    return state[:capacity]


@functools.partial(jax.jit, static_argnames=("m", "do_push"))
def kwonly(plan, x, *, m: int, do_push: bool = True):
    return x if do_push else x[:m]


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def nums_in_range(state, n):
    return state + n


def wrapped(state, mode):
    return state


jitted = jax.jit(wrapped, static_argnames=("mode",), donate_argnames=("state",))
