"""Fixture: a collective hidden behind a LAMBDA-WRAPPED branch arm.

This module is deliberately blind-spot-shaped for the AST tier: it
routes shard_map through the sanctioned compat shim (so the 65-line
``raw-shard-map`` rule in rules_shardmap.py has nothing to say) and
tucks a ``psum`` inside one lambda arm of a ``lax.cond`` gated on the
shard's OWN data — the classic multi-host deadlock. No AST rule can
prove which arm a traced cond takes or that the arms' collective
sequences differ; only the deep pass over the traced jaxpr
(``deep-collective-uniformity``) can. tests/analysis/test_collectives.py
asserts exactly that split: the AST lint of THIS FILE is clean, the
trace of ``build(mesh)`` is a finding.
"""

import jax
from jax.sharding import PartitionSpec as P

from tpu_gossip.dist._compat import shard_map_compat
from tpu_gossip.dist.mesh import AXIS


def build(mesh):
    """A shard_mapped round whose reduce rendezvous depends on local data."""

    def body(x):
        # shard-varying predicate: each shard reads its own slice
        return jax.lax.cond(
            x[0] > 0.0,
            lambda v: jax.lax.psum(v, AXIS),  # arm 1 rendezvouses...
            lambda v: v,                      # ...arm 0 never does
            x,
        )

    return shard_map_compat(
        body, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)
    )
