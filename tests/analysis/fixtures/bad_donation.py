"""Fixture: jit-state-donation graftlint must catch these."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("cfg",))  # no donation
def copying_entry(state, cfg):
    return state


@jax.jit  # bare form, no kwargs at all
def bare_jit(state):
    return state


@functools.partial(jax.jit, donate_argnums=(1,))  # wrong index (state is 0)
def wrong_num(state, aux):
    return state


@functools.partial(jax.jit, donate_argnames=("aux",))  # wrong name
def wrong_name(state, aux):
    return state


@functools.partial(jax.jit, donate_argnames="aux")  # bare-string wrong name
def wrong_bare_string(state, aux):
    return state


def wrapped(state, mode):
    return state


jitted = jax.jit(wrapped, static_argnames=("mode",))  # assignment form
