"""Fixture: shard_map routed through the compat shim — clean.

Mentioning shard_map in a docstring or comment is fine: the rule is an
AST pass, not a grep. Everything executable goes through
``shard_map_compat`` (the check_rep/check_vma rename shim).
"""

from tpu_gossip.dist._compat import shard_map_compat


def shimmed(f, mesh, specs):
    # shard_map spelled out here in a comment is not a finding
    return shard_map_compat(f, mesh=mesh, in_specs=specs, out_specs=specs)
