"""Fixture: deep-use-after-donate (AST side) must flag every read here.

Named ``deep_*`` (not ``bad_*``) deliberately: the plain-rules CLI glob
tests run every ``bad_*`` fixture WITHOUT ``--deep`` and expect exit 1 —
these reads are invisible to the AST rules and only the deep tier's
read-after-donate scan reports them.
"""

import functools

import jax


@functools.partial(jax.jit, donate_argnames=("state",))
def step(state):
    return state


def straight_line_read(state):
    out = step(state)
    return out, state.rng  # read after donation: buffers deleted


def branch_falls_through(state, flag):
    if flag:
        step(state)  # donates on this arm, no return
    return state  # the fall-through read sees deleted buffers when flag


def read_in_error_path(state, check):
    out = step(state)
    if check:
        raise ValueError(f"bad state: {state}")  # the ship-a-bug shape
    return out


def loop_cross_iteration(states_cfg, n):
    acc = 0.0
    for _ in range(n):
        acc += float(states_cfg.coverage)  # iteration k+1 reads k's donation
        step(states_cfg)
    return acc


def keyword_form(state):
    step(state=state)
    return state.round  # donation via keyword argument still counts
