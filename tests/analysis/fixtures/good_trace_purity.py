"""Fixture: purity patterns graftlint must NOT flag."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def static_shape_casts(x):
    n = int(x.shape[0])  # shape access is trace-time static
    return x + float(len(x.shape))


@jax.jit
def static_aval_attribute_casts(x):
    # .ndim/.dtype/.itemsize are aval metadata, as trace-time static as
    # .shape — the deep tier's jaxpr helpers size byte budgets this way
    rank = int(x.ndim)
    width = int(x.dtype.itemsize)
    bits = int(jnp.finfo(x.dtype).bits) if x.dtype == jnp.float32 else 32
    return x * float(rank * width * bits)


@jax.jit
def static_byte_budget(x):
    budget = int(x.size * x.itemsize // 8)  # byte sizing off static attrs
    return x + float(budget)


@functools.partial(jax.jit, static_argnames=("d_max",))
def static_argname_cast(x, d_max: int):
    return jnp.minimum(x, float(d_max))  # static arg: a host int at trace


def host_bench(run):
    t0 = time.perf_counter()  # NOT jit-reachable: host timing is fine
    out = run()
    wall = time.perf_counter() - t0
    return float(np.asarray(out).sum()), wall


@jax.jit
def pure_round(x, key):
    return x + jax.random.uniform(key, x.shape)
