"""Fixture: purity patterns graftlint must NOT flag."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def static_shape_casts(x):
    n = int(x.shape[0])  # shape access is trace-time static
    return x + float(len(x.shape))


@functools.partial(jax.jit, static_argnames=("d_max",))
def static_argname_cast(x, d_max: int):
    return jnp.minimum(x, float(d_max))  # static arg: a host int at trace


def host_bench(run):
    t0 = time.perf_counter()  # NOT jit-reachable: host timing is fine
    out = run()
    wall = time.perf_counter() - t0
    return float(np.asarray(out).sum()), wall


@jax.jit
def pure_round(x, key):
    return x + jax.random.uniform(key, x.shape)
