"""Fixture: pragma misuse graftlint must catch."""

import jax


def reasonless(key):
    a = jax.random.uniform(key)
    b = jax.random.uniform(key)  # graftlint: disable=key-linearity
    return a + b


def unknown_rule(key):
    # graftlint: disable=no-such-rule -- typo'd rule id must be reported
    return jax.random.uniform(key)
