"""Fixture: host syncs / impurity inside jit-reachable code."""

import functools
import random
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def wall_clock(x):
    t = time.time()  # host clock baked into the trace
    return x * t


@jax.jit
def host_rng(x):
    return x + random.random()  # stdlib RNG: one host draw at trace time


@jax.jit
def numpy_rng(x):
    return x + np.random.uniform()  # numpy RNG: same trace-time bake


@jax.jit
def materialize(x):
    h = np.asarray(x)  # device->host materialization
    return jnp.asarray(h)


@functools.partial(jax.jit, static_argnames=("n",))
def cast_traced(x, n: int):
    scale = float(x[0])  # host sync on a traced value
    return x * scale * n


@jax.jit
def cast_traced_reduction(x):
    # int() over a traced VALUE is a sync even when static-looking
    # attributes appear elsewhere in the function
    rank = x.ndim
    return x * int(x.sum()) * rank


@jax.jit
def cast_param_before_static_rebind(x, rank):
    # `rank` is a TRACED parameter at this float() — the later static
    # rebind must not retroactively exempt the sync above it
    bad = float(rank)
    rank = int(x.ndim)
    return x * bad * rank


@jax.jit
def cast_derived_from_rebound(x, y):
    # `c` derives from the traced binding of `b`; b's later static rebind
    # must not transitively exempt float(c) — the ambiguity drop has to
    # propagate to derived names
    b = y
    c = b * 2
    b = int(x.ndim)
    return x * float(c) * b


@jax.jit
def item_sync(x):
    return x.sum().item()  # .item() forces a device->host sync


@jax.jit(donate_argnames=("state",))
def item_sync_attribute_chain(state):
    # the COMMON form: .item() hanging off an attribute chain
    return state.coverage.item()


def helper_impure(x):
    return x * time.perf_counter()  # impure; reachable via jitted caller


@jax.jit
def calls_impure_helper(x):
    return helper_impure(x)
