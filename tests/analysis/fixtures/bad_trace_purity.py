"""Fixture: host syncs / impurity inside jit-reachable code."""

import functools
import random
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def wall_clock(x):
    t = time.time()  # host clock baked into the trace
    return x * t


@jax.jit
def host_rng(x):
    return x + random.random()  # stdlib RNG: one host draw at trace time


@jax.jit
def numpy_rng(x):
    return x + np.random.uniform()  # numpy RNG: same trace-time bake


@jax.jit
def materialize(x):
    h = np.asarray(x)  # device->host materialization
    return jnp.asarray(h)


@functools.partial(jax.jit, static_argnames=("n",))
def cast_traced(x, n: int):
    scale = float(x[0])  # host sync on a traced value
    return x * scale * n


@jax.jit
def item_sync(x):
    return x.sum().item()  # .item() forces a device->host sync


@jax.jit(donate_argnames=("state",))
def item_sync_attribute_chain(state):
    # the COMMON form: .item() hanging off an attribute chain
    return state.coverage.item()


def helper_impure(x):
    return x * time.perf_counter()  # impure; reachable via jitted caller


@jax.jit
def calls_impure_helper(x):
    return helper_impure(x)
