"""Fixture: deep-use-after-donate (AST side) must stay SILENT here —
every shape below is a sanctioned donation idiom (false-positive guard).
"""

import functools

import jax

from tpu_gossip.core.state import clone_state


@functools.partial(jax.jit, donate_argnames=("state",))
def step(state):
    return state


def threaded(state, n):
    for _ in range(n):
        state = step(state)  # rebinding from the result: the idiom
    return state


def cloned_keepalive(state):
    out = step(clone_state(state))  # the clone dies, the input survives
    return out, state.rng


def early_return_dispatch(state, fast):
    if fast:
        return step(state)  # this arm never falls through
    return state  # reads the UNdonated input: a different control path


def read_before(state):
    cov = state.coverage
    out = step(state)
    return out, cov  # everything needed was read BEFORE the call


def rebound_in_both_arms(state, flag):
    if flag:
        state = step(state)
    else:
        state = step(state)
    return state  # both arms rebind: no deleted handle survives


def nested_scope_is_its_own(state):
    out = step(state)

    def reader(s):
        return s.rng  # own-scope parameter, not the donated outer name

    return out, reader(out)
