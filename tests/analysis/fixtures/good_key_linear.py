"""Fixture: linear key discipline graftlint must NOT flag."""

import jax
import jax.numpy as jnp


def split_rebind(key):
    key, sub = jax.random.split(key)  # consume + rebind is linear
    a = jax.random.uniform(sub, (4,))
    key, sub2 = jax.random.split(key)  # rebound key: fresh again
    return a + jax.random.normal(sub2, (4,))


def fold_in_loop(key, n):
    out = jnp.zeros(())
    for i in range(n):
        out = out + jax.random.uniform(jax.random.fold_in(key, i))
    return out


def early_return_branches(key, mode):
    if mode == "a":
        return jax.random.uniform(key, (2,))  # branch terminates
    return jax.random.normal(key, (2,))  # so this is the only other use


def if_else_once_each(key, flag):
    if flag:
        x = jax.random.uniform(key)
    else:
        x = jax.random.normal(key)
    return x


def loop_rederive(key, n):
    out = jnp.zeros(())
    for _ in range(n):
        key, sub = jax.random.split(key)  # re-derived every iteration
        out = out + jax.random.uniform(sub)
    return out


def scan_body_folds_key(key, xs):
    def body(carry, x):
        # fold_in with the varying element: derivation, not consumption
        return carry + jax.random.uniform(jax.random.fold_in(key, x)), x

    out, _ = jax.lax.scan(body, jnp.zeros(()), xs)
    return out


def closure_capture_single_use(key):
    def helper():
        return jax.random.uniform(key)  # one consumption, nothing after

    return helper()
