"""Whole-tree pragma audit (ISSUE 17 satellite): every
``# graftlint: disable=`` pragma in the lint scope must name rules the
registry actually owns AND carry a ``-- reason``.

run_rules already reports ``pragma-needs-reason`` / ``pragma-unknown-rule``
per module, but only for modules a lint run visits and only as findings a
baseline could swallow. This audit is the backstop that cannot be
baselined: it walks the full default scope directly and FAILS the suite on
any stale pragma — a suppression that names a renamed/removed rule
silently suppresses nothing, which is worse than a loud finding.
"""

import functools

from tpu_gossip.analysis.cli import _DEFAULT_SCOPE, modules_for, repo_root
from tpu_gossip.analysis.registry import DEEP_RULES, MEM_RULES, RULES


@functools.lru_cache(maxsize=1)  # one tree parse serves all three audits
def _all_pragmas():
    """(module_rel, line, Pragma) for every pragma in the lint scope,
    deduped (comment-line pragmas register on two lines)."""
    out = []
    for m in modules_for(repo_root(), list(_DEFAULT_SCOPE)):
        seen = set()
        for line, prag in sorted(m.pragmas.items()):
            if id(prag) in seen:
                continue
            seen.add(id(prag))
            out.append((m.rel, line, prag))
    return tuple(out)


def test_tree_has_pragmas_to_audit():
    # the audit below must not vacuously pass because the walker broke
    assert _all_pragmas(), "pragma walker found no pragmas in the tree"


def test_every_pragma_names_a_registered_rule():
    known = (
        set(RULES) | set(DEEP_RULES) | set(MEM_RULES)
        | {"*", "pragma-needs-reason"}
    )
    stale = [
        f"{rel}:{line}: {','.join(sorted(prag.rules - known))}"
        for rel, line, prag in _all_pragmas()
        if prag.rules - known
    ]
    assert not stale, (
        "stale pragmas naming unregistered rules (they suppress NOTHING "
        "— delete or rename them):\n" + "\n".join(stale)
    )


def test_every_pragma_carries_a_reason():
    bare = [
        f"{rel}:{line}: disable={','.join(sorted(prag.rules))}"
        for rel, line, prag in _all_pragmas()
        if not prag.reason
    ]
    assert not bare, (
        "pragmas without a `-- reason` (the next reader deserves the "
        "why):\n" + "\n".join(bare)
    )
