"""The shared entry-point matrix: ONE parametrization, TWO consumers.

The contract audit (contracts.py) and the jaxpr deep tier (deep/) must
walk the same matrix — an engine/mode added to one and silently skipped
by the other re-opens the gap this harness closed. These tests pin (a)
the matrix's structural coverage, (b) that every entry is owned by a
registered audit check (the union covers the matrix), (c) that one trace
cache serves both consumers, and (d) that a broken entry is reported by
BOTH tiers (behavioral proof they read the same matrix).
"""

import pytest

from tpu_gossip.analysis.contracts import AUDIT_CHECKS, audit_contracts
from tpu_gossip.analysis.entrypoints import (
    EntryPoint,
    entry_points,
    trace_matrix,
)

EPS = entry_points()


def test_every_entry_owned_by_a_registered_audit_check():
    unowned = [ep.name for ep in EPS if ep.audit_check not in AUDIT_CHECKS]
    assert unowned == [], f"matrix entries no audit check owns: {unowned}"


def test_matrix_structural_coverage():
    """The product the bit-identity contract quantifies over: every local
    delivery engine, every mode, both slot widths, churn/SIR/compact,
    every tail, scenario and growth planes, both dist engines, sparse
    transport, and the jitted loop entries."""
    names = {ep.name for ep in EPS}
    engines = {ep.engine for ep in EPS}
    assert {"xla", "pallas", "matching"} <= engines
    for mode in ("push", "push_pull", "flood"):
        for eng in ("xla", "pallas", "matching"):
            for m in (1, 16):
                assert f"local[{eng},{mode},m={m}]" in names
    for extra in ("churn", "sir", "churn-compact", "scenario", "growth",
                  "stream", "scenario+growth", "scenario+growth+stream",
                  "control", "scenario+growth+stream+control",
                  "adversary", "scenario+growth+stream+control+adversary"):
        assert f"local[xla,{extra}]" in names
    for tail in ("reference", "fused", "pallas"):
        assert f"local[xla,tail={tail}]" in names
    assert "local[matching,scenario]" in names
    assert "local[matching,growth]" in names and "local[pallas,growth]" in names
    assert "local[matching,stream]" in names and "local[pallas,stream]" in names
    # the SERVED round (serve/ live-ingestion window) on every engine
    assert "local[matching,ingest]" in names and "local[pallas,ingest]" in names
    assert "local[xla,ingest]" in names
    assert "local[matching,control]" in names and "local[pallas,control]" in names
    assert "local[simulate]" in names and "local[run_until_coverage]" in names
    # the PACKED loop entries (core/packed.py): packed carries must be
    # fixed points too, and the mem tier prices the packed residency
    assert "local[simulate,packed]" in names
    assert "local[run_until_coverage,packed]" in names
    # the batched fleet entry (fleet/): composed campaign at batch rank
    assert "fleet[simulate,composed]" in names
    # dist half (present on this 8-device test host)
    assert {"dist-matching", "dist-bucketed"} <= engines
    for n in (
        "dist[matching]", "dist[matching,scenario]", "dist[matching,growth]",
        "dist[matching,stream]", "dist[matching,ingest]",
        "dist[bucketed]", "dist[bucketed,growth]", "dist[bucketed,stream]",
        "dist[matching,simulate]", "dist[bucketed,run_until_coverage]",
        "dist[matching,sparse]", "dist[bucketed,sparse]",
        "dist[matching,control]", "dist[bucketed,control]",
        "dist[matching,pipeline]", "dist[bucketed,pipeline]",
        "dist[matching,pipeline+scenario+stream]",
        "dist[matching,adversary+scenario]",
        "dist[matching,simulate,packed]",
    ):
        assert n in names, n


def test_jitted_loop_entries_declare_their_pjit_name():
    """Every simulate/coverage entry must carry jit_name — that is the
    hook the deep tier's donation pass verifies donated_invars through."""
    for ep in EPS:
        if ep.kind in ("simulate", "coverage"):
            assert ep.jit_name, (
                f"{ep.name}: jitted loop entry without jit_name"
            )
        else:
            assert ep.kind == "round"


def test_entry_names_unique():
    names = [ep.name for ep in EPS]
    assert len(names) == len(set(names))


def test_every_entry_declares_n_peers():
    """Every matrix entry carries an explicit n_peers (the mem tier's
    bytes/peer denominator) matching its built state's slot count — n
    used to be implicit in each builder closure, which a scale metric
    cannot read. Priced at BATCH RANK: a fleet entry's alive plane is
    (K, N), and its denominator is the AGGREGATE K*N slot count (the
    plane-registry pricing convention, core.state.state_bytes_per_peer)."""
    import numpy as np

    for ep in EPS:
        assert ep.n_peers > 0, f"{ep.name}: n_peers undeclared"
        _, st = ep.build()
        # packed entries carry the six masks in the shared flags word
        lead = st.alive.shape if hasattr(st, "alive") else st.flags.shape
        slots = int(np.prod(lead))
        assert slots == ep.n_peers, (
            f"{ep.name}: declared n_peers={ep.n_peers} but the built "
            f"state has {slots} slots"
        )


def test_trace_cache_shared_across_consumers():
    """The same cache dict must make the second consumer reuse the first's
    TracedEntry objects — the CLI's one-matrix-per-invocation guarantee."""
    eps = [ep for ep in EPS if ep.name == "local[xla,push,m=1]"]
    cache: dict = {}
    first = trace_matrix(eps, cache=cache)
    second = trace_matrix(eps, cache=cache)
    assert first["local[xla,push,m=1]"] is second["local[xla,push,m=1]"]
    assert first["local[xla,push,m=1]"].jaxpr is not None


def test_broken_entry_reported_by_both_tiers(monkeypatch):
    """Seed ONE broken matrix entry and assert the audit AND the deep tier
    both surface it — the behavioral pin that they consume the same
    parametrization, not two drifting copies."""
    from tpu_gossip.analysis import contracts as contracts_mod
    from tpu_gossip.analysis import entrypoints as ep_mod

    def boom_build():
        raise RuntimeError("synthetic matrix-entry break")

    broken = EntryPoint(
        name="synthetic[broken]", engine="xla", kind="round",
        audit_check="gossip_round_local", build=boom_build,
    )
    tiny = (broken,)
    monkeypatch.setattr(ep_mod, "entry_points", lambda: tiny)
    # contracts.py binds the names at import: patch its view too — the
    # production CLI resolves both through the same module function
    monkeypatch.setattr(contracts_mod, "entry_points", lambda: tiny)

    cache: dict = {}
    audit = audit_contracts(names=["gossip_round_local"], cache=cache)
    assert any(
        "synthetic[broken]" in f.message and "abstract eval failed"
        in f.message for f in audit
    ), [f.message for f in audit]

    from tpu_gossip.analysis.deep import run_deep

    deep = [f for f in run_deep(cache=cache) if f.rule == "deep-trace-error"]
    assert any(f.qualname == "synthetic[broken]" for f in deep), [
        f.render() for f in deep
    ]
    # and the shared cache means the broken build was attempted ONCE per
    # consumer-visible entry, not re-raised into divergent matrices
    assert "synthetic[broken]" in cache


@pytest.mark.parametrize("check", sorted(
    {ep.audit_check for ep in EPS if ep.kind in ("round", "simulate",
                                                 "coverage")}
))
def test_round_audit_checks_exist(check):
    assert check in AUDIT_CHECKS
