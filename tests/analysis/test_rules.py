"""Per-rule fixture coverage: each bad fixture trips exactly its rule,
each good fixture stays clean (false-positive regression guard)."""

from pathlib import Path

from tpu_gossip.analysis import lint_paths

FIXTURES = Path(__file__).parent / "fixtures"


def _rules_hit(name: str, project_wide: bool = False):
    findings = lint_paths(
        [str(FIXTURES / name)], root=FIXTURES, project_wide=project_wide
    )
    return findings, {f.rule for f in findings}


# ------------------------------------------------------------ key-linearity
def test_key_reuse_all_shapes_flagged():
    findings, rules = _rules_hit("bad_key_reuse.py")
    assert rules == {"key-linearity"}
    by_line = {f.line for f in findings}
    # one finding per bad function: double sampler, sample-then-split,
    # double split, loop reuse, transfer-then-sample, inline root key,
    # scan-body captured key, closure capture + outer reuse
    assert len(findings) == 8, [f.render() for f in findings]
    assert len(by_line) == 8


def test_linear_keys_clean():
    findings, _ = _rules_hit("good_key_linear.py")
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------------------ raw-shard-map
def test_raw_shard_map_flagged():
    findings, rules = _rules_hit("bad_shard_map.py")
    assert rules == {"raw-shard-map"}
    # the import + three call forms (from-import call resolves through the
    # import finding's alias; attribute forms are findings of their own)
    assert len(findings) >= 3, [f.render() for f in findings]


def test_shimmed_shard_map_clean():
    findings, _ = _rules_hit("good_shard_map.py")
    assert findings == [], [f.render() for f in findings]


def test_compat_shim_itself_exempt():
    from tpu_gossip.analysis.cli import repo_root

    root = repo_root()
    findings = lint_paths(
        ["tpu_gossip/dist/_compat.py"], root=root, project_wide=False
    )
    assert [f for f in findings if f.rule == "raw-shard-map"] == []


# ------------------------------------------------------------- trace-purity
def test_trace_impurity_flagged():
    findings, rules = _rules_hit("bad_trace_purity.py")
    assert rules == {"trace-purity"}
    msgs = "\n".join(f.message for f in findings)
    for needle in (
        "time.time", "random.random", "numpy.random.uniform",
        "numpy.asarray", "float()", "int()", ".item()",
        "time.perf_counter",
        # a traced parameter read BEFORE its static rebind is still a sync
        "cast_param_before_static_rebind",
        # the ambiguity drop propagates to names DERIVED from the traced
        # binding (b = y; c = b*2; b = int(x.ndim) — float(c) syncs)
        "cast_derived_from_rebound",
    ):
        assert needle in msgs, f"missing {needle} in:\n{msgs}"


def test_purity_allowances_clean():
    findings, _ = _rules_hit("good_trace_purity.py")
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------------- static-argnames-drift
def test_static_argnames_drift_flagged():
    findings, rules = _rules_hit("bad_static_argnames.py")
    assert rules == {"static-argnames-drift"}
    msgs = "\n".join(f.message for f in findings)
    assert "'capactiy'" in msgs
    assert "'num_rouns'" in msgs
    assert "'moed'" in msgs
    assert "static_argnums 3 out of range" in msgs


def test_static_argnames_correct_clean():
    findings, _ = _rules_hit("good_static_argnames.py")
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------------------------ pragmas
def test_pragma_suppresses_but_requires_reason():
    findings, rules = _rules_hit("bad_pragma.py")
    # the key-linearity finding is suppressed by the pragma, but the
    # reason-less pragma and the unknown rule id are findings themselves
    assert "key-linearity" not in rules
    assert "pragma-needs-reason" in rules
    assert "pragma-unknown-rule" in rules


def test_pragma_inside_string_is_text_not_suppression(tmp_path):
    """Pragma syntax quoted in a docstring/string must neither suppress the
    next line nor demand a reason (comments come from the tokenizer)."""
    src = (
        '"""Docs quoting the idiom: # graftlint: disable=key-linearity"""\n'
        "import jax\n\n\n"
        "def f(key):\n"
        "    msg = '# graftlint: disable=key-linearity'\n"
        "    a = jax.random.uniform(key)\n"
        "    b = jax.random.uniform(key)\n"  # real reuse must still flag
        "    return a + b, msg\n"
    )
    p = tmp_path / "quoted_pragma.py"
    p.write_text(src)
    findings = lint_paths([str(p)], root=tmp_path, project_wide=False)
    assert {f.rule for f in findings} == {"key-linearity"}, [
        f.render() for f in findings
    ]


def test_pragma_with_reason_suppresses_silently(tmp_path):
    src = (
        "import jax\n\n\n"
        "def f(key):\n"
        "    a = jax.random.uniform(key)\n"
        "    # graftlint: disable=key-linearity -- fixture: deliberate reuse\n"
        "    b = jax.random.uniform(key)\n"
        "    return a + b\n"
    )
    p = tmp_path / "pragma_ok.py"
    p.write_text(src)
    findings = lint_paths([str(p)], root=tmp_path, project_wide=False)
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------- jit-state-donation
def test_missing_state_donation_flagged():
    findings, rules = _rules_hit("bad_donation.py")
    assert rules == {"jit-state-donation"}
    # partial-without-donation, bare @jax.jit, wrong donate_argnums index,
    # wrong donate_argnames name (tuple AND bare-string forms),
    # assignment form
    assert len(findings) == 6, [f.render() for f in findings]


def test_declared_donation_clean():
    findings, _ = _rules_hit("good_donation.py")
    assert findings == [], [f.render() for f in findings]


def test_repo_round_entry_points_all_donate():
    """The live entry points themselves: the rule that exists to stop future
    regressions must find the current tree clean."""
    from tpu_gossip.analysis.cli import repo_root

    root = repo_root()
    findings = lint_paths(
        ["tpu_gossip/sim/engine.py", "tpu_gossip/dist/mesh.py"],
        root=root, project_wide=False,
    )
    assert [f for f in findings if f.rule == "jit-state-donation"] == [], [
        f.render() for f in findings
    ]
