"""Baseline round-trip + new/old partition semantics (the py3.10-compatible
minimal TOML subset in analysis/baseline.py)."""

from tpu_gossip.analysis.baseline import (
    load_baseline,
    load_baseline_entries,
    split_new,
    write_baseline,
)
from tpu_gossip.analysis.registry import Finding


def _f(file, rule, msg, line=3, qualname=""):
    return Finding(
        file=file, line=line, col=1, rule=rule, message=msg,
        qualname=qualname,
    )


def test_round_trip(tmp_path):
    p = tmp_path / "lint_baseline.toml"
    findings = [
        _f("a.py", "key-linearity", 'PRNG key "k" consumed twice'),
        _f("b.py", "trace-purity", 'tricky "quoted" \\ message\nwith newline'),
    ]
    write_baseline(p, findings)
    loaded = load_baseline(p)
    assert loaded == {f.baseline_key for f in findings}


def test_qualname_round_trip(tmp_path):
    """Identity anchors on (file, rule, qualname) when the finding carries
    a qualname: the write/load cycle preserves exactly that key."""
    p = tmp_path / "b.toml"
    findings = [
        _f("a.py", "key-linearity", "msg will drift", qualname="simulate"),
        _f("a.py", "trace-purity", "another", qualname="run.body"),
    ]
    write_baseline(p, findings)
    loaded = load_baseline(p)
    assert loaded == {
        ("a.py", "key-linearity", "simulate"),
        ("a.py", "trace-purity", "run.body"),
    }
    assert loaded == {f.baseline_key for f in findings}


def test_qualname_identity_survives_message_and_line_drift(tmp_path):
    """The satellite's point: an unrelated edit that shifts lines or
    reworded shapes/values inside the message must not churn the baseline
    — (rule, module, qualname) is the stable identity."""
    p = tmp_path / "b.toml"
    write_baseline(
        p, [_f("a.py", "r", "old message (128, 32)", line=3, qualname="fn")]
    )
    drifted = _f("a.py", "r", "new message (256, 64)", line=99, qualname="fn")
    new, old = split_new([drifted], load_baseline(p))
    assert new == [] and old == [drifted]


def test_legacy_message_entries_still_load(tmp_path):
    """A baseline written by a pre-qualname tree (message-keyed entries)
    must still suppress findings that carry no qualname."""
    p = tmp_path / "b.toml"
    p.write_text(
        '[[finding]]\nfile = "a.py"\nrule = "r"\nmessage = "legacy"\n'
    )
    legacy = _f("a.py", "r", "legacy")
    new, old = split_new([legacy], load_baseline(p))
    assert new == [] and old == [legacy]


def test_legacy_message_entries_suppress_qualname_findings(tmp_path):
    """The upgrade path: a pre-qualname baseline entry must keep
    suppressing after the rule starts attaching qualnames to the SAME
    finding — otherwise every baselined finding resurrects as new the
    moment the tree upgrades."""
    p = tmp_path / "b.toml"
    p.write_text(
        '[[finding]]\nfile = "a.py"\nrule = "trace-purity"\n'
        'message = "float() over traced value"\n'
    )
    upgraded = _f(
        "a.py", "trace-purity", "float() over traced value",
        qualname="some_fn",
    )
    new, old = split_new([upgraded], load_baseline(p))
    assert new == [] and old == [upgraded]


def test_line_numbers_do_not_affect_matching(tmp_path):
    p = tmp_path / "b.toml"
    write_baseline(p, [_f("a.py", "r", "m", line=3)])
    new, old = split_new([_f("a.py", "r", "m", line=99)], load_baseline(p))
    assert new == [] and len(old) == 1


def test_split_new_partition(tmp_path):
    p = tmp_path / "b.toml"
    known = _f("a.py", "r", "known")
    write_baseline(p, [known])
    fresh = _f("a.py", "r", "fresh")
    new, old = split_new([known, fresh], load_baseline(p))
    assert new == [fresh] and old == [known]


def test_missing_baseline_is_strict(tmp_path):
    assert load_baseline(tmp_path / "nope.toml") == set()


def test_duplicate_entries_deduped(tmp_path):
    p = tmp_path / "b.toml"
    write_baseline(p, [_f("a.py", "r", "m"), _f("a.py", "r", "m", line=9)])
    assert p.read_text().count("[[finding]]") == 1


def test_write_is_deterministically_ordered(tmp_path):
    """Entries sort by (rule, file, line) regardless of input order — the
    property that makes a regenerated baseline diff cleanly against the
    committed one instead of churning with scan order."""
    p1, p2 = tmp_path / "a.toml", tmp_path / "b.toml"
    findings = [
        _f("z.py", "trace-purity", "m1", line=9, qualname="f1"),
        _f("a.py", "trace-purity", "m2", line=2, qualname="f2"),
        _f("a.py", "trace-purity", "m3", line=40, qualname="f3"),
        _f("m.py", "key-linearity", "m4", line=1, qualname="f4"),
    ]
    write_baseline(p1, findings)
    write_baseline(p2, list(reversed(findings)))
    assert p1.read_text() == p2.read_text()
    entries = load_baseline_entries(p1)
    keys = [(e.rule, e.file, e.line) for e in entries]
    assert keys == sorted(keys)
    assert keys[0][0] == "key-linearity"  # rule is the primary column


def test_write_load_write_fixed_point(tmp_path):
    """write→load→write is a fixed point: every column the writer sorts
    by is a column it serializes, so regenerating from a loaded baseline
    reproduces the file byte-for-byte."""
    p1, p2 = tmp_path / "a.toml", tmp_path / "b.toml"
    findings = [
        _f("b.py", "trace-purity", 'tricky "quoted" \\ msg\nnewline',
           line=7),
        _f("a.py", "trace-purity", "same rule+file, later line", line=30,
           qualname="g"),
        _f("a.py", "trace-purity", "same rule+file, early line", line=4,
           qualname="f"),
        _f("a.py", "key-linearity", "other rule", line=11, qualname="h"),
    ]
    write_baseline(p1, findings)
    write_baseline(p2, load_baseline_entries(p1))
    assert p1.read_text() == p2.read_text()
    # and the identity set is unchanged through the cycle
    assert load_baseline(p1) == load_baseline(p2)
