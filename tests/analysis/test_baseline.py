"""Baseline round-trip + new/old partition semantics (the py3.10-compatible
minimal TOML subset in analysis/baseline.py)."""

from tpu_gossip.analysis.baseline import load_baseline, split_new, write_baseline
from tpu_gossip.analysis.registry import Finding


def _f(file, rule, msg, line=3):
    return Finding(file=file, line=line, col=1, rule=rule, message=msg)


def test_round_trip(tmp_path):
    p = tmp_path / "lint_baseline.toml"
    findings = [
        _f("a.py", "key-linearity", 'PRNG key "k" consumed twice'),
        _f("b.py", "trace-purity", 'tricky "quoted" \\ message\nwith newline'),
    ]
    write_baseline(p, findings)
    loaded = load_baseline(p)
    assert loaded == {f.baseline_key for f in findings}


def test_line_numbers_do_not_affect_matching(tmp_path):
    p = tmp_path / "b.toml"
    write_baseline(p, [_f("a.py", "r", "m", line=3)])
    new, old = split_new([_f("a.py", "r", "m", line=99)], load_baseline(p))
    assert new == [] and len(old) == 1


def test_split_new_partition(tmp_path):
    p = tmp_path / "b.toml"
    known = _f("a.py", "r", "known")
    write_baseline(p, [known])
    fresh = _f("a.py", "r", "fresh")
    new, old = split_new([known, fresh], load_baseline(p))
    assert new == [fresh] and old == [known]


def test_missing_baseline_is_strict(tmp_path):
    assert load_baseline(tmp_path / "nope.toml") == set()


def test_duplicate_entries_deduped(tmp_path):
    p = tmp_path / "b.toml"
    write_baseline(p, [_f("a.py", "r", "m"), _f("a.py", "r", "m", line=9)])
    assert p.read_text().count("[[finding]]") == 1
