"""graftlint deep tier: each jaxpr pass must CATCH a seeded violation
(break-and-detect — an analyzer that cannot fail is not analyzing) and
stay silent on the sanctioned twin, on SYNTHETIC traced entries so the
violations are precise and the tests stay fast. The clean-on-repo
enforcement run rides test_selflint/CI; the donation AST side has its own
fixture pair (fixtures/deep_{good,bad}_use_after_donate.py).
"""

import functools
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from tpu_gossip.analysis.deep.donation import (
    donation_ast_findings,
    donation_jaxpr_findings,
)
from tpu_gossip.analysis.deep.lineage import lineage_findings
from tpu_gossip.analysis.deep.reductions import reduction_findings
from tpu_gossip.analysis.entrypoints import EntryPoint, TracedEntry
from tpu_gossip.analysis.walker import ModuleInfo
from tpu_gossip.core.streams import FAULT_STREAM_SALT, GROWTH_STREAM_SALT
from tpu_gossip.dist._compat import shard_map_compat
from tpu_gossip.dist.mesh import AXIS, make_mesh

FIXTURES = Path(__file__).parent / "fixtures"


def _trace(fn, *args, engine="xla", jit_name=None, name="synthetic"):
    """One synthetic TracedEntry, shaped like trace_matrix's output."""
    ep = EntryPoint(
        name=name, engine=engine, kind="round", audit_check="synthetic",
        build=lambda: (fn, args[0]), jit_name=jit_name,
    )
    te = TracedEntry(ep=ep)
    te.state = args[0]
    te.jaxpr, te.out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    return {name: te}


# ------------------------------------------------------- deep-rng-lineage
def test_lineage_clean_on_registered_streams():
    def good(key):
        kf = jax.random.fold_in(key, FAULT_STREAM_SALT)
        kg = jax.random.fold_in(key, GROWTH_STREAM_SALT)
        k1, k2 = jax.random.split(key)
        return (
            jax.random.uniform(kf, (4,))
            + jax.random.uniform(kg, (4,))
            + jax.random.uniform(k1, (4,))
            + jax.random.uniform(k2, (4,))
        )

    assert lineage_findings(_trace(good, jax.random.key(0))) == []


def test_unregistered_salt_detected():
    def bad(key):
        k = jax.random.fold_in(key, 0x7777AAAA)  # nobody registered this
        return jax.random.uniform(k, (4,))

    fs = lineage_findings(_trace(bad, jax.random.key(0)))
    assert fs, "unregistered constant salt not flagged"
    assert any("not registered" in f.message for f in fs)
    assert all(f.rule == "deep-rng-lineage" for f in fs)


def test_key_reuse_detected():
    def bad(key):
        return jax.random.uniform(key, (4,)) + jax.random.normal(key, (4,))

    fs = lineage_findings(_trace(bad, jax.random.key(0)))
    assert any("consumed by 2 draws" in f.message for f in fs)


def test_salt_collision_detected():
    def bad(key):
        ka = jax.random.fold_in(key, FAULT_STREAM_SALT)
        kb = jax.random.fold_in(key, FAULT_STREAM_SALT)  # same stream twice
        return jax.random.uniform(ka, (4,)) + jax.random.uniform(kb, (4,))

    fs = lineage_findings(_trace(bad, jax.random.key(0)))
    assert any("folded from the same parent" in f.message for f in fs)


def test_minted_root_key_detected():
    def bad(x):
        k = jax.random.key(7)  # replays the same bits every round
        return x + jax.random.uniform(k, x.shape)

    fs = lineage_findings(_trace(bad, jnp.ones(4)))
    assert any("minted inside" in f.message for f in fs)


def test_constant_key_detected():
    baked = jax.random.key(3)

    def bad(x):
        return x + jax.random.uniform(baked, x.shape)  # closure constant

    fs = lineage_findings(_trace(bad, jnp.ones(4)))
    assert any("does not derive" in f.message for f in fs)


def test_draw_inside_shard_map_detected_and_licensable():
    mesh = make_mesh()

    def bad(key, x):
        def body(kb, xb):
            return xb + jax.random.uniform(kb[0], xb.shape)  # per-shard bits

        return shard_map_compat(
            body, mesh=mesh, in_specs=(P(), P(AXIS)), out_specs=P(AXIS),
        )(key[None], x)

    traced = _trace(bad, jax.random.key(0), jnp.ones(8), name="sm-draw")
    fs = lineage_findings(traced)
    hits = [f for f in fs if "inside a shard_map body" in f.message]
    assert hits, "per-shard draw inside shard_map not flagged"
    # the allowlist licenses EXACTLY that source site (the bucketed
    # engine's documented in-shard draw uses this), and only the
    # in-shard-map check — same semantics as the reduction allowlist
    lic = {(h.file, h.qualname): "test license" for h in hits}
    fs2 = lineage_findings(traced, allowlist=lic)
    assert not any("inside a shard_map body" in f.message for f in fs2)


def test_loop_invariant_key_draw_detected():
    """A key captured as a scan/while CONST is the same value every
    iteration — a draw off it inside the body replays identical bits per
    round even though the body traces once (the hoisted-key bug the
    per-round split discipline exists to prevent)."""
    def bad(key, xs):
        k = jax.random.fold_in(key, FAULT_STREAM_SALT)  # loop-invariant

        def body(c, x):
            return c + x * jax.random.uniform(k, x.shape), None

        out, _ = jax.lax.scan(body, jnp.zeros(4), xs)
        return out

    fs = lineage_findings(_trace(bad, jax.random.key(0), jnp.ones((3, 4))))
    assert any("loop-invariant key" in f.message for f in fs)


def test_loop_carried_split_key_is_clean():
    """The sanctioned twin: the key rides the carry and splits per
    iteration — fresh bits every round, no finding."""
    def good(key, xs):
        def body(carry, x):
            k, acc = carry
            k, kd = jax.random.split(k)
            return (k, acc + x * jax.random.uniform(kd, x.shape)), None

        (_, out), _ = jax.lax.scan(body, (key, jnp.zeros(4)), xs)
        return out

    assert lineage_findings(
        _trace(good, jax.random.key(0), jnp.ones((3, 4)))
    ) == []


def test_loop_invariant_key_with_iteration_fold_is_clean():
    """fold_in(k, i) with the traced iteration index derives a distinct
    child per iteration — the other sanctioned spelling."""
    def good(key, xs):
        k = jax.random.fold_in(key, FAULT_STREAM_SALT)

        def body(c, xi):
            x, i = xi
            kd = jax.random.fold_in(k, i)
            return c + x * jax.random.uniform(kd, x.shape), None

        out, _ = jax.lax.scan(
            body, jnp.zeros(4), (xs, jnp.arange(xs.shape[0]))
        )
        return out

    assert lineage_findings(
        _trace(good, jax.random.key(0), jnp.ones((3, 4)))
    ) == []


def test_draws_in_exclusive_cond_branches_are_not_reuse():
    """lax.cond branches are mutually exclusive at runtime — one executes
    per round — so each branch drawing off the same parent key is NOT
    reuse (the repo's runtime-gated stages pattern: has_loss_delay, the
    sparse-transport fallback); reuse WITHIN one branch still is."""
    def good(key, pred):
        return jax.lax.cond(
            pred,
            lambda k: jax.random.uniform(k, (4,)),
            lambda k: jax.random.normal(k, (4,)),
            key,
        )

    fs = lineage_findings(
        _trace(good, jax.random.key(0), jnp.bool_(True))
    )
    assert not any("consumed by" in f.message for f in fs), [
        f.render() for f in fs
    ]

    def bad(key, pred):
        def arm(k):
            return jax.random.uniform(k, (4,)) + jax.random.normal(k, (4,))

        return jax.lax.cond(
            pred, arm, lambda k: jax.random.uniform(k, (4,)), key
        )

    fs = lineage_findings(_trace(bad, jax.random.key(0), jnp.bool_(True)))
    assert any("consumed by 2 draws" in f.message for f in fs)


def test_same_salt_in_exclusive_cond_branches_not_collision():
    def good(key, pred):
        def arm(k):
            return jax.random.uniform(
                jax.random.fold_in(k, FAULT_STREAM_SALT), (4,)
            )

        return jax.lax.cond(pred, arm, arm, key)

    fs = lineage_findings(
        _trace(good, jax.random.key(0), jnp.bool_(True))
    )
    assert not any("folded from the same parent" in f.message for f in fs), [
        f.render() for f in fs
    ]


def test_split_children_are_distinct_not_reused():
    def good(key):
        keys = jax.random.split(key, 3)
        return (
            jax.random.uniform(keys[0], (2,))
            + jax.random.uniform(keys[1], (2,))
            + jax.random.uniform(keys[2], (2,))
        )

    fs = lineage_findings(_trace(good, jax.random.key(0)))
    assert not any("consumed by" in f.message for f in fs), [
        f.render() for f in fs
    ]


# --------------------------------------------------- deep-float-reduction
def test_float_psum_detected_int_psum_clean():
    mesh = make_mesh()

    def collective(x):
        return shard_map_compat(
            lambda b: jax.lax.psum(b, AXIS),
            mesh=mesh, in_specs=(P(AXIS),), out_specs=P(),
        )(x)

    fs = reduction_findings(_trace(collective, jnp.ones(8, jnp.float32)))
    assert any("float collective" in f.message for f in fs)
    assert all(f.rule == "deep-float-reduction" for f in fs)
    # integer bracketing is exact under any order: never flagged
    assert reduction_findings(
        _trace(collective, jnp.ones(8, jnp.int32))
    ) == []


def test_float_pmax_is_order_exact_and_clean():
    """max/min are associative and commutative EXACTLY — their bracketing
    cannot depend on layout — so float pmax/pmin are never flagged (the
    docstring's order-exact carve-out; only psum-family collectives are
    layout-dependent)."""
    mesh = make_mesh()

    def collective(x):
        return shard_map_compat(
            lambda b: jax.lax.pmax(b, AXIS),
            mesh=mesh, in_specs=(P(AXIS),), out_specs=P(),
        )(x)

    fs = reduction_findings(_trace(collective, jnp.ones(8, jnp.float32)))
    assert fs == [], [f.render() for f in fs]


def test_global_float_reduce_flagged_only_for_dist_entries():
    def f(x):
        return jnp.sum(x)

    x = jnp.ones(8, jnp.float32)
    # a dist entry's global-shape float sum is an implicit psum under SPMD
    fs = reduction_findings(_trace(f, x, engine="dist-matching"))
    assert any("implicit psum" in f.message for f in fs)
    # the same reduction in a LOCAL entry has one device order: clean
    assert reduction_findings(_trace(f, x, engine="xla")) == []


def test_reduction_allowlist_licenses_by_source_site():
    mesh = make_mesh()

    def collective(x):
        return shard_map_compat(
            lambda b: jax.lax.psum(b, AXIS),
            mesh=mesh, in_specs=(P(AXIS),), out_specs=P(),
        )(x)

    traced = _trace(collective, jnp.ones(8, jnp.float32))
    fs = reduction_findings(traced)
    assert fs
    lic = {(f.file, f.qualname): "test license" for f in fs}
    assert reduction_findings(traced, allowlist=lic) == []


def test_dead_allowlist_entry_detected(monkeypatch):
    """A license that stops matching any traced site must itself become a
    finding (on matrices that trace dist entries) — stale documentation
    accumulating in the allowlists is the suppression-debt failure mode
    the empty-baseline policy exists to prevent."""
    from tpu_gossip.analysis.deep import lineage, reductions

    monkeypatch.setitem(
        reductions.REDUCTION_ALLOWLIST, ("gone.py", "nope"), "stale",
    )
    monkeypatch.setitem(
        lineage.LINEAGE_ALLOWLIST, ("gone.py", "nope"), "stale",
    )
    traced = _trace(lambda x: x + 1, jnp.ones(4), engine="dist-matching")
    assert any(
        "dead license" in f.message for f in reduction_findings(traced)
    )
    assert any(
        "dead license" in f.message for f in lineage_findings(traced)
    )
    # a local-only matrix cannot anchor the dist licenses: no dead-entry
    # reporting there (single-device hosts must not cry wolf)
    local = _trace(lambda x: x + 1, jnp.ones(4), engine="xla")
    assert reduction_findings(local) == []
    assert lineage_findings(local) == []


# -------------------------------------------------- deep-use-after-donate
def test_undonated_jit_entry_detected():
    @jax.jit
    def loop(state):  # the forgotten-donation refactor
        return state * 2.0

    fs = donation_jaxpr_findings(
        _trace(lambda s: loop(s), jnp.ones(4), jit_name="loop")
    )
    assert fs and any("NOT donated" in f.message for f in fs)
    assert all(f.rule == "deep-use-after-donate" for f in fs)


def test_donating_jit_entry_clean():
    @functools.partial(jax.jit, donate_argnums=(0,))
    def loop(state):
        return state * 2.0

    fs = donation_jaxpr_findings(
        _trace(lambda s: loop(s), jnp.ones(4), jit_name="loop")
    )
    assert fs == [], [f.render() for f in fs]


def test_missing_jit_call_detected():
    fs = donation_jaxpr_findings(
        _trace(lambda s: s + 1.0, jnp.ones(4), jit_name="loop")
    )
    assert fs and "did not trace as a jit call" in fs[0].message


def _ast_findings(fixture: str):
    mod = ModuleInfo(FIXTURES / fixture, fixture)
    return donation_ast_findings([mod])


def test_ast_read_after_donate_fixture_flagged():
    fs = _ast_findings("deep_bad_use_after_donate.py")
    assert {f.rule for f in fs} == {"deep-use-after-donate"}
    # every bad function flagged: straight-line, fall-through branch,
    # error path, loop cross-iteration (the read AND the re-donation of
    # the deleted name — both reads of deleted buffers), keyword form
    assert {f.qualname for f in fs} == {
        "straight_line_read", "branch_falls_through", "read_in_error_path",
        "loop_cross_iteration", "keyword_form",
    }, [f.render() for f in fs]
    assert len(fs) == 6 and len({f.line for f in fs}) == 6


def test_ast_donation_idioms_clean():
    fs = _ast_findings("deep_good_use_after_donate.py")
    assert fs == [], [f.render() for f in fs]


def test_ast_pass_covers_the_real_scope():
    """The live callers (cli/run_sim.py, bench.py, sim/, dist/) are clean
    against the real donating entry points — the enforcement half of the
    pass, with tracing off (pure AST)."""
    from tpu_gossip.analysis.deep import run_deep

    fs = run_deep(trace=False)
    assert fs == [], [f.render() for f in fs]


def test_loop_body_read_reported_once(tmp_path):
    """The two-pass loop scan re-checks reads on pass 2 (the
    cross-iteration trick) — a read that fires on BOTH passes must
    surface as ONE finding, not two identical ones. `print(state)` is
    flagged on pass 1 (same-iteration read) and again on pass 2; the
    re-donating `step(state)` call is itself a read of deleted buffers
    on pass 2 (the fixture's loop_cross_iteration contract)."""
    src = (
        "import functools\n\n"
        "import jax\n\n\n"
        "@functools.partial(jax.jit, donate_argnames=('state',))\n"
        "def step(state):\n"
        "    return state\n\n\n"
        "def f(state, n):\n"
        "    for _ in range(n):\n"
        "        step(state)\n"
        "        print(state)\n"
    )
    p = tmp_path / "loop_donate.py"
    p.write_text(src)
    fs = donation_ast_findings([ModuleInfo(p, "loop_donate.py")])
    assert sorted(f.line for f in fs) == [13, 14], [f.render() for f in fs]


def test_pragma_suppresses_ast_side(tmp_path):
    src = (
        "import functools\n\n"
        "import jax\n\n\n"
        "@functools.partial(jax.jit, donate_argnames=('state',))\n"
        "def step(state):\n"
        "    return state\n\n\n"
        "def f(state):\n"
        "    out = step(state)\n"
        "    # graftlint: disable=deep-use-after-donate -- fixture: test\n"
        "    return out, state\n"
    )
    p = tmp_path / "pragma_donate.py"
    p.write_text(src)
    fs = donation_ast_findings([ModuleInfo(p, "pragma_donate.py")])
    assert fs == [], [f.render() for f in fs]


# ------------------------------------------------------- the full tier
@pytest.mark.slow
def test_run_deep_clean_on_repo():
    """The whole tier on the real tree: 0 findings (CI runs this same
    budgeted invocation as the lint-deep job; slow-marked so the tier-1
    loop doesn't pay the matrix trace twice)."""
    from tpu_gossip.analysis.deep import run_deep

    fs = run_deep(cache={})
    assert fs == [], [f.render() for f in fs]
