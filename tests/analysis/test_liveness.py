"""deep-transient-liveness (analysis/deep/liveness).

Pins (a) the attribution sweep is the GRAFTMEM sweep: peak bytes equal
entry_ledger's exactly (acceptance asks within 5%; identity is the
stronger pin) for the packed entries; (b) the packed-NATIVE round
killed the unpack spike: no packed local entry attributes its peak to
the codec's ``unpack_bits`` any more (the hot stages compute on the
words; full width survives only at the ops that genuinely need it,
like the round_tail int16 latch), and the packed LOOP entries' peak
live stays within a sliver of the packed resident; (c) the codec rail:
the real packed entries are clean, the deliberate out-of-codec decode
fixture fires, the sanctioned word-kernel fixture does NOT, and
structural ops alone never fire.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu_gossip.analysis.deep.liveness import (
    RULE,
    codec_findings,
    entry_liveness,
    liveness_findings,
)
from tpu_gossip.analysis.deep.selftest import unpack_spike_entry
from tpu_gossip.analysis.entrypoints import entry_points, trace_matrix
from tpu_gossip.analysis.mem.ledger import entry_ledger
from tpu_gossip.core.packed import pack_bits

EPS = {ep.name: ep for ep in entry_points()}
PACKED_LOCAL = [
    n for n, ep in EPS.items()
    if getattr(ep, "packed", False) and n.startswith("local[")
]


# one process-wide trace cache: every test reads the same TracedEntry
# instead of re-paying make_jaxpr (tier-1 wall budget)
from tests.analysis._tracecache import CACHE as _CACHE


def _traced(name):
    return trace_matrix([EPS[name]], cache=_CACHE)[name]


def test_matrix_declares_packed_entries():
    assert PACKED_LOCAL, "no packed local entries in the matrix"


@pytest.mark.parametrize("name", sorted(PACKED_LOCAL))
def test_peak_equals_ledger_and_the_codec_is_off_the_top(name):
    """One sweep, two reports: the liveness peak IS the ledger peak
    (same `_analyze`, different labeler) — and the unpack spike is GONE:
    with the packed-native round, no packed local entry's top
    attribution is the core/packed.py ``unpack_bits`` decode any more."""
    te = _traced(name)
    live = entry_liveness(name, te)
    ledger = entry_ledger(name, te)
    assert live is not None and ledger is not None
    assert live["peak_bytes"] == ledger.peak_bytes
    # acceptance phrasing: within 5% of graftmem's number
    assert abs(live["peak_bytes"] - ledger.peak_bytes) <= (
        0.05 * ledger.peak_bytes
    )
    top_label = live["top"][0][0]
    assert "unpack_bits" not in top_label, live["top"]


@pytest.mark.parametrize(
    "name", sorted(n for n in PACKED_LOCAL if "simulate" in n
                   or "run_until_coverage" in n)
)
def test_packed_loop_peak_hugs_the_resident(name):
    """The acceptance shape at the loop level: a packed loop's peak
    live is the packed RESIDENT (the scan/while carry), not a
    full-width round trip — well under the 1.5x acceptance ceiling."""
    te = _traced(name)
    live = entry_liveness(name, te)
    ledger = entry_ledger(name, te)
    assert live["peak_bytes"] <= 1.5 * ledger.state_bytes, (
        live["peak_bytes"], ledger.state_bytes, live["top"],
    )


def test_packed_native_round_tops_in_the_kernel_tier():
    """The packed-native round's residual transient belongs to the
    sanctioned full-width ops (the round_tail int16 latch), not the
    codec round trip."""
    name = "local[xla,round,packed-native]"
    live = entry_liveness(name, _traced(name))
    top_label = live["top"][0][0]
    assert "tpu_gossip/kernels/" in top_label, live["top"]


def test_labels_are_file_lines_not_prims():
    """The point of the pass: intermediates attribute to repo source
    lines, not `intermediate:<prim>` buckets."""
    name = PACKED_LOCAL[0]
    live = entry_liveness(name, _traced(name))
    assert not any(
        lbl.startswith("intermediate:") for lbl, _ in live["top"]
    ), live["top"]


# ------------------------------------------------------------- codec rail
def test_real_packed_entries_are_clean():
    packed = [ep for ep in entry_points() if getattr(ep, "packed", False)]
    traced = trace_matrix(packed, cache=_CACHE)
    findings = liveness_findings(traced)
    assert findings == [], [f.render() for f in findings]


def test_out_of_codec_decode_fires():
    name, te = unpack_spike_entry()
    findings = codec_findings(name, te)
    assert any(
        f.rule == RULE and f.file.endswith("selftest.py") for f in findings
    ), [f.render() for f in findings]
    # the finding names a real decode primitive with its output shape
    assert any("shift" in f.message or "and" in f.message for f in findings)


def test_structural_moves_do_not_fire():
    """Reshaping/slicing packed words (routing them around) is not a
    decode — only COMPUTING on their bits outside the codec is."""
    words = pack_bits((jnp.arange(32 * 16) % 3 == 0).reshape(32, 16))

    def mover(state):
        w = state["seen"]
        return jnp.transpose(w)[:1].reshape(-1)

    name, te = "synthetic[mover]", None
    from tpu_gossip.analysis.entrypoints import EntryPoint, TracedEntry

    ep = EntryPoint(
        name=name, engine="synthetic", kind="round",
        audit_check="synthetic", build=lambda: (mover, {"seen": words}),
        n_peers=32, packed=True,
    )
    te = TracedEntry(ep=ep, state={"seen": words})
    te.jaxpr, te.out_shape = jax.make_jaxpr(mover, return_shape=True)(
        {"seen": words}
    )
    assert codec_findings(name, te) == []
