"""deep-transient-liveness (analysis/deep/liveness).

Pins (a) the attribution sweep is the GRAFTMEM sweep: peak bytes equal
entry_ledger's exactly (acceptance asks within 5%; identity is the
stronger pin) for the packed entries; (b) the attribution names the
core/packed.py codec (unpack_bits) as the packed entries' peak-live
driver — ROADMAP's "unpack spike" as a file:line; (c) the codec rail:
the real packed entries are clean, the deliberate out-of-codec decode
fixture fires, and structural ops alone never fire.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu_gossip.analysis.deep.liveness import (
    RULE,
    codec_findings,
    entry_liveness,
    liveness_findings,
)
from tpu_gossip.analysis.deep.selftest import unpack_spike_entry
from tpu_gossip.analysis.entrypoints import entry_points, trace_matrix
from tpu_gossip.analysis.mem.ledger import entry_ledger
from tpu_gossip.core.packed import pack_bits

EPS = {ep.name: ep for ep in entry_points()}
PACKED_LOCAL = [
    n for n, ep in EPS.items()
    if getattr(ep, "packed", False) and n.startswith("local[")
]


# one process-wide trace cache: every test reads the same TracedEntry
# instead of re-paying make_jaxpr (tier-1 wall budget)
from tests.analysis._tracecache import CACHE as _CACHE


def _traced(name):
    return trace_matrix([EPS[name]], cache=_CACHE)[name]


def test_matrix_declares_packed_entries():
    assert PACKED_LOCAL, "no packed local entries in the matrix"


@pytest.mark.parametrize("name", sorted(PACKED_LOCAL))
def test_peak_equals_ledger_and_names_the_codec(name):
    """One sweep, two reports: the liveness peak IS the ledger peak
    (same `_analyze`, different labeler), and the top attribution for a
    packed local entry is the core/packed.py decode line — the unpack
    spike, named."""
    te = _traced(name)
    live = entry_liveness(name, te)
    ledger = entry_ledger(name, te)
    assert live is not None and ledger is not None
    assert live["peak_bytes"] == ledger.peak_bytes
    # acceptance phrasing: within 5% of graftmem's number
    assert abs(live["peak_bytes"] - ledger.peak_bytes) <= (
        0.05 * ledger.peak_bytes
    )
    top_label = live["top"][0][0]
    assert "tpu_gossip/core/packed.py" in top_label, live["top"]
    assert "unpack_bits" in top_label, live["top"]


def test_labels_are_file_lines_not_prims():
    """The point of the pass: intermediates attribute to repo source
    lines, not `intermediate:<prim>` buckets."""
    name = PACKED_LOCAL[0]
    live = entry_liveness(name, _traced(name))
    assert not any(
        lbl.startswith("intermediate:") for lbl, _ in live["top"]
    ), live["top"]


# ------------------------------------------------------------- codec rail
def test_real_packed_entries_are_clean():
    packed = [ep for ep in entry_points() if getattr(ep, "packed", False)]
    traced = trace_matrix(packed, cache=_CACHE)
    findings = liveness_findings(traced)
    assert findings == [], [f.render() for f in findings]


def test_out_of_codec_decode_fires():
    name, te = unpack_spike_entry()
    findings = codec_findings(name, te)
    assert any(
        f.rule == RULE and f.file.endswith("selftest.py") for f in findings
    ), [f.render() for f in findings]
    # the finding names a real decode primitive with its output shape
    assert any("shift" in f.message or "and" in f.message for f in findings)


def test_structural_moves_do_not_fire():
    """Reshaping/slicing packed words (routing them around) is not a
    decode — only COMPUTING on their bits outside the codec is."""
    words = pack_bits((jnp.arange(32 * 16) % 3 == 0).reshape(32, 16))

    def mover(state):
        w = state["seen"]
        return jnp.transpose(w)[:1].reshape(-1)

    name, te = "synthetic[mover]", None
    from tpu_gossip.analysis.entrypoints import EntryPoint, TracedEntry

    ep = EntryPoint(
        name=name, engine="synthetic", kind="round",
        audit_check="synthetic", build=lambda: (mover, {"seen": words}),
        n_peers=32, packed=True,
    )
    te = TracedEntry(ep=ep, state={"seen": words})
    te.jaxpr, te.out_shape = jax.make_jaxpr(mover, return_shape=True)(
        {"seen": words}
    )
    assert codec_findings(name, te) == []
