"""eval_shape contract audit: clean on the real tree, and a deliberately
broken contract is DETECTED (the audit's own regression guard — an audit
that cannot fail is not auditing)."""

import pytest

from tpu_gossip.analysis.contracts import AUDIT_CHECKS, audit_contracts


@pytest.mark.slow  # CI's lint job runs the full audit on every push;
# tier-1 keeps the break-and-detect contracts below as the audit's guard
def test_audit_clean_on_repo():
    findings = audit_contracts()
    assert findings == [], "\n".join(f.message for f in findings)


def test_audit_names_cover_declared_entry_points():
    assert set(AUDIT_CHECKS) == {
        "builder_csr",
        "builder_sharded",
        "gossip_round_local",
        "growth_registry_plane",
        "simulate_and_coverage",
        "pallas_wrappers",
        "gossip_round_dist",
        "sparse_transport",
    }


def test_broken_stats_dtype_detected(monkeypatch):
    """Drift RoundStats.msgs_sent to float32: every grid point must report
    the dtype contract violation (checks resolve entry points through the
    module at call time precisely so this test can exist)."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        st, stats = orig(state, cfg, plan, **kw)
        return st, stats._replace(msgs_sent=stats.msgs_sent.astype("float32"))

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate dtype break"
    assert all("msgs_sent" in f.message for f in findings)
    assert all(f.rule == "contract-audit" for f in findings)


def test_broken_state_shape_detected(monkeypatch):
    """Drop a peer row from the output state: the fixed-point contract
    (out specs == in specs) must catch it — on the packed-native entry
    too, whose row mask lives in the shared flags word."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        import dataclasses

        st, stats = orig(state, cfg, plan, **kw)
        plane = "alive" if hasattr(st, "alive") else "flags"
        return dataclasses.replace(
            st, **{plane: getattr(st, plane)[:-1]}), stats

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings and all("spec drift" in f.message for f in findings)


def test_broken_growth_registry_detected(monkeypatch):
    """Re-type a registry-plane leaf under an active growth schedule: the
    growing round's fixed-point check must report it — the growth plane
    is pinned the way fault_held is."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        import dataclasses

        st, stats = orig(state, cfg, plan, **kw)
        if kw.get("growth") is not None:
            st = dataclasses.replace(
                st, degree_credit=st.degree_credit.astype("int16")
            )
        return st, stats

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate registry-plane break"
    assert all("growth" in f.message for f in findings)


def test_broken_stream_lease_detected(monkeypatch):
    """Re-type the slot-lease table under an active stream: the loaded
    round's fixed-point check must report it — the streaming plane's
    state field is pinned the way fault_held and the growth registry are
    (a drifted lease could never ride a scan carry or a checkpoint)."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        import dataclasses

        st, stats = orig(state, cfg, plan, **kw)
        if kw.get("stream") is not None:
            # int32 is a WIDENING drift now that the plane's declared
            # dtype is int16 (core.state.PLANES)
            st = dataclasses.replace(
                st, slot_lease=st.slot_lease.astype("int32")
            )
        return st, stats

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate slot-lease break"
    # the served-round entries mount the same active stream, so the
    # break surfaces under their names too
    assert all("stream" in f.message or "ingest" in f.message
               for f in findings)


def test_broken_stream_stats_detected(monkeypatch):
    """Flatten the per-slot observability vector to a scalar: the stats
    contract declares slot_infected/slot_age as (M,) int32 — the
    steady-state report reconstructs per-message latency from them, so a
    silent shape drift would corrupt every serving metric."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        st, stats = orig(state, cfg, plan, **kw)
        return st, stats._replace(slot_age=stats.slot_age.sum())

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate slot_age shape break"
    assert all("slot_age" in f.message for f in findings)


def test_broken_control_stats_detected(monkeypatch):
    """Re-type a controller stat: the stats contract declares
    control_level/control_fanout/msgs_duplicate/control_refreshed as
    scalar int32 — the reliability report and the AIMD observability
    read them, so a silent dtype drift would corrupt the control
    track."""
    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        st, stats = orig(state, cfg, plan, **kw)
        return st, stats._replace(
            control_fanout=stats.control_fanout.astype("float32")
        )

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate control_fanout dtype break"
    assert all("control_fanout" in f.message for f in findings)


def test_broken_control_cursor_detected(monkeypatch):
    """Re-type the control cursor only on CONTROLLED rounds: the state
    fixed point must pin control_lvl through the controlled entries the
    matrix traces (the cursor rides scan carries and checkpoints)."""
    import dataclasses

    from tpu_gossip.sim import engine

    orig = engine.gossip_round

    def broken(state, cfg, plan=None, **kw):
        st, stats = orig(state, cfg, plan, **kw)
        if kw.get("control") is not None:
            st = dataclasses.replace(
                st, control_lvl=st.control_lvl.astype("int16")
            )
        return st, stats

    monkeypatch.setattr(engine, "gossip_round", broken)
    findings = audit_contracts(names=["gossip_round_local"])
    assert findings, "audit missed a deliberate control-cursor break"
    assert all("control" in f.message for f in findings)


def test_broken_occupancy_header_detected(monkeypatch):
    """Drift the occupancy header to float32: the sparse-transport check
    must report it against the declared header_spec (both the runtime
    gate and the analytic counter read this row — a silent drift would
    desynchronize the lanes)."""
    from tpu_gossip.dist import transport as tp

    orig = tp.occupancy_counts

    def broken(occ):
        return orig(occ).astype("float32")

    monkeypatch.setattr(tp, "occupancy_counts", broken)
    findings = audit_contracts(names=["sparse_transport"])
    assert findings, "audit missed a deliberate header dtype break"
    assert any("occupancy header" in f.message for f in findings)


def test_crashed_check_is_a_finding(monkeypatch):
    """A check that raises must surface as a finding (fail CI), not pass
    silently."""
    from tpu_gossip.analysis import contracts

    def boom():
        raise RuntimeError("synthetic check crash")

    monkeypatch.setitem(contracts.AUDIT_CHECKS, "boom", boom)
    findings = audit_contracts(names=["boom"])
    assert len(findings) == 1
    assert "check crashed" in findings[0].message


def test_distinct_problems_get_distinct_baseline_keys(monkeypatch):
    """One check covers ~40 matrix entries; baselining a problem on one
    entry must not suppress a future problem on a DIFFERENT entry — the
    qualname carries the sub-entry prefix, not just the check name."""
    from tpu_gossip.analysis import contracts

    def two_problems():
        return [
            "local[xla,push,m=1]: pytree structure changed: a != b",
            "local[staircase,push,m=1]: stats dtype drifted",
        ]

    monkeypatch.setitem(contracts.AUDIT_CHECKS, "fake", two_problems)
    findings = audit_contracts(names=["fake"])
    assert len(findings) == 2
    keys = {f.baseline_key for f in findings}
    assert len(keys) == 2, keys
    assert {f.qualname for f in findings} == {
        "fake.local[xla,push,m=1]",
        "fake.local[staircase,push,m=1]",
    }


@pytest.mark.parametrize("name", sorted(AUDIT_CHECKS))
def test_each_check_runs_standalone(name):
    findings = audit_contracts(names=[name])
    assert findings == [], "\n".join(f.message for f in findings)
