"""graftmem — the jaxpr memory tier (analysis/mem/).

Pins: (a) the liveness ledger's byte arithmetic on hand-computed
micro-jaxprs (chain, donation credit, scan-carry credit); (b) the plane
registry's coverage of SwarmState and its bytes/peer arithmetic;
(c) break-and-detect for every pass — a widened plane, a widening cast,
a hot-path clone, a dropped donation, and a skewed wire counter each
surface as a finding; (d) the budget file round-trip and its regression/
missing gates; (e) CLI exit codes and the identity-stable json ordering,
on a monkeypatched two-entry matrix so the tests stay fast.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_gossip.analysis.entrypoints import entry_points, trace_matrix
from tpu_gossip.analysis.mem.budget import (
    budget_findings,
    load_budget,
    write_budget,
)
from tpu_gossip.analysis.mem.ledger import (
    EntryLedger,
    _analyze,
    entry_ledger,
    ledger_findings,
)
from tpu_gossip.analysis.mem.widths import (
    plane_width_findings,
    widening_cast_findings,
)
from tpu_gossip.analysis.mem.wire import wire_findings

from tests.analysis._tracecache import CACHE as _CACHE

EPS = {ep.name: ep for ep in entry_points()}


def _traced(name):
    return trace_matrix([EPS[name]], cache=_CACHE)[name]


# ----------------------------------------------------------- micro ledger
def test_peak_of_straight_chain():
    """y = x + x; z = y * y: at each eqn exactly two (1024,) f32 buffers
    coexist — peak 8192 B."""

    def f(x):
        y = x + x
        return y * y

    closed = jax.make_jaxpr(f)(jnp.zeros((1024,), jnp.float32))
    peak, breakdown = _analyze(closed.jaxpr, {closed.jaxpr.invars[0]: "x"})
    assert peak == 8192, breakdown


def test_peak_counts_fanout_liveness():
    """x stays live across both uses: at the second eqn x, y, z coexist."""

    def f(x):
        y = x + 1.0
        z = x * 2.0
        return y, z

    closed = jax.make_jaxpr(f)(jnp.zeros((1024,), jnp.float32))
    peak, _ = _analyze(closed.jaxpr, {})
    assert peak == 3 * 4096


def test_donation_credit_collapses_pjit_footprint():
    """A donated pjit aliases its input: footprint 1x, not 2x."""
    g = jax.jit(lambda x: x + 1.0, donate_argnums=(0,))

    closed = jax.make_jaxpr(lambda x: g(x))(jnp.zeros((1024,), jnp.float32))
    [eqn] = closed.jaxpr.eqns
    assert eqn.primitive.name == "pjit" and any(
        eqn.params["donated_invars"]
    )
    peak, _ = _analyze(closed.jaxpr, {})
    assert peak == 4096  # in+out 8192 minus the 4096 donation credit

    h = jax.jit(lambda x: x + 1.0)  # undonated twin: the copy survives
    closed2 = jax.make_jaxpr(lambda x: h(x))(jnp.zeros((1024,), jnp.float32))
    peak2, _ = _analyze(closed2.jaxpr, {})
    assert peak2 == 8192


def test_scan_carry_credit():
    """A scan carry aliases in place: the loop costs one carry, not two."""

    def f(c):
        return jax.lax.scan(lambda c, _: (c + 1.0, None), c, None, length=3)[0]

    closed = jax.make_jaxpr(f)(jnp.zeros((1024,), jnp.float32))
    peak, _ = _analyze(closed.jaxpr, {})
    assert peak == 4096


# ---------------------------------------------------------- the registry
def test_registry_covers_swarm_state_exactly():
    from tpu_gossip.core.state import PLANES, SwarmState

    assert {p.name for p in PLANES} == {
        f.name for f in dataclasses.fields(SwarmState)
    }


def test_registry_bytes_per_peer_arithmetic():
    from tpu_gossip.core.state import state_bytes_per_peer, state_plane_bytes

    by_plane = state_plane_bytes(100, 16, rewire_slots=1)
    # hand sums at (N=100, M=16, S=1): five (N, M) bool planes, one
    # (N, M) int32, five (N,) bool, int32/int16 rows, scalars
    assert by_plane["seen"] == 100 * 16
    assert by_plane["infected_round"] == 100 * 16 * 2  # narrowed int16
    assert by_plane["last_hb"] == 100 * 2  # narrowed int16
    assert by_plane["join_round"] == 100 * 2  # the narrowed plane
    assert by_plane["slot_lease"] == 16 * 2
    assert by_plane["row_ptr"] == 101 * 4
    assert by_plane["rng"] == 8
    total = sum(by_plane.values())
    assert state_bytes_per_peer(100, 16) == total / 100


def test_narrowed_planes_materialize_declared_widths():
    te = _traced("local[xla,push,m=1]")
    assert str(te.state.join_round.dtype) == "int16"
    assert str(te.state.slot_lease.dtype) == "int16"
    assert str(te.state.infected_round.dtype) == "int16"
    assert str(te.state.last_hb.dtype) == "int16"


def test_entry_ledger_state_bytes_match_flattened_leaves():
    te = _traced("local[xla,push,m=1]")
    led = entry_ledger("local[xla,push,m=1]", te)
    want = sum(
        8 if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key)
        else leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(te.state)
    )
    assert led.state_bytes == want
    assert led.peak_bytes >= led.state_bytes  # the state is live at entry
    assert led.n_peers == EPS["local[xla,push,m=1]"].n_peers
    assert led.top and led.top[0][1] >= led.top[-1][1]


# ------------------------------------------------------- break-and-detect
def test_widened_plane_detected():
    """Re-widen join_round to int32 on a traced state: mem-plane-width."""
    te = _traced("local[xla,push,m=1]")
    doctored = dataclasses.replace(
        te, state=dataclasses.replace(
            te.state, join_round=te.state.join_round.astype(jnp.int32)
        )
    )
    findings = plane_width_findings({"x": doctored})
    assert any(
        f.rule == "mem-plane-width"
        and f.qualname == "SwarmState.join_round"
        and "WIDER" in f.message
        for f in findings
    ), [f.render() for f in findings]
    # the honest tree is width-clean
    assert not plane_width_findings({"x": te})


def test_widening_cast_detected():
    """An (N,)-scale int16->int32 cast inside a round body is a finding."""
    ep = EPS["local[xla,push,m=1]"]
    te = _traced("local[xla,push,m=1]")

    def widening(s):
        return jnp.sum(s.join_round.astype(jnp.int32) * 2)

    jaxpr = jax.make_jaxpr(widening)(te.state)
    doctored = dataclasses.replace(te, jaxpr=jaxpr)
    findings = widening_cast_findings({"x": doctored})
    assert any(
        f.rule == "mem-widening-cast" and "int16->int32" in f.message
        for f in findings
    ), [f.render() for f in findings]
    assert ep.n_peers > 0
    # the honest trace is cast-clean
    assert not widening_cast_findings({"x": te})


def test_hot_path_clone_detected():
    """clone_state traced inside the round: mem-hot-clone."""
    from tpu_gossip.core.state import clone_state
    from tpu_gossip.sim import engine

    name = "local[xla,push,m=1]"
    te = _traced(name)
    fn, st = EPS[name].build()
    jaxpr = jax.make_jaxpr(lambda s: fn(clone_state(s)))(st)
    doctored = dataclasses.replace(te, jaxpr=jaxpr)
    findings, _ = ledger_findings({name: doctored})
    assert any(f.rule == "mem-hot-clone" for f in findings), [
        f.render() for f in findings
    ]
    assert engine is not None
    findings_clean, _ = ledger_findings({name: te})
    assert not [f for f in findings_clean if f.rule == "mem-hot-clone"]


def test_dropped_donation_detected():
    """A jitted loop entry whose pjit stops donating re-materializes the
    state copy: mem-donation-residency."""
    name = "local[simulate]"
    te = _traced(name)
    # an undonated twin with the same pjit name: the call-site footprint
    # is state-in + state-out with no aliasing credit (the barrier keeps
    # the identity from being forwarded — a bare x would trace with no
    # pjit outvars at all)
    undonated = jax.jit(
        lambda state: jax.lax.optimization_barrier(state)
    )

    def fn(s):
        return undonated(s)

    jaxpr = jax.make_jaxpr(fn)(te.state)
    ep = dataclasses.replace(te.ep, jit_name="<lambda>")
    doctored = dataclasses.replace(te, ep=ep, jaxpr=jaxpr)
    findings, _ = ledger_findings({name: doctored})
    assert any(f.rule == "mem-donation-residency" for f in findings), [
        f.render() for f in findings
    ]
    # the honest donating entry is clean
    clean, _ = ledger_findings({name: te})
    assert not [f for f in clean if f.rule == "mem-donation-residency"]


def test_skewed_wire_counter_detected(monkeypatch):
    """Skew the bucketed engine's wire declaration: mem-wire-drift."""
    from tpu_gossip.dist import mesh as mesh_mod

    traced = trace_matrix([EPS["dist[bucketed]"]], cache=_CACHE)
    clean, report = wire_findings(traced)
    assert clean == [] and report["dist[bucketed]"]["traced_words"] == \
        report["dist[bucketed]"]["declared_words"]

    real = mesh_mod.dense_wire_words
    monkeypatch.setattr(
        mesh_mod, "dense_wire_words",
        lambda *a, **kw: real(*a, **kw) + 64,
    )
    findings, _ = wire_findings(traced)
    assert any(f.rule == "mem-wire-drift" for f in findings), [
        f.render() for f in findings
    ]


# ------------------------------------------------------------- the budget
def _tiny_ledgers():
    return {
        "a": EntryLedger(name="a", n_peers=100, state_bytes=1000,
                         const_bytes=50, peak_bytes=2000, top=[["x", 2000]]),
        "b": EntryLedger(name="b", n_peers=200, state_bytes=4000,
                         const_bytes=0, peak_bytes=6000, top=[["y", 6000]]),
    }


def test_budget_round_trip(tmp_path):
    path = tmp_path / "memory_budget.toml"
    ledgers = _tiny_ledgers()
    write_budget(path, ledgers)
    budget = load_budget(path)
    assert set(budget) == {"a", "b"}
    assert budget["a"]["peak_bytes"] == 2000
    assert budget["b"]["bytes_per_peer"] == 30.0
    findings, stale = budget_findings(ledgers, budget)
    assert findings == [] and stale == []


def test_budget_regression_and_missing(tmp_path):
    path = tmp_path / "memory_budget.toml"
    ledgers = _tiny_ledgers()
    write_budget(path, ledgers)
    budget = load_budget(path)
    # 10% growth > the 5% tolerance
    grown = dict(ledgers)
    grown["a"] = dataclasses.replace(ledgers["a"], peak_bytes=2200)
    findings, _ = budget_findings(grown, budget)
    assert any(f.rule == "mem-budget-regression" and f.qualname == "a"
               for f in findings), [f.render() for f in findings]
    # 4% stays inside tolerance
    ok = dict(ledgers)
    ok["a"] = dataclasses.replace(ledgers["a"], peak_bytes=2080)
    findings, _ = budget_findings(ok, budget)
    assert findings == []
    # an unbudgeted entry fails; a stale budget line only reports
    extra = dict(ledgers)
    extra["c"] = dataclasses.replace(ledgers["a"], name="c")
    findings, _ = budget_findings(extra, budget)
    assert any(f.rule == "mem-budget-missing" and f.qualname == "c"
               for f in findings)
    findings, stale = budget_findings({"a": ledgers["a"]}, budget)
    assert findings == [] and stale == ["b"]


def test_committed_budget_covers_current_matrix():
    """Every current matrix entry has a line in the committed budget (the
    gate CI runs; regenerating on a matrix edit is part of the PR)."""
    from tpu_gossip.analysis.cli import repo_root

    budget = load_budget(repo_root() / "memory_budget.toml")
    missing = [ep.name for ep in entry_points() if ep.name not in budget]
    assert missing == [], missing


# ------------------------------------------------------------------- CLI
@pytest.fixture
def tiny_matrix(monkeypatch):
    """Shrink the matrix to two local entries so CLI tests stay fast."""
    from tpu_gossip.analysis import entrypoints as ep_mod

    tiny = (EPS["local[xla,push,m=1]"], EPS["local[simulate]"])
    monkeypatch.setattr(ep_mod, "entry_points", lambda: tiny)
    return tiny


def test_cli_mem_budget_gate(tiny_matrix, tmp_path, capsys):
    from tpu_gossip.analysis.cli import main

    budget = tmp_path / "budget.toml"
    # price the tiny matrix, then gate against it: clean
    assert main(["--mem-only", "--write-budget", f"--budget={budget}"]) == 0
    capsys.readouterr()
    assert main(["--mem-only", f"--budget={budget}"]) == 0
    capsys.readouterr()
    # deflate one budget line 10%: the same tree now regresses -> exit 1
    text = budget.read_text()
    entries = load_budget(budget)
    peak = entries["local[simulate]"]["peak_bytes"]
    budget.write_text(text.replace(
        f"peak_bytes = {peak}", f"peak_bytes = {int(peak * 0.9)}", 1
    ))
    rc = main(["--mem-only", f"--budget={budget}", "--format=json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "mem-budget-regression" for f in data["new"])


def test_cli_mem_json_report_ordering(tiny_matrix, tmp_path, capsys):
    from tpu_gossip.analysis.cli import main

    budget = tmp_path / "budget.toml"
    assert main(["--mem-only", "--write-budget", f"--budget={budget}"]) == 0
    capsys.readouterr()
    rc = main(["--mem-only", f"--budget={budget}", "--format=json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["mem"] is True
    names = list(data["mem_report"]["entries"])
    assert names == sorted(names)
    entry = data["mem_report"]["entries"][names[0]]
    assert {"n_peers", "peak_bytes", "const_bytes", "bytes_per_peer",
            "state_bytes", "top"} <= set(entry)
    assert isinstance(data["mem_report"]["state_bytes_per_peer_1m"], float)
    assert data["mem_seconds"] is not None


def test_cli_mem_only_with_explicit_paths_is_a_usage_error(capsys):
    """--mem-only/--write-budget with explicit paths must refuse (exit 2),
    not exit 0 having analyzed nothing: the memory tier is trace-only."""
    from tpu_gossip.analysis.cli import main

    assert main(["--mem-only", "tpu_gossip/core/state.py"]) == 2
    capsys.readouterr()
    assert main(["--write-budget", "tpu_gossip/core/state.py"]) == 2
    capsys.readouterr()


def test_round_cap_saturates_narrow_plane_writes():
    """Past ROUND_CAP the round cursor saturates into the int16 planes
    (a late lease/join, never a wrap into the -1 sentinels)."""
    from tpu_gossip.core.state import ROUND_CAP
    from tpu_gossip.traffic import compile_stream
    from tpu_gossip.traffic.engine import apply_stream

    sp = compile_stream(
        rate=50.0, msg_slots=4, ttl=4, origin_rows=np.arange(4)
    )
    ones = jnp.ones((4,), bool)
    _, ir, lease, _ = apply_stream(
        sp, jax.random.key(0),
        jnp.asarray(ROUND_CAP + 100, jnp.int32), jnp.asarray(0, jnp.int32),
        seen=jnp.zeros((4, 4), bool),
        infected_round=jnp.full((4, 4), -1, jnp.int16),
        slot_lease=jnp.full((4,), -1, jnp.int16),
        row_ptr=jnp.zeros((5,), jnp.int32),
        col_idx=jnp.zeros((1,), jnp.int32),
        exists=ones, alive=ones, declared_dead=~ones,
    )
    lease = np.asarray(lease)
    assert (lease >= 0).any(), "rate 50 over 4 slots must land something"
    assert (lease[lease >= 0] == ROUND_CAP).all()
    ir = np.asarray(ir)
    assert str(ir.dtype) == "int16"
    assert (ir[ir >= 0] == ROUND_CAP).all(), "injection latch must saturate"

    from tpu_gossip.growth import compile_growth
    from tpu_gossip.growth.engine import apply_growth

    n = 8
    gp = compile_growth(n_initial=4, target=6, n_slots=n,
                        joins_per_round=2, attach_m=1)
    exists = jnp.arange(n) < 4
    out = apply_growth(
        gp, jax.random.key(0),
        jnp.asarray(ROUND_CAP + 100, jnp.int32),
        jnp.asarray(0, jnp.int32),
        row_ptr=jnp.asarray(np.arange(n + 1) * 2, jnp.int32),
        exists=exists, alive=exists, silent=jnp.zeros((n,), bool),
        last_hb=jnp.zeros((n,), jnp.int16), declared_dead=~exists,
        rewired=jnp.zeros((n,), bool),
        rewire_targets=jnp.full((n, 1), -1, jnp.int32),
        join_round=jnp.where(exists, 0, -1).astype(jnp.int16),
        admitted_by=jnp.full((n,), -1, jnp.int32),
        degree_credit=jnp.zeros((n,), jnp.int32),
    )
    jr = np.asarray(out["join_round"])
    joined = jr[np.asarray(out["exists"]) & ~np.asarray(exists)]
    assert joined.size and (joined == ROUND_CAP).all(), jr
    hb = np.asarray(out["last_hb"])
    assert str(hb.dtype) == "int16"
    admitted = hb[np.asarray(out["exists"]) & ~np.asarray(exists)]
    assert (admitted == ROUND_CAP).all(), "admission heartbeat must saturate"

    # the heartbeat refresh and the dedup latch saturate the same way
    from tpu_gossip.kernels.liveness import emit_heartbeats
    from tpu_gossip.kernels.round_tail import round_tail

    ones4 = jnp.ones((4,), bool)
    hb2 = emit_heartbeats(
        jnp.zeros((4,), jnp.int16), ones4, ~ones4, jnp.zeros((4,), bool),
        jnp.asarray(ROUND_CAP + 100, jnp.int32), 1,
    )
    assert str(hb2.dtype) == "int16" and (np.asarray(hb2) == ROUND_CAP).all()
    for impl in ("fused", "reference", "pallas"):
        _, _, ir2, _ = round_tail(
            jnp.zeros((4, 2), bool), jnp.zeros((4, 2), bool),
            jnp.full((4, 2), -1, jnp.int16), jnp.zeros((4, 2), bool),
            jnp.ones((4, 2), bool), jnp.ones((4, 2), bool),
            jnp.zeros((4, 2), bool), None,
            jnp.asarray(ROUND_CAP + 100, jnp.int32),
            forward_once=False, sir_recover_rounds=0, impl=impl,
        )
        ir2 = np.asarray(ir2)
        assert str(ir2.dtype) == "int16" and (ir2 == ROUND_CAP).all(), impl


def test_checkpoint_narrow_plane_round_trip(tmp_path):
    """A pre-narrowing checkpoint (int32 join_round/slot_lease) loads at
    the declared int16 widths with values intact — both formats."""
    from tpu_gossip.core.state import load_swarm, save_swarm

    te = _traced("local[xla,push,m=1]")
    st = te.state
    path = tmp_path / "ck.npz"
    save_swarm(path, st)
    data = dict(np.load(path))
    # forge the pre-narrowing format: re-widen the planes on disk
    for plane in ("join_round", "slot_lease", "infected_round", "last_hb"):
        data[f"field_{plane}"] = data[f"field_{plane}"].astype(np.int32)
    np.savez(path, **data)
    restored = load_swarm(path)
    for plane in ("join_round", "slot_lease", "infected_round", "last_hb"):
        assert str(getattr(restored, plane).dtype) == "int16", plane
        np.testing.assert_array_equal(
            np.asarray(getattr(restored, plane)),
            np.asarray(getattr(st, plane)),
        )
