"""Self-lint: the repo's own tree is clean modulo the committed baseline.

This is the enforcement test — a new violation anywhere in tpu_gossip/ or
bench.py fails HERE (and in CI via `python -m tpu_gossip.analysis`)
before it can land. Pragma hygiene is asserted alongside: every pragma in
the tree carries a reason.
"""

from tpu_gossip.analysis import lint_paths
from tpu_gossip.analysis.baseline import (
    DEFAULT_BASELINE, load_baseline, split_new,
)
from tpu_gossip.analysis.cli import _DEFAULT_SCOPE, repo_root


def test_repo_lints_clean_modulo_baseline():
    root = repo_root()
    findings = lint_paths(list(_DEFAULT_SCOPE), root=root)
    baseline = load_baseline(root / DEFAULT_BASELINE)
    new, _ = split_new(findings, baseline)
    assert new == [], "new lint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_baseline_is_empty():
    """The committed baseline carries NO suppressed debt: deliberate
    exceptions live as inline pragmas with reasons (ISSUE 2 satellite 1).
    If you are adding an entry here, prefer a pragma — or say why not in
    lint_baseline.toml."""
    root = repo_root()
    assert load_baseline(root / DEFAULT_BASELINE) == set()


def test_all_rules_registered():
    from tpu_gossip.analysis import RULES

    assert set(RULES) == {
        "key-linearity",
        "raw-shard-map",
        "trace-purity",
        "static-argnames-drift",
        "jit-state-donation",
    }
