"""CLI contract: exit codes, JSON output, baseline interplay — the exact
interface CI and bench.py depend on."""

import json
from pathlib import Path

import pytest

from tpu_gossip.analysis.cli import main, run_repo_lint

FIXTURES = Path(__file__).parent / "fixtures"


def test_bad_fixture_exits_nonzero(capsys):
    for bad in sorted(FIXTURES.glob("bad_*.py")):
        rc = main([str(bad)])
        out = capsys.readouterr()
        assert rc == 1, f"{bad.name} should fail: {out.out}\n{out.err}"


def test_good_fixtures_exit_zero(capsys):
    for good in sorted(FIXTURES.glob("good_*.py")):
        rc = main([str(good)])
        out = capsys.readouterr()
        assert rc == 0, f"{good.name} should pass: {out.out}"


def test_repo_ast_lint_clean(capsys):
    rc = main(["--no-contracts"])
    capsys.readouterr()
    assert rc == 0


def test_json_format(capsys):
    rc = main([str(FIXTURES / "bad_shard_map.py"), "--format=json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["clean"] is False
    assert data["new"], "json output must carry the findings"
    f = data["new"][0]
    assert {"file", "line", "col", "rule", "message", "hint"} <= set(f)
    assert "rules" in data and "elapsed_seconds" in data


def test_json_output_identity_sorted(capsys):
    """--format=json orders findings by (file, rule, qualname, message),
    NOT by line number — unrelated edits that shift lines must not churn
    diffs of the machine-readable output (same reason baseline keys drop
    line numbers)."""
    rc = main([
        str(FIXTURES / "bad_key_reuse.py"),
        str(FIXTURES / "bad_shard_map.py"),
        "--format=json",
    ])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    keys = [
        (f["file"], f["rule"], f.get("qualname", ""), f["message"])
        for f in data["new"]
    ]
    assert keys == sorted(keys), keys
    # findings from multiple rules/files present: the sort is exercised
    assert len({k[1] for k in keys}) >= 2


def test_deep_flag_on_explicit_paths_lints_ast_side(capsys):
    """--deep with explicit paths runs the AST-side donation pass (no
    tracing — fixture linting must not import the fixtures' runtime):
    the deep_bad fixture fails, the deep_good twin stays clean."""
    rc = main([str(FIXTURES / "deep_bad_use_after_donate.py"), "--deep"])
    out = capsys.readouterr()
    assert rc == 1, out.out
    assert "deep-use-after-donate" in out.out
    rc = main([str(FIXTURES / "deep_good_use_after_donate.py"), "--deep"])
    capsys.readouterr()
    assert rc == 0
    # without --deep the read-after-donate is invisible to the AST rules
    rc = main([str(FIXTURES / "deep_bad_use_after_donate.py")])
    capsys.readouterr()
    assert rc == 0


@pytest.mark.slow  # whole-tree AST walk just to accept a flag; CI's lint
# job passes --fail-on-new on every push
def test_fail_on_new_flag_accepted(capsys):
    rc = main(["--no-contracts", "--fail-on-new"])
    capsys.readouterr()
    assert rc == 0


def test_rules_subset(capsys):
    rc = main([str(FIXTURES / "bad_shard_map.py"), "--rules=key-linearity"])
    capsys.readouterr()
    assert rc == 0  # shard_map fixture is clean under the key rule alone
    rc = main([str(FIXTURES / "bad_shard_map.py"), "--rules=raw-shard-map"])
    capsys.readouterr()
    assert rc == 1


def test_unknown_rule_usage_error(capsys):
    rc = main(["--rules=no-such-rule"])
    capsys.readouterr()
    assert rc == 2


def test_write_and_respect_baseline(tmp_path, capsys):
    bad = str(FIXTURES / "bad_shard_map.py")
    bl = tmp_path / "baseline.toml"
    assert main([bad, "--write-baseline", f"--baseline={bl}"]) == 0
    capsys.readouterr()
    # baselined findings no longer fail...
    assert main([bad, f"--baseline={bl}"]) == 0
    capsys.readouterr()
    # ...but a different bad fixture still does
    assert main([str(FIXTURES / "bad_key_reuse.py"), f"--baseline={bl}"]) == 1
    capsys.readouterr()


@pytest.mark.slow  # whole-tree walk; the API shape is pinned here, the
# clean-tree claim is CI's lint job every push
def test_run_repo_lint_programmatic():
    out = run_repo_lint()
    assert out["clean"] is True, out["new"]
    assert out["new"] == []
    assert isinstance(out["baselined"], int)


def test_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out.split()
    assert "key-linearity" in out and "trace-purity" in out
