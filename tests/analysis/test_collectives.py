"""deep-collective-uniformity + collectives.lock (analysis/deep/collectives).

Pins (a) program extraction: every mesh entry in the committed lock,
non-empty, with per-axis ici/dcn byte columns; (b) the traced program of
a representative entry matches the lock byte-for-byte and drift/stale
split correctly; (c) uniformity semantics: a collective under a
shard-varying cond arm fires, identical-arms and uniform-pred conds
don't (the sparse transport's psum'd-header lanes depend on it); (d) the
rules_shardmap.py blind spot: a lambda-wrapped arm collective the AST
tier provably cannot see, caught by the trace walk; (e) the adversarial
self-test harness stays green (CI runs it via --deep-selftest).
"""

import importlib.util
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from tpu_gossip.analysis.cli import lint_paths, repo_root
from tpu_gossip.analysis.deep.collectives import (
    RULE,
    collective_report,
    entry_program,
    load_lock,
    lock_findings,
    program_summary,
    write_lock,
)
from tpu_gossip.analysis.deep.selftest import (
    divergent_collective_entry,
    run_selftest,
    unpack_spike_entry,
)
from tpu_gossip.analysis.entrypoints import (
    EntryPoint,
    TracedEntry,
    dist_guard,
    entry_points,
    trace_matrix,
)
from tpu_gossip.dist._compat import shard_map_compat
from tpu_gossip.dist.mesh import AXIS, make_mesh

FIXTURES = Path(__file__).parent / "fixtures"

EPS = {ep.name: ep for ep in entry_points()}
MESH_NAMES = sorted(n for n in EPS if n.startswith("dist["))

needs_mesh = pytest.mark.skipif(
    dist_guard() is not None, reason="needs a multi-device host"
)


# one process-wide trace cache: repeated entry traces are read, not paid
from tests.analysis._tracecache import CACHE as _CACHE


def _traced(name):
    return trace_matrix([EPS[name]], cache=_CACHE)[name]


def _entry_of(fn, state, name="synthetic"):
    ep = EntryPoint(
        name=name, engine="synthetic", kind="round", audit_check="synthetic",
        build=lambda: (fn, state), n_peers=32,
    )
    te = TracedEntry(ep=ep, state=state)
    te.jaxpr, te.out_shape = jax.make_jaxpr(fn, return_shape=True)(state)
    return name, te


# -------------------------------------------------------- committed lock
def test_lock_covers_every_mesh_entry_nonempty():
    """Acceptance pin: the committed collectives.lock carries a NON-EMPTY
    program with per-axis byte columns for every mesh entry the matrix
    declares — without tracing anything (the lock IS the witness)."""
    lock = load_lock(repo_root() / "collectives.lock")
    assert lock, "collectives.lock missing or empty"
    missing = [n for n in MESH_NAMES if n not in lock]
    assert not missing, f"mesh entries absent from collectives.lock: {missing}"
    for name in MESH_NAMES:
        ent = lock[name]
        assert ent["program"], f"{name}: empty collective program"
        assert int(ent["ops"]) == len(ent["program"])
        wire = int(ent["ici_bytes"]) + int(ent["dcn_bytes"])
        assert wire > 0, f"{name}: zero wire bytes in lock"


@needs_mesh
def test_traced_program_matches_lock():
    """Freshness of the committed lock for a representative entry: the
    trace-order op renders and the per-axis byte totals agree."""
    lock = load_lock(repo_root() / "collectives.lock")
    name = "dist[matching]"
    ops, findings = entry_program(name, _traced(name))
    assert findings == []
    assert [op.render() for op in ops] == lock[name]["program"]
    summ = program_summary({name: ops})[name]
    assert summ["ici_bytes"] == int(lock[name]["ici_bytes"])
    assert summ["dcn_bytes"] == int(lock[name]["dcn_bytes"])


def test_lock_round_trip(tmp_path):
    name, te = divergent_collective_entry()
    ops, _ = entry_program(name, te)
    programs = {name: ops}
    p = tmp_path / "c.lock"
    write_lock(p, programs)
    loaded = load_lock(p)
    assert loaded[name]["program"] == [op.render() for op in ops]
    drift, stale = lock_findings(programs, loaded)
    assert drift == [] and stale == []


def test_lock_drift_and_stale_split(tmp_path):
    name, te = divergent_collective_entry()
    ops, _ = entry_program(name, te)
    p = tmp_path / "c.lock"
    write_lock(p, {name: ops, "ghost[entry]": ops})
    lock = load_lock(p)
    # drifted program (dropped op) fails; unlocked entry fails; the
    # ghost entry (locked but not traced here) reports stale, NON-failing
    drift, stale = lock_findings(
        {name: ops[:-1], "fresh[entry]": ops}, lock
    )
    assert stale == ["ghost[entry]"]
    rules = {f.rule for f in drift}
    assert rules == {"deep-collective-lock-drift"}
    assert {f.qualname for f in drift} == {name, "fresh[entry]"}


# -------------------------------------------------- uniformity semantics
def test_divergent_collective_fires():
    name, te = divergent_collective_entry()
    ops, findings = entry_program(name, te)
    assert ops, "divergent fixture traced an empty program"
    assert any(f.rule == RULE and "diverges" in f.message for f in findings)


def test_identical_arms_are_uniform():
    """Both arms posting the SAME collective sequence rendezvous on every
    shard regardless of the branch — no finding."""
    mesh = make_mesh()

    def body(x):
        return jax.lax.cond(
            x[0] > 0.0,
            lambda v: jax.lax.psum(v * 2.0, AXIS),
            lambda v: jax.lax.psum(v + 1.0, AXIS),
            x,
        )

    fn = shard_map_compat(
        body, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)
    )
    state = jnp.arange(float(mesh.size * 4))
    _, findings = entry_program(*_entry_of(fn, state))
    assert findings == []


def test_uniform_pred_cond_may_diverge():
    """A cond whose predicate is itself collective-agreed (psum'd header)
    takes the SAME arm on every shard — the sparse transport's two-lane
    design. Divergent arms under it must NOT fire."""
    mesh = make_mesh()

    def body(x):
        total = jax.lax.psum(jnp.sum(x), AXIS)  # mesh-agreed scalar
        return jax.lax.cond(
            total > 0.0,
            lambda v: jax.lax.psum(v, AXIS),
            lambda v: v,
            x,
        )

    fn = shard_map_compat(
        body, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)
    )
    state = jnp.arange(float(mesh.size * 4))
    _, findings = entry_program(*_entry_of(fn, state))
    assert findings == []


@needs_mesh
def test_sparse_entries_lint_uniform():
    """The real two-lane sparse transports (both engines) must classify
    clean: their cond predicates are psum'd, so the asymmetric lanes are
    sanctioned. The acceptance's 'real tree lints clean' pin."""
    traced = trace_matrix(
        [EPS["dist[matching,sparse]"], EPS["dist[bucketed,sparse]"]],
        cache=_CACHE,
    )
    findings, programs = collective_report(traced)
    assert findings == []
    assert all(programs.values())


# ----------------------------------- rules_shardmap.py mode-arm blind spot
def test_lambda_arm_collective_blind_spot():
    """The fixture routes through the compat shim and hides a psum in a
    lambda-wrapped cond arm: the WHOLE AST tier is silent on the source
    (raw-shard-map included — its 65 lines only chase raw references),
    while the deep walk over the trace reports the divergence."""
    fix = FIXTURES / "lambda_arm_collective.py"
    ast_findings = lint_paths([str(fix)], project_wide=False)
    assert ast_findings == [], [f.render() for f in ast_findings]

    spec = importlib.util.spec_from_file_location("lambda_arm_fix", fix)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mesh = make_mesh()
    fn = mod.build(mesh)
    state = jnp.arange(float(mesh.size * 4))
    ops, findings = entry_program(*_entry_of(fn, state))
    assert ops, "lambda-arm psum missing from the extracted program"
    assert any(f.rule == RULE and "diverges" in f.message for f in findings)


# ----------------------------------------------------- adversarial harness
def test_selftest_harness_green():
    """CI's --deep-selftest step: both deliberately broken fixtures must
    keep firing (a dead rail reports, an alive one stays silent)."""
    assert run_selftest() == []


def test_unpack_fixture_has_no_collectives():
    """The spike fixture exercises ONLY the liveness rail — its program
    must be empty so the two self-tests stay independent."""
    name, te = unpack_spike_entry()
    ops, findings = entry_program(name, te)
    assert ops == [] and findings == []
