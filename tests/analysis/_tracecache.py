"""Process-wide trace cache shared by the analysis test modules.

Tracing the entry-point matrix (make_jaxpr per entry) is the dominant
compile cost in tests/analysis — test_mem, test_collectives and
test_liveness all walk the same TracedEntry objects, which are pure
values once built. Sharing ONE cache dict across the modules means each
entry is traced once per pytest process instead of once per module
(tier-1 wall budget; ISSUE 17 satellite: session-scope the heaviest
compile fixtures).

Not a conftest fixture on purpose: trace_matrix already takes a plain
``cache`` dict, so a shared module-level dict is the whole mechanism —
no fixture plumbing, and direct `python -m pytest tests/analysis/<one
file>` runs keep working unchanged.
"""

CACHE: dict = {}
