"""Slope-timed stage decomposition of the 1M matching round: where do the
~21 ms/round of the recorded headline go, given the permutation pipeline
itself costs ~1 ms? Candidates: per-round threshold/gate computation (the
expand is a 134-slice concat), the second pipeline for rec_slots, the
protocol tail, RNG, or while_loop condition overhead."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.matching_topology import matching_powerlaw_graph
from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.kernels.matching import matching_sampled
from tpu_gossip.sim.engine import gossip_round, simulate


def slope(body, carry, n1, n2, reps=3):
    def run(iters):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, iters, body, c))
        out = f(carry)
        _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(carry)
            _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    return (run(n2) - run(n1)) / (n2 - n1)


def main():
    n = 1_000_000
    g, plan = matching_powerlaw_graph(n, gamma=2.5, fanout=1, key=jax.random.key(0))
    cfg = SwarmConfig(n_peers=n + 1, msg_slots=16, mode="push_pull", fanout=1)
    state = init_swarm(
        g.as_padded_graph(), cfg, origins=np.arange(16),
        origin_slots=np.arange(16), exists=g.exists,
    )
    # mid-epidemic state for realistic density
    state, _ = simulate(state, cfg, 6, plan)
    tx = state.seen
    rec = state.alive

    def t_expand(i, c):
        return c ^ jnp.sum(
            plan.expand(jnp.full((n,), i, jnp.int32)), dtype=jnp.int32
        )

    def t_partner(i, c):
        return c ^ jnp.sum(
            plan.partner(jnp.full((plan.rows, 128), i, jnp.int32)),
            dtype=jnp.int32,
        )

    def t_reduce(i, c):
        return c ^ jnp.sum(
            plan.reduce(jnp.full((plan.rows, 128), i, jnp.int32), "or"),
            dtype=jnp.int32,
        )

    def t_push_gate(i, c):
        return c ^ jnp.sum(plan.push_threshold().astype(jnp.int32) + i, dtype=jnp.int32)

    def t_pull_gate(i, c):
        return c ^ jnp.sum(plan.pull_threshold().astype(jnp.int32) + i, dtype=jnp.int32)

    def t_rng(i, c):
        k = jax.random.fold_in(jax.random.key(0), i)
        return c ^ jnp.sum(
            jax.random.bits(k, (plan.rows, 128), jnp.uint32).astype(jnp.int32),
            dtype=jnp.int32,
        )

    def t_delivery(i, c):
        k = jax.random.fold_in(jax.random.key(1), i)
        inc, msgs = matching_sampled(
            plan, tx, None, 16, k, receptive_rows=rec,
            do_push=True, do_pull=True,
        )
        # keep the delivery fold live — msgs alone does not depend on the
        # reduce/unpack half and XLA would dead-code-eliminate it
        return c ^ msgs ^ jnp.sum(inc, dtype=jnp.int32)

    st0 = state

    def t_round(i, c):
        nonlocal_state = jax.lax.cond(
            i >= 0, lambda s: s, lambda s: s, c
        )
        nxt, stats = gossip_round(nonlocal_state, cfg, plan)
        return nxt

    for name, body, carry, n1, n2 in [
        ("expand (n->slots)", t_expand, jnp.int32(0), 8, 88),
        ("partner pipeline", t_partner, jnp.int32(0), 8, 88),
        ("reduce (slots->n)", t_reduce, jnp.int32(0), 8, 88),
        ("push gate", t_push_gate, jnp.int32(0), 8, 88),
        ("pull gate", t_pull_gate, jnp.int32(0), 8, 88),
        ("rng draw", t_rng, jnp.int32(0), 8, 88),
        ("matching_sampled full", t_delivery, jnp.int32(0), 4, 44),
        ("full gossip_round", t_round, st0, 4, 44),
    ]:
        dt = slope(body, carry, n1, n2)
        print(f"{name:24s} {dt*1e3:7.2f} ms", flush=True)


if __name__ == "__main__":
    main()
