"""Slope-timed stage decomposition of the 1M matching round.

Round-5 finding (VERDICT item 7): the permutation pipeline delivers in
~1.4 ms yet the composed round ran ~14.4 ms — the protocol tail (dedup
merge, SIR latching, liveness, churn masks) dominated ~10×. The shared
profiler (tpu_gossip.utils.profiling.profile_round_stages — also behind
``run_sim --profile-round``) now times the pipeline micro-stages AND the
tail per implementation (reference multi-pass vs fused single-traversal vs
the Pallas single-launch kernel); the published table lives in
docs/round_tail_profile.md.

Usage: ``python experiments/matching_round_profile.py [n]`` (default 1M).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.matching_topology import matching_powerlaw_graph
from tpu_gossip.core.state import SwarmConfig, init_swarm
from tpu_gossip.sim.engine import simulate
from tpu_gossip.utils.profiling import (
    format_stage_table, profile_round_stages, slope_time,
)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    g, plan = matching_powerlaw_graph(n, gamma=2.5, fanout=1, key=jax.random.key(0))
    cfg = SwarmConfig(n_peers=n + 1, msg_slots=16, mode="push_pull", fanout=1)
    state = init_swarm(
        g.as_padded_graph(), cfg, origins=np.arange(16),
        origin_slots=np.arange(16), exists=g.exists,
    )
    # mid-epidemic state for realistic density (simulate donates its input)
    state, _ = simulate(state, cfg, 6, plan)

    # pipeline micro-stages (the matching path's internals, unchanged from
    # the round-5 probe — kept so regressions in the delivery stage itself
    # stay visible next to the tail rows)
    def t_expand(i, c):
        return c ^ jnp.sum(
            plan.expand(jnp.full((n,), i, jnp.int32)), dtype=jnp.int32
        )

    def t_partner(i, c):
        return c ^ jnp.sum(
            plan.partner(jnp.full((plan.rows, 128), i, jnp.int32)),
            dtype=jnp.int32,
        )

    def t_reduce(i, c):
        return c ^ jnp.sum(
            plan.reduce(jnp.full((plan.rows, 128), i, jnp.int32), "or"),
            dtype=jnp.int32,
        )

    for name, body in [
        ("expand (n->slots)", t_expand),
        ("partner pipeline", t_partner),
        ("reduce (slots->n)", t_reduce),
    ]:
        dt = slope_time(body, jnp.int32(0), 8, 88)
        print(f"{name:24s} {dt*1e3:7.2f} ms", flush=True)

    # composed-round decomposition: delivery, tail per implementation,
    # liveness, stats, rng, and the full round per tail
    stages = profile_round_stages(
        state, cfg, plan, tails=("reference", "fused", "pallas"),
    )
    print(format_stage_table(stages), flush=True)


if __name__ == "__main__":
    main()
