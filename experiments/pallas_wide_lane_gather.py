"""Rate of in-kernel axis-1 take_along_axis at WIDE operands.

The fused-feed design gathers each edge's packed sender word with an
equal-shape lane gather: table plane (S, W) VMEM-resident, idx plane
(S, W) per grid step, idx values in [0, W). If Mosaic runs this near VPU
rate, the 40 ms XLA feed gather collapses to ~1 ms. Measures compile
success + slope-timed element rate for (S, W) in the design range, with
enough grid steps per call that dispatch overhead amortizes.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def probe(S, W, steps=8):
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, 2**31, (S, W), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, W, (steps * S, W), dtype=np.int32))

    def k(tab_ref, idx_ref, out_ref):
        out_ref[:] = jnp.take_along_axis(tab_ref[:], idx_ref[:], axis=1)

    @jax.jit
    def run(tab, idxs):
        return pl.pallas_call(
            k,
            grid=(steps,),
            in_specs=[
                pl.BlockSpec((S, W), lambda j: (0, 0)),
                pl.BlockSpec((S, W), lambda j: (j, 0)),
            ],
            out_specs=pl.BlockSpec((S, W), lambda j: (j, 0)),
            out_shape=jax.ShapeDtypeStruct((steps * S, W), jnp.int32),
        )(tab, idxs)

    try:
        out = run(table, idx)
        ref = np.take_along_axis(
            np.broadcast_to(np.asarray(table), (steps,) + table.shape).reshape(
                steps * S, W
            ),
            np.asarray(idx),
            axis=1,
        )
        ok = bool((np.asarray(out) == ref).all())
    except Exception as e:  # noqa: BLE001
        print(f"S={S} W={W}: FAIL {type(e).__name__}: {str(e)[:160]}")
        return

    def body(i, c):
        g = run(table, (idx + i) % W)
        return c ^ jnp.sum(g, dtype=jnp.int32)

    def wall(n):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, n, body, c))
        r = f(jnp.int32(0))
        _ = float(r)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = f(jnp.int32(0))
            _ = float(r)
            best = min(best, time.perf_counter() - t0)
        return best

    n1, n2 = 2, 10
    dt = (wall(n2) - wall(n1)) / (n2 - n1)
    elems = steps * S * W
    print(
        f"S={S} W={W}: {'OK' if ok else 'WRONG'}  {dt*1e3:.2f} ms/call "
        f"({elems/1e6:.1f}M elems) -> {elems/dt/1e9:.2f} G elem/s; "
        f"6.16M edges would take {6.16e6 * dt / elems * 1e3:.2f} ms"
    )


if __name__ == "__main__":
    probe(8, 1024, steps=64)
    probe(8, 8192, steps=32)
    probe(16, 8192, steps=16)
    probe(8, 65536, steps=8)
    probe(16, 65536, steps=8)
    probe(8, 131072, steps=4)
