"""Which tpu.dynamic_gather shapes does Mosaic actually compile, and how fast?

take_along_axis(x, idx, axis) with x.shape == idx.shape is the only gather
Mosaic lowers (tpu.dynamic_gather, per-lane for axis=0, per-sublane-row lane
shuffle for axis=1). Probe compile success + slope-timed rate per shape.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def try_shape(rows, axis, iters=None):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**31, (rows, 128), dtype=np.int32))
    hi = rows if axis == 0 else 128
    idx = jnp.asarray(rng.integers(0, hi, (rows, 128), dtype=np.int32))

    def k(x_ref, i_ref, o_ref):
        o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=axis)

    @jax.jit
    def run(x, idx):
        return pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.int32)
        )(x, idx)

    try:
        out = run(x, idx)
        ref = np.take_along_axis(np.asarray(x), np.asarray(idx), axis=axis)
        ok = bool((np.asarray(out) == ref).all())
        msg = "OK" if ok else "WRONG RESULT"
    except Exception as e:  # noqa: BLE001
        print(f"rows={rows} axis={axis}: FAIL {type(e).__name__}: {str(e)[:200]}")
        return None

    # slope-time: loop the gather on device
    mod = jnp.int32(hi)

    def body(i, c):
        g = run(x, (idx + i) % mod)
        return c ^ jnp.sum(g, dtype=jnp.int32)

    def wall(n):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, n, body, c))
        r = f(jnp.int32(0))
        _ = float(r)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = f(jnp.int32(0))
            _ = float(r)
            best = min(best, time.perf_counter() - t0)
        return best

    n1, n2 = 4, 64
    dt = (wall(n2) - wall(n1)) / (n2 - n1)
    rate = rows * 128 / dt / 1e6
    print(f"rows={rows} axis={axis}: {msg}  {dt*1e6:.0f} us/call  {rate:.0f} M elem/s")
    return dt


if __name__ == "__main__":
    for axis in (0, 1):
        for rows in (8, 64, 512, 2048, 8192):
            try_shape(rows, axis)
