"""Probe the chip's gather machinery to pick the round-5 feed design.

The 1M push_pull kernel round is bound by ONE XLA gather: 6.16M random
int32 reads from a 1M-word table (40 ms of ~50, docs/kernel_profile_1m.md).
This probe measures every candidate replacement at exactly that shape so
the kernel redesign is evidence-based, not guessed:

  flat        y = table[idx]                      (the current 40 ms feed)
  row<W>      two-step: gather W-word rows by idx>>log2(W), then
              take_along_axis(..., axis=1) lane-select idx&(W-1)
  taa0        tall sublane gather: take_along_axis((R,128) table,
              (R,128) idx, axis=0) in chunks — XLA's lowering of the
              per-lane batched gather (Mosaic's tpu.dynamic_gather shape)
  lane        take_along_axis((rows,128), idx, axis=1) alone — the lane
              shuffle's intrinsic rate
  pallas_taa0 the same tall sublane gather INSIDE a Pallas kernel with the
              table VMEM-resident across the grid

All slope-timed (two-point on-device fori_loop, min over 3 reps) per the
axon measurement protocol — single-shot walls lie by ~2x here.
"""

from __future__ import annotations

import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1_048_576  # table words (1M peers)
E = 6_160_384  # edge slots at the 1M headline (9.4% padded plan)


def slope(make_fn, carry, n1, n2, reps=3):
    def run(iters):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, iters, make_fn, c))
        out = f(carry)
        _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))  # axon barrier
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(carry)
            _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    dt = (run(n2) - run(n1)) / (n2 - n1)
    return dt


def main():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, 2**31, (N,), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, N, (E,), dtype=np.int32))
    results = {}

    # --- flat baseline ---
    def flat(i, c):
        return c ^ jnp.sum(table[(idx + i) & (N - 1)], dtype=jnp.int32)

    results["flat"] = slope(flat, jnp.int32(0), 2, 12)
    print(f"flat 4B gather: {results['flat']*1e3:.1f} ms", flush=True)

    # --- two-step: W-wide row gather + lane select ---
    for w in (8, 32, 128, 512):
        tab2 = table.reshape(N // w, w)
        rowm = jnp.asarray(rng.integers(0, N // w, (E,), dtype=np.int32))
        lane = jnp.asarray(rng.integers(0, w, (E, 1), dtype=np.int32))

        def two(i, c, tab2=tab2, rowm=rowm, lane=lane, w=w):
            rows = tab2[(rowm + i) & (N // w - 1)]  # (E, w) slice gather
            vals = jnp.take_along_axis(rows, lane, axis=1)[:, 0]
            return c ^ jnp.sum(vals, dtype=jnp.int32)

        results[f"row{w}"] = slope(two, jnp.int32(0), 2, 8)
        print(f"row{w} gather+laneselect: {results[f'row{w}']*1e3:.1f} ms", flush=True)

    # --- tall sublane take_along_axis (the dynamic_gather shape), chunked ---
    R = N // 128  # 8192
    tab128 = table.reshape(R, 128)
    nchunk = E // (R * 128)  # 5 full chunks ~ 5.2M of 6.16M; scale at end
    idx0 = jnp.asarray(rng.integers(0, R, (nchunk, R, 128), dtype=np.int32))

    def taa0(i, c):
        def body(j, acc):
            g = jnp.take_along_axis(tab128, (idx0[j] + i) & (R - 1), axis=0)
            return acc ^ jnp.sum(g, dtype=jnp.int32)

        return jax.lax.fori_loop(0, nchunk, body, c)

    t = slope(taa0, jnp.int32(0), 2, 12)
    results["taa0"] = t * E / (nchunk * R * 128)  # normalize to E accesses
    print(
        f"tall sublane taa axis0 ({nchunk} chunks of ({R},128)): "
        f"{t*1e3:.1f} ms raw -> {results['taa0']*1e3:.1f} ms at E",
        flush=True,
    )

    # --- lane shuffle alone at full E ---
    rowsE = E // 128
    bigrows = jnp.asarray(rng.integers(0, 2**31, (rowsE, 128), dtype=np.int32))
    lidx = jnp.asarray(rng.integers(0, 128, (rowsE, 128), dtype=np.int32))

    def lane(i, c):
        g = jnp.take_along_axis(bigrows, (lidx + i) & 127, axis=1)
        return c ^ jnp.sum(g, dtype=jnp.int32)

    results["lane"] = slope(lane, jnp.int32(0), 2, 12)
    print(f"lane shuffle axis1 at E: {results['lane']*1e3:.1f} ms", flush=True)

    # --- pallas: tall sublane gather with VMEM-resident table ---
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        CH = 2048  # chunk rows per grid step; idx block (CH,128)

        def pk(tab_ref, idx_ref, out_ref):
            tab = tab_ref[:]  # (R, 128) resident
            ii = idx_ref[:]  # (CH, 128)
            # equal-shape take_along_axis per Mosaic: pad idx rows to R? No —
            # gather semantics need idx shape == table shape. Instead tile:
            # do CH rows by gathering from tab with idx padded via broadcast
            # trick: take_along_axis requires same shape; emulate by looping
            # sub-blocks of 8 rows? Start simple: pad to R rows.
            pad = jnp.zeros((R - CH, 128), jnp.int32)
            full = jnp.concatenate([ii, pad], axis=0)
            g = jnp.take_along_axis(tab, full, axis=0)
            out_ref[:] = g[:CH]

        nch = E // (CH * 128)  # ~23 chunks
        idxp = jnp.asarray(
            rng.integers(0, R, (nch * CH, 128), dtype=np.int32)
        )

        @jax.jit
        def pallas_run(tab2d, idxs):
            return pl.pallas_call(
                pk,
                grid=(nch,),
                in_specs=[
                    pl.BlockSpec((R, 128), lambda j: (0, 0)),
                    pl.BlockSpec((CH, 128), lambda j: (j, 0)),
                ],
                out_specs=pl.BlockSpec((CH, 128), lambda j: (j, 0)),
                out_shape=jax.ShapeDtypeStruct((nch * CH, 128), jnp.int32),
            )(tab2d, idxs)

        def pallas_body(i, c):
            g = pallas_run(tab128, (idxp + i) & (R - 1))
            return c ^ jnp.sum(g, dtype=jnp.int32)

        t = slope(pallas_body, jnp.int32(0), 2, 12)
        results["pallas_taa0"] = t * E / (nch * CH * 128)
        print(
            f"pallas taa0 VMEM table: {t*1e3:.1f} ms raw -> "
            f"{results['pallas_taa0']*1e3:.1f} ms at E",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        print(f"pallas taa0 FAILED: {type(e).__name__}: {str(e)[:500]}", flush=True)

    print("\nsummary (ms at E=6.16M):")
    for k, v in results.items():
        print(f"  {k:12s} {v*1e3:8.1f}")


if __name__ == "__main__":
    main()
