"""Measure the structured-permutation pipeline's building blocks at 1M scale.

Blocks: XLA 2D transpose, in-Pallas per-row lane shuffle (tall blocks OK),
in-Pallas 8-way sublane shuffle, and the composed pipeline
T . shuffle . T . shuffle at E_pad ~ 8.4M int32 (the stub array for a 1M-peer
erased-configuration-model swarm). If the composed cost is ~1-3 ms, the
gather-free structured delivery replaces the 40 ms feed gather.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

E = 8_388_608  # 2^23 stub slots (pad to powers for clean reshapes)
R = E // 128  # 65536 rows


def slope(body, carry, n1, n2, reps=3):
    def run(iters):
        f = jax.jit(lambda c: jax.lax.fori_loop(0, iters, body, c))
        out = f(carry)
        _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(carry)
            _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    return (run(n2) - run(n1)) / (n2 - n1)


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**31, (R, 128), dtype=np.int32))

    # --- XLA transpose (R,128) -> (128,R) -> reshape (R,128) ---
    def t2d(i, c):
        return (c + i).T.reshape(R, 128)

    dt = slope(t2d, x, 4, 64)
    print(f"XLA transpose (R,128)->(128,R)+reshape: {dt*1e3:.2f} ms "
          f"({2*E*4/dt/1e9:.0f} GB/s eff)", flush=True)

    # --- XLA 3D middle transpose (r1, r2, 128) -> (r2, r1, 128) ---
    r1, r2 = 512, 128
    x3 = x.reshape(r1, r2, 128)

    def t3d(i, c):
        return (c + i).transpose(1, 0, 2)

    dt = slope(lambda i, c: t3d(i, c).transpose(1, 0, 2), x3, 4, 64)
    print(f"XLA 3D transpose pair (512,128,128)<->: {dt*1e3:.2f} ms", flush=True)

    # --- pallas lane shuffle at scale: block (2048,128), grid 32 ---
    BR = 2048
    lidx = jnp.asarray(rng.integers(0, 128, (R, 128), dtype=np.int32))

    def ksh(x_ref, i_ref, o_ref):
        o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=1)

    @jax.jit
    def lane_shuffle(v, idx):
        return pl.pallas_call(
            ksh,
            grid=(R // BR,),
            in_specs=[
                pl.BlockSpec((BR, 128), lambda j: (j, 0)),
                pl.BlockSpec((BR, 128), lambda j: (j, 0)),
            ],
            out_specs=pl.BlockSpec((BR, 128), lambda j: (j, 0)),
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.int32),
        )(v, idx)

    dt = slope(lambda i, c: lane_shuffle(c, lidx) + i, x, 4, 64)
    print(f"pallas lane shuffle 8.4M: {dt*1e3:.2f} ms "
          f"({E/dt/1e9:.1f} G elem/s)", flush=True)

    # --- pallas sublane 8-way shuffle: loop (8,128) slices in-kernel ---
    sidx = jnp.asarray(rng.integers(0, 8, (R, 128), dtype=np.int32))

    def ksub(x_ref, i_ref, o_ref):
        def body(j, _):
            sl = pl.ds(j * 8, 8)
            o_ref[sl, :] = jnp.take_along_axis(x_ref[sl, :], i_ref[sl, :], axis=0)
            return 0

        jax.lax.fori_loop(0, BR // 8, body, 0)

    @jax.jit
    def sub_shuffle(v, idx):
        return pl.pallas_call(
            ksub,
            grid=(R // BR,),
            in_specs=[
                pl.BlockSpec((BR, 128), lambda j: (j, 0)),
                pl.BlockSpec((BR, 128), lambda j: (j, 0)),
            ],
            out_specs=pl.BlockSpec((BR, 128), lambda j: (j, 0)),
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.int32),
        )(v, idx)

    try:
        out = sub_shuffle(x, sidx)
        ref = np.asarray(x).reshape(-1, 8, 128)
        ridx = np.asarray(sidx).reshape(-1, 8, 128)
        ok = bool(
            (np.asarray(out).reshape(-1, 8, 128)
             == np.take_along_axis(ref, ridx, axis=1)).all()
        )
        dt = slope(lambda i, c: sub_shuffle(c, sidx) + i, x, 4, 64)
        print(f"pallas sublane shuffle 8.4M: {'OK' if ok else 'WRONG'} "
              f"{dt*1e3:.2f} ms", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"pallas sublane shuffle FAIL: {type(e).__name__}: {str(e)[:160]}",
              flush=True)

    # --- composed pipeline: shuffle, transpose, shuffle, transpose, shuffle ---
    l2 = jnp.asarray(rng.integers(0, 128, (R, 128), dtype=np.int32))
    l3 = jnp.asarray(rng.integers(0, 128, (R, 128), dtype=np.int32))

    def pipeline(i, c):
        v = lane_shuffle(c + i, lidx)
        v = v.T.reshape(R, 128)
        v = lane_shuffle(v, l2)
        v = v.T.reshape(R, 128)
        v = lane_shuffle(v, l3)
        return v

    dt = slope(pipeline, x, 4, 64)
    print(f"composed 5-pass pipeline 8.4M: {dt*1e3:.2f} ms", flush=True)

    # lane shuffle fused with the transposed view read (avoid materializing T?)
    def pipeline2(i, c):
        v = lane_shuffle(c + i, lidx)
        v = jnp.transpose(v).reshape(R, 128)
        v = lane_shuffle(v, l2)
        return v

    dt = slope(pipeline2, x, 4, 64)
    print(f"composed 3-pass pipeline 8.4M: {dt*1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
