"""Slope-timed stage decomposition of the dist engine's _exchange at 1M
(VERDICT r4 item 2): where the single-device overhead lives, measured on
hardware, plus the post-rewrite end-to-end dist-vs-local comparison."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.state import SwarmConfig, clone_state
from tpu_gossip.core.topology import (
    build_csr, configuration_model, powerlaw_degree_sequence,
)
from tpu_gossip.dist import (
    build_shard_plans, init_sharded_swarm, make_mesh, partition_graph,
    run_until_coverage_dist, shard_swarm,
)
from tpu_gossip.sim.engine import run_until_coverage
from tpu_gossip.sim.metrics import bench_swarm

N = 1_000_000


def timed(run, state, reps=3):
    # the engines donate their state: one clone per invocation, pre-timer
    fin = run(clone_state(state))
    cov, rounds = float(fin.coverage(0)), int(fin.round)
    best = float("inf")
    for _ in range(reps):
        rep_state = clone_state(state)
        t0 = time.perf_counter()
        fin = run(rep_state)
        float(fin.coverage(0))
        best = min(best, time.perf_counter() - t0)
    return best, rounds, cov


def main():
    rng = np.random.default_rng(0)
    graph = build_csr(
        N, configuration_model(powerlaw_degree_sequence(N, gamma=2.5, rng=rng), rng=rng)
    )
    print("host graph built", flush=True)
    mesh = make_mesh()
    sg, relabeled, position = partition_graph(graph, mesh.size, seed=0)
    plans = build_shard_plans(sg)
    cfg = SwarmConfig(n_peers=sg.n_pad, msg_slots=16, fanout=1, mode="push_pull")
    st0 = init_sharded_swarm(sg, relabeled, position, cfg, origins=[0])
    st = shard_swarm(st0, mesh)
    print(f"devices={mesh.size} bucket={sg.bucket} per={sg.per_shard}", flush=True)

    w, r, c = timed(
        lambda s: run_until_coverage_dist(s, cfg, sg, mesh, 0.99, 300), st
    )
    print(f"dist scatter: {w/r*1e3:.1f} ms/round ({r} rounds, cov {c:.4f})",
          flush=True)
    w2, r2, c2 = timed(
        lambda s: run_until_coverage_dist(s, cfg, sg, mesh, 0.99, 300,
                                          shard_plan=plans), st
    )
    print(f"dist pallas:  {w2/r2*1e3:.1f} ms/round ({r2} rounds, cov {c2:.4f})",
          flush=True)
    w3, r3, c3 = timed(lambda s: run_until_coverage(s, cfg, 0.99, 300), st0)
    print(f"local xla:    {w3/r3*1e3:.1f} ms/round ({r3} rounds)", flush=True)
    print(f"overhead_vs_local: scatter {w/r/(w3/r3):.2f}x  "
          f"pallas {w2/r2/(w3/r3):.2f}x", flush=True)


if __name__ == "__main__":
    main()
