"""tpu_gossip — a TPU-native framework for gossip protocols on power-law networks.

Built from scratch in JAX/XLA/Pallas with the capability surface of the
reference `Sidharthshanu/Gossip-protocol-with-power-law` (see SURVEY.md):

- power-law (preferential-attachment) topology construction
  (reference intent: Seed.py:151-185, demonstrate_powerlaw.py:5-39)
- seed-based bootstrap / membership (Seed.py:240-299)
- push-gossip dissemination, generalized to real epidemic flooding with
  hash-based dedup (reference one-hop broadcast: Peer.py:395-408)
- heartbeat/timeout liveness + dead-node detection and purge
  (Peer.py:298-393, Seed.py:358-406)
- fault injection: silent peers (Peer.py:437-439), churn, SIR dynamics
- socket-compatible transport preserving the reference wire protocol
  (SURVEY.md §2.4) behind a ``transport="socket" | "tpu-sim"`` flag.

Instead of one OS process + thread-per-connection per node, the whole swarm
lives on the TPU as a pytree of arrays (CSR adjacency in HBM, infection /
liveness masks), one gossip round is a batched gather/scatter over all peers
at once, and multi-chip runs shard the peer axis 1-D over a
``jax.sharding.Mesh``.
"""

from tpu_gossip.core.topology import (
    Graph,
    powerlaw_degree_sequence,
    configuration_model,
    preferential_attachment,
    build_csr,
    fit_powerlaw_gamma,
)
from tpu_gossip.core.state import SwarmState, SwarmConfig, init_swarm
from tpu_gossip.core.matching_topology import (
    MatchingPlan,
    matching_powerlaw_graph,
    matching_powerlaw_graph_sharded,
)
from tpu_gossip.growth import (
    CompiledGrowth,
    compile_growth,
    pad_graph_for_growth,
)

__version__ = "0.1.0"

__all__ = [
    "Graph",
    "powerlaw_degree_sequence",
    "configuration_model",
    "preferential_attachment",
    "build_csr",
    "fit_powerlaw_gamma",
    "SwarmState",
    "SwarmConfig",
    "init_swarm",
    "MatchingPlan",
    "matching_powerlaw_graph",
    "matching_powerlaw_graph_sharded",
    "CompiledGrowth",
    "compile_growth",
    "pad_graph_for_growth",
]
