"""Campaign plans: parse a campaign TOML, compile a batched swarm fleet.

A *campaign* turns the chaos catalogue from ~6 hand-written trajectories
into a **Monte Carlo certification run**: K independent swarms — the
*lanes* — drawn from a sampled distribution over fault space, compiled
into ONE batched program the fleet engine (fleet/engine.py) vmaps over.
Reliability then comes back as *quantiles with confidence intervals per
scenario family* (the delivery-ratio frame of *Reliable Probabilistic
Gossip over Large-Scale Random Topologies*, PAPERS.md) instead of one
sample per TOML, and controller-bound sweeps locate where the declared
contract breaks (the AIMD-bound question PeerSwap-style adaptive refresh
raises, PAPERS.md).

A campaign TOML holds one ``[campaign]`` table, one ``[base]`` run config
(every knob the lanes share), and ``[[family]]`` entries — each naming a
scenario file from the catalogue, a seed count, and optional
``[[family.sweep]]`` axes::

    [campaign]
    name = "lossy-sweep"
    seed = 0

    [base]
    peers  = 96
    rounds = 30
    slots  = 4
    fanout = 2
    mode   = "push"

    [[family]]
    name     = "lossy"
    scenario = "scenarios/lossy_links.toml"
    seeds    = 32

    [[family.sweep]]
    axis = "phase.loss"
    dist = "uniform"
    lo   = 0.05
    hi   = 0.6

**The shared-static-shape rule.** One compile serves all K lanes, so
every lane must share every jit-static property: same n, m, horizon,
``max_inject``, fanout-table width. The sampled axes are exactly the
ones that ride TRACED leaves — fault-phase parameters (per-phase table
values), traffic rates (a traced scalar; ``max_inject`` is pinned to the
largest sampled rate, the bench.py saturation-curve pattern), and
controller bounds (per-lane CLAMPED fanout tables over one global-width
spec). An axis that would move a static shape — peers, slots, rounds,
TTL, Bloom width — is rejected at parse time (exit 2 from the CLI),
and after compilation every lane's plan pytree is structure-checked
against lane 0 as a backstop: a mismatch can never reach ``vmap``.

**Scenario-family unification.** Families compile their scenarios
independently, then unify to one static structure: per-phase tables are
zero-padded to the widest phase count (padded rows are quiescent and
unreachable — ``phase_of_round`` never names them), the ``has_*`` flags
become the OR across lanes, and lanes whose schedule lacks a fault class
run its machinery over all-zero tables — VALUE-identical to not running
it (the quiescent-scenario contract, tests/sim/test_faults.py), so a
mixed catalogue batches into one program without changing any lane's
trajectory.

**Determinism.** Lane k's root key is
``fold_in(fold_in(key(campaign_seed), FLEET_STREAM_SALT), k)`` — the
registered fleet stream (core/streams.py), derived host-side at compile
time. The conformance contract: lane k of the batched run is
BIT-IDENTICAL (full state + integer stats) to a solo ``simulate`` of
``campaign.lane(k)`` — test-pinned at composed scenario×stream×control
cells (tests/sim/test_fleet.py), and cross-checked across processes by
the fleet-smoke CI job's serial digest comparison.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from tpu_gossip.core.streams import FLEET_STREAM_SALT  # noqa: F401 (re-export)
from tpu_gossip.faults.scenario import (
    ScenarioError,
    _parse_value,
    _strip_comment,
)

__all__ = [
    "CampaignError",
    "SweepAxis",
    "FamilySpec",
    "CampaignSpec",
    "LaneInfo",
    "CompiledCampaign",
    "parse_campaign",
    "campaign_from_dict",
    "compile_campaign",
    "SWEEP_AXES",
]


class CampaignError(ValueError):
    """A campaign that cannot mean what it says (parse/compile time)."""


# the sampled axes a campaign may declare — each rides a TRACED leaf of a
# compiled plan, so sweeping it never changes a jit-static shape. Anything
# else is rejected by name with this list in the message.
SWEEP_AXES = (
    "phase.loss",
    "phase.delay",
    "phase.churn_leave",
    "phase.churn_join",
    "stream.rate",
    "control.lo",
    "control.hi",
    "control.target",
)

_DISTS = ("uniform", "linspace", "choice")

_BASE_KEYS = {
    "peers", "rounds", "slots", "fanout", "mode", "graph", "gamma", "m",
    "origins", "graph_seed", "forward_once", "sir_recover", "churn_leave",
    "churn_join", "rewire_slots", "coverage_target", "target_ratio",
    "stream_rate", "slot_ttl", "stream_origins", "stream_hashes",
    "control", "control_lo", "control_hi", "refresh_every",
    "grow", "grow_rate", "grow_capacity",
    "quorum_k", "suspicion_window", "accusation_budget",
}


@dataclasses.dataclass(frozen=True)
class SweepAxis:
    """One sampled axis of a family: ``axis`` ∈ :data:`SWEEP_AXES`."""

    axis: str
    dist: str  # "uniform" | "linspace" | "choice"
    lo: float = 0.0
    hi: float = 0.0
    values: tuple[float, ...] = ()
    phase: str | None = None  # phase.* axes: scope to one named phase

    def sample(self, n: int, rng: np.random.Generator) -> np.ndarray:
        if self.dist == "uniform":
            v = rng.uniform(self.lo, self.hi, size=n)
        elif self.dist == "linspace":
            v = np.linspace(self.lo, self.hi, num=n)
        else:  # choice: cycle deterministically over values
            v = np.asarray(
                [self.values[i % len(self.values)] for i in range(n)],
                dtype=float,
            )
        if self.axis in ("control.lo", "control.hi"):
            # bounds are integers: round AT SAMPLING time so the value a
            # lane's report/frontier groups by IS the bound its
            # controller ran with (not an unrounded float the compiler
            # would silently round)
            v = np.rint(v)
        return v


@dataclasses.dataclass(frozen=True)
class FamilySpec:
    """One scenario family: a catalogue entry plus its sampled axes.

    ``scenario`` is a path to a scenarios/*.toml, or (library/test use)
    an inline scenario dict in the ``scenario_from_dict`` surface, or
    ``None`` for a fault-free family.
    """

    name: str
    scenario: str | dict | None
    seeds: int
    sweeps: tuple[SweepAxis, ...] = ()

    @property
    def scenario_label(self) -> str | None:
        """Report-facing label: the path, or an inline dict's name."""
        if isinstance(self.scenario, dict):
            return str(self.scenario.get("name", "inline"))
        return self.scenario


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """A parsed, not-yet-compiled campaign.

    ``root`` is the campaign file's directory (when parsed from a file):
    family scenario paths resolve against the working directory first,
    then against ``root`` and its parents — so a campaign under
    ``scenarios/campaigns/`` can name ``scenarios/lossy_links.toml``
    repo-relative and still compile from any cwd.
    """

    name: str
    seed: int
    base: dict
    families: tuple[FamilySpec, ...]
    root: str | None = None

    @property
    def n_lanes(self) -> int:
        return sum(f.seeds for f in self.families)


@dataclasses.dataclass(frozen=True)
class LaneInfo:
    """Host-side metadata of one compiled lane (report bookkeeping)."""

    index: int
    family: str
    seed_index: int  # lane's index inside its family
    sampled: dict  # axis -> sampled value


@dataclasses.dataclass
class CompiledCampaign:
    """K per-swarm plans stacked into one batched pytree.

    ``states``/``scenario``/``growth``/``stream``/``control`` carry a
    leading lane axis on every array leaf (static fields shared — the
    shared-static-shape rule); ``lane(k)`` extracts one lane's solo plans
    for the bit-identity cross-check and the ``--solo`` CLI path.
    """

    name: str
    k: int
    cfg: object  # SwarmConfig (jit-static, shared by every lane)
    rounds: int
    coverage_target: float
    target_ratio: float
    states: object  # batched SwarmState
    scenario: object | None  # batched CompiledScenario
    growth: object | None  # batched CompiledGrowth (identical lanes)
    stream: object | None  # batched CompiledStream
    control: object | None  # batched ControlSpec
    lanes: tuple[LaneInfo, ...]
    families: tuple[FamilySpec, ...]
    base: dict
    # the quorum-detector spec (kernels/liveness.py) is jit-STATIC and
    # hashable, so it is shared by every lane rather than stacked — the
    # shared-static-shape rule's degenerate case
    liveness: object | None = None  # QuorumSpec (static, lane-shared)
    # set by run_campaign(keep_states=False): the initial states were
    # DONATED and self.states now holds the FINAL states — lane
    # extraction would silently hand out post-run state, so it refuses
    consumed: bool = False

    def lane(self, k: int):
        """(state, scenario, growth, stream, control) of lane ``k`` —
        exactly the plans the batched program runs for that lane, so a
        solo ``simulate`` over them is the conformance oracle."""
        from tpu_gossip.core.state import lane_state

        if self.consumed:
            raise CampaignError(
                "campaign states were donated by run_campaign("
                "keep_states=False) and now hold the FINAL states — "
                "extract lanes before the donating run, or run with "
                "keep_states=True"
            )
        if not 0 <= k < self.k:
            raise CampaignError(f"lane {k} outside [0, {self.k})")
        # lane_state works on any stacked pytree, plans included
        pick = lambda p: None if p is None else lane_state(p, k)  # noqa: E731
        return (
            lane_state(self.states, k),
            pick(self.scenario),
            pick(self.growth),
            pick(self.stream),
            pick(self.control),
        )


# ------------------------------------------------------------- the parser
def _toml_tables(text: str) -> tuple[dict, dict, list[dict]]:
    """(campaign, base, families) from the campaign TOML subset.

    Same restricted reader family as faults/scenario.py (Python 3.10
    container, no stdlib tomllib): ``[campaign]``/``[base]`` tables,
    ``[[family]]`` entries, nested ``[[family.sweep]]`` attaching to the
    most recent family.
    """
    campaign: dict = {}
    base: dict = {}
    families: list[dict] = []
    cur: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line == "[campaign]":
            cur = campaign
        elif line == "[base]":
            cur = base
        elif line == "[[family]]":
            cur = {"sweeps": []}
            families.append(cur)
        elif line == "[[family.sweep]]":
            if not families:
                raise CampaignError(
                    f"line {lineno}: [[family.sweep]] before any [[family]]"
                )
            cur = {}
            families[-1]["sweeps"].append(cur)
        elif line.startswith("["):
            raise CampaignError(
                f"line {lineno}: unknown table {line!r} (campaign files "
                "hold [campaign], [base], [[family]] and [[family.sweep]])"
            )
        else:
            key, eq, value = line.partition("=")
            if not eq:
                raise CampaignError(f"line {lineno}: expected key = value")
            if cur is None:
                raise CampaignError(f"line {lineno}: key outside any table")
            try:
                cur[key.strip()] = _parse_value(value)
            except ScenarioError as e:
                raise CampaignError(f"line {lineno}: {e}") from None
    return campaign, base, families


def campaign_from_dict(d: dict, root: str | None = None) -> CampaignSpec:
    """Build a spec from a plain dict (the TOML surface, for library use):
    ``{"name", "seed", "base": {...}, "families": [{...}, ...]}``."""
    base = dict(d.get("base", {}))
    unknown = set(base) - _BASE_KEYS
    if unknown:
        raise CampaignError(
            f"[base]: unknown keys {sorted(unknown)} (known: "
            f"{sorted(_BASE_KEYS)})"
        )
    families = []
    for i, f in enumerate(d.get("families", ())):
        unknown = set(f) - {"name", "scenario", "seeds", "sweeps"}
        if unknown:
            raise CampaignError(
                f"family {i}: unknown keys {sorted(unknown)}"
            )
        sweeps = []
        for j, s in enumerate(f.get("sweeps", ())):
            where = f"family {i} sweep {j}"
            axis = s.get("axis")
            if axis not in SWEEP_AXES:
                raise CampaignError(
                    f"{where}: unknown sampled axis {axis!r} — a campaign "
                    "can sample only axes that ride traced leaves (shared "
                    f"static shapes across the batch): {list(SWEEP_AXES)}"
                )
            dist = s.get("dist", "uniform")
            if dist not in _DISTS:
                raise CampaignError(
                    f"{where}: unknown dist {dist!r}; choose from {_DISTS}"
                )
            if dist == "choice":
                vals = tuple(float(v) for v in s.get("values", ()))
                if not vals:
                    raise CampaignError(f"{where}: choice needs values = [...]")
                if axis.startswith("phase.") and not all(
                    0.0 <= v <= 1.0 for v in vals
                ):
                    raise CampaignError(
                        f"{where}: {axis} samples a probability — every "
                        "value must lie in [0, 1] (the report groups lanes "
                        "by the sampled value, so an out-of-range sample "
                        "would misreport what actually ran)"
                    )
                sweeps.append(SweepAxis(axis=axis, dist=dist, values=vals,
                                        phase=s.get("phase")))
            else:
                if "lo" not in s or "hi" not in s:
                    raise CampaignError(f"{where}: {dist} needs lo and hi")
                lo, hi = float(s["lo"]), float(s["hi"])
                if hi < lo:
                    raise CampaignError(f"{where}: lo {lo} > hi {hi}")
                if axis.startswith("phase.") and not (
                    0.0 <= lo and hi <= 1.0
                ):
                    raise CampaignError(
                        f"{where}: {axis} samples a probability — lo/hi "
                        f"[{lo}, {hi}] must lie inside [0, 1] (the report "
                        "groups lanes by the sampled value, so a clamped "
                        "sample would misreport what actually ran)"
                    )
                sweeps.append(SweepAxis(axis=axis, dist=dist, lo=lo, hi=hi,
                                        phase=s.get("phase")))
        seeds = int(f.get("seeds", 0))
        if seeds < 1:
            raise CampaignError(
                f"family {i}: seeds must be >= 1 (got {seeds})"
            )
        families.append(FamilySpec(
            name=str(f.get("name", f"family{i}")),
            scenario=f.get("scenario"),
            seeds=seeds,
            sweeps=tuple(sweeps),
        ))
    spec = CampaignSpec(
        name=str(d.get("name", "campaign")),
        seed=int(d.get("seed", 0)),
        base=base,
        families=tuple(families),
        root=root,
    )
    if not spec.families:
        raise CampaignError("campaign declares no [[family]] entries")
    names = [f.name for f in spec.families]
    if len(names) != len(set(names)):
        dup = sorted({n for n in names if names.count(n) > 1})
        raise CampaignError(
            f"duplicate family names {dup} — lanes, scenarios and report "
            "blocks are grouped by family name, so duplicates would "
            "cross-wire them"
        )
    if spec.n_lanes < 2:
        raise CampaignError(
            f"campaign has {spec.n_lanes} lane — a one-lane campaign is a "
            "solo run (use run_sim --scenario); declare seeds >= 2 total"
        )
    if int(base.get("rounds", 0)) <= 0:
        raise CampaignError(
            "[base] needs rounds > 0 — campaigns run fixed horizons (the "
            "certification report reads per-round stats)"
        )
    return spec


def parse_campaign(source: str | Path) -> CampaignSpec:
    """Parse a campaign TOML file (or TOML text containing a newline)."""
    if isinstance(source, str) and "\n" in source:
        text, root = str(source), None
    else:
        text, root = Path(source).read_text(), str(Path(source).parent)
    campaign, base, families = _toml_tables(text)
    return campaign_from_dict({
        "name": campaign.get("name", "campaign"),
        "seed": campaign.get("seed", 0),
        "base": base,
        "families": families,
    }, root=root)


# ----------------------------------------------------------- the compiler
def _sample_lanes(spec: CampaignSpec) -> list[LaneInfo]:
    """Deterministic per-lane axis values: each (family, axis) draws from
    ``default_rng([campaign_seed, family_idx, axis_idx])`` — edits to one
    family never move another family's samples."""
    lanes: list[LaneInfo] = []
    idx = 0
    for fi, fam in enumerate(spec.families):
        values = {}
        for ai, ax in enumerate(fam.sweeps):
            rng = np.random.default_rng([spec.seed, fi, ai])
            values[ax.axis] = ax.sample(fam.seeds, rng)
        for si in range(fam.seeds):
            lanes.append(LaneInfo(
                index=idx, family=fam.name, seed_index=si,
                sampled={a: float(v[si]) for a, v in values.items()},
            ))
            idx += 1
    return lanes


def _override_phases(sdict: dict, axis: SweepAxis, value: float) -> None:
    """Apply a sampled phase-parameter to a scenario dict (in place).

    Scoped to ``axis.phase`` when named, else to every phase that
    DECLARES the parameter (> 0) — a lane cannot silently turn a fault
    class on in a phase its family never wrote, which would flip a
    static ``has_*`` flag mid-batch.
    """
    param = axis.axis.split(".", 1)[1]
    hits = 0
    for p in sdict["phases"]:
        if axis.phase is not None and p.get("name") != axis.phase:
            continue
        if axis.phase is None and not p.get(param, 0.0):
            continue
        # parse-time validation bounds samples to [0, 1]; the clip is
        # belt-and-braces so a float-rounding edge can't escape a
        # probability's domain
        p[param] = float(np.clip(value, 0.0, 1.0))
        hits += 1
    if hits == 0:
        where = (
            f"phase {axis.phase!r}" if axis.phase is not None
            else f"any phase declaring {param!r}"
        )
        raise CampaignError(
            f"sweep axis {axis.axis!r} matched no phase — the scenario "
            f"has no {where} (sampling it would flip a static has_* flag "
            "mid-batch)"
        )


def _scenario_dict(path: str, root: str | None) -> dict:
    """A scenario file as the dict surface ``scenario_from_dict`` takes,
    so sampled phase parameters can be overridden before compiling.
    Relative paths try the cwd first, then the campaign file's directory
    and its parents (a campaign under scenarios/campaigns/ names its
    families repo-relative)."""
    from tpu_gossip.faults.scenario import _toml_tables as _scenario_tables

    candidates = [Path(path)]
    if root is not None and not Path(path).is_absolute():
        r = Path(root)
        candidates += [r / path, r.parent / path, r.parent.parent / path]
    for c in candidates:
        if c.is_file():
            text = c.read_text()
            break
    else:
        raise CampaignError(
            f"family scenario {path!r}: no such file (tried "
            f"{[str(c) for c in candidates]})"
        )
    scenario, phases = _scenario_tables(text)
    return {"name": scenario.get("name", "scenario"), "phases": phases}


def _unify_scenarios(compiled: list, name: str):
    """Pad per-lane compiled scenarios to ONE static structure.

    Phase tables zero-pad to the widest phase count (padded rows are
    quiescent and ``phase_of_round`` never names them), ``has_*`` flags
    become the OR across lanes (a lane without the class runs its
    machinery over zero tables — value-identical to not running it, the
    quiescent-scenario contract), and ``join_burst`` unifies to a zero
    table on lanes without admission waves. Returns the per-lane list
    re-built with the shared structure.
    """
    import dataclasses as _dc

    import jax.numpy as jnp

    p_max = max(c.loss.shape[0] for c in compiled)
    flags = {
        f: any(getattr(c, f) for c in compiled)
        for f in ("has_partition", "has_blackout", "has_churn",
                  "has_loss_delay", "has_join_burst", "has_accusers",
                  "has_forgers", "has_floods")
    }
    # the static draw widths unify to the batch maximum (per-phase traced
    # fanouts stay the lane's own — columns past them are masked)
    statics = {
        f: max(getattr(c, f) for c in compiled)
        for f in ("max_forge_fanout", "max_flood_fanout")
    }

    def pad1(a, rows):
        return jnp.concatenate([
            a, jnp.zeros((rows - a.shape[0],) + a.shape[1:], dtype=a.dtype)
        ]) if a.shape[0] < rows else a

    def unify_opt(c, field, flag, n_cols=None, dtype=jnp.int32):
        if not flags[flag]:
            return None
        a = getattr(c, field)
        if a is None:
            shape = (c.loss.shape[0],) if n_cols is None else (
                c.loss.shape[0], n_cols)
            a = jnp.zeros(shape, dtype=dtype)
        return pad1(a, p_max)

    out = []
    for c in compiled:
        n_cols = c.burst.shape[1]
        out.append(_dc.replace(
            c,
            loss=pad1(c.loss, p_max), delay=pad1(c.delay, p_max),
            leave=pad1(c.leave, p_max), join=pad1(c.join, p_max),
            burst=pad1(c.burst, p_max), blackout=pad1(c.blackout, p_max),
            group_b=pad1(c.group_b, p_max),
            join_burst=unify_opt(c, "join_burst", "has_join_burst"),
            accuser=unify_opt(c, "accuser", "has_accusers", n_cols, bool),
            forger=unify_opt(c, "forger", "has_forgers", n_cols, bool),
            flooder=unify_opt(c, "flooder", "has_floods", n_cols, bool),
            forge_fanout=unify_opt(c, "forge_fanout", "has_forgers"),
            flood_fanout=unify_opt(c, "flood_fanout", "has_floods"),
            name=name,
            **flags,
            **statics,
        ))
    return out


def _check_lane_structures(plans: list, what: str) -> None:
    """The shared-static-shape backstop: every lane's compiled plan must
    match lane 0's pytree structure AND leaf shapes/dtypes — a mismatch
    would change a jit-static property mid-batch and can never reach
    ``vmap``. Raises :class:`CampaignError` naming the first divergence.
    """
    import jax

    ref = plans[0]
    ref_paths = jax.tree.structure(ref)
    ref_leaves = jax.tree.leaves(ref)
    for k, p in enumerate(plans[1:], 1):
        if jax.tree.structure(p) != ref_paths:
            raise CampaignError(
                f"{what}: lane {k}'s plan structure differs from lane 0's "
                "— the lanes disagree on a static field (shared-static-"
                "shape rule; every lane must compile to one structure)"
            )
        for a, b in zip(ref_leaves, jax.tree.leaves(p)):
            if a.shape != b.shape or a.dtype != b.dtype:
                raise CampaignError(
                    f"{what}: lane {k} materializes {b.shape}/{b.dtype} "
                    f"where lane 0 has {a.shape}/{a.dtype} — a static "
                    "shape changed across the batch"
                )


def _stack(plans: list):
    # stack_states is SwarmState-flavored in name only: it stacks any
    # list of same-structure pytrees — one stacking idiom, not two
    from tpu_gossip.core.state import stack_states

    return stack_states(plans)


def _clamped_control(spec, lo_k: int, hi_k: int):
    """A per-lane controller bound expressed over the GLOBAL spec's
    static table width: entries clamp into ``[lo_k, hi_k]``, so AIMD
    widening saturates at the lane's bound (levels past it repeat the
    bound's fanout) while the draw width — the static ``spec.hi`` — and
    the table length stay shared across the batch. The pull mix follows
    the clamped values; the stress rung keeps its anti-entropy bit."""
    import dataclasses as _dc

    import jax.numpy as jnp
    import numpy as np_

    tbl = np_.asarray(spec.fanout_table)
    clipped = np_.clip(tbl, lo_k, hi_k).astype(np_.int32)
    pull = clipped <= spec.base
    if spec.levels > (spec.hi - spec.lo + 1):  # stress rung present
        pull[-1] = True
    return _dc.replace(
        spec,
        fanout_table=jnp.asarray(clipped),
        pull_table=jnp.asarray(pull),
    )


def compile_campaign(spec: CampaignSpec):
    """Compile a validated campaign into a :class:`CompiledCampaign`.

    Builds the shared topology once (topology is campaign-static: a
    per-lane graph would move the edge count D — a static shape — so
    lane diversity comes from protocol RNG, fault parameters, traffic
    rates and controller bounds), compiles every lane's plans, unifies
    the scenario structure, enforces the shared-static-shape rule, and
    stacks everything into batched pytrees.
    """
    import jax

    from tpu_gossip.core import topology
    from tpu_gossip.core.state import SwarmConfig, init_swarm, stack_states

    b = spec.base
    n_peers = int(b.get("peers", 1000))
    rounds = int(b["rounds"])
    mode = str(b.get("mode", "push"))
    fanout = int(b.get("fanout", 3))
    attach_m = int(b.get("m", 3))
    grow = int(b.get("grow", 0))
    lanes = _sample_lanes(spec)
    k_lanes = len(lanes)

    # ------------------------------------------------ shared topology
    g_rng = np.random.default_rng(int(b.get("graph_seed", spec.seed)))
    kind = str(b.get("graph", "pa"))
    if kind == "pa":
        graph = topology.build_csr(
            n_peers,
            topology.preferential_attachment(n_peers, m=attach_m, rng=g_rng),
        )
    elif kind == "chung-lu":
        deg = topology.powerlaw_degree_sequence(
            n_peers, gamma=float(b.get("gamma", 2.5)), rng=g_rng
        )
        graph = topology.build_csr(n_peers, topology.configuration_model(
            deg, rng=g_rng))
    else:
        raise CampaignError(
            f"[base] graph {kind!r}: campaigns run the local engine over "
            "a host CSR ('pa' or 'chung-lu')"
        )

    exists = None
    growth = None
    rewire_slots = int(b.get("rewire_slots", 0))
    if grow:
        from tpu_gossip.growth import compile_growth, pad_graph_for_growth

        if grow <= n_peers:
            raise CampaignError(
                f"[base] grow {grow} must exceed peers {n_peers}"
            )
        capacity = int(b.get("grow_capacity", grow))
        if capacity < grow:
            raise CampaignError(
                f"[base] grow_capacity {capacity} below the target {grow}"
            )
        graph, exists = pad_graph_for_growth(graph, capacity)
        rewire_slots = max(rewire_slots, attach_m)
    n_slots = graph.n

    cfg = SwarmConfig(
        n_peers=n_slots,
        msg_slots=int(b.get("slots", 16)),
        fanout=fanout,
        mode=mode,
        forward_once=bool(b.get("forward_once", False)),
        sir_recover_rounds=int(b.get("sir_recover", 0)),
        churn_leave_prob=float(b.get("churn_leave", 0.0)),
        churn_join_prob=float(b.get("churn_join", 0.0)),
        rewire_slots=rewire_slots,
    )

    # ------------------------------------------------ per-lane scenarios
    from tpu_gossip.faults import compile_scenario, scenario_from_dict

    fam_dicts = {
        f.name: (
            f.scenario if isinstance(f.scenario, dict)
            else _scenario_dict(f.scenario, spec.root)
        ) if f.scenario else None
        for f in spec.families
    }
    with_s = [f for f in spec.families if f.scenario]
    if with_s and len(with_s) != len(spec.families):
        raise CampaignError(
            "families mix scenario and scenario-free lanes — the batch "
            "compiles ONE static structure; give every family a scenario "
            "(a quiescent one is free) or none"
        )
    scen_lanes = None
    max_jb = 0
    if with_s:
        import copy

        fam_by_name = {f.name: f for f in spec.families}
        scen_lanes = []
        for lane in lanes:
            sdict = copy.deepcopy(fam_dicts[lane.family])
            fam = fam_by_name[lane.family]
            for ax in fam.sweeps:
                if ax.axis.startswith("phase."):
                    _override_phases(sdict, ax, lane.sampled[ax.axis])
            try:
                sspec = scenario_from_dict(sdict)
                sspec.validate(total_rounds=rounds, n_peers=n_peers)
                if sspec.uses_join_burst and not grow:
                    raise CampaignError(
                        f"family {lane.family!r}: join_burst phases are "
                        "admission waves for a growing fleet; set [base] "
                        "grow (a lane cannot grow alone — capacity is a "
                        "static shape shared by the batch)"
                    )
                if sspec.uses_adversaries and not int(b.get("quorum_k", 0)):
                    raise CampaignError(
                        f"family {lane.family!r}: Byzantine adversary "
                        "phases (accusers/forgers/floods) need the "
                        "quorum-defense planes; set [base] quorum_k "
                        "(quorum_k = 1 reproduces the reference's "
                        "single-report purge)"
                    )
                max_jb = max(max_jb, sspec.max_join_burst)
                scen_lanes.append(compile_scenario(
                    sspec, n_peers=n_peers, n_slots=n_slots,
                    total_rounds=rounds,
                ))
            except ScenarioError as e:
                raise CampaignError(
                    f"family {lane.family!r} lane {lane.seed_index}: {e}"
                ) from None
        scen_lanes = _unify_scenarios(scen_lanes, spec.name)
        _check_lane_structures(scen_lanes, "scenario")

    # ------------------------------------------------ growth (shared plan)
    if grow:
        growth = compile_growth(
            n_initial=n_peers,
            target=grow,
            n_slots=n_slots,
            joins_per_round=int(
                b.get("grow_rate", 0)
                or max(1, -(-(grow - n_peers) // max(rounds // 2, 1)))
            ),
            attach_m=attach_m,
            max_join_burst=max_jb,
        )

    # ------------------------------------------------ per-lane streams
    from tpu_gossip.traffic import (
        StreamError, compile_stream, default_max_inject, min_feasible_ttl,
    )

    stream_lanes = None
    base_rate = float(b.get("stream_rate", 0.0))
    rate_axis = any(
        ax.axis == "stream.rate" for f in spec.families for ax in f.sweeps
    )
    if rate_axis and base_rate <= 0:
        raise CampaignError(
            "sweep axis 'stream.rate' needs a loaded [base] "
            "(stream_rate > 0) — the stream's static batch shape is "
            "shared by every lane"
        )
    slot_ttl = int(b.get("slot_ttl", 0))
    if base_rate > 0:
        feasible = min_feasible_ttl(n_peers, fanout, mode)
        if slot_ttl == 0:
            slot_ttl = 3 * feasible
        if slot_ttl < feasible:
            raise CampaignError(
                f"[base] slot_ttl {slot_ttl} below the feasible coverage "
                f"horizon (~{feasible} rounds) — every message would "
                "recycle before it could cover"
            )
        lane_rates = [
            float(lane.sampled.get("stream.rate", base_rate))
            for lane in lanes
        ]
        if min(lane_rates) < 0:
            raise CampaignError("sampled stream.rate went negative")
        origin_rows = (
            np.flatnonzero(np.asarray(exists)) if exists is not None
            else np.arange(n_peers)
        )
        # ONE static batch shape serves every sampled rate (the bench.py
        # saturation-curve pattern): max_inject pins to the largest lane
        peak = max(lane_rates)
        try:
            shared_inject = default_max_inject(peak)
            stream_lanes = [
                compile_stream(
                    rate=r,
                    msg_slots=cfg.msg_slots,
                    ttl=slot_ttl,
                    origin_rows=origin_rows,
                    origins=str(b.get("stream_origins", "uniform")),
                    k_hashes=int(b.get("stream_hashes", 1)),
                    max_inject=shared_inject,
                )
                for r in lane_rates
            ]
        except StreamError as e:
            raise CampaignError(f"[base] stream: {e}") from None
        _check_lane_structures(stream_lanes, "stream")

    # ------------------------------------------------ per-lane control
    from tpu_gossip.control import ControlError, compile_control

    control_lanes = None
    ctl_target = float(b.get("control", 0.0))
    bound_axis = any(
        ax.axis in ("control.lo", "control.hi", "control.target")
        for f in spec.families for ax in f.sweeps
    )
    if bound_axis and ctl_target <= 0:
        raise CampaignError(
            "sweep axes control.* need an active [base] controller "
            "(control = TARGET_RATIO) — the fanout table's static width "
            "is shared by every lane"
        )
    if ctl_target > 0:
        lo_b = int(b.get("control_lo", 1))
        hi_b = int(b.get("control_hi", max(2 * fanout, fanout)))
        lane_bounds = []
        for lane in lanes:
            lo_k = int(round(lane.sampled.get("control.lo", lo_b)))
            hi_k = int(round(lane.sampled.get("control.hi", hi_b)))
            if not (1 <= lo_k <= fanout <= hi_k):
                raise CampaignError(
                    f"lane {lane.index} ({lane.family!r}): sampled bounds "
                    f"[{lo_k}, {hi_k}] must satisfy 1 <= lo <= fanout "
                    f"{fanout} <= hi — the policy must express the static "
                    "rate on every lane"
                )
            lane_bounds.append((lo_k, hi_k))
        lo_g = min(lo for lo, _ in lane_bounds)
        hi_g = max(hi for _, hi in lane_bounds)
        if cfg.rewire_slots > 0 and hi_g > cfg.rewire_slots:
            raise CampaignError(
                f"controller bound hi {hi_g} exceeds the re-wiring width "
                f"rewire_slots {cfg.rewire_slots} (raise rewire_slots or "
                "narrow the sweep)"
            )
        try:
            import dataclasses as _dc

            g_spec = compile_control(
                target_ratio=ctl_target, fanout=fanout, lo=lo_g, hi=hi_g,
                refresh_every=int(b.get("refresh_every", 0)),
                ttl=slot_ttl if base_rate > 0 else 0,
            )
        except ControlError as e:
            raise CampaignError(f"[base] control: {e}") from None
        import jax.numpy as jnp

        control_lanes = []
        for lane, (lo_k, hi_k) in zip(lanes, lane_bounds):
            c = _clamped_control(g_spec, lo_k, hi_k)
            t = float(lane.sampled.get("control.target", ctl_target))
            if not (0.0 < t <= 1.0):
                raise CampaignError(
                    f"lane {lane.index}: sampled control.target {t} "
                    "outside (0, 1]"
                )
            control_lanes.append(_dc.replace(
                c, target_ratio=jnp.asarray(t, dtype=jnp.float32)
            ))
        _check_lane_structures(control_lanes, "control")

    # ------------------------------------------------ quorum detector
    liveness = None
    if int(b.get("quorum_k", 0)):
        from tpu_gossip.kernels.liveness import compile_quorum

        try:
            liveness = compile_quorum(
                quorum_k=int(b["quorum_k"]),
                window=int(b.get("suspicion_window",
                                 2 * cfg.detect_period_rounds)),
                budget=int(b.get("accusation_budget", 3)),
            )
        except ValueError as e:
            raise CampaignError(f"[base] quorum: {e}") from None
        if liveness.window < cfg.detect_period_rounds:
            raise CampaignError(
                f"[base] suspicion_window {liveness.window} is shorter "
                f"than the detector sweep period "
                f"({cfg.detect_period_rounds} rounds — the PING grace): "
                "a suspicion would expire before its probe could refute"
            )
    elif any(b.get(k) for k in ("suspicion_window", "accusation_budget")):
        raise CampaignError(
            "[base] suspicion_window/accusation_budget shape the quorum "
            "detector; set quorum_k"
        )

    # ------------------------------------------------ per-lane states
    parent = jax.random.fold_in(
        jax.random.key(spec.seed), FLEET_STREAM_SALT
    )
    n_origins = int(b.get("origins", 1))
    states = []
    for lane in lanes:
        o_rng = np.random.default_rng([spec.seed, 0x0F1E, lane.index])
        origins = o_rng.choice(
            n_peers, size=min(n_origins, n_peers), replace=False
        )
        states.append(init_swarm(
            graph, cfg,
            key=jax.random.fold_in(parent, lane.index),
            origins=origins, exists=exists,
        ))

    return CompiledCampaign(
        name=spec.name,
        k=k_lanes,
        cfg=cfg,
        rounds=rounds,
        coverage_target=float(b.get("coverage_target", 0.99)),
        target_ratio=float(b.get("target_ratio", 0.9)),
        states=stack_states(states),
        scenario=None if scen_lanes is None else _stack(scen_lanes),
        growth=None if growth is None else _stack([growth] * k_lanes),
        stream=None if stream_lanes is None else _stack(stream_lanes),
        control=None if control_lanes is None else _stack(control_lanes),
        lanes=tuple(lanes),
        families=spec.families,
        base=dict(b),
        liveness=liveness,
    )
