"""Certification reports: per-lane trajectories → fleet-level statistics.

The whole point of a campaign is error bars: one trajectory per TOML
certifies nothing. This module reduces the ``(K, rounds, ...)`` stats a
batched run produces into the certification artifacts the ROADMAP's
scenario-diversity item names:

- **reliability quantiles with bootstrap confidence intervals** per
  scenario family (the delivery-ratio frame of *Reliable Probabilistic
  Gossip*, PAPERS.md) — and per *phase-parameter bin* when a family
  sweeps a fault-phase axis, so "how does delivery degrade with loss?"
  is a curve with CIs, not an anecdote;
- **rounds-to-coverage distributions** (p50/p99 per lane, distributed
  over the family);
- a **contract-break frontier** for swept controller bounds: the
  bound value where the declared delivery-ratio target stops holding —
  the AIMD-bound question the adaptive-control plane left open.

Everything is host-side numpy over the already-fetched stats (the
sim.metrics pattern); per-lane judgments reuse
``sim.metrics.reliability_report`` verbatim, so a fleet lane and a solo
run are judged by the SAME code path.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "lane_stats",
    "campaign_report",
]

_QUANTILES = (5, 25, 50, 75, 95)
_BOOTSTRAP = 500


def lane_stats(stats, k: int):
    """Lane ``k``'s ``(rounds, ...)`` slice of batched ``(K, rounds, ...)``
    stats — the shape every sim.metrics reporting helper consumes."""
    return type(stats)(*(np.asarray(f)[k] for f in stats))


def _quantile_block(values: np.ndarray, rng: np.random.Generator) -> dict:
    """Quantiles + a bootstrap 95% CI of the mean over one lane set."""
    v = np.asarray(values, dtype=np.float64)
    boot = np.asarray([
        rng.choice(v, size=v.size, replace=True).mean()
        for _ in range(_BOOTSTRAP)
    ])
    return {
        "lanes": int(v.size),
        "mean": round(float(v.mean()), 4),
        "quantiles": {
            f"p{q:02d}": round(float(np.percentile(v, q)), 4)
            for q in _QUANTILES
        },
        "bootstrap_ci95_mean": [
            round(float(np.percentile(boot, 2.5)), 4),
            round(float(np.percentile(boot, 97.5)), 4),
        ],
    }


def _frontier(axis: str, values, ratios, target: float) -> dict:
    """The contract-break frontier of a swept controller bound: group
    lanes by bound value, mark each value held/broken by its mean
    delivery ratio vs ``target``, and report the boundary. ``found`` is
    True when the sweep actually LOCATED a break (some value breaks,
    some value holds) — a sweep that holds or breaks everywhere reports
    its one-sided truth instead of inventing a frontier."""
    values = np.asarray(values, dtype=np.float64)
    ratios = np.asarray(ratios, dtype=np.float64)
    table = []
    for v in np.unique(values):
        r = ratios[values == v]
        table.append({
            "value": round(float(v), 4),
            "lanes": int(r.size),
            "delivery_ratio_mean": round(float(r.mean()), 4),
            "holds": bool(r.mean() >= target),
        })
    breaks = [t["value"] for t in table if not t["holds"]]
    holds = [t["value"] for t in table if t["holds"]]
    # noisy few-seed sweeps can be non-monotone (a break value above a
    # holding one): first_hold is the smallest holding value ABOVE the
    # last break when one exists, else None — never a crash on a sweep
    # whose top value broke
    above = [v for v in holds if not breaks or v > max(breaks)]
    return {
        "axis": axis,
        "target_ratio": float(target),
        "per_value": table,
        "found": bool(breaks and holds),
        "last_break": max(breaks) if breaks else None,
        "first_hold": min(above) if above else None,
    }


def campaign_report(
    campaign, stats, *, bins: int = 4, bootstrap_seed: int = 0,
) -> dict:
    """The certification report of one campaign run.

    ``stats`` is :func:`~tpu_gossip.fleet.engine.run_campaign`'s batched
    stats. Per family: the per-lane reliability judgments (via
    ``sim.metrics.reliability_report`` — the exact code path a solo run
    is certified by), the family's delivery-ratio quantile block with a
    bootstrap CI, rounds-to-coverage distributions, per-bin blocks for
    each swept phase/stream axis (``bins`` equal-width bins over the
    sampled range), and the contract-break frontier for swept
    ``control.*`` axes. Deterministic: the bootstrap rng is seeded.
    """
    from tpu_gossip.sim import metrics as SM

    rng = np.random.default_rng([bootstrap_seed, campaign.k])
    per_lane = []
    for lane in campaign.lanes:
        rep = SM.reliability_report(
            lane_stats(stats, lane.index),
            target_ratio=campaign.target_ratio,
            coverage_target=campaign.coverage_target,
        )
        per_lane.append({
            "lane": lane.index,
            "family": lane.family,
            "sampled": lane.sampled,
            "delivery_ratio": rep["delivery_ratio"],
            "holds": rep["holds"],
            "messages_judged": rep["messages_judged"],
            "msgs_per_delivered_infection":
                rep["msgs_per_delivered_infection"],
            "rounds_to_coverage": rep["rounds_to_coverage"],
            "peak_coverage": rep["peak_coverage"],
        })

    families = []
    for fam in campaign.families:
        rows = [r for r in per_lane if r["family"] == fam.name]
        # a lane whose horizon judged nothing (delivery_ratio None) is
        # vacuous — excluded from the quantile math, counted explicitly
        judged = [r for r in rows if r["delivery_ratio"] is not None]
        ratios = np.asarray([r["delivery_ratio"] for r in judged])
        block = {
            "family": fam.name,
            "scenario": fam.scenario_label,
            "lanes": len(rows),
            "lanes_judged": len(judged),
            "target_ratio": campaign.target_ratio,
            "coverage_target": campaign.coverage_target,
        }
        if judged:
            rel = _quantile_block(ratios, rng)
            rel["holds_fraction"] = round(
                float(np.mean([r["holds"] for r in judged])), 4
            )
            # the certified verdict: the bootstrap CI's LOWER bound
            # clears the target — one lucky lane cannot certify a family
            rel["holds"] = bool(rel["mean"] >= campaign.target_ratio)
            rel["certified"] = bool(
                rel["bootstrap_ci95_mean"][0] >= campaign.target_ratio
            )
            block["reliability"] = rel
            p50s = [
                r["rounds_to_coverage"]["p50"] for r in judged
                if r["rounds_to_coverage"]["p50"] is not None
            ]
            p99s = [
                r["rounds_to_coverage"]["p99"] for r in judged
                if r["rounds_to_coverage"]["p99"] is not None
            ]
            block["rounds_to_coverage"] = {
                "p50_over_lanes": (
                    _quantile_block(np.asarray(p50s), rng) if p50s else None
                ),
                "p99_over_lanes": (
                    _quantile_block(np.asarray(p99s), rng) if p99s else None
                ),
            }
        sweep_blocks = []
        frontiers = []
        for ax in fam.sweeps:
            vals = np.asarray([r["sampled"][ax.axis] for r in judged])
            if not judged:
                continue
            if ax.axis.startswith("control."):
                frontiers.append(_frontier(
                    ax.axis, vals, ratios, campaign.target_ratio
                ))
                continue
            # equal-width bins over the family's realized sample range:
            # the per-phase-parameter reliability curve with CIs
            lo, hi = float(vals.min()), float(vals.max())
            edges = np.linspace(lo, hi, num=min(bins, len(judged)) + 1)
            bin_rows = []
            for i in range(len(edges) - 1):
                sel = (vals >= edges[i]) & (
                    vals <= edges[i + 1] if i == len(edges) - 2
                    else vals < edges[i + 1]
                )
                if not sel.any():
                    continue
                bin_rows.append({
                    "range": [round(float(edges[i]), 4),
                              round(float(edges[i + 1]), 4)],
                    **_quantile_block(ratios[sel], rng),
                })
            sweep_blocks.append({
                "axis": ax.axis, "dist": ax.dist, "bins": bin_rows,
            })
        if sweep_blocks:
            block["sweeps"] = sweep_blocks
        if frontiers:
            block["frontier"] = frontiers[0]
            if len(frontiers) > 1:
                # a family sweeping several control.* axes gets every
                # frontier; "frontier" stays the first axis's block
                block["frontiers"] = frontiers
        families.append(block)

    return {
        "campaign": campaign.name,
        "lanes": campaign.k,
        "rounds": campaign.rounds,
        "n_peers": int(campaign.base.get("peers", 0)),
        "families": families,
        "lanes_detail": per_lane,
    }
