"""The fleet engine: one vmapped compile runs K independent swarms.

``simulate_fleet`` is the batched twin of ``sim.engine.simulate``: a
``jax.vmap`` over the SHARED per-engine round driver
(``sim.stages.run_protocol_round`` via ``gossip_round``), scanned over a
fixed horizon. Every lane runs the full composed protocol — chaos
scenario, growth admission, streaming injection, adaptive control —
against its own stacked plan tables, and ONE compile serves all K lanes:
the lane axis is just one more array dimension to XLA, so the per-op
dispatch overhead that K serial processes pay K times is paid once
(bench.py ``fleet_1m`` records the realized swarms/sec win).

The conformance contract (tests/sim/test_fleet.py): lane k of the
batched run is BIT-IDENTICAL — full state plus every integer stat — to a
solo ``simulate`` over ``campaign.lane(k)``'s plans. This is vmap's
semantic guarantee (batching is stacking) made test-pinned: every
protocol draw happens at the same per-lane shape from the same per-lane
key, integer reductions are exact at any batching, and the compiled
plans carry no lane cross-talk. Float stats (coverage, the growth γ
track) are excluded exactly as in the local↔sharded contract — batched
float reduction order may differ by 1 ULP.

Donation: ``simulate_fleet`` DONATES its batched state like every other
jitted loop entry (the ~K×N×M pytree aliases the scan carry instead of
copying); ``run_campaign`` clones internally when asked to keep the
campaign's states reusable.
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "simulate_fleet",
    "run_campaign",
    "run_lane_solo",
    "state_digest",
    "stats_digest",
]


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "num_rounds", "liveness"),
    donate_argnames=("state",),
)
def simulate_fleet(
    state, cfg, num_rounds: int, scenario=None, growth=None, stream=None,
    control=None, liveness=None,
):
    """Run K stacked swarms ``num_rounds`` rounds in one batched program.

    ``state`` is a :func:`~tpu_gossip.core.state.stack_states` pytree
    (every leaf carries a leading lane axis); ``scenario``/``growth``/
    ``stream``/``control`` are the matching stacked compiled plans (or
    ``None`` — an absent subsystem is absent for every lane, the
    shared-static-structure rule). Returns ``(final_states, stats)``
    with every stats field shaped ``(K, num_rounds, ...)``.

    DONATES ``state`` (the ``simulate`` contract at batch rank): pass
    ``clone_state`` to keep the input alive.
    """
    from tpu_gossip.sim.engine import gossip_round

    def lane(st, sc, gr, sp, cp):
        def body(carry, _):
            return gossip_round(carry, cfg, scenario=sc, growth=gr,
                                stream=sp, control=cp, liveness=liveness)

        return jax.lax.scan(body, st, None, length=num_rounds)

    # absent plans broadcast as None (an empty pytree maps through any
    # in_axes); present plans batch on their stacked lane axis
    axes = tuple(
        None if p is None else 0
        for p in (scenario, growth, stream, control)
    )
    return jax.vmap(lane, in_axes=(0,) + axes)(
        state, scenario, growth, stream, control
    )


def run_campaign(campaign, *, keep_states: bool = True):
    """Run a :class:`~tpu_gossip.fleet.plan.CompiledCampaign` end to end.

    Returns ``(final_states, stats)`` — the batched final state and the
    ``(K, rounds, ...)`` stats the certification report
    (fleet/metrics.campaign_report) reduces. The default clones before
    the donating entry, so ``campaign.states`` stays usable afterwards
    (lane extraction, repeat runs — the bit-identity oracle's
    precondition). ``keep_states=False`` is the large-campaign path:
    the initial states are DONATED, ``campaign.states`` is replaced by
    the final states, and the campaign is marked ``consumed`` so
    ``campaign.lane()`` / :func:`run_lane_solo` refuse instead of
    silently handing out post-run state.
    """
    from tpu_gossip.core.state import clone_state

    st = clone_state(campaign.states) if keep_states else campaign.states
    fin, stats = simulate_fleet(
        st, campaign.cfg, campaign.rounds, campaign.scenario,
        campaign.growth, campaign.stream, campaign.control,
        campaign.liveness,
    )
    if not keep_states:
        campaign.states = fin  # the donated input is gone; keep the result
        campaign.consumed = True
    return fin, stats


def run_lane_solo(campaign, k: int):
    """The conformance oracle: lane ``k`` run UNBATCHED through the plain
    ``sim.engine.simulate`` over exactly the plans the batch compiled for
    it. Returns ``(final_state, stats)``; bit-identical (state + integer
    stats) to lane ``k`` of :func:`run_campaign` — test-pinned, and
    cross-checked across processes by the fleet-smoke CI digests.
    """
    from tpu_gossip.sim.engine import simulate

    st, sc, gr, sp, cp = campaign.lane(k)
    return simulate(st, campaign.cfg, campaign.rounds, None, "fused",
                    sc, gr, sp, cp, None, campaign.liveness)


def state_digest(state) -> str:
    """A platform-stable sha256 over every state leaf (PRNG keys via
    their raw key data) — the cross-process bit-identity fingerprint the
    fleet-smoke job compares between the batched run and a solo
    subprocess. Works on a solo state or one lane of a batch."""
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
            leaf.dtype, jax.dtypes.prng_key
        ):
            leaf = jax.random.key_data(leaf)
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def stats_digest(stats, k: int | None = None) -> str:
    """sha256 over the INTEGER stats tracks (the bit-exact half of the
    contract; float tracks — coverage, γ — are excluded like the
    local↔sharded matrix does). ``k`` selects one lane of batched stats.
    """
    h = hashlib.sha256()
    for name in stats._fields:
        arr = np.asarray(getattr(stats, name))
        if arr.dtype.kind not in "biu":
            continue
        if k is not None:
            arr = arr[k]
        h.update(name.encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()
