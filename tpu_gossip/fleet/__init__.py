"""Fleet engine: vmapped Monte Carlo certification campaigns.

``fleet/plan.py`` compiles a campaign TOML — base run config + sampled
axes over scenario families — into a :class:`CompiledCampaign` of K
per-swarm plans stacked into one batched pytree (shared static shapes);
``fleet/engine.py`` vmaps the shared protocol round driver over the
stack (one compile serves all K lanes, each bit-identical to its solo
run); ``fleet/metrics.py`` reduces the per-lane trajectories into
certification reports — reliability quantiles with bootstrap CIs per
scenario family, rounds-to-coverage distributions, and contract-break
frontiers for swept controller bounds. docs/fleet_campaigns.md has the
schema, the shared-static-shape rule, and the determinism contract.
"""

from tpu_gossip.core.streams import FLEET_STREAM_SALT
from tpu_gossip.fleet.engine import (
    run_campaign,
    run_lane_solo,
    simulate_fleet,
    state_digest,
    stats_digest,
)
from tpu_gossip.fleet.metrics import campaign_report, lane_stats
from tpu_gossip.fleet.plan import (
    CampaignError,
    CampaignSpec,
    CompiledCampaign,
    FamilySpec,
    SweepAxis,
    SWEEP_AXES,
    campaign_from_dict,
    compile_campaign,
    parse_campaign,
)

__all__ = [
    "FLEET_STREAM_SALT",
    "CampaignError",
    "CampaignSpec",
    "CompiledCampaign",
    "FamilySpec",
    "SweepAxis",
    "SWEEP_AXES",
    "campaign_from_dict",
    "compile_campaign",
    "parse_campaign",
    "simulate_fleet",
    "run_campaign",
    "run_lane_solo",
    "state_digest",
    "stats_digest",
    "campaign_report",
    "lane_stats",
]
