"""Word-level bit-plane algebra for packed-native rounds.

PR 15 packed the boolean message planes into LSB-first uint8 words
(core/packed.py); until now every round still unpacked them back to full
width, so the codec transient *was* the per-round memory spike. This
module is the packed-native replacement: the delivery merge, the stale
filter, the forward-once latch, and every infection/duplicate counter
run directly on the ``(N, W)`` words — OR/AND/ANDN plus
``jax.lax.population_count`` — and the full-width bool planes only ever
exist where an op genuinely needs them (the XLA push scatter, stream
injection, control feedback), decoded through ``core.packed`` at that
boundary.

Placement is load-bearing: the ``deep-transient-liveness`` taint rail
(analysis/deep/liveness.py) sanctions word-level compute on packed
planes only inside the kernel tier (``kernels/``, ``dist/``, the
matching topology) and keeps decode-to-bool-width licensed solely in
``core/packed.py`` — so the word equations live *here*, not in
``sim/engine.py``, and the rail can keep flagging stray full-width
transients elsewhere.

Two invariants every helper preserves (docs/memory_budget.md):

- **padding-always-zero**: bits ``m..8W`` of every plane stay clear, so
  OR/AND of conforming planes conforms and popcounts are exact with no
  ragged-tail mask;
- **NOT always masks**: bitwise negation is the one op that can
  manufacture padding ones, so it is only ever spelled
  ``~w & word_mask(m)`` (``not_words``/``andnot_words``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_gossip.core.packed import word_mask

__all__ = [
    "or_words",
    "and_words",
    "andnot_words",
    "not_words",
    "mask_rows",
    "mask_cols",
    "rows_any",
    "popcount_rows",
    "popcount_cols",
    "count_bits",
    "role_words",
    "pull_words",
    "gather_or_words",
]


def or_words(a: jax.Array, b: jax.Array) -> jax.Array:
    """Word-level delivery merge: ``a | b`` (conforming planes conform)."""
    return a | b


def and_words(a: jax.Array, b: jax.Array) -> jax.Array:
    """Word-level intersection: ``a & b``."""
    return a & b


def andnot_words(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a & ~b`` — the forward-once / stale-filter latch on words.

    Padding-safe without a mask: ``~b`` flips the padding bits on, but
    ``a`` honors padding-always-zero, so the AND clears them again.
    """
    return a & ~b


def not_words(a: jax.Array, m: int) -> jax.Array:
    """``~a`` with the ragged-tail padding bits re-cleared."""
    return ~a & word_mask(m)


def mask_rows(words: jax.Array, rows: jax.Array) -> jax.Array:
    """Zero whole rows: ``words & rows[:, None]`` with a bool row mask.

    Spelled as a select (structural under the taint rail) so row-level
    gating never counts as word compute anywhere it appears.
    """
    return jnp.where(rows[:, None], words, jnp.uint8(0))


def mask_cols(words: jax.Array, col_words: jax.Array) -> jax.Array:
    """AND a per-slot column mask, itself packed: ``words & col_words``.

    ``col_words`` is a conforming ``(W,)`` plane (e.g. ``pack_bits`` of
    ``~expired`` — pack after NOT, so padding stays zero).
    """
    return words & col_words[None, :]


def rows_any(words: jax.Array) -> jax.Array:
    """Bool (N,): row has any bit set — occupancy straight off the words."""
    return (words != jnp.uint8(0)).any(axis=-1)


def popcount_rows(words: jax.Array) -> jax.Array:
    """int32 (...,): per-row set-bit count, exact thanks to zero padding.

    Bit-identical to ``bools.sum(-1, dtype=int32)`` on the unpacked
    plane — the popcount replacement for every full-width boolean sum.
    """
    return jnp.sum(
        jax.lax.population_count(words), axis=-1, dtype=jnp.int32
    )


def popcount_cols(words: jax.Array) -> jax.Array:
    """int32 (W,): per-word-column set-bit totals (slot-granular stats
    still decode the column they need via ``bit_column``)."""
    return jnp.sum(
        jax.lax.population_count(words), axis=0, dtype=jnp.int32
    )


def count_bits(words: jax.Array) -> jax.Array:
    """int32 scalar: total set bits across the plane."""
    return jnp.sum(jax.lax.population_count(words), dtype=jnp.int32)


def role_words(recovered_w: jax.Array, active: jax.Array, m: int) -> jax.Array:
    """Word twin of ``compute_roles``' (N, M) masks.

    ``active[:, None] & ~recovered`` on words: transmitter and receptive
    are the same plane in the bool engine, so one call serves both.
    """
    return mask_rows(not_words(recovered_w, m), active)


def pull_words(answer_w: jax.Array, targets: jax.Array, valid: jax.Array) -> jax.Array:
    """Word twin of ``pull_fanout``: gather each peer's K partners'
    answer words and OR-reduce them.

    ``targets`` int32 (N, K), ``valid`` bool (N, K). Pure gather + OR —
    no scatter — so the pull half of push-pull never touches full width.
    """
    got = jnp.where(valid[:, :, None], answer_w[targets], jnp.uint8(0))
    return jax.lax.reduce(
        got, np.uint8(0), jax.lax.bitwise_or, dimensions=(1,)
    )


def gather_or_words(words: jax.Array, idx: jax.Array, valid: jax.Array) -> jax.Array:
    """OR-reduce a gathered word set per row: the reverse-fresh-push and
    matching-permutation merge primitive (``words[idx]`` masked by
    ``valid`` then OR-folded over the gather axis)."""
    got = jnp.where(valid[..., None], words[idx], jnp.uint8(0))
    return jax.lax.reduce(
        got, np.uint8(0), jax.lax.bitwise_or, dimensions=(got.ndim - 2,)
    )
