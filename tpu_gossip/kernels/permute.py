"""Structured-permutation passes: the gather-free data movement primitive.

Measured reality on this chip (experiments/gather_probe.py, 2026-07-30):
EVERY XLA-level gather shape — flat random, wide-slice two-step, tall
``take_along_axis``, even a pure in-register lane shuffle — runs at the same
~126M elements/s, because XLA:TPU lowers them all through one serialized
gather path. That rate is what bounds the staircase kernel's feed (40 ms of
a ~50 ms round at 1M peers, docs/kernel_profile_1m.md). Mosaic, by contrast,
compiles ``take_along_axis`` to the hardware's vreg-local ``dynamic_gather``:
strictly 8-wide on sublanes and 128-wide on lanes — useless as a general
gather, but running at ~188 G elements/s (experiments/perm_pipeline_probe.py).

This module turns that one fast primitive into bulk data movement: a
*structured permutation* is a composition of

- per-row lane shuffles (static (R,128) index tables, Pallas, VPU rate),
- full-array transposes (XLA, HBM-bandwidth rate),

which moves 8.4M int32 in ~0.4 ms — two orders of magnitude faster than any
gather XLA will emit. The matching topology (core/matching_topology.py)
CHOOSES its configuration-model stub pairing to be exactly such a
composition, so gossip delivery needs no gather at all: the reference's
per-socket send loop (reference Peer.py:395-408) becomes expand -> permute
-> reduce, all at streaming rates.

Row count is only required to be a multiple of 8 (one sublane tile): a
non-multiple of :data:`BLOCK_ROWS` is handled as one full-grid call plus a
single remainder block, so the stub array can hug the real stub count —
padding slots pair with real stubs and erase them, so the dead tail must
stay tiny (core/matching_topology.py sizes it at <= 1023 slots).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = [
    "BLOCK_ROWS",
    "lane_shuffle",
    "transpose_pass",
    "untranspose_pass",
    "transpose_pass_sharded",
    "untranspose_pass_sharded",
    "apply_pipeline",
    "inverse_tables",
    "fold_planes",
]

BLOCK_ROWS = 2048  # rows per Pallas grid step; R must be a multiple


def _shuffle_kernel(x_ref, idx_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], idx_ref[:].astype(jnp.int32), axis=1
    )


def _shuffle_call(x, idx, rows, interpret):
    return pl.pallas_call(
        _shuffle_kernel,
        grid=(x.shape[0] // rows,),
        in_specs=[
            pl.BlockSpec((rows, 128), lambda j: (j, 0)),
            pl.BlockSpec((rows, 128), lambda j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((rows, 128), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, idx)


@functools.partial(jax.jit, static_argnames=("interpret",))
def lane_shuffle(
    x: jax.Array, idx: jax.Array, *, interpret: bool | None = None
) -> jax.Array:
    """out[r, l] = x[r, idx[r, l]] — per-row 128-lane shuffle, Pallas.

    ``x`` (R, 128) int32, ``idx`` (R, 128) int32 with values in [0, 128);
    R must be a multiple of 8. Full :data:`BLOCK_ROWS` blocks go through one
    grid; a remainder tail (< BLOCK_ROWS rows) rides a second single-block
    call. Runs at VPU rate (~188 G elem/s measured) — the pass the whole
    permutation pipeline is built from.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    r = x.shape[0]
    if r % 8 != 0:
        raise ValueError(f"rows {r} not a multiple of 8")
    if idx.dtype == jnp.int8 and r % 32 != 0:
        # int8 sublane tiling is (32, 128); narrow tables require 32-row
        # granularity (matching_topology sizes large plans that way)
        raise ValueError(f"int8 index tables need rows % 32 == 0, got {r}")
    r0 = (r // BLOCK_ROWS) * BLOCK_ROWS
    parts = []
    if r0:
        parts.append(_shuffle_call(x[:r0], idx[:r0], BLOCK_ROWS, interpret))
    if r - r0:
        parts.append(_shuffle_call(x[r0:], idx[r0:], r - r0, interpret))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def transpose_pass(x: jax.Array) -> jax.Array:
    """Slot bijection: flat slot r*128+l -> l*R + r, reshaped back (R, 128).

    XLA transposes run at HBM bandwidth here (~2 TB/s effective measured),
    so this is the cheap cross-row mixing stage between lane shuffles.
    """
    r = x.shape[0]
    return x.T.reshape(r, 128)


def untranspose_pass(x: jax.Array) -> jax.Array:
    """Inverse of :func:`transpose_pass`."""
    r = x.shape[0]
    return x.reshape(128, r).T


def transpose_pass_sharded(
    x_blk: jax.Array, axis_name: str, n_shards: int
) -> jax.Array:
    """:func:`transpose_pass` under a 1-D row sharding: ONE ``all_to_all``.

    ``x_blk`` is shard s's (per, 128) row block of a global (R, 128) array,
    per = R / S, called inside ``shard_map``. Shard s of the transposed
    array holds the global flat slots [s·per·128, (s+1)·per·128) of the
    column-major flattening — i.e. lane columns [s·128/S, (s+1)·128/S) of
    the ORIGINAL array, all R rows. So the collective is: split the local
    block along LANES into S pieces, all_to_all them (shard d receives
    every shard's d-th lane piece, concatenated along rows = the full
    (R, 128/S) column slab), then a purely local transpose-reshape orders
    the slab column-major. Requires 128 % S == 0. The payload is dense and
    perfectly rectangular — no ragged-bucket padding, unlike the CSR
    bucket exchange (dist/mesh.py).
    """
    if 128 % n_shards:
        raise ValueError(f"transpose sharding needs 128 % n_shards == 0, got {n_shards}")
    per = x_blk.shape[0]
    slab = jax.lax.all_to_all(
        x_blk, axis_name, split_axis=1, concat_axis=0, tiled=True
    )  # (R, 128/S) = my lane slab of the global array
    return slab.T.reshape(per, 128)


def untranspose_pass_sharded(
    x_blk: jax.Array, axis_name: str, n_shards: int
) -> jax.Array:
    """Inverse of :func:`transpose_pass_sharded` (same collective, mirrored:
    local un-reshape back to the (R, 128/S) lane slab, then all_to_all
    splitting ROWS and concatenating lanes)."""
    if 128 % n_shards:
        raise ValueError(f"transpose sharding needs 128 % n_shards == 0, got {n_shards}")
    per = x_blk.shape[0]
    r = per * n_shards
    slab = x_blk.reshape(128 // n_shards, r).T  # (R, 128/S)
    return jax.lax.all_to_all(
        slab, axis_name, split_axis=0, concat_axis=1, tiled=True
    )


def inverse_tables(idx: jax.Array) -> jax.Array:
    """Per-row inverse permutation table, plan-time (dtype-preserving: int8
    tables quarter their HBM traffic and, at 10M scale, ~840 MB of plan
    residency — the margin between fitting in HBM and not)."""
    return jnp.argsort(idx.astype(jnp.int32), axis=1).astype(idx.dtype)


def apply_pipeline(
    x: jax.Array,
    stages: tuple,
    *,
    interpret: bool | None = None,
    axis_name: str | None = None,
    n_shards: int = 1,
) -> jax.Array:
    """Apply a permutation pipeline to slot data ``x`` (R, 128).

    ``stages`` is a tuple of ("lane", table) / ("t",) / ("tinv",) entries,
    applied left to right as DATA operations: a "lane" stage with table L
    maps out[r, l] = in[r, L[r, l]]; "t"/"tinv" are the transpose bijections
    above. The matching topology stores one pipeline whose composition IS
    the stub pairing.

    With ``axis_name`` (inside ``shard_map``), ``x`` and the lane tables
    are shard-local (per, 128) row blocks and every transpose stage runs as
    one ``all_to_all`` (:func:`transpose_pass_sharded`) — lane shuffles are
    row-local either way, so the sharded pipeline computes bit-identically
    the same global permutation.
    """
    for stage in stages:
        kind = stage[0]
        if kind == "lane":
            x = lane_shuffle(x, stage[1], interpret=interpret)
        elif kind == "t":
            x = (
                transpose_pass(x)
                if axis_name is None
                else transpose_pass_sharded(x, axis_name, n_shards)
            )
        elif kind == "tinv":
            x = (
                untranspose_pass(x)
                if axis_name is None
                else untranspose_pass_sharded(x, axis_name, n_shards)
            )
        else:  # pragma: no cover - plan construction bug
            raise ValueError(f"unknown stage kind {kind!r}")
    return x


def _fold_kernel(op: str):
    def kernel(x_ref, o_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            o_ref[:] = x_ref[:]

        @pl.when(i != 0)
        def _():
            o_ref[:] = (o_ref[:] | x_ref[:]) if op == "or" else o_ref[:] + x_ref[:]

    return kernel


def fold_planes(
    slots2d: jax.Array,
    slot_off: int,
    cstride: int,
    count: int,
    pad_deg: int,
    op: str = "or",
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """OR/sum-fold ``pad_deg`` contiguous planes of a flat slot buffer.

    out[j] = fold_i slots[slot_off + i*cstride + j], j < count — the
    matching topology's class reduction. Exists because EVERY HLO-level
    formulation of this fold (axis reduce, row indexing, slice chains,
    barriered slices) gets canonicalized by XLA:TPU into one interleaved
    [cstride, pad_deg] array whose tiny minor dim the (8, 128) tiling pads
    up to 64x — measured at 4 ms of a 6.9 ms 1M gossip round. In Pallas
    the planes stream through VMEM as natural (8, 128) blocks and the fold
    is pure vector ops. Requires ``slot_off`` and ``cstride`` multiples of
    1024 (whole blocks; matching_topology aligns populous classes so).

    The plane dimension is the MINOR grid axis over ONE operand (out block
    j revisited across i, accumulating): operand count and compile time no
    longer scale with ``pad_deg`` (the per-plane-operand formulation hit
    argument-count and compile-time walls as pad_deg grew).
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if slot_off % 1024 or cstride % 1024:
        raise ValueError("fold_planes needs 1024-aligned slot_off/cstride")
    base = slot_off // 1024
    step = cstride // 1024

    out = pl.pallas_call(
        _fold_kernel(op),
        grid=(step, pad_deg),
        in_specs=[pl.BlockSpec((8, 128), lambda j, i: (base + i * step + j, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((cstride // 128, 128), slots2d.dtype),
        interpret=interpret,
    )(slots2d)
    return out.reshape(-1)[:count]
