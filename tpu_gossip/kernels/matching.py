"""Gather-free gossip delivery over a structured-matching topology.

The round's dissemination (reference Peer.py:395-408's per-socket send loop)
becomes three streaming stages on a :class:`~tpu_gossip.core.
matching_topology.MatchingPlan`:

    expand   per-node packed words -> stub slots      (class broadcast)
    partner  slot j <- word of owner(pi(j))           (shuffle/transpose
                                                       pipeline, permute.py)
    reduce   OR slots into receivers                  (class reshape)

No gather, no scatter, no segment reduction — every pass runs at VPU or HBM
streaming rate (see permute.py's measured numbers). Sampling semantics are
the expected-``fanout`` Bernoulli-per-edge law shared by the staircase
kernel (pallas_segment.segment_sampled) and the dist engine's bucketed
exchange: per-slot uint32 thresholds gate each direction of every surviving
edge, one independent draw per direction per round. ``msgs`` accounting
matches segment_sampled's convention (delivered slot-bits per fired edge,
plus one request per fired pull edge of a receptive puller).

Interface mirrors ``segment_sampled``/``segment_or`` so the engine treats
the two kernel families interchangeably (sim/engine.py _disseminate_local).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_gossip.core.matching_topology import MatchingPlan
from tpu_gossip.kernels.pallas_segment import (
    _slot_groups,
    pack_words,
    unpack_words,
)

__all__ = ["matching_flood", "matching_sampled"]


def _pad_rows(x: jax.Array, n_state: int) -> jax.Array:
    """Pad per-node results (n, m) up to the state's row count (sentinel
    rows receive nothing)."""
    pad = n_state - x.shape[0]
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    return x


@functools.partial(jax.jit, static_argnames=("m", "interpret"))
def matching_flood(
    plan: MatchingPlan,
    transmit: jax.Array,
    m: int,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """incoming[i] = OR over neighbors j of transmit[j] — flood delivery.

    Bit-exact vs ``kernels.gossip.flood_all`` on the plan's exported CSR
    (parity-tested): the valid slot set IS the edge set.
    """
    n_state = transmit.shape[0]
    outs = []
    for lo, w in _slot_groups(m):
        words = pack_words(transmit[: plan.n, lo : lo + w])
        across = plan.partner(plan.expand(words), interpret=interpret)
        across = jnp.where(plan.valid, across, 0)
        outs.append(unpack_words(plan.reduce(across, "or"), w))
    inc = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    return _pad_rows(inc, n_state)


@functools.partial(
    jax.jit, static_argnames=("m", "do_push", "do_pull", "interpret")
)
def matching_sampled(
    plan: MatchingPlan,
    transmit: jax.Array,
    answer: jax.Array | None,
    m: int,
    key: jax.Array,
    *,
    receptive_rows: jax.Array | None = None,
    do_push: bool = True,
    do_pull: bool = False,
    interpret: bool | None = None,
    fanout: jax.Array | None = None,
    pull_gate: jax.Array | None = None,
    pull_needy_rows: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sampled (push / push-pull) delivery, gather-free.

    Same contract as ``segment_sampled`` (which documents the semantics):
    ``answer=None`` means the pull half answers with ``transmit``;
    ``receptive_rows`` (n_state,) bool gates the pull half by the puller at
    ROW level and zeroes non-receptive rows' deliveries; returns
    ``(incoming (n_state, m) bool, msgs_sent int32 scalar)``. Edge-level
    activation is drawn once and shared across 32-slot word groups.

    ``fanout`` (traced scalar, the adaptive controller's effective m —
    control/) substitutes into the push gate law ``B(fanout/deg)``: the
    thresholds are recomputed elementwise from the SAME degree tables
    with the same float arithmetic, so a traced fanout equal to the
    plan's static one yields bit-identical gates. ``pull_gate`` (traced
    bool) masks the pull activation (billing follows — a gated round
    bills no pull traffic). ``pull_needy_rows`` ((n_state,) bool) masks
    the pull activation by the PULLER's need — a sated peer issues no
    request — through the same class-expand the receptive gate rides.
    """
    if plan.fanout is None or plan.deg_other is None:
        raise ValueError("plan built without fanout — no sampling gates")
    n_state = transmit.shape[0]
    shape = (plan.rows, 128)
    k_push, k_pull = jax.random.split(key)
    msgs = jnp.zeros((), jnp.int32)
    rec_rows_n = rec_slots = None
    if receptive_rows is not None:
        rec_rows_n = receptive_rows[: plan.n]
        rec_slots = plan.expand(rec_rows_n.astype(jnp.int32)) > 0
    active_p = active_q = None
    pull_bill = None
    # gates computed elementwise from the plan's degree tables — storing
    # precomputed uint32 thresholds would cost ~450 MB at the 10M north star
    if do_push:
        active_p = (
            jax.random.bits(k_push, shape, jnp.uint32)
            < plan.push_threshold(fanout)
        )
    if do_pull:
        active_q = (
            jax.random.bits(k_pull, shape, jnp.uint32) < plan.pull_threshold()
        )
        if pull_gate is not None:
            active_q = active_q & pull_gate
        if pull_needy_rows is not None:
            active_q = active_q & (
                plan.expand(pull_needy_rows[: plan.n].astype(jnp.int32)) > 0
            )
        pull_bill = active_q.astype(jnp.int32)
    outs = []
    for lo, w in _slot_groups(m):
        tx_words = pack_words(transmit[: plan.n, lo : lo + w])
        slot_tx = plan.partner(plan.expand(tx_words), interpret=interpret)
        combined = jnp.zeros(shape, jnp.int32)
        if do_push:
            wp = jnp.where(active_p, slot_tx, 0)
            combined = combined | wp
            msgs = msgs + jnp.sum(jax.lax.population_count(wp), dtype=jnp.int32)
        if do_pull:
            slot_ans = (
                slot_tx
                if answer is None
                else plan.partner(
                    plan.expand(pack_words(answer[: plan.n, lo : lo + w])),
                    interpret=interpret,
                )
            )
            wq = jnp.where(active_q, slot_ans, 0)
            combined = combined | wq
            pull_bill = pull_bill + jax.lax.population_count(wq)
        outs.append(unpack_words(plan.reduce(combined, "or"), w))
    incoming = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    if rec_rows_n is not None:
        incoming = incoming & rec_rows_n[:, None]
    if do_pull:
        if rec_slots is not None:
            pull_bill = jnp.where(rec_slots, pull_bill, 0)
        msgs = msgs + jnp.sum(pull_bill, dtype=jnp.int32)
    return _pad_rows(incoming, n_state), msgs
