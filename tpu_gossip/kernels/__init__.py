"""Per-round batched protocol ops (the TPU replacement for socket I/O loops).

The reference executes one gossip "send" per socket per thread
(reference Peer.py:395-408, recv loops Peer.py:180,261). Here a whole round —
every peer's fan-out, dedup, and liveness bookkeeping — is a handful of
gather/scatter array ops over the CSR adjacency, jit-compiled and shardable
on the peer axis. ``gossip`` holds dissemination ops, ``liveness`` the
heartbeat/failure-detector state machine.
"""

from tpu_gossip.kernels.gossip import push_fanout, pull_fanout, flood_all
from tpu_gossip.kernels.liveness import emit_heartbeats, detect_failures

__all__ = [
    "push_fanout",
    "pull_fanout",
    "flood_all",
    "emit_heartbeats",
    "detect_failures",
]
