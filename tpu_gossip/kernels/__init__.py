"""Per-round batched protocol ops (the TPU replacement for socket I/O loops).

The reference executes one gossip "send" per socket per thread
(reference Peer.py:395-408, recv loops Peer.py:180,261). Here a whole round —
every peer's fan-out, dedup, and liveness bookkeeping — is a handful of
gather/scatter array ops over the CSR adjacency, jit-compiled and shardable
on the peer axis. ``gossip`` holds dissemination ops, ``liveness`` the
heartbeat/failure-detector state machine.
"""

from tpu_gossip.kernels.gossip import push_fanout, pull_fanout, flood_all
from tpu_gossip.kernels.liveness import emit_heartbeats, detect_failures

# NOTE: tpu_gossip.kernels.pallas_segment (StaircasePlan, plan builders,
# segment_or/segment_sampled) is deliberately NOT re-exported here — every
# consumer (sim/engine.py, cli/run_sim.py, bench.py) imports it lazily so
# the jax.experimental.pallas/.tpu stack loads only when a plan is used,
# and pure-XLA runs work even where that import can't.
__all__ = [
    "push_fanout",
    "pull_fanout",
    "flood_all",
    "emit_heartbeats",
    "detect_failures",
]
