"""Pallas staircase segment-OR: the gossip round's delivery as one TPU kernel.

The north-star formulation (BASELINE.json: "each gossip round ... runs as a
single Pallas segment-scatter kernel") replaces the reference's per-socket
send loop (reference Peer.py:395-408) with a segment reduction over the CSR:
``incoming[i] = OR_{j in N(i)} transmit[j]``. XLA's stock lowering for that
(``segment_max`` over a (D, M) gather) is slow on TPU — the reduction
serializes — so this module reformulates it for the MXU:

- Message bitmaps are PACKED into int32 words per peer: one word when
  M <= 32, else one kernel launch per 32-slot word group (the edge-level
  activation draw is shared across groups, so sampling semantics don't
  depend on M).
- Edges, already destination-grouped by the CSR, are cut into 1024-edge
  tiles that never cross an output block boundary (host-side plan, static
  per graph; block height ``rows`` is tunable — low-degree graphs want
  wider blocks, see :func:`build_staircase_plan`).
- Per tile, the kernel unpacks words into M bit-planes, builds the tile's
  "staircase" one-hot (row r vs per-edge local offset) with an iota
  compare, and contracts both on the MXU:
  ``acc[m, r] = sum_e bit_m[e] * (offs[e] == r)`` — a (M,1024)x(1024,128)
  NT matmul. Tiles of the same output block accumulate through Pallas
  output-block revisiting (the TPU grid is sequential), so the whole
  delivery is ONE kernel launch after one XLA gather of packed words.

``segment_or`` == ``kernels.gossip.flood_all`` bit-for-bit (parity-tested);
the engine uses it for flood-mode dissemination when a plan is supplied.

``segment_sampled`` runs SAMPLED delivery (push / push-pull, the headline
benchmark modes) through the same kernel: every edge slot carries a
precomputed uint32 Bernoulli threshold — ``min(1, fanout/deg(sender))`` for
push, ``1/deg(puller)`` for pull, the static-shape equivalence of exactly-k
neighbor sampling that dist/mesh.py already uses for its bucketed exchange —
and one uniform-bits draw masks the gathered words before the segment-OR.
Push and pull words are OR-combined per edge, so a push_pull round is ONE
kernel launch instead of XLA's serialized scatter + gather.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "StaircasePlan",
    "build_staircase_plan",
    "build_staircase_plan_device",
    "pack_words",
    "unpack_words",
    "segment_or",
    "segment_sampled",
    "stream_segment_or",
]

# Default output rows per block (out block last dim). Re-tuned 2026-07-30
# on the current kernel: 1024 wins at every measured scale and mode —
# 1M flood 49.3 vs 64.9 ms (old rows=128 tuning), 10M flood 617 vs 888 ms,
# 1M sampled flat across 512-2048, dist receive tables 38.7 vs 44.9 ms at
# 200k. Wider blocks cut the sequential tile grid; the MXU contraction
# stays (m, 1024) x (1024, rows).
ROWS = 1024
TILE = 1024  # edges per tile, stored (8, 128)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class StaircasePlan:
    """Static routing tables for one graph (device arrays + static sizes).

    ``push_thresh``/``pull_thresh`` (present when the plan was built with a
    ``fanout``) are per-edge-slot uint32 Bernoulli thresholds for sampled
    delivery; pad slots hold 0 (never active)."""

    tile_block: jax.Array  # int32 (T,) — output block index per tile
    first_visit: jax.Array  # int32 (T,) — 1 iff first tile of its block
    offs: jax.Array  # int32 (T*8, 128) — local row offset in [0, rows) or -1
    col_gather: jax.Array  # int32 (T*8, 128) — graph col_idx per edge slot (pad 0)
    n: int = dataclasses.field(metadata=dict(static=True))
    n_tiles: int = dataclasses.field(metadata=dict(static=True))
    n_blocks: int = dataclasses.field(metadata=dict(static=True))
    push_thresh: jax.Array | None = None  # uint32 (T*8, 128) — P(edge fires) for push
    pull_thresh: jax.Array | None = None  # uint32 (T*8, 128) — P(edge fires) for pull
    fanout: int | None = dataclasses.field(default=None, metadata=dict(static=True))
    rows: int = dataclasses.field(default=ROWS, metadata=dict(static=True))


def _pad_tiles(t: int) -> int:
    """Quantize a tile count up to ~0.8% granularity buckets.

    ``n_tiles`` is a static jit/pallas-grid parameter, so every fresh graph
    realization (new seed => slightly different tile count) would otherwise
    recompile the plan builder and the kernel. Padding tiles are inert:
    they revisit the last block with first_visit=0 and offs=-1, so the
    one-hot matches nothing and they contribute exactly zero — at <= 1/64
    (~1.6%) of the grid worst-case (the bucket is size-relative, between
    t/128 and t/64 depending on where t sits in its octave), their cost is
    noise, while same-sized graphs now share every compile (the persistent
    cache makes this cross-process). Tiny grids quantize little and may
    still recompile across seeds — they compile in under a second anyway.
    """
    b = max(1, 1 << max(0, t.bit_length() - 7))
    return -(-t // b) * b


def _bernoulli_threshold(p: np.ndarray) -> np.ndarray:
    """P(u32 < thresh) == min(p, 1) up to 2^-32 (p=1 fires with probability
    1 - 2^-32 — one silent miss per ~4e9 edge draws, immaterial)."""
    return np.minimum(np.ceil(np.clip(p, 0.0, 1.0) * 2.0**32), 2.0**32 - 1).astype(
        np.uint32
    )


def bernoulli_threshold_device(p: jax.Array) -> jax.Array:
    """Device twin of :func:`_bernoulli_threshold`, in f32 (x64 is off):
    thresholds agree with the host's f64 values to ~2^-24 relative — a
    per-edge firing-probability perturbation of < 1e-7. The clamp must be
    the largest f32 BELOW 2^32 (4294967040): f32 can't represent 2^32-1,
    and converting an out-of-range float to uint32 is
    implementation-defined in XLA (saturates here, poison under an fptoui
    lowering elsewhere). Shared by every device plan builder — the two
    kernel families' firing laws must never drift."""
    return jnp.minimum(
        jnp.ceil(jnp.clip(p, 0.0, 1.0) * jnp.float32(2.0**32)),
        jnp.float32(4294967040.0),
    ).astype(jnp.uint32)


def build_staircase_plan(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    fanout: int | None = None,
    *,
    rows: int = ROWS,
    n_tiles: int | None = None,
) -> StaircasePlan:
    """Cut the CSR's destination-grouped edges into MXU tiles (host, once).

    Every ``rows``-row output block gets >= 1 tile (so the kernel
    zero-initializes every block), and no tile spans two blocks (so
    accumulation is pure block revisiting). With ``fanout``, also precompute
    the sampled-delivery Bernoulli thresholds (enables
    :func:`segment_sampled`).

    ``rows`` trades tile count against per-tile compute: low-mean-degree
    graphs are tile-count-bound at rows=128 (a 128-row block holds ~128·d̄
    edges, far below the 1024-edge tile), so widening the block to 512 rows
    cuts the sequential grid ~4x for d̄ ≲ 2 while the MXU contraction stays
    (m, 1024) x (1024, rows). Must be a multiple of 128 (lane width).

    ``n_tiles`` forces the grid to an exact size instead of the quantized
    minimum — the SPMD fusion (dist/mesh.py build_shard_plans) needs every
    shard's plan to share one static tile count; the extra tiles are inert
    (they revisit the last block with offs=-1).
    """
    if rows % 128 != 0 or rows <= 0:
        raise ValueError(f"rows must be a positive multiple of 128, got {rows}")
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    col_idx = np.asarray(col_idx, dtype=np.int64)
    n = len(row_ptr) - 1
    n_blocks = max(1, math.ceil(n / rows))

    starts = row_ptr[np.minimum(np.arange(n_blocks) * rows, n)]
    ends = row_ptr[np.minimum((np.arange(n_blocks) + 1) * rows, n)]
    spans = ends - starts
    tiles_per_block = np.maximum(1, np.ceil(spans / TILE).astype(np.int64))
    # quantize the grid so same-sized graphs share compiles (_pad_tiles):
    # the extra tiles ride the last block with zero valid edges — tile_len
    # clips to 0, offs to -1, so they contribute nothing
    t_real = int(tiles_per_block.sum())
    T = _pad_tiles(t_real) if n_tiles is None else n_tiles
    if T < t_real:
        raise ValueError(f"n_tiles={T} below the plan's minimum {t_real}")
    tiles_per_block[-1] += T - t_real

    tile_block = np.repeat(np.arange(n_blocks, dtype=np.int32), tiles_per_block)
    first_visit = np.ones(T, dtype=np.int32)
    first_visit[1:] = tile_block[1:] != tile_block[:-1]

    # per-tile edge spans
    tile_ord = np.arange(T) - np.repeat(
        np.cumsum(tiles_per_block) - tiles_per_block, tiles_per_block
    )
    tile_start = np.repeat(starts, tiles_per_block) + tile_ord * TILE
    tile_len = np.minimum(np.repeat(ends, tiles_per_block) - tile_start, TILE)
    tile_len = np.maximum(tile_len, 0)

    # edge destination (CSR row) per edge, then per tile slot
    deg = row_ptr[1:] - row_ptr[:-1]
    dst = np.repeat(np.arange(n, dtype=np.int64), deg)
    if dst.size == 0:
        # edgeless CSR (e.g. a shard that receives nothing): every tile slot
        # is invalid, but the safe-index scheme below still reads slot 0
        dst = np.zeros(1, dtype=np.int64)
        col_idx = np.zeros(1, dtype=np.int64)

    slot = np.arange(TILE, dtype=np.int64)
    eidx = tile_start[:, None] + slot[None, :]  # (T, TILE)
    valid = slot[None, :] < tile_len[:, None]
    eidx_safe = np.where(valid, eidx, 0)
    edge_dst = dst[eidx_safe]  # CSR row (receiver) per edge slot
    offs = np.where(
        valid, edge_dst - tile_block[:, None].astype(np.int64) * rows, -1
    ).astype(np.int32)
    cols = np.where(valid, col_idx[eidx_safe], 0).astype(np.int32)

    push_thresh = pull_thresh = None
    if fanout is not None:
        # push: sender j fires each of its deg(j) out-edges w.p. fanout/deg(j)
        # (expected fanout pushes — the exactly-k twin with static shapes);
        # pull: receiver i draws each of its deg(i) in-edges w.p. 1/deg(i)
        # (expected one pull request). Same activation law as the bucketed
        # dist exchange (dist/mesh.py _exchange).
        edge_src_deg = np.where(valid, deg[col_idx[eidx_safe]], 0)
        edge_dst_deg = np.where(valid, deg[edge_dst], 0)
        with np.errstate(divide="ignore"):
            push_thresh = jnp.asarray(
                np.where(
                    valid & (edge_src_deg > 0),
                    _bernoulli_threshold(fanout / np.maximum(edge_src_deg, 1)),
                    np.uint32(0),
                ).reshape(T * 8, 128)
            )
            pull_thresh = jnp.asarray(
                np.where(
                    valid & (edge_dst_deg > 0),
                    _bernoulli_threshold(1.0 / np.maximum(edge_dst_deg, 1)),
                    np.uint32(0),
                ).reshape(T * 8, 128)
            )

    return StaircasePlan(
        tile_block=jnp.asarray(tile_block),
        first_visit=jnp.asarray(first_visit),
        offs=jnp.asarray(offs.reshape(T * 8, 128)),
        col_gather=jnp.asarray(cols.reshape(T * 8, 128)),
        n=n,
        n_tiles=T,
        n_blocks=n_blocks,
        push_thresh=push_thresh,
        pull_thresh=pull_thresh,
        fanout=fanout,
        rows=rows,
    )


@functools.partial(jax.jit, static_argnames=("n_blocks", "rows"))
def _tiles_per_block(row_ptr: jax.Array, n: int, n_blocks: int, rows: int):
    blocks = jnp.arange(n_blocks, dtype=jnp.int32)
    starts = row_ptr[jnp.minimum(blocks * rows, n)]
    ends = row_ptr[jnp.minimum((blocks + 1) * rows, n)]
    return jnp.maximum(1, -(-(ends - starts) // TILE))


@functools.partial(
    jax.jit, static_argnames=("n", "n_blocks", "n_tiles", "rows", "fanout")
)
def _plan_tables_device(
    row_ptr: jax.Array,
    col_idx: jax.Array,
    tpb: jax.Array,
    *,
    n: int,
    n_blocks: int,
    n_tiles: int,
    rows: int,
    fanout: int | None,
):
    T = n_tiles
    blocks = jnp.arange(n_blocks, dtype=jnp.int32)
    starts = row_ptr[jnp.minimum(blocks * rows, n)]
    ends = row_ptr[jnp.minimum((blocks + 1) * rows, n)]

    tile_block = jnp.repeat(blocks, tpb, total_repeat_length=T)
    first_visit = jnp.ones((T,), dtype=jnp.int32)
    first_visit = first_visit.at[1:].set(
        (tile_block[1:] != tile_block[:-1]).astype(jnp.int32)
    )
    tile_ord = jnp.arange(T, dtype=jnp.int32) - (jnp.cumsum(tpb) - tpb)[tile_block]
    tile_start = starts[tile_block] + tile_ord * TILE
    tile_len = jnp.clip(ends[tile_block] - tile_start, 0, TILE)

    deg = row_ptr[1:] - row_ptr[:-1]
    d_total = col_idx.shape[0]
    dst = jnp.repeat(
        jnp.arange(n, dtype=jnp.int32), deg, total_repeat_length=d_total
    )
    slot = jnp.arange(TILE, dtype=jnp.int32)
    eidx = tile_start[:, None] + slot[None, :]  # (T, TILE)
    valid = slot[None, :] < tile_len[:, None]
    eidx_safe = jnp.where(valid, eidx, 0)
    edge_dst = dst[eidx_safe]
    offs = jnp.where(valid, edge_dst - tile_block[:, None] * rows, -1).astype(
        jnp.int32
    )
    cols = jnp.where(valid, col_idx[eidx_safe], 0).astype(jnp.int32)

    push_thresh = pull_thresh = None
    if fanout is not None:
        thresh = bernoulli_threshold_device
        src_deg = jnp.where(valid, deg[col_idx[eidx_safe]], 0)
        dst_deg = jnp.where(valid, deg[edge_dst], 0)
        push_thresh = jnp.where(
            valid & (src_deg > 0),
            thresh(fanout / jnp.maximum(src_deg, 1).astype(jnp.float32)),
            jnp.uint32(0),
        ).reshape(T * 8, 128)
        pull_thresh = jnp.where(
            valid & (dst_deg > 0),
            thresh(1.0 / jnp.maximum(dst_deg, 1).astype(jnp.float32)),
            jnp.uint32(0),
        ).reshape(T * 8, 128)

    return (
        tile_block,
        first_visit,
        offs.reshape(T * 8, 128),
        cols.reshape(T * 8, 128),
        push_thresh,
        pull_thresh,
    )


def build_staircase_plan_device(
    row_ptr: jax.Array,
    col_idx: jax.Array,
    fanout: int | None = None,
    *,
    rows: int = ROWS,
) -> StaircasePlan:
    """Device-side twin of :func:`build_staircase_plan`.

    The host build moves the whole CSR device→host and the finished tables
    host→device (~620 MB at 10M peers — ~90 s over a tunneled link); here
    every table is computed where the CSR already lives and only ONE scalar
    (the tile count, which sizes the static shapes) crosses to the host.
    Routing tables match the host build exactly (parity-tested); Bernoulli
    thresholds agree to f32 rounding (~2^-24 relative — the host computes
    them in f64). int32 indices throughout — fine to ~2^31 edge slots.
    """
    if rows % 128 != 0 or rows <= 0:
        raise ValueError(f"rows must be a positive multiple of 128, got {rows}")
    row_ptr = jnp.asarray(row_ptr, dtype=jnp.int32)
    col_idx = jnp.asarray(col_idx, dtype=jnp.int32)
    n = int(row_ptr.shape[0]) - 1
    n_blocks = max(1, math.ceil(n / rows))
    tpb = _tiles_per_block(row_ptr, n, n_blocks, rows)
    t_real = int(jnp.sum(tpb))  # the one host sync
    # same grid quantization as the host build (_pad_tiles): padding tiles
    # ride the last block with tile_len 0, so they are inert — and n_tiles
    # stops varying per graph realization, which is what lets the jit
    # below (and the kernel) hit the compilation cache across seeds
    n_tiles = _pad_tiles(t_real)
    tpb = tpb.at[-1].add(n_tiles - t_real)
    tile_block, first_visit, offs, cols, push_thresh, pull_thresh = (
        _plan_tables_device(
            row_ptr, col_idx, tpb,
            n=n, n_blocks=n_blocks, n_tiles=n_tiles, rows=rows, fanout=fanout,
        )
    )
    return StaircasePlan(
        tile_block=tile_block,
        first_visit=first_visit,
        offs=offs,
        col_gather=cols,
        n=n,
        n_tiles=n_tiles,
        n_blocks=n_blocks,
        push_thresh=push_thresh,
        pull_thresh=pull_thresh,
        fanout=fanout,
        rows=rows,
    )


def pack_words(bitmap: jax.Array) -> jax.Array:
    """(N, M<=32) bool -> (N,) int32, bit m = slot m."""
    m = bitmap.shape[1]
    if m > 32:
        raise ValueError(f"msg_slots={m} exceeds the 32-bit packing width")
    weights = (1 << jnp.arange(m, dtype=jnp.int32))[None, :]
    return jnp.sum(bitmap.astype(jnp.int32) * weights, axis=1, dtype=jnp.int32)


def _slot_groups(m: int) -> list[tuple[int, int]]:
    """[(lo, width), ...] cutting M slots into <=32-bit word groups."""
    return [(lo, min(32, m - lo)) for lo in range(0, m, 32)]


def unpack_words(words: jax.Array, m: int) -> jax.Array:
    """(N,) int32 -> (N, m) bool."""
    return ((words[:, None] >> jnp.arange(m, dtype=jnp.int32)[None, :]) & 1).astype(bool)


def _tile_contract_accumulate(
    m: int, rows: int, fv_ref, offs_ref, vals_ref, bill_ref, out_ref
):
    """The ONE staircase tile computation (shared by every kernel variant):
    unpack bit planes, build the iota one-hot, contract on the MXU, and
    zero-init / accumulate the output block by first-visit. With
    ``bill_ref``, one extra contraction plane segment-sums per-edge counts
    on the same matmul (see the bill-exactness note on
    :func:`segment_sampled`)."""
    t = pl.program_id(0)
    offs = offs_ref[:].reshape(1, TILE)  # (1, 1024)
    words = vals_ref[:].reshape(1, TILE)
    planes = [((words >> s) & 1).astype(jnp.float32) for s in range(m)]
    if bill_ref is not None:
        planes.append(bill_ref[:].reshape(1, TILE).astype(jnp.float32))
    bits = jnp.concatenate(planes, axis=0)  # (m [+1], 1024)
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (rows, TILE), 0) == offs
    ).astype(jnp.float32)  # (rows, 1024); offs=-1 matches nothing
    acc = jax.lax.dot_general(
        bits, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (m [+1], rows)

    @pl.when(fv_ref[t] == 1)
    def _():
        out_ref[0] = acc

    @pl.when(fv_ref[t] == 0)
    def _():
        out_ref[0] = out_ref[0] + acc


def _kernel(m: int, rows: int, billed: bool):
    """Staircase tile kernel over gathered edge arrays (col_gather feed)."""

    def kernel(tb_ref, fv_ref, offs_ref, vals_ref, *rest):
        bill_ref, out_ref = rest if billed else (None, rest[0])
        del tb_ref  # consumed by the output index map only
        _tile_contract_accumulate(
            m, rows, fv_ref, offs_ref, vals_ref, bill_ref, out_ref
        )

    return kernel


def _launch(
    plan: StaircasePlan,
    vals: jax.Array,
    m: int,
    interpret: bool | None,
    bill: jax.Array | None = None,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Run the staircase kernel over pre-gathered per-edge words
    ``vals`` (T*8, 128) int32 → (N, m) bool segment-OR by destination row.

    With ``bill`` (per-edge int32 counts, same layout), also returns the
    per-row segment-SUM of those counts as an (N,) f32 array — one extra
    contraction plane, no extra launch. Runs standalone or per shard inside
    ``shard_map`` (dist/mesh.py, which must pass ``check_vma=False``: the
    scalar-prefetch index maps mix shard-varying tables with the loop
    index, which JAX's varying-axes tracker cannot type)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    rows = plan.rows
    billed = bill is not None
    mm = m + 1 if billed else m
    edge_spec = pl.BlockSpec((8, 128), lambda t, tb, fv: (t, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(plan.n_tiles,),
        in_specs=[edge_spec] * (3 if billed else 2),
        out_specs=pl.BlockSpec((1, mm, rows), lambda t, tb, fv: (tb[t], 0, 0)),
    )
    args = (plan.tile_block, plan.first_visit, plan.offs, vals) + (
        (bill,) if billed else ()
    )
    out = pl.pallas_call(
        _kernel(m, rows, billed),
        out_shape=jax.ShapeDtypeStruct((plan.n_blocks, mm, rows), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*args)
    # (NB, mm, rows) -> (NB*rows, mm) rows-major, trim padding rows
    flat = out.transpose(0, 2, 1).reshape(plan.n_blocks * rows, mm)
    inc = flat[: plan.n, :m] > 0.5
    if billed:
        return inc, flat[: plan.n, m]
    return inc


def _stream_kernel(m: int, rows: int):
    """Staircase tile kernel with a prefetched WINDOW table: tile t reads
    its 1024 words from aligned window ``wi[t]`` of a flat value stream
    instead of from a gathered edge array — the zero-gather receive path
    (dist/mesh.py): dest-sorted bucket runs are streamed straight out of the
    ``all_to_all`` result, and ``offs`` masks the window positions outside
    the tile's (block, run) segment with -1."""

    def kernel(tb_ref, fv_ref, wi_ref, offs_ref, vals_ref, out_ref):
        del tb_ref, wi_ref  # consumed by the index maps only
        _tile_contract_accumulate(
            m, rows, fv_ref, offs_ref, vals_ref, None, out_ref
        )

    return kernel


def stream_segment_or(
    tile_block: jax.Array,
    first_visit: jax.Array,
    window_idx: jax.Array,
    offs: jax.Array,
    vals_flat: jax.Array,
    m: int,
    *,
    n: int,
    n_tiles: int,
    n_blocks: int,
    rows: int = ROWS,
    interpret: bool | None = None,
) -> jax.Array:
    """Segment-OR over a FLAT packed-word stream with per-tile windows.

    ``vals_flat`` (L,) int32 with L a multiple of 1024; tile t consumes
    words [1024*window_idx[t], 1024*(window_idx[t]+1)) — no gather anywhere.
    ``offs`` (T*8, 128) holds each window position's destination row offset
    within the tile's output block, or -1 for positions outside the tile's
    segment. Returns (n, m) bool."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    vals2d = vals_flat.reshape(-1, 128)
    edge_spec = pl.BlockSpec((8, 128), lambda t, tb, fv, wi: (t, 0))
    vals_spec = pl.BlockSpec((8, 128), lambda t, tb, fv, wi: (wi[t], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_tiles,),
        in_specs=[edge_spec, vals_spec],
        out_specs=pl.BlockSpec(
            (1, m, rows), lambda t, tb, fv, wi: (tb[t], 0, 0)
        ),
    )
    out = pl.pallas_call(
        _stream_kernel(m, rows),
        out_shape=jax.ShapeDtypeStruct((n_blocks, m, rows), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tile_block, first_visit, window_idx, offs, vals2d)
    flat = out.transpose(0, 2, 1).reshape(n_blocks * rows, m)
    return flat[:n, :m] > 0.5


@functools.partial(jax.jit, static_argnames=("m", "interpret"))
def segment_or(
    plan: StaircasePlan, transmit: jax.Array, m: int, *, interpret: bool | None = None
) -> jax.Array:
    """incoming[i] = OR over CSR neighbors j of transmit[j] — flood delivery.

    ``transmit``: (N, m) bool. One XLA gather (packed words along the edge
    tiles) + one Pallas launch per 32-slot word group (one launch when
    ``m <= 32``). Bit-exact vs ``kernels.gossip.flood_all``.
    """
    outs = []
    for lo, w in _slot_groups(m):
        vals = pack_words(transmit[:, lo : lo + w])[plan.col_gather]
        outs.append(_launch(plan, vals, w, interpret))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)


@functools.partial(jax.jit, static_argnames=("m", "do_push", "do_pull", "interpret"))
def segment_sampled(
    plan: StaircasePlan,
    transmit: jax.Array,
    answer: jax.Array | None,
    m: int,
    key: jax.Array,
    *,
    receptive_rows: jax.Array | None = None,
    do_push: bool = True,
    do_pull: bool = False,
    interpret: bool | None = None,
    fanout: jax.Array | None = None,
    pull_gate: jax.Array | None = None,
    pull_needy_rows: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sampled (push / push-pull) delivery as ONE staircase kernel launch.

    Per-edge Bernoulli activation (thresholds precomputed in the plan; one
    independent uint32 draw per direction per edge slot) masks the gathered
    packed words; push and pull words are OR-combined so the MXU contraction
    runs once. ``answer=None`` means the pull half answers with ``transmit``
    (the usual non-forward_once case) and skips the second pack+gather.
    ``receptive_rows`` (N,) bool gates the PULL half by the puller: a dead
    or fully-removed peer asks nobody — matching the XLA path's ``pull_ok``
    gate. The gate is applied at ROW level (delivery mask on ``incoming``
    plus a row mask on the kernel's segment-summed pull bill), never per
    edge — callers that inspect raw ``incoming`` should note a
    non-receptive row is fully zeroed, including push deliveries the XLA
    path would leave for downstream masking; the engine's ``advance_round``
    masks both identically. Returns ``(incoming (N, m) bool, msgs_sent
    scalar)`` where msgs counts delivered slot-bits per active edge plus
    one request per active pull edge of a receptive puller (the XLA path's
    accounting in expectation).

    Sampling semantics are expected-``fanout`` Bernoulli per edge, not
    exactly-``fanout`` — identical to the dist engine's bucketed exchange
    (dist/mesh.py), and statistically indistinguishable on coverage curves
    (tests/unit/test_pallas_segment.py bounds the discrepancy).

    Bill exactness: the pull bill is segment-summed in f32 (one extra MXU
    contraction plane), exact while every row's partial sum stays < 2^24.
    The per-edge bill is at most ``1 + 32*ceil(m/32)``, and a row is billed
    only for FIRED in-edges; the plan's pull thresholds are exactly
    ``1/deg(dst)``, so a row's fired count is Binomial(deg, 1/deg) — mean 1
    regardless of degree (hubs fire each edge proportionally less often).
    Making the sum inexact therefore needs ``k = 2^24/(33*ceil(m/32))``
    simultaneous fires of a mean-1 variable (k ~ 5*10^5 at m=16): tail
    probability below (e/k)^k, i.e. zero for every physical ``m`` and
    degree. This exactness argument leans on the 1/deg law — a future
    builder wiring different pull thresholds must re-derive the bound
    (m * max_in_degree enters deterministically there).
    """
    if plan.push_thresh is None:
        raise ValueError("plan built without fanout — no sampling thresholds")
    if m > 2**18:
        # keeps the documented bill-exactness tail bound meaningful
        # (k >= 2^24/(33*ceil(m/32)) must stay astronomically improbable)
        raise ValueError(f"msg_slots={m} out of the supported range (<= 2^18)")
    shape = plan.col_gather.shape
    k_push, k_pull = jax.random.split(key)
    msgs = jnp.zeros((), jnp.int32)
    # edge-level activation is drawn ONCE and shared across all word groups:
    # an edge either fires this round or not, regardless of how many 32-slot
    # words the bitmap spans. receptive gating is NOT applied per edge (that
    # was a 6M-element random gather costing more than the rest of the round,
    # ~76 ms of a 127 ms round at 1M peers): deliveries are row-masked after
    # the kernel — equivalent, since the engine's advance_round applies the
    # stricter per-slot receptive mask — and pull billing is segment-summed
    # per puller row by an extra contraction plane, then masked by the same
    # row predicate, so msgs accounting still matches the XLA path.
    active_p = active_q = None
    pull_bill = None
    if do_push:
        # an adaptive controller's traced effective fanout (control/)
        # rescales the precomputed thresholds multiplicatively; the select
        # keeps the baseline table bit-exact when the round runs at the
        # plan's static fanout (the zero-adjustment identity). The scaled
        # branch rounds through float32 — a <2^-24 relative probability
        # error on an approximate Bernoulli law (the staircase engine has
        # no bit-identity twin; the matching family recomputes exactly)
        pt = plan.push_thresh
        if fanout is not None:
            scale = fanout.astype(jnp.float32) / jnp.float32(plan.fanout)
            scaled = jnp.minimum(
                pt.astype(jnp.float32) * scale, jnp.float32(2**32 - 2**8)
            ).astype(jnp.uint32)
            pt = jnp.where(fanout == plan.fanout, pt, scaled)
        active_p = jax.random.bits(k_push, shape, jnp.uint32) < pt
    if do_pull:
        active_q = jax.random.bits(k_pull, shape, jnp.uint32) < plan.pull_thresh
        if pull_gate is not None:
            active_q = active_q & pull_gate
        # one request per fired pull edge, billed to the puller (the edge's
        # destination row); the pulled bits are added per group below
        pull_bill = active_q.astype(jnp.int32)
    groups = _slot_groups(m)
    outs = []
    bill_row = None
    for gi, (lo, w) in enumerate(groups):
        w_push = pack_words(transmit[:, lo : lo + w])[plan.col_gather]
        combined = jnp.zeros(shape, jnp.int32)
        if do_push:
            wp = jnp.where(active_p, w_push, 0)
            combined = combined | wp
            msgs = msgs + jnp.sum(jax.lax.population_count(wp), dtype=jnp.int32)
        if do_pull:
            w_ans = (
                w_push if answer is None
                else pack_words(answer[:, lo : lo + w])[plan.col_gather]
            )
            wq = jnp.where(active_q, w_ans, 0)
            combined = combined | wq
            pull_bill = pull_bill + jax.lax.population_count(wq)
        if do_pull and gi == len(groups) - 1:
            # the bill is complete only after the LAST group's popcount, so
            # it rides that group's launch (also lets XLA free each group's
            # combined buffer before the next is built)
            inc, bill_row = _launch(plan, combined, w, interpret, bill=pull_bill)
        else:
            inc = _launch(plan, combined, w, interpret)
        outs.append(inc)
    incoming = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    if receptive_rows is not None:
        incoming = incoming & receptive_rows[:, None]
    if do_pull:
        # per-row f32 sums are exact (<< 2^24 per row); round to int before
        # the global sum so the total stays exact past 2^24
        billed = jnp.round(bill_row).astype(jnp.int32)
        if receptive_rows is not None:
            billed = jnp.where(receptive_rows, billed, 0)
        if pull_needy_rows is not None:
            # needy-pull gate (control/): a sated puller issues no request
            # — billed at row level like the receptive gate. Its edges'
            # pull DELIVERIES still merge (a per-edge puller gather is the
            # documented 6M-element cost this kernel avoids), which is
            # state-exact: a sated row has every live bit the answer
            # could carry.
            billed = jnp.where(pull_needy_rows, billed, 0)
        msgs = msgs + jnp.sum(billed, dtype=jnp.int32)
    return incoming, msgs
