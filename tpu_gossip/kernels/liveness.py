"""Vectorized liveness protocol: heartbeats, staleness probe, dead declaration.

The reference runs this as wall-clock threads per peer: a 15 s heartbeat
broadcaster (reference Peer.py:365-393), and a 10 s failure-detector sweep
that marks a peer stale after 30 s, sends "PING", waits a 2 s grace, then
declares it dead (Peer.py:298-363). Silent mode (operator types "1",
Peer.py:437-439) suppresses heartbeats and PING replies without closing
sockets — the fault the detector is built to catch.

Round-based mapping (1 round = SwarmConfig.round_seconds, default 5 s):
heartbeat every ``hb_period_rounds`` (3 ≡ 15 s), stale after
``timeout_rounds`` (6 ≡ 30 s ≈ "3 missed heartbeats", BASELINE.json
config 2), detector sweep every ``detect_period_rounds`` (2 ≡ 10 s). The
PING + grace-wait is collapsed into the sweep: a responsive stale peer
refreshes its heartbeat (exactly the reference's "heartbeat during the
grace wait revives the node", Peer.py:309,339); an unresponsive one is
declared dead, the vectorized form of the registry purge (Seed.py:358-406).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["emit_heartbeats", "detect_failures"]


def emit_heartbeats(
    last_hb: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    declared_dead: jax.Array,
    rnd: jax.Array,
    hb_period_rounds: int,
) -> jax.Array:
    """Refresh ``last_hb`` for every peer emitting a heartbeat this round.

    Crashed (``~alive``) and silenced peers emit nothing (Peer.py:367);
    declared-dead peers have had their connections closed (Peer.py:314-320),
    so their heartbeats no longer reach anyone.
    """
    from tpu_gossip.core.state import saturate_round

    tick = (rnd % hb_period_rounds) == 0
    emit = alive & ~silent & ~declared_dead & tick
    # the stored heartbeat round narrows to the plane's declared int16
    # width (saturated at ROUND_CAP); staleness arithmetic below reads it
    # back at int32 promotion
    return jnp.where(emit, saturate_round(rnd, last_hb.dtype), last_hb)


def detect_failures(
    last_hb: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    declared_dead: jax.Array,
    rnd: jax.Array,
    timeout_rounds: int,
    detect_period_rounds: int,
) -> tuple[jax.Array, jax.Array]:
    """One failure-detector sweep; returns ``(last_hb, declared_dead)``.

    On sweep rounds, stale peers (no heartbeat for > ``timeout_rounds``) are
    probed: a responsive peer (alive, not silent) answers with a heartbeat
    (Peer.py:201-205) which refreshes ``last_hb``; an unresponsive one is
    declared dead — the batched equivalent of "Dead Node" reporting + purge
    (Peer.py:310-320 → Seed.py:358-406). Idempotent on already-dead peers,
    mirroring the seeds' early return on re-receipt (Seed.py:373-375).
    """
    from tpu_gossip.core.state import saturate_round

    sweep = (rnd % detect_period_rounds) == 0
    stale = (rnd - last_hb) > timeout_rounds  # graftlint: disable=mem-widening-cast -- transient staleness staging: the stored plane stays int16; the age subtraction must ride the wide round cursor so runs past ROUND_CAP degrade by saturation, not wraparound
    responsive = alive & ~silent
    new_last = jnp.where(
        sweep & stale & responsive, saturate_round(rnd, last_hb.dtype),
        last_hb,
    )
    newly_dead = sweep & stale & ~responsive & ~declared_dead
    return new_last, declared_dead | newly_dead
