"""Vectorized liveness protocol: heartbeats, staleness probe, dead declaration.

The reference runs this as wall-clock threads per peer: a 15 s heartbeat
broadcaster (reference Peer.py:365-393), and a 10 s failure-detector sweep
that marks a peer stale after 30 s, sends "PING", waits a 2 s grace, then
declares it dead (Peer.py:298-363). Silent mode (operator types "1",
Peer.py:437-439) suppresses heartbeats and PING replies without closing
sockets — the fault the detector is built to catch.

Round-based mapping (1 round = SwarmConfig.round_seconds, default 5 s):
heartbeat every ``hb_period_rounds`` (3 ≡ 15 s), stale after
``timeout_rounds`` (6 ≡ 30 s ≈ "3 missed heartbeats", BASELINE.json
config 2), detector sweep every ``detect_period_rounds`` (2 ≡ 10 s). The
PING + grace-wait is collapsed into the sweep: a responsive stale peer
refreshes its heartbeat (exactly the reference's "heartbeat during the
grace wait revives the node", Peer.py:309,339); an unresponsive one is
declared dead, the vectorized form of the registry purge (Seed.py:358-406).

QUORUM HARDENING (docs/adversarial_model.md): the reference's seeds purge
a peer on a SINGLE "Dead Node" report (Seed.py:358-406 trusts the first
reporter), so one lying peer can evict any healthy node, and an
unauthenticated heartbeat relay keeps a dead one alive. The hardened
detector (:class:`QuorumSpec`, :func:`quorum_liveness`) replaces the
direct stale→PING→dead latch with a witness-quorum suspicion machine:

    alive --stale on a sweep--> suspected --quorum_k distinct witness
    confirmations inside a ``window``-round refutation window--> dead

A suspected peer that answers its probe (the probe carries a nonce, so a
third-party forgery cannot answer it) refutes: suspicion clears, votes
reset, and every accusation the refutation exposes as false charges a
STRIKE against its accuser — ``budget`` strikes latch the accuser into
``quarantine`` (sends masked, accusations ignored, rewire slots released
through the degree-credit book balance). On a healthy sweep the whole
live witness cohort confirms a genuinely-stale suspect at once, so for
any ``quorum_k`` up to the live witness count the hardened detector
declares on the SAME sweep the direct detector would — quorum costs no
detection latency (tests/conformance/test_liveness_band.py pins it), and
``quorum_k=1`` with no adversaries reproduces the direct detector's
trajectory bit for bit.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "SUSPECT_VOTE_CAP",
    "SUSPECT_STRIKE_CAP",
    "QuorumSpec",
    "LivenessTelemetry",
    "compile_quorum",
    "pack_suspicion",
    "unpack_suspicion",
    "emit_heartbeats",
    "detect_failures",
    "forge_heartbeats",
    "quorum_liveness",
]


class LivenessTelemetry(NamedTuple):
    """Per-round hardened-detector counters for RoundStats (scalar i32)."""

    evictions_new: jax.Array  # dead declarations this round
    false_evictions: jax.Array  # of those, victims that were responsive
    adv_accusations: jax.Array  # false dead-verdicts emitted this round
    adv_forged: jax.Array  # forged heartbeats emitted this round

# suspect_mark packing (core/state.py PLANES): votes in the low 8 bits
# (saturating), strikes in the high 7 — max packed value 255 + 256*127 =
# 32767, exactly int16's ceiling, so the packed plane can never overflow
SUSPECT_VOTE_CAP = 255
SUSPECT_STRIKE_CAP = 127


@dataclasses.dataclass(frozen=True)
class QuorumSpec:
    """Compiled quorum-detector contract (jit-static, hashable).

    ``quorum_k`` distinct witness confirmations — counted within ONE
    round, where every voter emits at most once, and stored as the
    suspicion's high-water cohort (max, never sum: a lone repeat
    accuser cannot add itself up past the quorum) — declare a suspect
    dead; ``window`` bounds how long a suspicion may wait for a
    quorum-sized cohort before it expires (stale accusations cannot
    slow-roll an eviction across the whole run); ``budget`` is the
    false-accusation count that latches an
    accuser into quarantine (0 disables quarantine). ``quorum_k=1``
    degrades to the reference's single-report purge — with no adversaries
    it reproduces the direct detector bit for bit (test-pinned), which is
    the determinism anchor every stronger setting is measured against.
    """

    quorum_k: int = 1
    window: int = 4
    budget: int = 3

    def __post_init__(self):
        if not 1 <= self.quorum_k <= SUSPECT_VOTE_CAP:
            raise ValueError(
                f"quorum_k must lie in [1, {SUSPECT_VOTE_CAP}] (the packed "
                f"vote counter saturates there); got {self.quorum_k}"
            )
        if self.window < 1:
            raise ValueError(f"suspicion window must be >= 1 round; got "
                             f"{self.window}")
        if not 0 <= self.budget <= SUSPECT_STRIKE_CAP:
            raise ValueError(
                f"accusation budget must lie in [0, {SUSPECT_STRIKE_CAP}] "
                f"(the packed strike counter saturates there); got "
                f"{self.budget}"
            )


def compile_quorum(
    quorum_k: int = 1, window: int = 4, budget: int = 3
) -> QuorumSpec:
    """Validate + freeze a quorum-detector spec (see QuorumSpec)."""
    return QuorumSpec(quorum_k=quorum_k, window=window, budget=budget)


def pack_suspicion(votes: jax.Array, strikes: jax.Array) -> jax.Array:
    """votes (<= 255) + strikes (<= 127) -> the packed int16 plane."""
    return (votes + 256 * strikes).astype(jnp.int16)


def unpack_suspicion(mark: jax.Array) -> tuple[jax.Array, jax.Array]:
    """The packed plane -> (votes, strikes), both int32 for arithmetic."""
    m = mark.astype(jnp.int32)  # graftlint: disable=mem-widening-cast -- transient unpack staging: the STORED plane stays the packed int16; vote/strike arithmetic (adding the witness-cohort count, an int32 scalar) must run wide before re-packing saturates it back down
    return m % 256, m // 256


def emit_heartbeats(
    last_hb: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    declared_dead: jax.Array,
    rnd: jax.Array,
    hb_period_rounds: int,
) -> jax.Array:
    """Refresh ``last_hb`` for every peer emitting a heartbeat this round.

    Crashed (``~alive``) and silenced peers emit nothing (Peer.py:367);
    declared-dead peers have had their connections closed (Peer.py:314-320),
    so their heartbeats no longer reach anyone.
    """
    from tpu_gossip.core.state import saturate_round

    tick = (rnd % hb_period_rounds) == 0
    emit = alive & ~silent & ~declared_dead & tick
    # the stored heartbeat round narrows to the plane's declared int16
    # width (saturated at ROUND_CAP); staleness arithmetic below reads it
    # back at int32 promotion
    return jnp.where(emit, saturate_round(rnd, last_hb.dtype), last_hb)


def detect_failures(
    last_hb: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    declared_dead: jax.Array,
    rnd: jax.Array,
    timeout_rounds: int,
    detect_period_rounds: int,
) -> tuple[jax.Array, jax.Array]:
    """One failure-detector sweep; returns ``(last_hb, declared_dead)``.

    On sweep rounds, stale peers (no heartbeat for > ``timeout_rounds``) are
    probed: a responsive peer (alive, not silent) answers with a heartbeat
    (Peer.py:201-205) which refreshes ``last_hb``; an unresponsive one is
    declared dead — the batched equivalent of "Dead Node" reporting + purge
    (Peer.py:310-320 → Seed.py:358-406). Idempotent on already-dead peers,
    mirroring the seeds' early return on re-receipt (Seed.py:373-375).
    """
    from tpu_gossip.core.state import saturate_round

    sweep = (rnd % detect_period_rounds) == 0
    stale = (rnd - last_hb) > timeout_rounds  # graftlint: disable=mem-widening-cast -- transient staleness staging: the stored plane stays int16; the age subtraction must ride the wide round cursor so runs past ROUND_CAP degrade by saturation, not wraparound
    responsive = alive & ~silent
    new_last = jnp.where(
        sweep & stale & responsive, saturate_round(rnd, last_hb.dtype),
        last_hb,
    )
    newly_dead = sweep & stale & ~responsive & ~declared_dead
    return new_last, declared_dead | newly_dead


def forge_heartbeats(
    last_hb: jax.Array,
    suspect_round: jax.Array,
    forger_ok: jax.Array,
    rnd: jax.Array,
    k_forge: jax.Array,
    fanout_now: jax.Array,
    max_fanout: int,
) -> tuple[jax.Array, jax.Array]:
    """Forgery attack: adversary rows emit heartbeats ON BEHALF of other
    peers, stalling the detector (the reference's heartbeat plane carries
    no sender authentication — Peer.py:201-205 trusts the socket line).

    Each row in ``forger_ok`` (phase forger mask ∧ alive ∧ not declared ∧
    not quarantined — a quarantined forger's sends are masked) forges
    ``fanout_now`` (traced, ≤ static ``max_fanout``) heartbeats at
    uniformly sampled targets from the adversary stream. A forged
    heartbeat refreshes the target's ``last_hb`` — delaying suspicion
    ENTRY of a genuinely dead peer — but cannot answer an ACTIVE
    suspicion's probe (the probe carries a nonce only the real peer can
    echo, the standard anti-spoofing assumption the quorum machine is
    built on), so suspected targets are never refreshed: once a dead
    peer's staleness slips through the forgers' sampling, detection
    proceeds. Returns ``(last_hb, n_forged)`` — the sends are billed by
    the caller's telemetry.
    """
    from tpu_gossip.core.state import saturate_round

    n = last_hb.shape[0]
    tgt = jax.random.randint(k_forge, (n, max_fanout), 0, n)
    act = (
        forger_ok[:, None]
        & (jnp.arange(max_fanout)[None, :] < fanout_now)
    )
    # a suspected target's probe cannot be answered by a third party —
    # forged refreshes land only pre-suspicion
    landed = act & (suspect_round[tgt] < 0)
    new_last = last_hb.at[jnp.where(landed, tgt, n).reshape(-1)].max(
        saturate_round(rnd, last_hb.dtype), mode="drop"
    )
    return new_last, jnp.sum(act, dtype=jnp.int32)


def quorum_liveness(
    spec: QuorumSpec,
    last_hb: jax.Array,
    alive: jax.Array,
    silent: jax.Array,
    declared_dead: jax.Array,
    suspect_round: jax.Array,
    suspect_mark: jax.Array,
    quarantine: jax.Array,
    exists: jax.Array,
    rnd: jax.Array,
    timeout_rounds: int,
    detect_period_rounds: int,
    k_accuse: jax.Array | None = None,
    accuser_ok: jax.Array | None = None,
) -> dict:
    """One round of the hardened detector (module docstring has the state
    machine). Replaces :func:`detect_failures` when a :class:`QuorumSpec`
    is active; at ``quorum_k=1`` with no adversaries the declared-dead
    trajectory — and the whole state, the suspicion planes included — is
    bit-identical to the direct detector's whenever at least one live
    witness exists (entry, cohort confirmation, and declaration land on
    the same sweep, so suspicion never persists across rounds).

    ``accuser_ok`` (None = no accusation attack this round) marks rows
    emitting one false dead-verdict each against a victim sampled
    uniformly from the adversary stream (``k_accuse``). An accusation IS
    a witness vote: it latches suspicion on its victim and counts toward
    the quorum — ``quorum_k=1`` evicts on a single report, exactly the
    reference's vulnerability. An accusation whose victim refutes (the
    victim answers its probe inside the window — charged at accusation
    time against a responsive, not-declared victim, the attribution the
    guaranteed-within-window refutation broadcast carries) is a STRIKE
    against the accuser; ``spec.budget`` strikes latch ``quarantine``.

    Returns a dict: the five updated planes plus ``newly_quarantined``
    (the caller releases those rows' rewire slots through the
    degree-credit book) and the round's telemetry counters
    (``evictions_new``, ``false_evictions``, ``adv_accusations``).
    """
    from tpu_gossip.core.state import saturate_round

    n = last_hb.shape[0]
    votes, strikes = unpack_suspicion(suspect_mark)
    responsive = alive & ~silent
    sweep = (rnd % detect_period_rounds) == 0
    stale = (rnd - last_hb) > timeout_rounds  # graftlint: disable=mem-widening-cast -- transient staleness staging, same license as detect_failures above
    suspected = suspect_round >= 0

    # refutation + revival: the sweep probes every suspect and every
    # stale peer; a responsive one answers (nonce-carrying — forgery
    # cannot), refreshing its heartbeat and clearing any suspicion
    revive = sweep & stale & responsive
    last_hb = jnp.where(revive, saturate_round(rnd, last_hb.dtype), last_hb)
    refuted = sweep & suspected & responsive
    # window expiry: a suspicion that outlived the refutation window
    # without reaching quorum resets — stale accusations cannot pool
    # votes across the whole run
    expired = suspected & ((rnd - suspect_round) > spec.window)  # graftlint: disable=mem-widening-cast -- same transient staging license
    cleared = refuted | expired
    suspect_round = jnp.where(cleared, -1, suspect_round).astype(
        suspect_round.dtype
    )
    votes = jnp.where(cleared, 0, votes)
    suspected = suspect_round >= 0

    # entry + cohort confirmation (sweep rounds): a stale unresponsive
    # peer enters suspicion, and every CURRENT suspect that stays stale
    # and unanswering is confirmed by the whole live witness cohort at
    # once — the sweep is the vectorized form of each witness's
    # independent probe, so quorum_k <= n_wit declares on the same sweep
    # the direct detector would (no added latency, band test-pinned)
    enter = sweep & stale & ~responsive & ~declared_dead & ~suspected
    suspect_round = jnp.where(
        enter, saturate_round(rnd, suspect_round.dtype), suspect_round
    )
    suspected = suspected | enter
    n_wit = jnp.sum(
        responsive & ~declared_dead & ~quarantine, dtype=jnp.int32
    )
    confirm = sweep & suspected & stale & ~responsive & ~declared_dead
    # THIS round's distinct-witness cohort: the sweep's confirming
    # witnesses plus (below) the round's accusers — every voter emits at
    # most once per round, so within one round the count IS a distinct
    # count. The stored vote plane keeps the suspicion's largest
    # single-round cohort (max, never sum): a lone Byzantine reporter
    # re-accusing the same victim on later rounds of the window can
    # never add itself up past the quorum — "quorum_k DISTINCT
    # witnesses" holds by construction.
    round_votes = jnp.where(confirm, jnp.minimum(n_wit, SUSPECT_VOTE_CAP), 0)

    # accusation attack: one false dead-verdict per active accuser, each
    # a vote against a uniformly sampled victim
    vic_valid = None
    vic = None
    n_accusations = jnp.zeros((), dtype=jnp.int32)
    if accuser_ok is not None:
        vic = jax.random.randint(k_accuse, (n,), 0, n)
        rows = jnp.arange(n, dtype=vic.dtype)
        vic_valid = (
            accuser_ok
            & exists[vic]
            & alive[vic]
            & ~declared_dead[vic]
            & (vic != rows)
        )
        accused = jnp.zeros((n,), dtype=bool).at[
            jnp.where(vic_valid, vic, n)
        ].set(True, mode="drop")
        counts = jnp.zeros((n,), dtype=jnp.int32).at[
            jnp.where(vic_valid, vic, n)
        ].add(1, mode="drop")
        suspect_round = jnp.where(
            accused & ~suspected, saturate_round(rnd, suspect_round.dtype),
            suspect_round,
        )
        suspected = suspected | accused
        round_votes = round_votes + counts
        n_accusations = jnp.sum(vic_valid, dtype=jnp.int32)
    votes = jnp.minimum(
        jnp.maximum(votes, round_votes), SUSPECT_VOTE_CAP
    )

    # declaration: quorum reached inside the window (checked every round —
    # accusation votes land off-sweep too)
    newly_dead = suspected & (votes >= spec.quorum_k) & ~declared_dead
    declared_dead = declared_dead | newly_dead
    suspect_round = jnp.where(newly_dead, -1, suspect_round).astype(
        suspect_round.dtype
    )
    votes = jnp.where(newly_dead, 0, votes)

    # strikes + quarantine: an accusation the victim survives to refute
    # charges its accuser; budget crossings latch the quarantine verdict
    newly_q = jnp.zeros((n,), dtype=bool)
    if accuser_ok is not None and spec.budget > 0:
        failed = vic_valid & responsive[vic] & ~newly_dead[vic]
        strikes = jnp.minimum(
            strikes + failed.astype(jnp.int32), SUSPECT_STRIKE_CAP
        )
        newly_q = (strikes >= spec.budget) & ~quarantine
        quarantine = quarantine | newly_q

    return {
        "last_hb": last_hb,
        "declared_dead": declared_dead,
        "suspect_round": suspect_round,
        "suspect_mark": pack_suspicion(votes, strikes),
        "quarantine": quarantine,
        "newly_quarantined": newly_q,
        "evictions_new": jnp.sum(newly_dead, dtype=jnp.int32),
        "false_evictions": jnp.sum(
            newly_dead & responsive, dtype=jnp.int32
        ),
        "adv_accusations": n_accusations,
    }
