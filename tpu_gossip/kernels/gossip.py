"""Gossip dissemination ops: one round = batched gather/scatter over CSR.

The reference's gossip "round" is each peer thread writing one line to each
connected socket (reference Peer.py:395-408) with no receive-side handling
(Peer.py:286,206 just log). The TPU design replaces per-socket sends with
array ops over the whole swarm at once:

- ``push_fanout``: every transmitting peer scatters its message bitmap to
  ``k`` uniformly sampled neighbors (classic push gossip; the reference's
  subset-limited broadcast generalized to epidemic relay).
- ``pull_fanout``: every peer gathers from ``k`` sampled neighbors (no
  scatter conflicts — the pull half of push-pull anti-entropy,
  BASELINE.json config 3).
- ``flood_all``: push to *all* neighbors via an edge-gather + segment-OR —
  the deterministic flooding upper bound used for conformance runs.

All take/return plain arrays so the same code runs under `jit`, inside
`shard_map` partitions (dist/mesh.py), and as a reference implementation for
the Pallas kernels. Message state is a per-peer boolean bitmap over
``msg_slots`` hash slots (hash-based dedup per BASELINE.json's north star:
a peer "has" a message iff its slot bit is set, so re-receipt is a no-op).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_fanout_targets", "push_fanout", "pull_fanout", "flood_all", "edge_sources"]


def edge_sources(row_ptr: jax.Array, num_edges: int) -> jax.Array:
    """Row (source peer) id of every CSR entry: int32 (D,).

    ``num_edges`` must equal ``col_idx.shape[0]`` (static under jit).
    """
    n = row_ptr.shape[0] - 1
    deg = row_ptr[1:] - row_ptr[:-1]
    return jnp.repeat(
        jnp.arange(n, dtype=jnp.int32), deg, total_repeat_length=num_edges
    )


def sample_fanout_targets(
    key: jax.Array, row_ptr: jax.Array, col_idx: jax.Array, fanout: int
) -> tuple[jax.Array, jax.Array]:
    """Sample ``fanout`` uniform neighbors per peer (with replacement).

    Returns ``(targets, valid)``: int32 (N, K) neighbor ids and a bool (N, K)
    mask (False where a peer has no neighbors). Uniform-over-neighbors is the
    vectorized analogue of the reference pushing to its connected subset
    (Peer.py:402): on a power-law graph, landing on a hub is automatically
    degree-proportional.
    """
    n = row_ptr.shape[0] - 1
    deg = row_ptr[1:] - row_ptr[:-1]
    if col_idx.shape[0] == 0:
        return (
            jnp.zeros((n, fanout), dtype=jnp.int32),
            jnp.zeros((n, fanout), dtype=bool),
        )
    u = jax.random.uniform(key, (n, fanout))
    off = jnp.minimum((u * deg[:, None]).astype(jnp.int32), deg[:, None] - 1)
    idx = jnp.clip(row_ptr[:-1, None] + off, 0, col_idx.shape[0] - 1)
    valid = jnp.broadcast_to((deg > 0)[:, None], (n, fanout))
    return col_idx[idx], valid


def push_fanout(
    transmit: jax.Array, targets: jax.Array, push_valid: jax.Array
) -> jax.Array:
    """Scatter-OR each sender's message bitmap into its sampled targets.

    ``transmit``: bool (N, M) — slots each peer pushes this round.
    ``targets``/``push_valid``: (N, K) from :func:`sample_fanout_targets`,
    with sender-side masks (dead/silenced senders) folded into ``push_valid``.
    Returns ``incoming``: bool (N, M) — slots delivered to each peer (dedup
    happens when the caller ORs into ``seen``).
    """
    n, m = transmit.shape
    k = targets.shape[1]
    payload = transmit[:, None, :] & push_valid[:, :, None]  # (N, K, M)
    return (
        jnp.zeros((n, m), dtype=bool)
        .at[targets.reshape(-1)]
        .max(payload.reshape(n * k, m), mode="drop")
    )


def pull_fanout(
    transmit: jax.Array, targets: jax.Array, valid: jax.Array
) -> jax.Array:
    """Gather-OR from each peer's sampled neighbors (anti-entropy pull half).

    Conflict-free by construction: each row only reads. Returns ``incoming``
    bool (N, M).
    """
    got = transmit[targets] & valid[:, :, None]  # (N, K, M)
    return got.any(axis=1)


def flood_all(
    transmit: jax.Array, row_ptr: jax.Array, col_idx: jax.Array
) -> jax.Array:
    """Push to *all* neighbors: edge-gather + segment-OR over the CSR.

    Formulated as a pull over incoming edges (undirected CSR stores both
    directions): ``incoming[i] = OR_{j in N(i)} transmit[j]`` — a (D, M)
    gather reduced by source row. Deterministic; used for conformance curves
    and as the flooding upper bound.
    """
    n = row_ptr.shape[0] - 1
    if col_idx.shape[0] == 0:
        return jnp.zeros_like(transmit)
    src = edge_sources(row_ptr, col_idx.shape[0])
    # slots past row_ptr[-1] are capacity padding (a re-materialized CSR,
    # sim/engine.py rematerialize_rewired, keeps col_idx at a fixed length);
    # repeat-padding attributes them to the last degreed row, so they must
    # carry nothing or raw incoming diverges across delivery paths
    real = jnp.arange(col_idx.shape[0]) < row_ptr[-1]
    vals = (transmit[col_idx] & real[:, None]).astype(jnp.uint8)  # (D, M)
    return jax.ops.segment_max(vals, src, num_segments=n).astype(bool)
