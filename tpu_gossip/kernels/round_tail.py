"""Fused protocol tail: every post-delivery slot-array pass in ONE traversal.

After dissemination, the round still owes dedup merge (``seen |=
incoming``), first-infection latching (``infected_round``), per-slot SIR
recovery, forward-once bookkeeping, and the churn fresh-slot resets — five
logical passes over the (N, M) slot arrays. At 1M peers the delivery stage
is ~1.4 ms while the composed round is ~14.4 ms (VERDICT r5 item 7): the
protocol tail dominates ~10×, and its binding resource is HBM traffic over
the slot arrays (``infected_round`` alone is 64 MB at 1M×16), not compute.

This module states the tail ONCE as a single traversal and provides five
implementations that are **bit-identical by construction** (boolean algebra
and int32 selects only — no floats, nothing rounds):

- :func:`tail_reference` — a literal transcription of the historical
  ``advance_round`` pass sequence (merge, latch, SIR, then fresh masks as a
  second sweep). Kept as the bitwise ORACLE the fused paths are tested
  against (tests/sim/test_round_tail.py), and available via
  ``gossip_round(..., tail="reference")``.
- :func:`tail_fused` — the same function as one dependency chain with each
  output materialized exactly once (the churn fresh mask folded into the
  producing expression instead of a second sweep), so XLA emits one fused
  loop reading every input once: the ``lax``-fused path, the default on
  every engine and backend.
- :func:`tail_pallas` — the same math as one Pallas launch over row blocks:
  each grid step streams a (block_rows, M) window of every operand through
  VMEM and writes all four outputs, so the whole tail is a single kernel
  with no XLA fusion-boundary re-reads. Opt-in
  (``gossip_round(..., tail="pallas")``, ``run_sim --tail pallas``) until a
  hardware A/B picks the default: this container is CPU-only, so the kernel
  is conformance-tested in interpret mode and the TPU decision rides the
  next hardware bench (docs/round_tail_profile.md).
- :func:`round_tail_words` — the packed-native tail: the same algebra on
  the ``(N, W)`` uint8 bit words (``W = ceil(M/8)``), so a ``--packed``
  run's tail reads/writes 1/8 the boolean bytes. Only the
  ``infected_round`` latch decodes one transient bool plane (the int16
  plane is full width regardless); everything else is word OR/AND/ANDN.
  The bool-signature shells ``tail_packed`` (``impl="packed"``) and its
  Pallas word-block twin (``impl="packed_pallas"``) route full-width
  operands through the word path — they exist so the bitwise oracle in
  tests/sim/test_round_tail.py pins word-vs-bool identity per stage with
  the same harness as the other impls.

Because every implementation is exact over bools/int32, choosing any of
them preserves the local↔sharded bit-identity contract
(tests/sim/test_dist.py::test_matching_dist_bit_identical_to_single_chip):
the dist engines share :func:`round_tail` through
``sim.engine.advance_round``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = [
    "TAIL_IMPLS",
    "round_tail",
    "round_tail_words",
    "tail_reference",
    "tail_fused",
    "tail_pallas",
    "tail_packed",
]

TAIL_IMPLS = ("fused", "reference", "pallas", "packed", "packed_pallas")

# rows per Pallas grid step: bounds VMEM residency to ~block_rows * M words
# per operand while keeping the sequential grid short (1M rows / 512 = ~2k
# steps). The slot dim rides the lane axis as-is (M=16 underfills the
# 128-lane VPU); the kernel is HBM-bound, so the single launch — one read
# and one write per operand — is the win, not lane occupancy.
BLOCK_ROWS = 512


def _fresh_col(fresh: jax.Array | None) -> jax.Array | None:
    return None if fresh is None else fresh[:, None]


def tail_reference(
    seen: jax.Array,
    forwarded: jax.Array,
    infected_round: jax.Array,
    recovered: jax.Array,
    incoming: jax.Array,
    receptive: jax.Array,
    transmit: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The historical pass sequence, verbatim — the bitwise oracle.

    Merge/latch/SIR first, then (when a churn rejoin fired) the fresh-slot
    resets as a SECOND sweep over the just-produced arrays — exactly the
    order ``advance_round`` used before the fusion, so regressions in the
    fused paths are caught against the original semantics, not against
    themselves. ``expired`` ((M,) bool, the streaming plane's age-out —
    traffic/engine.slot_expiry) clears whole slot COLUMNS as a final
    sweep: the recycled slot's message is gone everywhere at once, a
    delivery into it this round dies with it.
    """
    from tpu_gossip.core.state import saturate_round

    inc = incoming & receptive
    new_seen = seen | inc
    new_fwd = (forwarded | transmit) if forward_once else forwarded
    newly = inc & ~seen
    # the stored latch value narrows to the plane's declared width
    # (int16, saturated at ROUND_CAP); the SIR arithmetic below stays at
    # the wide cursor via int32 promotion
    new_ir = jnp.where(
        newly & (infected_round < 0),
        saturate_round(rnd, infected_round.dtype), infected_round,
    )
    new_rec = recovered
    if sir_recover_rounds > 0:
        new_rec = recovered | (
            (new_ir >= 0) & (rnd - new_ir >= sir_recover_rounds)  # graftlint: disable=mem-widening-cast -- transient SIR age staging: the stored plane stays int16; the subtraction must ride the wide round cursor so ages past ROUND_CAP cannot wrap
        )
    if fresh is not None:
        fc = _fresh_col(fresh)
        new_seen = new_seen & ~fc
        new_fwd = new_fwd & ~fc
        new_ir = jnp.where(fc, -1, new_ir)
        new_rec = new_rec & ~fc
    if expired is not None:
        ec = expired[None, :]
        new_seen = new_seen & ~ec
        new_fwd = new_fwd & ~ec
        new_ir = jnp.where(ec, -1, new_ir)
        new_rec = new_rec & ~ec
    return new_seen, new_fwd, new_ir, new_rec


def tail_fused(
    seen: jax.Array,
    forwarded: jax.Array,
    infected_round: jax.Array,
    recovered: jax.Array,
    incoming: jax.Array,
    receptive: jax.Array,
    transmit: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Single-traversal form: each output is one expression, materialized
    once, with the fresh ROW mask and the streaming plane's expired
    COLUMN mask folded into the producing selects instead of extra
    sweeps. Bitwise-equal to :func:`tail_reference` (pure boolean
    algebra: ``(a | b) & ~f & ~e`` has one value however it is
    scheduled)."""
    from tpu_gossip.core.state import saturate_round

    fc = _fresh_col(fresh)
    inc = incoming & receptive
    # keep = ~fresh_row & ~expired_col, folded to one (broadcast) operand
    if fc is None and expired is None:
        keep = None
    elif fc is None:
        keep = ~expired[None, :]
    elif expired is None:
        keep = ~fc
    else:
        keep = ~fc & ~expired[None, :]
    new_seen = (seen | inc) if keep is None else ((seen | inc) & keep)
    if forward_once:
        new_fwd = (forwarded | transmit) if keep is None else (
            (forwarded | transmit) & keep
        )
    else:
        new_fwd = forwarded if keep is None else (forwarded & keep)
    latch = (inc & ~seen) & (infected_round < 0)
    new_ir = jnp.where(
        latch, saturate_round(rnd, infected_round.dtype), infected_round,
    )
    if sir_recover_rounds > 0:
        new_rec = recovered | (
            (new_ir >= 0) & (rnd - new_ir >= sir_recover_rounds)  # graftlint: disable=mem-widening-cast -- transient SIR age staging: the stored plane stays int16; the subtraction must ride the wide round cursor so ages past ROUND_CAP cannot wrap
        )
    else:
        new_rec = recovered
    if keep is not None:
        new_ir = jnp.where(keep, new_ir, -1)
        new_rec = new_rec & keep
    return new_seen, new_fwd, new_ir, new_rec


def _tail_kernel(
    forward_once: bool, sir: int, has_fresh: bool, has_expired: bool
):
    """One grid step: the whole tail over a (block_rows, M) row window."""
    needs_fwd = forward_once or has_fresh or has_expired

    def kernel(*refs):
        it = iter(refs)
        seen_ref = next(it)
        ir_ref = next(it)
        rec_ref = next(it)
        inc_ref = next(it)
        recp_ref = next(it)
        fwd_ref = next(it) if needs_fwd else None
        tx_ref = next(it) if forward_once else None
        fresh_ref = next(it) if has_fresh else None
        exp_ref = next(it) if has_expired else None
        rnd_ref = next(it)
        o_seen = next(it)
        o_ir = next(it)
        o_rec = next(it)
        o_fwd = next(it) if needs_fwd else None

        rnd = rnd_ref[0, 0]
        seen = seen_ref[...]
        inc = inc_ref[...] & recp_ref[...]
        keep = None
        if has_fresh:
            keep = ~fresh_ref[...]  # (blk, 1) broadcasts over the slot dim
        if has_expired:
            ec = ~exp_ref[...]  # (1, M) broadcasts over the row dim
            keep = ec if keep is None else keep & ec
        new_seen = seen | inc
        if keep is not None:
            new_seen = new_seen & keep
        o_seen[...] = new_seen

        ir = ir_ref[...]
        # rnd arrives pre-saturated at the plane's narrow dtype; the SIR
        # age arithmetic widens to int32 so the (-1)-sentinel lanes can't
        # wrap at the cap edge
        new_ir = jnp.where((inc & ~seen) & (ir < 0), rnd, ir)
        rec = rec_ref[...]
        if sir > 0:
            rec = rec | (
                (new_ir >= 0)
                & (rnd.astype(jnp.int32) - new_ir.astype(jnp.int32) >= sir)  # graftlint: disable=mem-widening-cast -- transient SIR age staging inside the kernel window: the stored plane stays int16; the subtraction widens so sentinel lanes cannot wrap
            )
        if keep is not None:
            new_ir = jnp.where(keep, new_ir, -1)
            rec = rec & keep
        o_ir[...] = new_ir
        o_rec[...] = rec

        if o_fwd is not None:
            fwd = fwd_ref[...]
            if forward_once:
                fwd = fwd | tx_ref[...]
            if keep is not None:
                fwd = fwd & keep
            o_fwd[...] = fwd

    return kernel


def tail_pallas(
    seen: jax.Array,
    forwarded: jax.Array,
    infected_round: jax.Array,
    recovered: jax.Array,
    incoming: jax.Array,
    receptive: jax.Array,
    transmit: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
    interpret: bool | None = None,
    block_rows: int = BLOCK_ROWS,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The tail as ONE Pallas launch over row blocks (same math, same bits).

    When neither forward-once nor a churn rejoin nor a streaming age-out
    touches ``forwarded``, the kernel skips it entirely and the input
    passes through untouched — the common headline configuration moves
    three outputs, not four. ``expired`` ((M,) bool) rides as one
    replicated (1, M) operand every grid step reads.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    n, m = seen.shape
    has_fresh = fresh is not None
    has_expired = expired is not None
    needs_fwd = forward_once or has_fresh or has_expired
    blk = min(block_rows, n)
    grid = (-(-n // blk),)

    row_spec = pl.BlockSpec((blk, m), lambda i: (i, 0))
    one_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0))
    col_spec = pl.BlockSpec((1, m), lambda i: (0, 0))
    rnd_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))

    args = [seen, infected_round, recovered, incoming, receptive]
    in_specs = [row_spec] * 5
    if needs_fwd:
        args.append(forwarded)
        in_specs.append(row_spec)
    if forward_once:
        args.append(transmit)
        in_specs.append(row_spec)
    if has_fresh:
        args.append(fresh[:, None])
        in_specs.append(one_spec)
    if has_expired:
        args.append(expired[None, :])
        in_specs.append(col_spec)
    from tpu_gossip.core.state import saturate_round

    args.append(
        saturate_round(jnp.asarray(rnd, jnp.int32), infected_round.dtype)
        .reshape(1, 1)
    )
    in_specs.append(rnd_spec)

    out_shape = [
        jax.ShapeDtypeStruct((n, m), jnp.bool_),  # seen
        jax.ShapeDtypeStruct((n, m), infected_round.dtype),
        jax.ShapeDtypeStruct((n, m), jnp.bool_),  # recovered
    ]
    out_specs = [row_spec, row_spec, row_spec]
    if needs_fwd:
        out_shape.append(jax.ShapeDtypeStruct((n, m), jnp.bool_))
        out_specs.append(row_spec)

    outs = pl.pallas_call(
        _tail_kernel(forward_once, sir_recover_rounds, has_fresh, has_expired),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    new_seen, new_ir, new_rec = outs[0], outs[1], outs[2]
    new_fwd = outs[3] if needs_fwd else forwarded
    return new_seen, new_fwd, new_ir, new_rec


def _decode_words(words, m):
    """Static-unrolled word->bool decode for INSIDE Pallas kernels (no
    reshape games on the lane dim; M is small). Host-side code never uses
    this — full-width decode routes through ``core.packed.unpack_bits``."""
    cols = [
        (words[:, j // 8] >> np.uint8(j % 8)) & np.uint8(1)
        for j in range(m)
    ]
    return jnp.stack(cols, axis=-1) != 0


def _encode_words(bools, w):
    """Static-unrolled bool->word encode for INSIDE Pallas kernels."""
    m = bools.shape[-1]
    outs = []
    for g in range(w):
        acc = None
        for k in range(8):
            j = g * 8 + k
            if j >= m:
                break
            bit = bools[:, j].astype(jnp.uint8) << np.uint8(k)
            acc = bit if acc is None else acc | bit
        outs.append(acc)
    return jnp.stack(outs, axis=-1)


def _tail_words_kernel(m, w, forward_once, sir, has_fresh, has_expired):
    """One grid step of the packed tail over a (block_rows,) row window:
    uint8 word planes ride (blk, W) blocks, the int16 ``infected_round``
    plane rides (blk, M) blocks, in the same launch."""
    needs_fwd = forward_once or has_fresh or has_expired

    def kernel(*refs):
        it = iter(refs)
        seen_ref = next(it)
        ir_ref = next(it)
        rec_ref = next(it)
        inc_ref = next(it)
        recp_ref = next(it)
        fwd_ref = next(it) if needs_fwd else None
        tx_ref = next(it) if forward_once else None
        fresh_ref = next(it) if has_fresh else None
        exp_ref = next(it) if has_expired else None
        rnd_ref = next(it)
        o_seen = next(it)
        o_ir = next(it)
        o_rec = next(it)
        o_fwd = next(it) if needs_fwd else None

        rnd = rnd_ref[0, 0]
        seen = seen_ref[...]
        inc = inc_ref[...] & recp_ref[...]
        keep_w = None
        keep_rows = None
        if has_fresh:
            keep_rows = ~fresh_ref[...]  # (blk, 1) bool
            keep_w = jnp.where(keep_rows, jnp.uint8(0xFF), jnp.uint8(0))
        if has_expired:
            exp = exp_ref[...]  # (1, M) bool
            ec = _encode_words(~exp, w)  # conforming (1, W) keep words
            keep_w = ec if keep_w is None else keep_w & ec
        new_seen = seen | inc
        if keep_w is not None:
            new_seen = new_seen & keep_w
        o_seen[...] = new_seen

        ir = ir_ref[...]
        newly = _decode_words(inc & ~seen, m)
        new_ir = jnp.where(newly & (ir < 0), rnd, ir)
        rec = rec_ref[...]
        if sir > 0:
            rec = rec | _encode_words(
                (new_ir >= 0)
                & (rnd.astype(jnp.int32) - new_ir.astype(jnp.int32) >= sir),  # graftlint: disable=mem-widening-cast -- transient SIR age staging inside the kernel window: the stored plane stays int16; the subtraction widens so sentinel lanes cannot wrap
                w,
            )
        if has_fresh:
            new_ir = jnp.where(keep_rows, new_ir, -1)
        if has_expired:
            new_ir = jnp.where(exp_ref[...], -1, new_ir)
        if keep_w is not None:
            rec = rec & keep_w
        o_ir[...] = new_ir
        o_rec[...] = rec

        if o_fwd is not None:
            fwd = fwd_ref[...]
            if forward_once:
                fwd = fwd | tx_ref[...]
            if keep_w is not None:
                fwd = fwd & keep_w
            o_fwd[...] = fwd

    return kernel


def round_tail_words(
    seen_w: jax.Array,
    forwarded_w: jax.Array,
    infected_round: jax.Array,
    recovered_w: jax.Array,
    incoming_w: jax.Array,
    receptive_w: jax.Array,
    transmit_w: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    m: int,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
    pallas: bool = False,
    interpret: bool | None = None,
    block_rows: int = BLOCK_ROWS,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The packed-native tail: same algebra as :func:`tail_fused`, on the
    ``(N, W)`` uint8 bit words.

    Word planes in, word planes out — ``seen``/``forwarded``/``recovered``
    /``incoming``/``receptive``/``transmit`` are LSB-first uint8 words
    honoring padding-always-zero; ``infected_round`` stays the full-width
    int16 plane (a narrow integer, resident either way). The dedup merge,
    forward-once latch, churn fresh mask, and stream age-out are word
    OR/AND/ANDN selects; the only full-width bool transient is the
    first-infection latch (``inc & ~seen`` decoded once to gate the int16
    select) plus, when SIR is on, the recovery condition re-encoded to
    words. Bit-identical to the bool tails by construction — the words
    are an exact encoding. ``pallas=True`` runs the same math as one
    Pallas launch over word blocks (interpret-mode on CPU).
    """
    from tpu_gossip.core.packed import pack_bits, unpack_bits

    if pallas:
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        from tpu_gossip.core.state import saturate_round

        n, w = seen_w.shape
        has_fresh = fresh is not None
        has_expired = expired is not None
        needs_fwd = forward_once or has_fresh or has_expired
        blk = min(block_rows, n)
        grid = (-(-n // blk),)
        word_spec = pl.BlockSpec((blk, w), lambda i: (i, 0))
        wide_spec = pl.BlockSpec((blk, m), lambda i: (i, 0))
        one_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0))
        col_spec = pl.BlockSpec((1, m), lambda i: (0, 0))
        rnd_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))

        args = [seen_w, infected_round, recovered_w, incoming_w, receptive_w]
        in_specs = [word_spec, wide_spec, word_spec, word_spec, word_spec]
        if needs_fwd:
            args.append(forwarded_w)
            in_specs.append(word_spec)
        if forward_once:
            args.append(transmit_w)
            in_specs.append(word_spec)
        if has_fresh:
            args.append(fresh[:, None])
            in_specs.append(one_spec)
        if has_expired:
            args.append(expired[None, :])
            in_specs.append(col_spec)
        args.append(
            saturate_round(jnp.asarray(rnd, jnp.int32), infected_round.dtype)
            .reshape(1, 1)
        )
        in_specs.append(rnd_spec)

        out_shape = [
            jax.ShapeDtypeStruct((n, w), jnp.uint8),
            jax.ShapeDtypeStruct((n, m), infected_round.dtype),
            jax.ShapeDtypeStruct((n, w), jnp.uint8),
        ]
        out_specs = [word_spec, wide_spec, word_spec]
        if needs_fwd:
            out_shape.append(jax.ShapeDtypeStruct((n, w), jnp.uint8))
            out_specs.append(word_spec)
        outs = pl.pallas_call(
            _tail_words_kernel(
                m, w, forward_once, sir_recover_rounds, has_fresh, has_expired
            ),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*args)
        new_seen = outs[0]
        new_ir = outs[1]
        new_rec = outs[2]
        new_fwd = outs[3] if needs_fwd else forwarded_w
        return new_seen, new_fwd, new_ir, new_rec

    from tpu_gossip.core.state import saturate_round

    inc_w = incoming_w & receptive_w
    # keep = ~fresh_row & ~expired_col, as one conforming word operand
    keep_w = None
    if fresh is not None:
        keep_w = jnp.where(fresh[:, None], jnp.uint8(0), jnp.uint8(0xFF))
    if expired is not None:
        ec = pack_bits(~expired)[None, :]  # pack after NOT: padding stays 0
        keep_w = ec if keep_w is None else keep_w & ec
    new_seen = (seen_w | inc_w) if keep_w is None else ((seen_w | inc_w) & keep_w)
    if forward_once:
        new_fwd = forwarded_w | transmit_w
    else:
        new_fwd = forwarded_w
    if keep_w is not None:
        new_fwd = new_fwd & keep_w
    # the one full-width decode the packed tail owes: the int16 latch
    newly = unpack_bits(inc_w & ~seen_w, m)
    new_ir = jnp.where(
        newly & (infected_round < 0),
        saturate_round(rnd, infected_round.dtype), infected_round,
    )
    if sir_recover_rounds > 0:
        new_rec = recovered_w | pack_bits(
            (new_ir >= 0) & (rnd - new_ir >= sir_recover_rounds)  # graftlint: disable=mem-widening-cast -- transient SIR age staging: the stored plane stays int16; the subtraction must ride the wide round cursor so ages past ROUND_CAP cannot wrap
        )
    else:
        new_rec = recovered_w
    if fresh is not None:
        new_ir = jnp.where(fresh[:, None], -1, new_ir)
    if expired is not None:
        new_ir = jnp.where(expired[None, :], -1, new_ir)
    if keep_w is not None:
        new_rec = new_rec & keep_w
    return new_seen, new_fwd, new_ir, new_rec


def tail_packed(
    seen: jax.Array,
    forwarded: jax.Array,
    infected_round: jax.Array,
    recovered: jax.Array,
    incoming: jax.Array,
    receptive: jax.Array,
    transmit: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
    pallas: bool = False,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Bool-signature shell over :func:`round_tail_words`: packs the
    full-width operands, runs the word tail, unpacks the outputs.

    This is the oracle-harness adapter — a full-width engine gains
    nothing routing through it (it pays the codec both ways); its job is
    letting tests/sim/test_round_tail.py pin word-vs-bool bit-identity
    with the identical call signature as the other impls. The packed
    engine calls :func:`round_tail_words` directly on its resident words.
    """
    from tpu_gossip.core.packed import pack_bits, unpack_bits

    m = seen.shape[-1]
    seen_w, fwd_w, ir, rec_w = round_tail_words(
        pack_bits(seen), pack_bits(forwarded), infected_round,
        pack_bits(recovered), pack_bits(incoming), pack_bits(receptive),
        pack_bits(transmit), fresh, rnd,
        m=m, forward_once=forward_once,
        sir_recover_rounds=sir_recover_rounds, expired=expired,
        pallas=pallas, interpret=interpret,
    )
    return (
        unpack_bits(seen_w, m), unpack_bits(fwd_w, m), ir,
        unpack_bits(rec_w, m),
    )


def round_tail(
    seen: jax.Array,
    forwarded: jax.Array,
    infected_round: jax.Array,
    recovered: jax.Array,
    incoming: jax.Array,
    receptive: jax.Array,
    transmit: jax.Array,
    fresh: jax.Array | None,
    rnd: jax.Array,
    *,
    forward_once: bool,
    sir_recover_rounds: int,
    expired: jax.Array | None = None,
    impl: str = "fused",
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Dispatch to one of the three bit-identical tail implementations.

    Returns ``(seen, forwarded, infected_round, recovered)``. ``fresh``
    (N,) bool marks slots a churn rejoin reset this round; ``expired``
    (M,) bool marks slot COLUMNS the streaming age-out recycles this
    round (traffic/engine.slot_expiry). Either None compiles its masks
    away entirely — the no-churn / no-stream rounds pay nothing.
    """
    if impl not in TAIL_IMPLS:
        raise ValueError(f"unknown tail impl {impl!r}; choose from {TAIL_IMPLS}")
    kw = dict(
        forward_once=forward_once, sir_recover_rounds=sir_recover_rounds,
        expired=expired,
    )
    if impl == "pallas":
        return tail_pallas(
            seen, forwarded, infected_round, recovered, incoming, receptive,
            transmit, fresh, rnd, interpret=interpret, **kw,
        )
    if impl in ("packed", "packed_pallas"):
        return tail_packed(
            seen, forwarded, infected_round, recovered, incoming, receptive,
            transmit, fresh, rnd, pallas=impl == "packed_pallas",
            interpret=interpret, **kw,
        )
    fn = tail_reference if impl == "reference" else tail_fused
    return fn(
        seen, forwarded, infected_round, recovered, incoming, receptive,
        transmit, fresh, rnd, **kw,
    )
